"""Cluster resource monitor, SLO watchdog and closed-loop footprint
calibration (docs/OBSERVABILITY.md "Cluster monitor", docs/SCALING.md
§7).

The monitor is tested against injected collectors (no service layer),
the watchdog against the real histogram module with synthetic clocks,
and calibration end-to-end down to the SliceLease grant size — the
acceptance property is that a measured peak produces a SMALLER slice
than the padded static estimate.
"""

import json
import time
import types

import pytest

from learningorchestra_tpu.observability import hist as obs_hist
from learningorchestra_tpu.observability import monitor as mon
from learningorchestra_tpu.observability import slo as slo_mod


@pytest.fixture(autouse=True)
def _reset_telemetry():
    obs_hist.reset()
    mon.reset_calibration()
    yield
    obs_hist.reset()
    mon.reset_calibration()


def _fake_devices(in_use=2 << 30, peak=3 << 30, limit=16 << 30, n=2):
    def collect():
        return [{"device": i, "platform": "tpu",
                 "bytesInUse": in_use, "peakBytesInUse": peak,
                 "bytesLimit": limit} for i in range(n)]
    return collect


# ----------------------------------------------------------------------
# sampler
# ----------------------------------------------------------------------

def test_sample_once_builds_rings_and_latest():
    sched = {"devicesBusy": 5, "fragmentation": 0.25}
    serving = {"queueDepth": 3, "batchFill": 0.5}
    jobs = {"running": 2, "queued": 1, "deadLettered": 0}
    arena = {"bytesInUse": 1024, "evictions": 7}
    m = mon.ClusterMonitor(
        interval_seconds=0.5, ring=16,
        scheduler_stats=lambda: sched, serving_stats=lambda: serving,
        job_stats=lambda: jobs, arena_stats=lambda: arena,
        device_stats=_fake_devices())
    for t in (100.0, 101.0, 102.0):
        m.sample_once(now=t)
    latest = m.latest()
    assert latest["hbm"]["bytesInUse"] == 2 * (2 << 30)
    assert latest["hbm"]["peakBytesInUse"] == 2 * (3 << 30)
    assert latest["hbm"]["headroomFrac"] == pytest.approx(
        1 - (2 * (2 << 30)) / (2 * (16 << 30)), abs=1e-6)
    assert latest["scheduler"]["fragmentation"] == 0.25
    assert len(latest["devices"]) == 2
    assert len(m.series("hbmBytesInUse")) == 3
    assert m.series("sliceFragmentation")[-1] == [102.0, 0.25]
    assert m.series("servingQueueDepth")[-1][1] == 3
    assert m.series("jobQueueDepth")[-1][1] == 1
    # windowing: only the two newest samples fall in a 1.5s window
    assert len(m.series_window("hbmBytesInUse", 1.5, now=102.0)) == 2
    snap = m.snapshot()
    assert snap["samples"] == 3 and snap["sampleErrors"] == 0
    assert "arenaBytesInUse" in snap["series"]


def test_ring_is_bounded():
    m = mon.ClusterMonitor(ring=8, device_stats=_fake_devices())
    for t in range(20):
        m.sample_once(now=float(t))
    assert len(m.series("hbmBytesInUse")) == 8
    assert m.series("hbmBytesInUse")[0][0] == 12.0  # oldest evicted


def test_failing_collector_is_counted_not_raised():
    def boom():
        raise RuntimeError("collector down")

    m = mon.ClusterMonitor(scheduler_stats=boom,
                           device_stats=_fake_devices())
    sample = m.sample_once(now=1.0)
    assert sample["scheduler"] is None
    assert m.snapshot()["sampleErrors"] == 1


def test_background_thread_samples_and_stops():
    m = mon.ClusterMonitor(interval_seconds=0.01,
                           device_stats=_fake_devices())
    m.start()
    deadline = time.monotonic() + 5.0
    while m.snapshot()["samples"] < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    m.stop()
    assert m.snapshot()["samples"] >= 2
    n = m.snapshot()["samples"]
    time.sleep(0.05)
    assert m.snapshot()["samples"] == n  # really stopped


def test_device_stats_and_rss_never_raise():
    stats = mon.device_memory_stats()
    assert isinstance(stats, list)
    for entry in stats:
        assert {"device", "platform", "bytesInUse"} <= set(entry)
    peak = mon.peak_hbm_bytes()
    assert peak is None or peak > 0
    assert mon.host_rss_bytes() > 0


# ----------------------------------------------------------------------
# calibration registry
# ----------------------------------------------------------------------

def test_calibration_registry_keeps_high_water():
    mon.record_peak("m:fit", 100)
    mon.record_peak("m:fit", 50)       # lower: ignored
    assert mon.measured_peak("m:fit") == 100
    mon.record_peak("m:fit", 150)
    assert mon.measured_peak("m:fit") == 150
    mon.record_peak(None, 10)          # no key: dropped
    mon.record_peak("m:fit", None)     # no measurement: dropped
    assert mon.measured_peak("other") is None


def test_calibrated_bytes_margin_and_clamps():
    # margin applies, and margins below 1 never shrink the measurement
    assert mon.calibrated_hbm_bytes(1000, 10_000, 1.25) == 1250
    assert mon.calibrated_hbm_bytes(1000, 10_000, 0.5) == 1000
    # clamped to [estimate/10, estimate*10]
    assert mon.calibrated_hbm_bytes(10, 10_000, 1.0) == 1000
    assert mon.calibrated_hbm_bytes(10**9, 10_000, 1.0) == 100_000


def test_calibrate_prefers_measured_peak(tmp_config):
    from learningorchestra_tpu.services.execution import \
        ExecutionService

    tmp_config.footprint_calibrate = True
    tmp_config.footprint_margin = 1.25
    fake = types.SimpleNamespace(
        _ctx=types.SimpleNamespace(config=tmp_config))
    root = {"name": "titanic_model"}

    # first execution: no measurement yet — the static estimate
    # stands, but the key is stamped so the job can record its peak
    fp = {"hbmBytes": 6 << 30}
    ExecutionService._calibrate(fake, fp, root, "fit")
    assert fp["calibrationKey"] == "titanic_model:fit"
    assert fp["hbmBytes"] == 6 << 30

    # the job measured 1.5 GiB — a repeat execution's footprint is the
    # margined measurement, far below the padded estimate
    mon.record_peak("titanic_model:fit", int(1.5 * (1 << 30)))
    fp2 = {"hbmBytes": 6 << 30}
    ExecutionService._calibrate(fake, fp2, root, "fit")
    assert fp2["estimator"] == "measured-peak"
    assert fp2["estimatedHbmBytes"] == 6 << 30
    assert fp2["hbmBytes"] == int(1.5 * (1 << 30) * 1.25)
    assert fp2["hbmBytes"] < 6 << 30


def test_calibrate_off_by_default(tmp_config):
    from learningorchestra_tpu.services.execution import \
        ExecutionService

    mon.record_peak("m:fit", 1)
    fake = types.SimpleNamespace(
        _ctx=types.SimpleNamespace(config=tmp_config))
    fp = {"hbmBytes": 1000}
    ExecutionService._calibrate(fake, fp, {"name": "m"}, "fit")
    assert "calibrationKey" not in fp and fp["hbmBytes"] == 1000


def test_calibrated_slice_grant_is_smaller(tmp_config):
    """ISSUE acceptance: with LO_FOOTPRINT_CALIBRATE a repeat
    execution's granted slice is sized from the measured peak — fewer
    devices than the padded static estimate demands."""
    from learningorchestra_tpu.services.execution import \
        ExecutionService
    from learningorchestra_tpu.services.scheduler import SliceLease

    gib = 1 << 30
    lease = SliceLease(leases=4, total_devices=8, aging_seconds=0.0,
                       device_bytes=gib)

    # static estimate: 6 GiB -> 6 of 8 devices
    fp = {"hbmBytes": 6 * gib}
    g1 = lease.acquire("train", footprint=fp)
    assert len(g1.devices) == 6
    lease.release("train", 0.0, grant=g1)

    # measured: the job actually peaked at 1.5 GiB
    tmp_config.footprint_calibrate = True
    fake = types.SimpleNamespace(
        _ctx=types.SimpleNamespace(config=tmp_config))
    mon.record_peak("titanic_model:fit", int(1.5 * gib))
    fp2 = {"hbmBytes": 6 * gib}
    ExecutionService._calibrate(fake, fp2, {"name": "titanic_model"},
                                "fit")
    g2 = lease.acquire("train", footprint=fp2)
    assert len(g2.devices) == 2   # ceil(1.875 GiB / 1 GiB)
    assert len(g2.devices) < len(g1.devices)
    lease.release("train", 0.0, grant=g2)


# ----------------------------------------------------------------------
# scheduler fragmentation + job queue stats
# ----------------------------------------------------------------------

def test_scheduler_stats_fragmentation():
    from learningorchestra_tpu.services.scheduler import SliceLease

    lease = SliceLease(leases=4, total_devices=8, aging_seconds=0.0)
    a = lease.acquire("train", footprint={"devices": 1})
    b = lease.acquire("train", footprint={"devices": 1})
    c = lease.acquire("train", footprint={"devices": 1})
    stats = lease.stats()
    assert stats["devicesBusy"] == 3 and stats["devicesFree"] == 5
    # free run 3..7 is contiguous: no fragmentation
    assert stats["largestFreeRun"] == 5
    assert stats["fragmentation"] == 0.0
    # free the MIDDLE device: free = {1, 3..7} -> largest run 5 of 6
    lease.release("train", 0.0, grant=b)
    stats = lease.stats()
    assert stats["devicesFree"] == 6
    assert stats["largestFreeRun"] == 5
    assert stats["fragmentation"] == pytest.approx(1 - 5 / 6, abs=1e-6)
    lease.release("train", 0.0, grant=a)
    lease.release("train", 0.0, grant=c)
    assert lease.stats()["fragmentation"] == 0.0


def test_queue_stats_and_peak_hbm_metadata(tmp_config, catalog,
                                           monkeypatch):
    """Jobs report running/queued split to the monitor, and a mesh job
    stamps its measured ``peakHbmBytes`` on the terminal metadata and
    into the calibration registry."""
    import threading

    from learningorchestra_tpu.services.jobs import JobManager

    monkeypatch.setattr(mon, "peak_hbm_bytes", lambda: 7 << 30)
    jobs = JobManager(catalog, max_workers=1, mesh_leases=1)
    catalog.create_collection("first", "train/tensorflow")
    catalog.create_collection("second", "train/tensorflow")
    release = threading.Event()
    started = threading.Event()

    def hold():
        started.set()
        release.wait(20)
        return "done"

    jobs.submit("first", hold, needs_mesh=True, pool="train",
                footprint={"devices": 1,
                           "calibrationKey": "root:fit"})
    assert started.wait(10)
    jobs.submit("second", lambda: "x", needs_mesh=False, pool="train")
    qs = jobs.queue_stats()
    assert qs["running"] == 1 and qs["queued"] == 1
    assert jobs.active_job() == "first"
    release.set()
    assert jobs.wait("first", timeout=20) == "done"
    jobs.wait("second", timeout=10)
    meta = catalog.get_metadata("first")
    assert meta["peakHbmBytes"] == 7 << 30
    assert mon.measured_peak("root:fit") == 7 << 30
    jobs.shutdown()


def test_dead_letter_counter_feeds_queue_stats(tmp_config, catalog):
    from learningorchestra_tpu.services.jobs import JobManager

    jobs = JobManager(catalog, max_workers=1, retry_backoff=0.01)
    catalog.create_collection("always_fails", "function/python")

    def boom():
        raise ValueError("no")

    jobs.submit("always_fails", boom, pool="function", max_retries=0)
    # terminal failure is recorded in the documents, not raised
    assert jobs.wait("always_fails", timeout=10) is None
    assert jobs.queue_stats()["deadLettered"] == 1
    jobs.shutdown()


# ----------------------------------------------------------------------
# SLO watchdog
# ----------------------------------------------------------------------

def _tick(watchdog, now, monitor=None):
    watchdog.evaluate(now=now, monitor=monitor)


def test_hist_window_quantile_diffs_snapshots():
    w = slo_mod._HistWindow("lo_serving_request_seconds")
    w.observe(now=0.0)                     # zero-traffic baseline
    for _ in range(100):
        obs_hist.observe("lo_serving_request_seconds", 0.003)
    w.observe(now=10.0)
    # whole history: ~3ms traffic
    assert w.quantile_over(0.99, window=100.0, now=10.0) <= 0.01
    # a window that starts AFTER the traffic sees none
    for _ in range(100):
        obs_hist.observe("lo_serving_request_seconds", 2.0)
    w.observe(now=20.0)
    q = w.quantile_over(0.99, window=5.0, now=20.0)
    assert q is not None and q >= 2.0 - 1e-9
    # a window wide enough to reach the zero-traffic baseline blends
    # both bursts: the p50 is the fast traffic, the p99 the slow
    assert w.quantile_over(0.50, window=100.0, now=20.0) <= 0.01
    assert w.quantile_over(0.99, window=100.0, now=20.0) >= 2.0


def test_serving_p99_alert_fires_and_resolves(tmp_config, tmp_path):
    tmp_config.event_log = str(tmp_path / "events.jsonl")
    tmp_config.slo_serving_p99_ms = 100.0
    tmp_config.slo_fast_window_s = 1.0
    tmp_config.slo_slow_window_s = 5.0
    w = slo_mod.SloWatchdog(active_trace=lambda: "serve/lm/1")
    t0 = 1000.0
    _tick(w, t0)                            # healthy baseline
    assert w.firing_count() == 0
    # slow traffic (500ms >> the 100ms objective)
    for _ in range(50):
        obs_hist.observe("lo_serving_request_seconds", 0.5)
    _tick(w, t0 + 1.0)
    firing = w.firing()
    assert len(firing) == 1
    assert firing[0]["name"] == "servingP99"
    assert firing[0]["severity"] == "page"
    assert firing[0]["value"] > 100.0
    assert firing[0]["trace"] == "serve/lm/1"
    assert w.page_firing()
    # fault clears: the fast window drains and the alert resolves
    _tick(w, t0 + 3.0)
    assert w.firing_count() == 0 and not w.page_firing()
    snap = w.snapshot()
    transitions = [(h["name"], h["transition"]) for h in
                   snap["history"]]
    assert transitions == [("servingP99", "firing"),
                           ("servingP99", "resolved")]
    # satellite: both transitions landed in the JSONL event log with
    # the serving trace attached
    lines = [json.loads(line) for line in
             open(tmp_config.event_log).read().splitlines()]
    alerts = [e for e in lines if e["kind"] == "alert"]
    assert [e["name"] for e in alerts] == \
        ["servingP99.firing", "servingP99.resolved"]
    assert all(e["traceId"] == "serve/lm/1" for e in alerts)
    assert alerts[0]["severity"] == "page"
    assert alerts[0]["threshold"] == 100.0


def test_transient_spike_does_not_page(tmp_config):
    """Breach in the fast window only (slow window still healthy)
    must not fire — that's the burn-rate double-window contract."""
    tmp_config.slo_serving_p99_ms = 100.0
    tmp_config.slo_fast_window_s = 1.0
    tmp_config.slo_slow_window_s = 60.0
    w = slo_mod.SloWatchdog()
    t0 = 2000.0
    _tick(w, t0)
    # long healthy history dominates the slow window
    for _ in range(2000):
        obs_hist.observe("lo_serving_request_seconds", 0.001)
    _tick(w, t0 + 1.0)
    # brief spike: 5 slow requests in the fast window
    for _ in range(5):
        obs_hist.observe("lo_serving_request_seconds", 0.5)
    _tick(w, t0 + 2.0)
    assert w.firing_count() == 0


def test_hbm_headroom_alert(tmp_config):
    tmp_config.slo_hbm_headroom_frac = 0.2
    tmp_config.slo_fast_window_s = 1.0
    tmp_config.slo_slow_window_s = 2.0
    w = slo_mod.SloWatchdog()
    m = mon.ClusterMonitor(
        device_stats=_fake_devices(in_use=15 << 30, limit=16 << 30,
                                   n=1),
        watchdog=w)
    t0 = 3000.0
    for dt in (0.0, 1.0, 2.0, 3.0):
        m.sample_once(now=t0 + dt)    # headroom 1/16 < 0.2 sustained
    firing = w.firing()
    assert [a["name"] for a in firing] == ["hbmHeadroom"]
    assert firing[0]["severity"] == "page"
    assert firing[0]["value"] == pytest.approx(1 / 16, abs=1e-6)


def test_deadletter_rate_alert(tmp_config):
    tmp_config.slo_deadletter_rate = 1.0    # > 1 dead letter / minute
    tmp_config.slo_fast_window_s = 60.0
    tmp_config.slo_slow_window_s = 120.0
    dead = {"n": 0}
    w = slo_mod.SloWatchdog()
    m = mon.ClusterMonitor(
        job_stats=lambda: {"running": 0, "queued": 0,
                           "deadLettered": dead["n"]},
        device_stats=lambda: [], watchdog=w)
    t0 = 5000.0
    m.sample_once(now=t0)
    dead["n"] = 10                           # 10 dead letters in 30s
    m.sample_once(now=t0 + 30.0)
    m.sample_once(now=t0 + 31.0)
    firing = w.firing()
    assert [a["name"] for a in firing] == ["deadLetterRate"]
    assert firing[0]["severity"] == "ticket"
    assert not w.page_firing()               # ticket severity


def test_disabled_objectives_never_fire(tmp_config):
    # all thresholds default 0 = disabled
    w = slo_mod.SloWatchdog()
    for _ in range(50):
        obs_hist.observe("lo_serving_request_seconds", 30.0)
    _tick(w, 100.0)
    _tick(w, 101.0)
    assert w.firing_count() == 0
    assert w.snapshot()["history"] == []


def test_objectives_reflect_config(tmp_config):
    tmp_config.slo_serving_p99_ms = 250.0
    w = slo_mod.SloWatchdog()
    objectives = w.objectives()
    assert objectives["servingP99"]["threshold"] == 250.0
    assert objectives["servingP99"]["severity"] == "page"
    assert set(objectives) == {"servingP99", "queueWait",
                               "hbmHeadroom", "deadLetterRate",
                               "unattributedGrowth", "servingDrift"}
    # leak detector ships disabled; evaluate() retires thr<=0 objectives
    assert objectives["unattributedGrowth"]["threshold"] == 0.0
    # quantized-serving drift objective follows the config bound
    assert objectives["servingDrift"]["severity"] == "ticket"
    assert objectives["servingDrift"]["threshold"] == tmp_config.serve_drift_max


# ----------------------------------------------------------------------
# REST surface: /observability/cluster, /observability/alerts, /healthz,
# /metrics gauges, /profile stop-path
# ----------------------------------------------------------------------

import json as _json
import re
import urllib.error
import urllib.request


@pytest.fixture()
def slo_server(tmp_config):
    """Live server with SLOs configured and the background sampler
    effectively parked (1h interval) so tests drive every monitor /
    watchdog tick deterministically."""
    from learningorchestra_tpu.services.server import RestServer

    tmp_config.monitor_interval_ms = 3_600_000.0
    tmp_config.slo_serving_p99_ms = 100.0
    tmp_config.slo_fast_window_s = 1.0
    tmp_config.slo_slow_window_s = 5.0
    srv = RestServer(host="127.0.0.1", port=0).start()
    yield srv
    srv.stop()


API = "/api/learningOrchestra/v1"


def _call(server, method, path, body=None, params=""):
    url = f"{server.base_url}{path}{params}"
    data = _json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            raw, ctype, status = (resp.read(),
                                  resp.headers.get("Content-Type", ""),
                                  resp.status)
    except urllib.error.HTTPError as e:
        raw, ctype, status = (e.read(),
                              e.headers.get("Content-Type", ""), e.code)
    return status, _json.loads(raw) if "json" in ctype else raw


def test_cluster_endpoint_document(slo_server):
    monitor = slo_server.api.ctx.monitor
    assert monitor is not None
    monitor.sample_once()
    status, doc = _call(slo_server, "GET",
                        f"{API}/observability/cluster")
    assert status == 200
    latest = doc["latest"]
    assert isinstance(latest["devices"], list)
    assert set(latest["hbm"]) == {"bytesInUse", "peakBytesInUse",
                                  "bytesLimit", "headroomFrac"}
    assert "fragmentation" in latest["scheduler"]
    assert "queueDepth" in latest["serving"]
    assert latest["jobs"]["running"] == 0
    assert latest["hostRssBytes"] > 0
    assert "bytesInUse" in latest["arena"]
    assert doc["samples"] >= 1 and "hostRssBytes" in doc["series"]
    # the context wires real collectors: arena + scheduler present
    assert doc["intervalSeconds"] == 3600.0


def test_alerts_fire_resolve_healthz_and_gauges(slo_server,
                                               tmp_config):
    """ISSUE acceptance: an injected serving-latency breach flips
    ``lo_alerts_firing`` >= 1 AND /healthz to 503; both healthy after
    the fault clears."""
    watchdog = slo_server.api.ctx.monitor.watchdog
    status, body = _call(slo_server, "GET", "/healthz")
    assert status == 200 and body["status"] == "ok"

    t0 = time.time()
    watchdog.evaluate(now=t0)
    for _ in range(50):   # 700ms >> the 100ms p99 objective
        obs_hist.observe("lo_serving_request_seconds", 0.7)
    watchdog.evaluate(now=t0 + 1.0)
    assert watchdog.page_firing()

    status, body = _call(slo_server, "GET", "/healthz")
    assert status == 503 and body["status"] == "failing"
    assert body["alerts"][0]["name"] == "servingP99"

    status, m = _call(slo_server, "GET", "/metrics")
    assert m["alertsFiring"] >= 1
    assert m["alerts"][0]["severity"] == "page"
    assert "cluster" in m
    status, raw = _call(slo_server, "GET", "/metrics",
                        params="?format=prometheus")
    text = raw.decode()
    assert re.search(r"^lo_alerts_firing [1-9]", text, re.M)
    assert 'lo_alert_firing{alert="servingP99",severity="page"} 1' \
        in text

    status, doc = _call(slo_server, "GET",
                        f"{API}/observability/alerts")
    assert status == 200
    assert doc["objectives"]["servingP99"]["threshold"] == 100.0
    assert [a["name"] for a in doc["firing"]] == ["servingP99"]
    assert doc["history"][0]["transition"] == "firing"

    # fault clears: the fast window drains, everything goes healthy
    watchdog.evaluate(now=t0 + 3.0)
    status, body = _call(slo_server, "GET", "/healthz")
    assert status == 200 and body["status"] == "ok"
    status, raw = _call(slo_server, "GET", "/metrics",
                        params="?format=prometheus")
    assert re.search(r"^lo_alerts_firing 0", raw.decode(), re.M)


def test_healthz_503_while_draining(slo_server):
    slo_server.api.ctx.begin_drain()
    status, body = _call(slo_server, "GET", "/healthz")
    assert status == 503 and body["status"] == "draining"


def test_monitor_disabled_404(tmp_config):
    from learningorchestra_tpu.services.server import RestServer

    tmp_config.monitor = False
    srv = RestServer(host="127.0.0.1", port=0).start()
    try:
        assert srv.api.ctx.monitor is None
        status, _ = _call(srv, "GET", f"{API}/observability/cluster")
        assert status == 404
        status, _ = _call(srv, "GET", f"{API}/observability/alerts")
        assert status == 404
        # /healthz still answers without the watchdog
        status, body = _call(srv, "GET", "/healthz")
        assert status == 200 and body["status"] == "ok"
        status, m = _call(srv, "GET", "/metrics")
        assert "cluster" not in m and "alertsFiring" not in m
    finally:
        srv.stop()


# ----------------------------------------------------------------------
# /profile stop-path leak (satellite 1)
# ----------------------------------------------------------------------

def test_profile_lifecycle_with_stubbed_profiler(slo_server,
                                                 monkeypatch):
    import jax

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    # GET before anything: inactive, empty listing
    status, body = _call(slo_server, "GET", f"{API}/profile")
    assert status == 200
    assert body == {"active": False, "traces": []}
    # stop without start -> 406
    status, _ = _call(slo_server, "POST", f"{API}/profile",
                      body={"action": "stop"})
    assert status == 406
    # bad action -> 406
    status, _ = _call(slo_server, "POST", f"{API}/profile",
                      body={"action": "pause"})
    assert status == 406
    status, body = _call(slo_server, "POST", f"{API}/profile",
                         body={"action": "start"})
    assert status == 201
    # double start -> 406
    status, _ = _call(slo_server, "POST", f"{API}/profile",
                      body={"action": "start"})
    assert status == 406
    status, body = _call(slo_server, "POST", f"{API}/profile",
                         body={"action": "stop"})
    assert status == 200 and body["files"] == 0
    status, body = _call(slo_server, "GET", f"{API}/profile")
    assert status == 200
    assert body["active"] is False and len(body["traces"]) == 1


def test_profile_stop_failure_clears_active_state(slo_server,
                                                  monkeypatch):
    """The leak this PR fixes: a raising ``stop_trace`` left
    ``_profile_dir`` set, so every later start 406'd forever with no
    live profiler behind it. Now the failure surfaces as a 500 and
    the profiler is startable again."""
    import jax

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)

    def broken_stop():
        raise RuntimeError("profiler session lost")

    monkeypatch.setattr(jax.profiler, "stop_trace", broken_stop)
    status, _ = _call(slo_server, "POST", f"{API}/profile",
                      body={"action": "start"})
    assert status == 201
    status, body = _call(slo_server, "POST", f"{API}/profile",
                         body={"action": "stop"})
    assert status == 500
    assert "profiler session lost" in body["result"]
    # state cleared: a new start succeeds (pre-fix: 406 forever)
    status, body = _call(slo_server, "GET", f"{API}/profile")
    assert body["active"] is False
    status, _ = _call(slo_server, "POST", f"{API}/profile",
                      body={"action": "start"})
    assert status == 201
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    status, _ = _call(slo_server, "POST", f"{API}/profile",
                      body={"action": "stop"})
    assert status == 200


# ----------------------------------------------------------------------
# strict Prometheus exposition (satellite 3)
# ----------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r'([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (\S+)')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(raw):
    out, i = [], 0
    while i < len(raw):
        if raw[i] == "\\" and i + 1 < len(raw):
            out.append({"\\": "\\", '"': '"', "n": "\n"}.get(
                raw[i + 1], raw[i:i + 2]))
            i += 2
        else:
            out.append(raw[i])
            i += 1
    return "".join(out)


def test_prometheus_exposition_is_strictly_well_formed(slo_server):
    """Satellite: every series has a # TYPE, histogram buckets are
    cumulative/monotone with +Inf == _count, and every label value
    survives an escape_label_value round-trip."""
    from learningorchestra_tpu.services.server import \
        escape_label_value

    # traffic with label values that exercise the escaper
    _call(slo_server, "GET", "/health")
    _call(slo_server, "GET", f"{API}/dataset/csv")
    obs_hist.observe("lo_serving_request_seconds", 0.02)
    obs_hist.observe("lo_serving_request_seconds", 4.0)
    slo_server.api.ctx.monitor.sample_once()

    status, raw = _call(slo_server, "GET", "/metrics",
                        params="?format=prometheus")
    assert status == 200
    text = raw.decode()
    types = {}
    samples = []
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split()
            assert name not in types, f"duplicate TYPE for {name}"
            assert kind in ("gauge", "counter", "histogram")
            types[name] = kind
            continue
        assert not line.startswith("#"), line
        match = _SAMPLE_RE.fullmatch(line)
        assert match, f"malformed sample line: {line!r}"
        name, labelstr, value = match.groups()
        float(value)  # parseable
        labels = {}
        if labelstr is not None:
            consumed = 0
            for lm in _LABEL_RE.finditer(labelstr):
                labels[lm.group(1)] = lm.group(2)
                consumed = lm.end()
            # nothing but separators between/after label pairs
            assert not labelstr[consumed:].strip(", "), line
        samples.append((name, labels, float(value)))
    assert samples, "empty exposition"

    histogram_buckets = {}
    for name, labels, value in samples:
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            stripped = name[:-len(suffix)] \
                if name.endswith(suffix) else None
            if stripped and types.get(stripped) == "histogram":
                base = stripped
                break
        assert base in types, f"sample {name} has no # TYPE"
        if types[base] == "histogram" and name.endswith("_bucket"):
            assert "le" in labels, line
            key = (base, tuple(sorted((k, v) for k, v in
                                      labels.items() if k != "le")))
            histogram_buckets.setdefault(key, []).append(
                (float("inf") if labels["le"] == "+Inf"
                 else float(labels["le"]), value))
        # label values survive the escaping round-trip
        for raw_value in labels.values():
            assert escape_label_value(_unescape(raw_value)) == \
                raw_value

    counts = {(n, tuple(sorted(lbl.items()))): v
              for n, lbl, v in samples if n.endswith("_count")}
    assert histogram_buckets, "no histogram series in exposition"
    for (base, label_key), buckets in histogram_buckets.items():
        buckets.sort()
        values = [v for _, v in buckets]
        assert values == sorted(values), \
            f"{base} buckets not cumulative/monotone"
        assert buckets[-1][0] == float("inf"), f"{base} missing +Inf"
        count = counts.get((f"{base}_count", label_key))
        assert count is not None, f"{base}_count missing"
        assert buckets[-1][1] == count, \
            f"{base} +Inf bucket != _count"
