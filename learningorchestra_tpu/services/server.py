"""The REST control plane: one server, the reference's full URI
contract.

Replaces KrakenD:80 + 9 Flask microservices (reference
krakend.json:1-1773, SURVEY §L1-L2) with a single threaded stdlib HTTP
server. Route table (all under ``/api/learningOrchestra/v1``):

====== ================================== ==============================
verb   path                               handler
====== ================================== ==============================
POST   /dataset/{csv,generic}             DatasetService.create
POST   /model/{tensorflow,scikitlearn,jax} ModelService.create
POST   /{train,tune,evaluate,predict}/{tool} ExecutionService.create
POST   /explore/histogram                 HistogramService.create
POST   /explore/{tool}                    DatabaseExecutorService.create
POST   /transform/projection              ProjectionService.create
POST   /transform/dataType                DataTypeService.create
POST   /transform/{tool}                  DatabaseExecutorService.create
POST   /function/python                   FunctionService.create
POST   /builder/sparkml                   BuilderService.create
PATCH  /{service}/{tool}/{name}           per-service ``update``
GET    /{service}/{tool}                  catalog listing by type
GET    /{service}/{tool}/{name}           universal paged read
                                          (?skip&limit&query, images
                                          for explore plots)
DELETE /{service}/{tool}/{name}           per-service ``delete``
GET    /observe/{name}?seq=N              long-poll change feed
GET    /observability/trace/{name}        span tree (?format=chrome)
GET    /observability/timeline/{name}     per-step training telemetry
POST   /profile {action: start|stop}      jax.profiler trace capture
GET    /profile                           profiler status + trace list
GET    /health                            liveness + topology info
====== ================================== ==============================

Semantics preserved: POST validates synchronously (406/409/404), then
returns **201 with the artifact's future GET URI while the job runs
async**; clients poll ``finished`` in the metadata (reference
server.py:65-71 in every image). The Observe service — client-side
Mongo change streams in the reference (README.md:81) — is served here
directly from the catalog's change feed as long-poll JSON.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from learningorchestra_tpu import analysis as A
from learningorchestra_tpu.catalog import documents as D
from learningorchestra_tpu.observability import export as obs_export
from learningorchestra_tpu.observability import hist as obs_hist
from learningorchestra_tpu.observability import perf as obs_perf
from learningorchestra_tpu.observability import timeline as obs_timeline
from learningorchestra_tpu.observability import trace as obs_trace
from learningorchestra_tpu.observability import xray as obs_xray
from learningorchestra_tpu.services import validators as V
from learningorchestra_tpu.services.builder_service import BuilderService
from learningorchestra_tpu.services.columnar import (DataTypeService,
                                                     HistogramService,
                                                     ProjectionService)
from learningorchestra_tpu.services.context import ServiceContext
from learningorchestra_tpu.services.database_executor import (
    DatabaseExecutorService)
from learningorchestra_tpu.services.dataset import (DatasetService,
                                                    parse_query_param)
from learningorchestra_tpu.services.execution import ExecutionService
from learningorchestra_tpu.services.function_service import FunctionService
from learningorchestra_tpu.services.model_service import ModelService
from learningorchestra_tpu.runtime import locks

EXECUTION_VERBS = ("train", "tune", "evaluate", "predict")
SERVICES = ("dataset", "model", "transform", "explore", "tune", "train",
            "evaluate", "predict", "builder", "function", "serve")


def escape_label_value(v: Any) -> str:
    """Prometheus exposition-format label-value escaping. Per the
    spec, backslash MUST be escaped first (or the escapes introduced
    for ``"`` and newline would themselves be double-escaped), then
    the double quote, then line feeds."""
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


class Api:
    """Transport-independent dispatch (unit-testable without sockets)."""

    def __init__(self, context: Optional[ServiceContext] = None):
        self.ctx = context or ServiceContext()
        self.dataset = DatasetService(self.ctx)
        self.model = ModelService(self.ctx)
        self.execution = ExecutionService(self.ctx)
        self.dbexec = DatabaseExecutorService(self.ctx)
        self.function = FunctionService(self.ctx)
        self.histogram = HistogramService(self.ctx)
        self.projection = ProjectionService(self.ctx)
        self.datatype = DataTypeService(self.ctx)
        self.builder = BuilderService(self.ctx)
        # jax.profiler singleton owner, shared with the incident
        # flight recorder's triggered-profiling window (context.py)
        self._profiler_gate = self.ctx.profiler_gate
        from learningorchestra_tpu.services.cache import ReadCache

        self.read_cache = ReadCache(
            ttl_seconds=self.ctx.config.get_cache_ttl_seconds)
        # gateway metrics (KrakenD exposes a metrics collector on
        # :8090, krakend.json:1752-1760; here it's first-party)
        self._metrics_lock = locks.make_lock("server.metrics")
        self._started = time.monotonic()
        self._requests: Dict[str, int] = {}
        self._statuses: Dict[str, int] = {}
        self._latency_sum = 0.0
        self._latency_count = 0
        # timed-dispatch accounting (the LO_REQUEST_TIMEOUT path in
        # _Handler._respond spawns a thread per request and abandons
        # it on 504 — without a cap N slow dispatches pile up unseen)
        self._gateway_lock = locks.make_lock("server.gateway")
        self._gateway_inflight = 0
        self._gateway_abandoned_inflight = 0
        self._gateway_abandoned_total = 0
        self._gateway_saturated_total = 0
        self.recover_unfinished()
        # elastic pod recovery: when the guard sees heartbeats resume,
        # requeue checkpointed worker-lost executions automatically
        self.ctx.on_pod_healthy.append(self.recover_worker_lost)

    # ------------------------------------------------------------------
    def recover_unfinished(self) -> Dict[str, list]:
        """Boot-time job durability (beyond the reference, whose
        in-flight jobs are silently lost on restart, README.md:194-198;
        SURVEY §7 step 8 sets the bar at requeue-or-fail):

        - executions (train/tune/evaluate/predict) and functions store
          their full request in metadata, so they are REQUEUED — a
          checkpointed train resumes from its latest orbax step;
        - everything else (ingests mid-stream, explore/transform,
          builder) gets a typed ``exception`` execution document so a
          polling client sees a terminal failure instead of a forever-
          False ``finished`` flag.
        """
        requeued, failed = [], []
        for meta in self.ctx.catalog.list_collections():
            if meta.get(D.FINISHED_FIELD):
                continue
            name = meta.get(D.NAME_FIELD)
            type_string = str(meta.get(D.TYPE_FIELD, ""))
            verb = type_string.split("/")[0]
            # a trailing exception document means the job TERMINATED
            # in failure (client already has the error; reference
            # parity keeps finished=False) — only jobs interrupted
            # mid-flight (no terminal record) are recovered, or every
            # restart would re-run failed fits / stack duplicate
            # InterruptedError docs. EXCEPTION: a WorkerLost failure
            # on a REQUEUEABLE job is the pod's fault, not the job's —
            # elastic-recovery policy requeues those here too, or a
            # restart would strand jobs the running server
            # auto-recovers. Non-requeueable worker-lost jobs (model/
            # builder) keep their typed WorkerLost record as-is.
            requeueable = (
                (verb in EXECUTION_VERBS and
                 meta.get(D.METHOD_FIELD) is not None) or
                (verb == "function" and
                 meta.get(D.FUNCTION_FIELD) is not None))
            # shutdownAborted is the same story for a DRAINED server:
            # the job never ran; the doc only exists so the orphan is
            # not silent — requeue it like a mid-flight interruption
            docs = self.ctx.catalog.get_documents(name)
            if docs and docs[-1].get(D.EXCEPTION_FIELD) and \
                    not ((docs[-1].get("workerLost") or
                          docs[-1].get("shutdownAborted"))
                         and requeueable):
                continue
            try:
                if verb in EXECUTION_VERBS and \
                        meta.get(D.METHOD_FIELD) is not None:
                    self._requeue_execution(name, type_string, meta)
                    requeued.append(name)
                elif verb == "function" and \
                        meta.get(D.FUNCTION_FIELD) is not None:
                    from learningorchestra_tpu.services import (
                        function_service as fsvc)

                    # replay under the originally granted mode — but
                    # re-resolve against the CURRENT ceiling, so a
                    # lowered LO_SANDBOX_MAX is honored (failure lands
                    # in the catch below as a typed requeue error)
                    mode = fsvc.resolve_sandbox_mode(
                        self.ctx.config,
                        meta.get(fsvc.SANDBOX_MODE_FIELD))
                    self.function._submit(
                        name, type_string, meta[D.FUNCTION_FIELD],
                        meta.get(D.FUNCTION_PARAMETERS_FIELD) or {},
                        meta.get(D.DESCRIPTION_FIELD, ""), mode=mode,
                        timeout=meta.get(V.TIMEOUT_FIELD))
                    requeued.append(name)
                else:
                    self.ctx.catalog.append_document(
                        name, D.execution_document(
                            meta.get(D.DESCRIPTION_FIELD, ""), None,
                            exception="InterruptedError('job was in "
                                      "flight when the server stopped; "
                                      "resubmit it')"))
                    failed.append(name)
            except Exception as exc:  # noqa: BLE001 — boot must finish
                self.ctx.catalog.append_document(
                    name, D.execution_document(
                        meta.get(D.DESCRIPTION_FIELD, ""), None,
                        exception=f"requeue-on-boot failed: {exc!r}"))
                failed.append(name)
        return {"requeued": requeued, "failed": failed}

    def _requeue_execution(self, name: str, type_string: str,
                           meta: Dict[str, Any],
                           only_if_idle: bool = False) -> None:
        """Shared requeue-from-stored-request used by boot recovery
        and elastic re-form recovery (one place owns the _submit
        signature)."""
        self.execution._submit(
            name, type_string, meta[D.PARENT_NAME_FIELD],
            meta[D.METHOD_FIELD],
            meta.get(D.METHOD_PARAMETERS_FIELD) or {},
            meta.get(D.DESCRIPTION_FIELD, ""),
            only_if_idle=only_if_idle,
            timeout=meta.get(V.TIMEOUT_FIELD),
            footprint=meta.get(A.FOOTPRINT_FIELD),
            health_policy=meta.get(V.HEALTH_POLICY_FIELD))

    def recover_worker_lost(self) -> list:
        """Elastic pod recovery (beyond the reference, whose node loss
        loses the work outright, README.md:194-202): when the pod
        guard reports heartbeats resumed, requeue every unfinished
        execution whose LAST failure was attributed to the pod
        (``workerLost`` — a pre-submit refusal, or a mesh job whose
        collective errored while the pod was degraded). A checkpointed
        train then picks up at its latest orbax step with NO server
        restart. Not eligible: jobs whose newest failure is a genuine
        (non-pod) error — re-running those on every degrade/heal flap
        would loop a broken fit forever — and jobs whose original
        thread is still live (the atomic ``only_if_idle`` submit skips
        them; a thread wedged in a dead collective can only be cleared
        by a pod restart, which boot recovery then handles)."""
        requeued = []
        for meta in self.ctx.catalog.list_collections():
            if meta.get(D.FINISHED_FIELD):
                continue
            name = meta.get(D.NAME_FIELD)
            type_string = str(meta.get(D.TYPE_FIELD, ""))
            verb = type_string.split("/")[0]
            if verb not in EXECUTION_VERBS or \
                    meta.get(D.METHOD_FIELD) is None:
                continue
            docs = self.ctx.catalog.get_documents(name)
            exc_docs = [d for d in docs if d.get(D.EXCEPTION_FIELD)]
            if not exc_docs or not exc_docs[-1].get("workerLost"):
                continue
            try:
                self._requeue_execution(name, type_string, meta,
                                        only_if_idle=True)
                requeued.append(name)
            except Exception as exc:  # noqa: BLE001 — recovery must
                # not kill the guard thread; record and move on. The
                # doc keeps the workerLost attribution so a transient
                # requeue error leaves the job retryable by the next
                # heal / the next boot instead of stranding it
                self.ctx.catalog.append_document(
                    name, D.execution_document(
                        meta.get(D.DESCRIPTION_FIELD, ""), None,
                        exception=f"requeue-on-reform failed: {exc!r}",
                        extra={"workerLost": True}))
        if requeued:
            print(f"pod re-form: requeued {len(requeued)} worker-lost "
                  f"job(s): {requeued}", flush=True)
        return requeued

    # ------------------------------------------------------------------
    def dispatch(self, method: str, path: str, params: Dict[str, Any],
                 body: Optional[Dict[str, Any]],
                 record: bool = True) -> Tuple[int, Any, str]:
        """Returns (status, payload, content_type). payload is a dict
        (JSON) or raw bytes when content_type is not JSON.
        ``record=False`` lets a deadline-bound caller own the metrics
        record (otherwise a timed-out request would be counted twice:
        the 504 the client saw AND the late real completion)."""
        t0 = time.monotonic()
        try:
            out = self._route(method, path, params, body)
        except V.HttpError as e:
            payload = {"result": e.message}
            if e.findings:
                payload["analysis"] = e.findings
            out = e.status, payload, "application/json"
        except Exception as e:  # noqa: BLE001
            out = 500, {"result": f"internal error: {e!r}"}, \
                "application/json"
        if record:
            self._record_metrics(method, path, out[0],
                                 time.monotonic() - t0)
        return out

    def _record_metrics(self, method: str, path: str, status: int,
                        seconds: float) -> None:
        prefix = self.ctx.config.api_prefix
        parts = [p for p in path[len(prefix):].split("/") if p] \
            if path.startswith(prefix + "/") else []
        service = parts[0] if parts else path.lstrip("/").split("/")[0] \
            or "root"
        with self._metrics_lock:
            key = f"{method} {service}"
            self._requests[key] = self._requests.get(key, 0) + 1
            sk = str(status)
            self._statuses[sk] = self._statuses.get(sk, 0) + 1
            self._latency_sum += seconds
            self._latency_count += 1
        obs_hist.observe("lo_dispatch_seconds", seconds)

    def metrics(self) -> Dict[str, Any]:
        with self._metrics_lock:
            n = self._latency_count
            out = {
                "uptimeSeconds": round(
                    time.monotonic() - self._started, 3),
                "requestsTotal": n,
                "requestsByRoute": dict(sorted(self._requests.items())),
                "responsesByStatus": dict(sorted(self._statuses.items())),
                "meanDispatchSeconds": round(
                    self._latency_sum / n, 6) if n else None,
                "dispatchSecondsSum": round(self._latency_sum, 6),
            }
        out["jobsRunning"] = self.ctx.jobs.running()
        out["collections"] = len(self.ctx.catalog.list_collections())
        out["getCache"] = self.read_cache.stats()
        out["meshSecondsByPool"] = {
            pool: round(seconds, 3) for pool, seconds in
            sorted(self.ctx.jobs.mesh_served().items())}
        out["jobLifecycle"] = self.ctx.jobs.lifecycle_counters()
        out["meshScheduler"] = self.ctx.jobs.scheduler_stats()
        # live migration between slices (docs/SCALING.md §7)
        out["migrationStats"] = self.ctx.jobs.migration_stats()
        # elastic slice autoscaler (docs/SCALING.md "Elastic
        # autoscaling"); absent when LO_AUTOSCALE=0
        autoscaler = getattr(self.ctx, "autoscaler", None)
        if autoscaler is not None:
            out["autoscaler"] = autoscaler.stats()
        # feature-plane cache tiers (docs/PERFORMANCE.md). Lazy
        # imports: arena/engine stats never initialize a backend.
        out["featureCache"] = self.ctx.features.stats()
        from learningorchestra_tpu.runtime import arena as arena_lib
        from learningorchestra_tpu.runtime import engine as engine_lib
        out["arena"] = arena_lib.get_default_arena().stats()
        out["executableCache"] = engine_lib.executable_cache_stats()
        # training-health sentinel + checkpoint-integrity counters
        # (docs/RELIABILITY.md); health.py is jax-free so this import
        # is always cheap
        from learningorchestra_tpu.runtime import health as health_lib
        out["trainingHealth"] = health_lib.health_stats()
        # resident serving plane (docs/SERVING.md): session counts,
        # admission rejects, decode throughput and p50/p99 latency
        out["serving"] = self.ctx.serving.stats()
        # vectorized sweep fusion (docs/PERFORMANCE.md "Sweep fusion")
        from learningorchestra_tpu.models import sweep as sweep_lib
        out["sweepFusion"] = sweep_lib.fusion_stats()
        # latency histograms (docs/OBSERVABILITY.md): cumulative
        # buckets, same snapshots the Prometheus exposition serializes
        out["latencyHistograms"] = obs_hist.snapshot_all()
        # timed-dispatch gateway counters (docs/OBSERVABILITY.md):
        # in-flight/abandoned dispatch threads and saturation rejects
        with self._gateway_lock:
            out["gateway"] = {
                "inflight": self._gateway_inflight,
                "abandonedInflight": self._gateway_abandoned_inflight,
                "abandonedTotal": self._gateway_abandoned_total,
                "saturatedTotal": self._gateway_saturated_total,
                "maxInflight": self.ctx.config.gateway_max_inflight,
            }
        # roofline perf reports (docs/OBSERVABILITY.md "Roofline &
        # perf reports"): latest per-job window + the platform peaks
        # they measure against
        out["perf"] = {
            "platform": obs_perf.platform_summary(),
            "jobs": obs_perf.latest(),
        }
        # HBM attribution ledger + retrace/transfer sentinels
        # (docs/OBSERVABILITY.md "HBM attribution & X-ray"). Only the
        # jax-free subset — the full report with bytes-in-use lives on
        # GET /observability/memory
        out["xray"] = {
            "enabled": obs_xray.enabled(),
            "owners": obs_xray.by_owner(),
            "attributedBytes": obs_xray.attributed_bytes(),
            "counters": obs_xray.counters(),
        }
        # cluster resource sampler + SLO watchdog (docs/OBSERVABILITY
        # .md "Cluster monitor"); absent when LO_MONITOR=0
        monitor = getattr(self.ctx, "monitor", None)
        if monitor is not None:
            out["cluster"] = monitor.latest()
            watchdog = monitor.watchdog
            if watchdog is not None:
                out["alerts"] = watchdog.firing()
                out["alertsFiring"] = len(out["alerts"])
        # incident flight recorder (docs/OBSERVABILITY.md "Incidents
        # & flight recorder"); absent when LO_INCIDENTS=0
        recorder = getattr(self.ctx, "incidents", None)
        if recorder is not None:
            out["incidents"] = recorder.stats()
        return out

    def metrics_prometheus(self) -> bytes:
        """Prometheus text exposition of :meth:`metrics` (KrakenD's
        collector on :8090 is the reference's version of this,
        krakend.json:1752-1760; text format is what the ecosystem's
        scrapers actually ingest)."""
        # sum and count come from the same metrics() snapshot so
        # rate(sum)/rate(count) stays consistent under load
        m = self.metrics()
        esc = escape_label_value
        # constant build pin (satellite: dashboards and bundles can
        # join every series onto exactly what was running)
        from learningorchestra_tpu.observability import \
            incidents as obs_incidents
        info = obs_incidents.build_info()
        lines = [
            "# TYPE lo_build_info gauge",
            f'lo_build_info{{version="{esc(info["version"])}"'
            f',jax_version="{esc(info["jaxVersion"])}"'
            f',backend="{esc(info["backend"])}"'
            f',device_kind="{esc(info["deviceKind"])}"}} 1',
            "# TYPE lo_uptime_seconds gauge",
            f"lo_uptime_seconds {m['uptimeSeconds']}",
            "# TYPE lo_requests_total counter",
        ]
        for route, n in m["requestsByRoute"].items():
            lines.append(
                f'lo_requests_total{{route="{esc(route)}"}} {n}')
        lines.append("# TYPE lo_responses_total counter")
        for status, n in m["responsesByStatus"].items():
            lines.append(
                f'lo_responses_total{{status="{esc(status)}"}} {n}')
        # lo_dispatch_seconds / lo_lease_wait_seconds moved from
        # sum+count summaries to full histograms — emitted with every
        # other latency histogram at the end of this exposition
        lines += [
            "# TYPE lo_jobs_running gauge",
            f"lo_jobs_running {m['jobsRunning']}",
            "# TYPE lo_collections gauge",
            f"lo_collections {m['collections']}",
            "# TYPE lo_mesh_seconds_total counter",
        ]
        for pool, seconds in m["meshSecondsByPool"].items():
            lines.append(
                f'lo_mesh_seconds_total{{pool="{esc(pool)}"}} {seconds}')
        lines += [
            "# TYPE lo_get_cache_hits_total counter",
            f"lo_get_cache_hits_total {m['getCache']['hits']}",
            "# TYPE lo_get_cache_misses_total counter",
            f"lo_get_cache_misses_total {m['getCache']['misses']}",
            "# TYPE lo_get_cache_entries gauge",
            f"lo_get_cache_entries {m['getCache']['entries']}",
        ]
        feature = m["featureCache"]
        arena = m["arena"]
        exec_cache = m["executableCache"]
        lines += [
            "# TYPE lo_feature_cache_hits_total counter",
            f"lo_feature_cache_hits_total {feature['hits']}",
            "# TYPE lo_feature_cache_misses_total counter",
            f"lo_feature_cache_misses_total {feature['misses']}",
            "# TYPE lo_feature_cache_bytes_in_use gauge",
            f"lo_feature_cache_bytes_in_use {feature['bytesInUse']}",
            "# TYPE lo_arena_bytes_in_use gauge",
            f"lo_arena_bytes_in_use {arena['bytesInUse']}",
            "# TYPE lo_arena_evictions_total counter",
            f"lo_arena_evictions_total {arena['evictions']}",
            "# TYPE lo_arena_hits_total counter",
            f"lo_arena_hits_total {arena['hits']}",
            "# TYPE lo_arena_misses_total counter",
            f"lo_arena_misses_total {arena['misses']}",
            "# TYPE lo_executable_cache_hits_total counter",
            f"lo_executable_cache_hits_total {exec_cache['hits']}",
            "# TYPE lo_executable_cache_misses_total counter",
            f"lo_executable_cache_misses_total {exec_cache['misses']}",
        ]
        lifecycle = m["jobLifecycle"]
        lines += [
            "# TYPE lo_job_retries_total counter",
            f"lo_job_retries_total {lifecycle.get('retries', 0)}",
            "# TYPE lo_jobs_cancelled_total counter",
            f"lo_jobs_cancelled_total {lifecycle.get('cancelled', 0)}",
            "# TYPE lo_jobs_timed_out_total counter",
            f"lo_jobs_timed_out_total {lifecycle.get('timedOut', 0)}",
            "# TYPE lo_jobs_stalled gauge",
            f"lo_jobs_stalled {lifecycle.get('stalled', 0)}",
        ]
        scheduler = m["meshScheduler"]
        lines += [
            "# TYPE lo_lease_wait_seconds_max gauge",
            f"lo_lease_wait_seconds_max "
            f"{scheduler.get('leaseWaitMax', 0.0)}",
            "# TYPE lo_mesh_devices_busy gauge",
            f"lo_mesh_devices_busy {scheduler.get('devicesBusy', 0)}",
            "# TYPE lo_slice_grants_total counter",
        ]
        for pool, n in sorted(
                (scheduler.get("grantsByPool") or {}).items()):
            lines.append(
                f'lo_slice_grants_total{{pool="{esc(pool)}"}} {n}')
        lines += [
            "# TYPE lo_job_numerical_retries_total counter",
            f"lo_job_numerical_retries_total "
            f"{lifecycle.get('numericalRetries', 0)}",
        ]
        training_health = m["trainingHealth"]
        lines += [
            "# TYPE lo_nonfinite_steps_total counter",
            f"lo_nonfinite_steps_total "
            f"{training_health.get('nonfiniteSteps', 0)}",
            "# TYPE lo_rollbacks_total counter",
            f"lo_rollbacks_total {training_health.get('rollbacks', 0)}",
            "# TYPE lo_loss_spikes_total counter",
            f"lo_loss_spikes_total "
            f"{training_health.get('lossSpikes', 0)}",
            "# TYPE lo_checkpoints_quarantined_total counter",
            f"lo_checkpoints_quarantined_total "
            f"{training_health.get('quarantined', 0)}",
            # quantized-serving quality gate (services/serving.py)
            "# TYPE lo_serving_drift_breaches_total counter",
            f"lo_serving_drift_breaches_total "
            f"{training_health.get('driftBreaches', 0)}",
            "# TYPE lo_serving_quant_degrades_total counter",
            f"lo_serving_quant_degrades_total "
            f"{training_health.get('quantDegrades', 0)}",
        ]
        sweep_fusion = m["sweepFusion"]
        lines += [
            "# TYPE lo_sweep_fused_trials_total counter",
            f"lo_sweep_fused_trials_total "
            f"{sweep_fusion.get('fusedTrials', 0)}",
            "# TYPE lo_sweep_cohorts_total counter",
            f"lo_sweep_cohorts_total {sweep_fusion.get('cohorts', 0)}",
            "# TYPE lo_sweep_fallback_trials_total counter",
            f"lo_sweep_fallback_trials_total "
            f"{sweep_fusion.get('fallbackTrials', 0)}",
            "# TYPE lo_sweep_early_stopped_total counter",
            f"lo_sweep_early_stopped_total "
            f"{sweep_fusion.get('earlyStopped', 0)}",
            "# TYPE lo_sweep_trial_errors_total counter",
            f"lo_sweep_trial_errors_total "
            f"{sweep_fusion.get('trialErrors', 0)}",
        ]
        serving = m["serving"]
        lines += [
            "# TYPE lo_serving_sessions gauge",
            f"lo_serving_sessions {serving['sessions']}",
            "# TYPE lo_serving_requests_total counter",
            f"lo_serving_requests_total {serving['requestsTotal']}",
            "# TYPE lo_serving_rejected_total counter",
            f"lo_serving_rejected_total {serving['rejectedTotal']}",
            "# TYPE lo_serving_tokens_total counter",
            f"lo_serving_tokens_total {serving['tokensTotal']}",
            "# TYPE lo_serving_lease_yields_total counter",
            f"lo_serving_lease_yields_total {serving['leaseYields']}",
        ]
        for metric, value_of in (
                ("lo_serving_latency_p50_ms",
                 lambda s: s["latency"]["p50Ms"]),
                ("lo_serving_latency_p99_ms",
                 lambda s: s["latency"]["p99Ms"]),
                ("lo_serving_queue_depth",
                 lambda s: s["queueDepth"])):
            lines.append(f"# TYPE {metric} gauge")
            for sess in serving["bySession"]:
                lines.append(
                    f'{metric}{{model="{esc(sess["model"])}"}} '
                    f'{value_of(sess)}')
        # paged-KV pool state per session (services/serving.py
        # PagedLMServingSession): free/shared pages, prefix reuse and
        # per-tenant page holdings
        # NB: pool size is a gauge, so the metric must not end in
        # _total (the suffix drives the TYPE annotation below)
        for metric, kv_value in (
                ("lo_serving_kv_pages",
                 lambda kv: kv["pagesTotal"]),
                ("lo_serving_kv_pages_free",
                 lambda kv: kv["pagesFree"]),
                ("lo_serving_kv_pages_shared",
                 lambda kv: kv["pagesShared"]),
                ("lo_serving_kv_alloc_failures_total",
                 lambda kv: kv["allocFailures"]),
                ("lo_serving_kv_prefills_skipped_total",
                 lambda kv: kv["prefix"]["prefillsSkipped"]),
                ("lo_serving_kv_pages_reused_total",
                 lambda kv: kv["prefix"]["pagesReused"])):
            rows = [s for s in serving["bySession"] if s.get("kv")]
            if not rows:
                break
            kind = ("counter" if metric.endswith("_total")
                    else "gauge")
            lines.append(f"# TYPE {metric} {kind}")
            for sess in rows:
                lines.append(
                    f'{metric}{{model="{esc(sess["model"])}"}} '
                    f'{kv_value(sess["kv"])}')
        lines_added_tenant = False
        for sess in serving["bySession"]:
            tenants = (sess.get("kv") or {}).get("tenants") or {}
            for tenant, tstats in sorted(tenants.items()):
                if not lines_added_tenant:
                    lines.append(
                        "# TYPE lo_serving_tenant_pages gauge")
                    lines_added_tenant = True
                lines.append(
                    f'lo_serving_tenant_pages{{model='
                    f'"{esc(sess["model"])}",tenant='
                    f'"{esc(tenant)}"}} {tstats["pages"]}')
        # serving goodput (observability/perf): decode tokens/s/chip
        # per LM session — the headline serving-efficiency gauge
        lines.append("# TYPE lo_serving_tokens_per_sec_per_chip gauge")
        for sess in serving["bySession"]:
            tps = (sess.get("perf") or {}).get(
                "decodeTokensPerSecPerChip")
            if tps is not None:
                lines.append(
                    f'lo_serving_tokens_per_sec_per_chip'
                    f'{{model="{esc(sess["model"])}"}} {tps}')
        # quantized serving: true KV bytes per cached token (int8 pool
        # + scale pool funded together, so int8 shows ~2x headroom) and
        # the latest drift-probe value per quantized session
        lines.append("# TYPE lo_serving_kv_bytes_per_token gauge")
        for sess in serving["bySession"]:
            bpt = (sess.get("kv") or {}).get("bytesPerToken")
            if bpt is not None:
                lines.append(
                    f'lo_serving_kv_bytes_per_token'
                    f'{{model="{esc(sess["model"])}"}} {bpt}')
        lines.append("# TYPE lo_serving_drift gauge")
        for sess in serving["bySession"]:
            drift = (sess.get("drift") or {}).get("value")
            if drift is not None:
                lines.append(
                    f'lo_serving_drift'
                    f'{{model="{esc(sess["model"])}"}} {drift}')
        # disaggregated serving + speculative decoding
        # (services/serving.py DisaggLMServingSession / spec path):
        # per-role latency over a CLOSED role set
        # (prefill/decode/draft — bounded cardinality by
        # construction), time-to-first-token, handoff volume and the
        # speculative acceptance rate
        for metric, of_sess in (
                ("lo_serving_ttft_p50_ms",
                 lambda s: (s.get("ttft") or {}).get("p50Ms")),
                ("lo_serving_ttft_p99_ms",
                 lambda s: (s.get("ttft") or {}).get("p99Ms")),
                ("lo_serving_accepted_tokens_per_step",
                 lambda s: (s.get("spec") or {}).get(
                     "acceptedTokensPerStep")),
                ("lo_serving_handoff_queue",
                 lambda s: (s.get("disagg") or {}).get(
                     "handoffQueue"))):
            rows = []
            for sess in serving["bySession"]:
                value = of_sess(sess)
                if value is not None:
                    rows.append((sess["model"], value))
            if rows:
                lines.append(f"# TYPE {metric} gauge")
                for model, value in rows:
                    lines.append(
                        f'{metric}{{model="{esc(model)}"}} {value}')
        rows = []
        for sess in serving["bySession"]:
            handoffs = (sess.get("disagg") or {}).get("handoffsTotal")
            if handoffs is not None:
                rows.append((sess["model"], handoffs))
        if rows:
            lines.append(
                "# TYPE lo_serving_handoffs_total counter")
            for model, value in rows:
                lines.append(
                    f'lo_serving_handoffs_total'
                    f'{{model="{esc(model)}"}} {value}')
        role_rows = []
        for sess in serving["bySession"]:
            for role, tracker in sorted(
                    (sess.get("roles") or {}).items()):
                role_rows.append((sess["model"], role, tracker))
        if role_rows:
            for metric, pkey in (
                    ("lo_serving_role_latency_p50_ms", "p50Ms"),
                    ("lo_serving_role_latency_p99_ms", "p99Ms")):
                lines.append(f"# TYPE {metric} gauge")
                for model, role, tracker in role_rows:
                    lines.append(
                        f'{metric}{{model="{esc(model)}",'
                        f'role="{esc(role)}"}} {tracker[pkey]}')
        # timed-dispatch gateway
        gateway = m["gateway"]
        lines += [
            "# TYPE lo_abandoned_dispatches gauge",
            f"lo_abandoned_dispatches {gateway['abandonedInflight']}",
            "# TYPE lo_abandoned_dispatches_total counter",
            f"lo_abandoned_dispatches_total "
            f"{gateway['abandonedTotal']}",
            "# TYPE lo_gateway_inflight gauge",
            f"lo_gateway_inflight {gateway['inflight']}",
            "# TYPE lo_gateway_saturated_total counter",
            f"lo_gateway_saturated_total {gateway['saturatedTotal']}",
        ]
        # roofline gauges per train job (observability/perf); absent
        # until a job records a steady-state window
        perf_jobs = (m.get("perf") or {}).get("jobs") or {}
        for metric, key in (("lo_mfu", "mfu"),
                            ("lo_tflops_per_chip",
                             "tflopsPerSecPerChip"),
                            ("lo_hbm_bw_util_frac", "hbmBwUtil")):
            rows = [(job, rep[key]) for job, rep in perf_jobs.items()
                    if rep.get(key) is not None]
            if rows:
                lines.append(f"# TYPE {metric} gauge")
                for job, value in rows:
                    lines.append(
                        f'{metric}{{job="{esc(job)}"}} {value}')
        # X-ray HBM attribution + sentinels (observability/xray): the
        # per-owner ledger gauge family and the retrace / implicit-
        # transfer counters
        xr = m.get("xray") or {}
        owners = xr.get("owners") or {}
        if owners:
            lines.append("# TYPE lo_hbm_attributed_bytes gauge")
            for owner, nbytes in sorted(owners.items()):
                lines.append(
                    f'lo_hbm_attributed_bytes{{owner="{esc(owner)}"}} '
                    f'{nbytes}')
        xr_counters = xr.get("counters") or {}
        lines += [
            "# TYPE lo_retraces_total counter",
            f"lo_retraces_total {xr_counters.get('retraces', 0)}",
            "# TYPE lo_implicit_transfers_total counter",
            f"lo_implicit_transfers_total "
            f"{xr_counters.get('implicitTransfers', 0)}",
        ]
        # cluster monitor + SLO watchdog gauges (absent when
        # LO_MONITOR=0, so scrapers see the series disappear rather
        # than freeze at the last value)
        cluster = m.get("cluster")
        if cluster:
            hbm = cluster.get("hbm") or {}
            sched = cluster.get("scheduler") or {}
            serving_sample = cluster.get("serving") or {}
            xray_sample = cluster.get("xray") or {}
            for metric, value in (
                    ("lo_hbm_bytes_in_use", hbm.get("bytesInUse")),
                    ("lo_hbm_peak_bytes_in_use",
                     hbm.get("peakBytesInUse")),
                    ("lo_hbm_headroom_frac", hbm.get("headroomFrac")),
                    ("lo_slice_fragmentation",
                     sched.get("fragmentation")),
                    ("lo_serving_queue_depth_total",
                     serving_sample.get("queueDepth")),
                    ("lo_host_rss_bytes", cluster.get("hostRssBytes")),
                    ("lo_hbm_unattributed_bytes",
                     xray_sample.get("unattributedBytes"))):
                if value is not None:
                    lines.append(f"# TYPE {metric} gauge")
                    lines.append(f"{metric} {value}")
        if "alertsFiring" in m:
            lines += [
                "# TYPE lo_alerts_firing gauge",
                f"lo_alerts_firing {m['alertsFiring']}",
            ]
            if m.get("alerts"):
                lines.append("# TYPE lo_alert_firing gauge")
                for alert in m["alerts"]:
                    lines.append(
                        f'lo_alert_firing{{alert="{esc(alert["name"])}"'
                        f',severity="{esc(alert["severity"])}"}} 1')
        # elastic autoscaler counters (absent when LO_AUTOSCALE=0)
        autoscaler = m.get("autoscaler")
        if autoscaler is not None:
            counters = autoscaler.get("counters") or {}
            lines += [
                "# TYPE lo_autoscaler_resizes_total counter",
                f'lo_autoscaler_resizes_total{{direction="shrink"}} '
                f"{counters.get('shrinksCompleted', 0)}",
                f'lo_autoscaler_resizes_total{{direction="grow"}} '
                f"{counters.get('growsCompleted', 0)}",
                "# TYPE lo_autoscaler_rollbacks_total counter",
                f"lo_autoscaler_rollbacks_total "
                f"{counters.get('rollbacks', 0)}",
                "# TYPE lo_autoscaler_dead_lettered_total counter",
                f"lo_autoscaler_dead_lettered_total "
                f"{counters.get('deadLettered', 0)}",
            ]
        # incident flight recorder (absent when LO_INCIDENTS=0)
        incidents = m.get("incidents")
        if incidents is not None:
            lines.append("# TYPE lo_incidents_total counter")
            for trig, n in sorted(
                    (incidents.get("byTrigger") or {}).items()):
                lines.append(
                    f'lo_incidents_total{{trigger="{esc(trig)}"}} {n}')
            lines += [
                "# TYPE lo_incident_bundles gauge",
                f"lo_incident_bundles {incidents['bundles']}",
                "# TYPE lo_incident_bytes gauge",
                f"lo_incident_bytes {incidents['bytes']}",
            ]
        # latency histograms: lo_dispatch_seconds, lo_lease_wait_...,
        # lo_serving_request_..., lo_compile_..., lo_checkpoint_commit_
        # — cumulative _bucket{le=...}/_sum/_count per the exposition
        # format, sharing the escaper above
        lines.extend(obs_hist.prometheus_lines(esc))
        return ("\n".join(lines) + "\n").encode()

    # ------------------------------------------------------------------
    def _route(self, method: str, path: str, params: Dict[str, Any],
               body: Optional[Dict[str, Any]],
               ) -> Tuple[int, Any, str]:
        prefix = self.ctx.config.api_prefix
        if path == "/health":
            return 200, self._health(), "application/json"
        if path == "/healthz":
            return self._healthz()
        if path == "/metrics":
            if params.get("format") == "prometheus":
                return (200, self.metrics_prometheus(),
                        "text/plain; version=0.0.4; charset=utf-8")
            return 200, self.metrics(), "application/json"
        if not path.startswith(prefix + "/"):
            return 404, {"result": "unknown route"}, "application/json"
        parts = [p for p in path[len(prefix):].split("/") if p]
        if parts and parts[0] == "observe":
            return self._observe(parts, params)
        if parts and parts[0] == "profile":
            return self._profile(method, body or {})
        if parts and parts[0] == "observability":
            return self._observability(method, parts, params,
                                       body or {})
        if parts and parts[0] == "serve":
            # serving sessions address the MODEL in the path (the
            # session IS the resource), so the generic
            # /{service}/{tool}/{name} dispatch doesn't fit
            return self._serve(method, parts, body or {})
        if len(parts) < 2 or parts[0] not in SERVICES:
            return 404, {"result": "unknown route"}, "application/json"
        service, tool = parts[0], parts[1]
        name = "/".join(parts[2:]) if len(parts) > 2 else None

        if method == "GET":
            return self._get(service, tool, name, params)
        if method == "POST":
            if name is not None:
                if name.endswith("/migrate") and \
                        len(name) > len("/migrate"):
                    return self._migrate_run(name[:-len("/migrate")])
                raise V.HttpError(V.HTTP_NOT_ACCEPTABLE,
                                  "POST takes no name in the path")
            return self._post(service, tool, body or {})
        if method == "PATCH":
            if name is None:
                raise V.HttpError(V.HTTP_NOT_ACCEPTABLE, "missing name")
            return self._patch(service, tool, name, body or {})
        if method == "DELETE":
            if name is None:
                raise V.HttpError(V.HTTP_NOT_ACCEPTABLE, "missing name")
            return self._delete(service, tool, name)
        return 405, {"result": "unsupported method"}, "application/json"

    # ------------------------------------------------------------------
    def _observability(self, method: str, parts: list,
                       params: Dict[str, Any],
                       body: Optional[Dict[str, Any]] = None,
                       ) -> Tuple[int, Any, str]:
        """Trace / timeline read surface (docs/OBSERVABILITY.md):

        - ``GET /observability/trace``              known trace ids
        - ``GET /observability/trace/{name}``       span tree JSON
        - ``GET /observability/trace/{name}?format=chrome``
          Chrome/Perfetto ``trace_event`` JSON (drag into ui.perfetto.dev)
        - ``GET /observability/timeline``           jobs with telemetry
        - ``GET /observability/timeline/{name}``    per-step ring +
          percentile summary
        - ``GET /observability/cluster``            resource-sampler
          rings (HBM, arena, slices, queues, RSS)
        - ``GET /observability/alerts``             SLO objectives +
          firing/ resolved alert history
        - ``GET /observability/autoscaler``         elastic-resize
          policy state: counters, last pressure signals, per-job
          backoff/dead-letter ledger (docs/SCALING.md "Elastic
          autoscaling")
        - ``GET /observability/perf``               jobs with perf
          reports + platform peaks
        - ``GET /observability/perf/{name}``        roofline report
          (live serving session, in-process train window, or the
          ``perf`` block stamped on terminal train metadata)
        - ``GET /observability/memory``             HBM attribution
          ledger: per-owner byte totals, bytes-in-use and the
          unattributed remainder (XLA temps / leaks) + sentinel
          counters
        - ``GET /observability/memory/{name}``      ledger rows tagged
          with one job / serving session / model name
        - ``GET /observability/compile/{name}``     compiled-artifact
          X-ray: per-program ``memory_analysis()`` (argument/output/
          temp/code bytes) and ``cost_analysis()`` extracts
        - ``GET  /observability/incidents``          captured debug
          bundles (docs/OBSERVABILITY.md "Incidents & flight
          recorder")
        - ``GET  /observability/incidents/{id}``     bundle manifest
        - ``GET  /observability/incidents/{id}/download``  the whole
          bundle as a tar stream
        - ``POST /observability/incidents``          manual on-demand
          capture (bypasses the trigger cooldown)

        Trace names may contain ``/`` (serving requests are
        ``serve/{model}/{seq}``), so the remaining path joins back up.
        """
        kind = parts[1] if len(parts) > 1 else ""
        if kind == "incidents":
            return self._incidents(method, parts, body or {})
        if method != "GET":
            return (405, {"result": "unsupported method"},
                    "application/json")
        name = "/".join(parts[2:])
        if kind == "trace":
            if not name:
                return (200, {"result": obs_trace.known_traces()},
                        "application/json")
            if params.get("format") == "chrome":
                doc = obs_export.chrome_trace(name)
            else:
                doc = obs_trace.tree(name)
            if doc is None:
                raise V.HttpError(
                    V.HTTP_NOT_FOUND,
                    f"no trace recorded for {name} (job never ran "
                    f"here, trace evicted, or LO_TRACE=0)")
            return 200, doc, "application/json"
        if kind == "timeline":
            if not name:
                return (200, {"result": obs_timeline.known_jobs()},
                        "application/json")
            summary = obs_timeline.summary(name)
            if summary is None:
                raise V.HttpError(
                    V.HTTP_NOT_FOUND,
                    f"no step telemetry recorded for {name}")
            return (200, {"job": name, "summary": summary,
                          "timeline": obs_timeline.entries(name)},
                    "application/json")
        if kind == "perf":
            platform = obs_perf.platform_summary()
            if not name:
                return (200, {"platform": platform,
                              "jobs": obs_perf.known_jobs()},
                        "application/json")
            # resolution order: live serving session -> in-process
            # train registry -> the perf block stamped on terminal
            # train metadata (survives the registry's LRU)
            report = self.ctx.serving.perf_report(name)
            if report is None:
                job = obs_perf.job_report(name)
                if job is not None:
                    report = {"kind": "train", "job": name,
                              "perf": job}
            if report is None:
                meta = self.ctx.catalog.get_metadata(name) or {}
                stamped = meta.get("perf")
                if stamped:
                    report = {"kind": "train", "job": name,
                              "perf": stamped, "terminal": True}
            if report is None:
                raise V.HttpError(
                    V.HTTP_NOT_FOUND,
                    f"no perf report for {name} (job never recorded "
                    f"a steady-state window here, or LO_PERF=0)")
            report["platform"] = platform
            return 200, report, "application/json"
        if kind == "memory":
            report = obs_xray.memory_report(name or None)
            if name and not report["entries"]:
                raise V.HttpError(
                    V.HTTP_NOT_FOUND,
                    f"no ledgered allocations tagged {name} (nothing "
                    f"resident for it right now, or LO_XRAY=0)")
            return 200, report, "application/json"
        if kind == "compile":
            if not name:
                return (200, {"result": obs_xray.known_compiles()},
                        "application/json")
            report = obs_xray.compile_report(name)
            if report is None:
                raise V.HttpError(
                    V.HTTP_NOT_FOUND,
                    f"no compiled-artifact report for {name} (job "
                    f"never compiled a step here, report evicted, or "
                    f"LO_XRAY=0)")
            return 200, report, "application/json"
        if kind == "cluster":
            monitor = getattr(self.ctx, "monitor", None)
            if monitor is None:
                raise V.HttpError(
                    V.HTTP_NOT_FOUND,
                    "cluster monitor disabled (LO_MONITOR=0)")
            return 200, monitor.snapshot(), "application/json"
        if kind == "alerts":
            monitor = getattr(self.ctx, "monitor", None)
            watchdog = getattr(monitor, "watchdog", None)
            if watchdog is None:
                raise V.HttpError(
                    V.HTTP_NOT_FOUND,
                    "SLO watchdog disabled (LO_MONITOR=0)")
            return 200, watchdog.snapshot(), "application/json"
        if kind == "autoscaler":
            autoscaler = getattr(self.ctx, "autoscaler", None)
            if autoscaler is None:
                raise V.HttpError(
                    V.HTTP_NOT_FOUND,
                    "elastic autoscaler disabled (LO_AUTOSCALE=0)")
            doc = autoscaler.stats()
            doc["migration"] = self.ctx.jobs.migration_stats()
            return 200, doc, "application/json"
        return 404, {"result": "unknown route"}, "application/json"

    # ------------------------------------------------------------------
    def _incidents(self, method: str, parts: list,
                   body: Dict[str, Any]) -> Tuple[int, Any, str]:
        """Incident flight-recorder surface (docs/OBSERVABILITY.md
        "Incidents & flight recorder"). Auto captures ride the
        trigger queue; POST here is the synchronous manual path —
        both are serialized by the recorder's commit lock, so they
        are race-safe against each other."""
        recorder = getattr(self.ctx, "incidents", None)
        if recorder is None:
            raise V.HttpError(
                V.HTTP_NOT_FOUND,
                "incident recorder disabled (LO_INCIDENTS=0)")
        if method == "POST":
            if len(parts) != 2:
                return (404, {"result": "unknown route"},
                        "application/json")
            manifest = recorder.capture("manual", body)
            return V.HTTP_CREATED, manifest, "application/json"
        if method != "GET":
            return (405, {"result": "unsupported method"},
                    "application/json")
        if len(parts) == 2:
            return (200, {"result": recorder.list()},
                    "application/json")
        iid = parts[2]
        if len(parts) == 4 and parts[3] == "download":
            data = recorder.tar_bytes(iid)
            if data is None:
                raise V.HttpError(V.HTTP_NOT_FOUND,
                                  f"no incident bundle {iid}")
            return 200, data, "application/x-tar"
        if len(parts) == 3:
            manifest = recorder.manifest(iid)
            if manifest is None:
                raise V.HttpError(V.HTTP_NOT_FOUND,
                                  f"no incident bundle {iid}")
            return 200, manifest, "application/json"
        return 404, {"result": "unknown route"}, "application/json"

    # ------------------------------------------------------------------
    def _serve(self, method: str, parts: list,
               body: Dict[str, Any]) -> Tuple[int, Any, str]:
        """Resident serving plane (docs/SERVING.md):

        - ``POST /serve/{model}``            create a session (201)
        - ``POST /serve/{model}/predict``    synchronous inference
        - ``GET  /serve`` / ``/serve/{model}``  stats
        - ``DELETE /serve/{model}``          teardown
        """
        serving = self.ctx.serving
        if method == "GET":
            if len(parts) == 1:
                return (200, {"result": serving.list_sessions()},
                        "application/json")
            if len(parts) == 2:
                return (200, serving.session_stats(parts[1]),
                        "application/json")
        elif method == "POST":
            if len(parts) == 2:
                return (V.HTTP_CREATED, serving.create(parts[1], body),
                        "application/json")
            if len(parts) == 3 and parts[2] == "predict":
                return (200, serving.predict(parts[1], body),
                        "application/json")
        elif method == "DELETE":
            if len(parts) == 2:
                return (200, serving.delete(parts[1]),
                        "application/json")
        else:
            return (405, {"result": "unsupported method"},
                    "application/json")
        return 404, {"result": "unknown route"}, "application/json"

    # ------------------------------------------------------------------
    def _health(self) -> Dict[str, Any]:
        info: Dict[str, Any] = {"status": "ok",
                                "jobsRunning": self.ctx.jobs.running()}
        from learningorchestra_tpu.runtime import distributed as dist

        # pod liveness FIRST and outside the topology try: a broken
        # distributed runtime is when host_info() is most likely to
        # raise, and that must not mask the degraded status
        failure = dist.pod_failure()
        if failure:
            info["status"] = "degraded"
            info["podFailure"] = failure
        try:
            info.update(dist.host_info())
            info["deviceCount"] = info["globalDevices"]
            info["devicePlatform"] = info["platform"]
        except Exception as e:  # noqa: BLE001
            info["deviceError"] = repr(e)
        return info

    def _healthz(self) -> Tuple[int, Any, str]:
        """Readiness probe (docs/OBSERVABILITY.md "/healthz"): 503
        while the server drains (load balancers stop routing before
        the listener dies) or while any page-severity SLO alert fires;
        200 otherwise. Distinct from ``/health``, which reports
        liveness detail and never changes the status code."""
        monitor = getattr(self.ctx, "monitor", None)
        watchdog = getattr(monitor, "watchdog", None)
        paging = [a for a in watchdog.firing()
                  if a["severity"] == "page"] if watchdog else []
        if self.ctx.draining:
            return (503, {"status": "draining"}, "application/json")
        if paging:
            return (503, {"status": "failing", "alerts": paging},
                    "application/json")
        return 200, {"status": "ok"}, "application/json"

    def _profile(self, method: str, body: Dict[str, Any],
                 ) -> Tuple[int, Any, str]:
        """``POST /profile {"action": "start"|"stop"}`` captures a
        ``jax.profiler`` trace (XLA device activity, HLO timelines —
        view in TensorBoard/Perfetto). ``GET /profile`` lists captured
        traces. The singleton session is owned by the process-wide
        :class:`~..observability.incidents.ProfilerGate` (shared with
        the flight recorder's triggered windows), which arms a
        ``LO_PROFILE_MAX_SECONDS`` auto-stop on every manual start;
        captured dirs under ``home/profiles`` are retention-bounded
        to the ``LO_PROFILE_KEEP`` newest. The reference's only
        profiling surface is the Spark UI + builder fitTime
        (SURVEY §5); this is first-party and covers every jitted
        computation in the process."""
        import os
        import time as time_mod

        from learningorchestra_tpu.observability import \
            incidents as obs_incidents

        gate = self._profiler_gate
        root = os.path.join(self.ctx.config.home, "profiles")
        if method == "GET":
            traces = sorted(os.listdir(root)) \
                if os.path.isdir(root) else []
            doc: Dict[str, Any] = {"active": gate.active() is not None,
                                   "traces": traces}
            auto_stop = gate.last_auto_stop()
            if auto_stop is not None:
                doc["lastAutoStop"] = auto_stop
            return 200, doc, "application/json"
        if method != "POST":
            return 405, {"result": "unsupported method"}, "application/json"
        action = (body.get("action") or "").lower()
        if action == "start":
            trace_dir = os.path.join(
                root,
                f"{time_mod.strftime('%Y%m%d-%H%M%S')}-"
                f"{time_mod.time_ns() % 1_000_000:06d}")
            started = gate.try_start(
                trace_dir,
                max_seconds=float(getattr(
                    self.ctx.config, "profile_max_seconds", 0) or 0))
            if not started:
                raise V.HttpError(V.HTTP_NOT_ACCEPTABLE,
                                  "a trace is already active")
            return 201, {"result": trace_dir}, "application/json"
        if action == "stop":
            # the gate clears its active marker even when stop_trace
            # raises (the raise propagates to the generic 500
            # handler), so a failed stop never wedges later starts
            trace_dir = gate.stop()
            if trace_dir is None:
                raise V.HttpError(V.HTTP_NOT_ACCEPTABLE,
                                  "no active trace")
            n_files = sum(len(fs) for _, _, fs in os.walk(trace_dir))
            obs_incidents.prune_dirs(root, int(getattr(
                self.ctx.config, "profile_keep", 0) or 0))
            return 200, {"result": trace_dir,
                         "files": n_files}, "application/json"
        raise V.HttpError(V.HTTP_NOT_ACCEPTABLE,
                          "action must be 'start' or 'stop'")

    def _post(self, service: str, tool: str, body: Dict[str, Any],
              ) -> Tuple[int, Any, str]:
        if service == "dataset":
            status, payload = self.dataset.create(body, tool)
        elif service == "model":
            status, payload = self.model.create(body, tool)
        elif service in EXECUTION_VERBS:
            status, payload = self.execution.create(body, service, tool)
        elif service == "explore" and tool == "histogram":
            status, payload = self.histogram.create(body, tool)
        elif service == "explore":
            status, payload = self.dbexec.create(body, service, tool)
        elif service == "transform" and tool == "projection":
            status, payload = self.projection.create(body, tool)
        elif service == "transform" and tool == "dataType":
            status, payload = self.datatype.create(body, tool)
        elif service == "transform":
            status, payload = self.dbexec.create(body, service, tool)
        elif service == "function":
            status, payload = self.function.create(body, tool)
        elif service == "builder":
            status, payload = self.builder.create(body, tool)
        else:
            raise V.HttpError(404, "unknown route")
        return status, payload, "application/json"

    def _patch(self, service: str, tool: str, name: str,
               body: Dict[str, Any]) -> Tuple[int, Any, str]:
        if service == "model":
            status, payload = self.model.update(name, body, tool)
        elif service in EXECUTION_VERBS:
            status, payload = self.execution.update(name, body, service,
                                                    tool)
        elif service in ("explore", "transform"):
            status, payload = self.dbexec.update(name, body, service, tool)
        elif service == "function":
            status, payload = self.function.update(name, body, tool)
        else:
            raise V.HttpError(V.HTTP_NOT_ACCEPTABLE,
                              f"PATCH unsupported for {service}")
        return status, payload, "application/json"

    def _delete(self, service: str, tool: str, name: str,
                ) -> Tuple[int, Any, str]:
        # ``DELETE .../{name}/run`` cancels the RUNNING JOB, keeping
        # the collection (safe_name forbids "/", so no real collection
        # can shadow the suffix). The job's cancel token flips and the
        # terminal ``cancelled`` document is written at the next
        # cooperative check (docs/LIFECYCLE.md).
        if name.endswith("/run") and len(name) > len("/run"):
            return self._cancel_run(name[:-len("/run")])
        if service == "dataset":
            status, payload = self.dataset.delete_file(name)
        elif service == "model":
            status, payload = self.model.delete(name, tool)
        elif service in EXECUTION_VERBS:
            status, payload = self.execution.delete(name, service, tool)
        elif service in ("explore", "transform", "function", "builder"):
            status, payload = self.dataset.delete_file(name)
        else:
            raise V.HttpError(404, "unknown route")
        return status, payload, "application/json"

    def _cancel_run(self, name: str) -> Tuple[int, Any, str]:
        if self.ctx.catalog.get_metadata(name) is None:
            raise V.HttpError(V.HTTP_NOT_FOUND,
                              f"{V.MESSAGE_NONEXISTENT_FILE}: {name}")
        if not self.ctx.jobs.cancel(name):
            raise V.HttpError(V.HTTP_NOT_ACCEPTABLE,
                              f"no cancellable job for {name} (already "
                              f"finished or never submitted here)")
        return 200, {"result": f"cancellation requested for {name}"}, \
            "application/json"

    def _migrate_run(self, name: str) -> Tuple[int, Any, str]:
        # ``POST .../{name}/migrate`` asks the RUNNING JOB to move to
        # a fresh slice placement at its next epoch boundary
        # (docs/SCALING.md §7); refused (406) when the job is not a
        # live migratable mesh job — finished, never submitted here,
        # whole-mesh, or multi-host.
        if self.ctx.catalog.get_metadata(name) is None:
            raise V.HttpError(V.HTTP_NOT_FOUND,
                              f"{V.MESSAGE_NONEXISTENT_FILE}: {name}")
        if not self.ctx.jobs.migrate(name):
            raise V.HttpError(V.HTTP_NOT_ACCEPTABLE,
                              f"no migratable job for {name} (not "
                              f"running, not sliced, or multi-host)")
        return 200, {"result": f"migration requested for {name}"}, \
            "application/json"

    def _get(self, service: str, tool: str, name: Optional[str],
             params: Dict[str, Any]) -> Tuple[int, Any, str]:
        now = time.monotonic()
        if name is None:
            # listing: every collection of this type (reference routes
            # list GETs to the dataset reader with ?type=,
            # krakend.json:722-757). Cached against the global change
            # seq — any create/update/delete invalidates.
            if self.read_cache.enabled:
                key = ("list", service, tool)
                version = self.ctx.catalog.latest_seq()
                hit = self.read_cache.get(key, version, now)
                if hit is not None:
                    return hit[0], hit[1], "application/json"
            type_string = D.normalize_type(f"{service}/{tool}")
            payload = {"result": self.ctx.catalog.list_collections(
                type_string)}
            if self.read_cache.enabled:
                self.read_cache.put(key, version, now, 200, payload)
            return 200, payload, "application/json"
        # explore plots are PNGs (reference send_file image/png,
        # database_executor server.py:151-166); paged/queried GETs
        # still read the JSON documents so status polling works
        has_paging = any(k in params for k in ("skip", "limit", "query"))
        if service == "explore" and tool != "histogram" and not has_paging:
            meta = self.ctx.catalog.get_metadata(name)
            if meta is not None and str(
                    meta.get(D.TYPE_FIELD, "")).startswith("explore/"):
                try:
                    png, content_type = self.dbexec.image_response(name)
                    return 200, png, content_type
                except Exception:  # noqa: BLE001 - fall through to JSON
                    pass
        skip = int(params.get("skip", 0) or 0)
        limit = params.get("limit")
        limit = int(limit) if limit not in (None, "") else None
        query = parse_query_param(params.get("query"))
        # universal read, cached per (name, page) against the
        # collection's content version: change-feed seq for docs +
        # parquet file stats for rows (appends bypass the feed). A
        # poller spinning on ?limit=1 stops re-reading sqlite/parquet;
        # the doc append that flips ``finished`` bumps the seq and
        # invalidates (krakend.json:1769 "cache_ttl" parity, made
        # staleness-proof).
        key = ("read", name, skip, limit, params.get("query"))
        if self.read_cache.enabled:
            version = (self.ctx.catalog.collection_seq(name),
                       self.ctx.catalog.dataset_version(name))
            hit = self.read_cache.get(key, version, now)
            if hit is not None:
                return hit[0], hit[1], "application/json"
        status, payload = self.dataset.read_file(
            name, skip=skip, limit=limit, query=query)
        if self.read_cache.enabled:
            self.read_cache.put(key, version, now, status, payload)
        return status, payload, "application/json"

    # ------------------------------------------------------------------
    def _observe(self, parts, params) -> Tuple[int, Any, str]:
        """``GET /observe/{name}?seq=N&timeout=S``: block until the
        collection changes past sequence N, then return the new changes
        + current metadata (the reference's Observe service is a
        client-side Mongo change stream; README.md:81)."""
        if len(parts) < 2:
            return 200, {"result": {"seq": self.ctx.catalog.latest_seq()}}, \
                "application/json"
        name = parts[1]
        seq = int(params.get("seq", 0) or 0)
        timeout = min(float(params.get("timeout", 25) or 25), 120.0)
        # under a gateway deadline the long-poll window clamps to just
        # inside it: the client gets an empty 200 (and re-polls, the
        # normal long-poll idiom) instead of a 504 whose abandoned
        # dispatch would sit in the condition wait for the full window
        gateway = self.ctx.config.request_timeout_seconds
        if gateway > 0:
            timeout = min(timeout, max(0.05, gateway - 0.1))
        changes = self.ctx.catalog.watch(seq, collection=name,
                                         timeout=timeout)
        return 200, {"result": {
            "changes": changes,
            "seq": self.ctx.catalog.latest_seq(),
            "metadata": self.ctx.catalog.get_metadata(name),
        }}, "application/json"


# ----------------------------------------------------------------------
class _Handler(BaseHTTPRequestHandler):
    api: Api = None  # set by make_server
    protocol_version = "HTTP/1.1"

    # quiet the default stderr-per-request logging
    def log_message(self, format, *args):  # noqa: A002
        pass

    def _read_body(self) -> Optional[Dict[str, Any]]:
        length = int(self.headers.get("Content-Length") or 0)
        if not length:
            return None
        raw = self.rfile.read(length)
        try:
            body = json.loads(raw)
        except json.JSONDecodeError:
            return None
        return body if isinstance(body, dict) else None

    def _respond(self, method: str) -> None:
        parsed = urlparse(self.path)
        params = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        body = self._read_body() if method in ("POST", "PATCH") else None
        timeout = self.api.ctx.config.request_timeout_seconds
        if timeout > 0:
            # KrakenD proxies with a per-endpoint "timeout": "10s"
            # (krakend.json:1770): the client gets 504 while the
            # backend call keeps running — same semantics here (the
            # dispatch daemon thread finishes its work; only the
            # response is abandoned). A thread per timed request, not
            # a shared pool: N abandoned slow dispatches must never
            # starve unrelated requests, and daemon threads don't
            # block interpreter exit. Metrics are recorded HERE with
            # the status the client actually saw (record=False below).
            t0 = time.monotonic()
            result: list = []
            done = threading.Event()
            # abandoned dispatches are invisible by construction — the
            # 504 already went out — so they are capped and counted
            # (LO_GATEWAY_MAX_INFLIGHT; lo_abandoned_dispatches on
            # /metrics): at the cap new timed requests get an instant
            # 503 instead of stacking another thread on a slow backend
            api = self.api
            cap = api.ctx.config.gateway_max_inflight
            finished = [False]
            abandoned = [False]
            with api._gateway_lock:
                saturated = cap > 0 and api._gateway_inflight >= cap
                if saturated:
                    api._gateway_saturated_total += 1
                else:
                    api._gateway_inflight += 1
            if saturated:
                status, payload, content_type = (
                    503,
                    {"result": f"gateway saturated ({cap} timed "
                               f"dispatches in flight) — retry with "
                               f"backoff"},
                    "application/json")
                api._record_metrics(method, parsed.path, status,
                                    time.monotonic() - t0)
                self._send(status, payload, content_type)
                return

            def run_dispatch() -> None:
                try:
                    result.append(api.dispatch(
                        method, parsed.path, params, body,
                        record=False))
                    done.set()
                finally:
                    with api._gateway_lock:
                        api._gateway_inflight -= 1
                        finished[0] = True
                        if abandoned[0]:
                            api._gateway_abandoned_inflight -= 1

            threading.Thread(target=run_dispatch, daemon=True,
                             name="lo-gateway").start()
            if done.wait(timeout):
                status, payload, content_type = result[0]
            else:
                with api._gateway_lock:
                    # the dispatch may land between wait() expiring
                    # and this lock — only a still-running one counts
                    # as abandoned (its finally block decrements)
                    if not finished[0]:
                        abandoned[0] = True
                        api._gateway_abandoned_total += 1
                        api._gateway_abandoned_inflight += 1
                status, payload, content_type = (
                    504,
                    {"result": f"request timed out after {timeout:g}s"},
                    "application/json")
            self.api._record_metrics(method, parsed.path, status,
                                     time.monotonic() - t0)
        else:
            status, payload, content_type = self.api.dispatch(
                method, parsed.path, params, body)
        self._send(status, payload, content_type)

    def _send(self, status: int, payload: Any,
              content_type: str) -> None:
        if isinstance(payload, (bytes, bytearray)):
            data = bytes(payload)
        else:
            data = json.dumps(payload).encode()
            content_type = "application/json"
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802
        self._respond("GET")

    def do_POST(self):  # noqa: N802
        self._respond("POST")

    def do_PATCH(self):  # noqa: N802
        self._respond("PATCH")

    def do_DELETE(self):  # noqa: N802
        self._respond("DELETE")


class RestServer:
    """Owns the HTTP server + its ServiceContext."""

    def __init__(self, context: Optional[ServiceContext] = None,
                 host: Optional[str] = None, port: Optional[int] = None):
        self.api = Api(context)
        cfg = self.api.ctx.config
        handler = type("BoundHandler", (_Handler,), {"api": self.api})
        self.httpd = ThreadingHTTPServer(
            (host or cfg.host, cfg.port if port is None else port), handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self.httpd.server_address[:2]

    @property
    def base_url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "RestServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True, name="lo-rest")
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def stop(self) -> None:
        # flip /healthz to 503 while the listener still answers, so a
        # load balancer health-checking this node drains it first
        self.api.ctx.begin_drain()
        self.httpd.shutdown()
        self.httpd.server_close()
        self.api.ctx.close()


def main(argv=None) -> None:
    import argparse

    from learningorchestra_tpu.config import Config, get_config, set_config

    parser = argparse.ArgumentParser(
        description="learningOrchestra-TPU REST server")
    parser.add_argument("--host", default=None)
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument("--home", default=None,
                        help="storage root (default LO_HOME or ./.lo_store)")
    parser.add_argument("--config", default=None,
                        help="JSON config file")
    parser.add_argument("--coordinator", default=None,
                        help="host:port of process 0 for multi-host runs "
                             "(default LO_COORDINATOR)")
    parser.add_argument("--num-hosts", type=int, default=None,
                        help="total processes in the pod "
                             "(default LO_NUM_HOSTS)")
    parser.add_argument("--host-id", type=int, default=None,
                        help="this process's index (default LO_HOST_ID)")
    args = parser.parse_args(argv)
    if args.config:
        set_config(Config.from_file(args.config))
    if args.home:
        set_config(get_config().replace(home=args.home))

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        # honor the operator's platform choice even when a site hook
        # force-registers an accelerator plugin through jax.config
        # (config wins over the env var, so re-assert it here, before
        # anything touches the backend)
        import jax

        jax.config.update("jax_platforms", plat)

    from learningorchestra_tpu.runtime import distributed as dist

    multi_host = dist.initialize(coordinator_address=args.coordinator,
                                 num_processes=args.num_hosts,
                                 process_id=args.host_id)
    if multi_host and not dist.is_coordinator():
        # workers never serve REST: they follow the coordinator's job
        # broadcasts so every global-mesh jit has all participants
        info = dist.host_info()
        print(f"learningOrchestra-TPU worker {info['processIndex']}/"
              f"{info['processCount']} following coordinator", flush=True)
        dist.HostBridge().follow(lambda msg: None)
        return

    server = RestServer(host=args.host, port=args.port)
    host, port = server.address
    print(f"learningOrchestra-TPU REST on http://{host}:{port}"
          f"{get_config().api_prefix}", flush=True)

    # SIGTERM (the k8s/systemd stop signal) drains like Ctrl-C: stop
    # accepting requests, then the shutdown path below runs. In-flight
    # jobs left unfinished are requeued by the next boot's
    # recover_unfinished().
    import signal as signal_mod

    def _terminate(signum, frame):  # noqa: ARG001
        raise KeyboardInterrupt

    signal_mod.signal(signal_mod.SIGTERM, _terminate)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.stop()
    finally:
        if multi_host:
            import time as time_mod

            # drain in-flight mesh jobs first: a job thread publishing
            # its fan-out after our shutdown broadcast would block on a
            # collective the workers already left
            deadline = time_mod.monotonic() + 60
            while server.api.ctx.jobs.running() and \
                    time_mod.monotonic() < deadline:
                time_mod.sleep(0.25)
            try:
                dist.HostBridge().publish({"op": "shutdown"})
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
            dist.shutdown()


if __name__ == "__main__":
    main()
