"""Async tiered checkpointing (runtime/async_ckpt.py) and the
checkpoint hardening that rides with it (runtime/checkpoint.py):
sharded layout, verified partial restore with fallback, bounded
quarantine, atomic progress sidecar, crash-shaped step dirs."""

import dataclasses
import json
import os

import numpy as np
import pytest

from learningorchestra_tpu.runtime.async_ckpt import (
    AsyncCheckpointError, AsyncCheckpointManager, wrap_checkpointer)
from learningorchestra_tpu.runtime.checkpoint import (
    CheckpointCorrupted, Checkpointer)


def _tree(step):
    return {"step": np.int32(step),
            "params": {"w": np.full((4, 4), float(step), np.float32),
                       "b": np.arange(8, dtype=np.float32) + step}}


def _corrupt_first_payload(ckpt_dir, step):
    """Flip bytes in one payload file WITHOUT changing its size, so
    the cheap stat check passes and the sha256 re-hash is what must
    catch it."""
    step_dir = os.path.join(ckpt_dir, str(step))
    names = sorted(n for n in os.listdir(step_dir)
                   if n.endswith(".msgpack"))
    path = os.path.join(step_dir, names[0])
    with open(path, "r+b") as f:
        f.seek(0)
        f.write(b"\xde\xad\xbe\xef")


def _arm_faults(tmp_config, spec):
    from learningorchestra_tpu import config as config_mod
    from learningorchestra_tpu.services import faults

    config_mod.set_config(
        dataclasses.replace(tmp_config, fault_inject=spec))
    faults.reset()


# ----------------------------------------------------------------------
# async manager
# ----------------------------------------------------------------------
def test_async_fifo_commits_and_reads_barrier(tmp_config, tmp_path):
    mgr = AsyncCheckpointManager(
        Checkpointer(str(tmp_path / "ck"), max_to_keep=10))
    try:
        for step in (1, 2, 3, 5, 8):
            mgr.save(step, _tree(step))
        # every read barriers first: what was saved is on disk
        assert mgr.latest_step() == 8
        restored = mgr.restore(_tree(0))
        np.testing.assert_array_equal(
            np.asarray(restored["params"]["w"]),
            _tree(8)["params"]["w"])
        # FIFO worker landed every step, in order (none overwritten
        # out of order / dropped)
        on_disk = sorted(int(d) for d in os.listdir(tmp_path / "ck")
                         if d.isdigit())
        assert on_disk == [1, 2, 3, 5, 8]
    finally:
        mgr.close()


def test_async_meta_rides_the_same_fifo(tmp_config, tmp_path):
    mgr = AsyncCheckpointManager(Checkpointer(str(tmp_path / "ck")))
    try:
        mgr.save(1, _tree(1))
        mgr.save_meta({"epoch": 7})
        # load_meta barriers, so the sidecar commit has landed
        assert mgr.load_meta() == {"epoch": 7}
    finally:
        mgr.close()


def test_async_commit_failure_latches_on_train_thread(
        tmp_config, tmp_path):
    _arm_faults(tmp_config, "ckpt_async_commit:1:raise")
    from learningorchestra_tpu.services import faults

    mgr = AsyncCheckpointManager(Checkpointer(str(tmp_path / "ck")))
    try:
        mgr.save(1, _tree(1))  # worker fails, latches, keeps draining
        with pytest.raises(AsyncCheckpointError):
            mgr.wait_until_finished()
        # the latched error re-raises on the NEXT save too
        with pytest.raises(AsyncCheckpointError):
            mgr.save(2, _tree(2))
        # the failed commit never left an accepted step on disk
        probe = Checkpointer(str(tmp_path / "ck"))
        assert probe.latest_step() is None
        probe.close()
    finally:
        faults.reset()
        # close() drains WITHOUT re-raising (teardown must not mask
        # the job's real error) and must not hang on a latched error
        mgr.close()


def test_async_save_after_close_refuses(tmp_config, tmp_path):
    mgr = AsyncCheckpointManager(Checkpointer(str(tmp_path / "ck")))
    mgr.close()
    with pytest.raises(AsyncCheckpointError):
        mgr.save(1, _tree(1))


def test_wrap_checkpointer_honors_config(tmp_config, tmp_path):
    sync = Checkpointer(str(tmp_path / "ck"))
    off = dataclasses.replace(tmp_config, ckpt_async=False)
    assert wrap_checkpointer(sync, config=off) is sync
    cfg = dataclasses.replace(tmp_config, ckpt_async=True,
                              ckpt_inflight=3)
    wrapped = wrap_checkpointer(sync, config=cfg)
    assert isinstance(wrapped, AsyncCheckpointManager)
    assert wrapped._queue.maxsize == 3
    wrapped.close()


# ----------------------------------------------------------------------
# sharded layout
# ----------------------------------------------------------------------
def test_sharded_layout_roundtrip(tmp_config, tmp_path):
    ckpt = Checkpointer(str(tmp_path / "ck"), shards=2)
    try:
        ckpt.save(3, _tree(3))
        step_dir = tmp_path / "ck" / "3"
        names = sorted(os.listdir(step_dir))
        assert "shard-00000-of-00002.msgpack" in names
        assert "shard-00001-of-00002.msgpack" in names
        assert "checkpoint.msgpack" not in names
        with open(step_dir / "manifest.json") as f:
            manifest = json.load(f)
        assert set(manifest["files"]) == {n for n in names
                                          if n.endswith(".msgpack")}
        restored = ckpt.restore(_tree(0))
        np.testing.assert_array_equal(
            np.asarray(restored["params"]["b"]),
            _tree(3)["params"]["b"])
    finally:
        ckpt.close()


def test_shard_corruption_quarantines_and_falls_back(
        tmp_config, tmp_path):
    ckpt = Checkpointer(str(tmp_path / "ck"), max_to_keep=5, shards=2)
    try:
        ckpt.save(1, _tree(1))
        ckpt.save(2, _tree(2))
        _corrupt_first_payload(str(tmp_path / "ck"), 2)
        # size check still passes, so latest_step is fooled...
        assert ckpt.latest_step() == 2
        # ...but the re-hashing restore catches it, quarantines the
        # torn step and falls back to the previous verified one
        with pytest.warns(RuntimeWarning, match="quarantined"):
            restored = ckpt.restore(_tree(0))
        np.testing.assert_array_equal(
            np.asarray(restored["params"]["w"]),
            _tree(1)["params"]["w"])
        qdir = tmp_path / "ck" / ".quarantine"
        assert len(os.listdir(qdir)) == 1
        assert ckpt.latest_step() == 1
    finally:
        ckpt.close()


def test_restore_partial_verifies_and_falls_back(tmp_config, tmp_path):
    ckpt = Checkpointer(str(tmp_path / "ck"), max_to_keep=5, shards=2)
    try:
        ckpt.save(1, _tree(1))
        ckpt.save(2, _tree(2))
        _corrupt_first_payload(str(tmp_path / "ck"), 2)
        with pytest.warns(RuntimeWarning, match="quarantined"):
            out = ckpt.restore_partial({"params": _tree(0)["params"]})
        np.testing.assert_array_equal(
            np.asarray(out["params"]["w"]), _tree(1)["params"]["w"])
        # an EXPLICITLY requested corrupt step has no substitute
        ckpt.save(4, _tree(4))
        _corrupt_first_payload(str(tmp_path / "ck"), 4)
        with pytest.warns(RuntimeWarning, match="quarantined"):
            with pytest.raises(CheckpointCorrupted):
                ckpt.restore_partial({"params": _tree(0)["params"]},
                                     step=4)
    finally:
        ckpt.close()


# ----------------------------------------------------------------------
# quarantine bound + crash shapes + sidecar
# ----------------------------------------------------------------------
def test_quarantine_is_bounded(tmp_config, tmp_path):
    from learningorchestra_tpu import config as config_mod

    config_mod.set_config(
        dataclasses.replace(tmp_config, ckpt_quarantine_keep=2))
    ckpt = Checkpointer(str(tmp_path / "ck"), max_to_keep=10)
    try:
        for step in range(1, 6):
            ckpt.save(step, _tree(step))
            _corrupt_first_payload(str(tmp_path / "ck"), step)
        with pytest.warns(RuntimeWarning, match="quarantined"):
            assert ckpt.restore(_tree(0)) is None  # nothing verifies
        qdir = tmp_path / "ck" / ".quarantine"
        assert len(os.listdir(qdir)) <= 2
    finally:
        ckpt.close()


def test_crash_mid_commit_never_accepted(tmp_config, tmp_path):
    ckpt = Checkpointer(str(tmp_path / "ck"))
    try:
        ckpt.save(1, _tree(1))
        # a crash mid-commit leaves a .tmp stage dir — readers must
        # never see it as a step
        tmp_dir = tmp_path / "ck" / "2.tmp"
        os.makedirs(tmp_dir)
        with open(tmp_dir / "checkpoint.msgpack", "wb") as f:
            f.write(b"torn")
        assert ckpt.latest_step() == 1
        restored = ckpt.restore(_tree(0))
        np.testing.assert_array_equal(
            np.asarray(restored["params"]["w"]),
            _tree(1)["params"]["w"])
        # a manifest naming a missing payload (post-rename tamper) is
        # skipped by the cheap check too
        bad = tmp_path / "ck" / "3"
        os.makedirs(bad)
        with open(bad / "manifest.json", "w") as f:
            json.dump({"step": 3, "wallTime": 0.0,
                       "files": {"checkpoint.msgpack":
                                 {"sha256": "0" * 64, "bytes": 4}}}, f)
        assert ckpt.latest_step() == 1
    finally:
        ckpt.close()


def test_save_meta_atomic_and_torn_sidecar_ignored(
        tmp_config, tmp_path):
    ckpt = Checkpointer(str(tmp_path / "ck"))
    try:
        ckpt.save_meta({"epoch": 3, "step": 12})
        assert not os.path.exists(
            tmp_path / "ck" / "progress.json.tmp")
        assert ckpt.load_meta() == {"epoch": 3, "step": 12}
        with open(tmp_path / "ck" / "progress.json", "w") as f:
            f.write('{"epoch": 3, "ste')  # torn write
        assert ckpt.load_meta() is None
    finally:
        ckpt.close()
