"""Cooperative preemption + cancellation hooks for long device jobs.

The reference gives each Spark service its own FAIR scheduler pool so
a long job cannot monopolize the cluster
(reference spark_image/fairscheduler.xml:1-8, builder_image
server.py:57-63). The TPU analogue: the mesh is an exclusive lease
(services/scheduler.FairLease), and long engine fits offer to YIELD
the lease at epoch boundaries — per-epoch orbax checkpoints make the
hand-off durable, and since all jobs share one process the model
state stays live in memory across the yield.

The engine can't import the services layer (layering), so the lease
installs a thread-local callback here and the engine's epoch loops
call :func:`maybe_yield` between epochs. No lease installed (direct
library use, tests, workers) → no-op.

The SAME yield points double as cancellation points: the job manager
installs a :class:`CancelToken` per job thread and the engine's
epoch/step loops call :func:`check_cancel` / :func:`heartbeat` — so a
deadline expiry or a ``DELETE .../run`` surfaces as
:class:`JobCancelled` at the next safe boundary, the lease is
released, and no single request can wedge the accelerator
(docs/LIFECYCLE.md).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

_tls = threading.local()


class JobCancelled(Exception):
    """Cooperative cancellation signal. ``reason`` is the terminal
    lifecycle state it produces: ``"timedOut"`` (deadline expired),
    ``"cancelled"`` (user DELETE), or ``"stalled"`` (watchdog
    escalation). Raised from :meth:`CancelToken.check` at the engine /
    sandbox / scheduler yield points, caught by the job manager."""

    def __init__(self, reason: str, message: str = ""):
        super().__init__(message or f"job {reason}")
        self.reason = reason


class CancelToken:
    """Per-job cancellation + progress record.

    - ``cancel(reason)`` flips a latched event (first reason wins:
      a user cancel that races the deadline keeps its attribution);
    - ``deadline`` (``time.monotonic`` basis) is checked lazily on
      every :meth:`cancelled` call, so an expired job cancels itself
      at its next cooperative check with no timer thread per job;
    - ``beat(**progress)`` publishes a heartbeat (step/epoch
      counters) the stall watchdog reads via :meth:`heartbeat_age`.
    """

    def __init__(self, deadline: Optional[float] = None):
        self._event = threading.Event()
        self._lock = threading.Lock()
        self.deadline = deadline
        self.reason: Optional[str] = None
        self.progress: Dict[str, Any] = {}
        self.last_beat: Optional[float] = None
        self.started: Optional[float] = None

    # -- cancellation --------------------------------------------------
    def cancel(self, reason: str = "cancelled") -> bool:
        """Latch the token. Returns True if this call set the reason
        (False when already cancelled — the original reason stands)."""
        with self._lock:
            if self.reason is None:
                self.reason = reason
                self._event.set()
                return True
            return False

    def cancelled(self) -> bool:
        if self._event.is_set():
            return True
        if self.deadline is not None and \
                time.monotonic() >= self.deadline:
            self.cancel("timedOut")
            return True
        return False

    def check(self) -> None:
        if self.cancelled():
            raise JobCancelled(self.reason or "cancelled")

    def wait(self, seconds: float) -> bool:
        """Cancel-aware sleep (retry backoff): returns True the moment
        the token cancels, False after the full wait. Deadline-based
        expiry is honored too — the wait is clipped so a backoff never
        outsleeps the job's own deadline."""
        end = time.monotonic() + max(0.0, seconds)
        while True:
            if self.cancelled():
                return True
            now = time.monotonic()
            if now >= end:
                return False
            step = end - now
            if self.deadline is not None:
                step = min(step, max(0.0, self.deadline - now))
            if self._event.wait(min(step, 0.5) or 0.001):
                return True

    def remaining(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return max(0.0, self.deadline - time.monotonic())

    # -- progress heartbeat --------------------------------------------
    def beat(self, **progress: Any) -> None:
        with self._lock:
            self.last_beat = time.monotonic()
            self.progress.update(progress)

    def heartbeat_age(self) -> Optional[float]:
        """Seconds since the last beat; None before the first beat
        (jobs that never publish progress — sklearn fits, ingests —
        are exempt from stall detection)."""
        last = self.last_beat
        return None if last is None else time.monotonic() - last

    def progress_snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self.progress)


# ----------------------------------------------------------------------
# thread-local install points (yield + cancel are separate slots: the
# lease CM owns the yield slot, the job manager owns the cancel slot)
# ----------------------------------------------------------------------
def install(fn: Callable[[], None],
            contended_fn: Optional[Callable[[], bool]] = None) -> None:
    """Register ``fn`` as this thread's between-epochs yield point
    (called by the mesh lease when a job thread acquires it).
    ``contended_fn`` lets long jobs ASK whether a yield is wanted
    without performing one — sweeps use it to drain in-flight trials
    before handing the lease over."""
    _tls.fn = fn
    _tls.contended = contended_fn


def clear() -> None:
    _tls.fn = None
    _tls.contended = None


def current() -> Optional[Callable[[], None]]:
    return getattr(_tls, "fn", None)


def contended() -> bool:
    """True when another job is waiting for this thread's lease (a
    yield at the next safe point would hand it over). Always False
    outside the service layer."""
    fn = getattr(_tls, "contended", None)
    return bool(fn()) if fn is not None else False


def snapshot():
    """(yield_fn, contended_fn) for save/restore around nested
    installs (the lease CM restores its predecessor on exit)."""
    return (getattr(_tls, "fn", None), getattr(_tls, "contended", None))


def restore(snap) -> None:
    _tls.fn, _tls.contended = snap


def install_cancel(token: Optional[CancelToken]) -> None:
    """Bind ``token`` to this thread (job manager, around each job)."""
    _tls.cancel = token


def clear_cancel() -> None:
    _tls.cancel = None


def current_cancel() -> Optional[CancelToken]:
    return getattr(_tls, "cancel", None)


def check_cancel() -> None:
    """Raise :class:`JobCancelled` if this thread's job was cancelled
    or ran past its deadline. No token installed → no-op (direct
    library use, tests, workers)."""
    token = current_cancel()
    if token is not None:
        token.check()


def heartbeat(**progress: Any) -> None:
    """Publish step/epoch progress for the stall watchdog. No token
    installed → no-op."""
    token = current_cancel()
    if token is not None:
        token.beat(**progress)


def maybe_yield() -> None:
    """Engine epoch boundary: first honor any pending cancellation,
    then hand the mesh lease to a waiting job of another pool (if any)
    and re-acquire it through the fair queue."""
    check_cancel()
    fn = current()
    if fn is not None:
        fn()
