"""Decoder-only transformer family — the framework's flagship
long-context architecture.

The reference has no attention or transformer anywhere (SURVEY §5
"long-context" row: sequence models run as opaque user TF code through
the generic executor, binary_execution.py:177-189). This module is the
net-new TPU-first model family the parallelism library was built for:

- param naming matches ``parallel.sharding.TRANSFORMER_RULES`` exactly
  (``embed/embedding``, ``q_proj|k_proj|v_proj|o_proj/kernel``,
  ``gate|up_proj|down_proj/kernel``, ``experts/wi|wo``,
  ``lm_head/kernel``), so TP/FSDP/EP sharding is a table lookup;
- attention is pluggable per config: ``dot`` (XLA-fused reference),
  ``flash`` (Pallas kernel, shard_map'd over heads so TP keeps the
  kernel local), ``ring`` (sequence-parallel KV rotation over ``sp``),
  ``ulysses`` (all-to-all head scatter over ``sp``);
- rotary position embeddings + RMSNorm + gated-SiLU MLP — the modern
  decoder block, all MXU-shaped matmuls;
- optional mixture-of-experts MLP (``n_experts > 0``) through
  ``parallel.moe`` with expert parallelism over ``ep``.

``LanguageModel`` wraps the flax module in the same keras-shaped
method surface as :class:`~learningorchestra_tpu.models.neural.
NeuralModel` (fit/evaluate/predict + generate), because those method
names and kwargs are the reference's REST contract
(``method: "fit"``, binary_executor_image/server.py:23-71).
"""

from __future__ import annotations

import functools
import json
import math
import os
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from learningorchestra_tpu.ops import attention as attn_ops
from learningorchestra_tpu.parallel import moe as moe_lib
from learningorchestra_tpu.parallel import ring as ring_lib
from learningorchestra_tpu.parallel import sharding as sharding_lib
from learningorchestra_tpu.parallel import ulysses as ulysses_lib
from learningorchestra_tpu.runtime import data as data_lib
from learningorchestra_tpu.runtime import engine as engine_lib
from learningorchestra_tpu.runtime import mesh as mesh_lib

ATTENTION_IMPLS = ("dot", "flash", "ring", "ulysses")


# ----------------------------------------------------------------------
# rotary position embeddings
# ----------------------------------------------------------------------
def rope_tables(seq_len: int, head_dim: int, base: float = 10000.0,
                offset: int = 0) -> Tuple[jax.Array, jax.Array]:
    half = head_dim // 2
    freqs = 1.0 / (base ** (jnp.arange(half, dtype=jnp.float32) / half))
    pos = jnp.arange(offset, offset + seq_len, dtype=jnp.float32)
    ang = pos[:, None] * freqs[None, :]                    # (s, half)
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: (b, s, h, d) with d even; rotate pairs (x1, x2)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[None, :, None, :].astype(x.dtype)
    s = sin[None, :, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)


# ----------------------------------------------------------------------
# flax modules
# ----------------------------------------------------------------------
class _Experts(nn.Module):
    """Bare param holder so expert weights live at ``.../experts/*``
    where the EP sharding rules expect them."""
    n_experts: int
    d_model: int
    d_ff: int

    @nn.compact
    def __call__(self):
        wi = self.param(
            "wi", nn.initializers.normal(1.0 / math.sqrt(self.d_model)),
            (self.n_experts, self.d_model, self.d_ff))
        wo = self.param(
            "wo", nn.initializers.normal(1.0 / math.sqrt(self.d_ff)),
            (self.n_experts, self.d_ff, self.d_model))
        return wi, wo


class _LoRADense(nn.Module):
    """Dense with an additive low-rank adapter: y = xW + (xA)B·(α/r).

    A is init'd like a normal layer, B at zero, so step 0 reproduces
    the base model exactly. The base ``kernel`` keeps the plain
    nn.Dense param name/shape, so existing artifacts load into the
    LoRA variant unchanged (the adapters init fresh) and sharding
    rules keyed on the module path still match."""

    features: int
    rank: int
    alpha: float

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.features))
        y = x @ kernel
        a = self.param("lora_a", nn.initializers.lecun_normal(),
                       (x.shape[-1], self.rank))
        b = self.param("lora_b", nn.initializers.zeros,
                       (self.rank, self.features))
        scale = jnp.asarray(self.alpha / self.rank, x.dtype)
        return y + (x @ a.astype(x.dtype)) @ b.astype(x.dtype) * scale


def _make_dense(name: str, features: int, lora_rank: int,
                lora_alpha: float):
    if lora_rank > 0:
        return _LoRADense(features, lora_rank, lora_alpha, name=name)
    return nn.Dense(features, use_bias=False, name=name)


class _Attention(nn.Module):
    """Multi-head attention with optional grouped-query KV heads.

    ``n_kv_heads < n_heads`` is GQA (``=1`` is MQA): K/V are projected
    to fewer heads and each KV head serves a GROUP of query heads. On
    TPU the win is HBM, not FLOPs — the KV cache (the whole memory
    story of long-context decode) shrinks by ``n_heads/n_kv_heads``,
    and the decode step reads proportionally less HBM per token. The
    decode path computes grouped attention directly (no head repeat);
    the train/prefill path repeats KV up to ``n_heads`` before
    :func:`_dispatch_attention` so every impl (dot/flash/ring/ulysses)
    sees uniform heads — XLA fuses the repeat into the consuming
    matmul, so training costs the same as full-head attention."""

    n_heads: int
    head_dim: int
    impl: str
    causal: bool
    mesh: Any = None
    n_kv_heads: int = 0      # 0 -> n_heads (standard MHA)
    # one (d, 3*proj) matmul instead of three (d, proj) ones: at small
    # d_model the MXU is under-tiled in the output dim, so widening N
    # 3x raises utilization (the BENCHMARKS.md d=512 roofline gap).
    # MHA only — under GQA the q/k/v widths differ and column-sharding
    # the concatenation would split across block boundaries.
    fused_qkv: bool = False
    # LoRA adapters on the attention projections (rank 0 = off)
    lora_rank: int = 0
    lora_alpha: float = 16.0
    # sliding-window (banded causal) attention; 0 = unlimited
    window: int = 0
    # RoPE frequency base; raise (e.g. 500000) to stretch usable
    # context (NTK-style scaling)
    rope_base: float = 10000.0

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    def _cache_vars(self, b: int, cache_len: int, dtype):
        shape = (b, cache_len, self.kv_heads, self.head_dim)
        ck = self.variable("cache", "k", jnp.zeros, shape, dtype)
        cv = self.variable("cache", "v", jnp.zeros, shape, dtype)
        return ck, cv

    @nn.compact
    def __call__(self, x, train: bool, decode_pos=None, cache_len: int = 0,
                 pad_offset=None, kv_len=None, block_tables=None,
                 page_len: int = 0, kv_pages: int = 0,
                 kv_quant: bool = False, verify_limit=None):
        d_model = x.shape[-1]
        kv = self.kv_heads
        if self.n_heads % kv:
            raise ValueError(
                f"n_kv_heads={kv} must divide n_heads={self.n_heads}")
        group = self.n_heads // kv
        proj = self.n_heads * self.head_dim
        dense = lambda name, feats: _make_dense(  # noqa: E731
            name, feats, self.lora_rank, self.lora_alpha)
        b, s, _ = x.shape
        shape4 = (b, s, self.n_heads, self.head_dim)
        kv_shape4 = (b, s, kv, self.head_dim)
        if self.fused_qkv and kv == self.n_heads:
            qkv = dense("qkv_proj", 3 * proj)(x)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q, k, v = (q.reshape(shape4), k.reshape(shape4),
                       v.reshape(shape4))
        else:
            q = dense("q_proj", proj)(x).reshape(shape4)
            k = dense("k_proj", kv * self.head_dim)(x).reshape(kv_shape4)
            v = dense("v_proj", kv * self.head_dim)(x).reshape(kv_shape4)

        if decode_pos is not None and jnp.ndim(decode_pos) == 0 \
                and pad_offset is None:
            # single-token step at absolute position decode_pos: rope
            # from the scalar position, attend over the KV cache
            half = self.head_dim // 2
            freqs = 1.0 / (self.rope_base ** (
                jnp.arange(half, dtype=jnp.float32) / half))
            ang = decode_pos.astype(jnp.float32) * freqs       # (half,)
            cos, sin = jnp.cos(ang)[None, :], jnp.sin(ang)[None, :]
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            ck, cv = self._cache_vars(b, cache_len, x.dtype)
            ck.value = jax.lax.dynamic_update_slice(
                ck.value, k.astype(x.dtype), (0, decode_pos, 0, 0))
            cv.value = jax.lax.dynamic_update_slice(
                cv.value, v.astype(x.dtype), (0, decode_pos, 0, 0))
            # grouped scores: each KV head serves its `group` query
            # heads directly — the cache is never expanded to n_heads
            qg = q.astype(jnp.float32).reshape(
                b, s, kv, group, self.head_dim)
            scores = jnp.einsum(
                "bqhgd,bkhd->bqhgk", qg,
                ck.value.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            ) / math.sqrt(self.head_dim)
            visible = jnp.arange(cache_len) <= decode_pos
            if self.window > 0:
                visible = jnp.logical_and(
                    visible,
                    jnp.arange(cache_len) > decode_pos - self.window)
            scores = jnp.where(visible[None, None, None, None, :], scores,
                               ring_lib.NEG_INF)
            p = jax.nn.softmax(scores, axis=-1)
            o = jnp.einsum("bqhgk,bkhd->bqhgd", p,
                           cv.value.astype(jnp.float32)
                           ).reshape(shape4).astype(x.dtype)
        elif decode_pos is not None:
            # per-row decode step: continuous-batched serving (every
            # slot sits at its OWN cache position) or a left-padded
            # batch decoding one shared column. The math is the scalar
            # branch's, elementwise per row — rope angle, cache write,
            # grouped scores, visibility mask — so a slot's output
            # bits match a solo batch-1 decode of the same request
            # (docs/SERVING.md bit-identity contract).
            pos = decode_pos if jnp.ndim(decode_pos) else \
                jnp.full((b,), decode_pos, jnp.int32)
            rel = pos if pad_offset is None else pos - pad_offset
            half = self.head_dim // 2
            freqs = 1.0 / (self.rope_base ** (
                jnp.arange(half, dtype=jnp.float32) / half))
            if s > 1:
                # speculative-verify step: s consecutive positions per
                # row (last accepted token + k drafts), query j at
                # absolute position pos + j. Per-position rope, the
                # sequential append order and the per-position masked
                # reduction all match s single-token steps bit-for-bit
                # (ops/attention.py paged_verify_attention), which is
                # what lets greedy speculative decode inherit the
                # bit-identity contract (docs/SERVING.md).
                if block_tables is None:
                    raise ValueError(
                        "multi-position decode (speculative verify) "
                        "requires the paged KV path (block_tables)")
                rel2 = (rel[:, None]
                        + jnp.arange(s)[None, :]).astype(jnp.float32)
                angv = rel2[:, :, None] * freqs[None, None, :]
                cosv = jnp.cos(angv)[:, :, None, :]  # (b, s, 1, half)
                sinv = jnp.sin(angv)[:, :, None, :]

                def rotv(t):
                    t1, t2 = jnp.split(t, 2, axis=-1)
                    c, si = cosv.astype(t.dtype), sinv.astype(t.dtype)
                    return jnp.concatenate(
                        [t1 * c - t2 * si, t1 * si + t2 * c], axis=-1)

                q, k = rotv(q), rotv(k)
                pool_shape = (kv_pages, page_len, kv, self.head_dim)
                if kv_quant:
                    ck = self.variable("cache", "k", jnp.zeros,
                                       pool_shape, jnp.int8)
                    cv = self.variable("cache", "v", jnp.zeros,
                                       pool_shape, jnp.int8)
                    cks = self.variable("cache", "k_scale", jnp.zeros,
                                        (kv_pages, kv), jnp.float32)
                    cvs = self.variable("cache", "v_scale", jnp.zeros,
                                        (kv_pages, kv), jnp.float32)
                    ck.value, cks.value = \
                        attn_ops.quantized_paged_append_tokens(
                            ck.value, cks.value, k, block_tables,
                            pos, page_len, limit=verify_limit)
                    cv.value, cvs.value = \
                        attn_ops.quantized_paged_append_tokens(
                            cv.value, cvs.value, v, block_tables,
                            pos, page_len, limit=verify_limit)
                    o = attn_ops.quantized_paged_verify_attention(
                        q, ck.value, cks.value, cv.value, cvs.value,
                        block_tables, pos, pad_offset=pad_offset,
                        window=self.window).reshape(shape4)
                else:
                    ck = self.variable("cache", "k", jnp.zeros,
                                       pool_shape, x.dtype)
                    cv = self.variable("cache", "v", jnp.zeros,
                                       pool_shape, x.dtype)
                    ck.value = attn_ops.paged_append_tokens(
                        ck.value, k, block_tables, pos, page_len,
                        limit=verify_limit)
                    cv.value = attn_ops.paged_append_tokens(
                        cv.value, v, block_tables, pos, page_len,
                        limit=verify_limit)
                    o = attn_ops.paged_verify_attention(
                        q, ck.value, cv.value, block_tables, pos,
                        pad_offset=pad_offset,
                        window=self.window).reshape(shape4)
                o = o.reshape(b, s, proj)
                return dense("o_proj", d_model)(o)
            ang = rel.astype(jnp.float32)[:, None] * freqs[None, :]
            cos = jnp.cos(ang)[:, None, None, :]       # (b, 1, 1, half)
            sin = jnp.sin(ang)[:, None, None, :]

            def rot(t):
                t1, t2 = jnp.split(t, 2, axis=-1)
                c, si = cos.astype(t.dtype), sin.astype(t.dtype)
                return jnp.concatenate(
                    [t1 * c - t2 * si, t1 * si + t2 * c], axis=-1)

            q, k = rot(q), rot(k)
            if block_tables is not None:
                # paged serving decode: the cache variable is the
                # SHARED page pool, not a per-slot rectangle. Rope,
                # the written K/V values, the grouped reduction and
                # the visibility mask are all the slot branch's —
                # only the storage addressing differs — so a paged
                # stream's output bits still match a solo decode
                # (docs/SERVING.md bit-identity contract).
                pool_shape = (kv_pages, page_len, kv, self.head_dim)
                if kv_quant:
                    # int8 pool + per-page-per-head float32 scale pool
                    # (docs/SERVING.md "Quantized serving"): append
                    # requantizes the touched page against its live
                    # rows; decode fuses dequant into the bounded
                    # gather, so no bf16 pool copy ever materializes
                    ck = self.variable("cache", "k", jnp.zeros,
                                       pool_shape, jnp.int8)
                    cv = self.variable("cache", "v", jnp.zeros,
                                       pool_shape, jnp.int8)
                    cks = self.variable("cache", "k_scale", jnp.zeros,
                                        (kv_pages, kv), jnp.float32)
                    cvs = self.variable("cache", "v_scale", jnp.zeros,
                                        (kv_pages, kv), jnp.float32)
                    ck.value, cks.value = \
                        attn_ops.quantized_paged_append_token(
                            ck.value, cks.value, k[:, 0], block_tables,
                            pos, page_len)
                    cv.value, cvs.value = \
                        attn_ops.quantized_paged_append_token(
                            cv.value, cvs.value, v[:, 0], block_tables,
                            pos, page_len)
                    o = attn_ops.quantized_paged_decode_attention(
                        q, ck.value, cks.value, cv.value, cvs.value,
                        block_tables, pos, pad_offset=pad_offset,
                        window=self.window).reshape(shape4)
                else:
                    ck = self.variable("cache", "k", jnp.zeros,
                                       pool_shape, x.dtype)
                    cv = self.variable("cache", "v", jnp.zeros,
                                       pool_shape, x.dtype)
                    ck.value = attn_ops.paged_append_token(
                        ck.value, k[:, 0], block_tables, pos, page_len)
                    cv.value = attn_ops.paged_append_token(
                        cv.value, v[:, 0], block_tables, pos, page_len)
                    o = attn_ops.paged_decode_attention(
                        q, ck.value, cv.value, block_tables, pos,
                        pad_offset=pad_offset,
                        window=self.window).reshape(shape4)
            else:
                ck, cv = self._cache_vars(b, cache_len, x.dtype)
                rows = jnp.arange(b)
                ck.value = ck.value.at[rows, pos].set(
                    k[:, 0].astype(x.dtype))
                cv.value = cv.value.at[rows, pos].set(
                    v[:, 0].astype(x.dtype))
                o = attn_ops.decode_attention(
                    q, ck.value, cv.value, pos, pad_offset=pad_offset,
                    window=self.window).reshape(shape4)
        else:
            if pad_offset is None:
                cos, sin = rope_tables(s, self.head_dim,
                                       base=self.rope_base)
                q, k = apply_rope(q, cos, sin), apply_rope(k, cos, sin)
            else:
                # left-padded batch prefill: each row's rope position
                # is its content-relative index (negative over the pad
                # columns — masked below, never read)
                half = self.head_dim // 2
                freqs = 1.0 / (self.rope_base ** (
                    jnp.arange(half, dtype=jnp.float32) / half))
                rel = (jnp.arange(s)[None, :]
                       - pad_offset[:, None]).astype(jnp.float32)
                ang = rel[:, :, None] * freqs[None, None, :]
                cos = jnp.cos(ang)[:, :, None, :]   # (b, s, 1, half)
                sin = jnp.sin(ang)[:, :, None, :]

                def rot(t):
                    t1, t2 = jnp.split(t, 2, axis=-1)
                    c, si = cos.astype(t.dtype), sin.astype(t.dtype)
                    return jnp.concatenate(
                        [t1 * c - t2 * si, t1 * si + t2 * c], axis=-1)

                q, k = rot(q), rot(k)
            kv_valid = None
            if pad_offset is not None:
                kv_valid = jnp.arange(s)[None, :] >= pad_offset[:, None]
            elif kv_len is not None:
                # right-padded serving prefill: rows past a request's
                # true length hold garbage keys — masked here; the
                # decode loop overwrites their cache rows column by
                # column before they ever become visible
                kv_valid = jnp.arange(s)[None, :] < kv_len[:, None]
            if cache_len:
                # prefill: stash the prompt's K/V so decode steps can
                # continue from position s without recomputing them
                ck, cv = self._cache_vars(b, cache_len, x.dtype)
                ck.value = ck.value.at[:, :s].set(k.astype(x.dtype))
                cv.value = cv.value.at[:, :s].set(v.astype(x.dtype))
            o = _dispatch_attention(q, k, v, impl=self.impl,
                                    causal=self.causal, mesh=self.mesh,
                                    window=self.window,
                                    kv_valid=kv_valid)
        o = o.reshape(b, s, proj)
        return dense("o_proj", d_model)(o)


def _dispatch_attention(q, k, v, *, impl: str, causal: bool, mesh=None,
                        window: int = 0, kv_valid=None):
    """q: (b, s, h, d); k/v may carry FEWER (kv) heads under GQA.
    The single-chip flash path consumes them natively (the kernel
    folds the query group — K/V never materialize at h heads); every
    other impl repeats K/V up to h first, which XLA fuses into the
    consuming matmul on the dot path. ``window`` composes with every
    impl: ring hops apply the exact banded mask at static cross-shard
    offsets (hops wholly below the band skip), Ulysses windows its
    local full-sequence attention. ``kv_valid`` (``(b, s)`` bool,
    padded-batch prefill) always routes to the dense reference path —
    the sharded/flash kernels take no per-row mask, a documented cost
    of unequal-length batches (docs/SERVING.md)."""
    mesh = mesh or mesh_lib.current_mesh()
    b, s, h, _ = q.shape
    kvh = k.shape[2]
    group = h // kvh

    def repeated():
        if group == 1:
            return k, v
        return (jnp.repeat(k, group, axis=2),
                jnp.repeat(v, group, axis=2))
    if kv_valid is not None:
        kr, vr = repeated()
        return ring_lib.full_attention_reference(
            q, kr, vr, causal=causal, window=window, kv_valid=kv_valid)
    data_size = mesh_lib.data_parallel_size(mesh)
    sp = mesh.shape.get(mesh_lib.SP, 1)
    tp = mesh.shape.get(mesh_lib.TP, 1)
    # shard_map needs every mapped dim to divide its mesh axis; the
    # 1-sample param-init trace (and odd user shapes) fall back to the
    # fused full-softmax path, which is numerically identical
    divisible = b % data_size == 0 and s % sp == 0

    if impl == "ring" and sp > 1 and divisible:
        kr, vr = repeated()
        return ring_lib.ring_attention_sharded(q, kr, vr, mesh,
                                               causal=causal,
                                               window=window)
    if impl == "ulysses" and sp > 1 and divisible and h % sp == 0:
        # GQA-native when kv heads divide sp: the head scatter moves
        # kv-width K/V (group-fold less all_to_all traffic) and the
        # local flash kernel consumes the group directly
        kr, vr = (k, v) if kvh % sp == 0 else repeated()
        return ulysses_lib.ulysses_attention_sharded(q, kr, vr, mesh,
                                                     causal=causal,
                                                     window=window)
    if impl == "flash":
        sharded = tp > 1 or data_size > 1
        if not sharded:
            # GQA-native: unrepeated K/V straight into the kernel
            return attn_ops.flash_attention(q, k, v, causal=causal,
                                            window=window)
        if b % data_size == 0 and h % tp == 0:
            if kvh % tp:
                # kv heads don't divide tp: repeat up to full heads so
                # the contiguous head shards stay well-formed
                k, v = repeated()
            # else: shard the kv-width K/V directly — contiguous head
            # sharding aligns each device's q-head chunk with its
            # kv-head chunk (h/tp == group * kvh/tp), so the per-shard
            # kernel stays GQA-native and K/V HBM still scales with kv
            # pallas_call is opaque to GSPMD — shard_map it so each
            # device runs the kernel on its local (batch, heads) tile
            # and TP never gathers heads
            data = mesh_lib.data_axes(mesh)
            spec = P(data if data else None, None,
                     mesh_lib.TP if tp > 1 else None, None)
            # check_vma=False: pallas_call emits ShapeDtypeStructs with
            # no varying-mesh-axes info, which the vma checker rejects
            fn = mesh_lib.shard_map(
                lambda a, b_, c: attn_ops.flash_attention(
                    a, b_, c, causal=causal, window=window),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                check_vma=False)
            return fn(q, k, v)
    # "dot" and all fallbacks (no sp axis, non-divisible shapes)
    kr, vr = repeated()
    return ring_lib.full_attention_reference(q, kr, vr, causal=causal,
                                             window=window)


class _MLP(nn.Module):
    d_ff: int
    fused_gate_up: bool = False  # one (d, 2*d_ff) matmul (see fused_qkv)

    @nn.compact
    def __call__(self, x):
        d_model = x.shape[-1]
        if self.fused_gate_up:
            gu = nn.Dense(2 * self.d_ff, use_bias=False,
                          name="gate_up")(x)
            gate, up = jnp.split(gu, 2, axis=-1)
        else:
            gate = nn.Dense(self.d_ff, use_bias=False, name="gate")(x)
            up = nn.Dense(self.d_ff, use_bias=False, name="up_proj")(x)
        h = nn.silu(gate) * up
        return nn.Dense(d_model, use_bias=False, name="down_proj")(h)


class _MoE(nn.Module):
    n_experts: int
    d_ff: int
    k: int = 2
    mesh: Any = None

    @nn.compact
    def __call__(self, x):
        d_model = x.shape[-1]
        gate = self.param("gate",
                          nn.initializers.normal(1.0 / math.sqrt(d_model)),
                          (d_model, self.n_experts))
        wi, wo = _Experts(self.n_experts, d_model, self.d_ff,
                          name="experts")()
        params = {"gate": gate, "experts": {"wi": wi, "wo": wo}}
        mesh = self.mesh or mesh_lib.current_mesh()
        ep_mesh = mesh if (mesh_lib.EP in mesh.axis_names and
                           mesh.shape[mesh_lib.EP] > 1) else None
        return moe_lib.moe_layer(params, x, k=self.k, mesh=ep_mesh)


class _Block(nn.Module):
    n_heads: int
    head_dim: int
    d_ff: int
    attention: str
    causal: bool
    n_experts: int
    moe_k: int
    dropout: float
    mesh: Any = None
    n_kv_heads: int = 0
    fused_proj: bool = False
    lora_rank: int = 0
    lora_alpha: float = 16.0
    window: int = 0
    rope_base: float = 10000.0

    @nn.compact
    def __call__(self, x, train: bool, decode_pos=None, cache_len: int = 0,
                 pad_offset=None, kv_len=None, block_tables=None,
                 page_len: int = 0, kv_pages: int = 0,
                 kv_quant: bool = False, verify_limit=None):
        h = nn.RMSNorm(name="attn_norm")(x)
        h = _Attention(self.n_heads, self.head_dim, self.attention,
                       self.causal, self.mesh,
                       n_kv_heads=self.n_kv_heads,
                       fused_qkv=self.fused_proj,
                       lora_rank=self.lora_rank,
                       lora_alpha=self.lora_alpha,
                       window=self.window,
                       rope_base=self.rope_base, name="attn")(
            h, train, decode_pos=decode_pos, cache_len=cache_len,
            pad_offset=pad_offset, kv_len=kv_len,
            block_tables=block_tables, page_len=page_len,
            kv_pages=kv_pages, kv_quant=kv_quant,
            verify_limit=verify_limit)
        if self.dropout and train:
            h = nn.Dropout(self.dropout, deterministic=False)(h)
        x = x + h
        h = nn.RMSNorm(name="mlp_norm")(x)
        aux = jnp.zeros((), jnp.float32)
        if self.n_experts > 0:
            h, aux = _MoE(self.n_experts, self.d_ff, self.moe_k,
                          self.mesh, name="moe")(h)
        else:
            h = _MLP(self.d_ff, fused_gate_up=self.fused_proj,
                     name="mlp")(h)
        if self.dropout and train:
            h = nn.Dropout(self.dropout, deterministic=False)(h)
        return x + h, aux


class FusedHeadOut(NamedTuple):
    """Training output of a ``fused_head_chunk`` TransformerLM: the
    final hidden states plus the lm_head kernel, so the loss can run
    the vocab projection + cross-entropy in token chunks and the
    (tokens, vocab) logits tensor never materializes in HBM (the
    d_model=512/vocab-32k roofline gap named in BENCHMARKS.md)."""
    hidden: Any     # (b, s, d) final-norm output
    kernel: Any     # (d, vocab) lm_head weight
    aux: Any        # MoE load-balance scalar


class _LMHead(nn.Module):
    """The vocab projection as its own submodule (param tree stays
    ``lm_head/kernel``, identical to the previous nn.Dense) so the
    fused-loss path can hand the kernel to the loss instead of
    computing full logits."""
    vocab_size: int

    @nn.compact
    def __call__(self, h, return_kernel: bool = False):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (h.shape[-1], self.vocab_size))
        if return_kernel:
            return kernel
        return h @ kernel.astype(h.dtype)


class TransformerLM(nn.Module):
    """Decoder-only LM: tokens (b, s) int32 -> (logits (b, s, V), aux).

    ``aux`` is the summed MoE load-balance loss (zero for dense MLP).
    With ``fused_head_chunk > 0`` the TRAIN forward returns
    :class:`FusedHeadOut` instead of logits; eval/decode always
    produce full logits.
    """
    vocab_size: int
    d_model: int = 256
    n_layers: int = 4
    n_heads: int = 4
    n_kv_heads: int = 0      # 0 -> n_heads; < n_heads is GQA, 1 is MQA
    d_ff: int = 0            # 0 -> 4 * d_model
    attention: str = "dot"
    causal: bool = True
    n_experts: int = 0
    moe_k: int = 2
    dropout: float = 0.0
    mesh: Any = None
    fused_head_chunk: int = 0
    # fuse q/k/v into one (d, 3*proj) matmul and gate/up into one
    # (d, 2*d_ff) matmul — wider MXU output tiles at small d_model
    # (the measured d=512 roofline gap). The param-tree layout depends
    # ONLY on this config (never on the ambient mesh, so artifacts
    # stay portable across mesh shapes): under GQA the attention
    # self-gates back to separate q/k/v (unequal widths) while the
    # MLP still fuses, and under TP the sharding rules REPLICATE the
    # fused kernels (a column shard would cross block boundaries)
    # instead of changing the tree.
    fused_proj: bool = False
    # LoRA: rank-r adapters on the attention projections; the base
    # kernels keep their plain names/shapes so a pre-trained artifact
    # loads into the LoRA variant unchanged (adapters init fresh)
    lora_rank: int = 0
    lora_alpha: float = 16.0
    # sliding-window attention (banded causal, Mistral-style): query p
    # attends [p-W+1, p]; the flash kernels iterate a banded tile
    # grid so compute AND K/V DMA scale ~O(s*W). Composes with every
    # impl incl. ring/Ulysses sequence parallelism.
    sliding_window: int = 0
    # RoPE frequency base (NTK-style context stretching)
    rope_base: float = 10000.0
    # per-layer rematerialization under training: "none" saves all
    # activations, "dots" saves matmul outputs only (the standard TPU
    # memory/FLOPs trade), "full" recomputes everything in backward
    remat: str = "none"

    @nn.compact
    def __call__(self, tokens, train: bool = False, decode_pos=None,
                 cache_len: int = 0, pad_offset=None, kv_len=None,
                 block_tables=None, page_len: int = 0,
                 kv_pages: int = 0, kv_quant: bool = False,
                 verify_limit=None):
        if self.attention not in ATTENTION_IMPLS:
            raise ValueError(f"unknown attention impl: {self.attention!r}")
        d_ff = self.d_ff or 4 * self.d_model
        head_dim = self.d_model // self.n_heads
        mesh = self.mesh or mesh_lib.current_mesh()
        fuse = self.fused_proj

        x = nn.Embed(self.vocab_size, self.d_model, name="embed")(tokens)
        if decode_pos is None:
            x = sharding_lib.constrain(
                x, mesh, mesh_lib.data_axes(mesh) or None,
                mesh_lib.SP if self.attention in ("ring", "ulysses")
                else None,
                None)
        block_cls = _Block
        if self.remat != "none" and train and decode_pos is None:
            policies = {
                "dots": jax.checkpoint_policies
                .dots_with_no_batch_dims_saveable,
                "full": jax.checkpoint_policies.nothing_saveable,
            }
            if self.remat not in policies:
                raise ValueError(
                    f"unknown remat policy {self.remat!r} "
                    f"(none|dots|full)")
            # args: (self, x, train, decode_pos, cache_len, ...,
            # block_tables, page_len, kv_pages, kv_quant) — the
            # non-array flags are static (the paged-decode args are
            # always None/0/False here: remat only wraps train)
            # prevent_cse=True: outside nn.scan, XLA's CSE can undo
            # the recomputation and keep activations live (the flax
            # docs' reason it defaults True under jit)
            block_cls = nn.remat(_Block, policy=policies[self.remat],
                                 prevent_cse=True,
                                 static_argnums=(2, 3, 4, 7, 8, 9, 10))
        aux_total = jnp.zeros((), jnp.float32)
        for i in range(self.n_layers):
            x, aux = block_cls(self.n_heads, head_dim, d_ff,
                               self.attention, self.causal,
                               self.n_experts, self.moe_k,
                               self.dropout, self.mesh,
                               self.n_kv_heads, fuse,
                               self.lora_rank, self.lora_alpha,
                               self.sliding_window, self.rope_base,
                               name=f"layer_{i}")(
                x, train, decode_pos, cache_len, pad_offset, kv_len,
                block_tables, page_len, kv_pages, kv_quant,
                verify_limit)
            aux_total = aux_total + aux
        x = nn.RMSNorm(name="final_norm")(x)
        head = _LMHead(self.vocab_size, name="lm_head")
        if self.fused_head_chunk and train and decode_pos is None:
            return FusedHeadOut(hidden=x,
                                kernel=head(x, return_kernel=True),
                                aux=aux_total)
        return head(x), aux_total


# ----------------------------------------------------------------------
# losses over (outputs=(logits, aux), batch, weights)
# ----------------------------------------------------------------------
def _token_targets(batch, weights):
    tokens = batch["x"].astype(jnp.int32)
    tgt = tokens[:, 1:]
    tok_mask = (tgt != 0).astype(jnp.float32)
    if weights is not None:
        tok_mask = tok_mask * weights.astype(jnp.float32)[:, None]
    return tgt, tok_mask


def _fused_head_loss(out: FusedHeadOut, batch, weights, chunk: int,
                     aux_coef: float):
    """Chunked vocab-projection + softmax cross-entropy: scans token
    chunks of the final hidden states through the lm_head matmul, so
    peak logits memory is (chunk, vocab) instead of (b*s, vocab) and
    the full logits tensor never round-trips HBM between forward and
    loss (BENCHMARKS.md names this epilogue as the d=512 roofline
    gap: one (8192, 512) x (512, 32000) matmul per step feeding an
    elementwise log-softmax over 262M f32 logits). The backward
    recomputes each chunk's logits via jax.checkpoint. Accuracy is
    computed inside the same scan and emitted as a loss metric, so
    the engine does not re-run the projection for it."""
    tgt, tok_mask = _token_targets(batch, weights)
    hs = out.hidden[:, :-1]
    b, sm1, d = hs.shape
    t_total = b * sm1
    chunk = max(1, min(chunk, t_total))  # no padding blowup on tiny shapes
    hs = hs.reshape(t_total, d)
    tg = tgt.reshape(t_total)
    mk = tok_mask.reshape(t_total)
    n_chunks = -(-t_total // chunk)
    pad = n_chunks * chunk - t_total
    if pad:
        hs = jnp.pad(hs, ((0, pad), (0, 0)))
        tg = jnp.pad(tg, (0, pad))
        mk = jnp.pad(mk, (0, pad))
    kernel = out.kernel.astype(hs.dtype)

    def body(carry, xs):
        h_c, t_c, m_c = xs
        # bf16 inputs, f32 accumulate — the MXU-native layout
        lg = jnp.einsum("cd,dv->cv", h_c, kernel,
                        preferred_element_type=jnp.float32)
        lse = jax.scipy.special.logsumexp(lg, axis=-1)
        correct = jnp.take_along_axis(lg, t_c[:, None], axis=1)[:, 0]
        ok = (jnp.argmax(lg, axis=-1) == t_c).astype(jnp.float32)
        loss_sum, ok_sum = carry
        return (loss_sum + jnp.sum((lse - correct) * m_c),
                ok_sum + jnp.sum(ok * m_c)), None

    (loss_sum, ok_sum), _ = jax.lax.scan(
        jax.checkpoint(body),
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (hs.reshape(n_chunks, chunk, d),
         tg.reshape(n_chunks, chunk),
         mk.reshape(n_chunks, chunk)))
    total = jnp.maximum(jnp.sum(mk), 1e-9)
    loss = loss_sum / total + aux_coef * out.aux.astype(jnp.float32)
    return loss, {"accuracy": (ok_sum, total)}


def _fused_head_loss_sharded(out: FusedHeadOut, batch, weights,
                             chunk: int, aux_coef: float, mesh):
    """Sequence-parallel twin of :func:`_fused_head_loss`: under
    ring/Ulysses the hidden states are sharded over ``sp`` (and batch
    over dp/fsdp), which is exactly where the (tokens, vocab) logits
    hurt most — a 32k-token, 32k-vocab step would materialize 4 GB of
    f32 logits per batch row. The projection + CE runs INSIDE
    ``shard_map``: each shard scans its local token chunks; with
    tensor parallelism the lm_head columns stay sharded and the
    softmax reduces over ``tp`` (Megatron-style parallel CE: pmax of
    the local maxima, psum of the local exp-sums, psum of the local
    one-hot correct logit). Loss/accuracy sums then psum over the
    row-sharding axes, so the result is replicated and exact."""
    tokens = batch["x"].astype(jnp.int32)
    b, s = tokens.shape
    # global shift OUTSIDE shard_map (a one-position halo the compiler
    # handles); the appended 0 column self-masks via tgt != 0
    tgt = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros((b, 1), jnp.int32)], axis=1)
    tok_mask = (tgt != 0).astype(jnp.float32)
    if weights is not None:
        tok_mask = tok_mask * weights.astype(jnp.float32)[:, None]

    data = mesh_lib.data_axes(mesh)
    tp = mesh.shape.get(mesh_lib.TP, 1)
    row_axes = tuple(a for a in (*data, mesh_lib.SP)
                     if mesh.shape.get(a, 1) > 1)
    h_spec = P(data if data else None, mesh_lib.SP, None)
    t_spec = P(data if data else None, mesh_lib.SP)
    k_spec = P(None, mesh_lib.TP if tp > 1 else None)
    kernel = out.kernel.astype(out.hidden.dtype)

    def local_loss(h, tg, mk, W):
        d = h.shape[-1]
        v_loc = W.shape[-1]
        t_total = h.shape[0] * h.shape[1]
        c = max(1, min(chunk, t_total))
        n_chunks = -(-t_total // c)
        pad = n_chunks * c - t_total
        hs = h.reshape(t_total, d)
        tgl = tg.reshape(t_total)
        mkl = mk.reshape(t_total)
        if pad:
            hs = jnp.pad(hs, ((0, pad), (0, 0)))
            tgl = jnp.pad(tgl, (0, pad))
            mkl = jnp.pad(mkl, (0, pad))
        if tp > 1:
            v_off = jax.lax.axis_index(mesh_lib.TP) * v_loc
        else:
            v_off = 0

        def body(carry, xs):
            h_c, t_c, m_c = xs
            lg = jnp.einsum("cd,dv->cv", h_c, W,
                            preferred_element_type=jnp.float32)
            lmax = jnp.max(lg, axis=-1)
            # the max subtraction is a stability constant — keep it
            # out of the grad graph; cross-tp reduction goes through
            # all_gather (pmax has no differentiation rule, which the
            # checkpointed scan's linearization requires even for
            # zero-tangent values)
            if tp > 1:
                gmax = jnp.max(jax.lax.all_gather(
                    lmax, mesh_lib.TP), axis=0)
            else:
                gmax = lmax
            gmax = jax.lax.stop_gradient(gmax)
            se = jnp.sum(jnp.exp(lg - gmax[:, None]), axis=-1)
            if tp > 1:
                se = jax.lax.psum(se, mesh_lib.TP)
            lse = gmax + jnp.log(se)
            loc = t_c - v_off
            in_range = (loc >= 0) & (loc < v_loc)
            corr = jnp.take_along_axis(
                lg, jnp.clip(loc, 0, v_loc - 1)[:, None], axis=1)[:, 0]
            corr = jnp.where(in_range, corr, 0.0)
            if tp > 1:
                corr = jax.lax.psum(corr, mesh_lib.TP)
            lg_sg = jax.lax.stop_gradient(lg)  # accuracy carries no grad
            amax_v = jnp.max(lg_sg, axis=-1)
            amax_i = jnp.argmax(lg_sg, axis=-1) + v_off
            if tp > 1:
                vs = jax.lax.all_gather(amax_v, mesh_lib.TP)  # (tp, c)
                is_ = jax.lax.all_gather(amax_i, mesh_lib.TP)
                win = jnp.argmax(vs, axis=0)
                amax_i = jnp.take_along_axis(
                    is_, win[None, :], axis=0)[0]
            ok = (amax_i == t_c).astype(jnp.float32)
            loss_sum, ok_sum, n_sum = carry
            return (loss_sum + jnp.sum((lse - corr) * m_c),
                    ok_sum + jnp.sum(ok * m_c),
                    n_sum + jnp.sum(m_c)), None

        zeros = (jnp.zeros((), jnp.float32),) * 3
        (loss_sum, ok_sum, n_sum), _ = jax.lax.scan(
            jax.checkpoint(body), zeros,
            (hs.reshape(n_chunks, c, d), tgl.reshape(n_chunks, c),
             mkl.reshape(n_chunks, c)))
        if row_axes:
            loss_sum = jax.lax.psum(loss_sum, row_axes)
            ok_sum = jax.lax.psum(ok_sum, row_axes)
            n_sum = jax.lax.psum(n_sum, row_axes)
        return loss_sum, ok_sum, n_sum

    loss_sum, ok_sum, n_sum = mesh_lib.shard_map(
        local_loss, mesh=mesh,
        in_specs=(h_spec, t_spec, t_spec, k_spec),
        out_specs=(P(), P(), P()), check_vma=False)(
        out.hidden, tgt, tok_mask, kernel)
    total = jnp.maximum(n_sum, 1e-9)
    loss = loss_sum / total + aux_coef * out.aux.astype(jnp.float32)
    return loss, {"accuracy": (ok_sum, total)}


def next_token_loss(aux_coef: float = 0.01, head_chunk: int = 1024,
                    mesh=None):
    """Causal LM loss: predict token t+1 from prefix <= t; padding
    tokens (id 0) and padded tail samples are masked out. On
    :class:`FusedHeadOut` training outputs the projection + CE runs
    chunked (``head_chunk`` tokens at a time) and the return value is
    ``(loss, {"accuracy": (sum, count)})`` — the engine merges
    loss-emitted metrics. With a sequence-parallel mesh the chunked
    scan runs inside ``shard_map`` (see
    :func:`_fused_head_loss_sharded`)."""
    import optax

    def loss_fn(outputs, batch, weights):
        if isinstance(outputs, FusedHeadOut):
            m = mesh or mesh_lib.current_mesh()
            b, s = batch["x"].shape[:2]
            sp = m.shape.get(mesh_lib.SP, 1)
            tp = m.shape.get(mesh_lib.TP, 1)
            vocab = outputs.kernel.shape[-1]
            data_size = max(int(np.prod(
                [m.shape[a] for a in mesh_lib.data_axes(m)] or [1])), 1)
            # shard_map needs divisible mapped dims (incl. the vocab
            # columns under tp); odd shapes fall back to the flat
            # path (GSPMD gathers — correct, bigger)
            if sp > 1 and b % data_size == 0 and s % sp == 0 \
                    and vocab % tp == 0:
                return _fused_head_loss_sharded(
                    outputs, batch, weights, head_chunk, aux_coef, m)
            return _fused_head_loss(outputs, batch, weights,
                                    head_chunk, aux_coef)
        logits, aux = outputs
        tgt, tok_mask = _token_targets(batch, weights)
        lg = logits[:, :-1].astype(jnp.float32)
        per_tok = optax.softmax_cross_entropy_with_integer_labels(lg, tgt)
        total = jnp.maximum(jnp.sum(tok_mask), 1e-9)
        loss = jnp.sum(per_tok * tok_mask) / total
        return loss + aux_coef * aux.astype(jnp.float32)

    return loss_fn


def token_accuracy(outputs, batch, weights):
    if isinstance(outputs, FusedHeadOut):
        # the fused loss emits accuracy itself; recomputing it here
        # would cost a second full vocab projection
        raise RuntimeError(
            "token_accuracy on FusedHeadOut — use the accuracy the "
            "fused loss emits (the engine skips same-named metric fns)")
    logits, _ = outputs
    tokens = batch["x"].astype(jnp.int32)
    tgt = tokens[:, 1:]
    pred = jnp.argmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    tok_mask = (tgt != 0).astype(jnp.float32)
    if weights is not None:
        tok_mask = tok_mask * weights.astype(jnp.float32)[:, None]
    correct = (pred == tgt).astype(jnp.float32) * tok_mask
    return jnp.sum(correct), jnp.sum(tok_mask)


# ----------------------------------------------------------------------
# keras-shaped wrapper (the stored lineage-root instance)
# ----------------------------------------------------------------------
class TransformerEncoder(nn.Module):
    """Non-causal (bidirectional) transformer encoder for sequence
    classification: embed → blocks(causal=False) → final RMSNorm →
    pad-masked mean pool → class head. Shares every block/param
    convention with :class:`TransformerLM`, so the TP/FSDP sharding
    rules and the attention impl table (dot/flash/ring/ulysses) apply
    unchanged; token id 0 is the pad and is excluded from the pool."""

    vocab_size: int
    n_classes: int
    d_model: int = 256
    n_layers: int = 4
    n_heads: int = 4
    n_kv_heads: int = 0
    d_ff: int = 0
    attention: str = "dot"
    dropout: float = 0.0
    mesh: Any = None

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        if self.attention not in ATTENTION_IMPLS:
            raise ValueError(
                f"unknown attention impl: {self.attention!r}")
        d_ff = self.d_ff or 4 * self.d_model
        head_dim = self.d_model // self.n_heads
        x = nn.Embed(self.vocab_size, self.d_model, name="embed")(tokens)
        mesh = self.mesh or mesh_lib.current_mesh()
        x = sharding_lib.constrain(
            x, mesh, mesh_lib.data_axes(mesh) or None,
            mesh_lib.SP if self.attention in ("ring", "ulysses")
            else None,
            None)
        for i in range(self.n_layers):
            x, _ = _Block(self.n_heads, head_dim, d_ff,
                          self.attention, False, 0, 2,
                          self.dropout, self.mesh, self.n_kv_heads,
                          name=f"layer_{i}")(x, train)
        x = nn.RMSNorm(name="final_norm")(x)
        mask = (tokens != 0).astype(jnp.float32)[..., None]
        pooled = jnp.sum(x * mask, axis=1) / jnp.maximum(
            jnp.sum(mask, axis=1), 1e-9)
        return nn.Dense(self.n_classes, use_bias=True,
                        name="cls_head")(pooled)


class TextClassifier:
    """Keras-shaped sequence classifier over the transformer encoder
    (the modern counterpart to the reference's IMDb-LSTM config):
    ``fit/evaluate/predict`` through the same GSPMD engine as every
    other model, reachable by module path through ``POST /model``."""

    _CONFIG_KEYS = ("vocab_size", "n_classes", "d_model", "n_layers",
                    "n_heads", "n_kv_heads", "d_ff", "max_len",
                    "attention", "dropout")

    def __init__(self, vocab_size: int, n_classes: int,
                 d_model: int = 256, n_layers: int = 4,
                 n_heads: int = 4, n_kv_heads: int = 0, d_ff: int = 0,
                 max_len: int = 512, attention: str = "dot",
                 dropout: float = 0.0, name: str = "text_classifier"):
        self.name = name
        self.vocab_size = int(vocab_size)
        self.n_classes = int(n_classes)
        self.d_model = int(d_model)
        self.n_layers = int(n_layers)
        self.n_heads = int(n_heads)
        self.n_kv_heads = int(n_kv_heads)
        self.d_ff = int(d_ff)
        self.max_len = int(max_len)
        if attention not in ATTENTION_IMPLS + ("auto",):
            raise ValueError(f"unknown attention impl: {attention!r}")
        self.attention = attention
        if self.n_kv_heads < 0 or (
                self.n_kv_heads and self.n_heads % self.n_kv_heads):
            raise ValueError(
                f"n_kv_heads={self.n_kv_heads} must be a positive "
                f"divisor of n_heads={self.n_heads} (or 0 for MHA)")
        self.dropout = float(dropout)
        self.optimizer_spec: Dict[str, Any] = {"kind": "adamw",
                                               "learning_rate": 3e-4}
        self.params: Any = None
        self.history: List[Dict[str, Any]] = []
        self.seed = 0
        self._engine: Optional[engine_lib.Engine] = None
        self._state = None
        self._mesh_override = None
        self._accum = engine_lib.default_grad_accum()

    def _require_built(self) -> None:
        if self.params is None:
            raise RuntimeError(
                "model has no parameters yet — call fit() first "
                "(or load a trained artifact)")

    def _resolved_attention(self, seq_len: Optional[int] = None) -> str:
        if self.attention != "auto":
            return self.attention
        # same measured crossover as the LM (BENCHMARKS.md flash
        # table), resolved from the ACTUAL batch width when known — a
        # max_len=2048 classifier fed 128-token batches should take
        # the dot path, not flash below the measured crossover
        if jax.default_backend() == "tpu":
            return "flash" if (seq_len or self.max_len) >= 1024 else "dot"
        return "dot"

    def _mesh(self):
        return self._mesh_override or mesh_lib.current_mesh()

    def set_mesh(self, mesh) -> None:
        self._mesh_override = mesh
        self._engine = None
        self._state = None

    def compile(self, optimizer: Any = "adamw", **_: Any) -> None:
        if isinstance(optimizer, str):
            self.optimizer_spec = {"kind": optimizer}
        elif isinstance(optimizer, dict):
            self.optimizer_spec = dict(optimizer)
        else:
            raise TypeError(f"unsupported optimizer: {optimizer!r}")
        self._engine = None

    @property
    def module(self) -> TransformerEncoder:
        return self._module()

    def _module(self, seq_len: Optional[int] = None) -> TransformerEncoder:
        return TransformerEncoder(
            vocab_size=self.vocab_size, n_classes=self.n_classes,
            d_model=self.d_model, n_layers=self.n_layers,
            n_heads=self.n_heads, n_kv_heads=self.n_kv_heads,
            d_ff=self.d_ff, attention=self._resolved_attention(seq_len),
            dropout=self.dropout, mesh=self._mesh_override)

    def _apply_fn(self, params, model_state, batch, train, rng):
        rngs = {"dropout": rng} if (train and rng is not None and
                                    self.dropout) else None
        # the attention impl resolves from the traced batch width, so
        # an "auto" classifier takes flash only at-or-above the
        # measured crossover regardless of its configured max_len
        module = self._module(int(batch["x"].shape[1]))
        out = module.apply({"params": params}, batch["x"],
                           train=train, rngs=rngs)
        return out, model_state

    def _get_engine(self) -> engine_lib.Engine:
        if self._engine is None:
            from learningorchestra_tpu.config import get_config
            from learningorchestra_tpu.models.neural import (
                build_optimizer)
            dtype = jnp.bfloat16 \
                if get_config().compute_dtype == "bfloat16" \
                else jnp.float32
            mesh = self._mesh()
            self._engine = engine_lib.Engine(
                apply_fn=self._apply_fn,
                loss_fn=engine_lib.sparse_softmax_loss,
                optimizer=build_optimizer(self.optimizer_spec),
                mesh=mesh,
                metrics={"accuracy": engine_lib.accuracy_metric},
                compute_dtype=dtype,
                param_rules=sharding_lib.TRANSFORMER_RULES,
                batch_sharding=jax.sharding.NamedSharding(
                    mesh, sharding_lib.batch_spec(
                        mesh, seq_axis=self.attention in
                        ("ring", "ulysses"))),
                grad_accum=self._accum)
        return self._engine

    def _coerce(self, x) -> np.ndarray:
        if hasattr(x, "to_numpy"):
            x = data_lib.dataframe_to_arrays(x)["x"]
        x = np.atleast_2d(np.asarray(x)).astype(np.int32)
        if x.shape[1] > self.max_len:
            x = x[:, :self.max_len]
        return x

    def _batcher(self, x, y=None, batch_size=None, shuffle=False):
        from learningorchestra_tpu.config import get_config
        arrays = {"x": self._coerce(x)}
        if y is not None:
            arrays["y"] = np.asarray(y).astype(np.int32).reshape(-1)
        return data_lib.ArrayBatcher(
            arrays, batch_size or get_config().default_batch_size,
            shuffle=shuffle, seed=self.seed,
            dp_multiple=mesh_lib.data_parallel_size(self._mesh()))

    def _build_params(self, sample_x) -> None:
        sample = np.asarray(sample_x)
        variables = self._module(int(sample.shape[1])).init(
            jax.random.PRNGKey(self.seed),
            jnp.asarray(sample[:1]), train=False)
        self.params = variables["params"]

    def fit(self, x=None, y=None, batch_size: Optional[int] = None,
            epochs: int = 1, shuffle: bool = True, checkpointer=None,
            log_fn=None, grad_accum: Optional[int] = None, **_: Any):
        from learningorchestra_tpu.models.neural import History

        self._accum, changed = engine_lib.resolve_grad_accum(
            grad_accum, self._accum)
        if changed:
            self._engine = None
        batcher = self._batcher(x, y, batch_size, shuffle=shuffle)
        if self.params is None:
            self._build_params(batcher.array("x"))
        eng = self._get_engine()
        state = eng.init_state(self.params)
        state, history = eng.fit(state, batcher, epochs=epochs,
                                 seed=self.seed,
                                 checkpointer=checkpointer,
                                 log_fn=log_fn)
        self._state = state
        self.params = engine_lib.to_host(state.params)
        self.history.extend(history)
        return History(history)

    def evaluate(self, x=None, y=None,
                 batch_size: Optional[int] = None,
                 **_: Any) -> Dict[str, float]:
        self._require_built()
        eng = self._get_engine()
        state = self._state or eng.init_state(self.params)
        return eng.evaluate(state, self._batcher(x, y, batch_size))

    def predict(self, x=None, batch_size: Optional[int] = None,
                **_: Any) -> np.ndarray:
        """Class probabilities (n, n_classes)."""
        self._require_built()
        eng = self._get_engine()
        state = self._state or eng.init_state(self.params)
        logits = eng.predict(state, self._batcher(x, None, batch_size))
        return np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))

    def num_params(self) -> int:
        if self.params is None:
            return 0
        return sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(self.params))

    # artifact-store native protocol --------------------------------
    def __lo_save__(self, path: str) -> None:
        from learningorchestra_tpu.runtime import checkpoint as ckpt

        config = {k: getattr(self, k) for k in self._CONFIG_KEYS}
        config.update(name=self.name, optimizer_spec=self.optimizer_spec,
                      seed=self.seed, history=self.history,
                      built=self.params is not None)
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump(config, f)
        if self.params is not None:
            ckpt.save_pytree({"params": self.params},
                             os.path.join(path, "weights.msgpack"))

    @classmethod
    def __lo_load__(cls, path: str) -> "TextClassifier":
        from learningorchestra_tpu.runtime import checkpoint as ckpt

        with open(os.path.join(path, "config.json")) as f:
            config = json.load(f)
        model = cls(**{k: config[k] for k in cls._CONFIG_KEYS
                       if k in config},
                    name=config["name"])
        model.optimizer_spec = config["optimizer_spec"]
        model.seed = config["seed"]
        model.history = config["history"]
        if config["built"]:
            sample = np.zeros((1, 8), np.int32)
            model._build_params(sample)
            restored = ckpt.load_pytree(
                os.path.join(path, "weights.msgpack"),
                {"params": model.params})
            model.params = restored["params"]
        return model


def _lora_optimizer(base):
    """Freeze everything except ``lora_*`` leaves: optax.multi_transform
    routes adapter params through the real optimizer and pins the base
    weights with set_to_zero — so optimizer state (adam mu/nu) exists
    ONLY for the adapters, the actual memory win of LoRA."""
    import optax

    def labels(params):
        def label(path, _):
            leaf = getattr(path[-1], "key", str(path[-1]))
            return "lora" if str(leaf).startswith("lora_") else "frozen"

        return jax.tree_util.tree_map_with_path(label, params)

    return optax.multi_transform(
        {"lora": base, "frozen": optax.set_to_zero()}, labels)


# ----------------------------------------------------------------------
# Quantized serving weights (docs/SERVING.md "Quantized serving").
#
# Serving is read-only over a pinned copy of the params, so the
# fp32/bf16 MASTER tree stays untouched for training/LoRA — only the
# serving pin narrows. A quantized leaf is replaced by a dict
# {"qvalue": int8/fp8, "qscale": f32 per-output-channel,
#  "qlike": 0-d array carrying the original dtype}; dequant runs as
# the first op INSIDE the jitted serve step/prefill, so XLA fuses the
# convert+scale into the consuming matmul operand and no full-width
# copy of the weights persists in HBM. Unquantized trees pass through
# both functions structurally unchanged, which is what keeps bf16
# sessions bit-identical to the pre-quantization serving plane.
# ----------------------------------------------------------------------

_WEIGHT_QUANT_LEAVES = ("kernel", "embedding")
_FP8_MAX = 448.0  # float8_e4m3fn finite max


def quantize_serving_params(params, dtype: str):
    """Quantize the matmul weights of a param tree for serving.

    ``dtype`` is ``"bf16"`` (no-op — the tree is returned as-is),
    ``"int8"`` (symmetric per-output-channel, scale = amax/127) or
    ``"fp8"`` (float8_e4m3fn, scale = amax/448; raises
    :class:`ValueError` when the installed jax lacks fp8 dtypes so
    the platform gate fails loudly at session create, not mid-step).
    Only ``kernel``/``embedding`` leaves with ndim >= 2 narrow; norms,
    biases and LoRA adapters (tiny, precision-sensitive) ride along
    unchanged."""
    if dtype in (None, "", "bf16"):
        return params
    if dtype == "fp8" and not hasattr(jnp, "float8_e4m3fn"):
        raise ValueError(
            "fp8 serving weights need jax.numpy.float8_e4m3fn, which "
            "this jax build does not provide — use int8 or bf16")
    if dtype not in ("int8", "fp8"):
        raise ValueError(
            f"unknown serving weight dtype {dtype!r} (bf16|int8|fp8)")

    def quant_leaf(a):
        f = jnp.asarray(a).astype(jnp.float32)
        axes = tuple(range(f.ndim - 1))
        amax = jnp.max(jnp.abs(f), axis=axes)
        if dtype == "int8":
            scale = jnp.maximum(amax / 127.0, attn_ops._QUANT_EPS)
            q = jnp.clip(jnp.round(f / scale), -127,
                         127).astype(jnp.int8)
        else:
            scale = jnp.maximum(amax / _FP8_MAX, attn_ops._QUANT_EPS)
            q = (f / scale).astype(jnp.float8_e4m3fn)
        return {"qvalue": q, "qscale": scale,
                "qlike": jnp.zeros((), jnp.asarray(a).dtype)}

    def walk(node):
        if isinstance(node, dict) or hasattr(node, "items"):
            return {k: (quant_leaf(v)
                        if k in _WEIGHT_QUANT_LEAVES
                        and jnp.ndim(v) >= 2 else walk(v))
                    for k, v in node.items()}
        return node

    return walk(params)


def dequantize_serving_params(tree):
    """Inverse of :func:`quantize_serving_params` — expand quantized
    leaf dicts back to their original dtype. Called INSIDE the jitted
    serve fns (fused dequant); a tree with no quantized leaves passes
    through with identical leaves, so the bf16 path compiles to the
    exact pre-quantization program."""
    if isinstance(tree, dict) or hasattr(tree, "items"):
        if "qvalue" in tree and "qscale" in tree:
            deq = tree["qvalue"].astype(jnp.float32) * tree["qscale"]
            return deq.astype(tree["qlike"].dtype)
        return {k: dequantize_serving_params(v)
                for k, v in tree.items()}
    return tree


class LanguageModel:
    """Trainable LM artifact with the reference's method-call surface.

    ``attention="auto"`` picks the Pallas flash kernel on TPU and the
    XLA-fused dot implementation elsewhere.
    """

    _CONFIG_KEYS = ("vocab_size", "d_model", "n_layers", "n_heads",
                    "n_kv_heads", "d_ff", "max_len", "attention",
                    "n_experts", "moe_k",
                    "dropout", "aux_coef", "head_chunk", "remat",
                    "fused_proj", "lora_rank", "lora_alpha",
                    "sliding_window", "rope_base")

    def __init__(self, vocab_size: int, d_model: int = 256,
                 n_layers: int = 4, n_heads: int = 4,
                 n_kv_heads: int = 0, d_ff: int = 0,
                 max_len: int = 512, attention: str = "auto",
                 n_experts: int = 0, moe_k: int = 2, dropout: float = 0.0,
                 aux_coef: float = 0.01, head_chunk: Optional[int] = None,
                 remat: Optional[str] = None, fused_proj: bool = False,
                 lora_rank: int = 0, lora_alpha: float = 16.0,
                 sliding_window: int = 0, rope_base: float = 10000.0,
                 name: str = "language_model"):
        self.name = name
        self.head_chunk = head_chunk
        self.fused_proj = bool(fused_proj)
        self.lora_rank = int(lora_rank)
        self.lora_alpha = float(lora_alpha)
        if self.lora_rank < 0:
            raise ValueError(f"lora_rank must be >= 0, got {lora_rank}")
        self.rope_base = float(rope_base)
        if self.rope_base <= 1.0:
            raise ValueError(
                f"rope_base must be > 1, got {rope_base}")
        self.sliding_window = int(sliding_window)
        if self.sliding_window < 0:
            raise ValueError(
                f"sliding_window must be >= 0, got {sliding_window}")
        # LO_TLM_REMAT env overrides; default "none" (measure before
        # paying recompute FLOPs — see BENCHMARKS.md queued table)
        self.remat = remat
        self.vocab_size = int(vocab_size)
        self.d_model = int(d_model)
        self.n_layers = int(n_layers)
        self.n_heads = int(n_heads)
        self.n_kv_heads = int(n_kv_heads)
        if self.n_kv_heads < 0 or (
                self.n_kv_heads and self.n_heads % self.n_kv_heads):
            raise ValueError(
                f"n_kv_heads={self.n_kv_heads} must be a positive "
                f"divisor of n_heads={self.n_heads} (or 0 for MHA)")
        self.d_ff = int(d_ff)
        self.max_len = int(max_len)
        self.attention = attention
        self.n_experts = int(n_experts)
        self.moe_k = int(moe_k)
        self.dropout = float(dropout)
        self.aux_coef = float(aux_coef)
        self.optimizer_spec: Dict[str, Any] = {"kind": "adamw",
                                               "learning_rate": 3e-4}
        self.params: Any = None
        self.history: List[Dict[str, Any]] = []
        self.seed = 0
        self._engine: Optional[engine_lib.Engine] = None
        self._state = None
        self._mesh_override = None
        self._accum = engine_lib.default_grad_accum()
        self._drop_decode_caches()

    def set_mesh(self, mesh) -> None:
        """Pin this model to a mesh (e.g. a sweep trial's sub-slice of
        the default mesh) instead of the process-wide default."""
        self._mesh_override = mesh
        self._engine = None
        # device state from a previous fit is laid out on the old mesh;
        # host params survive, state must rebuild on the new mesh
        self._state = None
        self._drop_decode_caches()

    def _drop_decode_caches(self) -> None:
        """Generation/beam compiles close over the mesh-resolved
        module — anything that changes the mesh or the param layout
        must drop them or a stale compile serves the old config."""
        self._gen_cache_fns = {}
        self._beam_cache_fns = {}
        self._serve_cache_fns = {}
        self._serve_paged_fns = {}
        self._serve_spec_fns = {}

    def _mesh(self):
        return self._mesh_override or mesh_lib.current_mesh()

    # ------------------------------------------------------------------
    def _resolved_attention(self, seq_len: Optional[int] = None) -> str:
        if self.attention != "auto":
            return self.attention
        # On-chip micro-bench (BENCHMARKS.md "Flash kernel", re-run
        # 2026-07-31 at the committed 512^2 auto tiles): the Pallas
        # flash kernel now beats XLA's fused dot at EVERY measured
        # length — 1024: 8.8 vs 9.7 ms causal (2.2x at full), 2048:
        # 12.1 vs 15.5 ms, 4096: 19.6 vs 36.0 ms — and is the only
        # path that compiles at 8k+ (dot materializes the (bh, s, s)
        # scores). Cross over at 1024 on the ACTUAL sequence length
        # when known; below 1024 is unmeasured, keep the dot oracle.
        if jax.default_backend() == "tpu":
            return "flash" if (seq_len or self.max_len) >= 1024 else "dot"
        return "dot"

    def _head_chunk(self) -> int:
        """Fused-head chunk size (0 = full logits). Auto rule: fuse
        when the vocab is large enough that the (tokens, vocab) f32
        logits tensor dominates the step's HBM traffic (the measured
        d=512 roofline gap, BENCHMARKS.md). Under sequence-parallel
        attention the loss runs its shard_map twin
        (:func:`_fused_head_loss_sharded`), keeping the sequence dim
        sharded. ``LO_LM_HEAD_CHUNK`` overrides (0 disables, N sets
        tokens per chunk)."""
        env = os.environ.get("LO_LM_HEAD_CHUNK")
        if env is not None:
            return max(0, int(env))
        if self.head_chunk is not None:
            return max(0, int(self.head_chunk))
        return 1024 if self.vocab_size >= 8192 else 0

    def _param_rules(self, mesh):
        """TP sharding rules, head-granular: a projection whose HEAD
        count doesn't divide tp replicates, even when the raw column
        count happens to divide — column-sharding across a head
        boundary is numerically fine under GSPMD but defeats the
        head-parallel attention plan (extra resharding at the
        attention einsum). Checked separately for q/o (n_heads) and
        k/v (n_kv_heads), which differ under GQA/MQA."""
        rules = tuple(sharding_lib.TRANSFORMER_RULES)
        kv = self.n_kv_heads or self.n_heads
        tp_size = mesh.shape.get(mesh_lib.TP, 1)
        if tp_size > 1 and kv % tp_size:
            rules = ((r".*(k_proj|v_proj)/kernel$", P()),) + rules
        if tp_size > 1 and self.n_heads % tp_size:
            rules = ((r".*(q_proj|o_proj)/kernel$", P()),) + rules
        if tp_size > 1:
            # fused projections: a column shard of the [q|k|v] (or
            # [gate|up]) concatenation crosses block boundaries, so
            # replicate — the param tree never changes with the mesh
            # (artifact portability); FSDP may still storage-shard
            rules = ((r".*(qkv_proj|gate_up)/kernel$", P()),) + rules
        return rules

    def _resolved_remat(self) -> str:
        value = os.environ.get("LO_TLM_REMAT") or self.remat or "none"
        if value not in ("none", "dots", "full"):
            # fail at construction/resolution, not deep inside the
            # first training trace — eval paths never hit the module's
            # own check
            raise ValueError(
                f"unknown remat policy {value!r} (none|dots|full)")
        return value

    def _resolved_fused_proj(self) -> bool:
        env = os.environ.get("LO_TLM_FUSED_PROJ")
        if not env:  # unset or empty -> constructor value
            return self.fused_proj
        value = env.strip().lower()
        if value in ("1", "true", "yes"):
            return True
        if value in ("0", "false", "no"):
            return False
        # fail at resolution, not by silently measuring the wrong
        # path (the _resolved_remat convention)
        raise ValueError(
            f"LO_TLM_FUSED_PROJ={env!r} (want 1/true/yes or "
            f"0/false/no)")

    def _module_for(self, seq_len: Optional[int] = None) -> TransformerLM:
        return TransformerLM(
            vocab_size=self.vocab_size, d_model=self.d_model,
            n_layers=self.n_layers, n_heads=self.n_heads,
            n_kv_heads=self.n_kv_heads, d_ff=self.d_ff,
            attention=self._resolved_attention(seq_len), causal=True,
            n_experts=self.n_experts, moe_k=self.moe_k,
            dropout=self.dropout, mesh=self._mesh_override,
            fused_head_chunk=self._head_chunk(),
            remat=self._resolved_remat(),
            fused_proj=self._resolved_fused_proj(),
            lora_rank=self.lora_rank, lora_alpha=self.lora_alpha,
            sliding_window=self.sliding_window,
            rope_base=self.rope_base)

    @property
    def module(self) -> TransformerLM:
        return self._module_for(None)

    def compile(self, optimizer: Any = "adamw", loss: Any = None,
                metrics: Any = None, **_: Any) -> None:
        if isinstance(optimizer, str):
            self.optimizer_spec = {"kind": optimizer}
        elif isinstance(optimizer, dict):
            self.optimizer_spec = dict(optimizer)
        elif hasattr(optimizer, "spec"):
            self.optimizer_spec = dict(optimizer.spec)
        else:
            raise TypeError(f"unsupported optimizer: {optimizer!r}")
        self._engine = None

    # ------------------------------------------------------------------
    def _apply_fn(self, params, model_state, batch, train, rng):
        rngs = {"dropout": rng} if (train and rng is not None and
                                    self.dropout) else None
        # batch["x"].shape is static under jit, so "auto" attention
        # resolves against the real window length at trace time
        module = self._module_for(int(batch["x"].shape[1]))
        out = module.apply({"params": params}, batch["x"],
                           train=train, rngs=rngs)
        return out, model_state

    def _build_params(self, sample_x: np.ndarray) -> None:
        rng = jax.random.PRNGKey(self.seed)
        variables = self.module.init(rng, jnp.asarray(sample_x[:1]),
                                     train=False)
        self.params = dict(variables)["params"]

    def _get_engine(self) -> engine_lib.Engine:
        if self._engine is None:
            from learningorchestra_tpu.config import get_config
            from learningorchestra_tpu.models.neural import build_optimizer

            dtype = jnp.bfloat16 \
                if get_config().compute_dtype == "bfloat16" else jnp.float32
            mesh = self._mesh()
            seq_axis = self._resolved_attention() in ("ring", "ulysses")
            def flops_floor(batch):
                # analytic train-step lower bound (6 flops per matmul
                # param per token + the causal-attention quad term):
                # pallas_call is a custom call XLA's cost analysis
                # counts as ZERO flops, so the flash path would
                # otherwise report a deflated MFU. The embedding table
                # is excluded — its lookup is a gather, not a matmul
                # (lm_head is a separate, counted matrix).
                b, s = batch["x"].shape[:2]
                matmul_params = (self.num_params()
                                 - self.vocab_size * self.d_model)
                attn = 6.0 * self.n_layers * b * s * s * self.d_model
                return 6.0 * max(matmul_params, 0) * b * s + attn

            optimizer = build_optimizer(self.optimizer_spec)
            if self.lora_rank > 0:
                optimizer = _lora_optimizer(optimizer)
            self._engine = engine_lib.Engine(
                apply_fn=self._apply_fn,
                loss_fn=next_token_loss(
                    self.aux_coef,
                    head_chunk=self._head_chunk() or 1024,
                    mesh=mesh),
                optimizer=optimizer,
                mesh=mesh,
                metrics={"accuracy": token_accuracy},
                compute_dtype=dtype,
                param_rules=self._param_rules(mesh),
                batch_sharding=jax.sharding.NamedSharding(
                    mesh, sharding_lib.batch_spec(mesh, seq_axis=seq_axis)),
                predict_transform=lambda outputs: outputs[0],
                flops_floor_fn=flops_floor,
                grad_accum=self._accum)
        return self._engine

    def _set_grad_accum(self, grad_accum: Optional[int]) -> None:
        """Fit-time microbatch override (env default LO_GRAD_ACCUM) —
        an effective change rebuilds the engine."""
        self._accum, changed = engine_lib.resolve_grad_accum(
            grad_accum, self._accum)
        if changed:
            self._engine = None

    # ------------------------------------------------------------------
    def _coerce_tokens(self, x) -> np.ndarray:
        if hasattr(x, "to_numpy"):
            x = data_lib.dataframe_to_arrays(x)["x"]
        x = np.asarray(x)
        if x.ndim == 1:  # flat corpus -> non-overlapping windows
            seq = min(self.max_len, max(2, len(x) // 2))
            n = len(x) // seq
            x = x[:n * seq].reshape(n, seq)
        if x.shape[1] > self.max_len:
            x = x[:, :self.max_len]
        return x.astype(np.int32)

    def _batcher(self, x, batch_size: Optional[int],
                 shuffle: bool = False) -> data_lib.ArrayBatcher:
        from learningorchestra_tpu.config import get_config

        mesh = self._mesh()
        return data_lib.ArrayBatcher(
            {"x": self._coerce_tokens(x)},
            batch_size or get_config().default_batch_size,
            shuffle=shuffle, seed=self.seed,
            dp_multiple=mesh_lib.data_parallel_size(mesh))

    def fit(self, x=None, y=None, batch_size: Optional[int] = None,
            epochs: int = 1, shuffle: bool = True, checkpointer=None,
            log_fn=None, grad_accum: Optional[int] = None,
            validation_split: float = 0.0, **_: Any):
        from learningorchestra_tpu.models.neural import History

        self._set_grad_accum(grad_accum)
        val_x = None
        if validation_split:
            # keras-parity tail split (sequences, no labels: held-out
            # windows scored on next-token loss/accuracy); range
            # validation shared with NeuralModel
            from learningorchestra_tpu.models.neural import (
                validation_tail_count)
            x = self._coerce_tokens(x)
            n_val = validation_tail_count(len(x), validation_split)
            val_x = x[-n_val:]
            x = x[:-n_val]
        batcher = self._batcher(x, batch_size, shuffle=shuffle)
        if self.params is None:
            self._build_params(batcher.array("x"))
        eng = self._get_engine()
        state = eng.init_state(self.params)
        state, history = eng.fit(state, batcher, epochs=epochs,
                                 seed=self.seed, checkpointer=checkpointer,
                                 log_fn=log_fn)
        if val_x is not None:
            val = eng.evaluate(state, self._batcher(val_x, batch_size))
            if not history:
                history.append({})
            for k, v in val.items():
                history[-1][f"val_{k}"] = v
        self._state = state
        self.params = engine_lib.to_host(state.params)
        self.history.extend(history)
        return History(history)

    def evaluate(self, x=None, y=None, batch_size: Optional[int] = None,
                 **_: Any) -> Dict[str, float]:
        self._require_built()
        eng = self._get_engine()
        state = self._state or eng.init_state(self.params)
        return eng.evaluate(state, self._batcher(x, batch_size))

    def predict(self, x=None, batch_size: Optional[int] = None,
                **_: Any) -> np.ndarray:
        """Next-token logits (n, seq, vocab)."""
        self._require_built()
        eng = self._get_engine()
        state = self._state or eng.init_state(self.params)
        return eng.predict(state, self._batcher(x, batch_size))

    def generate(self, prompt, max_new_tokens: int = 32,
                 temperature: float = 0.0, seed: int = 0,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 num_beams: int = 1) -> np.ndarray:
        """Greedy / temperature sampling with an incremental KV cache:
        the prompt runs ONCE (prefill fills every layer's K/V cache),
        then the whole continuation decodes inside ONE jitted
        ``lax.fori_loop`` of single-position forwards attending over
        the cache — O(L) per token instead of the O(L²) full
        re-forward, and one host round trip for the entire
        continuation. prompt: (b, s) token ids.

        ``top_k`` keeps only the k highest-logit tokens and ``top_p``
        keeps the smallest nucleus whose probability mass reaches p;
        both apply only when ``temperature > 0`` (greedy decoding
        ignores them) and compose (k-filter first, then nucleus).

        Prompts longer than ``max_len`` keep their last ``max_len - 1``
        tokens (sliding-window truncation). Token id 0 is reserved as
        padding by ``next_token_loss`` and is masked out of sampling.

        Unequal-length prompts are accepted (list of lists): rows are
        left-padded with id 0 so the last prompt tokens align, and the
        attention mask hides pad columns — each row's continuation is
        the same tokens a solo ``generate([row])`` call would produce
        (greedy; sampled runs draw per-position keys from the shared
        buffer layout). The returned array keeps the leading pad zeros
        so rows stay rectangular; slice ``row[pad:]`` to recover the
        solo-shaped sequence.
        """
        self._require_built()
        if num_beams > 1:
            if temperature > 0:
                raise ValueError(
                    "beam search is deterministic — use temperature=0 "
                    "(sampling and beams don't compose)")
            if top_k is not None or top_p is not None:
                raise ValueError(
                    "beam search is deterministic — top_k/top_p "
                    "sampling filters don't compose with num_beams>1")
            if num_beams >= self.vocab_size:
                # token 0 is pad-masked, so vocab-1 real candidates
                raise ValueError(
                    f"num_beams={num_beams} exceeds the "
                    f"{self.vocab_size - 1} non-pad vocabulary "
                    f"candidates")
            return self._beam_search(prompt, max_new_tokens,
                                     int(num_beams))
        if temperature <= 0:
            # greedy argmax never reads the filters — normalize so
            # generate(.., top_k=50) shares the greedy compile
            top_k = top_p = None
        if top_k is not None:
            top_k = int(top_k)
            if top_k < 1:
                raise ValueError(f"top_k must be >= 1, got {top_k}")
            if top_k >= self.vocab_size:
                top_k = None  # keeps everything — same compile as None
        if top_p is not None:
            top_p = float(top_p)
            if not 0.0 < top_p <= 1.0:
                raise ValueError(f"top_p must be in (0, 1], got {top_p}")
            if top_p == 1.0:
                top_p = None  # keeps everything — same compile as None
        prompt, b, s, total, pad = self._prep_prompt(prompt,
                                                     max_new_tokens)
        if total <= s:
            # nothing to generate — prefill would clamp buf[:, s] onto
            # the last prompt column and corrupt it
            return prompt
        buf = np.zeros((b, total), np.int32)
        buf[:, :s] = prompt
        buf = jnp.asarray(buf)
        prefill, decode = self._gen_fns(
            b, s, total, float(temperature), top_k, top_p,
            padded=pad is not None)
        params = self.params
        key = jax.random.PRNGKey(seed)
        key, sub = jax.random.split(key)
        if pad is None:
            buf, cache = prefill(params, buf, sub)
            if total > s + 1:
                key, sub = jax.random.split(key)
                buf, cache = decode(params, cache, buf, sub)
        else:
            # unequal-length prompts: left-pad aligned the rows' last
            # tokens, so the whole batch prefills and decodes in
            # lockstep — pad rows are hidden by the attention mask
            pad_j = jnp.asarray(pad)
            buf, cache = prefill(params, buf, sub, pad_j)
            if total > s + 1:
                key, sub = jax.random.split(key)
                buf, cache = decode(params, cache, buf, sub, pad_j)
        return np.asarray(buf)

    def _prep_prompt(self, prompt, max_new_tokens: int):
        """Shared generate/beam preprocessing: 2-D int32 prompt,
        sliding-window truncation of prompts at/over max_len, and the
        clamped total length. A list of UNEQUAL-length prompts is
        left-padded (with the reserved pad id 0) so every row's last
        prompt token lands in the same column and the batch decodes in
        lockstep; the returned ``pad`` (``(b,)`` int32, None for
        rectangular input) carries each row's pad width into the
        attention masks."""
        pad = None
        if isinstance(prompt, (list, tuple)) and len(prompt) > 1 and \
                all(hasattr(p, "__len__") for p in prompt) and \
                len({len(p) for p in prompt}) > 1:
            s = max(len(p) for p in prompt)
            rows = np.zeros((len(prompt), s), np.int32)
            pad = np.zeros(len(prompt), np.int32)
            for i, p in enumerate(prompt):
                arr = np.asarray(p, dtype=np.int32).reshape(-1)
                pad[i] = s - arr.shape[0]
                rows[i, pad[i]:] = arr
            prompt = rows
        prompt = np.atleast_2d(np.asarray(prompt)).astype(np.int32)
        b, s = prompt.shape
        if s >= self.max_len:
            keep = self.max_len - 1
            prompt = prompt[:, -keep:]
            if pad is not None:
                pad = np.minimum(pad - (s - keep), keep).clip(0) \
                    .astype(np.int32)
            s = prompt.shape[1]
        total = min(self.max_len, s + max_new_tokens)
        return prompt, b, s, total, pad

    # ------------------------------------------------------------------
    # beam search
    # ------------------------------------------------------------------
    def _beam_search(self, prompt, max_new_tokens: int,
                     num_beams: int) -> np.ndarray:
        """Deterministic beam search over the KV cache: prefill runs
        once per sample, the cache tiles to ``b·beams`` rows, and each
        jitted ``fori_loop`` step scores every (beam, token) candidate
        (summed log-probs), keeps the top ``num_beams``, and REORDERS
        buf+cache by each survivor's parent beam (a batch-axis gather
        inside the loop). All beams share one fixed length, so raw
        summed log-prob is the ranking (no length penalty needed);
        returns the best beam per sample, shape (b, s+new)."""
        prompt, b, s, total, pad = self._prep_prompt(prompt,
                                                     max_new_tokens)
        if pad is not None:
            raise ValueError(
                "beam search requires equal-length prompts (pass one "
                "prompt at a time, or use num_beams=1 which "
                "left-pads)")
        if total <= s:
            return prompt
        fns = self._beam_cache_fns
        sig = (b, s, total, num_beams, self._resolved_attention(s))
        if sig not in fns:
            fns[sig] = self._build_beam_fns(b, s, total, num_beams)
        run = fns[sig]
        return np.asarray(run(self.params, jnp.asarray(prompt)))

    def _build_beam_fns(self, b: int, s: int, total: int, n: int):
        module = self._module_for(s)
        V = self.vocab_size

        def logp_of(logits):
            lg = logits.astype(jnp.float32)
            lg = lg.at[..., 0].set(ring_lib.NEG_INF)  # pad token
            return jax.nn.log_softmax(lg, axis=-1)

        @jax.jit
        def run(params, prompt):
            buf0 = jnp.zeros((b, total), jnp.int32).at[:, :s].set(prompt)
            (logits, _), mut = module.apply(
                {"params": params}, prompt, train=False,
                cache_len=total, mutable=["cache"])
            first = logp_of(logits[:, -1])                  # (b, V)
            scores, toks = jax.lax.top_k(first, n)          # (b, n)
            buf = jnp.repeat(buf0[:, None, :], n, axis=1)   # (b, n, T)
            buf = buf.at[:, :, s].set(toks)
            cache = jax.tree_util.tree_map(
                lambda c: jnp.repeat(c, n, axis=0), mut["cache"])

            def body(pos, carry):
                buf, cache, scores = carry
                tok = jax.lax.dynamic_slice(
                    buf, (0, 0, pos - 1), (b, n, 1)).reshape(b * n, 1)
                (lg, _), mut = module.apply(
                    {"params": params, "cache": cache}, tok,
                    train=False, decode_pos=pos - 1, cache_len=total,
                    mutable=["cache"])
                logp = logp_of(lg[:, 0]).reshape(b, n, V)
                cand = scores[..., None] + logp             # (b, n, V)
                scores, flat = jax.lax.top_k(
                    cand.reshape(b, n * V), n)              # (b, n)
                parent = flat // V
                token = (flat % V).astype(jnp.int32)
                buf = jnp.take_along_axis(
                    buf, parent[..., None], axis=1)
                buf = jax.lax.dynamic_update_slice(
                    buf, token[..., None], (0, 0, pos))
                rows = (jnp.arange(b)[:, None] * n
                        + parent).reshape(-1)               # (b*n,)
                cache = jax.tree_util.tree_map(
                    lambda c: jnp.take(c, rows, axis=0), mut["cache"])
                return buf, cache, scores

            buf, cache, scores = jax.lax.fori_loop(
                s + 1, total, body, (buf, cache, scores))
            best = jnp.argmax(scores, axis=1)
            return jnp.take_along_axis(
                buf, best[:, None, None], axis=1)[:, 0]

        return run

    @staticmethod
    def _filter_logits(last, temperature: float,
                       top_k: Optional[int] = None,
                       top_p: Optional[float] = None):
        """The sampling transform of :meth:`_sample` up to (but not
        including) the draw: pad mask, temperature, top-k, top-p.
        Factored out so speculative acceptance (serve_fns_spec) can
        score draft tokens against the EXACT distribution _sample
        draws from — ``softmax(_filter_logits(...))`` for
        ``temperature > 0``, ``argmax`` for greedy."""
        # id 0 is the padding/loss-mask token — never emit it
        last = last.astype(jnp.float32).at[..., 0].set(ring_lib.NEG_INF)
        if temperature <= 0:
            return last
        logits = last / temperature
        if top_k is not None and top_k < logits.shape[-1]:
            kth = jnp.sort(logits, axis=-1)[..., -top_k, None]
            logits = jnp.where(logits < kth, ring_lib.NEG_INF, logits)
        if top_p is not None and top_p < 1.0:
            order = jnp.argsort(-logits, axis=-1)
            ranked = jnp.take_along_axis(logits, order, axis=-1)
            probs = jax.nn.softmax(ranked, axis=-1)
            # keep tokens whose EXCLUSIVE prefix mass is < p, so the
            # token that crosses the threshold stays in the nucleus
            keep = (jnp.cumsum(probs, axis=-1) - probs) < top_p
            ranked = jnp.where(keep, ranked, ring_lib.NEG_INF)
            inv = jnp.argsort(order, axis=-1)
            logits = jnp.take_along_axis(ranked, inv, axis=-1)
        return logits

    @staticmethod
    def _sample(last, temperature: float, key,
                top_k: Optional[int] = None,
                top_p: Optional[float] = None):
        logits = LanguageModel._filter_logits(last, temperature,
                                              top_k, top_p)
        if temperature <= 0:
            return jnp.argmax(logits, axis=-1)
        return jax.random.categorical(key, logits, axis=-1)

    def _gen_fns(self, b: int, s: int, total: int, temperature: float,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 padded: bool = False):
        """Jitted (prefill, decode) per (batch, prompt_len, total,
        temperature) — params/cache are arguments, not closures, so
        weights stay device-resident and repeated generate() calls
        reuse the compile. ``decode`` runs the WHOLE continuation in
        one fori_loop program (buf and cache donated into it, updated
        in place across iterations — no per-token host round trip).
        ``padded=True`` compiles the left-padded variant: prefill and
        decode take a per-row ``pad`` width and mask pad rows out of
        attention (unequal-length prompt batches)."""
        fns = self._gen_cache_fns
        # resolve flash-vs-dot from the PREFILL length, not max_len: a
        # max_len>=2048 model generating from a short prompt attends
        # over only s tokens, below the measured flash crossover
        sig = (b, s, total, temperature, top_k, top_p,
               self._resolved_attention(s), padded)
        if sig in fns:
            return fns[sig]
        module = self._module_for(s)

        if padded:
            @jax.jit
            def prefill(params, buf, key, pad):
                (logits, _), mut = module.apply(
                    {"params": params}, buf[:, :s], train=False,
                    cache_len=total, pad_offset=pad,
                    mutable=["cache"])
                nxt = self._sample(logits[:, -1], temperature, key,
                                   top_k, top_p)
                buf = buf.at[:, s].set(nxt.astype(jnp.int32))
                return buf, mut["cache"]

            @functools.partial(jax.jit, donate_argnums=(1, 2))
            def decode(params, cache, buf, key, pad):
                def body(pos, carry):
                    buf, cache = carry
                    tok = jax.lax.dynamic_slice(buf, (0, pos - 1),
                                                (b, 1))
                    (logits, _), mut = module.apply(
                        {"params": params, "cache": cache}, tok,
                        train=False, decode_pos=pos - 1,
                        cache_len=total, pad_offset=pad,
                        mutable=["cache"])
                    nxt = self._sample(logits[:, 0], temperature,
                                       jax.random.fold_in(key, pos),
                                       top_k, top_p)
                    buf = jax.lax.dynamic_update_slice(
                        buf, nxt[:, None].astype(jnp.int32), (0, pos))
                    return buf, mut["cache"]

                return jax.lax.fori_loop(s + 1, total, body,
                                         (buf, cache))

            fns[sig] = (prefill, decode)
            return fns[sig]

        @jax.jit
        def prefill(params, buf, key):
            (logits, _), mut = module.apply(
                {"params": params}, buf[:, :s], train=False,
                cache_len=total, mutable=["cache"])
            nxt = self._sample(logits[:, -1], temperature, key,
                               top_k, top_p)
            buf = buf.at[:, s].set(nxt.astype(jnp.int32))
            return buf, mut["cache"]

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def decode(params, cache, buf, key):
            # the WHOLE decode loop runs as one device program
            # (lax.fori_loop carrying buf+cache) — one host round trip
            # for the entire continuation instead of one per token,
            # which dominates generate() latency on relayed backends
            def body(pos, carry):
                buf, cache = carry
                tok = jax.lax.dynamic_slice(buf, (0, pos - 1), (b, 1))
                (logits, _), mut = module.apply(
                    {"params": params, "cache": cache}, tok, train=False,
                    decode_pos=pos - 1, cache_len=total,
                    mutable=["cache"])
                nxt = self._sample(logits[:, 0], temperature,
                                   jax.random.fold_in(key, pos),
                                   top_k, top_p)
                buf = jax.lax.dynamic_update_slice(
                    buf, nxt[:, None].astype(jnp.int32), (0, pos))
                return buf, mut["cache"]

            return jax.lax.fori_loop(s + 1, total, body, (buf, cache))

        fns[sig] = (prefill, decode)
        return fns[sig]

    # ------------------------------------------------------------------
    # resident serving (services/serving.py)
    # ------------------------------------------------------------------
    def serve_fns(self, slots: int, cache_len: int, temperature: float,
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None):
        """Jitted continuous-batching kernel set for a serving session
        (docs/SERVING.md): ``(step, prefill_for, join)``.

        - ``step(params, cache, tok (slots,1), col (slots,), keys
          (slots,2))`` advances EVERY slot one token: each row attends
          its own cache prefix at its own position ``col[i]`` and
          samples with its own fold_in(key_i, col_i+1) — exactly the
          key/position schedule a solo ``generate()`` row follows, so
          a slot's token stream is bit-identical to decoding that
          request alone. Idle slots compute garbage (finite — their
          mask sees a valid self position) that the caller discards.
        - ``prefill_for(s)`` returns the jitted batch-1 prompt prefill
          for prompt length ``s`` (cached per length): fills a
          (1, cache_len) layer cache and samples the first token.
        - ``join(cache, pcache, slot)`` scatters a prefill cache into
          the session cache at ``slot`` (traced index — one compile
          covers every slot, so slot reuse never recompiles).
        """
        fns = self._serve_cache_fns
        sig = (slots, cache_len, temperature, top_k, top_p)
        if sig not in fns:
            fns[sig] = self._build_serve_fns(slots, cache_len,
                                             temperature, top_k, top_p)
        return fns[sig]

    def _build_serve_fns(self, slots: int, cache_len: int,
                         temperature: float, top_k: Optional[int],
                         top_p: Optional[float]):
        module = self._module_for(1)
        sample = self._sample

        @functools.partial(jax.jit, donate_argnums=(1,))
        def step(params, cache, tok, col, keys):
            params = dequantize_serving_params(params)
            (logits, _), mut = module.apply(
                {"params": params, "cache": cache}, tok, train=False,
                decode_pos=col, cache_len=cache_len,
                mutable=["cache"])
            # per-row key schedule: fold_in(row_key, buffer_position)
            # where the position being WRITTEN is col+1 — matching the
            # solo decode loop's fold_in(key, pos) at pos = col + 1
            ks = jax.vmap(jax.random.fold_in)(keys, col + 1)
            nxt = jax.vmap(
                lambda lg, k: sample(lg[None], temperature, k,
                                     top_k, top_p)[0])(logits[:, 0], ks)
            return nxt.astype(jnp.int32), mut["cache"]

        prefill_cache: Dict[int, Any] = {}

        def prefill_for(s: int):
            if s in prefill_cache:
                return prefill_cache[s]
            pmod = self._module_for(s)

            @jax.jit
            def prefill(params, tokens, key):
                params = dequantize_serving_params(params)
                (logits, _), mut = pmod.apply(
                    {"params": params}, tokens, train=False,
                    cache_len=cache_len, mutable=["cache"])
                nxt = sample(logits[:, -1], temperature, key,
                             top_k, top_p)
                return nxt.astype(jnp.int32), mut["cache"]

            prefill_cache[s] = prefill
            return prefill

        @jax.jit
        def join(cache, pcache, slot):
            return jax.tree_util.tree_map(
                lambda sc, pc: sc.at[slot].set(pc[0]), cache, pcache)

        return step, prefill_for, join

    def serve_cache(self, slots: int, cache_len: int):
        """Zero-initialized per-layer KV cache for a serving session
        (the shape ``init`` would produce for a (slots, ·) decode)."""
        module = self._module_for(1)
        shapes = jax.eval_shape(
            lambda: module.init(
                jax.random.PRNGKey(0),
                jnp.zeros((slots, 1), jnp.int32), train=False,
                decode_pos=jnp.zeros((slots,), jnp.int32),
                cache_len=cache_len)["cache"])
        return jax.tree_util.tree_map(
            lambda sd: jnp.zeros(sd.shape, sd.dtype), shapes)

    def serve_fns_paged(self, slots: int, cache_len: int,
                        page_len: int, n_pages: int,
                        temperature: float,
                        top_k: Optional[int] = None,
                        top_p: Optional[float] = None,
                        kv_dtype: str = "bf16"):
        """Paged-KV variant of :meth:`serve_fns` (docs/SERVING.md
        "Paged KV"): the per-layer cache is one SHARED
        ``(n_pages, page_len, kv, d)`` pool and each stream owns an
        ordered page list (its block-table row) instead of a
        ``cache_len`` rectangle. Returns
        ``(step, prefill_for, join_paged, copy_page, sample_first)``:

        - ``step(params, pool, tok, col, block_tables, keys)`` — one
          continuous-batch decode step over the pool. The gather
          width is ``block_tables.shape[1]``: the session slices the
          table to the live-length bucket on the host, so one compile
          per bucket and short streams never gather long-stream
          pages. Rope/mask/sampling schedule is byte-for-byte the
          slot step's (bit-identity contract).
        - ``prefill_for(s)`` — per-length batch-1 prefill returning
          ``(next_token, last_logits, pcache)``; ``last_logits``
          feeds the prefix cache so an exact-prompt hit can resample
          a first token without recomputing the prefill.
        - ``join_paged(pool, pcache, page_ids, start_row)`` — write
          prefill KV rows ``[start_row, ·)`` directly into
          ``page_ids`` (one compile per page count; shared prefix
          pages are excluded and never rewritten).
        - ``copy_page(pool, src, dst)`` — clone one page (a prefix
          hit's partially-filled tail page is copy-on-write: the new
          stream appends into its own copy).
        - ``sample_first(logits, key)`` — the prefill's sampling
          epilogue alone, for prefix hits that skipped the prefill.

        ``kv_dtype="int8"`` switches the pool to int8 values + a
        per-page-per-head scale pool ("Quantized serving"): the same
        five functions over half the pool bytes, with dequant fused
        into the gather/step.
        """
        fns = self._serve_paged_fns
        sig = (slots, cache_len, page_len, n_pages, temperature,
               top_k, top_p, kv_dtype)
        if sig not in fns:
            fns[sig] = self._build_serve_fns_paged(
                slots, cache_len, page_len, n_pages, temperature,
                top_k, top_p, kv_dtype)
        return fns[sig]

    def _build_serve_fns_paged(self, slots: int, cache_len: int,
                               page_len: int, n_pages: int,
                               temperature: float,
                               top_k: Optional[int],
                               top_p: Optional[float],
                               kv_dtype: str = "bf16"):
        module = self._module_for(1)
        sample = self._sample
        kv_quant = kv_dtype == "int8"

        @functools.partial(jax.jit, donate_argnums=(1,))
        def step(params, pool, tok, col, block_tables, keys):
            params = dequantize_serving_params(params)
            (logits, _), mut = module.apply(
                {"params": params, "cache": pool}, tok, train=False,
                decode_pos=col, cache_len=cache_len,
                block_tables=block_tables, page_len=page_len,
                kv_pages=n_pages, kv_quant=kv_quant,
                mutable=["cache"])
            # same per-row fold_in(key, col + 1) schedule as the slot
            # step — the whole bit-identity story rides on it
            ks = jax.vmap(jax.random.fold_in)(keys, col + 1)
            nxt = jax.vmap(
                lambda lg, k: sample(lg[None], temperature, k,
                                     top_k, top_p)[0])(logits[:, 0], ks)
            return nxt.astype(jnp.int32), mut["cache"]

        prefill_cache: Dict[int, Any] = {}

        def prefill_for(s: int):
            if s in prefill_cache:
                return prefill_cache[s]
            pmod = self._module_for(s)

            @jax.jit
            def prefill(params, tokens, key):
                params = dequantize_serving_params(params)
                (logits, _), mut = pmod.apply(
                    {"params": params}, tokens, train=False,
                    cache_len=cache_len, mutable=["cache"])
                nxt = sample(logits[:, -1], temperature, key,
                             top_k, top_p)
                return (nxt.astype(jnp.int32), logits[:, -1],
                        mut["cache"])

            prefill_cache[s] = prefill
            return prefill

        # both donate the pool like step() does: without donation
        # every prefill join / tail clone materializes a second full
        # copy of the page pool in HBM (transient 2x footprint per
        # layer tree), which would break equal-HBM sizing at large
        # pool sizes
        if kv_quant:
            # the pool tree carries k_scale/v_scale leaves the plain
            # prefill cache lacks, so tree_map's structure match fails;
            # walk the dicts by hand and quantize at the k/v level
            @functools.partial(jax.jit, donate_argnums=(0,))
            def join_paged(pool, pcache, page_ids, start_row):
                def walk(pl, pc):
                    if isinstance(pl, dict) or hasattr(pl, "items"):
                        if "k_scale" in pl:
                            kq, ks = \
                                attn_ops.quantized_paged_prefill_write(
                                    pl["k"], pl["k_scale"], pc["k"][0],
                                    page_ids, start_row)
                            vq, vs = \
                                attn_ops.quantized_paged_prefill_write(
                                    pl["v"], pl["v_scale"], pc["v"][0],
                                    page_ids, start_row)
                            return {"k": kq, "k_scale": ks,
                                    "v": vq, "v_scale": vs}
                        return {k: walk(pl[k], pc[k]) for k in pl}
                    return pl

                return walk(pool, pcache)
        else:
            @functools.partial(jax.jit, donate_argnums=(0,))
            def join_paged(pool, pcache, page_ids, start_row):
                return jax.tree_util.tree_map(
                    lambda pl, pc: attn_ops.paged_prefill_write(
                        pl, pc[0], page_ids, start_row), pool, pcache)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def copy_page(pool, src, dst):
            return jax.tree_util.tree_map(
                lambda pl: pl.at[dst].set(pl[src]), pool)

        @jax.jit
        def sample_first(logits, key):
            # identical floats to the prefill's own epilogue: the
            # cached logits ARE the prefill's logits[:, -1] row
            return sample(logits[None], temperature, key,
                          top_k, top_p)[0].astype(jnp.int32)

        return step, prefill_for, join_paged, copy_page, sample_first

    def serve_cache_paged(self, n_pages: int, page_len: int,
                          kv_dtype: str = "bf16"):
        """Zero-initialized shared KV page pool:
        ``{layer: {k/v: (n_pages, page_len, kv_heads, head_dim)}}`` —
        ONE allocation every stream's block table indexes into. Under
        ``kv_dtype="int8"`` the k/v leaves are int8 and per-layer
        ``k_scale``/``v_scale`` ``(n_pages, kv_heads)`` float32 leaves
        ride along (zero scales dequantize to exact zeros, matching
        the zero pool)."""
        module = self._module_for(1)
        shapes = jax.eval_shape(
            lambda: module.init(
                jax.random.PRNGKey(0),
                jnp.zeros((1, 1), jnp.int32), train=False,
                decode_pos=jnp.zeros((1,), jnp.int32),
                cache_len=page_len * n_pages,
                block_tables=jnp.zeros((1, 1), jnp.int32),
                page_len=page_len, kv_pages=n_pages,
                kv_quant=kv_dtype == "int8")["cache"])
        return jax.tree_util.tree_map(
            lambda sd: jnp.zeros(sd.shape, sd.dtype), shapes)

    def serve_fns_spec(self, slots: int, cache_len: int,
                       page_len: int, n_pages: int, spec_k: int,
                       temperature: float,
                       top_k: Optional[int] = None,
                       top_p: Optional[float] = None,
                       kv_dtype: str = "bf16"):
        """Speculative-decoding verify step for a paged serving
        session (docs/SERVING.md "Disaggregated serving & speculative
        decoding"): ONE jitted dispatch that scores the last accepted
        token plus ``spec_k`` draft tokens, accepts a prefix of the
        drafts by exact rejection sampling against this (target)
        model's sampling distribution, and emits the correction/bonus
        token — up to ``spec_k + 1`` tokens per step.

        ``verify(params, pool, tok (slots,1), drafts (slots,k),
        col (slots,), keys (slots,2), block_tables, limit (slots,))``
        returns ``(emitted (slots, k+1) int32, n_acc (slots,) int32,
        pool)``; a slot's valid emissions are
        ``emitted[:n_acc + 1]``, continuing its stream at positions
        ``col+1 .. col+n_acc+1``.

        Exactness: the drafts are the draft model's GREEDY picks — a
        one-hot proposal q — so the standard accept probability
        ``min(1, p/q)`` reduces to ``p(draft)`` under the target's
        :meth:`_filter_logits` distribution, and the rejection
        residual ``max(p - q, 0)`` normalized is exactly p with the
        draft token excluded: every emitted position is distributed
        exactly as a solo :meth:`_sample` draw. For greedy sessions
        (``temperature <= 0``) accept degenerates to
        ``draft == argmax(target)`` and the emitted stream is
        BIT-IDENTICAL to solo decode: the verify forward reproduces
        sequential single-token steps float-for-float
        (ops/attention.py paged_verify_attention) and argmax needs no
        randomness. Per-position keys follow the solo schedule —
        position ``pos`` folds ``fold_in(row_key, pos)``, split once
        into (accept-uniform, residual) keys for sampled sessions.

        Rejected drafts leave stale KV rows beyond the new ``col``;
        the visibility mask hides them and the next window overwrites
        them — no rollback. ``limit`` is each stream's last funded
        position: past-limit appends land in trash page 0, so a
        window overrunning a stream's pages can never corrupt a
        neighbor (the host discards the overrun emissions).
        """
        fns = self._serve_spec_fns
        sig = ("verify", slots, cache_len, page_len, n_pages, spec_k,
               temperature, top_k, top_p, kv_dtype)
        if sig in fns:
            return fns[sig]
        module = self._module_for(1)
        filter_fn = self._filter_logits
        kv_quant = kv_dtype == "int8"

        @functools.partial(jax.jit, donate_argnums=(1,))
        def verify(params, pool, tok, drafts, col, keys,
                   block_tables, limit):
            params = dequantize_serving_params(params)
            toks = jnp.concatenate([tok, drafts], axis=1)
            (logits, _), mut = module.apply(
                {"params": params, "cache": pool}, toks, train=False,
                decode_pos=col, cache_len=cache_len,
                block_tables=block_tables, page_len=page_len,
                kv_pages=n_pages, kv_quant=kv_quant,
                verify_limit=limit, mutable=["cache"])
            # logits[:, i] scores position col + i + 1 — the position
            # draft i (or the correction after a rejection) lands at
            filt = filter_fn(logits, temperature, top_k, top_p)
            rows = jnp.arange(toks.shape[0])
            if temperature <= 0:
                choice = jnp.argmax(filt, axis=-1).astype(jnp.int32)
                accept = drafts == choice[:, :spec_k]
                corr = choice
            else:
                probs = jax.nn.softmax(filt, axis=-1)
                acc_cols, corr_cols = [], []
                for i in range(spec_k):
                    kp = jax.vmap(jax.random.fold_in)(keys,
                                                      col + i + 1)
                    kur = jax.vmap(jax.random.split)(kp)
                    u = jax.vmap(
                        lambda k: jax.random.uniform(k))(kur[:, 0])
                    p_d = probs[rows, i, drafts[:, i]]
                    acc_cols.append(u < p_d)
                    resid = filt[:, i].at[rows, drafts[:, i]].set(
                        ring_lib.NEG_INF)
                    corr_cols.append(jax.vmap(
                        lambda lg, k: jax.random.categorical(k, lg))(
                        resid, kur[:, 1]))
                # bonus position (every draft accepted): a plain
                # categorical under the solo key schedule for
                # position col + spec_k + 1
                kp = jax.vmap(jax.random.fold_in)(keys,
                                                  col + spec_k + 1)
                corr_cols.append(jax.vmap(
                    lambda lg, k: jax.random.categorical(k, lg))(
                    filt[:, spec_k], kp))
                accept = jnp.stack(acc_cols, axis=1)
                corr = jnp.stack(corr_cols, axis=1).astype(jnp.int32)
            n_acc = jnp.sum(jnp.cumprod(
                accept.astype(jnp.int32), axis=1), axis=1)
            padded = jnp.concatenate(
                [drafts, jnp.zeros((drafts.shape[0], 1), jnp.int32)],
                axis=1)
            idx = jnp.arange(spec_k + 1)[None, :]
            emitted = jnp.where(
                idx < n_acc[:, None], padded,
                jnp.where(idx == n_acc[:, None], corr, 0))
            return (emitted.astype(jnp.int32),
                    n_acc.astype(jnp.int32), mut["cache"])

        fns[sig] = verify
        return fns[sig]

    def serve_fns_draft(self, slots: int, cache_len: int,
                        spec_k: int):
        """Draft-side propose step for speculative decoding: ONE
        jitted scan that greedily extends every slot by ``spec_k``
        tokens over the draft model's own slot KV cache (prompt KV
        arrives via :meth:`serve_fns`'s prefill/join, so the draft
        shares the target's admission path). The scan runs
        ``spec_k + 1`` forwards: the last feeds draft k purely to
        append its KV row, so the NEXT window's propose attends a
        complete prefix whatever the acceptance count was. Greedy
        proposals make the proposal distribution one-hot, which is
        what keeps acceptance sampling exact (see serve_fns_spec)."""
        fns = self._serve_spec_fns
        sig = ("draft", slots, cache_len, spec_k)
        if sig in fns:
            return fns[sig]
        module = self._module_for(1)
        sample = self._sample

        @functools.partial(jax.jit, donate_argnums=(1,))
        def propose(params, cache, tok, col):
            params = dequantize_serving_params(params)

            def body(carry, _):
                cache, tok, col = carry
                (logits, _), mut = module.apply(
                    {"params": params, "cache": cache}, tok,
                    train=False, decode_pos=col, cache_len=cache_len,
                    mutable=["cache"])
                nxt = sample(logits[:, 0], 0.0, None).astype(jnp.int32)
                return (mut["cache"], nxt[:, None], col + 1), nxt

            (cache, _, _), drafts = jax.lax.scan(
                body, (cache, tok, col), None, length=spec_k + 1)
            return jnp.transpose(drafts[:spec_k]), cache

        fns[sig] = propose
        return fns[sig]

    def _require_built(self) -> None:
        if self.params is None:
            raise RuntimeError(
                "model has no parameters yet — call fit() first "
                "(or load a trained artifact)")

    def enable_lora(self, rank: int, alpha: float = 16.0) -> None:
        """Attach fresh rank-``rank`` adapters to a trained model: the
        base kernels keep their values (B inits at zero, so step-0
        predictions are unchanged) and subsequent fit() updates ONLY
        the adapters (frozen-base optimizer). Reachable through the
        reference's call-method-on-stored-object train contract."""
        if self.lora_rank > 0:
            raise RuntimeError(
                f"model already has LoRA adapters (rank "
                f"{self.lora_rank}); merge_lora() first")
        if int(rank) <= 0:
            raise ValueError(f"rank must be >= 1, got {rank}")
        self._require_built()
        self.lora_rank = int(rank)
        self.lora_alpha = float(alpha)
        sample = jnp.zeros((1, min(8, self.max_len)), jnp.int32)
        fresh = self._module_for(None).init(
            jax.random.PRNGKey(self.seed), sample)["params"]

        def graft(fresh_node, old_node, path=""):
            if isinstance(fresh_node, dict):
                old = old_node if isinstance(old_node, dict) else {}
                return {k: graft(v, old.get(k), f"{path}/{k}")
                        for k, v in fresh_node.items()}
            if old_node is not None:
                return old_node
            # ONLY adapters may init fresh — any other missing leaf
            # means the trained tree's layout doesn't match this
            # config (e.g. a fused_proj env toggle) and silently
            # re-initializing it would discard trained weights
            if path.rsplit("/", 1)[-1].startswith("lora_"):
                return fresh_node
            raise ValueError(
                f"enable_lora: trained params have no leaf at "
                f"{path!r} — the model config resolves to a "
                f"different param layout (fused_proj/attention "
                f"mismatch?); refusing to re-initialize a base "
                f"weight")

        self.params = graft(fresh, engine_lib.to_host(self.params))
        self._engine = None
        self._state = None
        self._drop_decode_caches()

    def merge_lora(self) -> None:
        """Fold the adapters into the base kernels (W += A·B·α/r) and
        drop them: the model becomes a plain artifact, numerically
        identical to the adapted one, loadable anywhere without LoRA
        config."""
        if self.lora_rank <= 0:
            raise RuntimeError("model has no LoRA adapters to merge")
        self._require_built()
        scale = self.lora_alpha / self.lora_rank

        def walk(node):
            if isinstance(node, dict):
                if "lora_a" in node and "kernel" in node:
                    merged = node["kernel"] + np.asarray(
                        node["lora_a"]) @ np.asarray(
                        node["lora_b"]) * scale
                    return {"kernel": merged}
                return {k: walk(v) for k, v in node.items()}
            return node

        self.params = walk(engine_lib.to_host(self.params))
        self.lora_rank = 0
        self._engine = None
        self._state = None
        self._drop_decode_caches()

    def num_params(self) -> int:
        if self.params is None:
            return 0
        return sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(self.params))

    # ------------------------------------------------------------------
    # artifact-store native protocol (catalog/artifacts.py)
    # ------------------------------------------------------------------
    def __lo_save__(self, path: str) -> None:
        from learningorchestra_tpu.runtime import checkpoint as ckpt

        config = {k: getattr(self, k) for k in self._CONFIG_KEYS}
        config.update(name=self.name, optimizer_spec=self.optimizer_spec,
                      seed=self.seed, history=self.history,
                      built=self.params is not None)
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump(config, f)
        if self.params is not None:
            ckpt.save_pytree({"params": self.params},
                             os.path.join(path, "weights.msgpack"))

    @classmethod
    def __lo_load__(cls, path: str) -> "LanguageModel":
        from learningorchestra_tpu.runtime import checkpoint as ckpt

        with open(os.path.join(path, "config.json")) as f:
            config = json.load(f)
        # .get-style filter: configs saved before a key existed fall
        # back to the constructor default (e.g. head_chunk)
        model = cls(**{k: config[k] for k in cls._CONFIG_KEYS
                       if k in config},
                    name=config["name"])
        model.optimizer_spec = config["optimizer_spec"]
        model.seed = config["seed"]
        model.history = config["history"]
        if config["built"]:
            sample = np.zeros((1, 8), np.int32)
            model._build_params(sample)
            restored = ckpt.load_pytree(
                os.path.join(path, "weights.msgpack"),
                {"params": model.params})
            model.params = restored["params"]
        return model
