"""Incident flight recorder (docs/OBSERVABILITY.md "Incidents &
flight recorder") — tentpole + satellites.

The recorder is tested standalone against injected collectors (no
service layer), the trigger wiring against the real SLO watchdog and
JobManager, and the REST surface through ``Api.dispatch`` plus one
socket-level download. Satellite coverage: /profile auto-stop +
retention, bare trace/timeline listings, ``lo_build_info``, and the
event-log rotation torn-read race the bundle tail-read depends on.
"""

import json
import io
import os
import tarfile
import threading
import time

import pytest

from learningorchestra_tpu.observability import export as obs_export
from learningorchestra_tpu.observability import incidents as inc
from learningorchestra_tpu.observability import slo as slo_mod

# sections every bundle must freeze (ISSUE 13 acceptance)
REQUIRED_SECTIONS = {"cluster.json", "alerts.json", "memory.json",
                     "perf.json", "metrics.json", "eventlog.tail",
                     "config.json", "versions.json", "manifest.json"}

API = "/api/learningOrchestra/v1"


@pytest.fixture(autouse=True)
def _clear_registry():
    inc.set_recorder(None)
    yield
    inc.set_recorder(None)


@pytest.fixture()
def recorder(tmp_config):
    rec = inc.FlightRecorder(
        home=tmp_config.home,
        cluster_snapshot=lambda: {"samples": 1,
                                  "latest": {"hostRssBytes": 123}},
        alerts_snapshot=lambda: {"alerts": [], "firing": []},
        stats_snapshot=lambda: {"jobLifecycle": {"retries": 0}},
        active_names=lambda: [])
    yield rec
    rec.close()


@pytest.fixture()
def api(tmp_config):
    """In-process Api over a real ServiceContext (sampler parked)."""
    from learningorchestra_tpu.services.server import Api

    tmp_config.monitor_interval_ms = 3_600_000.0
    a = Api()
    yield a
    a.ctx.close()


def _wait(predicate, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return predicate()


def _bundle_files(tmp_config, iid):
    root = os.path.join(tmp_config.home, "incidents", iid)
    out = set()
    for dirpath, _dirs, fnames in os.walk(root):
        for fname in fnames:
            out.add(os.path.relpath(
                os.path.join(dirpath, fname), root))
    return out


# ----------------------------------------------------------------------
# recorder core
# ----------------------------------------------------------------------

def test_manual_capture_freezes_every_section(tmp_config, recorder):
    tmp_config.event_log = os.path.join(tmp_config.home, "events.jsonl")
    obs_export.log_event("test", "before-capture")
    manifest = recorder.capture("manual", {"reason": "unit"})
    iid = manifest["id"]
    on_disk = _bundle_files(tmp_config, iid)
    assert REQUIRED_SECTIONS <= on_disk
    assert manifest["trigger"] == "manual"
    assert manifest["context"]["reason"] == "unit"
    assert manifest["errors"] == {}
    assert manifest["totalBytes"] > 0
    assert set(manifest["buildInfo"]) == {
        "version", "jaxVersion", "backend", "deviceKind"}
    # the event-log tail rode in and is complete JSONL
    tail = open(os.path.join(tmp_config.home, "incidents", iid,
                             "eventlog.tail")).read()
    assert any(json.loads(line)["name"] == "before-capture"
               for line in tail.splitlines())
    # atomic commit: no half-written tmp dir left behind
    assert not [e for e in
                os.listdir(os.path.join(tmp_config.home, "incidents"))
                if e.startswith(".")]


def test_trigger_cooldown_mutes_storm_manual_bypasses(tmp_config,
                                                      recorder):
    tmp_config.incident_cooldown_s = 300.0
    assert recorder.trigger("slo:servingP99", trace="t") is True
    # a flapping alert re-fires inside the cooldown: muted
    assert recorder.trigger("slo:servingP99", trace="t") is False
    # distinct triggers have independent cooldowns
    assert recorder.trigger("job:deadLettered", job="j") is True
    # manual captures bypass the cooldown entirely
    recorder.capture("manual")
    recorder.capture("manual")
    assert _wait(lambda: recorder.stats()["captured"] >= 4)
    by = recorder.stats()["byTrigger"]
    assert by["slo:servingP99"] == 1 and by["manual"] == 2


def test_retention_prunes_oldest(tmp_config, recorder):
    tmp_config.incident_keep = 2
    ids = [recorder.capture("manual", {"n": i})["id"]
           for i in range(3)]
    kept = [b["id"] for b in recorder.list()]
    assert kept == sorted(ids)[-2:]
    assert recorder.stats()["bundles"] == 2


def test_manual_and_auto_captures_race_safely(tmp_config, recorder):
    tmp_config.incident_cooldown_s = 0.0
    tmp_config.incident_keep = 64  # retention must not eat the count
    inc.set_recorder(recorder)
    auto_fired = []

    def storm():
        for i in range(10):
            auto_fired.append(inc.trigger("job:stalled", job=f"j{i}"))

    threads = [threading.Thread(target=storm)] + [
        threading.Thread(
            target=lambda n=n: recorder.capture("manual", {"n": n}))
        for n in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    expected = 3 + sum(1 for ok in auto_fired if ok)
    assert _wait(
        lambda: recorder.stats()["captured"] == expected, timeout=60)
    # every committed bundle is complete and readable
    bundles = recorder.list()
    assert len(bundles) == expected
    for b in bundles:
        assert recorder.manifest(b["id"]) is not None
    assert not [e for e in
                os.listdir(os.path.join(tmp_config.home, "incidents"))
                if e.startswith(".")]


def test_tar_download_roundtrip(tmp_config, recorder):
    iid = recorder.capture("manual")["id"]
    blob = recorder.tar_bytes(iid)
    with tarfile.open(fileobj=io.BytesIO(blob)) as tar:
        names = tar.getnames()
        manifest = json.load(
            tar.extractfile(f"{iid}/manifest.json"))
    assert manifest["id"] == iid
    assert f"{iid}/versions.json" in names
    assert recorder.tar_bytes("nope") is None
    assert recorder.tar_bytes("../etc") is None


def test_failing_collector_becomes_manifest_error(tmp_config):
    def boom():
        raise RuntimeError("collector down")

    rec = inc.FlightRecorder(home=tmp_config.home,
                             cluster_snapshot=boom)
    try:
        manifest = rec.capture("manual")
        assert "cluster.json" in manifest["errors"]
        assert "collector down" in manifest["errors"]["cluster.json"]
        # the bundle still committed with every other section
        assert "versions.json" in manifest["files"]
    finally:
        rec.close()


def test_disabled_recorder_ignores_triggers(tmp_config, recorder):
    tmp_config.incidents = False
    inc.set_recorder(recorder)
    assert inc.trigger("slo:servingP99") is False
    assert recorder.stats()["captured"] == 0


# ----------------------------------------------------------------------
# trigger wiring: SLO watchdog, job manager, health sentinel
# ----------------------------------------------------------------------

def test_slo_firing_transition_captures_bundle(tmp_config, recorder):
    """The watchdog fires while holding its own alert lock; the
    recorder's alert collector re-takes that lock on the worker — the
    capture completing at all proves the enqueue never collects
    evidence synchronously."""
    inc.set_recorder(recorder)
    watchdog = slo_mod.SloWatchdog()
    recorder._alerts = watchdog.snapshot
    spec = {"severity": "page", "threshold": 10.0}
    watchdog._transition("servingP99", spec, True, True, 55.0,
                         time.time())
    assert _wait(lambda: any(
        b["trigger"] == "slo:servingP99" for b in recorder.list()))
    bundle = [b for b in recorder.list()
              if b["trigger"] == "slo:servingP99"][0]
    manifest = recorder.manifest(bundle["id"])
    # the firing alert context rode into the manifest
    assert manifest["context"]["alert"]["name"] == "servingP99"
    assert manifest["context"]["alert"]["transition"] == "firing"
    # and the frozen alert snapshot shows it firing
    alerts = json.load(open(os.path.join(
        tmp_config.home, "incidents", bundle["id"], "alerts.json")))
    assert any(a["name"] == "servingP99" and a["state"] == "firing"
               for a in alerts["alerts"])


def test_deadlettered_job_captures_bundle(tmp_config, recorder,
                                          catalog):
    from learningorchestra_tpu.services.jobs import JobManager

    inc.set_recorder(recorder)
    jobs = JobManager(catalog)
    try:
        catalog.create_collection("dead_job", "train/tensorflow")

        def bad_user_code():
            raise ValueError("bad hyperparameter")

        jobs.submit("dead_job", bad_user_code,
                    description="unit").result(timeout=30)
        assert _wait(lambda: any(
            b["trigger"] == "job:deadLettered"
            for b in recorder.list()))
        bundle = [b for b in recorder.list()
                  if b["trigger"] == "job:deadLettered"][0]
        manifest = recorder.manifest(bundle["id"])
        assert manifest["context"]["job"] == "dead_job"
        assert manifest["context"]["errorKind"] == "permanent"
        # the implicated job's span tree was frozen into the bundle
        assert "dead_job" in manifest["implicated"]["traces"]
        assert "trace/dead_job.json" in manifest["files"]
    finally:
        jobs.shutdown()


def test_health_rollback_listener_fires_recorder(tmp_config,
                                                 recorder):
    from learningorchestra_tpu.runtime import health as health_lib

    inc.set_recorder(recorder)
    seen = []

    def listener(kind, n):
        seen.append((kind, n))
        if kind == "rollbacks":
            inc.trigger("health:rollback")

    health_lib.add_listener(listener)
    try:
        health_lib.record("rollbacks")
        assert ("rollbacks", 1) in seen
        assert _wait(lambda: any(
            b["trigger"] == "health:rollback"
            for b in recorder.list()))
    finally:
        health_lib.remove_listener(listener)
        health_lib.reset_health_stats()


# ----------------------------------------------------------------------
# REST surface + context wiring
# ----------------------------------------------------------------------

def test_rest_incident_surface(api, tmp_config):
    status, body, _ = api.dispatch(
        "GET", f"{API}/observability/incidents", {}, None)
    assert status == 200 and body == {"result": []}
    status, manifest, _ = api.dispatch(
        "POST", f"{API}/observability/incidents", {},
        {"reason": "drill"})
    assert status == 201
    iid = manifest["id"]
    assert REQUIRED_SECTIONS <= _bundle_files(tmp_config, iid)
    status, body, _ = api.dispatch(
        "GET", f"{API}/observability/incidents", {}, None)
    assert [b["id"] for b in body["result"]] == [iid]
    status, body, _ = api.dispatch(
        "GET", f"{API}/observability/incidents/{iid}", {}, None)
    assert status == 200 and body["id"] == iid
    status, blob, ctype = api.dispatch(
        "GET", f"{API}/observability/incidents/{iid}/download",
        {}, None)
    assert status == 200 and ctype == "application/x-tar"
    with tarfile.open(fileobj=io.BytesIO(blob)) as tar:
        assert f"{iid}/manifest.json" in tar.getnames()
    status, _, _ = api.dispatch(
        "GET", f"{API}/observability/incidents/nope", {}, None)
    assert status == 404
    # the /metrics document and prometheus exposition both carry it
    status, m, _ = api.dispatch("GET", "/metrics", {}, None)
    assert m["incidents"]["captured"] == 1
    assert m["incidents"]["byTrigger"] == {"manual": 1}
    status, text, _ = api.dispatch(
        "GET", "/metrics", {"format": "prometheus"}, None)
    text = text.decode()
    assert 'lo_incidents_total{trigger="manual"} 1' in text
    assert "lo_incident_bytes " in text


def test_rest_incidents_disabled_404(tmp_config):
    from learningorchestra_tpu.services.server import Api

    tmp_config.monitor_interval_ms = 3_600_000.0
    tmp_config.incidents = False
    api = Api()
    try:
        assert api.ctx.incidents is None
        status, _, _ = api.dispatch(
            "GET", f"{API}/observability/incidents", {}, None)
        assert status == 404
        status, _, _ = api.dispatch(
            "POST", f"{API}/observability/incidents", {}, {})
        assert status == 404
        status, m, _ = api.dispatch("GET", "/metrics", {}, None)
        assert "incidents" not in m
    finally:
        api.ctx.close()


def test_context_wires_and_unwires_registry(api):
    assert inc.get_recorder() is api.ctx.incidents
    # a live-context trigger lands in the context's recorder
    assert inc.trigger("job:stalled", job="ghost") is True
    assert _wait(
        lambda: api.ctx.incidents.stats()["captured"] >= 1)


def test_incident_profile_coordinates_with_manual_profile(
        api, tmp_config, monkeypatch):
    import jax

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    tmp_config.incident_profile_s = 0.05
    # manual /profile session holds the gate: the incident window is
    # skipped and noted, never a double-start
    status, _, _ = api.dispatch("POST", f"{API}/profile", {},
                                {"action": "start"})
    assert status == 201
    manifest = api.ctx.incidents.capture("manual", {"profile": True})
    assert "profileSkipped" in manifest["notes"]
    status, _, _ = api.dispatch("POST", f"{API}/profile", {},
                                {"action": "stop"})
    assert status == 200
    # gate free: the window is captured into the bundle
    manifest = api.ctx.incidents.capture("manual", {"profile": True})
    assert manifest["notes"]["profileSeconds"] == 0.05
    assert "profileSkipped" not in manifest["notes"]


# ----------------------------------------------------------------------
# satellite: /profile auto-stop watchdog + retention
# ----------------------------------------------------------------------

def test_profile_auto_stop_watchdog(api, tmp_config, monkeypatch):
    import jax

    stopped = []
    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: stopped.append(True))
    tmp_config.profile_max_seconds = 0.1
    status, _, _ = api.dispatch("POST", f"{API}/profile", {},
                                {"action": "start"})
    assert status == 201
    assert _wait(lambda: bool(stopped), timeout=10)
    status, body, _ = api.dispatch("GET", f"{API}/profile", {}, None)
    assert body["active"] is False
    assert body["lastAutoStop"]["dir"]
    # startable again after the watchdog reclaimed the session
    status, _, _ = api.dispatch("POST", f"{API}/profile", {},
                                {"action": "start"})
    assert status == 201
    api.dispatch("POST", f"{API}/profile", {}, {"action": "stop"})


def test_profile_retention_bound(api, tmp_config, monkeypatch):
    import jax

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    tmp_config.profile_keep = 2
    for _ in range(3):
        status, _, _ = api.dispatch("POST", f"{API}/profile", {},
                                    {"action": "start"})
        assert status == 201
        time.sleep(0.01)  # distinct timestamped dir names
        status, _, _ = api.dispatch("POST", f"{API}/profile", {},
                                    {"action": "stop"})
        assert status == 200
    status, body, _ = api.dispatch("GET", f"{API}/profile", {}, None)
    assert len(body["traces"]) == 2


# ----------------------------------------------------------------------
# satellite: bare trace/timeline listings
# ----------------------------------------------------------------------

def test_bare_trace_and_timeline_listings(api):
    from learningorchestra_tpu.observability import timeline as tl
    from learningorchestra_tpu.observability import trace as tr

    with tr.span("job", trace="listing_job"):
        pass
    tl.record("listing_job", step=1, dt=0.1,
              examples_per_second=10.0)
    status, body, _ = api.dispatch(
        "GET", f"{API}/observability/trace", {}, None)
    assert status == 200 and "listing_job" in body["result"]
    status, body, _ = api.dispatch(
        "GET", f"{API}/observability/timeline", {}, None)
    assert status == 200 and "listing_job" in body["result"]


# ----------------------------------------------------------------------
# satellite: lo_build_info
# ----------------------------------------------------------------------

def test_build_info_gauge(api):
    from learningorchestra_tpu import __version__

    info = inc.build_info()
    assert info["version"] == __version__
    assert info["jaxVersion"] not in ("", None)
    status, text, _ = api.dispatch(
        "GET", "/metrics", {"format": "prometheus"}, None)
    line = [ln for ln in text.decode().splitlines()
            if ln.startswith("lo_build_info{")][0]
    for label in ("version=", "jax_version=", "backend=",
                  "device_kind="):
        assert label in line
    assert line.endswith("} 1")


# ----------------------------------------------------------------------
# satellite: event-log rotation vs the tail reader
# ----------------------------------------------------------------------

def test_event_log_tail_survives_concurrent_rotation(tmp_config):
    """Writers rolling the log to ``.1`` every few KB race a reader:
    the tail must always be complete JSONL lines — no torn line, no
    crash on the rollover instant (ISSUE 13 satellite)."""
    tmp_config.event_log = os.path.join(tmp_config.home, "ev.jsonl")
    tmp_config.event_log_max_bytes = 4096
    stop = threading.Event()
    failures = []

    def writer(wid):
        seq = 0
        while not stop.is_set():
            obs_export.log_event("race", f"w{wid}", seq=seq,
                                 pad="x" * 64)
            seq += 1

    def reader():
        while not stop.is_set():
            try:
                tail = obs_export.read_tail(8192)
                for line in tail.splitlines():
                    json.loads(line)
            except Exception as exc:  # noqa: BLE001
                failures.append(repr(exc))
                return

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(3)] + [threading.Thread(target=reader)]
    for t in threads:
        t.start()
    time.sleep(1.0)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert failures == []
    # rotation actually happened and the splice still reads whole
    # lines across it
    assert os.path.exists(tmp_config.event_log + ".1")
    tail = obs_export.read_tail(1 << 20)
    assert tail
    for line in tail.splitlines():
        json.loads(line)


def test_read_tail_off_and_missing(tmp_config):
    tmp_config.event_log = ""
    assert obs_export.read_tail() == ""
    tmp_config.event_log = os.path.join(tmp_config.home, "none.jsonl")
    assert obs_export.read_tail() == ""


# ----------------------------------------------------------------------
# postmortem tooling: scripts/incident_diff.py
# ----------------------------------------------------------------------

def _load_incident_diff():
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "incident_diff.py")
    spec = importlib.util.spec_from_file_location(
        "incident_diff", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_incident_diff_dirs_and_tars(tmp_config, tmp_path):
    diff_mod = _load_incident_diff()
    stats = {"jobLifecycle": {"retries": 0, "deadLettered": 0}}
    rec = inc.FlightRecorder(home=tmp_config.home,
                             stats_snapshot=lambda: dict(stats))
    try:
        id_a = rec.capture("manual")["id"]
        stats["jobLifecycle"] = {"retries": 3, "deadLettered": 1}
        tmp_config.monitor_ring = 999  # config drift between captures
        id_b = rec.capture("manual")["id"]
        root = os.path.join(tmp_config.home, "incidents")
        report = diff_mod.diff_bundles(os.path.join(root, id_a),
                                       os.path.join(root, id_b))
        deltas = {r["metric"]: r["delta"]
                  for r in report["metricDeltas"]}
        assert deltas["jobLifecycle.retries"] == 3
        assert deltas["jobLifecycle.deadLettered"] == 1
        drift = {r["key"]: (r["a"], r["b"])
                 for r in report["configDrift"]}
        assert drift["monitor_ring"][1] == 999
        assert report["buildDrift"] == []
        # same report from the REST download tar streams
        tar_a, tar_b = (tmp_path / "a.tar"), (tmp_path / "b.tar")
        tar_a.write_bytes(rec.tar_bytes(id_a))
        tar_b.write_bytes(rec.tar_bytes(id_b))
        report2 = diff_mod.diff_bundles(str(tar_a), str(tar_b))
        assert report2["metricDeltas"] == report["metricDeltas"]
    finally:
        rec.close()


def test_incident_diff_alert_changes(tmp_path):
    diff_mod = _load_incident_diff()

    def bundle(name, alerts):
        d = tmp_path / name
        d.mkdir()
        (d / "manifest.json").write_text(json.dumps(
            {"id": name, "trigger": "manual"}))
        (d / "alerts.json").write_text(json.dumps(
            {"alerts": alerts}))
        return str(d)

    a = bundle("a", [{"name": "servingP99", "state": "ok",
                      "value": 10.0}])
    b = bundle("b", [{"name": "servingP99", "state": "firing",
                      "value": 220.0}])
    report = diff_mod.diff_bundles(a, b)
    assert report["alertChanges"] == [
        {"alert": "servingP99", "stateA": "ok", "stateB": "firing",
         "valueA": 10.0, "valueB": 220.0}]
