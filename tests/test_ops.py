"""Flash attention kernel vs the full-softmax oracle.

Runs the real Pallas kernel in interpreter mode on the CPU backend
(same kernel source the TPU compiles), checking values AND gradients
against reference_attention.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learningorchestra_tpu.ops import flash_attention, reference_attention


def _rand(shape, key):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sq,sk", [(32, 32), (40, 56)])
def test_forward_matches_reference(causal, sq, sk):
    if causal and sq != sk:
        pytest.skip("causal oracle assumes square positions")
    b, h, d = 2, 3, 16
    q, k, v = (_rand((b, s, h, d), i)
               for i, s in enumerate((sq, sk, sk)))
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_reference(causal):
    b, s, h, d = 1, 24, 2, 8
    q, k, v = (_rand((b, s, h, d), 10 + i) for i in range(3))

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(reference_attention(q, k, v, causal=causal)))

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=5e-5, rtol=5e-4)


def test_jit_and_uneven_blocks():
    b, s, h, d = 2, 50, 2, 12  # nothing divides the block sizes
    q, k, v = (_rand((b, s, h, d), 20 + i) for i in range(3))
    f = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=16, block_k=16))
    out = f(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_bfloat16_path():
    b, s, h, d = 1, 32, 2, 16
    q, k, v = (_rand((b, s, h, d), 30 + i).astype(jnp.bfloat16)
               for i in range(3))
    out = flash_attention(q, k, v, causal=False, block_q=16, block_k=16)
    assert out.dtype == jnp.bfloat16
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-2, rtol=3e-2)
