"""Feature-plane cache (docs/PERFORMANCE.md): host-tier versioned
memoization, HBM arena LRU/pinning, change-feed invalidation, and the
acceptance property — a warm builder run on an unchanged dataset does
ZERO catalog reads and ZERO retraces."""

import threading

import numpy as np
import pandas as pd
import pytest

from learningorchestra_tpu.catalog.store import CollectionNotFound
from learningorchestra_tpu.runtime.arena import DeviceArena
from learningorchestra_tpu.services.feature_cache import FeatureCache


def _write(catalog, name, df):
    if not catalog.exists(name):
        catalog.create_collection(name, "dataset/csv", {})
    catalog.write_dataframe(name, df)
    catalog.mark_finished(name)


# ------------------------------------------------------------ host tier
def test_host_tier_hit_then_append_revalidates(catalog):
    fc = FeatureCache(catalog, arena=DeviceArena(0))
    _write(catalog, "ds", pd.DataFrame({"a": [1, 2]}))
    assert fc.dataframe("ds")["a"].tolist() == [1, 2]
    assert fc.dataframe("ds")["a"].tolist() == [1, 2]
    s = fc.stats()
    assert (s["hits"], s["misses"]) == (1, 1)
    # appends write parquet parts WITHOUT a change-feed entry; only
    # the dataset_version component of the key can catch them
    catalog.write_dataframe("ds", pd.DataFrame({"a": [3]}), replace=False)
    assert fc.dataframe("ds")["a"].tolist() == [1, 2, 3]
    s = fc.stats()
    assert s["misses"] == 2 and s["invalidations"] == 1
    # in-place replace (the dataType service rewrite) as well
    catalog.write_dataframe("ds", pd.DataFrame({"a": [9]}))
    assert fc.dataframe("ds")["a"].tolist() == [9]


def test_projection_and_dtype_key_separately(catalog):
    fc = FeatureCache(catalog, arena=DeviceArena(0))
    _write(catalog, "ds", pd.DataFrame({"a": [1.0, 2.0], "b": [3, 4]}))
    full = fc.dataframe("ds")
    proj = fc.dataframe("ds", columns=["a"])
    assert list(full.columns) == ["a", "b"]
    assert list(proj.columns) == ["a"]
    arrs = fc.arrays("ds", ["a", "b"], np.float32)
    assert arrs["a"].dtype == np.float32
    assert fc.stats()["entries"] == 3
    # each keyed independently -> repeat access hits
    fc.dataframe("ds", columns=["a"])
    fc.arrays("ds", ["a", "b"], np.float32)
    assert fc.stats()["hits"] == 2


def test_cached_frame_isolated_from_caller_mutation(catalog):
    fc = FeatureCache(catalog, arena=DeviceArena(0))
    _write(catalog, "ds", pd.DataFrame({"a": [1]}))
    df = fc.dataframe("ds")
    df["extra"] = 7  # whole-column add on the shallow copy
    again = fc.dataframe("ds")
    assert "extra" not in again.columns
    assert fc.stats()["hits"] == 1  # and it WAS served from cache


def test_delete_collection_sweeps_both_tiers(catalog):
    arena = DeviceArena(1 << 20)
    fc = FeatureCache(catalog, arena=arena)
    _write(catalog, "doomed", pd.DataFrame({"a": [1]}))
    _write(catalog, "other", pd.DataFrame({"b": [2]}))
    fc.dataframe("doomed")
    arena.get_or_put(fc.token("doomed"),
                     lambda: {"x": np.ones(8, np.float32)},
                     tags=("doomed",)).release()
    assert fc.stats()["entries"] == 1 and arena.stats()["entries"] == 1
    catalog.delete_collection("doomed")
    # the delete rides the change feed; the next access sweeps it out
    # of BOTH tiers so budget frees promptly
    fc.dataframe("other")
    assert fc.stats()["entries"] == 1  # only "other" remains
    assert arena.stats()["entries"] == 0
    assert arena.stats()["invalidations"] == 1


# ------------------------------------------------------------ HBM arena
def test_arena_lru_eviction_skips_pinned_readers():
    arena = DeviceArena(byte_budget=3000)

    def block():
        return {"x": np.ones(250, np.float32)}  # 1000 bytes

    pinned = arena.get_or_put("k1", block, tags=("c1",))  # stays pinned
    arena.get_or_put("k2", block, tags=("c2",)).release()
    arena.get_or_put("k3", block, tags=("c3",)).release()
    arena.get_or_put("k4", block, tags=("c4",)).release()  # over budget
    s = arena.stats()
    assert s["evictions"] == 1 and s["bytesInUse"] == 3000
    # k1 was the LRU victim candidate but is pinned -> k2 went instead
    arena.get_or_put(
        "k1", lambda: pytest.fail("pinned entry was evicted")).release()
    assert arena.stats()["hits"] == 1
    # the in-flight reader's arrays are intact throughout
    assert float(pinned.arrays["x"].sum()) == 250.0
    pinned.release()
    pinned.release()  # idempotent


def test_arena_all_pinned_degrades_to_no_eviction():
    arena = DeviceArena(byte_budget=1500)
    a = arena.get_or_put("a", lambda: {"x": np.ones(250, np.float32)})
    b = arena.get_or_put("b", lambda: {"x": np.ones(250, np.float32)})
    s = arena.stats()  # over budget but every entry is in use
    assert s["bytesInUse"] == 2000 and s["evictions"] == 0
    a.release(), b.release()
    arena.get_or_put("c", lambda: {"x": np.ones(250, np.float32)}).release()
    assert arena.stats()["bytesInUse"] <= 1500  # pins gone -> swept


def test_arena_invalidate_keeps_inflight_arrays():
    arena = DeviceArena(byte_budget=1 << 20)
    entry = arena.get_or_put(("k", 0), lambda: {"x": np.arange(10)},
                             tags=("ds",))
    assert arena.invalidate("ds") == 1
    assert arena.stats()["entries"] == 0
    # the reader mid-fit keeps its (now unlinked) arrays
    assert int(entry.arrays["x"].sum()) == 45
    entry.release()  # must not raise on the unlinked key
    rebuilt = arena.get_or_put(("k", 0), lambda: {"x": np.arange(10)})
    assert arena.stats()["misses"] == 2
    rebuilt.release()


def test_arena_zero_budget_disables_caching():
    arena = DeviceArena(byte_budget=0)
    e = arena.get_or_put("k", lambda: {"x": np.ones(4)})
    assert int(e.arrays["x"].sum()) == 4
    e.release()
    assert arena.stats()["entries"] == 0
    assert arena.get_or_put("k", lambda: {"x": np.ones(4)}).arrays is not None
    assert arena.stats()["hits"] == 0  # every access rebuilds


# ---------------------------------------------------- read-during-write
def test_concurrent_read_during_write_never_mixes(catalog):
    """Readers racing write_dataframe's staging-rename swap must see
    one coherent version — every row from the same write."""
    def frame(i):
        return pd.DataFrame({"v": [i] * 256, "w": [i] * 256})

    _write(catalog, "ds", frame(0))
    fc = FeatureCache(catalog, arena=DeviceArena(0))
    stop = threading.Event()
    bad = []

    def reader():
        while not stop.is_set():
            try:
                df = fc.dataframe("ds")
            except CollectionNotFound:
                continue  # transient mid-rename window
            vals = set(df["v"].tolist()) | set(df["w"].tolist())
            if len(vals) != 1:
                bad.append(sorted(vals))

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    for i in range(1, 40):
        catalog.write_dataframe("ds", frame(i))
    stop.set()
    for t in threads:
        t.join()
    assert not bad, f"mixed-version frames observed: {bad[:3]}"
    assert fc.dataframe("ds")["v"].iloc[0] == 39  # converges to newest


# ------------------------------------------------- warm builder pipeline
@pytest.fixture()
def ctx(tmp_config):
    from learningorchestra_tpu.services.context import ServiceContext
    c = ServiceContext(tmp_config)
    yield c
    c.close()


def _synth(n, seed, d=4):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x @ np.array([1.0, -2.0, 0.5, 1.5])[:d] > 0).astype(np.int64)
    return x, y


def _write_synth(catalog, name, n, seed):
    import pyarrow as pa

    x, y = _synth(n, seed)
    catalog.create_collection(name, "dataset/csv", {})
    with catalog.dataset_writer(name) as w:
        w.write_batch(pa.table({
            "f0": x[:, 0], "f1": x[:, 1], "f2": x[:, 2], "f3": x[:, 3],
            "label": y}))
    catalog.mark_finished(name)


MODELING = """
import numpy as np
feats = ["f0", "f1", "f2", "f3"]
features_training = (training_df[feats].to_numpy(np.float32),
                     training_df["label"].to_numpy())
features_testing = testing_df[feats].to_numpy(np.float32)
features_evaluation = (testing_df[feats].to_numpy(np.float32),
                       testing_df["label"].to_numpy())
"""


def test_warm_builder_run_zero_reads_zero_retraces(ctx, monkeypatch):
    """ISSUE acceptance: the second builder run on an unchanged
    dataset must touch neither the catalog (zero read_dataframe) nor
    the tracer (zero executable-cache misses), then a mutation must be
    observed by the very next run."""
    from learningorchestra_tpu.runtime import engine as engine_lib
    from learningorchestra_tpu.services.builder_service import BuilderService

    _write_synth(ctx.catalog, "fcb_train", 2048, seed=1)
    _write_synth(ctx.catalog, "fcb_test", 512, seed=2)
    svc = BuilderService(ctx)
    body = {"trainDatasetName": "fcb_train", "testDatasetName": "fcb_test",
            "evaluationDatasetName": "fcb_test", "modelingCode": MODELING,
            "classifiersList": ["LR", "NB"], "meshParallel": True}

    status, _ = svc.create(dict(body))
    assert status == 201
    ctx.jobs.wait("fcb_testLR", timeout=600)

    calls = []
    orig = ctx.catalog.read_dataframe

    def counted(*a, **k):
        calls.append(a)
        return orig(*a, **k)

    monkeypatch.setattr(ctx.catalog, "read_dataframe", counted)
    fc0, ex0 = ctx.features.stats(), engine_lib.executable_cache_stats()

    status, _ = svc.create(dict(body))
    assert status == 201
    ctx.jobs.wait("fcb_testLR", timeout=600)

    fc1, ex1 = ctx.features.stats(), engine_lib.executable_cache_stats()
    assert calls == [], f"warm run hit the catalog: {calls}"
    assert fc1["hits"] - fc0["hits"] >= 2  # train + test served warm
    assert fc1["misses"] == fc0["misses"]
    assert ex1["misses"] == ex0["misses"], "warm run retraced"
    assert ex1["hits"] > ex0["hits"]
    for c in ("LR", "NB"):
        meta = ctx.catalog.get_metadata(f"fcb_test{c}")
        assert meta["finished"] is True and meta["engine"] == "jax", meta
        assert meta["accuracy"] > 0.9, meta

    # staleness: append rows -> the NEXT run must re-read and re-stage
    x, y = _synth(256, seed=3)
    ctx.catalog.write_dataframe("fcb_train", pd.DataFrame({
        "f0": x[:, 0], "f1": x[:, 1], "f2": x[:, 2], "f3": x[:, 3],
        "label": y}), replace=False)
    status, _ = svc.create(dict(body))
    assert status == 201
    ctx.jobs.wait("fcb_testLR", timeout=600)
    assert any(a[0] == "fcb_train" for a in calls), \
        "mutated dataset was served stale"
    fc2 = ctx.features.stats()
    assert fc2["misses"] > fc1["misses"]
    meta = ctx.catalog.get_metadata("fcb_testLR")
    assert meta["finished"] is True and meta["accuracy"] > 0.9, meta
