"""Importable helper for artifact native-protocol tests (must live in a
real module so ArtifactStore can re-import it by path)."""

import json
import os


class NativeThing:
    def __init__(self, value):
        self.value = value

    def __lo_save__(self, path):
        with open(os.path.join(path, "v.json"), "w") as f:
            json.dump({"value": self.value}, f)

    @classmethod
    def __lo_load__(cls, path):
        with open(os.path.join(path, "v.json")) as f:
            return cls(json.load(f)["value"])
