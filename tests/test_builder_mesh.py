"""Mesh-parallel Builder (SURVEY §7: 'N models trained as parallel
jobs over mesh slices'; reference trains 5 classifiers concurrently on
a 3-executor Spark cluster, builder_image/builder.py:62-78).

``meshParallel: true`` hands each JAX-native family (LR, NB) a
disjoint device sub-slice (runtime/mesh.sub_meshes) while the tree
families keep host sklearn threads.
"""
import numpy as np
import pytest

from learningorchestra_tpu.models.estimators import (
    GaussianNBJAX,
    LogisticRegressionJAX,
)
from learningorchestra_tpu.runtime import mesh as mesh_lib
from learningorchestra_tpu.services.builder_service import BuilderService
from learningorchestra_tpu.services.context import ServiceContext
from learningorchestra_tpu.services.validators import HttpError


def _synth(n, seed, d=4):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x @ np.array([1.0, -2.0, 0.5, 1.5])[:d] > 0).astype(np.int64)
    return x, y


# ---------------------------------------------------------------- unit
def test_logreg_jax_learns_separable():
    x, y = _synth(4096, seed=0)
    clf = LogisticRegressionJAX(epochs=8, batch_size=512)
    clf.fit(x, y)
    xt, yt = _synth(1024, seed=1)
    assert clf.score(xt, yt) > 0.95
    probs = clf.predict_proba(xt)
    assert probs.shape == (1024, 2)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)


def test_logreg_jax_on_sub_mesh():
    from learningorchestra_tpu.runtime.mesh import sub_meshes

    slices = sub_meshes(mesh_lib.get_default_mesh(), 2)
    assert len(slices) == 2 and slices[0].size >= 2
    x, y = _synth(2048, seed=2)
    clf = LogisticRegressionJAX(epochs=6, batch_size=256)
    clf.set_mesh(slices[1])  # a non-default disjoint slice
    clf.fit(x, y)
    assert clf.score(*_synth(512, seed=3)) > 0.9


def test_gaussian_nb_jax_matches_sklearn():
    from sklearn.naive_bayes import GaussianNB

    x, y = _synth(2048, seed=4)
    ours = GaussianNBJAX().fit(x, y)
    ref = GaussianNB().fit(x, y)
    xt, _ = _synth(512, seed=5)
    agree = np.mean(ours.predict(xt) == ref.predict(xt))
    assert agree > 0.99
    np.testing.assert_allclose(ours.theta_, ref.theta_, atol=1e-4)


def test_gaussian_nb_jax_large_mean_features():
    """E[x^2]-mean^2 on raw f32 data cancels catastrophically when
    |mean| >> std (timestamps, unscaled sensors); the global-mean
    centering must keep variances and predictions sklearn-accurate."""
    from sklearn.naive_bayes import GaussianNB

    rng = np.random.default_rng(11)
    x = rng.normal(size=(4096, 3)).astype(np.float64)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.int64)
    x = x + np.array([1e4, 5e4, 1e5])  # huge means, unit stds
    ours = GaussianNBJAX().fit(x, y)
    ref = GaussianNB().fit(x, y)
    np.testing.assert_allclose(ours.var_, ref.var_, rtol=5e-2)
    xt = rng.normal(size=(512, 3)) + np.array([1e4, 5e4, 1e5])
    assert np.mean(ours.predict(xt) == ref.predict(xt)) > 0.99


def test_gaussian_nb_jax_sharded_matches_unsharded():
    """The dp-sharded sufficient-stats pass (with zero-padded rows)
    must give the same model as the unsharded one — rows don't divide
    the slice evenly on purpose."""
    from learningorchestra_tpu.runtime.mesh import sub_meshes

    x, y = _synth(1000, seed=6)  # 1000 % 4 != 0
    plain = GaussianNBJAX().fit(x, y)
    sharded = GaussianNBJAX()
    sharded.set_mesh(sub_meshes(mesh_lib.get_default_mesh(), 2)[0])
    sharded.fit(x, y)
    np.testing.assert_allclose(sharded.theta_, plain.theta_, atol=1e-5)
    np.testing.assert_allclose(sharded.var_, plain.var_, atol=1e-5)
    np.testing.assert_allclose(sharded.class_prior_, plain.class_prior_,
                               atol=1e-7)


# ------------------------------------------------------------- service
@pytest.fixture()
def ctx(tmp_config):
    c = ServiceContext(tmp_config)
    yield c
    c.close()


def _write_df(catalog, name, n, seed):
    import pyarrow as pa

    x, y = _synth(n, seed)
    catalog.create_collection(name, "dataset/csv", {})
    with catalog.dataset_writer(name) as w:
        w.write_batch(pa.table({
            "f0": x[:, 0], "f1": x[:, 1], "f2": x[:, 2], "f3": x[:, 3],
            "label": y}))
    catalog.mark_finished(name)


MODELING = """
import numpy as np
feats = ["f0", "f1", "f2", "f3"]
features_training = (training_df[feats].to_numpy(np.float32),
                     training_df["label"].to_numpy())
features_testing = testing_df[feats].to_numpy(np.float32)
features_evaluation = (testing_df[feats].to_numpy(np.float32),
                       testing_df["label"].to_numpy())
"""


def test_mesh_parallel_builder_pipeline(ctx):
    _write_df(ctx.catalog, "mp_train", 4096, seed=7)
    _write_df(ctx.catalog, "mp_test", 1024, seed=8)
    svc = BuilderService(ctx)
    status, body = svc.create({
        "trainDatasetName": "mp_train", "testDatasetName": "mp_test",
        "evaluationDatasetName": "mp_test",
        "modelingCode": MODELING,
        "classifiersList": ["LR", "NB", "DT"],
        "meshParallel": True})
    assert status == 201
    ctx.jobs.wait("mp_testLR", timeout=600)
    for c, engine in (("LR", "jax"), ("NB", "jax"), ("DT", "sklearn")):
        meta = ctx.catalog.get_metadata(f"mp_test{c}")
        assert meta["finished"] is True, meta
        assert meta["engine"] == engine, (c, meta)
        assert meta["accuracy"] > 0.9, (c, meta)
        assert ctx.catalog.count_rows(f"mp_test{c}") == 1024
    # the two JAX families each got a DISJOINT sub-slice of the
    # 8-device test mesh (4 devices each)
    for c in ("LR", "NB"):
        meta = ctx.catalog.get_metadata(f"mp_test{c}")
        assert meta["meshDevices"] == 4, meta
    # the mesh job went through the builder fair-scheduling pool
    assert "builder" in ctx.jobs.mesh_served()


def test_mesh_parallel_rejects_streaming(ctx):
    _write_df(ctx.catalog, "x_train", 64, seed=9)
    _write_df(ctx.catalog, "x_test", 64, seed=10)
    svc = BuilderService(ctx)
    with pytest.raises(HttpError, match="exclusive"):
        svc.create({
            "trainDatasetName": "x_train", "testDatasetName": "x_test",
            "classifiersList": ["LR"],
            "streaming": True, "meshParallel": True})
