"""Checkpointing.

The reference has NO mid-training checkpointing — persistence is the
final artifact only, and a failed job is simply re-run from its stored
parent (SURVEY §5: binary_executor utils.py:195-208, server.py:74-118).
Here training jobs checkpoint per-epoch/step via Orbax on TPU and can
resume, and pytree artifacts are serialized with msgpack
(flax.serialization) instead of pickles.

Off-TPU the step checkpoints use the same msgpack serialization
instead of Orbax: on this jaxlib, tensorstore reads (Orbax restore)
and XLA:CPU executables deserialized from jax's persistent
compilation cache corrupt the glibc heap when they share a process
("corrupted double-linked list" / SIGSEGV in the next jitted step),
and once the cache is warm no amount of disabling-at-restore helps —
the poisoned executable has already run during fit. Keeping
tensorstore out of CPU processes entirely removes the conflict while
the compilation cache stays on.

Integrity (docs/RELIABILITY.md): each msgpack step dir carries a
``manifest.json`` (per-file byte size + sha256, step, wall time) and
is committed ATOMICALLY — payload and manifest are written and
fsynced into ``<step>.tmp/`` which one ``os.replace`` renames into
place, so a kill mid-save can never leave a half-written step that
``latest_step()`` would pick (leftover ``*.tmp`` dirs are swept on
init). ``restore()`` re-hashes the payload against the manifest;
a torn or bit-flipped step dir is moved to ``<dir>/.quarantine/``
(bounded to the ``LO_CKPT_QUARANTINE_KEEP`` newest entries) and
restore transparently falls back to the newest VERIFIED step.
Orbax (TPU) keeps its own atomic-commit + metadata machinery.

Layout (``shards > 1``): the state dict is partitioned into N
byte-balanced sub-files (``shard-00000-of-00002.msgpack``, …) under
one merged manifest, so each mesh-slice shard can be written by its
owning host on a multi-host pod; every sub-file verifies
independently and restore merges them. ``shards == 1`` keeps the
single ``checkpoint.msgpack`` layout, byte-compatible with older
dirs.

The commit machinery is split so the async manager
(``runtime/async_ckpt.py``) can reuse it off the training thread:
``save()`` = device→host + ``_commit_host()``; the async worker
calls ``_commit_host()`` directly on an already-host-resident tree.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
import warnings
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from learningorchestra_tpu.runtime import health as health_lib

_MSGPACK_NAME = "checkpoint.msgpack"
_MANIFEST_NAME = "manifest.json"
_QUARANTINE_DIR = ".quarantine"
_SHARD_PREFIX = "shard-"


def _quarantine_keep() -> int:
    """How many quarantined step dirs to retain (newest wins).
    Config-first so tests overriding Config see it; env fallback keeps
    the runtime layer importable standalone."""
    try:
        from learningorchestra_tpu.config import get_config

        return max(0, int(get_config().ckpt_quarantine_keep))
    except Exception:  # noqa: BLE001
        return max(0, int(os.environ.get(
            "LO_CKPT_QUARANTINE_KEEP", "4") or 4))


def _flatten_state(tree: Any, prefix: str = "") -> dict:
    """Flatten a nested state dict to ``{"a/b/c": leaf}``. Empty dict
    nodes survive as leaves (``from_state_dict`` requires every target
    key present, including ``model_state: {}``)."""
    if isinstance(tree, dict) and tree:
        out: dict = {}
        for key in tree:
            joined = f"{prefix}/{key}" if prefix else str(key)
            out.update(_flatten_state(tree[key], joined))
        return out
    return {prefix: tree}


def _unflatten_state(flat: dict) -> dict:
    out: dict = {}
    for key, value in flat.items():
        parts = key.split("/")
        node = out
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return out


def _leaf_nbytes(leaf: Any) -> int:
    try:
        return max(1, int(np.asarray(leaf).nbytes))
    except Exception:  # noqa: BLE001 — non-array leaf (e.g. {} node)
        return 1


class CheckpointCorrupted(IOError):
    """A step dir failed manifest verification (missing payload, size
    mismatch, sha256 mismatch, unreadable manifest). IOError subclass:
    if one ever escapes the fallback (explicit-step restore), the jobs
    layer classifies it transient."""


def _use_orbax() -> bool:
    return jax.default_backend() == "tpu"


def _fsync_file(f) -> None:
    f.flush()
    os.fsync(f.fileno())


def _fsync_dir(path: str) -> None:
    # the rename itself must reach disk or a crash can forget a
    # committed step (POSIX: fsync the parent directory)
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platforms without directory fds
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _chaos_corrupt(path: str) -> None:
    """``ckpt_write:*:corrupt:<nbytes>`` chaos site: flip bytes of the
    just-written payload AFTER its checksum was taken — simulated bit
    rot that restore-side verification must catch. Lazy import: the
    runtime layer only touches services.faults when armed chaos specs
    are plausible, and never lets injection plumbing sink a save."""
    try:
        from learningorchestra_tpu.services import faults

        nbytes = faults.corrupt_nbytes("ckpt_write")
    except Exception:  # noqa: BLE001
        return
    if not nbytes:
        return
    size = os.path.getsize(path)
    nbytes = min(nbytes, size)
    with open(path, "r+b") as f:
        f.seek(size - nbytes)
        chunk = f.read(nbytes)
        f.seek(size - nbytes)
        f.write(bytes(b ^ 0xFF for b in chunk))
        _fsync_file(f)


def _place_like(restored: Any, target: Any) -> Any:
    """Put restored host leaves back onto the target's shardings."""

    def _place(leaf, tgt):
        if isinstance(tgt, jax.Array):
            return jax.device_put(
                jnp.asarray(leaf, tgt.dtype), tgt.sharding)
        return leaf

    return jax.tree_util.tree_map(_place, restored, target)


class _NullAsyncManager:
    """Orbax-shaped facade for the msgpack backend: saves are
    synchronous, so finishing/closing are no-ops."""

    def wait_until_finished(self) -> None:
        pass

    def close(self) -> None:
        pass


class Checkpointer:
    """save(step, pytree) / latest_step() / restore — Orbax on TPU,
    msgpack files off-TPU (same directory-per-step layout)."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 shards: int = 1):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._max_to_keep = max_to_keep
        # sub-files per step commit (multi-host: one per mesh-slice
        # shard, i.e. shards=jax.process_count()); 1 = legacy layout
        self._shards = max(1, int(shards))
        if _use_orbax():
            import orbax.checkpoint as ocp

            self._mgr = ocp.CheckpointManager(
                self._dir,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, create=True),
            )
        else:
            self._mgr = _NullAsyncManager()
            # a kill mid-save leaves a <step>.tmp dir that was never
            # committed — it holds no verified state, sweep it
            for name in os.listdir(self._dir):
                if name.endswith(".tmp"):
                    shutil.rmtree(os.path.join(self._dir, name),
                                  ignore_errors=True)

    # -- msgpack layout helpers ----------------------------------------
    def _step_dirs(self) -> List[int]:
        steps = []
        for name in os.listdir(self._dir):
            if not name.isdigit():
                continue
            # sharded steps have no checkpoint.msgpack — the manifest
            # is the commit marker either way (legacy dirs keep the
            # payload-only check)
            step_dir = os.path.join(self._dir, name)
            if os.path.exists(os.path.join(step_dir, _MSGPACK_NAME)) \
                    or os.path.exists(
                        os.path.join(step_dir, _MANIFEST_NAME)):
                steps.append(int(name))
        return sorted(steps)

    def _step_path(self, step: int) -> str:
        return os.path.join(self._dir, str(step), _MSGPACK_NAME)

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self._dir, str(step), _MANIFEST_NAME)

    def _load_manifest(self, step: int) -> Optional[dict]:
        """The step's manifest dict, None for a legacy (pre-manifest)
        dir, CheckpointCorrupted for an unreadable/malformed one."""
        path = self._manifest_path(step)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as exc:
            raise CheckpointCorrupted(
                f"step {step}: unreadable manifest: {exc}") from exc
        if not isinstance(manifest, dict) or \
                not isinstance(manifest.get("files"), dict):
            raise CheckpointCorrupted(
                f"step {step}: malformed manifest (no files map)")
        return manifest

    def _verify_sizes(self, step: int) -> None:
        """Cheap (stat-only) verification against the manifest; legacy
        dirs with a payload pass. Raises CheckpointCorrupted."""
        manifest = self._load_manifest(step)
        if manifest is None:
            if not os.path.exists(self._step_path(step)):
                raise CheckpointCorrupted(f"step {step}: missing payload")
            return
        for name, meta in manifest["files"].items():
            path = os.path.join(self._dir, str(step), name)
            if not os.path.exists(path):
                raise CheckpointCorrupted(
                    f"step {step}: manifest names missing file {name!r}")
            size = os.path.getsize(path)
            if size != meta.get("bytes"):
                raise CheckpointCorrupted(
                    f"step {step}: {name} is {size} bytes, manifest "
                    f"says {meta.get('bytes')} (torn write?)")

    def _read_file_verified(self, step: int, name: str,
                            meta: dict) -> bytes:
        """One payload file's bytes, re-hashed against its manifest
        entry. Raises CheckpointCorrupted on any mismatch."""
        try:
            with open(os.path.join(self._dir, str(step), name),
                      "rb") as f:
                data = f.read()
        except OSError as exc:
            raise CheckpointCorrupted(
                f"step {step}: unreadable payload {name!r}: "
                f"{exc}") from exc
        if len(data) != meta.get("bytes"):
            raise CheckpointCorrupted(
                f"step {step}: {name} is {len(data)} bytes, "
                f"manifest says {meta.get('bytes')} (torn write?)")
        digest = hashlib.sha256(data).hexdigest()
        if digest != meta.get("sha256"):
            raise CheckpointCorrupted(
                f"step {step}: {name} sha256 {digest[:12]}… does "
                f"not match manifest {str(meta.get('sha256'))[:12]}… "
                f"(bit rot?)")
        return data

    def _read_verified_tree(self, step: int) -> Any:
        """The step's raw (nested) state dict, every manifest-listed
        sub-file re-hashed — the single- and sharded-layout read path.
        Raises CheckpointCorrupted; a legacy dir with no manifest is
        accepted as-is."""
        manifest = self._load_manifest(step)
        if manifest is None:
            try:
                with open(self._step_path(step), "rb") as f:
                    data = f.read()
            except OSError as exc:
                raise CheckpointCorrupted(
                    f"step {step}: unreadable payload: {exc}") from exc
            return serialization.msgpack_restore(data)
        shard_names = sorted(n for n in manifest["files"]
                             if n.startswith(_SHARD_PREFIX))
        try:
            if not shard_names:
                data = self._read_file_verified(
                    step, _MSGPACK_NAME,
                    manifest["files"].get(_MSGPACK_NAME, {}))
                return serialization.msgpack_restore(data)
            flat: dict = {}
            for name in shard_names:
                data = self._read_file_verified(
                    step, name, manifest["files"][name])
                part = serialization.msgpack_restore(data)
                if not isinstance(part, dict):
                    raise CheckpointCorrupted(
                        f"step {step}: {name} is not a shard map")
                flat.update(part)
            return _unflatten_state(flat)
        except CheckpointCorrupted:
            raise
        except Exception as exc:  # noqa: BLE001 — undecodable bytes
            raise CheckpointCorrupted(
                f"step {step}: undecodable payload: {exc}") from exc

    def _quarantine(self, step: int, reason: str) -> None:
        """Move a corrupt step dir aside (evidence over deletion) so
        latest_step()/restore() stop seeing it. The quarantine itself
        is BOUNDED — only the newest ``LO_CKPT_QUARANTINE_KEEP``
        entries survive, so repeated corruption under chaos cannot
        fill the disk."""
        src = os.path.join(self._dir, str(step))
        qdir = os.path.join(self._dir, _QUARANTINE_DIR)
        os.makedirs(qdir, exist_ok=True)
        dst = os.path.join(qdir, f"{step}-{int(time.time() * 1000)}")
        while os.path.exists(dst):
            dst += "x"
        try:
            os.replace(src, dst)
        except OSError:
            shutil.rmtree(src, ignore_errors=True)
        self._prune_quarantine(qdir)
        health_lib.record("quarantined")
        warnings.warn(
            f"quarantined checkpoint step {step} -> {dst}: {reason}",
            RuntimeWarning, stacklevel=3)

    @staticmethod
    def _prune_quarantine(qdir: str) -> None:
        keep = _quarantine_keep()
        try:
            entries = sorted(
                os.listdir(qdir),
                key=lambda n: os.path.getmtime(os.path.join(qdir, n)))
        except OSError:
            return
        for name in entries[:max(0, len(entries) - keep)]:
            shutil.rmtree(os.path.join(qdir, name), ignore_errors=True)

    def save(self, step: int, tree: Any) -> None:
        """Commit ``step`` (atomic; see module docstring). The commit
        wall clock — the training thread's checkpoint stall — is
        recorded as a ``checkpointCommit`` span on the current job
        trace and in the ``lo_checkpoint_commit_seconds`` histogram."""
        t0 = time.monotonic()
        try:
            self._save_impl(step, tree)
        finally:
            self._observe_commit(step, t0)

    @staticmethod
    def _observe_commit(step: int, t0: float) -> None:
        # lazy import, like _chaos_corrupt: the runtime layer must
        # stay importable without the services package
        try:
            from learningorchestra_tpu.observability import hist
            from learningorchestra_tpu.observability import trace

            end = time.monotonic()
            cur = trace.current()
            if cur is not None:
                trace.add("checkpointCommit", cur[0], t0, end,
                          parent=cur[1], step=int(step))
            hist.observe("lo_checkpoint_commit_seconds", end - t0)
        except Exception:  # noqa: BLE001 — observability is advisory
            pass

    def _save_impl(self, step: int, tree: Any) -> None:
        if _use_orbax():
            import orbax.checkpoint as ocp

            self._mgr.save(step, args=ocp.args.StandardSave(tree))
            return
        host = jax.tree_util.tree_map(np.asarray, tree)
        self._commit_host(step, host)

    def _shard_payloads(self, host: Any) -> dict:
        """``{file_name: payload_bytes}`` for one commit: a single
        msgpack blob, or N byte-balanced shard sub-files (greedy
        least-loaded bin packing over the flattened leaves, sorted by
        size then path — deterministic)."""
        state = serialization.to_state_dict(host)
        if self._shards <= 1 or not isinstance(state, dict) or not state:
            return {_MSGPACK_NAME: serialization.to_bytes(host)}
        flat = _flatten_state(state)
        n = min(self._shards, len(flat))
        bins: List[dict] = [{} for _ in range(n)]
        loads = [0] * n
        order = sorted(flat, key=lambda k: (-_leaf_nbytes(flat[k]), k))
        for key in order:
            i = loads.index(min(loads))
            bins[i][key] = flat[key]
            loads[i] += _leaf_nbytes(flat[key])
        return {
            f"{_SHARD_PREFIX}{i:05d}-of-{n:05d}.msgpack":
                serialization.msgpack_serialize(bins[i])
            for i in range(n)}

    def _commit_host(self, step: int, host: Any) -> None:
        """Atomically commit an already-host-resident pytree: stage
        the whole step dir, fsync contents, then one rename — a crash
        at any point leaves either the previous state or a .tmp dir
        the next init sweeps. This is the piece the async manager's
        background worker shares with the synchronous save path."""
        payloads = self._shard_payloads(host)
        final_dir = os.path.join(self._dir, str(step))
        tmp_dir = final_dir + ".tmp"
        shutil.rmtree(tmp_dir, ignore_errors=True)
        os.makedirs(tmp_dir)
        files = {}
        first_payload = None
        for name, data in payloads.items():
            path = os.path.join(tmp_dir, name)
            if first_payload is None:
                first_payload = path
            with open(path, "wb") as f:
                f.write(data)
                _fsync_file(f)
            files[name] = {"sha256": hashlib.sha256(data).hexdigest(),
                           "bytes": len(data)}
        manifest = {
            "step": int(step),
            "wallTime": time.time(),
            "files": files,
        }
        if first_payload is not None:
            _chaos_corrupt(first_payload)
        with open(os.path.join(tmp_dir, _MANIFEST_NAME), "w") as f:
            json.dump(manifest, f)
            _fsync_file(f)
        if os.path.exists(final_dir):
            shutil.rmtree(final_dir, ignore_errors=True)
        os.replace(tmp_dir, final_dir)
        _fsync_dir(self._dir)
        for old in self._step_dirs()[:-self._max_to_keep]:
            shutil.rmtree(os.path.join(self._dir, str(old)),
                          ignore_errors=True)

    def latest_step(self) -> Optional[int]:
        """Newest step passing cheap (size) verification. Steps failing
        it are skipped — not quarantined; only restore(), which does the
        full re-hash, moves dirs aside."""
        if _use_orbax():
            return self._mgr.latest_step()
        for step in reversed(self._step_dirs()):
            try:
                self._verify_sizes(step)
            except CheckpointCorrupted:
                continue
            return step
        return None

    def restore(self, target: Any, step: Optional[int] = None) -> Any:
        if _use_orbax():
            if step is None:
                step = self._mgr.latest_step()
            if step is None:
                return None
            import orbax.checkpoint as ocp

            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(target))
        if step is not None:
            try:
                raw = self._read_verified_tree(step)
            except CheckpointCorrupted as exc:
                # an explicitly requested step has no substitute
                self._quarantine(step, str(exc))
                raise
            return self._decode(raw, target)
        # newest VERIFIED step: quarantine corrupt/torn dirs and fall
        # back until one passes (or none are left -> fresh start)
        while True:
            candidates = self._step_dirs()
            if not candidates:
                return None
            step = candidates[-1]
            try:
                raw = self._read_verified_tree(step)
            except CheckpointCorrupted as exc:
                self._quarantine(step, str(exc))
                continue
            return self._decode(raw, target)

    def _decode(self, raw: Any, target: Any) -> Any:
        host_target = jax.tree_util.tree_map(np.asarray, target)
        # raises ValueError on structural drift (missing/extra keys) —
        # same contract the engine's migration fallback keys off
        restored = serialization.from_state_dict(host_target, raw)
        for got, want in zip(jax.tree_util.tree_leaves(restored),
                             jax.tree_util.tree_leaves(host_target)):
            if np.shape(got) != np.shape(want):
                raise ValueError(
                    f"checkpoint leaf shape {np.shape(got)} does not "
                    f"match target shape {np.shape(want)}")
        return _place_like(restored, target)

    def saved_metadata(self, step: Optional[int] = None) -> Any:
        """The SAVED tree's structure as a pytree whose leaves carry
        shape/dtype — the layout-drift discriminator: comparing it
        structurally against the live state beats sniffing a restore
        error message, which rewords across releases."""
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        if _use_orbax():
            meta = self._mgr.item_metadata(step)
            return getattr(meta, "tree", meta)
        # raw nested state dict; numpy leaves expose .shape/.dtype
        return self._read_verified_tree(step)

    def restore_partial(self, target_subtree: Any,
                        step: Optional[int] = None) -> Any:
        """Restore only the subtrees named in ``target_subtree`` (e.g.
        params + step, skipping a drifted opt_state entirely, so the
        stale optimizer arrays are never grafted into the new state).
        Reads are VERIFIED like ``restore()``: a corrupt step is
        quarantined; with ``step=None`` the read falls back to the
        next-newest verified step, an explicit step raises."""
        if _use_orbax():
            if step is None:
                step = self.latest_step()
            if step is None:
                return None
            return self._restore_partial_orbax(target_subtree, step)
        while True:
            explicit = step is not None
            if not explicit:
                step = self.latest_step()
            if step is None:
                return None
            try:
                raw = self._read_verified_tree(step)
            except CheckpointCorrupted as exc:
                self._quarantine(step, str(exc))
                if explicit:
                    raise
                step = None
                continue
            break
        if not isinstance(raw, dict):
            return None
        out = {}
        for key, sub_target in target_subtree.items():
            if key not in raw:
                return None
            out[key] = serialization.from_state_dict(sub_target, raw[key])
        return out

    def _restore_partial_orbax(self, target_subtree: Any,
                               step: int) -> Any:
        """Uses a fresh read-only manager: the instance manager's
        handler registry is pinned to StandardRestore by the failed
        full restore that precedes a migration."""
        import orbax.checkpoint as ocp

        mgr = ocp.CheckpointManager(self._dir)
        try:
            # newer orbax spells partial restore `partial_restore=True`;
            # 0.7.x uses the empty-transforms idiom (keys absent from
            # ``item`` are skipped, present ones restore 1:1 — which
            # requires explicit per-leaf restore_args)
            try:
                return mgr.restore(step, args=ocp.args.PyTreeRestore(
                    item=target_subtree, partial_restore=True))
            except TypeError:
                restore_args = jax.tree_util.tree_map(
                    lambda _: ocp.RestoreArgs(), target_subtree)
                return mgr.restore(step, args=ocp.args.PyTreeRestore(
                    item=target_subtree, restore_args=restore_args,
                    transforms={}))
        finally:
            mgr.close()

    # -- sidecar progress metadata ------------------------------------
    # Epoch progress can't be reconstructed from the restored step when
    # a re-run reshapes the feed (different batch_size / data size), so
    # the engine records it here next to the step checkpoints.
    def save_meta(self, meta: dict) -> None:
        # atomic like a step commit (tmp + fsync + replace + parent
        # fsync): a crash mid-write must never leave a torn sidecar
        # that poisons resume
        path = os.path.join(self._dir, "progress.json")
        with open(path + ".tmp", "w") as f:
            json.dump(meta, f)
            _fsync_file(f)
        os.replace(path + ".tmp", path)
        _fsync_dir(self._dir)

    def load_meta(self) -> Optional[dict]:
        path = os.path.join(self._dir, "progress.json")
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            # a torn sidecar must not poison the restore path — step
            # checkpoints carry the real state; progress is best-effort
            return None
        return meta if isinstance(meta, dict) else None

    def wait_until_finished(self, reraise: bool = True) -> None:
        """Barrier for in-flight commits. The synchronous backend has
        none (msgpack saves return committed; Orbax's manager drains
        itself) — this exists so callers can treat sync and async
        checkpointers uniformly (runtime/async_ckpt.py)."""
        del reraise
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()


# ----------------------------------------------------------------------
# msgpack pytree IO for artifact persistence (no pickle of jax arrays)
# ----------------------------------------------------------------------
def save_pytree(tree: Any, path: str) -> None:
    host_tree = jax.tree_util.tree_map(np.asarray, tree)
    with open(path, "wb") as f:
        f.write(serialization.to_bytes(host_tree))


def load_pytree(path: str, target: Any) -> Any:
    with open(path, "rb") as f:
        data = f.read()
    return serialization.from_bytes(target, data)
