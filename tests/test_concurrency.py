"""Concurrency static pass + runtime lock witness (ISSUE 16).

Static half (analysis/concurrency.py): seeded AB/BA deadlock fixtures
the pass must flag, blocking-under-lock and callback-under-lock
fixtures, waiver syntax, and the zero-error invariant over the real
package. Runtime half (runtime/locks.py): witness violation raise /
count modes, RLock re-entrancy, condition-wait rank release, and an
end-to-end subprocess run of real control-plane flows with
``LO_LOCK_WITNESS=1`` asserting zero violations.
"""

import pathlib
import subprocess
import sys
import threading

import pytest

from learningorchestra_tpu.analysis import concurrency
from learningorchestra_tpu.analysis.findings import SEVERITY_ERROR
from learningorchestra_tpu.runtime import locks

REPO = pathlib.Path(__file__).resolve().parent.parent

# A small fixture hierarchy: a outermost, d innermost.
H = {"fix.a": 10, "fix.b": 20, "fix.c": 30, "fix.d": 40}


def _errors(findings, rule=None):
    return [f for f in findings if f.severity == SEVERITY_ERROR
            and (rule is None or f.rule == rule)]


# ----------------------------------------------------------------------
# static pass: lock-order / cycles
# ----------------------------------------------------------------------

def test_static_flags_seeded_ab_ba_deadlock():
    # classic AB/BA: thread_one nests a->b, thread_two nests b->a.
    # Whatever the declared ranks, one of the two is a rank inversion
    # and the pair is a cycle.
    src = (
        "from learningorchestra_tpu.runtime import locks\n"
        "LA = locks.make_lock('fix.a')\n"
        "LB = locks.make_lock('fix.b')\n"
        "def thread_one():\n"
        "    with LA:\n"
        "        with LB:\n"
        "            pass\n"
        "def thread_two():\n"
        "    with LB:\n"
        "        with LA:\n"
        "            pass\n"
    )
    findings = concurrency.analyze_source(src, "fix", "fix.py",
                                          hierarchy=H)
    order = _errors(findings, concurrency.RULE_ORDER)
    assert order, findings
    # the BA side (b outer, a inner) is the inversion: rank(a) < rank(b)
    assert any("fix.a" in f.message and "fix.b" in f.message
               for f in order)


def test_static_flags_cross_function_cycle():
    # the nesting is split across a call edge: f holds a and calls g,
    # which takes b; h holds b and calls k, which takes a. No single
    # function nests both orders — only the interprocedural closure
    # sees the cycle.
    src = (
        "from learningorchestra_tpu.runtime import locks\n"
        "LA = locks.make_lock('fix.a')\n"
        "LB = locks.make_lock('fix.b')\n"
        "def g():\n"
        "    with LB:\n"
        "        pass\n"
        "def f():\n"
        "    with LA:\n"
        "        g()\n"
        "def k():\n"
        "    with LA:\n"
        "        pass\n"
        "def h():\n"
        "    with LB:\n"
        "        k()\n"
    )
    findings = concurrency.analyze_source(src, "fix", "fix.py",
                                          hierarchy=H)
    assert _errors(findings, concurrency.RULE_ORDER), findings


def test_static_rank_respecting_nesting_is_clean():
    src = (
        "from learningorchestra_tpu.runtime import locks\n"
        "LA = locks.make_lock('fix.a')\n"
        "LB = locks.make_lock('fix.b')\n"
        "def fine():\n"
        "    with LA:\n"
        "        with LB:\n"
        "            pass\n"
    )
    findings = concurrency.analyze_source(src, "fix", "fix.py",
                                          hierarchy=H)
    assert not _errors(findings), findings


def test_static_flags_undeclared_and_unregistered_locks():
    src = (
        "import threading\n"
        "from learningorchestra_tpu.runtime import locks\n"
        "ANON = threading.Lock()\n"
        "TYPO = locks.make_lock('fix.nope')\n"
    )
    findings = concurrency.analyze_source(src, "fix", "fix.py",
                                          hierarchy=H)
    assert _errors(findings, concurrency.RULE_UNDECLARED)
    assert _errors(findings, concurrency.RULE_UNREGISTERED)


# ----------------------------------------------------------------------
# static pass: blocking-under-lock / callback-under-lock
# ----------------------------------------------------------------------

@pytest.mark.parametrize("stmt", [
    "time.sleep(0.1)",
    "fut.result()",
    "work_queue.get()",
    "jax.block_until_ready(x)",
    "jax.device_put(x)",
    "requests.get('http://x')",
])
def test_static_flags_blocking_under_lock(stmt):
    src = (
        "import time, jax, requests\n"
        "from learningorchestra_tpu.runtime import locks\n"
        "LA = locks.make_lock('fix.a')\n"
        "def f(fut, work_queue, x):\n"
        "    with LA:\n"
        f"        {stmt}\n"
    )
    findings = concurrency.analyze_source(src, "fix", "fix.py",
                                          hierarchy=H)
    assert _errors(findings, concurrency.RULE_BLOCKING), (stmt, findings)


def test_static_cv_wait_on_own_innermost_lock_is_legal():
    # `with cv: cv.wait()` releases the lock it waits on — legal.
    src = (
        "from learningorchestra_tpu.runtime import locks\n"
        "CV = locks.make_condition('fix.a')\n"
        "def f():\n"
        "    with CV:\n"
        "        CV.wait()\n"
    )
    findings = concurrency.analyze_source(src, "fix", "fix.py",
                                          hierarchy=H)
    assert not _errors(findings), findings


def test_static_cv_wait_with_outer_lock_held_is_flagged():
    # wait() only releases the innermost — the outer lock is held for
    # the whole sleep.
    src = (
        "from learningorchestra_tpu.runtime import locks\n"
        "LA = locks.make_lock('fix.a')\n"
        "CV = locks.make_condition('fix.b')\n"
        "def f():\n"
        "    with LA:\n"
        "        with CV:\n"
        "            CV.wait()\n"
    )
    findings = concurrency.analyze_source(src, "fix", "fix.py",
                                          hierarchy=H)
    assert _errors(findings, concurrency.RULE_BLOCKING), findings


def test_static_flags_callback_under_lock():
    src = (
        "from learningorchestra_tpu.runtime import locks\n"
        "LA = locks.make_lock('fix.a')\n"
        "def f(self):\n"
        "    with LA:\n"
        "        for cb in self.listeners:\n"
        "            cb()\n"
        "def g(self):\n"
        "    with LA:\n"
        "        self.on_change(1)\n"
    )
    findings = concurrency.analyze_source(src, "fix", "fix.py",
                                          hierarchy=H)
    cbs = _errors(findings, concurrency.RULE_CALLBACK)
    assert len(cbs) >= 2, findings


def test_static_waiver_downgrades_to_warning():
    src = (
        "import time\n"
        "from learningorchestra_tpu.runtime import locks\n"
        "LA = locks.make_lock('fix.a')\n"
        "def f():\n"
        "    with LA:\n"
        "        # lo-conc: waive(blocking-under-lock) — test fixture\n"
        "        time.sleep(0.01)\n"
    )
    findings = concurrency.analyze_source(src, "fix", "fix.py",
                                          hierarchy=H)
    assert not _errors(findings), findings
    waived = [f for f in findings
              if f.rule == concurrency.RULE_BLOCKING]
    assert waived and waived[0].severity == "warning"
    assert "waived" in waived[0].message


def test_real_package_has_zero_error_findings():
    findings = concurrency.analyze_package()
    assert not _errors(findings), [
        (f.rule, f.location, f.message) for f in _errors(findings)]


# ----------------------------------------------------------------------
# runtime witness
# ----------------------------------------------------------------------

@pytest.fixture
def witness(monkeypatch):
    monkeypatch.setenv("LO_LOCK_WITNESS", "1")
    monkeypatch.setenv("LO_LOCK_WITNESS_MODE", "raise")
    locks.reset_witness()
    # isolate this thread's held stack from any leftovers
    locks._tls.held = []
    yield locks
    locks.reset_witness()
    locks._tls.held = []


def test_factories_are_plain_primitives_when_disabled(monkeypatch):
    monkeypatch.setenv("LO_LOCK_WITNESS", "0")
    assert type(locks.make_lock("scheduler.fair")) is \
        type(threading.Lock())
    assert isinstance(locks.make_condition("scheduler.fair"),
                      threading.Condition)


def test_factory_rejects_unregistered_name(witness):
    with pytest.raises(KeyError):
        locks.make_lock("no.such.lock")


def test_witness_raises_on_rank_inversion(witness):
    outer = locks.make_lock("scheduler.fair")        # rank 80
    inner = locks.make_lock("jobs.manager")          # rank 30
    with outer:
        with pytest.raises(locks.LockOrderViolation):
            inner.acquire()
    # the violating acquire never took the underlying lock
    assert not inner._lock.locked()
    stats = locks.witness_stats()
    assert stats["violations"] == 1
    assert stats["samples"][0]["acquiring"] == "jobs.manager"


def test_witness_correct_order_is_silent(witness):
    a = locks.make_lock("jobs.manager")
    b = locks.make_lock("scheduler.fair")
    with a:
        with b:
            pass
    assert locks.witness_stats()["violations"] == 0
    assert ("jobs.manager", "scheduler.fair") in locks.witness_edges()


def test_witness_count_mode_records_and_continues(witness, monkeypatch):
    monkeypatch.setenv("LO_LOCK_WITNESS_MODE", "count")
    outer = locks.make_lock("scheduler.fair")
    inner = locks.make_lock("jobs.manager")
    with outer:
        with inner:       # inverted, but count mode: no raise
            pass
    stats = locks.witness_stats()
    assert stats["violations"] == 1
    assert stats["mode"] == "count"


def test_witness_rlock_reentry_is_legal(witness):
    rl = locks.make_rlock("jobs.manager")
    with rl:
        with rl:
            pass
    assert locks.witness_stats()["violations"] == 0


def test_witness_plain_lock_reentry_is_violation(witness):
    lk = locks.make_lock("jobs.manager")
    with lk:
        with pytest.raises(locks.LockOrderViolation):
            lk.acquire()
        # the raise fired BEFORE blocking on the primitive: a real
        # self-deadlock turns into a diagnosable exception
    assert locks.witness_stats()["violations"] == 1


def test_witness_condition_wait_releases_rank(witness):
    # While a thread waits on cv (rank 80) it holds no rank, so a
    # helper acquiring a lower-ranked lock (rank 30) on the SAME
    # thread after wake must not see stale held state; and another
    # thread may do low-then-notify without inversion.
    cv = locks.make_condition("scheduler.fair")
    low = locks.make_lock("jobs.manager")
    woke = []

    def waiter():
        locks._tls.held = []
        with cv:
            cv.wait(timeout=5)
            woke.append(True)
        with low:     # rank 30 AFTER releasing cv: legal
            pass

    t = threading.Thread(target=waiter)
    t.start()
    # during the wait, the waiter's stack must not pin rank 80
    import time
    time.sleep(0.1)
    with low:         # main thread: unrelated, legal
        pass
    with cv:
        cv.notify_all()
    t.join(timeout=5)
    assert woke
    assert locks.witness_stats()["violations"] == 0


def test_witness_wait_under_foreign_lock_flags_inversion(
        witness, monkeypatch):
    # holding a HIGHER-ranked lock while taking a lower-ranked cv:
    # an inversion (count mode so the fixture doesn't unwind mid-hold).
    monkeypatch.setenv("LO_LOCK_WITNESS_MODE", "count")
    high = locks.make_lock("serving.kvpool")       # rank 90
    cv = locks.make_condition("scheduler.fair")    # rank 80
    with high:
        cv.acquire()    # inversion: 80 under 90
        cv.release()
    assert locks.witness_stats()["violations"] >= 1


def test_witness_nonblocking_acquire_skips_order_check(witness):
    outer = locks.make_lock("scheduler.fair")
    inner = locks.make_lock("jobs.manager")
    with outer:
        # try-lock is a legal deadlock-avoidance idiom: no order check
        ok = inner.acquire(blocking=False)
        assert ok
        inner.release()
    assert locks.witness_stats()["violations"] == 0


# ----------------------------------------------------------------------
# end-to-end: real control-plane flows under the armed witness
# ----------------------------------------------------------------------

def test_control_plane_flows_zero_violations_subprocess():
    """Import the lock-heavy modules with LO_LOCK_WITNESS=1 (so every
    factory returns a witness wrapper) and drive incident capture —
    the flow that takes the commit lock and then freezes every other
    subsystem — plus SLO evaluation and monitor sampling. Zero
    violations required."""
    code = (
        "import os, tempfile\n"
        "os.environ['LO_LOCK_WITNESS'] = '1'\n"
        "os.environ['LO_LOCK_WITNESS_MODE'] = 'raise'\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "from learningorchestra_tpu.runtime import locks\n"
        "from learningorchestra_tpu.observability import incidents\n"
        "home = tempfile.mkdtemp()\n"
        "rec = incidents.FlightRecorder(home=home)\n"
        "bundle = rec.capture('witness-e2e', {'k': 'v'})\n"
        "assert bundle, 'no bundle captured'\n"
        "rec.close()\n"
        "stats = locks.witness_stats()\n"
        "assert stats['enabled'] and stats['violations'] == 0, stats\n"
        "print('edges:', len(locks.witness_edges()))\n"
        "print('OK')\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], cwd=str(REPO),
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OK" in proc.stdout, proc.stdout
