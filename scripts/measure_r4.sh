#!/usr/bin/env bash
# Round-4 measurement runner, hardened for a flappy chip: every
# experiment is gated on a fresh bounded probe (a wedged chip hangs
# backend init forever), so a mid-session wedge costs one probe
# timeout, not 30 idle minutes per remaining phase. Results land in
# $OUT as one JSON file per experiment; already-present results are
# skipped, so the script is resumable.
#
#   bash scripts/measure_r4.sh [OUT_DIR]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-queued_results}"
mkdir -p "$OUT"
PROBE_INTERVAL="${LO_PROBE_INTERVAL:-120}"
PHASE_TIMEOUT="${LO_PHASE_TIMEOUT:-1500}"

probe() {
  timeout 90 python - <<'EOF' >/dev/null 2>&1
import faulthandler
faulthandler.dump_traceback_later(80, exit=True)
import jax
assert any(d.platform != "cpu" for d in jax.devices())
import jax.numpy as jnp
assert float(jnp.ones((8, 8)).sum()) == 64.0
EOF
}

wait_for_chip() {
  until probe; do
    echo "$(date -u +%FT%TZ) chip not answering; retry in ${PROBE_INTERVAL}s"
    sleep "$PROBE_INTERVAL"
  done
}

run() {  # run NAME ENV... -- ARGS...
  local name="$1"; shift
  if [ -s "$OUT/$name.out" ] && grep -q '"ok": true' "$OUT/$name.out"; then
    echo "$(date -u +%FT%TZ) [$name] already done, skipping"
    return
  fi
  local envs=()
  while [ "$1" != "--" ]; do envs+=("$1"); shift; done
  shift
  wait_for_chip
  echo "$(date -u +%FT%TZ) [$name] env ${envs[*]-} bench $*"
  env "${envs[@]}" timeout "$PHASE_TIMEOUT" \
      python bench.py "$@" > "$OUT/$name.out" 2> "$OUT/$name.err"
  echo "exit=$? $(tail -c 400 "$OUT/$name.out")"
}

# the d=512 roofline pair (VERDICT next-round #2) first
run tlm_fused LO_NOOP=1 -- --phase tlm
run tlm_unfused LO_LM_HEAD_CHUNK=0 -- --phase tlm
# fused q/k/v + gate/up projections (wider MXU output tiles at d=512)
run tlm_fused_proj LO_TLM_FUSED_PROJ=1 -- --phase tlm
# long-context MFU on the flash path (VERDICT #1)
run tlm_longctx LO_BENCH_TLM_SEQ=2048 LO_BENCH_TLM_D=1024 \
    LO_BENCH_TLM_LAYERS=12 LO_BENCH_TLM_HEADS=16 LO_BENCH_TLM_FF=4096 \
    LO_BENCH_TLM_BATCH=8 LO_BENCH_TLM_N=1024 -- --phase tlm
# LSTM hoist decision (unroll=8 already measured: regression)
run lstm_hoist LO_LSTM_HOIST=1 -- --phase lstm
# remat batch scaling at the flagship shape
run tlm_remat_dots_b32 LO_TLM_REMAT=dots LO_BENCH_TLM_BATCH=32 \
    -- --phase tlm
run tlm_remat_full_b64 LO_TLM_REMAT=full LO_BENCH_TLM_BATCH=64 \
    -- --phase tlm
# decode throughput (net-new lm_decode row) + the GQA cache win
run gen LO_NOOP=1 -- --phase gen
run gen_gqa LO_BENCH_GEN_KV=2 -- --phase gen
# flash crossover below 1024
run flash512 LO_BENCH_FLASH_SEQS=512,1024 -- --phase flash
# sliding-window banded-grid evidence (W=1024 at long seq)
run flash_window LO_BENCH_FLASH_WINDOW=1024 \
    LO_BENCH_FLASH_SEQS=4096,8192 -- --phase flash
# full flash table on the BANDED kernels (flash_auto measured the
# pre-banding kernel; the causal rows should improve)
run flash_banded LO_NOOP=1 -- --phase flash
# full run + BENCHMARKS.md regeneration (bench.py's own guard keeps
# the committed table unless the chip answered)
wait_for_chip
echo "$(date -u +%FT%TZ) full bench + BENCHMARKS.md regeneration"
timeout 5400 python bench.py --write-md BENCHMARKS.md \
    > "$OUT/full_bench.out" 2> "$OUT/full_bench.err"
echo "$(date -u +%FT%TZ) done (exit=$?) — results in $OUT/"
