"""Function service: the wildcard Python-execution step.

Reference parity (code_executor_image/): POST body ``name``,
``description``, ``function`` (code text OR a URL to fetch it from),
``functionParameters`` (server.py:24-57, code_execution.py:11-21).
Parameters go through the ``$`` DSL so datasets arrive as DataFrames;
the code runs with them as globals, must leave its result in a
``response`` variable, and captured stdout is stored as
``functionMessage`` in the execution document
(code_execution.py:169-196, utils.py:113-138).

Difference by design: the code runs in the framework sandbox
(services/sandbox.py) rather than bare ``exec`` — same capability
surface for scientific code, no ambient filesystem/process authority
(SURVEY §7 hard part #3). ``Config.sandbox_mode = "trusted"`` restores
reference-equivalent trust.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from learningorchestra_tpu import analysis as A
from learningorchestra_tpu.catalog import documents as D
from learningorchestra_tpu.services import sandbox
from learningorchestra_tpu.services import validators as V

NAME_FIELD = "name"
ANALYSIS_FIELD = "analysis"
DESCRIPTION_FIELD = "description"
FUNCTION_FIELD = "function"
FUNCTION_PARAMETERS_FIELD = "functionParameters"
SANDBOX_MODE_FIELD = "sandboxMode"
RESPONSE_VARIABLE = "response"

# trust ordering for per-request escalation (config.sandbox_max_mode
# is the ceiling; config.sandbox_mode the default)
_TRUST_ORDER = {"subprocess": 0, "restricted": 1, "trusted": 2}


def resolve_sandbox_mode(config, requested: str | None) -> str:
    """The mode a request actually runs under: the config default, or
    the requested escalation if it stays at or below the operator's
    ceiling (406 otherwise). With no explicit ``sandbox_max_mode``
    the ceiling IS ``sandbox_mode`` — escalation past the default
    jail is an operator opt-in, never an API-caller choice."""
    if not requested:
        return config.sandbox_mode
    if requested not in _TRUST_ORDER:
        raise V.HttpError(
            V.HTTP_NOT_ACCEPTABLE,
            f"invalid sandboxMode {requested!r} (one of "
            f"{sorted(_TRUST_ORDER)})")
    base = _TRUST_ORDER.get(config.sandbox_mode, 0)
    ceiling = max(_TRUST_ORDER.get(config.sandbox_max_mode, base), base)
    if _TRUST_ORDER[requested] > ceiling:
        raise V.HttpError(
            V.HTTP_NOT_ACCEPTABLE,
            f"sandboxMode {requested!r} exceeds this server's ceiling "
            f"(sandbox_max_mode={config.sandbox_max_mode or 'unset'}); "
            f"set LO_SANDBOX_MAX to allow it")
    return requested


def fetch_function_code(function: str) -> str:
    """``function`` may be inline code or a URL to it (reference
    Function.treat, code_execution.py:11-21)."""
    if function.startswith(("http://", "https://")):
        import requests

        resp = requests.get(function, timeout=60)
        resp.raise_for_status()
        return resp.text
    if function.startswith("file://"):
        with open(function[len("file://"):]) as f:
            return f.read()
    return function


class FunctionService:
    def __init__(self, context):
        self._ctx = context
        self._validator = V.RequestValidator(context)

    def create(self, body: Dict[str, Any], tool: str = "python",
               ) -> Tuple[int, Dict[str, Any]]:
        self._validator.required_fields(
            body, [NAME_FIELD, FUNCTION_FIELD, FUNCTION_PARAMETERS_FIELD])
        name = self._validator.safe_name(body[NAME_FIELD])
        self._validator.not_duplicate(name)
        function = body[FUNCTION_FIELD]
        parameters = body[FUNCTION_PARAMETERS_FIELD] or {}
        description = body.get(DESCRIPTION_FIELD, "")
        timeout = V.valid_timeout(body.get(V.TIMEOUT_FIELD))
        mode = resolve_sandbox_mode(self._ctx.config,
                                    body.get(SANDBOX_MODE_FIELD))
        analysis = self._preflight(function, parameters, mode)
        type_string = f"function/{tool}"
        extra = {
            D.FUNCTION_FIELD: function,
            D.FUNCTION_PARAMETERS_FIELD: parameters,
            D.DESCRIPTION_FIELD: description,
            SANDBOX_MODE_FIELD: mode,  # boot requeue replays the same mode
        }
        if timeout is not None:
            extra[V.TIMEOUT_FIELD] = timeout  # requeues replay it too
        if analysis:
            extra[ANALYSIS_FIELD] = analysis
        self._ctx.catalog.create_collection(name, type_string, extra)
        self._submit(name, type_string, function, parameters, description,
                     mode=mode, timeout=timeout)
        return V.HTTP_CREATED, {
            "result": f"/api/learningOrchestra/v1/function/{tool}/{name}"}

    def update(self, name: str, body: Dict[str, Any],
               tool: str = "python") -> Tuple[int, Dict[str, Any]]:
        meta = self._validator.existing(name)
        function = body.get(FUNCTION_FIELD, meta.get(D.FUNCTION_FIELD))
        parameters = body.get(
            FUNCTION_PARAMETERS_FIELD,
            meta.get(D.FUNCTION_PARAMETERS_FIELD)) or {}
        description = body.get(DESCRIPTION_FIELD, "")
        timeout = V.valid_timeout(
            body.get(V.TIMEOUT_FIELD, meta.get(V.TIMEOUT_FIELD)))
        mode = resolve_sandbox_mode(self._ctx.config,
                                    body.get(SANDBOX_MODE_FIELD))
        analysis = self._preflight(function, parameters, mode)
        self._ctx.catalog.update_metadata(
            name, {D.FUNCTION_FIELD: function,
                   D.FUNCTION_PARAMETERS_FIELD: parameters,
                   SANDBOX_MODE_FIELD: mode,
                   ANALYSIS_FIELD: analysis,
                   V.TIMEOUT_FIELD: timeout,
                   D.FINISHED_FIELD: False})
        self._submit(name, meta[D.TYPE_FIELD], function, parameters,
                     description, mode=mode, timeout=timeout)
        return V.HTTP_SUCCESS, {
            "result": f"/api/learningOrchestra/v1/function/{tool}/{name}"}

    def delete(self, name: str, tool: str = "python",
               ) -> Tuple[int, Dict[str, Any]]:
        meta = self._validator.existing(name)
        self._ctx.catalog.delete_collection(name)
        self._ctx.artifacts.delete(name, meta.get(D.TYPE_FIELD))
        return V.HTTP_SUCCESS, {"result": f"deleted {name}"}

    # ------------------------------------------------------------------
    def _preflight(self, function: str, parameters: Dict[str, Any],
                   mode: str) -> list:
        """Submit-time AST lint of inline code and '#'-DSL parameters
        (URL-referenced code is screened at run time by the sandbox's
        own lint hook). 406 with findings on provable escapes."""
        if not self._ctx.config.preflight:
            return []
        findings = []
        if isinstance(function, str) and not function.startswith(
                ("http://", "https://", "file://")):
            findings.extend(A.lint_code(function, mode=mode,
                                        filename="<function>"))
        findings.extend(A.lint_parameter_code(parameters, mode))
        return V.run_preflight(findings)

    def _submit(self, name: str, type_string: str, function: str,
                parameters: Dict[str, Any], description: str,
                mode: Optional[str] = None,
                timeout: Optional[float] = None) -> None:
        def run():
            code = fetch_function_code(function)
            treated = self._ctx.params.treat(parameters)
            ctx_vars, stdout = sandbox.run_user_code(
                code, treated, mode=mode or self._ctx.config.sandbox_mode)
            if RESPONSE_VARIABLE not in ctx_vars:
                raise sandbox.missing_variable_error(
                    ctx_vars, RESPONSE_VARIABLE,
                    f"function must assign a {RESPONSE_VARIABLE!r} "
                    "variable")
            result = ctx_vars[RESPONSE_VARIABLE]
            self._ctx.artifacts.save(result, name, type_string)
            try:
                shapes = A.result_shapes(result)
                if shapes:
                    self._ctx.catalog.update_metadata(
                        name, {A.RESULT_SHAPES_FIELD: shapes})
            except Exception:  # noqa: BLE001 — advisory metadata only
                pass
            self._ctx.catalog.append_document(
                name, {D.FUNCTION_MESSAGE_FIELD: stdout})
            return result

        self._ctx.jobs.submit(name, run, description=description,
                              parameters=parameters,
                              max_retries=self._ctx.config.job_max_retries,
                              timeout=timeout)
