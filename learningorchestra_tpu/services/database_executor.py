"""Explore & Transform service (per-request reflection executor).

Reference parity (database_executor_image/): POST body ``name``,
``description``, ``modulePath``, ``class``, ``classParameters``,
``method``, ``methodParameters`` (server.py:31-37); the class is
instantiated fresh per request (no stored parent), the method result
is the artifact (database_execution.py:147-182):

- ``explore/*``  -> the result is rendered to a scatterplot PNG
  (utils.py:295-320 does ``sns.scatterplot(...).get_figure()
  .savefig``) served by a ``GET`` with ``image/png``
  (server.py:151-166);
- ``transform/*`` -> the result object (fitted scaler / transformed
  array) is stored as a binary for later steps (utils.py:241-292).

If ``method`` is empty the instance itself is the result (matching the
reference's method-optional transform flows).
"""

from __future__ import annotations

import io
from typing import Any, Dict, Optional, Tuple

from learningorchestra_tpu.catalog import documents as D
from learningorchestra_tpu.services import validators as V

NAME_FIELD = "name"
DESCRIPTION_FIELD = "description"
MODULE_PATH_FIELD = "modulePath"
CLASS_FIELD = "class"
CLASS_PARAMETERS_FIELD = "classParameters"
METHOD_FIELD = "method"
METHOD_PARAMETERS_FIELD = "methodParameters"


def render_plot_png(result: Any) -> bytes:
    """Render an explore result to PNG bytes.

    Accepts matplotlib figures/axes directly, else scatterplots the
    first two columns of array/DataFrame-shaped results (the
    reference's fixed seaborn scatterplot, utils.py:295-320).
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import numpy as np

    fig = None
    if hasattr(result, "savefig"):  # a Figure
        fig = result
    elif hasattr(result, "get_figure"):  # an Axes
        fig = result.get_figure()
    else:
        import pandas as pd
        import seaborn as sns

        if hasattr(result, "toarray"):  # scipy sparse
            result = result.toarray()
        arr = np.asarray(result)
        if arr.ndim == 1:
            arr = arr.reshape(-1, 1)
        frame = pd.DataFrame(arr[:, :2], columns=["x", "y"] if
                             arr.shape[1] >= 2 else ["x"])
        if arr.shape[1] == 1:
            frame["y"] = np.arange(len(frame))
        ax = sns.scatterplot(data=frame, x="x", y="y")
        fig = ax.get_figure()
    buf = io.BytesIO()
    fig.savefig(buf, format="png")
    plt.close(fig)
    return buf.getvalue()


class DatabaseExecutorService:
    def __init__(self, context):
        self._ctx = context
        self._validator = V.RequestValidator(context)

    def create(self, body: Dict[str, Any], verb: str, tool: str,
               ) -> Tuple[int, Dict[str, Any]]:
        self._validator.required_fields(
            body, [NAME_FIELD, MODULE_PATH_FIELD, CLASS_FIELD])
        name = self._validator.safe_name(body[NAME_FIELD])
        module_path = body[MODULE_PATH_FIELD]
        class_name = body[CLASS_FIELD]
        class_parameters = body.get(CLASS_PARAMETERS_FIELD) or {}
        method = body.get(METHOD_FIELD) or ""
        method_parameters = body.get(METHOD_PARAMETERS_FIELD) or {}
        description = body.get(DESCRIPTION_FIELD, "")
        self._validator.not_duplicate(name)
        cls = self._validator.valid_class(module_path, class_name)
        self._validator.valid_class_parameters(cls, class_parameters)
        if method:
            self._validator.valid_method(cls, method)
            self._validator.valid_method_parameters(
                cls, method, method_parameters)
        type_string = D.normalize_type(f"{verb}/{tool}")
        self._ctx.catalog.create_collection(name, type_string, {
            D.MODULE_PATH_FIELD: module_path,
            D.CLASS_FIELD: class_name,
            D.CLASS_PARAMETERS_FIELD: class_parameters,
            D.METHOD_FIELD: method,
            D.METHOD_PARAMETERS_FIELD: method_parameters,
            D.DESCRIPTION_FIELD: description,
        })
        self._submit(name, type_string, cls, class_parameters, method,
                     method_parameters, description, verb)
        return V.HTTP_CREATED, {
            "result": f"/api/learningOrchestra/v1/{verb}/{tool}/{name}"}

    def update(self, name: str, body: Dict[str, Any], verb: str, tool: str,
               ) -> Tuple[int, Dict[str, Any]]:
        meta = self._validator.existing(name)
        method = body.get(METHOD_FIELD, meta.get(D.METHOD_FIELD)) or ""
        method_parameters = body.get(
            METHOD_PARAMETERS_FIELD,
            meta.get(D.METHOD_PARAMETERS_FIELD)) or {}
        class_parameters = body.get(
            CLASS_PARAMETERS_FIELD, meta.get(D.CLASS_PARAMETERS_FIELD)) or {}
        description = body.get(DESCRIPTION_FIELD, "")
        cls = self._validator.valid_class(
            meta[D.MODULE_PATH_FIELD], meta[D.CLASS_FIELD])
        if method:
            self._validator.valid_method(cls, method)
        self._ctx.catalog.update_metadata(
            name, {D.METHOD_PARAMETERS_FIELD: method_parameters,
                   D.CLASS_PARAMETERS_FIELD: class_parameters,
                   D.FINISHED_FIELD: False})
        self._submit(name, meta[D.TYPE_FIELD], cls, class_parameters,
                     method, method_parameters, description, verb)
        return V.HTTP_SUCCESS, {
            "result": f"/api/learningOrchestra/v1/{verb}/{tool}/{name}"}

    def delete(self, name: str, verb: str, tool: str,
               ) -> Tuple[int, Dict[str, Any]]:
        meta = self._validator.existing(name)
        self._ctx.catalog.delete_collection(name)
        self._ctx.artifacts.delete(name, meta.get(D.TYPE_FIELD))
        return V.HTTP_SUCCESS, {"result": f"deleted {name}"}

    # ------------------------------------------------------------------
    def image_response(self, name: str) -> Tuple[bytes, str]:
        """PNG bytes for ``GET /explore/<name>`` (reference
        server.py:151-166 ``send_file(mimetype="image/png")``)."""
        meta = self._validator.existing(name)
        path, content_type = self._ctx.artifacts.bytes_path(
            name, meta[D.TYPE_FIELD])
        with open(path, "rb") as f:
            return f.read(), content_type

    def _submit(self, name: str, type_string: str, cls,
                class_parameters: Dict[str, Any], method: str,
                method_parameters: Dict[str, Any], description: str,
                verb: str) -> None:
        def run():
            instance = cls(**self._ctx.params.treat(class_parameters))
            if method:
                result = getattr(instance, method)(
                    **self._ctx.params.treat(method_parameters))
            else:
                result = instance
            if verb == "explore":
                png = render_plot_png(result)
                self._ctx.artifacts.save_bytes(
                    png, name, type_string, filename="plot.png",
                    content_type="image/png")
            else:
                self._ctx.artifacts.save(result, name, type_string)
            return result

        self._ctx.jobs.submit(name, run, description=description,
                              parameters=method_parameters)
