"""Hyperparameter sweep: trial-parallel grid/random search over mesh
sub-slices, GridSearchCV-shaped surface, artifact round-trip."""

import numpy as np
import pytest

from learningorchestra_tpu import config as config_mod
from learningorchestra_tpu.models import GridSearch, NeuralModel, RandomSearch
from learningorchestra_tpu.runtime import mesh as mesh_lib
from learningorchestra_tpu.runtime.mesh import sub_meshes


@pytest.fixture(autouse=True)
def _cfg(tmp_path):
    config_mod.set_config(config_mod.Config(
        home=str(tmp_path / "lo_home"), mesh_shape="auto",
        compute_dtype="float32"))
    yield
    config_mod.reset_config()


def _estimator():
    model = NeuralModel([
        {"kind": "dense", "units": 16, "activation": "relu"},
        {"kind": "dense", "units": 2, "activation": "softmax"},
    ], name="toy")
    model.compile({"kind": "adam", "learning_rate": 1e-3})
    return model


def _data(n=64):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    x[:, 1] = y * 2.0  # separable
    return x, y


def test_sub_meshes_partition():
    mesh = mesh_lib.get_default_mesh()
    slices = sub_meshes(mesh, 4)
    assert len(slices) == 4
    seen = set()
    for m in slices:
        ids = {d.id for d in np.asarray(m.devices).flat}
        assert not (ids & seen)
        seen |= ids


def test_grid_search_finds_better_lr():
    x, y = _data()
    sweep = GridSearch(_estimator(),
                       {"learning_rate": [1e-5, 5e-2]},
                       validation_split=0.25)
    sweep.fit(x, y, epochs=8, batch_size=16)
    assert len(sweep.cv_results_["params"]) == 2
    assert sweep.best_params_["learning_rate"] == 5e-2
    assert sweep.best_estimator_ is not None
    preds = sweep.predict(x[:8])
    assert preds.shape == (8, 2)


def test_random_search_samples():
    x, y = _data(32)
    sweep = RandomSearch(_estimator(),
                         {"learning_rate": [1e-4, 1e-3, 1e-2, 1e-1],
                          "batch_size": [8, 16]},
                         n_iter=3, refit=False, seed=1)
    sweep.fit(x, y, epochs=1)
    assert len(sweep.cv_results_["params"]) == 3
    assert sweep.best_params_ is not None
    assert sweep.best_estimator_ is None  # refit=False


def test_unknown_hyperparameter_rejected():
    x, y = _data(16)
    sweep = GridSearch(_estimator(), {"warp_factor": [9]}, refit=False)
    with pytest.raises(ValueError, match="warp_factor"):
        sweep.fit(x, y, epochs=1)


def test_save_load_roundtrip(tmp_path):
    x, y = _data(32)
    sweep = GridSearch(_estimator(), {"learning_rate": [1e-2]},
                       validation_split=0.25)
    sweep.fit(x, y, epochs=2, batch_size=16)
    art = tmp_path / "sweep_art"
    art.mkdir()
    sweep.__lo_save__(str(art))
    loaded = GridSearch.__lo_load__(str(art))
    assert loaded.best_params_ == sweep.best_params_
    assert loaded.best_score_ == sweep.best_score_
    p1 = sweep.predict(x[:8])
    p2 = loaded.predict(x[:8])
    np.testing.assert_allclose(p1, p2, atol=1e-5)


def test_grid_search_over_text_classifier(tmp_config):
    """The sweep's clone protocol (__lo_save__/__lo_load__/set_mesh)
    works for the encoder family too: a 2-point learning-rate grid
    over TextClassifier runs trial-parallel and reports a best."""
    import numpy as np

    from learningorchestra_tpu.models import GridSearch, TextClassifier

    rng = np.random.default_rng(0)
    x = rng.integers(1, 16, size=(32, 8)).astype(np.int32)
    y = (x[:, 0] > 8).astype(np.int32)
    base = TextClassifier(vocab_size=16, n_classes=2, d_model=16,
                          n_layers=1, n_heads=2, max_len=8)
    sweep = GridSearch(base, {"learning_rate": [1e-2, 1e-3]},
                       validation_split=0.25, refit=False)
    sweep.fit(x, y, batch_size=8, epochs=2)
    assert sweep.best_params_ is not None
    assert len(sweep.cv_results_["params"]) == 2
