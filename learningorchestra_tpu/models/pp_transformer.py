"""Pipeline-parallel transformer LM (GPipe over the ``pp`` axis).

The fifth parallelism axis, integrated with a real model: decoder
blocks are the pipelined middle (one or more layers per stage, stage
params stacked on a leading ``n_stages`` dim and sharded over ``pp``
by :func:`parallel.pipeline.pipeline_apply`), while the embedding and
the tied output head run outside the pipeline where activation shapes
change. Blocks are pure-jnp (pre-norm causal attention + gated MLP) so
one ``stage_fn`` serves every stage — the GPipe schedule requires
uniform activation shapes across stage boundaries.

Backward is plain autodiff through the pipelined scan: the transpose
of ``ppermute`` is the reverse rotation, so XLA derives the backward
fill/drain schedule from the forward one.

The reference has no pipeline (or any) model parallelism
(SURVEY §2.4); this module plus ``parallel/pipeline.py`` is the
net-new PP component pair.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from learningorchestra_tpu.parallel import pipeline as pp_lib
from learningorchestra_tpu.runtime import mesh as mesh_lib

NEG_INF = -1e30


def init_params(rng, vocab_size: int, d_model: int, n_layers: int,
                d_ff: Optional[int] = None) -> Dict[str, Any]:
    """Param pytree: ``embed`` (V, D) + per-layer tensors stacked on a
    leading ``n_layers`` dim (the layout PP stage-sharding wants)."""
    d_ff = d_ff or 4 * d_model
    ke, kq, ko, ki, kw = jax.random.split(rng, 5)
    s_in = 1.0 / math.sqrt(d_model)
    s_ff = 1.0 / math.sqrt(d_ff)

    def stack(key, shape, scale):
        return (jax.random.normal(key, (n_layers,) + shape) *
                scale).astype(jnp.float32)

    return {
        "embed": (jax.random.normal(ke, (vocab_size, d_model)) *
                  s_in).astype(jnp.float32),
        "blocks": {
            "ln1": jnp.ones((n_layers, d_model), jnp.float32),
            "qkv": stack(kq, (d_model, 3 * d_model), s_in),
            "o": stack(ko, (d_model, d_model), s_in),
            "ln2": jnp.ones((n_layers, d_model), jnp.float32),
            "wi": stack(ki, (d_model, d_ff), s_in),
            "wo": stack(kw, (d_ff, d_model), s_ff),
        },
    }


def _rms_norm(x, scale):
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(var + 1e-6) * scale


def _block(p: Dict[str, jnp.ndarray], x: jnp.ndarray,
           n_heads: int, attention: str = "auto",
           window: int = 0) -> jnp.ndarray:
    """One decoder block, (b, s, d) -> (b, s, d). Pure jnp so it can be
    the uniform GPipe stage body; on TPU the attention runs the Pallas
    flash kernel (no (s, s) score tensor per microbatch — the same
    long-context property as the main LM family), the dense einsum
    elsewhere."""
    if attention not in ("auto", "flash", "dense"):
        raise ValueError(
            f"unknown attention impl: {attention!r} "
            f"(auto|flash|dense)")
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    b, s, d = x.shape
    h = _rms_norm(x, p["ln1"])
    q, k, v = jnp.split(h @ p["qkv"], 3, axis=-1)

    def heads(t):
        return t.reshape(b, s, n_heads, d // n_heads)

    q, k, v = heads(q), heads(k), heads(v)
    scale = 1.0 / math.sqrt(d // n_heads)
    # same measured crossover as the LM families (BENCHMARKS.md):
    # flash from seq 1024 on TPU, dense oracle below
    use_flash = (attention == "flash" or
                 (attention == "auto" and s >= 1024 and
                  jax.default_backend() == "tpu"))
    if use_flash:
        from learningorchestra_tpu.ops import attention as attn_ops

        attn = attn_ops.flash_attention(
            q, k, v, causal=True, scale=scale,
            window=window).reshape(b, s, d)
    else:
        from learningorchestra_tpu.parallel import ring as ring_lib

        # the dense oracle (and its banded-window mask) lives in ONE
        # place — the same fallback _dispatch_attention uses
        attn = ring_lib.full_attention_reference(
            q, k, v, causal=True, scale=scale,
            window=window).reshape(b, s, d)
    x = x + attn @ p["o"]
    h = _rms_norm(x, p["ln2"])
    return x + (jax.nn.silu(h @ p["wi"]) @ p["wo"])


def _stage_fn_for(n_heads: int, layers_per_stage: int,
                  attention: str = "auto", window: int = 0):
    """Uniform stage body: run this stage's ``layers_per_stage`` blocks
    in order. ``pipeline_apply_local`` already stripped the leading
    local-shard dim, so leaves arrive as (layers_per_stage, ...)."""
    def stage_fn(stage_params, x):
        if layers_per_stage == 1:
            lp = jax.tree_util.tree_map(lambda a: a[0], stage_params)
            return _block(lp, x, n_heads, attention, window)
        x, _ = jax.lax.scan(
            lambda carry, lp: (_block(lp, carry, n_heads, attention,
                                      window),
                               None),
            x, stage_params)
        return x

    return stage_fn


def _embed_in(embed: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Embedding + fixed sinusoidal positions (params-free positions
    keep the pipelined stages uniform)."""
    x = embed[tokens]
    d = x.shape[-1]
    pos = jnp.arange(x.shape[1], dtype=jnp.float32)
    freqs = jnp.exp(-jnp.arange(0, d, 2, dtype=jnp.float32) / d *
                    math.log(10000.0))
    ang = pos[:, None] * freqs[None, :]
    pe = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)[None]
    return x + pe.astype(x.dtype)


def _stage_setup(params: Dict[str, Any], mesh):
    """Shared pipeline prologue: pp size, stage layout validation, and
    the (pp, layers_per_stage, ...) stage-param reshape — one place so
    the GPipe and 1F1B schedules can't desynchronize."""
    blocks = params["blocks"]
    n_layers = blocks["qkv"].shape[0]
    pp = mesh.shape.get(mesh_lib.PP, 1) if mesh is not None else 1
    if n_layers % pp:
        raise ValueError(f"{n_layers} layers not divisible by pp={pp}")
    layers_per_stage = n_layers // pp
    stage_params = None
    if pp > 1:
        stage_params = jax.tree_util.tree_map(
            lambda a: a.reshape((pp, layers_per_stage) + a.shape[1:]),
            blocks)
    return pp, layers_per_stage, stage_params


def forward(params: Dict[str, Any], tokens: jnp.ndarray, mesh,
            n_heads: int, num_microbatches: int = 4,
            attention: str = "auto", window: int = 0) -> jnp.ndarray:
    """tokens (b, s) int32 -> logits (b, s, V); blocks pipelined over
    ``pp``, embedding and tied head outside the pipeline."""
    pp, layers_per_stage, stage_params = _stage_setup(params, mesh)
    blocks = params["blocks"]
    embed = params["embed"]
    x = _embed_in(embed, tokens)

    if pp > 1:
        x = pp_lib.pipeline_apply(
            _stage_fn_for(n_heads, layers_per_stage, attention,
                          window),
            stage_params, x,
            mesh, num_microbatches=num_microbatches)
    else:
        for i in range(blocks["qkv"].shape[0]):
            x = _block(jax.tree_util.tree_map(lambda a, i=i: a[i], blocks),
                       x, n_heads, attention, window)
    return x @ embed.T  # tied head


def next_token_loss(params, tokens, mesh, n_heads: int,
                    num_microbatches: int = 4,
                    attention: str = "auto", window: int = 0):
    logits = forward(params, tokens, mesh, n_heads,
                     num_microbatches=num_microbatches,
                     attention=attention, window=window)
    tgt = tokens[:, 1:]
    lg = logits[:, :-1].astype(jnp.float32)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(lg, tgt)
    mask = (tgt != 0).astype(jnp.float32)
    return jnp.sum(per_tok * mask) / jnp.maximum(jnp.sum(mask), 1e-9)


def _head_loss(embed: jnp.ndarray, out: jnp.ndarray,
               y_mb: jnp.ndarray) -> jnp.ndarray:
    """Tied-head next-token loss for one microbatch (mean over its
    unpadded tokens). 1F1B's total loss is the mean over microbatches
    — identical to the full-batch mean when microbatches carry equal
    mask counts (no padding), the standard practice tradeoff."""
    logits = out @ embed.T
    tgt = y_mb[:, 1:]
    lg = logits[:, :-1].astype(jnp.float32)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(lg, tgt)
    mask = (tgt != 0).astype(jnp.float32)
    return jnp.sum(per_tok * mask) / jnp.maximum(jnp.sum(mask), 1e-9)


def value_and_grad_1f1b(params, tokens: jnp.ndarray, mesh, n_heads: int,
                        num_microbatches: int = 4,
                        attention: str = "auto", window: int = 0):
    """Hand-assembled train pass on the 1F1B schedule
    (parallel/pipeline.py): the pipelined middle returns its stage
    grads plus dx; the embedding's gradient combines the tied head's
    contribution with the lookup scatter — no outer autodiff through
    the pipeline loop."""
    pp, layers_per_stage, stage_params = _stage_setup(params, mesh)
    if pp <= 1:
        raise ValueError("1F1B needs a pp axis of size >= 2")
    embed = params["embed"]
    n_layers = params["blocks"]["qkv"].shape[0]
    x = _embed_in(embed, tokens)
    loss, dstage, dembed_head, dx = pp_lib.pipeline_value_and_grad_1f1b(
        _stage_fn_for(n_heads, layers_per_stage, attention, window),
        _head_loss,
        stage_params, embed, x, tokens, mesh,
        num_microbatches=num_microbatches)
    dblocks = jax.tree_util.tree_map(
        lambda g: g.reshape((n_layers,) + g.shape[2:]), dstage)
    d = embed.shape[-1]
    dembed = dembed_head + jnp.zeros_like(embed, jnp.float32).at[
        tokens.reshape(-1)].add(dx.reshape(-1, d))
    return loss, {"embed": dembed.astype(embed.dtype), "blocks": dblocks}


def fit(params, tokens: np.ndarray, mesh, n_heads: int, steps: int = 4,
        batch_size: Optional[int] = None, learning_rate: float = 1e-3,
        num_microbatches: int = 4, schedule: str = "gpipe",
        attention: str = "auto", window: int = 0,
        ) -> Tuple[Dict[str, Any], List[float]]:
    """Minimal jitted training loop (dryrun / test harness — the full
    REST-facing engine path uses LanguageModel; this validates the PP
    compute path, forward AND backward, end to end).

    ``schedule``: ``"gpipe"`` (autodiff through the fill/drain scan)
    or ``"1f1b"`` (hand-scheduled one-forward-one-backward with
    bounded activation stash)."""
    optimizer = optax.adam(learning_rate)
    opt_state = optimizer.init(params)
    bs = batch_size or tokens.shape[0]

    @jax.jit
    def step(p, o, batch):
        if schedule == "1f1b":
            loss, grads = value_and_grad_1f1b(p, batch, mesh, n_heads,
                                              num_microbatches,
                                              attention=attention,
                                              window=window)
        else:
            def loss_of(t):
                return next_token_loss(t, batch, mesh, n_heads,
                                       num_microbatches,
                                       attention=attention,
                                       window=window)

            loss, grads = jax.value_and_grad(loss_of)(p)
        updates, o = optimizer.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    losses: List[float] = []
    for i in range(steps):
        start = (i * bs) % max(1, len(tokens) - bs + 1)
        batch = jnp.asarray(tokens[start:start + bs])
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    return params, losses
