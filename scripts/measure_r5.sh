#!/usr/bin/env bash
# Round-5 follow-up measurement queue: waits for the round-4 runner
# (scripts/measure_r4.sh) to finish its list, then lands the rows the
# round-5 features added. Same discipline: bounded probe before every
# experiment, resumable outputs, one probe timeout per wedge.
#
#   bash scripts/measure_r5.sh [OUT_DIR]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-queued_results}"
mkdir -p "$OUT"
PROBE_INTERVAL="${LO_PROBE_INTERVAL:-120}"
PHASE_TIMEOUT="${LO_PHASE_TIMEOUT:-1500}"

probe() {
  timeout 90 python - <<'EOF' >/dev/null 2>&1
import faulthandler
faulthandler.dump_traceback_later(80, exit=True)
import jax
assert any(d.platform != "cpu" for d in jax.devices())
import jax.numpy as jnp
assert float(jnp.ones((8, 8)).sum()) == 64.0
EOF
}

wait_for_chip() {
  until probe; do
    echo "$(date -u +%FT%TZ) chip not answering; retry in ${PROBE_INTERVAL}s"
    sleep "$PROBE_INTERVAL"
  done
}

run() {  # run NAME ENV... -- ARGS...
  local name="$1"; shift
  if [ -s "$OUT/$name.out" ] && grep -q '"ok": true' "$OUT/$name.out"; then
    echo "$(date -u +%FT%TZ) [$name] already done, skipping"
    return
  fi
  local envs=()
  while [ "$1" != "--" ]; do envs+=("$1"); shift; done
  shift
  wait_for_chip
  echo "$(date -u +%FT%TZ) [$name] env ${envs[*]-} bench $*"
  env "${envs[@]}" timeout "$PHASE_TIMEOUT" \
      python bench.py "$@" > "$OUT/$name.out" 2> "$OUT/$name.err"
  echo "exit=$? $(tail -c 400 "$OUT/$name.out")"
}

# never run two chip users at once: wait for the r4 runner to exit
while pgrep -f "measure_r4.sh" >/dev/null 2>&1; do
  echo "$(date -u +%FT%TZ) waiting for measure_r4.sh to finish"
  sleep 120
done

# mesh-parallel Builder on silicon (jax LR on the chip vs host sklearn)
run builder_mesh_tpu LO_NOOP=1 -- --phase builder_mesh
# MQA decode (kv=1): the full KV-cache-shrink story next to kv=2
run gen_mqa LO_BENCH_GEN_KV=1 -- --phase gen
# combined d=512 closing attempt: fused head (default) + fused_proj +
# dots-remat + batch 32 in ONE config
run tlm_combo LO_TLM_FUSED_PROJ=1 LO_TLM_REMAT=dots \
    LO_BENCH_TLM_BATCH=32 -- --phase tlm
echo "$(date -u +%FT%TZ) r5 follow-up queue done — results in $OUT/"
