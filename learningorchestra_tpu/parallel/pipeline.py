"""Pipeline parallelism over the ``pp`` axis: GPipe and 1F1B.

Layer stages live on different devices; microbatches flow through the
ring of stages with activations handed to the next stage by
``ppermute`` each tick. Every device runs the same jitted tick body
(SPMD — no MPMD program needed). Two schedules:

- **GPipe** (:func:`pipeline_apply`): forward-only fill/drain,
  ``M + n - 1`` ticks; backward comes from plain autodiff through the
  scan (the transpose of ``ppermute`` is the reverse rotation).
  Simple, composes with any outer loss, but autodiff stashes every
  scan-tick residual — activation memory grows with M.
- **1F1B** (:func:`pipeline_value_and_grad_1f1b`): the classic
  one-forward-one-backward schedule — each tick a stage runs one
  microbatch forward AND one backward; microbatch j's backward starts
  as soon as its forward leaves the last stage, so the input stash is
  a ring buffer of depth ``2n - 1`` **independent of M**. The loss
  head runs INSIDE the last stage's tick (``lax.cond`` on the stage
  index, so only that device pays the head matmul), gradients are
  hand-assembled from per-tick ``jax.vjp`` with activation recompute,
  and the function returns ``(loss, dstage_params, dhead_params,
  dx)`` directly — no outer autodiff through the loop.

Stage parameters are stacked on a leading ``n_stages`` dim and sharded
over ``pp``, so each device holds exactly its stage's weights.
Activation shapes must be uniform across stage boundaries (wrap
embed/head layers outside the pipelined middle, transformer-style).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from learningorchestra_tpu.runtime import mesh as mesh_lib


def pipeline_apply_local(stage_fn: Callable[[Any, jax.Array], jax.Array],
                         stage_params: Any, x: jax.Array,
                         num_microbatches: int,
                         axis_name: str = mesh_lib.PP) -> jax.Array:
    """Inside shard_map: ``stage_params`` leaves are (1, ...) local
    stage shards; ``x`` is the local batch (replicated over pp).
    Returns the pipelined ``stage_{n-1}(...stage_0(x))``, replicated.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    params = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    m = num_microbatches
    if x.shape[0] % m:
        raise ValueError(f"batch {x.shape[0]} not divisible by "
                         f"microbatches {m}")
    micro = x.reshape(m, x.shape[0] // m, *x.shape[1:])

    def tick(carry, t):
        inp_buf, out_buf = carry
        mb = lax.dynamic_index_in_dim(
            micro, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
        inp = jnp.where(idx == 0, mb, inp_buf)
        # fill/drain bubbles used to compute on garbage and mask the
        # result; branch instead so bubble ticks cost ~nothing
        # ((n-1)/(m+n-1) of stage compute saved)
        fvalid = (t - idx >= 0) & (t - idx < m)
        y = lax.cond(fvalid, lambda i: stage_fn(params, i),
                     lambda i: i * jnp.zeros((), i.dtype), inp)
        out_mb = t - (n - 1)
        write = (idx == n - 1) & (out_mb >= 0) & (out_mb < m)
        slot = jnp.clip(out_mb, 0, m - 1)
        old = lax.dynamic_index_in_dim(out_buf, slot, axis=0,
                                       keepdims=False)
        out_buf = lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(write, y, old), slot, axis=0)
        nxt = lax.ppermute(y, axis_name, _forward_perm(n))
        return (nxt, out_buf), None

    # scan carries become pp-varying (each stage computes different
    # values), so the initial values must be cast varying too
    zero = mesh_lib.pcast(jnp.zeros_like(micro[0]), axis_name, to="varying")
    out0 = mesh_lib.pcast(jnp.zeros_like(micro), axis_name, to="varying")
    (_, out), _ = lax.scan(tick, (zero, out0),
                           jnp.arange(m + _static_size(n) - 1))
    # only the last stage holds real outputs; replicate via masked psum
    out = lax.psum(jnp.where(idx == n - 1, out, 0.0), axis_name)
    return out.reshape(x.shape[0], *out.shape[2:])


def _static_size(n) -> int:
    """lax.psum(1, axis) inside shard_map is a traced value in some
    versions; the scan length must be static. shard_map guarantees the
    axis size is known at trace time via the abstract mesh."""
    try:
        return int(n)
    except Exception:  # noqa: BLE001 — fall back to concrete int carrier
        raise ValueError("pipeline axis size must be static")


def _forward_perm(n) -> list:
    size = _static_size(n)
    return [(i, i + 1) for i in range(size - 1)]


def _backward_perm(n) -> list:
    size = _static_size(n)
    return [(i, i - 1) for i in range(1, size)]


def pipeline_1f1b_local(stage_fn: Callable[[Any, jax.Array], jax.Array],
                        head_fn: Callable[[Any, jax.Array, jax.Array],
                                          jax.Array],
                        stage_params: Any, head_params: Any,
                        x: jax.Array, y: jax.Array,
                        num_microbatches: int,
                        axis_name: str = mesh_lib.PP,
                        mesh_axes: tuple = (mesh_lib.PP,)):
    """Inside shard_map: one 1F1B training pass.

    Schedule (stage ``s`` of ``n``, tick ``t``): forward of microbatch
    ``f = t - s`` and backward of microbatch ``b = t - 2(n-1) + s``,
    both skipped via ``lax.cond`` outside their ranges. The last stage
    finishes microbatch j's forward and starts its backward in the
    SAME tick (``b == f`` at ``s = n-1``), which is what bounds the
    in-flight window: a stage holds at most ``2(n-1-s) + 1`` stashed
    inputs, so the ring buffer depth ``2n - 1`` suffices for any M.
    Backward recomputes the stage forward from the stashed INPUT
    (``jax.vjp`` per tick) rather than stashing internals —
    memory O(n·microbatch), compute ≈ 4/3× (the standard
    rematerialized-pipeline tradeoff).

    ``head_fn(head_params, out_mb, y_mb) -> scalar`` is the
    per-microbatch loss (mean over the microbatch); the total loss is
    the mean over microbatches. Returns ``(loss, dstage_params_local,
    dhead_params, dx)`` where ``dx`` is the gradient w.r.t. ``x`` (for
    an embedding backward outside the pipeline).
    """
    n = _static_size(lax.psum(1, axis_name))
    idx = lax.axis_index(axis_name)
    m = num_microbatches
    if x.shape[0] % m:
        raise ValueError(f"batch {x.shape[0]} not divisible by "
                         f"microbatches {m}")
    micro_x = x.reshape(m, x.shape[0] // m, *x.shape[1:])
    micro_y = y.reshape(m, y.shape[0] // m, *y.shape[1:])
    depth = max(1, 2 * n - 1)
    f32 = jnp.float32
    extra_axes = tuple(a for a in mesh_axes if a != axis_name)

    def varying(v):
        # mark values as device-varying over EVERY mesh axis (adding
        # only the axes each leaf is missing) — the vjp calls below
        # must see only varying inputs, or AD inserts psums for the
        # replicated ones INSIDE the lax.cond branches (a collective
        # not all devices reach); reductions happen explicitly at the
        # end of the pass instead
        def one(x):
            vma = getattr(mesh_lib.typeof(x), "vma", frozenset())
            missing = tuple(a for a in mesh_axes if a not in vma)
            return mesh_lib.pcast(x, missing, to="varying") if missing else x

        return jax.tree_util.tree_map(one, v)

    params = varying(jax.tree_util.tree_map(lambda p: p[0], stage_params))
    head_params = varying(head_params)

    def zeros_f32(tree):
        return jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, f32), tree)

    mb_shape = micro_x[0]
    init = (
        varying(jnp.zeros_like(mb_shape)),                 # act_in
        varying(jnp.zeros_like(mb_shape)),                 # grad_in
        varying(jnp.zeros((depth,) + mb_shape.shape, mb_shape.dtype)),
        varying(zeros_f32(params)),                        # dparams
        varying(zeros_f32(head_params)),                   # dhead
        varying(jnp.zeros((m,) + mb_shape.shape, f32)),    # dx buffer
        varying(jnp.zeros((), f32)),                       # loss acc
    )

    def tick(carry, t):
        act_in, grad_in, stash, dparams, dhead, dx_buf, loss_acc = carry

        # ---- forward half: microbatch f = t - s -----------------------
        f = t - idx
        fvalid = (f >= 0) & (f < m)
        inp = jnp.where(idx == 0,
                        lax.dynamic_index_in_dim(
                            micro_x, jnp.clip(f, 0, m - 1), 0,
                            keepdims=False),
                        act_in)
        y_out = lax.cond(fvalid,
                         lambda i: stage_fn(params, i),
                         lambda i: jnp.zeros_like(i), inp)
        fslot = jnp.where(fvalid, f, 0) % depth
        prev = lax.dynamic_index_in_dim(stash, fslot, 0, keepdims=False)
        stash = lax.dynamic_update_index_in_dim(
            stash, jnp.where(fvalid, inp, prev), fslot, 0)

        # ---- backward half: microbatch b = t - 2(n-1) + s -------------
        b = t - 2 * (n - 1) + idx
        bvalid = (b >= 0) & (b < m)
        bslot = jnp.where(bvalid, b, 0) % depth
        binp = lax.dynamic_index_in_dim(stash, bslot, 0, keepdims=False)
        yb = lax.dynamic_index_in_dim(micro_y, jnp.clip(b, 0, m - 1), 0,
                                      keepdims=False)

        def do_bwd(_):
            out_b, vjp = jax.vjp(stage_fn, params, binp)

            def last_stage(_):
                def hl(hp, o):
                    return head_fn(hp, o, yb)

                loss_b, (dh, go) = jax.value_and_grad(
                    hl, argnums=(0, 1))(head_params, out_b)
                scale = 1.0 / m
                dh = jax.tree_util.tree_map(
                    lambda g: g.astype(f32) * scale, dh)
                return (loss_b.astype(f32) * scale, dh,
                        (go * scale).astype(out_b.dtype))

            def mid_stage(_):
                # fresh zeros are axis-unvarying; pcast them so both
                # cond branches carry the same varying type
                return (varying(jnp.zeros((), f32)),
                        varying(zeros_f32(head_params)),
                        grad_in.astype(out_b.dtype))

            loss_b, dh, gout = lax.cond(idx == n - 1, last_stage,
                                        mid_stage, None)
            dp, dinp = vjp(gout)
            dp = jax.tree_util.tree_map(lambda g: g.astype(f32), dp)
            return loss_b, dh, dp, dinp.astype(mb_shape.dtype)

        def no_bwd(_):
            return (varying(jnp.zeros((), f32)),
                    varying(zeros_f32(head_params)),
                    varying(zeros_f32(params)),
                    varying(jnp.zeros_like(mb_shape)))

        loss_b, dh, dp, dinp = lax.cond(bvalid, do_bwd, no_bwd, None)
        dparams = jax.tree_util.tree_map(jnp.add, dparams, dp)
        dhead = jax.tree_util.tree_map(jnp.add, dhead, dh)
        loss_acc = loss_acc + loss_b

        # stage 0 owns dx (the embedding backward's input)
        dslot = jnp.clip(b, 0, m - 1)
        old_dx = lax.dynamic_index_in_dim(dx_buf, dslot, 0,
                                          keepdims=False)
        dx_buf = lax.dynamic_update_index_in_dim(
            dx_buf,
            jnp.where(bvalid & (idx == 0), dinp.astype(f32), old_dx),
            dslot, 0)

        # unconditional comms keep the collective schedule static
        act_next = lax.ppermute(y_out, axis_name, _forward_perm(n))
        grad_next = lax.ppermute(dinp, axis_name, _backward_perm(n))
        return (act_next, grad_next, stash, dparams, dhead, dx_buf,
                loss_acc), None

    ticks = jnp.arange(m + 2 * (n - 1))
    (_, _, _, dparams, dhead, dx_buf, loss_acc), _ = lax.scan(
        tick, init, ticks)

    # replicate across pp: loss/dhead live on the last stage only; each
    # stage's dparams stay local (restacked by the caller's out_specs);
    # dx lives on stage 0 only
    loss = lax.psum(loss_acc, axis_name)
    dhead = jax.tree_util.tree_map(
        lambda g: lax.psum(g, axis_name), dhead)
    dx = lax.psum(dx_buf, axis_name)
    dparams = jax.tree_util.tree_map(lambda g: g[None], dparams)
    return loss, dparams, dhead, dx.reshape(x.shape[0], *x.shape[1:])


def pipeline_value_and_grad_1f1b(
        stage_fn: Callable[[Any, jax.Array], jax.Array],
        head_fn: Callable[[Any, jax.Array, jax.Array], jax.Array],
        stage_params: Any, head_params: Any,
        x: jax.Array, y: jax.Array, mesh: Mesh,
        num_microbatches: int = 4):
    """pjit-level 1F1B train pass: returns ``(loss, dstage_params
    (stacked like the input), dhead_params, dx)``. ``x``/``dx`` are
    sharded over the data axes; gradients are averaged over them."""
    if mesh_lib.PP not in mesh.axis_names:
        raise ValueError("mesh has no 'pp' axis")
    data = mesh_lib.data_axes(mesh)
    xspec = P(data if data else None)
    pspec = jax.tree_util.tree_map(
        lambda p: P(*((mesh_lib.PP,) + (None,) * (p.ndim - 1))),
        stage_params)
    hspec = jax.tree_util.tree_map(lambda p: P(), head_params)

    def body(sp, hp, xx, yy):
        loss, dsp, dhp, dx = pipeline_1f1b_local(
            stage_fn, head_fn, sp, hp, xx, yy,
            num_microbatches=num_microbatches,
            mesh_axes=tuple(mesh.axis_names))
        # mean over data shards (per-shard losses are per-shard means)
        if data:
            loss = lax.pmean(loss, data)
            dsp = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, data), dsp)
            dhp = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, data), dhp)
            # dx rows belong to this shard's batch slice — no averaging
            # across shards, but the global loss carries the same 1/n
            # factor pmean applied to the param grads
            dx = dx / lax.psum(1, data)
        return loss, dsp, dhp, dx

    # check_vma=False: stage bodies may run pallas_call (the PP
    # block's flash attention), whose ShapeDtypeStructs carry no
    # varying-mesh-axes info — the vma checker rejects them (same as
    # the tp flash path and ring_flash)
    fn = mesh_lib.shard_map(
        body, mesh=mesh,
        in_specs=(pspec, hspec, xspec, xspec),
        out_specs=(P(), pspec, hspec, xspec),
        check_vma=False)
    return fn(stage_params, head_params, x, y)


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any, x: jax.Array, mesh: Mesh,
                   num_microbatches: int = 4) -> jax.Array:
    """pjit-level entry. ``stage_params`` leaves are stacked
    (n_stages, ...) and get sharded over ``pp``; ``x`` is the global
    batch, sharded over the data axes and replicated over ``pp``."""
    if mesh_lib.PP not in mesh.axis_names:
        raise ValueError("mesh has no 'pp' axis")
    data = mesh_lib.data_axes(mesh)
    xspec = P(data if data else None)
    pspec = jax.tree_util.tree_map(
        lambda p: P(*((mesh_lib.PP,) + (None,) * (p.ndim - 1))),
        stage_params)
    # check_vma=False: see value_and_grad_1f1b — stage bodies may
    # contain pallas_call
    fn = mesh_lib.shard_map(
        functools.partial(pipeline_apply_local, stage_fn,
                          num_microbatches=num_microbatches,
                          axis_name=mesh_lib.PP),
        mesh=mesh, in_specs=(pspec, xspec), out_specs=xspec,
        check_vma=False)
    return fn(stage_params, x)
