"""Pure-python reader for TensorFlow SavedModel variable bundles.

The reference round-trips live Keras models through
``keras.models.save_model`` / ``load_model`` on a SavedModel directory
(reference binary_executor_image/utils.py:201-220) — the one artifact
format a TF-free runtime cannot open through h5py. This module reads
that format directly, without importing tensorflow:

- ``variables/variables.index`` is a leveldb-style immutable table
  (block-based SSTable, prefix-compressed keys, varint-encoded
  lengths, 48-byte footer ending in the 0xdb4775248b80fb57 magic)
  whose values are serialized ``BundleEntryProto`` messages;
- ``variables/variables.data-NNNNN-of-NNNNN`` shards hold the raw
  little-endian tensor bytes at (offset, size) from the entry;
- ``keras_metadata.pb`` is a ``SavedMetadata`` protobuf whose nodes
  carry the Keras layer/model configs as JSON strings.

Only the subset TF actually writes for checkpoints is implemented
(uncompressed blocks, single-level index); anything else fails loudly.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Any, Dict, Iterator, List, Tuple

import numpy as np

_TABLE_MAGIC = 0xdb4775248b80fb57
_FOOTER_LEN = 48

# tensorflow DataType enum -> numpy dtype (the checkpointable subset)
_DTYPES = {
    1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8,
    5: np.int16, 6: np.int8, 9: np.int64, 10: np.bool_,
    14: None,  # bfloat16 — resolved lazily via ml_dtypes
    17: np.uint16, 19: np.float16, 22: np.uint32, 23: np.uint64,
}


def _np_dtype(enum: int):
    if enum == 14:
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    try:
        dt = _DTYPES[enum]
    except KeyError:
        raise ValueError(
            f"unsupported tensor dtype enum {enum} in bundle") from None
    return np.dtype(dt)


# ----------------------------------------------------------------------
# minimal protobuf wire-format decoding (no generated classes)
# ----------------------------------------------------------------------
def _varint(buf: bytes, i: int) -> Tuple[int, int]:
    v = 0
    shift = 0
    while True:
        b = buf[i]
        i += 1
        v |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            return v, i


def pb_fields(buf: bytes) -> Iterator[Tuple[int, int, Any]]:
    """Yield (field_number, wire_type, value) triples; length-delimited
    values are raw bytes, varints are ints."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _varint(buf, i)
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield field, wt, v


def _parse_shape(buf: bytes) -> Tuple[int, ...]:
    dims: List[int] = []
    for field, _, v in pb_fields(buf):
        if field == 2:  # Dim
            for f2, _, v2 in pb_fields(v):
                if f2 == 1:  # size
                    dims.append(v2)
        elif field == 3 and v:  # unknown_rank
            raise ValueError("bundle tensor with unknown rank")
    return tuple(dims)


def _parse_entry(buf: bytes) -> Dict[str, Any]:
    out = {"dtype": 0, "shape": (), "shard_id": 0, "offset": 0,
           "size": 0}
    for field, _, v in pb_fields(buf):
        if field == 1:
            out["dtype"] = v
        elif field == 2:
            out["shape"] = _parse_shape(v)
        elif field == 3:
            out["shard_id"] = v
        elif field == 4:
            out["offset"] = v
        elif field == 5:
            out["size"] = v
        elif field == 7:
            raise ValueError("sliced/partitioned bundle tensors are "
                             "not supported")
    return out


# ----------------------------------------------------------------------
# leveldb-style immutable table (the .index file)
# ----------------------------------------------------------------------
def _read_block(data: bytes, offset: int, size: int) -> bytes:
    block = data[offset:offset + size]
    if len(block) != size:
        raise ValueError("truncated table block")
    comp = data[offset + size]
    if comp != 0:
        raise ValueError(
            f"compressed table block (type {comp}); TF writes "
            f"checkpoint indexes uncompressed")
    return block


def _block_entries(block: bytes) -> Iterator[Tuple[bytes, bytes]]:
    (n_restarts,) = struct.unpack("<I", block[-4:])
    data_end = len(block) - 4 - 4 * n_restarts
    i = 0
    key = b""
    while i < data_end:
        shared, i = _varint(block, i)
        unshared, i = _varint(block, i)
        vlen, i = _varint(block, i)
        key = key[:shared] + block[i:i + unshared]
        i += unshared
        yield key, block[i:i + vlen]
        i += vlen


def read_index(path: str) -> Dict[str, Dict[str, Any]]:
    """All (tensor_name -> BundleEntry dict) pairs from a
    ``variables.index`` file, plus the header under the ``""`` key."""
    data = open(path, "rb").read()
    if len(data) < _FOOTER_LEN:
        raise ValueError(f"{path}: too short to be a bundle index")
    footer = data[-_FOOTER_LEN:]
    (magic,) = struct.unpack("<Q", footer[-8:])
    if magic != _TABLE_MAGIC:
        raise ValueError(f"{path}: bad table magic {magic:#x}")
    # footer = metaindex handle + index handle (varints), padded
    i = 0
    _, i = _varint(footer, i)   # metaindex offset (unused)
    _, i = _varint(footer, i)   # metaindex size
    idx_off, i = _varint(footer, i)
    idx_size, i = _varint(footer, i)
    index_block = _read_block(data, idx_off, idx_size)
    entries: Dict[str, Dict[str, Any]] = {}
    header: Dict[str, Any] = {}
    for _, handle in _block_entries(index_block):
        off, j = _varint(handle, 0)
        size, j = _varint(handle, j)
        for key, value in _block_entries(_read_block(data, off, size)):
            name = key.decode("utf-8")
            if name == "":
                for field, _, v in pb_fields(value):  # BundleHeader
                    if field == 1:
                        header["num_shards"] = v
            else:
                entries[name] = _parse_entry(value)
    entries[""] = header or {"num_shards": 1}
    return entries


def _shard_reader(variables_prefix: str, num_shards: int):
    shards: Dict[int, bytes] = {}

    def shard(i: int) -> bytes:
        if i not in shards:
            p = f"{variables_prefix}.data-{i:05d}-of-{num_shards:05d}"
            shards[i] = open(p, "rb").read()
        return shards[i]

    return shard


def read_tensors(variables_prefix: str, keys,
                 entries: Dict[str, Dict[str, Any]] = None,
                 ) -> Dict[str, np.ndarray]:
    """Decode only ``keys`` from a checkpoint bundle (a trained
    checkpoint also carries optimizer slot variables ~2x the model
    size — selective decode skips them). ``entries`` reuses an
    already-parsed index."""
    if entries is None:
        entries = read_index(variables_prefix + ".index")
    header = entries.get("", {})
    shard = _shard_reader(variables_prefix,
                          header.get("num_shards", 1))
    out: Dict[str, np.ndarray] = {}
    for name in keys:
        if name in out:
            continue
        e = entries[name]
        dt = _np_dtype(e["dtype"])
        raw = shard(e["shard_id"])[e["offset"]:e["offset"] + e["size"]]
        n = int(np.prod(e["shape"])) if e["shape"] else 1
        if len(raw) != n * dt.itemsize:
            raise ValueError(
                f"{name}: shard slice has {len(raw)} bytes, expected "
                f"{n * dt.itemsize} for shape {e['shape']} {dt}")
        out[name] = np.frombuffer(raw, dtype=dt).reshape(e["shape"])
    return out


def read_bundle(variables_prefix: str) -> Dict[str, np.ndarray]:
    """All (non-string) tensors of a checkpoint bundle, keyed by
    checkpoint name.

    ``variables_prefix`` is the path without extensions, e.g.
    ``<savedmodel>/variables/variables``.
    """
    entries = read_index(variables_prefix + ".index")
    keys = [k for k, e in entries.items()
            if k and e.get("dtype") != 7]  # strings = bookkeeping
    return read_tensors(variables_prefix, keys, entries=entries)


# ----------------------------------------------------------------------
# the checkpoint object graph (how named paths map to tensor keys)
# ----------------------------------------------------------------------
OBJECT_GRAPH_KEY = "_CHECKPOINTABLE_OBJECT_GRAPH"


def read_object_graph(variables_prefix: str,
                      entries: Dict[str, Dict[str, Any]] = None,
                      ) -> List[Dict[str, Any]]:
    """The checkpoint's ``TrackableObjectGraph`` as a list of nodes:
    ``{"children": {local_name: node_id},
    "attributes": {attr_name: checkpoint_key}}``.

    The saver dedupes shared variables by storing each tensor under ONE
    canonical key (e.g. an RNN cell kernel lands under ``variables/3``
    rather than ``layer_with_weights-1/cell/kernel/...``), so named
    lookups must resolve through this graph, not by string-joining
    paths."""
    if entries is None:
        entries = read_index(variables_prefix + ".index")
    header = entries.get("", {})
    e = entries.get(OBJECT_GRAPH_KEY)
    if e is None:
        raise ValueError(
            f"{variables_prefix}: checkpoint has no object graph")
    num_shards = header.get("num_shards", 1)
    p = (f"{variables_prefix}.data-{e['shard_id']:05d}-of-"
         f"{num_shards:05d}")
    raw = open(p, "rb").read()[e["offset"]:e["offset"] + e["size"]]
    # DT_STRING tensor encoding: one varint64 length per element, a
    # fixed32 crc32c of the lengths, then the concatenated bytes —
    # the graph is a scalar (1 element)
    ln, i = _varint(raw, 0)
    i += 4  # crc32c of the lengths region
    graph_bytes = raw[i:i + ln]
    nodes: List[Dict[str, Any]] = []
    for field, _, node_buf in pb_fields(graph_bytes):
        if field != 1:  # TrackableObjectGraph.nodes
            continue
        node = {"children": {}, "attributes": {}}
        for f2, _, v2 in pb_fields(node_buf):
            if f2 == 1:  # ObjectReference children
                node_id, local_name = 0, ""
                for f3, _, v3 in pb_fields(v2):
                    if f3 == 1:
                        node_id = v3
                    elif f3 == 2:
                        local_name = v3.decode("utf-8")
                node["children"][local_name] = node_id
            elif f2 == 2:  # SerializedTensor attributes
                attr_name, ckpt_key = "", ""
                for f3, _, v3 in pb_fields(v2):
                    if f3 == 1:
                        attr_name = v3.decode("utf-8")
                    elif f3 == 3:
                        ckpt_key = v3.decode("utf-8")
                node["attributes"][attr_name] = ckpt_key
        nodes.append(node)
    if not nodes:
        raise ValueError(f"{variables_prefix}: empty object graph")
    return nodes


def resolve_variable(nodes: List[Dict[str, Any]], path: str,
                     start: int = 0) -> str:
    """Follow ``path`` ("layer_with_weights-0/cell/kernel") through the
    object graph from node ``start`` and return the variable's
    canonical checkpoint key."""
    node_id = start
    for part in path.split("/"):
        children = nodes[node_id]["children"]
        if part not in children:
            raise KeyError(
                f"object-graph path {path!r}: node {node_id} has no "
                f"child {part!r} (has {sorted(children)})")
        node_id = children[part]
    attrs = nodes[node_id]["attributes"]
    if "VARIABLE_VALUE" not in attrs:
        raise KeyError(
            f"object-graph path {path!r} ends at node {node_id} which "
            f"is not a variable (attributes: {sorted(attrs)})")
    return attrs["VARIABLE_VALUE"]


# ----------------------------------------------------------------------
# keras_metadata.pb — model config extraction
# ----------------------------------------------------------------------
def _untuple(obj: Any) -> Any:
    """tf_keras serializes python tuples as {"class_name": "__tuple__",
    "items": [...]} and tf.TensorShape as {"class_name": "TensorShape",
    "items": [...]}; flatten both back to lists recursively."""
    if isinstance(obj, dict):
        if obj.get("class_name") in ("__tuple__", "TensorShape") \
                and "items" in obj:
            return [_untuple(v) for v in obj["items"]]
        return {k: _untuple(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_untuple(v) for v in obj]
    return obj


def read_saved_model_config(path: str) -> Dict[str, Any]:
    """The Sequential model config (keras dialect, ``__tuple__``
    wrappers removed) from a SavedModel directory's
    ``keras_metadata.pb``."""
    meta_path = os.path.join(path, "keras_metadata.pb")
    if not os.path.exists(meta_path):
        raise ValueError(
            f"{path}: no keras_metadata.pb — not a Keras SavedModel "
            f"(plain tf.Modules have no layer configs to import)")
    data = open(meta_path, "rb").read()
    for field, _, node in pb_fields(data):
        if field != 1:  # SavedMetadata.nodes
            continue
        ident, meta = None, None
        for f2, _, v2 in pb_fields(node):
            if f2 == 4:
                ident = v2.decode("utf-8", "replace")
            elif f2 == 5:
                meta = v2
        if ident in ("_tf_keras_sequential", "_tf_keras_model",
                     "_tf_keras_network") and meta:
            j = json.loads(meta)
            cfg = {"class_name": j.get("class_name"),
                   "config": _untuple(j.get("config", {}))}
            if cfg["class_name"] != "Sequential":
                raise ValueError(
                    f"only Sequential SavedModels are supported, got "
                    f"{cfg['class_name']!r}")
            # tf_keras records the built shape on the metadata node,
            # not inside the Sequential config
            build_shape = _untuple(j.get("build_input_shape"))
            if build_shape and not cfg["config"].get(
                    "build_input_shape"):
                cfg["config"]["build_input_shape"] = build_shape
            return cfg
    raise ValueError(f"{meta_path}: no Keras model node found")
