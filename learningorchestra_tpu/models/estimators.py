"""JAX-native classical estimators.

The reference Builder trains five MLlib classifier families on a Spark
cluster capped at 3 one-core executors
(reference builder_image/builder.py:62-78, docker-compose.yml:157-163).
Here the linear-algebra families run ON the device mesh through the
same sharded engine the neural models use — an MXU matmul per step for
logistic regression, one-hot matmul reductions for Gaussian NB — so a
mesh-parallel Builder (``meshParallel: true``) actually puts the TPU
to work per classifier slice. Tree families stay on host sklearn
(data-dependent branching has no MXU mapping worth forcing).

Both classes speak the sklearn surface the Builder consumes
(``fit(X, y)`` / ``predict`` / ``predict_proba``) plus ``set_mesh``
for sub-slice placement (runtime/mesh.py ``sub_meshes``).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from learningorchestra_tpu.runtime import arena as arena_lib
from learningorchestra_tpu.runtime import data as data_lib
from learningorchestra_tpu.runtime import engine as engine_lib
from learningorchestra_tpu.runtime import mesh as mesh_lib


class LogisticRegressionJAX:
    """Multinomial logistic regression trained by the sharded engine:
    minibatch softmax cross-entropy on the mesh (DP over the batch,
    bf16 matmuls on the MXU), adam updates. The engine gives it
    scan-fit epochs, grad-accum and sharding for free — the same
    machinery as the deep models, at d x C scale."""

    def __init__(self, epochs: int = 12, batch_size: int = 4096,
                 learning_rate: float = 0.05, seed: int = 0):
        self.epochs = int(epochs)
        self.batch_size = int(batch_size)
        self.learning_rate = float(learning_rate)
        self.seed = int(seed)
        self.classes_: Optional[np.ndarray] = None
        self.params: Any = None
        self.history: list = []
        self._mesh_override = None
        # content identity of the upcoming fit's (x, y), set by the
        # builder (feature cache token): enables arena reuse of the
        # staged device arrays and executable sharing across jobs
        self.feature_token = None
        self.feature_tags: tuple = ()

    def set_mesh(self, mesh) -> None:
        self._mesh_override = mesh

    def _mesh(self):
        return self._mesh_override or mesh_lib.current_mesh()

    @staticmethod
    def _apply(params, model_state, batch, train, rng):
        logits = batch["x"] @ params["w"] + params["b"]
        return logits, model_state

    def fit(self, x, y) -> "LogisticRegressionJAX":
        import optax

        x = np.asarray(x, np.float32)
        y = np.asarray(y)
        self.classes_, y_idx = np.unique(y, return_inverse=True)
        n_classes = len(self.classes_)
        mesh = self._mesh()
        eng = engine_lib.Engine(
            apply_fn=self._apply,
            loss_fn=engine_lib.sparse_softmax_loss,
            optimizer=optax.adam(self.learning_rate),
            mesh=mesh,
            metrics={"accuracy": engine_lib.accuracy_metric},
            # apply/loss/metrics are module-static; the optimizer is
            # fully determined by the learning rate — so engines of
            # equal key trace identical programs
            cache_key=("estimators.LR", self.learning_rate))
        d = x.shape[1]
        params = {"w": jnp.zeros((d, n_classes), jnp.float32),
                  "b": jnp.zeros((n_classes,), jnp.float32)}
        state = eng.init_state(params)
        batcher = data_lib.ArrayBatcher(
            {"x": x, "y": y_idx.astype(np.int32)},
            min(self.batch_size, len(x)), shuffle=True, seed=self.seed,
            dp_multiple=mesh_lib.data_parallel_size(mesh),
            cache_token=self.feature_token,
            cache_tags=self.feature_tags)
        state, history = eng.fit(state, batcher, epochs=self.epochs,
                                 seed=self.seed)
        self.params = engine_lib.to_host(state.params)
        self.history = history
        return self

    def _check_fitted(self) -> None:
        if self.params is None:
            raise RuntimeError("not fitted — call fit(X, y) first")

    def decision_function(self, x) -> np.ndarray:
        self._check_fitted()
        x = np.asarray(x, np.float32)
        return x @ self.params["w"] + self.params["b"]

    def predict_proba(self, x) -> np.ndarray:
        z = self.decision_function(x)
        z = z - z.max(axis=1, keepdims=True)
        e = np.exp(z)
        return e / e.sum(axis=1, keepdims=True)

    def predict(self, x) -> np.ndarray:
        return self.classes_[np.argmax(self.decision_function(x), axis=1)]

    def score(self, x, y) -> float:
        return float(np.mean(self.predict(x) == np.asarray(y)))


class GaussianNBJAX:
    """Gaussian naive Bayes as three one-hot matmuls: per-class counts,
    sums and squared sums come from ``onehot.T @ [1, x, x^2]`` — large
    batched contractions the MXU eats, one pass over the data, no
    per-class Python loop."""

    def __init__(self, var_smoothing: float = 1e-9):
        self.var_smoothing = float(var_smoothing)
        self.classes_: Optional[np.ndarray] = None
        self.theta_: Optional[np.ndarray] = None  # (C, d) means
        self.var_: Optional[np.ndarray] = None    # (C, d) variances
        self.class_prior_: Optional[np.ndarray] = None
        self._mesh_override = None
        # content identity of the fit's (x, y) — see
        # LogisticRegressionJAX.feature_token
        self.feature_token = None
        self.feature_tags: tuple = ()

    def set_mesh(self, mesh) -> None:
        self._mesh_override = mesh

    @staticmethod
    @jax.jit
    def _sufficient_stats(x, onehot):
        counts = onehot.sum(axis=0)
        sums = onehot.T @ x
        sq_sums = onehot.T @ (x * x)
        return counts, sums, sq_sums

    def fit(self, x, y) -> "GaussianNBJAX":
        x = np.asarray(x, np.float32)
        y = np.asarray(y)
        self.classes_, y_idx = np.unique(y, return_inverse=True)
        # center on the global per-feature mean (f64, host) before the
        # f32 device reductions: E[x^2]-mean^2 on RAW data cancels
        # catastrophically when |mean| >> std (timestamps, unscaled
        # sensors); on centered data both terms are O(std^2)
        shift = np.mean(x, axis=0, dtype=np.float64).astype(np.float32)
        x_c = x - shift[None, :]
        onehot_np = np.zeros((len(x), len(self.classes_)), np.float32)
        onehot_np[np.arange(len(x)), y_idx] = 1.0
        entry = None
        if self._mesh_override is not None:
            # place the pass on THIS estimator's sub-slice, rows
            # sharded over dp; zero-padded rows have all-zero one-hot
            # so they contribute nothing to any statistic
            mesh = self._mesh_override
            dp = mesh_lib.data_parallel_size(mesh)
            sharding = mesh_lib.batch_sharding(mesh)

            def stage():
                xs, hs = jnp.asarray(x_c), jnp.asarray(onehot_np)
                pad = (-len(x)) % dp
                if pad:
                    xs = jnp.concatenate(
                        [xs, jnp.zeros((pad,) + xs.shape[1:], xs.dtype)])
                    hs = jnp.concatenate(
                        [hs, jnp.zeros((pad, hs.shape[1]), hs.dtype)])
                return {"x": jax.device_put(xs, sharding),
                        "onehot": jax.device_put(hs, sharding)}

            if self.feature_token is not None:
                # centered x + one-hot are deterministic functions of
                # the (x, y) content the token identifies, so a repeat
                # fit reuses the resident device copies
                entry = arena_lib.get_default_arena().get_or_put(
                    ("nb_stats", self.feature_token, mesh), stage,
                    tags=self.feature_tags, group=mesh,
                    group_fraction=mesh_lib.mesh_fraction(mesh))
                xj, onehot = entry.arrays["x"], entry.arrays["onehot"]
            else:
                staged = stage()
                xj, onehot = staged["x"], staged["onehot"]
        else:
            xj, onehot = jnp.asarray(x_c), jnp.asarray(onehot_np)
        try:
            counts, sums, sq_sums = self._sufficient_stats(xj, onehot)
            counts = np.asarray(counts, np.float64)
            sums = np.asarray(sums, np.float64)
            sq_sums = np.asarray(sq_sums, np.float64)
        finally:
            if entry is not None:
                entry.release()
        n = np.maximum(counts, 1.0)[:, None]
        theta_c = sums / n          # class means of CENTERED data
        self.theta_ = theta_c + shift[None, :].astype(np.float64)
        var = sq_sums / n - theta_c ** 2
        eps = self.var_smoothing * float(np.var(x, axis=0).max())
        self.var_ = np.maximum(var, 0.0) + max(eps, 1e-12)
        self.class_prior_ = counts / counts.sum()
        return self

    def _joint_log_likelihood(self, x) -> np.ndarray:
        x = np.asarray(x, np.float32)
        # (n, C): sum_d log N(x_d | theta_cd, var_cd) + log prior_c
        ll = -0.5 * (np.log(2.0 * np.pi * self.var_)[None, :, :]
                     + (x[:, None, :] - self.theta_[None, :, :]) ** 2
                     / self.var_[None, :, :]).sum(axis=2)
        return ll + np.log(self.class_prior_)[None, :]

    def predict(self, x) -> np.ndarray:
        if self.classes_ is None:
            raise RuntimeError("not fitted — call fit(X, y) first")
        return self.classes_[
            np.argmax(self._joint_log_likelihood(x), axis=1)]

    def predict_proba(self, x) -> np.ndarray:
        ll = self._joint_log_likelihood(x)
        ll = ll - ll.max(axis=1, keepdims=True)
        e = np.exp(ll)
        return e / e.sum(axis=1, keepdims=True)

    def score(self, x, y) -> float:
        return float(np.mean(self.predict(x) == np.asarray(y)))
