"""``tensorflow.keras.optimizers`` shim -> optax specs."""

from __future__ import annotations

from typing import Any


class _Optimizer:
    kind = "adam"

    def __init__(self, learning_rate: float = 0.001, **kwargs: Any):
        self.spec = {"kind": self.kind, "learning_rate": learning_rate}
        for key in ("beta_1", "beta_2", "momentum", "nesterov", "rho",
                    "weight_decay"):
            if key in kwargs:
                self.spec[key] = kwargs[key]


class Adam(_Optimizer):
    kind = "adam"


class AdamW(_Optimizer):
    kind = "adamw"


class SGD(_Optimizer):
    kind = "sgd"


class RMSprop(_Optimizer):
    kind = "rmsprop"


class Adagrad(_Optimizer):
    kind = "adagrad"
