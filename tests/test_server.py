"""REST API tests over real HTTP: the reference's URI contract,
async-201 + finished-poll, universal reads, observe long-poll.

(Test strategy per SURVEY §4: golden end-to-end pipeline tests against
the REST API with a live server.)
"""

import csv
import json
import time
import urllib.request
import urllib.error

import numpy as np
import pytest

API = "/api/learningOrchestra/v1"


@pytest.fixture()
def server(tmp_config):
    from learningorchestra_tpu.services.server import RestServer

    srv = RestServer(host="127.0.0.1", port=0).start()
    yield srv
    srv.stop()


def _call(server, method, path, body=None, params=""):
    url = f"{server.base_url}{path}{params}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=120) as resp:
            raw = resp.read()
            ctype = resp.headers.get("Content-Type", "")
            status = resp.status
    except urllib.error.HTTPError as e:
        raw = e.read()
        ctype = e.headers.get("Content-Type", "")
        status = e.code
    if "json" in ctype:
        return status, json.loads(raw)
    return status, raw


def _poll_finished(server, path, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        status, body = _call(server, "GET", path, params="?limit=1")
        assert status == 200, body
        meta = body["metadata"]
        if meta.get("finished"):
            return meta
        time.sleep(0.1)
    raise AssertionError(f"timeout polling {path}")


@pytest.fixture()
def titanic_csv(tmp_path):
    """Titanic-shaped CSV (the reference's flagship demo pipeline,
    BASELINE config 1)."""
    rng = np.random.default_rng(7)
    rows = []
    for i in range(200):
        pclass = int(rng.integers(1, 4))
        sex = rng.choice(["male", "female"])
        age = round(float(rng.uniform(1, 70)), 1)
        fare = round(float(rng.uniform(5, 200)), 2)
        p = 0.8 if sex == "female" else 0.2
        survived = int(rng.random() < p)
        rows.append([i, survived, pclass, sex, age, fare])
    path = tmp_path / "titanic.csv"
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["pid", "survived", "pclass", "sex", "age", "fare"])
        w.writerows(rows)
    return path


def test_health(server):
    status, body = _call(server, "GET", "/health")
    assert status == 200
    assert body["status"] == "ok"
    assert body.get("deviceCount", 0) >= 1


def test_unknown_route(server):
    status, body = _call(server, "GET", f"{API}/nonsense/x")
    assert status == 404


def test_metrics_endpoint(server):
    """Gateway-metrics parity (KrakenD collector, krakend.json:1752):
    request counters by route/status, latency, job and collection
    gauges."""
    _call(server, "GET", "/health")
    _call(server, "GET", f"{API}/dataset/csv")   # listing (200)
    _call(server, "GET", f"{API}/nonsense/x")    # 404
    status, m = _call(server, "GET", "/metrics")
    assert status == 200
    assert m["requestsTotal"] >= 3
    assert m["requestsByRoute"].get("GET dataset", 0) >= 1
    assert m["responsesByStatus"].get("404", 0) >= 1
    assert m["meanDispatchSeconds"] is not None
    assert m["uptimeSeconds"] > 0
    assert "jobsRunning" in m and "collections" in m
    assert "getCache" in m and "meshSecondsByPool" in m
    status, raw = _call(server, "GET", "/metrics",
                        params="?format=prometheus")
    assert status == 200
    text = raw.decode()
    assert "lo_get_cache_hits_total" in text and \
        "lo_mesh_seconds_total" in text


def test_dataset_rest_roundtrip(server, titanic_csv):
    status, body = _call(server, "POST", f"{API}/dataset/csv", {
        "datasetName": "titanic", "datasetURI": str(titanic_csv)})
    assert status == 201
    assert body["result"] == f"{API}/dataset/csv/titanic"
    meta = _poll_finished(server, body["result"])
    assert meta["rows"] == 200
    assert "survived" in meta["fields"]

    # paged + queried reads
    status, body = _call(server, "GET", f"{API}/dataset/csv/titanic",
                         params="?skip=1&limit=2")
    assert status == 200 and len(body["result"]) == 2
    q = json.dumps({"sex": "female"})
    status, body = _call(
        server, "GET", f"{API}/dataset/csv/titanic",
        params=f"?limit=5&query={urllib.request.quote(q)}")
    assert all(r["sex"] == "female" for r in body["result"])

    # listing by type
    status, body = _call(server, "GET", f"{API}/dataset/csv")
    assert any(m["name"] == "titanic" for m in body["result"])

    # duplicate -> 409
    status, _ = _call(server, "POST", f"{API}/dataset/csv", {
        "datasetName": "titanic", "datasetURI": str(titanic_csv)})
    assert status == 409


def test_titanic_pipeline_over_rest(server, titanic_csv):
    """Dataset -> Function(feature prep) -> Model -> Train -> Evaluate
    -> Predict, entirely through the REST API (reference north-star
    call stack, SURVEY §3.3; BASELINE config 1)."""
    status, body = _call(server, "POST", f"{API}/dataset/csv", {
        "datasetName": "titanic", "datasetURI": str(titanic_csv)})
    assert status == 201
    _poll_finished(server, body["result"])

    prep = (
        "import numpy as np\n"
        "df = titanic\n"
        "x = np.stack([df['pclass'].to_numpy(float),"
        " (df['sex']=='female').to_numpy(float),"
        " df['age'].to_numpy(float)/80.0,"
        " df['fare'].to_numpy(float)/250.0], axis=1)\n"
        "y = df['survived'].to_numpy('int64')\n"
        "response = {'x': x, 'y': y}\n"
    )
    status, body = _call(server, "POST", f"{API}/function/python", {
        "name": "prep", "function": prep,
        "functionParameters": {"titanic": "$titanic"}})
    assert status == 201
    _poll_finished(server, body["result"])

    status, body = _call(server, "POST", f"{API}/model/scikitlearn", {
        "modelName": "lr", "modulePath": "sklearn.linear_model",
        "class": "LogisticRegression",
        "classParameters": {"max_iter": 500}})
    assert status == 201
    _poll_finished(server, body["result"])

    status, body = _call(server, "POST", f"{API}/train/scikitlearn", {
        "name": "lr_t", "modelName": "lr", "method": "fit",
        "methodParameters": {"X": "$prep.x", "y": "$prep.y"}})
    assert status == 201
    _poll_finished(server, body["result"])

    status, body = _call(server, "POST", f"{API}/evaluate/scikitlearn", {
        "name": "lr_e", "modelName": "lr_t", "method": "score",
        "methodParameters": {"X": "$prep.x", "y": "$prep.y"}})
    assert status == 201
    _poll_finished(server, body["result"])
    status, body = _call(server, "GET", f"{API}/evaluate/scikitlearn/lr_e")
    results = [d["result"] for d in body["result"] if "result" in d]
    assert results and results[0] > 0.7

    status, body = _call(server, "POST", f"{API}/predict/scikitlearn", {
        "name": "lr_p", "modelName": "lr_t", "method": "predict",
        "methodParameters": {"X": "$prep.x"}})
    assert status == 201
    _poll_finished(server, body["result"])

    # PATCH re-run with same parent (reference PATCH semantics)
    status, body = _call(server, "PATCH", f"{API}/predict/scikitlearn/lr_p",
                         {"methodParameters": {"X": "$prep.x"}})
    assert status == 200
    _poll_finished(server, f"{API}/predict/scikitlearn/lr_p")

    # DELETE
    status, _ = _call(server, "DELETE", f"{API}/predict/scikitlearn/lr_p")
    assert status == 200
    status, _ = _call(server, "GET", f"{API}/predict/scikitlearn/lr_p")
    assert status == 404


def test_transform_explore_histogram_over_rest(server, titanic_csv):
    status, body = _call(server, "POST", f"{API}/dataset/csv", {
        "datasetName": "t2", "datasetURI": str(titanic_csv)})
    _poll_finished(server, body["result"])

    # projection
    status, body = _call(server, "POST", f"{API}/transform/projection", {
        "inputDatasetName": "t2", "outputDatasetName": "t2_small",
        "names": ["age", "fare"]})
    assert status == 201
    _poll_finished(server, f"{API}/transform/projection/t2_small")

    # histogram
    status, body = _call(server, "POST", f"{API}/explore/histogram", {
        "inputDatasetName": "t2", "outputDatasetName": "t2_hist",
        "names": ["survived"]})
    assert status == 201
    _poll_finished(server, f"{API}/explore/histogram/t2_hist")
    status, body = _call(server, "GET", f"{API}/explore/histogram/t2_hist")
    hist = next(d for d in body["result"] if "survived" in d)
    assert sum(b["count"] for b in hist["survived"]) == 200

    # dataType: survived int -> string
    status, body = _call(server, "POST", f"{API}/transform/dataType", {
        "datasetName": "t2_small", "types": {"age": "string"}})
    assert status == 200
    _poll_finished(server, f"{API}/transform/dataType/t2_small")

    # explore plot (PNG)
    status, body = _call(server, "POST", f"{API}/explore/scikitlearn", {
        "name": "pca2", "modulePath": "sklearn.decomposition",
        "class": "PCA", "classParameters": {"n_components": 2},
        "method": "fit_transform",
        "methodParameters": {"X": "$proj_xy"}})
    assert status == 201
    # stage the numeric matrix it needs, then re-run via PATCH
    # (cheaper than a second function step)
    ctx = server.api.ctx
    df = ctx.catalog.read_dataframe("t2", columns=["age", "fare"])
    ctx.artifacts.save(df.to_numpy(), "proj_xy", "function/python")
    ctx.catalog.create_collection("proj_xy", "function/python")
    ctx.catalog.mark_finished("proj_xy")
    status, _ = _call(server, "PATCH", f"{API}/explore/scikitlearn/pca2",
                      {})
    _poll_finished(server, f"{API}/explore/scikitlearn/pca2")
    status, png = _call(server, "GET", f"{API}/explore/scikitlearn/pca2")
    assert status == 200 and isinstance(png, bytes)
    assert png[:8] == b"\x89PNG\r\n\x1a\n"


def test_builder_over_rest(server, titanic_csv):
    for ds in ("btr", "bte"):
        status, body = _call(server, "POST", f"{API}/dataset/csv", {
            "datasetName": ds, "datasetURI": str(titanic_csv)})
        _poll_finished(server, body["result"])
    code = (
        "import numpy as np\n"
        "def feats(df):\n"
        "    return np.stack([df['pclass'].to_numpy(float),"
        " (df['sex']=='female').to_numpy(float)], axis=1)\n"
        "features_training = (feats(training_df),"
        " training_df['survived'].to_numpy('int64'))\n"
        "features_evaluation = features_training\n"
        "features_testing = feats(testing_df)\n"
    )
    status, body = _call(server, "POST", f"{API}/builder/sparkml", {
        "trainDatasetName": "btr", "testDatasetName": "bte",
        "modelingCode": code, "classifiersList": ["LR", "NB"]})
    assert status == 201
    assert len(body["result"]) == 2
    for uri in body["result"]:
        meta = _poll_finished(server, uri)
        assert meta["accuracy"] > 0.6
        status, rows = _call(server, "GET", uri, params="?skip=1&limit=3")
        assert any("prediction" in r for r in rows["result"])


def test_observe_long_poll(server, titanic_csv):
    import threading

    status, body = _call(server, "GET", f"{API}/observe")
    seq0 = body["result"]["seq"]
    results = {}

    def watcher():
        results["resp"] = _call(
            server, "GET", f"{API}/observe/obs_ds",
            params=f"?seq={seq0}&timeout=30")

    t = threading.Thread(target=watcher)
    t.start()
    time.sleep(0.2)
    _call(server, "POST", f"{API}/dataset/csv", {
        "datasetName": "obs_ds", "datasetURI": str(titanic_csv)})
    t.join(timeout=40)
    assert not t.is_alive()
    status, body = results["resp"]
    assert status == 200
    changes = body["result"]["changes"]
    assert changes and all(c["collection"] == "obs_ds" for c in changes)


def test_tune_grid_search_pipeline(server):
    """/model creates a GridSearch over a $model ref; /tune fit runs
    trial-parallel over mesh sub-slices; results readable via GET."""
    st, body = _call(server, "POST", f"{API}/function/python", body={
        "name": "tune_data", "functionParameters": {},
        "function": ("import numpy as np\n"
                     "rng = np.random.default_rng(0)\n"
                     "x = rng.normal(size=(48, 8)).astype(np.float32)\n"
                     "y = (x[:, 0] > 0).astype(np.int32)\n"
                     "x[:, 1] = y * 2.0\n"
                     "response = {'x': x, 'y': y}\n")})
    assert st == 201, body
    _poll_finished(server, f"{API}/function/python/tune_data")

    st, body = _call(server, "POST", f"{API}/model/tensorflow", body={
        "modelName": "tune_base",
        "modulePath": "learningorchestra_tpu.models",
        "class": "NeuralModel",
        "classParameters": {"layer_configs": [
            {"kind": "dense", "units": 8, "activation": "relu"},
            {"kind": "dense", "units": 2, "activation": "softmax"}]}})
    assert st == 201, body
    _poll_finished(server, f"{API}/model/tensorflow/tune_base")

    st, body = _call(server, "POST", f"{API}/model/tensorflow", body={
        "modelName": "tune_sweep",
        "modulePath": "learningorchestra_tpu.models",
        "class": "GridSearch",
        "classParameters": {"estimator": "$tune_base",
                            "param_grid": {"learning_rate": [0.0001, 0.05]},
                            "validation_split": 0.25}})
    assert st == 201, body
    _poll_finished(server, f"{API}/model/tensorflow/tune_sweep")

    st, body = _call(server, "POST", f"{API}/tune/tensorflow", body={
        "name": "tune_run", "modelName": "tune_sweep", "method": "fit",
        "methodParameters": {"x": "$tune_data.x", "y": "$tune_data.y",
                             "epochs": 4, "batch_size": 8}})
    assert st == 201, body
    meta = _poll_finished(server, f"{API}/tune/tensorflow/tune_run",
                          timeout=300)
    assert meta["finished"]


def _resnet_transfer_tune(server, tmp_path, stage_sizes,
                          learning_rates=(1e-3, 1e-4)):
    """BASELINE config 5 end-to-end: a pretrained ResNet-50 (weights
    loaded from a real npz export, not silent random init) created by
    module path through /model, then a learning-rate sweep through
    /tune — the reference's transfer-learn + GridSearchCV flow.
    ``stage_sizes`` shrinks the bottleneck stages for the fast run
    (same architecture family, ~10x cheaper compile on the CPU test
    backend); the fast run also sweeps ONE learning rate (each trial
    pays a full compile; multi-trial tune mechanics are covered by
    test_tune_grid_search_pipeline on a cheap model)."""
    import os

    from learningorchestra_tpu.models.tf_compat.keras import applications

    # "pretrained" artifact: an exported ResNet-50 weight file
    pre = applications.ResNet50(classes=3, input_shape=(32, 32, 3),
                                stage_sizes=stage_sizes)
    pre._build_params(np.zeros((1, 32, 32, 3), np.float32))
    weights_path = os.path.join(tmp_path, "resnet50_pretrained.npz")
    pre.save_weights(weights_path)

    st, body = _call(server, "POST", f"{API}/function/python", body={
        "name": "rn_data", "functionParameters": {},
        "function": ("import numpy as np\n"
                     "rng = np.random.default_rng(0)\n"
                     "x = rng.normal(size=(12, 32, 32, 3))"
                     ".astype(np.float32)\n"
                     "y = rng.integers(0, 3, size=12).astype(np.int32)\n"
                     "response = {'x': x, 'y': y}\n")})
    assert st == 201, body
    _poll_finished(server, f"{API}/function/python/rn_data")

    st, body = _call(server, "POST", f"{API}/model/tensorflow", body={
        "modelName": "rn_model",
        "modulePath": "tensorflow.keras.applications",
        "class": "ResNet50",
        "classParameters": {"classes": 3, "weights": weights_path,
                            "input_shape": [32, 32, 3],
                            **({"stage_sizes": stage_sizes}
                               if stage_sizes else {})}})
    assert st == 201, body
    _poll_finished(server, f"{API}/model/tensorflow/rn_model", timeout=300)

    st, body = _call(server, "POST", f"{API}/model/tensorflow", body={
        "modelName": "rn_sweep",
        "modulePath": "learningorchestra_tpu.models",
        "class": "GridSearch",
        "classParameters": {"estimator": "$rn_model",
                            "param_grid": {
                                "learning_rate": list(learning_rates)},
                            "validation_split": 0.25}})
    assert st == 201, body
    _poll_finished(server, f"{API}/model/tensorflow/rn_sweep")

    st, body = _call(server, "POST", f"{API}/tune/tensorflow", body={
        "name": "rn_tune", "modelName": "rn_sweep", "method": "fit",
        "methodParameters": {"x": "$rn_data.x", "y": "$rn_data.y",
                             "epochs": 1, "batch_size": 4}})
    assert st == 201, body
    meta = _poll_finished(server, f"{API}/tune/tensorflow/rn_tune",
                          timeout=900)
    assert meta["finished"]
    sweep = server.api.ctx.artifacts.load("rn_tune", "tune/tensorflow")
    assert sweep.best_params_ is not None
    assert len(sweep.cv_results_["params"]) == len(learning_rates)


def test_resnet_transfer_tune_pipeline_fast(server, tmp_path):
    """Shrunken-stages variant ([1, 1, 1, 1] bottlenecks, one sweep
    trial) — the whole REST transfer+tune flow at a fraction of the
    compile cost."""
    _resnet_transfer_tune(server, tmp_path, [1, 1, 1, 1],
                          learning_rates=(1e-3,))


@pytest.mark.slow
def test_resnet50_transfer_tune_pipeline(server, tmp_path):
    """Full-size ResNet-50 (stages 3/4/6/3) — run with ``-m slow``."""
    _resnet_transfer_tune(server, tmp_path, None)


def test_generate_through_predict_verb(server):
    """Token generation is reachable through the reference's generic
    call-method-X-on-stored-object-Y contract: POST /predict with
    method="generate" runs the KV-cache decode loop and the sampled
    ids surface in the execution documents via the universal GET."""
    st, body = _call(server, "POST", f"{API}/function/python", body={
        "name": "gen_data", "functionParameters": {},
        "function": ("import numpy as np\n"
                     "response = {'x': ((np.arange(32*12)"
                     ".reshape(32,12)*7) % 31 + 1).astype('int32')}\n")})
    assert st == 201, body
    _poll_finished(server, f"{API}/function/python/gen_data")
    st, body = _call(server, "POST", f"{API}/model/tensorflow", body={
        "modelName": "gen_lm",
        "modulePath": "learningorchestra_tpu.models",
        "class": "LanguageModel",
        "classParameters": {"vocab_size": 32, "d_model": 16,
                            "n_layers": 1, "n_heads": 2, "max_len": 12,
                            "attention": "dot"}})
    assert st == 201, body
    _poll_finished(server, f"{API}/model/tensorflow/gen_lm")
    st, body = _call(server, "POST", f"{API}/train/tensorflow", body={
        "name": "gen_train", "modelName": "gen_lm", "method": "fit",
        "methodParameters": {"x": "$gen_data.x", "epochs": 1,
                             "batch_size": 16}})
    assert st == 201, body
    _poll_finished(server, f"{API}/train/tensorflow/gen_train",
                   timeout=300)

    st, body = _call(server, "POST", f"{API}/predict/tensorflow", body={
        "name": "gen_out", "modelName": "gen_train",
        "method": "generate",
        "methodParameters": {"prompt": [[1, 2, 3]],
                             "max_new_tokens": 5}})
    assert st == 201, body
    _poll_finished(server, f"{API}/predict/tensorflow/gen_out",
                   timeout=300)
    st, body = _call(server, "GET", f"{API}/predict/tensorflow/gen_out",
                     params="?skip=0&limit=20")
    results = [d["result"] for d in body["result"] if d.get("result")]
    assert results, body
    tokens = results[-1][0]
    assert tokens[:3] == [1, 2, 3] and len(tokens) == 8


def test_train_checkpoint_and_patch_resume(server):
    """checkpoint: true saves per-epoch orbax steps under the execution
    name; PATCH re-runs the same execution and resumes from them."""
    import os

    st, body = _call(server, "POST", f"{API}/function/python", body={
        "name": "ck_data", "functionParameters": {},
        "function": ("import numpy as np\n"
                     "rng = np.random.default_rng(0)\n"
                     "x = rng.normal(size=(32, 8)).astype(np.float32)\n"
                     "y = (x[:, 0] > 0).astype(np.int32)\n"
                     "response = {'x': x, 'y': y}\n")})
    assert st == 201, body
    _poll_finished(server, f"{API}/function/python/ck_data")

    st, body = _call(server, "POST", f"{API}/model/tensorflow", body={
        "modelName": "ck_model",
        "modulePath": "learningorchestra_tpu.models",
        "class": "NeuralModel",
        "classParameters": {"layer_configs": [
            {"kind": "dense", "units": 4, "activation": "relu"},
            {"kind": "dense", "units": 2, "activation": "softmax"}]}})
    assert st == 201, body
    _poll_finished(server, f"{API}/model/tensorflow/ck_model")

    st, body = _call(server, "POST", f"{API}/train/tensorflow", body={
        "name": "ck_train", "modelName": "ck_model", "method": "fit",
        "methodParameters": {"x": "$ck_data.x", "y": "$ck_data.y",
                             "epochs": 2, "batch_size": 8,
                             "checkpoint": True}})
    assert st == 201, body
    _poll_finished(server, f"{API}/train/tensorflow/ck_train")

    ckpt_dir = os.path.join(server.api.ctx.config.checkpoints_dir,
                            "ck_train")
    assert os.path.isdir(ckpt_dir) and os.listdir(ckpt_dir)

    from learningorchestra_tpu.runtime.checkpoint import Checkpointer

    ck = Checkpointer(ckpt_dir)
    assert ck.latest_step() == 8  # 2 epochs x 4 steps
    ck.close()

    st, body = _call(server, "PATCH", f"{API}/train/tensorflow/ck_train",
                     body={"methodParameters": {
                         "x": "$ck_data.x", "y": "$ck_data.y",
                         "epochs": 3, "batch_size": 8,
                         "checkpoint": True}})
    assert st == 200, body
    _poll_finished(server, f"{API}/train/tensorflow/ck_train")
    # resumed from step 8 with a TOTAL budget of 3 epochs: 2 already
    # done, so exactly one more epoch runs -> step 12 (a restart from
    # scratch would have left the latest checkpoint at 4; the old
    # overshoot bug would have trained 3 more epochs -> step 20)
    ck = Checkpointer(ckpt_dir)
    assert ck.latest_step() == 12
    ck.close()


def test_profile_trace_capture(server):
    """POST /profile start/stop captures a jax.profiler trace."""
    import jax.numpy as jnp

    st, body = _call(server, "POST", f"{API}/profile",
                     body={"action": "start"})
    assert st == 201, body
    # give the profiler something to record
    (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready()
    st, body = _call(server, "POST", f"{API}/profile",
                     body={"action": "stop"})
    assert st == 200, body
    assert body["files"] > 0
    st, body = _call(server, "GET", f"{API}/profile")
    assert st == 200 and len(body["traces"]) == 1
    # double-stop is a client error, not a crash
    st, body = _call(server, "POST", f"{API}/profile",
                     body={"action": "stop"})
    assert st == 406


def test_metrics_prometheus_exposition(server):
    status, _ = _call(server, "GET", "/health")
    assert status == 200
    import urllib.request
    with urllib.request.urlopen(
            f"{server.base_url}/metrics?format=prometheus") as r:
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/plain")
        text = r.read().decode()
    assert "lo_uptime_seconds" in text
    assert 'lo_requests_total{route=' in text
    assert "lo_jobs_running" in text
    # every sample line is "name{labels} value" or "name value"
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        assert len(line.rsplit(" ", 1)) == 2, line


def test_savedmodel_import_through_model_service(server, tmp_path):
    """The reference's primary artifact flow over REST: a stock
    tf.keras SavedModel DIRECTORY imported by module path through
    POST /model (``tensorflow.keras.models.load_model`` resolves to
    the tf_compat shim, which reads the bundle with zero tensorflow
    imports), then served for prediction."""
    tfk = pytest.importorskip("tf_keras")
    kl = tfk.layers

    km = tfk.Sequential([
        kl.Dense(6, activation="relu", input_shape=(4,)),
        kl.Dense(2, activation="softmax")])
    x = np.random.default_rng(9).normal(size=(5, 4)).astype(np.float32)
    want = np.asarray(km(x))
    sm_dir = str(tmp_path / "sm_dir")
    km.save(sm_dir, save_format="tf")

    st, body = _call(server, "POST", f"{API}/model/tensorflow", body={
        "modelName": "smi",
        "modulePath": "tensorflow.keras.models",
        "class": "load_model",
        "classParameters": {"path": sm_dir}})
    assert st == 201, body
    _poll_finished(server, f"{API}/model/tensorflow/smi")

    st, body = _call(server, "POST", f"{API}/predict/tensorflow", body={
        "name": "smi_pred", "modelName": "smi", "method": "predict",
        "methodParameters": {"x": x.tolist(), "batch_size": 5}})
    assert st == 201, body
    _poll_finished(server, f"{API}/predict/tensorflow/smi_pred")
    got = np.asarray(server.api.ctx.artifacts.load(
        "smi_pred", "predict/tensorflow"))
    np.testing.assert_allclose(got, want, atol=2e-2)  # bf16 default
