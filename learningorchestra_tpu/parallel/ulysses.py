"""Ulysses-style sequence parallelism: all-to-all head scatter.

The alternative SP strategy (SURVEY §2.4): instead of rotating KV
around a ring, re-shard with two ``all_to_all``s — gather the full
sequence while scattering heads, run ordinary full attention on
``heads / sp`` local heads, then reverse. Communication volume is
O(seq·hidden / sp) per all-to-all (cheaper than ring for moderate
sequences; ring wins when seq >> devices·heads or memory forbids
materializing full seq).

Used inside ``shard_map``; :func:`ulysses_attention_sharded` is the
pjit-level wrapper.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from learningorchestra_tpu.parallel import ring as ring_lib
from learningorchestra_tpu.runtime import mesh as mesh_lib


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = mesh_lib.SP,
                      causal: bool = False, window: int = 0,
                      scale: Optional[float] = None,
                      attn_fn: Optional[Callable] = None) -> jax.Array:
    """Inside shard_map: q local shard (b, seq_local, heads, d); k/v
    may carry FEWER (kv) heads (GQA) — both head counts must divide
    the axis size, and the head scatter then moves kv-width K/V
    (n-fold less all_to_all traffic than repeating first). Returns
    the local output shard (b, seq_local, heads, d)."""
    n = lax.psum(1, axis_name)
    h, kvh = q.shape[2], k.shape[2]
    if h % n:
        raise ValueError(f"heads {h} not divisible by sp={n}")
    if kvh != h and (h % kvh or kvh % n):
        raise ValueError(
            f"GQA kv heads {kvh} must divide query heads {h} and be "
            f"divisible by sp={n} (repeat K/V to full heads "
            f"otherwise)")
    if attn_fn is None:
        if jax.default_backend() == "tpu":
            # local attention over the gathered sequence runs the
            # fused flash kernel — O(block) memory for the full-seq
            # score rows instead of a dense (s, s) tile per head;
            # grouped K/V consumed natively
            from learningorchestra_tpu.ops import attention as attn_ops

            attn_fn = functools.partial(attn_ops.flash_attention,
                                        causal=causal, scale=scale,
                                        window=window)
        else:
            def attn_fn(ql, kl, vl):
                if kl.shape[2] != ql.shape[2]:
                    g = ql.shape[2] // kl.shape[2]
                    kl = jnp.repeat(kl, g, axis=2)
                    vl = jnp.repeat(vl, g, axis=2)
                return ring_lib.full_attention_reference(
                    ql, kl, vl, causal=causal, window=window,
                    scale=scale)

    def scatter_heads(x):  # (b, s/n, h, d) -> (b, s, h/n, d)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def gather_heads(x):  # (b, s, h/n, d) -> (b, s/n, h, d)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    out = attn_fn(scatter_heads(q), scatter_heads(k), scatter_heads(v))
    return gather_heads(out)


def ulysses_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                              mesh: Mesh, causal: bool = False,
                              window: int = 0,
                              scale: Optional[float] = None) -> jax.Array:
    if mesh_lib.SP not in mesh.axis_names:
        raise ValueError("mesh has no 'sp' axis")
    data = mesh_lib.data_axes(mesh)
    spec = P(data if data else None, mesh_lib.SP, None, None)
    fn = mesh_lib.shard_map(
        functools.partial(ulysses_attention, axis_name=mesh_lib.SP,
                          causal=causal, scale=scale, window=window),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
