"""Pipeline parallelism (GPipe schedule) over the ``pp`` axis.

Layer stages live on different devices; microbatches flow through the
ring of stages with activations handed to the next stage by
``ppermute`` each tick. The schedule is the classic GPipe fill/drain:
``M + n_stages - 1`` ticks for M microbatches, bubble fraction
``(n-1)/(M+n-1)``. Every device runs the same jitted tick body (SPMD —
no MPMD program needed); invalid bubble ticks compute on garbage and
are masked out of the result, which keeps control flow static for XLA.

Stage parameters are stacked on a leading ``n_stages`` dim and sharded
over ``pp``, so each device holds exactly its stage's weights.
Activation shapes must be uniform across stage boundaries (wrap
embed/head layers outside the pipelined middle, transformer-style).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from learningorchestra_tpu.runtime import mesh as mesh_lib


def pipeline_apply_local(stage_fn: Callable[[Any, jax.Array], jax.Array],
                         stage_params: Any, x: jax.Array,
                         num_microbatches: int,
                         axis_name: str = mesh_lib.PP) -> jax.Array:
    """Inside shard_map: ``stage_params`` leaves are (1, ...) local
    stage shards; ``x`` is the local batch (replicated over pp).
    Returns the pipelined ``stage_{n-1}(...stage_0(x))``, replicated.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    params = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    m = num_microbatches
    if x.shape[0] % m:
        raise ValueError(f"batch {x.shape[0]} not divisible by "
                         f"microbatches {m}")
    micro = x.reshape(m, x.shape[0] // m, *x.shape[1:])

    def tick(carry, t):
        inp_buf, out_buf = carry
        mb = lax.dynamic_index_in_dim(
            micro, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
        inp = jnp.where(idx == 0, mb, inp_buf)
        y = stage_fn(params, inp)
        out_mb = t - (n - 1)
        write = (idx == n - 1) & (out_mb >= 0) & (out_mb < m)
        slot = jnp.clip(out_mb, 0, m - 1)
        old = lax.dynamic_index_in_dim(out_buf, slot, axis=0,
                                       keepdims=False)
        out_buf = lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(write, y, old), slot, axis=0)
        nxt = lax.ppermute(y, axis_name, _forward_perm(n))
        return (nxt, out_buf), None

    # scan carries become pp-varying (each stage computes different
    # values), so the initial values must be cast varying too
    zero = lax.pcast(jnp.zeros_like(micro[0]), axis_name, to="varying")
    out0 = lax.pcast(jnp.zeros_like(micro), axis_name, to="varying")
    (_, out), _ = lax.scan(tick, (zero, out0),
                           jnp.arange(m + _static_size(n) - 1))
    # only the last stage holds real outputs; replicate via masked psum
    out = lax.psum(jnp.where(idx == n - 1, out, 0.0), axis_name)
    return out.reshape(x.shape[0], *out.shape[2:])


def _static_size(n) -> int:
    """lax.psum(1, axis) inside shard_map is a traced value in some
    versions; the scan length must be static. shard_map guarantees the
    axis size is known at trace time via the abstract mesh."""
    try:
        return int(n)
    except Exception:  # noqa: BLE001 — fall back to concrete int carrier
        raise ValueError("pipeline axis size must be static")


def _forward_perm(n) -> list:
    size = _static_size(n)
    return [(i, i + 1) for i in range(size - 1)]


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any, x: jax.Array, mesh: Mesh,
                   num_microbatches: int = 4) -> jax.Array:
    """pjit-level entry. ``stage_params`` leaves are stacked
    (n_stages, ...) and get sharded over ``pp``; ``x`` is the global
    batch, sharded over the data axes and replicated over ``pp``."""
    if mesh_lib.PP not in mesh.axis_names:
        raise ValueError("mesh has no 'pp' axis")
    data = mesh_lib.data_axes(mesh)
    xspec = P(data if data else None)
    pspec = jax.tree_util.tree_map(
        lambda p: P(*((mesh_lib.PP,) + (None,) * (p.ndim - 1))),
        stage_params)
    fn = jax.shard_map(
        functools.partial(pipeline_apply_local, stage_fn,
                          num_microbatches=num_microbatches,
                          axis_name=mesh_lib.PP),
        mesh=mesh, in_specs=(pspec, xspec), out_specs=xspec)
    return fn(stage_params, x)
