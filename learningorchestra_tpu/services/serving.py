"""Resident serving plane: continuous-batched LM decode and
shape-bucketed predict behind long-lived serving leases.

The batch path (``POST /model/train`` then poll) pays catalog writes,
job scheduling, artifact (re)loads and a mesh gang-acquire on EVERY
request. A serving session pays them ONCE: the fitted model stays
resident (params pinned in the HBM arena), the slice is held under a
``ServingLease`` (services/scheduler.py) that periodically yields to
batch gang jobs, and requests flow through an admission-controlled
bounded queue straight into compiled kernels.

Two session kinds (docs/SERVING.md):

- :class:`LMServingSession` — iteration-level continuous batching
  (Orca-style): a fixed-width slot cache decodes every in-flight
  request one token per step; requests join at any token boundary via
  a per-length prefill scattered into their slot and leave the moment
  they finish. Slot reuse never recompiles (the slot index is a traced
  argument), and each slot's token stream is bit-identical to decoding
  that request alone through ``LanguageModel.generate`` (tested).
- :class:`BucketServingSession` — shape-bucketed micro-batching for
  classifiers/estimators: a burst of n queued requests pads to the
  smallest precompiled bucket >= n and runs ONE ``predict`` call, so
  warm predicts never retrace and per-request latency is amortized.

Admission control: a full queue rejects with 429 (back off + retry), a
closed/tearing-down session with 503. p50/p99 latency per session is
exported through ``/metrics``.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Deque, Dict, List, Optional

import numpy as np

from learningorchestra_tpu.observability import export as obs_export
from learningorchestra_tpu.observability import hist as obs_hist
from learningorchestra_tpu.observability import perf as obs_perf
from learningorchestra_tpu.observability import trace as obs_trace
from learningorchestra_tpu.observability import xray as obs_xray
from learningorchestra_tpu.services import faults
from learningorchestra_tpu.services import validators as V
from learningorchestra_tpu.services.scheduler import ServingLease

_IDLE_TICK_SECONDS = 0.05  # lease-yield poll cadence when no traffic


class LatencyTracker:
    """Ring buffer of request latencies -> p50/p99 snapshot. Bounded
    (last 2048 requests) so a long-lived session's metrics reflect
    current behavior, not its lifetime average."""

    def __init__(self, maxlen: int = 2048):
        self._lat: Deque[float] = collections.deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self.count = 0

    def record(self, seconds: float) -> None:
        with self._lock:
            self._lat.append(seconds)
            self.count += 1

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            lat = sorted(self._lat)
            count = self.count
        if not lat:
            return {"count": 0, "p50Ms": 0.0, "p99Ms": 0.0}
        p50 = lat[int(0.50 * (len(lat) - 1))]
        p99 = lat[int(0.99 * (len(lat) - 1))]
        return {"count": count, "p50Ms": round(p50 * 1e3, 3),
                "p99Ms": round(p99 * 1e3, 3)}


class _Request:
    __slots__ = ("payload", "event", "result", "error", "queued_at",
                 "trace_id", "popped_at", "stages", "finished_at")

    def __init__(self, payload: Dict[str, Any]):
        self.payload = payload
        self.event = threading.Event()
        self.result: Optional[Dict[str, Any]] = None
        self.error: Optional[V.HttpError] = None
        self.queued_at = time.monotonic()
        # observability marks: the worker thread appends completed
        # (name, start, end, attrs) stage intervals; the client thread
        # replays them into a span tree after the response arrives
        self.trace_id = ""
        self.popped_at = 0.0
        self.stages: List[Any] = []
        self.finished_at = 0.0

    def finish(self, result: Dict[str, Any]) -> None:
        self.result = result
        self.finished_at = time.monotonic()
        self.event.set()

    def fail(self, error: V.HttpError) -> None:
        self.error = error
        self.finished_at = time.monotonic()
        self.event.set()


class _SessionBase:
    """Queue + worker-thread + lease skeleton shared by both session
    kinds. Subclasses implement :meth:`_serve_once` (drain some queued
    work, return True if anything was done)."""

    kind = "base"

    def __init__(self, name: str, ctx, lease: ServingLease):
        self.name = name
        self._ctx = ctx
        self._lease = lease
        self._queue: Deque[_Request] = collections.deque()
        self._depth = int(ctx.config.serve_queue_depth)
        self._cv = threading.Condition()
        self._closed = False
        self.latency = LatencyTracker()
        self.requests_total = 0
        self.rejected_total = 0
        self.created_at = time.time()
        self._thread = threading.Thread(
            target=self._run, name=f"serving-{name}", daemon=True)

    def start(self) -> None:
        self._thread.start()

    # -- request side --------------------------------------------------
    def submit(self, payload: Dict[str, Any],
               timeout: Optional[float] = None) -> Dict[str, Any]:
        req = _Request(payload)
        with self._cv:
            if self._closed:
                raise V.HttpError(V.HTTP_UNAVAILABLE,
                                  f"serving session {self.name} is "
                                  f"shutting down")
            if len(self._queue) >= self._depth:
                self.rejected_total += 1
                raise V.HttpError(
                    V.HTTP_TOO_MANY_REQUESTS,
                    f"serving queue full ({self._depth} requests "
                    f"queued) — retry with backoff")
            self.requests_total += 1
            req.trace_id = f"serve/{self.name}/{self.requests_total}"
            self._queue.append(req)
            self._cv.notify_all()
        if timeout is None:
            # 0 = no gateway deadline configured -> wait indefinitely
            # (the client's socket timeout still bounds the call)
            timeout = self._ctx.config.request_timeout_seconds or None
        if not req.event.wait(timeout):
            self._trace_request(req, time.monotonic(), error="timeout")
            raise V.HttpError(V.HTTP_UNAVAILABLE,
                              f"request timed out after {timeout}s "
                              f"(session overloaded or preempted)")
        if req.error is not None:
            self._trace_request(req, time.monotonic(),
                                error=type(req.error).__name__)
            raise req.error
        now = time.monotonic()
        elapsed = now - req.queued_at
        self.latency.record(elapsed)
        obs_hist.observe("lo_serving_request_seconds", elapsed)
        self._trace_request(req, now)
        assert req.result is not None
        return req.result

    def _trace_request(self, req: _Request, end: float,
                       error: Optional[str] = None) -> None:
        """Retro-build the request's span tree (``admit → queueWait →
        stage… → respond``) under its own trace id. The batcher thread
        only knows stage boundaries after the fact, so it stashes
        (name, start, end, attrs) marks on the request and the client
        thread replays them here once the response lands."""
        try:
            attrs: Dict[str, Any] = {"model": self.name,
                                     "kind": self.kind}
            if error is not None:
                attrs["error"] = error
            root = obs_trace.add("request", req.trace_id,
                                 req.queued_at, end, **attrs)
            if root is None:
                return
            picked = req.popped_at or min(
                (s[1] for s in req.stages), default=end)
            obs_trace.add("queueWait", req.trace_id, req.queued_at,
                          min(picked, end), parent=root)
            for name, start, stop, st_attrs in req.stages:
                obs_trace.add(name, req.trace_id, start, stop,
                              parent=root, **st_attrs)
            if req.finished_at:
                obs_trace.add("respond", req.trace_id,
                              req.finished_at, end, parent=root)
        except Exception:  # noqa: BLE001 — observability is advisory
            pass

    # -- worker side ---------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cv:
                if self._closed:
                    break
                if not self._have_work():
                    self._cv.wait(timeout=_IDLE_TICK_SECONDS)
                    if self._closed:
                        break
            try:
                # yield the slice to waiting batch gang jobs between
                # iterations (and on every idle tick) — this is the
                # no-deadlock guarantee: a gang acquire needs EVERY
                # device free, and a preempt-policy session never
                # holds its grant across a contended boundary
                if self._lease.maybe_yield():
                    self._on_reacquired()
                if self._have_work():
                    # chaos site (latency mode inflates request
                    # latency for the SLO watchdog's servingP99
                    # alert); gated on queued work so idle ticks
                    # don't burn a count-budgeted fault spec
                    faults.maybe_inject("serving_step")
                self._serve_once()
            except Exception as exc:  # noqa: BLE001 — fail requests, not the thread
                self._fail_all(V.HttpError(
                    V.HTTP_UNAVAILABLE, f"serving step failed: {exc}"))

    def _have_work(self) -> bool:
        return bool(self._queue)

    def _serve_once(self) -> bool:
        raise NotImplementedError

    def _on_reacquired(self) -> None:
        """Hook after a lease yield/re-acquire cycle (re-pin params)."""

    def _fail_all(self, error: V.HttpError) -> None:
        with self._cv:
            pending = list(self._queue)
            self._queue.clear()
        for req in pending:
            req.fail(error)

    def close(self) -> None:
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=30.0)
        self._fail_all(V.HttpError(
            V.HTTP_UNAVAILABLE,
            f"serving session {self.name} was deleted"))
        self._lease.release()

    def _batch_fill(self) -> Optional[float]:
        """Fraction of the compiled batch the last iteration actually
        used (slot occupancy / bucket fill), for the cluster monitor;
        None before any batch formed."""
        return None

    def _n_chips(self) -> int:
        """Chips under the session's current grant (falls back to the
        process device count) — the per-chip denominator for goodput."""
        try:
            grant = getattr(self._lease, "_grant", None)
            devices = getattr(grant, "devices", None)
            if devices:
                return max(1, len(devices))
        except Exception:  # noqa: BLE001
            pass
        import jax

        return max(1, jax.device_count())

    def perf_stats(self) -> Dict[str, Any]:
        """Goodput/roofline block for the session (observability/perf);
        empty until the first served iteration."""
        return {}

    def stats(self) -> Dict[str, Any]:
        with self._cv:
            depth = len(self._queue)
        out = {
            "model": self.name,
            "kind": self.kind,
            "queueDepth": depth,
            "queueBound": self._depth,
            "batchFill": self._batch_fill(),
            "requestsTotal": self.requests_total,
            "rejectedTotal": self.rejected_total,
            "uptimeSeconds": round(time.time() - self.created_at, 3),
            "latency": self.latency.snapshot(),
            "lease": self._lease.stats(),
            "perf": self.perf_stats(),
        }
        return out


class LMServingSession(_SessionBase):
    """Iteration-level continuous batcher over a fixed slot cache.

    Every worker iteration: (1) admit queued requests into free slots
    (per-length prefill, cache scattered into the slot by a traced
    index — no recompile per slot), (2) run ONE compiled ``step`` that
    advances every active slot a token, (3) retire finished requests.
    Per-slot key/position bookkeeping replays the exact schedule
    ``LanguageModel.generate`` uses, so the emitted tokens are
    bit-identical to a solo decode of the same request (tested in
    tests/test_serving.py)."""

    kind = "lm"

    def __init__(self, name: str, ctx, lease: ServingLease, model,
                 slots: int, cache_len: int, temperature: float,
                 top_k: Optional[int], top_p: Optional[float]):
        super().__init__(name, ctx, lease)
        self._model = model
        self.slots = int(slots)
        self.cache_len = int(cache_len)
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        self._step, self._prefill_for, self._join = model.serve_fns(
            self.slots, self.cache_len, self.temperature, top_k, top_p)
        self._cache = model.serve_cache(self.slots, self.cache_len)
        self.tokens_total = 0
        # decode-phase goodput accounting (observability/perf): every
        # compiled step advances ALL slots; only active ones emit a
        # useful token, so goodput = tokens / (steps x slots)
        self.decode_steps = 0
        self.decode_tokens_total = 0
        self._decode_seconds = 0.0
        # analytic decode footprint: each step reads every param and
        # the whole slot KV cache from HBM (the classic reason decode
        # is bandwidth-bound), and costs ~2 flops per param per token
        import jax

        p_leaves = jax.tree_util.tree_leaves(model.params)
        self._param_count = int(sum(a.size for a in p_leaves))
        self._param_bytes = int(sum(a.nbytes for a in p_leaves))
        self._cache_bytes = int(sum(
            a.nbytes for a in jax.tree_util.tree_leaves(self._cache)))
        # host-side slot state (device state is the KV cache)
        self._tok = np.zeros((self.slots, 1), np.int32)
        self._col = np.zeros((self.slots,), np.int32)
        self._keys = np.zeros((self.slots, 2), np.uint32)
        self._slot_req: List[Optional[_Request]] = [None] * self.slots
        self._slot_out: List[List[int]] = [[] for _ in range(self.slots)]
        self._slot_left = np.zeros((self.slots,), np.int64)
        self._slot_t0 = [0.0] * self.slots
        # pin params in the HBM arena for the session's lifetime —
        # tagged with the model name so a retrain invalidates the pin
        self._params_entry = self._pin_params()
        # the slot KV cache is the session's other standing HBM claim
        obs_xray.register("kv-cache", ("kv", self.name, id(self)),
                          self._cache_bytes, name=self.name,
                          slots=self.slots, cacheLen=self.cache_len)

    def _pin_params(self):
        import jax

        from learningorchestra_tpu.runtime import arena as arena_lib

        leaves = jax.tree_util.tree_leaves(self._model.params)
        flat = {f"leaf{i}": a for i, a in enumerate(leaves)}
        key = ("serving", self.name, id(self))
        entry = arena_lib.get_default_arena().get_or_put(
            key, lambda: flat, tags=(self.name,))
        # re-tag the pin in the X-ray ledger: these bytes are THIS
        # session's resident params, not anonymous arena residency
        # (the arena's own registration would double-count them)
        obs_xray.release("arena", key)
        obs_xray.register("serving-params", key, entry.nbytes,
                          name=self.name)
        return entry

    def _on_reacquired(self) -> None:
        # the slice changed hands while we were yielded: re-pin so
        # arena residency accounting follows the live grant
        self._params_entry.release()
        self._params_entry = self._pin_params()

    def _have_work(self) -> bool:
        return bool(self._queue) or any(
            r is not None for r in self._slot_req)

    def validate_request(self, payload: Dict[str, Any]) -> None:
        prompt = payload.get("prompt")
        if not isinstance(prompt, (list, tuple)) or not prompt or \
                not all(isinstance(t, int) and not isinstance(t, bool)
                        for t in prompt):
            raise V.HttpError(
                V.HTTP_NOT_ACCEPTABLE,
                f"{V.MESSAGE_INVALID_FIELD}: prompt must be a non-empty "
                f"list of token ids")
        new = V.valid_positive_int(payload.get("maxNewTokens"),
                                   "maxNewTokens", default=32)
        if new >= self.cache_len:
            raise V.HttpError(
                V.HTTP_NOT_ACCEPTABLE,
                f"{V.MESSAGE_INVALID_FIELD}: maxNewTokens={new} leaves "
                f"no prompt room in cacheLen={self.cache_len}")
        seed = payload.get("seed", 0)
        if isinstance(seed, bool) or not isinstance(seed, int):
            raise V.HttpError(
                V.HTTP_NOT_ACCEPTABLE,
                f"{V.MESSAGE_INVALID_FIELD}: seed must be an integer, "
                f"got {seed!r}")

    def _admit(self, slot: int, req: _Request) -> None:
        import jax.numpy as jnp
        import jax.random as jr

        admit_t0 = time.monotonic()
        payload = req.payload
        prompt = list(payload["prompt"])
        new = int(payload.get("maxNewTokens") or 32)
        seed = int(payload.get("seed", 0))
        # same sliding-window truncation generate() applies, bounded
        # by the session cache instead of max_len
        keep = self.cache_len - new
        if len(prompt) > keep:
            prompt = prompt[-keep:]
        s = len(prompt)
        # generate()'s key schedule: split once for the prefill sample,
        # split again for the decode loop's fold_in base
        key = jr.PRNGKey(seed)
        key, sub_prefill = jr.split(key)
        key, sub_decode = jr.split(key)
        prefill = self._prefill_for(s)
        tokens = jnp.asarray(np.asarray(prompt, np.int32)[None, :])
        nxt, pcache = prefill(self._model.params, tokens, sub_prefill)
        self._cache = self._join(self._cache, pcache, slot)
        req.stages.append(("prefill", admit_t0, time.monotonic(),
                           {"promptTokens": s, "slot": slot}))
        first = int(nxt[0])
        self._slot_req[slot] = req
        self._slot_out[slot] = [first]
        self._slot_left[slot] = new - 1
        self._slot_t0[slot] = time.monotonic()
        self._tok[slot, 0] = first
        self._col[slot] = s  # next step attends positions <= s
        self._keys[slot] = np.asarray(sub_decode)
        self.tokens_total += 1
        if self._slot_left[slot] <= 0:
            self._retire(slot)

    def _retire(self, slot: int) -> None:
        req = self._slot_req[slot]
        self._slot_req[slot] = None
        if req is None:
            return
        tokens = [int(t) for t in self._slot_out[slot]]
        req.stages.append(("decodeIters", self._slot_t0[slot],
                           time.monotonic(), {"tokens": len(tokens)}))
        req.finish({
            "tokens": tokens,
            "decodeSeconds": round(
                time.monotonic() - self._slot_t0[slot], 6),
        })
        self._slot_out[slot] = []

    def _serve_once(self) -> bool:
        import jax.numpy as jnp

        # (1) admit — join at the token boundary, one slot per request
        admitted = False
        while True:
            with self._cv:
                free = [i for i, r in enumerate(self._slot_req)
                        if r is None]
                if not free or not self._queue:
                    break
                req = self._queue.popleft()
            req.popped_at = time.monotonic()
            try:
                self._admit(free[0], req)
                admitted = True
            except V.HttpError as exc:
                req.fail(exc)
            except Exception as exc:  # noqa: BLE001
                req.fail(V.HttpError(V.HTTP_UNAVAILABLE,
                                     f"prefill failed: {exc}"))
        active = [i for i, r in enumerate(self._slot_req)
                  if r is not None]
        if not active:
            return admitted
        # (2) one continuous-batch step: every active slot advances a
        # token; idle slots compute masked garbage that is discarded
        step_t0 = time.monotonic()
        nxt, self._cache = self._step(
            self._model.params, self._cache, jnp.asarray(self._tok),
            jnp.asarray(self._col), jnp.asarray(self._keys))
        nxt = np.asarray(nxt)  # the device sync — step wall time ends here
        self._decode_seconds += time.monotonic() - step_t0
        self.decode_steps += 1
        self.decode_tokens_total += len(active)
        # (3) harvest + retire
        for slot in active:
            tok = int(nxt[slot])
            self._slot_out[slot].append(tok)
            self._slot_left[slot] -= 1
            self.tokens_total += 1
            self._tok[slot, 0] = tok
            self._col[slot] += 1
            if self._slot_left[slot] <= 0 or \
                    self._col[slot] >= self.cache_len - 1:
                self._retire(slot)
        return True

    def close(self) -> None:
        super().close()
        self._params_entry.release()
        obs_xray.release("serving-params",
                         ("serving", self.name, id(self)))
        obs_xray.release("kv-cache", ("kv", self.name, id(self)))

    def _batch_fill(self) -> Optional[float]:
        active = sum(1 for r in self._slot_req if r is not None)
        if not active and not self.tokens_total:
            return None
        return round(active / self.slots, 4)

    def perf_stats(self) -> Dict[str, Any]:
        if not self.decode_steps or self._decode_seconds <= 0:
            return {}
        n = self._n_chips()
        dt = self._decode_seconds
        tps = self.decode_tokens_total / dt
        out: Dict[str, Any] = {
            "decodeSteps": self.decode_steps,
            "decodeTokensPerSec": round(tps, 2),
            "decodeTokensPerSecPerChip": round(tps / n, 3),
            # batch-fill-weighted goodput: the fraction of slot-steps
            # the batcher spent on real tokens vs masked idle lanes
            "goodputFrac": round(
                self.decode_tokens_total /
                (self.decode_steps * self.slots), 4),
        }
        # analytic roofline for decode (XLA cost analysis never ran
        # here): ~2 flops per param per emitted token, and every step
        # streams params + the whole slot KV cache through HBM
        flops_per_step = 2.0 * self._param_count * (
            self.decode_tokens_total / self.decode_steps)
        out.update(obs_perf.roofline(
            flops_per_step,
            float(self._param_bytes + self._cache_bytes),
            self.decode_steps, dt, n))
        return out

    def stats(self) -> Dict[str, Any]:
        out = super().stats()
        out.update({
            "slots": self.slots,
            "activeSlots": sum(1 for r in self._slot_req
                               if r is not None),
            "cacheLen": self.cache_len,
            "tokensTotal": self.tokens_total,
            "temperature": self.temperature,
        })
        return out


class BucketServingSession(_SessionBase):
    """Shape-bucketed micro-batcher for ``predict``-style models.

    Queued requests aggregate for up to ``LO_SERVE_MAX_WAIT_MS`` (or
    until the largest bucket fills), the stacked rows pad to the
    smallest precompiled bucket >= n, and ONE ``predict`` call serves
    the whole burst through the PR-3 executable cache — so a warm
    request never traces, never touches the catalog, and never waits
    on the job queue."""

    kind = "predict"

    def __init__(self, name: str, ctx, lease: ServingLease, instance):
        super().__init__(name, ctx, lease)
        self._instance = instance
        buckets = sorted({int(b) for b in
                          str(ctx.config.serve_buckets).split(",") if b})
        self.buckets = [b for b in buckets if b > 0] or [1]
        self._max_wait = float(ctx.config.serve_max_wait_ms) / 1e3
        self.predicts_total = 0
        self.rows_total = 0
        self._last_fill: Optional[float] = None
        # fill-weighted goodput accounting: useful rows vs padded
        # bucket capacity, and the device time spent producing them
        self._predict_seconds = 0.0
        self._fill_rows_sum = 0
        self._fill_bucket_sum = 0

    def validate_request(self, payload: Dict[str, Any]) -> None:
        x = payload.get("x")
        if not isinstance(x, (list, tuple)) or not x:
            raise V.HttpError(
                V.HTTP_NOT_ACCEPTABLE,
                f"{V.MESSAGE_INVALID_FIELD}: x must be a non-empty "
                f"list of feature rows")

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _serve_once(self) -> bool:
        # gather a burst: first request opens the window, then wait up
        # to max_wait for co-travelers (bounded by the largest bucket)
        limit = self.buckets[-1]
        batch: List[_Request] = []
        rows = 0
        deadline = None
        while True:
            with self._cv:
                while self._queue and rows < limit:
                    req = self._queue.popleft()
                    req.popped_at = time.monotonic()
                    n = len(req.payload["x"])
                    batch.append(req)
                    rows += n
                if not batch:
                    return False
                if rows >= limit:
                    break
                if deadline is None:
                    deadline = time.monotonic() + self._max_wait
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(timeout=remaining)
                if not self._queue:
                    break
        try:
            stacked = np.concatenate(
                [np.asarray(r.payload["x"]) for r in batch], axis=0)
        except ValueError as exc:
            for req in batch:
                req.fail(V.HttpError(
                    V.HTTP_NOT_ACCEPTABLE,
                    f"{V.MESSAGE_INVALID_FIELD}: rows do not stack "
                    f"({exc})"))
            return True
        n = stacked.shape[0]
        bucket = self._bucket_for(n)
        if bucket > n:
            # pad the batch dim with row 0 so the compiled bucket shape
            # is hit exactly; padded rows are sliced off below
            pad = np.repeat(stacked[:1], bucket - n, axis=0)
            stacked = np.concatenate([stacked, pad], axis=0)
        predict_t0 = time.monotonic()
        try:
            out = np.asarray(self._instance.predict(stacked))
        except Exception as exc:  # noqa: BLE001
            for req in batch:
                req.fail(V.HttpError(V.HTTP_UNAVAILABLE,
                                     f"predict failed: {exc}"))
            return True
        predict_t1 = time.monotonic()
        self.predicts_total += 1
        self.rows_total += n
        self._last_fill = round(n / bucket, 4)
        self._predict_seconds += predict_t1 - predict_t0
        self._fill_rows_sum += n
        self._fill_bucket_sum += bucket
        offset = 0
        for req in batch:
            k = len(req.payload["x"])
            req.stages.append(("batchForm", req.popped_at, predict_t0,
                               {"rows": k}))
            req.stages.append(("predict", predict_t0, predict_t1,
                               {"bucket": bucket, "batchRows": n}))
            req.finish({"predictions": out[offset:offset + k].tolist(),
                        "bucket": bucket})
            offset += k
        return True

    def _batch_fill(self) -> Optional[float]:
        return self._last_fill

    def perf_stats(self) -> Dict[str, Any]:
        if not self.predicts_total or self._predict_seconds <= 0:
            return {}
        n = self._n_chips()
        rps = self._fill_rows_sum / self._predict_seconds
        return {
            "predictsTotal": self.predicts_total,
            "rowsPerSec": round(rps, 2),
            "rowsPerSecPerChip": round(rps / n, 3),
            # fill-weighted goodput: useful rows over padded capacity
            "goodputFrac": round(
                self._fill_rows_sum / max(1, self._fill_bucket_sum), 4),
        }

    def stats(self) -> Dict[str, Any]:
        out = super().stats()
        out.update({
            "buckets": self.buckets,
            "predictsTotal": self.predicts_total,
            "rowsTotal": self.rows_total,
        })
        return out


class ServingManager:
    """Session registry + REST verbs (create/predict/stats/delete).

    One session per model name; sessions share the JobManager's
    SliceLease allocator through ``ServingLease`` handles so resident
    serving and batch gang jobs contend in one fair queue."""

    def __init__(self, ctx):
        self._ctx = ctx
        self._sessions: Dict[str, _SessionBase] = {}
        self._lock = threading.Lock()

    # -- verbs ---------------------------------------------------------
    def create(self, model_name: str, body: Dict[str, Any]) -> Dict[str, Any]:
        body = body or {}
        with self._lock:
            if model_name in self._sessions:
                raise V.HttpError(
                    V.HTTP_CONFLICT,
                    f"{V.MESSAGE_DUPLICATE_FILE}: serving session for "
                    f"{model_name} already exists")
        type_string = self._ctx.params.artifact_type(model_name)
        if type_string is None:
            raise V.HttpError(V.HTTP_NOT_FOUND,
                              f"{V.MESSAGE_NONEXISTENT_FILE}: "
                              f"{model_name}")
        instance = self._ctx.artifacts.load(model_name, type_string)
        kind = body.get("type")
        if kind is None:
            kind = "lm" if hasattr(instance, "serve_fns") else "predict"
        if kind not in ("lm", "predict"):
            raise V.HttpError(
                V.HTTP_NOT_ACCEPTABLE,
                f"{V.MESSAGE_INVALID_FIELD}: type must be 'lm' or "
                f"'predict', got {kind!r}")
        footprint = None
        devices = V.valid_slice_devices(body.get(V.SLICE_DEVICES_FIELD))
        if devices is not None:
            footprint = {"devices": devices}
        lease = ServingLease(
            self._ctx.jobs.slice_lease, pool="serving",
            policy=self._ctx.config.serve_lease_policy,
            footprint=footprint)
        lease.acquire()
        try:
            session = self._build_session(model_name, instance, kind,
                                          body, lease)
        except BaseException:
            lease.release()
            raise
        session.start()
        with self._lock:
            if model_name in self._sessions:  # lost a create race
                session.close()
                raise V.HttpError(
                    V.HTTP_CONFLICT,
                    f"{V.MESSAGE_DUPLICATE_FILE}: serving session for "
                    f"{model_name} already exists")
            self._sessions[model_name] = session
        obs_export.log_event("serving", "create", model=model_name,
                             sessionKind=kind)
        return session.stats()

    def _build_session(self, model_name: str, instance: Any, kind: str,
                       body: Dict[str, Any],
                       lease: ServingLease) -> _SessionBase:
        if kind == "lm":
            if not hasattr(instance, "serve_fns"):
                raise V.HttpError(
                    V.HTTP_NOT_ACCEPTABLE,
                    f"{V.MESSAGE_INVALID_FIELD}: {model_name} is not a "
                    f"language model (no decode cache support)")
            slots = V.valid_positive_int(
                body.get("maxSlots"), "maxSlots",
                default=self._ctx.config.serve_max_batch)
            cache_len = V.valid_positive_int(
                body.get("cacheLen"), "cacheLen",
                default=int(instance.max_len))
            cache_len = min(cache_len, int(instance.max_len))
            temperature, top_k, top_p = V.valid_sampling(body)
            if top_k is not None and top_k >= instance.vocab_size:
                top_k = None
            return LMServingSession(
                model_name, self._ctx, lease, instance, slots,
                cache_len, temperature, top_k, top_p)
        if not hasattr(instance, "predict"):
            raise V.HttpError(
                V.HTTP_NOT_ACCEPTABLE,
                f"{V.MESSAGE_INVALID_FIELD}: {model_name} has no "
                f"predict method")
        return BucketServingSession(model_name, self._ctx, lease,
                                    instance)

    def predict(self, model_name: str,
                body: Dict[str, Any]) -> Dict[str, Any]:
        session = self._get(model_name)
        body = body or {}
        session.validate_request(body)
        timeout = V.valid_timeout(body.get(V.TIMEOUT_FIELD))
        return session.submit(body, timeout=timeout)

    def _get(self, model_name: str) -> _SessionBase:
        with self._lock:
            session = self._sessions.get(model_name)
        if session is None:
            raise V.HttpError(
                V.HTTP_NOT_FOUND,
                f"{V.MESSAGE_NONEXISTENT_FILE}: no serving session "
                f"for {model_name}")
        return session

    def session_stats(self, model_name: str) -> Dict[str, Any]:
        return self._get(model_name).stats()

    def list_sessions(self) -> List[Dict[str, Any]]:
        with self._lock:
            sessions = list(self._sessions.values())
        return [s.stats() for s in sessions]

    def delete(self, model_name: str) -> Dict[str, Any]:
        with self._lock:
            session = self._sessions.pop(model_name, None)
        if session is None:
            raise V.HttpError(
                V.HTTP_NOT_FOUND,
                f"{V.MESSAGE_NONEXISTENT_FILE}: no serving session "
                f"for {model_name}")
        final = session.stats()
        session.close()
        final["deleted"] = True
        obs_export.log_event("serving", "delete", model=model_name)
        return final

    # -- observability / lifecycle ------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            sessions = list(self._sessions.values())
        per = [s.stats() for s in sessions]
        out = {
            "sessions": len(per),
            "requestsTotal": sum(p["requestsTotal"] for p in per),
            "rejectedTotal": sum(p["rejectedTotal"] for p in per),
            "tokensTotal": sum(p.get("tokensTotal", 0) for p in per),
            "leaseYields": sum(p["lease"].get("yields", 0)
                               for p in per),
            "bySession": per,
        }
        # fleet goodput roll-up (each session's per-chip rate is
        # already normalized by its own grant)
        perf_blocks = [p.get("perf") or {} for p in per]
        agg = {
            "decodeTokensPerSec": round(sum(
                b.get("decodeTokensPerSec", 0.0)
                for b in perf_blocks), 2),
            "decodeTokensPerSecPerChip": round(sum(
                b.get("decodeTokensPerSecPerChip", 0.0)
                for b in perf_blocks), 3),
            "rowsPerSecPerChip": round(sum(
                b.get("rowsPerSecPerChip", 0.0)
                for b in perf_blocks), 3),
        }
        if any(v for v in agg.values()):
            out["perf"] = agg
        return out

    def perf_report(self, model_name: str) -> Optional[Dict[str, Any]]:
        """Roofline/goodput report for one live session, served by
        ``GET /observability/perf/{name}``; None if no session holds
        the name (the route then falls back to train-job reports)."""
        with self._lock:
            session = self._sessions.get(model_name)
        if session is None:
            return None
        return {
            "kind": "serving",
            "model": model_name,
            "sessionKind": session.kind,
            "batchFill": session._batch_fill(),
            "perf": session.perf_stats(),
        }

    def close(self) -> None:
        with self._lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
        for session in sessions:
            session.close()
