"""Synchronous request validation, with the reference's status codes.

The reference validates every POST against the live library before
accepting the job — importlib for module paths, getattr/getmembers for
classes and methods, ``inspect.signature`` for kwargs
(binary_executor_image/utils.py:138-184, model_image/utils.py:124-159,
database_executor_image/utils.py:151-224) — and maps failures to
409 (duplicate), 406 (invalid input), 404 (nonexistent target)
(binary_executor_image/constants.py:21-25, server.py:145-248).
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, List, Optional, Sequence

from learningorchestra_tpu.catalog import documents as D
from learningorchestra_tpu.catalog.artifacts import _NAME_RE
from learningorchestra_tpu.services import sandbox

HTTP_SUCCESS = 200
HTTP_CREATED = 201
HTTP_CONFLICT = 409
HTTP_NOT_ACCEPTABLE = 406
HTTP_NOT_FOUND = 404
# serving-plane admission control (docs/SERVING.md): 429 = the
# session's bounded request queue is full (back off and retry), 503 =
# the session exists but cannot take traffic right now (still warming,
# or tearing down)
HTTP_TOO_MANY_REQUESTS = 429
HTTP_UNAVAILABLE = 503

MESSAGE_DUPLICATE_FILE = "duplicated name"
MESSAGE_INVALID_NAME = "invalid name"
MESSAGE_INVALID_MODULE_PATH = "invalid module path name"
MESSAGE_INVALID_CLASS = "invalid class name"
MESSAGE_INVALID_CLASS_PARAMETER = "invalid class parameter"
MESSAGE_INVALID_METHOD = "invalid method name"
MESSAGE_INVALID_METHOD_PARAMETER = "invalid method parameter"
MESSAGE_NONEXISTENT_FILE = "nonexistent file"
MESSAGE_UNFINISHED_PARENT = "unfinished parent"
MESSAGE_INVALID_FIELD = "invalid field"
MESSAGE_MISSING_FIELD = "missing required field"


MESSAGE_ANALYSIS_REJECTED = "analysis rejected the request"


class HttpError(Exception):
    def __init__(self, status: int, message: str, findings=None):
        super().__init__(message)
        self.status = status
        self.message = message
        # structured analyzer findings (list of dicts) for the
        # response body, when the rejection came from pre-flight
        self.findings = list(findings) if findings else []


TIMEOUT_FIELD = "timeout"


def valid_timeout(value: Any) -> Optional[float]:
    """Optional per-job deadline request field: a positive number of
    seconds, or None (falls back to ``LO_JOB_TIMEOUT``). Bools are
    rejected explicitly — ``"timeout": true`` is a spec typo, and bool
    is an int subclass."""
    if value is None:
        return None
    if isinstance(value, bool) or not isinstance(value, (int, float)) \
            or value <= 0:
        raise HttpError(
            HTTP_NOT_ACCEPTABLE,
            f"{MESSAGE_INVALID_FIELD}: timeout must be a positive "
            f"number of seconds, got {value!r}")
    return float(value)


SLICE_DEVICES_FIELD = "sliceDevices"


def valid_slice_devices(value: Any):
    """Optional explicit device-footprint request field: a positive
    integer count of mesh devices this job needs (the slice scheduler
    packs it onto a sub-mesh that size), an ELASTIC bounds object
    ``{"min": m, "max": M}`` (the job starts at ``max`` and the
    autoscaler may resize it within the declared bounds,
    docs/SCALING.md "Elastic autoscaling"), or None (footprint comes
    from the preflight estimate, else the job gang-acquires). Returns
    the normalized int / ``{"min", "max"}`` dict (stored on job
    metadata for boot replay)."""
    if value is None:
        return None
    if isinstance(value, dict):
        unknown = set(value) - {"min", "max"}
        if unknown:
            raise HttpError(
                HTTP_NOT_ACCEPTABLE,
                f"{MESSAGE_INVALID_FIELD}: sliceDevices has unknown "
                f"keys {sorted(unknown)} (want {{'min', 'max'}})")
        lo, hi = value.get("min"), value.get("max")
        for name, bound in (("min", lo), ("max", hi)):
            if isinstance(bound, bool) or not isinstance(bound, int) \
                    or bound <= 0:
                raise HttpError(
                    HTTP_NOT_ACCEPTABLE,
                    f"{MESSAGE_INVALID_FIELD}: sliceDevices.{name} must "
                    f"be a positive integer device count, got {bound!r}")
        if lo > hi:
            raise HttpError(
                HTTP_NOT_ACCEPTABLE,
                f"{MESSAGE_INVALID_FIELD}: sliceDevices.min ({lo}) must "
                f"not exceed sliceDevices.max ({hi})")
        return {"min": int(lo), "max": int(hi)}
    if isinstance(value, bool) or not isinstance(value, int) or value <= 0:
        raise HttpError(
            HTTP_NOT_ACCEPTABLE,
            f"{MESSAGE_INVALID_FIELD}: sliceDevices must be a positive "
            f"integer device count or {{'min', 'max'}} bounds object, "
            f"got {value!r}")
    return int(value)


HEALTH_POLICY_FIELD = "healthPolicy"


def valid_health_policy(value: Any) -> Optional[Any]:
    """Optional training-health request field (docs/RELIABILITY.md):
    an action string (``"skip"``/``"rollback"``/``"fail"``/``"off"``)
    or an object ``{"action", "spikeFactor", "emaAlpha",
    "maxRollbacks", "cooldownEpochs"}``. Returns the normalized value
    (stored on job metadata for boot replay); None when absent —
    ``LO_HEALTH_*`` defaults then decide."""
    if value is None:
        return None
    if not isinstance(value, (str, dict)):
        raise HttpError(
            HTTP_NOT_ACCEPTABLE,
            f"{MESSAGE_INVALID_FIELD}: healthPolicy must be an action "
            f"string or object, got {value!r}")
    if isinstance(value, dict):
        unknown = set(value) - {"action", "spikeFactor", "emaAlpha",
                                "maxRollbacks", "cooldownEpochs"}
        if unknown:
            raise HttpError(
                HTTP_NOT_ACCEPTABLE,
                f"{MESSAGE_INVALID_FIELD}: healthPolicy has unknown "
                f"key(s) {sorted(unknown)}")
    from learningorchestra_tpu.runtime import health as health_lib

    try:
        # full range/type validation — the same coercion the engine
        # applies, so a request that validates here never blows up at
        # fit time
        health_lib.coerce_policy(value)
    except (ValueError, TypeError) as exc:
        raise HttpError(
            HTTP_NOT_ACCEPTABLE,
            f"{MESSAGE_INVALID_FIELD}: {exc}") from None
    return value


SCORING_FIELD = "scoring"


def valid_scoring(value: Any) -> Optional[str]:
    """Sweep ``scoring`` class parameter (GridSearch/RandomSearch): a
    metric name the estimator can report. Validated at submit time —
    without this, an unknown name surfaced as a raw KeyError from
    ``_score`` only AFTER every trial had trained. ``"auto"`` and
    ``"loss"`` are the selector modes; the rest are the evaluate()
    metric names."""
    if value is None:
        return None
    from learningorchestra_tpu.models import neural as neural_lib

    allowed = sorted({"auto", "loss"} | set(neural_lib._METRICS))
    if not isinstance(value, str) or value not in allowed:
        raise HttpError(
            HTTP_NOT_ACCEPTABLE,
            f"{MESSAGE_INVALID_FIELD}: scoring must be one of "
            f"{allowed}, got {value!r}")
    return value


def valid_positive_int(value: Any, field: str,
                       default: Optional[int] = None) -> Optional[int]:
    """Serving-session sizing field (maxSlots, maxNewTokens, cacheLen):
    a positive integer, or None → ``default``. Bools rejected (int
    subclass)."""
    if value is None:
        return default
    if isinstance(value, bool) or not isinstance(value, int) or value <= 0:
        raise HttpError(
            HTTP_NOT_ACCEPTABLE,
            f"{MESSAGE_INVALID_FIELD}: {field} must be a positive "
            f"integer, got {value!r}")
    return int(value)


def valid_choice(value: Any, field: str, allowed,
                 default: Optional[str] = None) -> Optional[str]:
    """Closed-enum request field (serving ``kvDtype``/``weights``): one
    of ``allowed``, or None → ``default``. Validated at session create
    so a typo'd dtype is a 406, not a mid-session compile error."""
    if value is None:
        return default
    if not isinstance(value, str) or value not in allowed:
        raise HttpError(
            HTTP_NOT_ACCEPTABLE,
            f"{MESSAGE_INVALID_FIELD}: {field} must be one of "
            f"{sorted(allowed)}, got {value!r}")
    return value


def valid_sampling(body: Dict[str, Any]):
    """Serving-session sampling triple (``temperature``/``topK``/
    ``topP``) — fixed per session so every slot shares one compiled
    step function. Returns the normalized (temperature, top_k, top_p)
    exactly as ``LanguageModel.generate`` would resolve them."""
    temperature = body.get("temperature", 0.0)
    if isinstance(temperature, bool) or \
            not isinstance(temperature, (int, float)):
        raise HttpError(
            HTTP_NOT_ACCEPTABLE,
            f"{MESSAGE_INVALID_FIELD}: temperature must be a number, "
            f"got {temperature!r}")
    top_k = body.get("topK")
    if top_k is not None and (isinstance(top_k, bool)
                              or not isinstance(top_k, int) or top_k < 1):
        raise HttpError(
            HTTP_NOT_ACCEPTABLE,
            f"{MESSAGE_INVALID_FIELD}: topK must be a positive integer, "
            f"got {top_k!r}")
    top_p = body.get("topP")
    if top_p is not None and (isinstance(top_p, bool)
                              or not isinstance(top_p, (int, float))
                              or not 0.0 < float(top_p) <= 1.0):
        raise HttpError(
            HTTP_NOT_ACCEPTABLE,
            f"{MESSAGE_INVALID_FIELD}: topP must be in (0, 1], "
            f"got {top_p!r}")
    if float(temperature) <= 0:
        top_k = top_p = None  # greedy ignores the filters
    if top_p is not None and float(top_p) == 1.0:
        top_p = None
    return float(temperature), top_k, (None if top_p is None
                                       else float(top_p))


def run_preflight(findings) -> list:
    """Gate a request on analyzer findings: raise a 406 carrying the
    full structured finding list if any error-severity finding fired,
    else return ALL findings as dicts for the caller to store on the
    job document (warnings ride along with accepted jobs)."""
    from learningorchestra_tpu import analysis as A

    if A.error_findings(findings):
        summary = A.LintRejected(findings).summary
        raise HttpError(HTTP_NOT_ACCEPTABLE,
                        f"{MESSAGE_ANALYSIS_REJECTED}: {summary}",
                        findings=A.findings_to_dicts(findings))
    return A.findings_to_dicts(findings)


class RequestValidator:
    """One validator instance per ServiceContext (the reference vendors
    a ``UserRequest`` copy per image; SURVEY §2.1 cross-cutting)."""

    def __init__(self, context: "ServiceContext"):  # noqa: F821
        self._ctx = context

    # -- names ----------------------------------------------------------
    def safe_name(self, name: Any) -> str:
        if not isinstance(name, str) or not _NAME_RE.match(name) \
                or ".." in name or "/" in name or "\\" in name:
            raise HttpError(HTTP_NOT_ACCEPTABLE,
                            f"{MESSAGE_INVALID_NAME}: {name!r}")
        return name

    def not_duplicate(self, name: str) -> None:
        if self._ctx.catalog.exists(name):
            raise HttpError(HTTP_CONFLICT,
                            f"{MESSAGE_DUPLICATE_FILE}: {name}")

    def existing(self, name: str) -> Dict[str, Any]:
        meta = self._ctx.catalog.get_metadata(name)
        if meta is None:
            raise HttpError(HTTP_NOT_FOUND,
                            f"{MESSAGE_NONEXISTENT_FILE}: {name}")
        return meta

    def existing_finished(self, name: str,
                          status: int = HTTP_NOT_ACCEPTABLE,
                          ) -> Dict[str, Any]:
        """Parent artifacts must exist and be finished before a
        dependent job is accepted (reference server.py:162-181)."""
        meta = self._ctx.catalog.get_metadata(name)
        if meta is None:
            raise HttpError(status, f"{MESSAGE_NONEXISTENT_FILE}: {name}")
        if not meta.get(D.FINISHED_FIELD, False):
            raise HttpError(status, f"{MESSAGE_UNFINISHED_PARENT}: {name}")
        return meta

    def required_fields(self, body: Dict[str, Any],
                        fields: Sequence[str]) -> None:
        for f in fields:
            if f not in body:
                raise HttpError(HTTP_NOT_ACCEPTABLE,
                                f"{MESSAGE_MISSING_FIELD}: {f}")

    # -- reflection targets --------------------------------------------
    def valid_module(self, module_path: str):
        try:
            return sandbox.resolve_module(module_path)
        except Exception:
            raise HttpError(HTTP_NOT_ACCEPTABLE,
                            f"{MESSAGE_INVALID_MODULE_PATH}: {module_path}")

    def valid_class(self, module_path: str, class_name: str):
        module = self.valid_module(module_path)
        cls = getattr(module, class_name, None)
        if cls is None:
            raise HttpError(HTTP_NOT_ACCEPTABLE,
                            f"{MESSAGE_INVALID_CLASS}: {class_name}")
        return cls

    def valid_class_parameters(self, cls, parameters: Dict[str, Any]) -> None:
        """``inspect.signature(__init__)`` kwargs check (reference
        model_image/utils.py:151-159). DSL-valued strings are checked
        by name only — their resolved type is known only at run time.
        """
        self._check_kwargs(cls.__init__, parameters, skip_first=True,
                           message=MESSAGE_INVALID_CLASS_PARAMETER)

    def valid_method(self, target, method_name: str):
        method = getattr(target, method_name, None)
        if method is None or not callable(method):
            raise HttpError(HTTP_NOT_ACCEPTABLE,
                            f"{MESSAGE_INVALID_METHOD}: {method_name}")
        return method

    def valid_method_parameters(self, target, method_name: str,
                                parameters: Dict[str, Any]) -> None:
        method = getattr(target, method_name)
        self._check_kwargs(method, parameters, skip_first=False,
                           message=MESSAGE_INVALID_METHOD_PARAMETER)

    def _check_kwargs(self, fn, parameters: Optional[Dict[str, Any]],
                      skip_first: bool, message: str) -> None:
        if not parameters:
            return
        try:
            sig = inspect.signature(fn)
        except (TypeError, ValueError):
            return  # C-implemented callables: accept (reference behavior)
        names = list(sig.parameters.keys())
        if skip_first and names and names[0] in ("self", "cls"):
            names = names[1:]
        has_var_kw = any(
            p.kind is inspect.Parameter.VAR_KEYWORD
            for p in sig.parameters.values())
        if has_var_kw:
            return
        for key in parameters:
            if key not in names:
                raise HttpError(
                    HTTP_NOT_ACCEPTABLE,
                    f"{message}: {key} (accepted: {', '.join(names)})")

    # -- dataset fields -------------------------------------------------
    def valid_fields(self, dataset_name: str,
                     fields: Sequence[str]) -> None:
        """Projection/histogram field check against the dataset's
        metadata ``fields`` (reference projection_image/utils.py:103-114).
        """
        meta = self.existing(dataset_name)
        known = meta.get(D.FIELDS_FIELD) or \
            self._ctx.catalog.dataset_fields(dataset_name)
        for f in fields:
            if f not in known:
                raise HttpError(HTTP_NOT_ACCEPTABLE,
                                f"{MESSAGE_INVALID_FIELD}: {f}")
