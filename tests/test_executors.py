"""Executor-layer tests: every service family end-to-end in-process.

Covers the reference's service inventory (SURVEY §2.1): dataset ingest,
model creation, train/evaluate/predict lineage, explore/transform,
function, histogram, projection, dataType, builder — all against a
tmp-dir catalog, no server.
"""

import csv
import os
import time

import numpy as np
import pytest


@pytest.fixture()
def ctx(tmp_config):
    from learningorchestra_tpu.services.context import ServiceContext

    context = ServiceContext(tmp_config)
    yield context
    context.close()


def _write_csv(path, header, rows):
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(header)
        w.writerows(rows)
    return path


@pytest.fixture()
def iris_csv(tmp_path):
    """Small linearly-separable 2-class dataset."""
    rng = np.random.default_rng(0)
    rows = []
    for i in range(120):
        label = i % 2
        base = 1.0 if label else -1.0
        rows.append([round(base + rng.normal(0, 0.3), 4),
                     round(base + rng.normal(0, 0.3), 4),
                     label])
    return _write_csv(tmp_path / "iris.csv", ["f1", "f2", "label"], rows)


def _wait(ctx, name, timeout=60):
    ctx.jobs.wait(name, timeout=timeout)
    meta = ctx.catalog.get_metadata(name)
    assert meta is not None, name
    if not meta.get("finished"):
        docs = ctx.catalog.get_documents(name)
        raise AssertionError(f"job {name} not finished: {docs}")
    return meta


# ----------------------------------------------------------------- dataset
def test_dataset_csv_ingest(ctx, iris_csv):
    from learningorchestra_tpu.services.dataset import DatasetService

    ds = DatasetService(ctx)
    status, body = ds.create(
        {"datasetName": "iris", "datasetURI": str(iris_csv)}, "csv")
    assert status == 201 and "iris" in body["result"]
    meta = _wait(ctx, "iris")
    assert meta["fields"] == ["f1", "f2", "label"]
    assert meta["rows"] == 120
    status, body = ds.read_file("iris", skip=2, limit=3)
    assert status == 200 and len(body["result"]) == 3
    # paged sequence is metadata(_id 0), rows(_id 1..N), exec docs --
    # skip=2 lands on row _id 2 (reference find(skip) semantics)
    assert body["result"][0]["_id"] == 2
    # duplicate name -> 409
    from learningorchestra_tpu.services.validators import HttpError
    with pytest.raises(HttpError) as e:
        ds.create({"datasetName": "iris", "datasetURI": str(iris_csv)},
                  "csv")
    assert e.value.status == 409


def test_dataset_generic_and_delete(ctx, tmp_path):
    from learningorchestra_tpu.services.dataset import DatasetService

    payload = tmp_path / "blob.bin"
    payload.write_bytes(b"\x00\x01hello")
    ds = DatasetService(ctx)
    status, _ = ds.create(
        {"datasetName": "blob", "datasetURI": f"file://{payload}"},
        "generic")
    assert status == 201
    _wait(ctx, "blob")
    assert ctx.artifacts.load("blob", "dataset/generic") == b"\x00\x01hello"
    status, _ = ds.delete_file("blob")
    assert status == 200
    assert ctx.catalog.get_metadata("blob") is None


# ------------------------------------------------------------- model/train
def _ingest(ctx, iris_csv, name="iris"):
    from learningorchestra_tpu.services.dataset import DatasetService

    DatasetService(ctx).create(
        {"datasetName": name, "datasetURI": str(iris_csv)}, "csv")
    _wait(ctx, name)


def test_failed_job_records_exception(ctx, iris_csv):
    """A failing method call leaves finished=False and an exception
    execution document (reference binary_execution.py:160-175)."""
    from learningorchestra_tpu.services.execution import ExecutionService
    from learningorchestra_tpu.services.model_service import ModelService

    _ingest(ctx, iris_csv)
    ms = ModelService(ctx)
    status, _ = ms.create({
        "modelName": "logreg",
        "modulePath": "sklearn.linear_model",
        "class": "LogisticRegression",
        "classParameters": {"max_iter": 200},
    }, "scikitlearn")
    assert status == 201
    _wait(ctx, "logreg")

    ex = ExecutionService(ctx)
    status, _ = ex.create({
        "name": "trained",
        "modelName": "logreg",
        "method": "fit",
        "methodParameters": {"X": "$iris.features", "y": "$iris.label"},
    }, "train", "scikitlearn")
    assert status == 201
    # "$iris.features" indexes into a DataFrame artifact -- not a dict;
    # the job must fail and record it
    ctx.jobs.wait("trained", timeout=60)
    meta = ctx.catalog.get_metadata("trained")
    assert meta["finished"] is False
    docs = ctx.catalog.get_documents("trained")
    assert any(d.get("exception") for d in docs)


def test_sklearn_full_lineage(ctx, iris_csv):
    """Dataset -> model -> train -> evaluate -> predict, the reference's
    north-star call stack (SURVEY §3.3) on the sklearn tool."""
    from learningorchestra_tpu.services.execution import ExecutionService
    from learningorchestra_tpu.services.model_service import ModelService

    _ingest(ctx, iris_csv)
    ModelService(ctx).create({
        "modelName": "m1",
        "modulePath": "sklearn.linear_model",
        "class": "LogisticRegression",
        "classParameters": {"max_iter": 500},
    }, "scikitlearn")
    _wait(ctx, "m1")

    # stage the split arrays as function-produced artifacts
    # (mirrors the reference's tfds-tuple flow, utils.py:328-332)
    df = ctx.catalog.read_dataframe("iris")
    x = df[["f1", "f2"]].to_numpy()
    y = df["label"].to_numpy()
    ctx.artifacts.save(x, "iris_x", "function/python")
    ctx.catalog.create_collection("iris_x", "function/python")
    ctx.catalog.mark_finished("iris_x")
    ctx.artifacts.save(y, "iris_y", "function/python")
    ctx.catalog.create_collection("iris_y", "function/python")
    ctx.catalog.mark_finished("iris_y")

    ex = ExecutionService(ctx)
    ex.create({
        "name": "t1", "modelName": "m1", "method": "fit",
        "methodParameters": {"X": "$iris_x", "y": "$iris_y"},
    }, "train", "scikitlearn")
    _wait(ctx, "t1")
    trained = ctx.artifacts.load("t1", "train/scikitlearn")
    assert hasattr(trained, "coef_")

    ex.create({
        "name": "s1", "modelName": "t1", "method": "score",
        "methodParameters": {"X": "$iris_x", "y": "$iris_y"},
    }, "evaluate", "scikitlearn")
    _wait(ctx, "s1")
    score = ctx.artifacts.load("s1", "evaluate/scikitlearn")
    assert score > 0.9
    # result surfaced in documents for the universal GET
    docs = ctx.catalog.get_documents("s1")
    assert any("result" in d for d in docs)

    ex.create({
        "name": "p1", "modelName": "t1", "method": "predict",
        "methodParameters": {"X": "$iris_x"},
    }, "predict", "scikitlearn")
    _wait(ctx, "p1")
    preds = ctx.artifacts.load("p1", "predict/scikitlearn")
    assert len(preds) == 120


def test_keras_shim_model_lineage(ctx, iris_csv):
    """model/tensorflow -> train/tensorflow through the JAX-backed shim
    (the reference's MNIST-CNN flow shape, BASELINE config 2)."""
    from learningorchestra_tpu.services.execution import ExecutionService
    from learningorchestra_tpu.services.model_service import ModelService

    _ingest(ctx, iris_csv)
    df = ctx.catalog.read_dataframe("iris")
    ctx.artifacts.save(df[["f1", "f2"]].to_numpy().astype("float32"),
                       "ix", "function/python")
    ctx.catalog.create_collection("ix", "function/python")
    ctx.catalog.mark_finished("ix")
    ctx.artifacts.save(df["label"].to_numpy().astype("int32"),
                       "iy", "function/python")
    ctx.catalog.create_collection("iy", "function/python")
    ctx.catalog.mark_finished("iy")

    ModelService(ctx).create({
        "modelName": "net",
        "modulePath": "tensorflow.keras.models",
        "class": "Sequential",
        "classParameters": {"layers": [
            "#tensorflow.keras.layers.Dense(16, activation='relu')",
            "#tensorflow.keras.layers.Dense(2, activation='softmax')",
        ]},
    }, "tensorflow")
    _wait(ctx, "net")

    ex = ExecutionService(ctx)
    ex.create({
        "name": "net_c", "modelName": "net", "method": "compile",
        "methodParameters": {
            "optimizer": "#tensorflow.keras.optimizers.Adam(0.05)",
            "loss": "sparse_categorical_crossentropy",
            "metrics": ["accuracy"]},
    }, "train", "tensorflow")
    _wait(ctx, "net_c")

    ex.create({
        "name": "net_t", "modelName": "net_c", "method": "fit",
        "methodParameters": {"x": "$ix", "y": "$iy", "epochs": 8,
                             "batch_size": 32},
    }, "train", "tensorflow")
    _wait(ctx, "net_t")

    ex.create({
        "name": "net_e", "modelName": "net_t", "method": "evaluate",
        "methodParameters": {"x": "$ix", "y": "$iy"},
    }, "evaluate", "tensorflow")
    _wait(ctx, "net_e")
    result = ctx.artifacts.load("net_e", "evaluate/tensorflow")
    assert result["accuracy"] > 0.85


# -------------------------------------------------------- explore/transform
def test_transform_and_explore(ctx, iris_csv):
    from learningorchestra_tpu.services.database_executor import (
        DatabaseExecutorService)

    _ingest(ctx, iris_csv)
    # stage numeric-only feature matrix for the transform
    df = ctx.catalog.read_dataframe("iris")
    ctx.artifacts.save(df[["f1", "f2"]].to_numpy(), "proj_iris",
                       "function/python")
    ctx.catalog.create_collection("proj_iris", "function/python")
    ctx.catalog.mark_finished("proj_iris")
    svc = DatabaseExecutorService(ctx)
    status, _ = svc.create({
        "name": "scaled",
        "modulePath": "sklearn.preprocessing",
        "class": "StandardScaler",
        "classParameters": {},
        "method": "fit_transform",
        "methodParameters": {"X": "$proj_iris"},
    }, "transform", "scikitlearn")
    assert status == 201
    _wait(ctx, "scaled")
    arr = ctx.artifacts.load("scaled", "transform/scikitlearn")
    assert abs(float(np.mean(arr))) < 1e-6

    status, _ = svc.create({
        "name": "pca_plot",
        "modulePath": "sklearn.decomposition",
        "class": "PCA",
        "classParameters": {"n_components": 2},
        "method": "fit_transform",
        "methodParameters": {"X": "$proj_iris"},
    }, "explore", "scikitlearn")
    _wait(ctx, "pca_plot")
    png, content_type = svc.image_response("pca_plot")
    assert content_type == "image/png"
    assert png[:8] == b"\x89PNG\r\n\x1a\n"


# ----------------------------------------------------------------- function
def test_function_service(ctx, iris_csv):
    from learningorchestra_tpu.services.function_service import (
        FunctionService)

    _ingest(ctx, iris_csv)
    fs = FunctionService(ctx)
    code = (
        "print('rows', len(iris))\n"
        "import numpy as np\n"
        "x = iris[['f1','f2']].to_numpy(dtype='float32')\n"
        "y = iris['label'].to_numpy(dtype='int32')\n"
        "response = {'x': x, 'y': y}\n"
    )
    status, _ = fs.create({
        "name": "split",
        "function": code,
        "functionParameters": {"iris": "$iris"},
    })
    assert status == 201
    _wait(ctx, "split")
    stored = ctx.artifacts.load("split", "function/python")
    assert stored["x"].shape == (120, 2)
    docs = ctx.catalog.get_documents("split")
    assert any("rows 120" in (d.get("functionMessage") or "")
               for d in docs)
    # $split.x indexing (the reference's $name.X DSL)
    resolved = ctx.params.resolve_value("$split.x")
    assert resolved.shape == (120, 2)


def test_function_sandbox_blocks_os(ctx, tmp_config):
    import dataclasses

    from learningorchestra_tpu.services import validators as V
    from learningorchestra_tpu.services.context import ServiceContext
    from learningorchestra_tpu.services.function_service import (
        FunctionService)

    body = {"name": "evil",
            "function": "import os\nresponse = os.listdir('/')",
            "functionParameters": {}}
    # layer 1: the pre-flight lint refuses the import at submit time
    with pytest.raises(V.HttpError) as exc:
        FunctionService(ctx).create(dict(body))
    assert exc.value.status == V.HTTP_NOT_ACCEPTABLE
    assert ctx.catalog.get_metadata("evil") is None
    # layer 2: with pre-flight off (reference submit-blind behavior)
    # the runtime jail still kills the job with ImportError
    from learningorchestra_tpu import config as config_mod

    blind_cfg = dataclasses.replace(tmp_config, preflight=False)
    config_mod.set_config(blind_cfg)  # sandbox lint hook reads global
    blind = ServiceContext(blind_cfg)
    try:
        FunctionService(blind).create(dict(body))
        blind.jobs.wait("evil", timeout=30)
        meta = blind.catalog.get_metadata("evil")
        assert meta["finished"] is False
        docs = blind.catalog.get_documents("evil")
        assert any("ImportError" in (d.get("exception") or "")
                   for d in docs)
    finally:
        blind.close()
        config_mod.set_config(tmp_config)


# ------------------------------------------------- histogram/projection/dt
def test_histogram(ctx, iris_csv):
    from learningorchestra_tpu.services.columnar import HistogramService

    _ingest(ctx, iris_csv)
    hs = HistogramService(ctx)
    status, _ = hs.create({
        "inputDatasetName": "iris", "outputDatasetName": "iris_hist",
        "names": ["label"]})
    assert status == 201
    _wait(ctx, "iris_hist")
    docs = ctx.catalog.get_documents("iris_hist")
    hist_doc = next(d for d in docs if "label" in d)
    counts = {b["_id"]: b["count"] for b in hist_doc["label"]}
    assert counts == {0: 60, 1: 60}


def test_projection(ctx, iris_csv):
    from learningorchestra_tpu.services.columnar import ProjectionService

    _ingest(ctx, iris_csv)
    ps = ProjectionService(ctx)
    status, _ = ps.create({
        "inputDatasetName": "iris", "outputDatasetName": "iris_f1",
        "names": ["f1"]})
    assert status == 201
    meta = _wait(ctx, "iris_f1")
    assert meta["fields"] == ["f1"]
    rows = ctx.catalog.read_rows("iris_f1", limit=2)
    assert set(rows[0].keys()) == {"f1", "_id"}
    # unknown field -> 406
    from learningorchestra_tpu.services.validators import HttpError
    with pytest.raises(HttpError) as e:
        ps.create({"inputDatasetName": "iris",
                   "outputDatasetName": "bad", "names": ["nope"]})
    assert e.value.status == 406


def test_datatype(ctx, tmp_path):
    from learningorchestra_tpu.services.columnar import DataTypeService

    _ingest(ctx, _write_csv(
        tmp_path / "mix.csv", ["a", "b"],
        [["1", "x"], ["2", "y"], ["", "z"]]), name="mix")
    # pyarrow infers a as int64 already (with null); force to string
    dts = DataTypeService(ctx)
    status, _ = dts.create({"datasetName": "mix",
                            "types": {"a": "string"}})
    assert status == 200
    _wait(ctx, "mix")
    rows = ctx.catalog.read_rows("mix")
    assert all(isinstance(r["a"], str) for r in rows)
    # and back to number: "" -> None, ints stay ints
    dts.create({"datasetName": "mix", "types": {"a": "number"}})
    _wait(ctx, "mix")
    rows = ctx.catalog.read_rows("mix")
    values = [r["a"] for r in rows]
    assert values[0] == 1 and values[1] == 2
    assert values[2] is None


# ------------------------------------------------------------------ builder
def test_builder_pipeline(ctx, iris_csv, tmp_path):
    from learningorchestra_tpu.services.builder_service import BuilderService

    _ingest(ctx, iris_csv, name="tr")
    _ingest(ctx, iris_csv, name="te")
    bs = BuilderService(ctx)
    code = (
        "features_training = (training_df[['f1','f2']].to_numpy(),"
        " training_df['label'].to_numpy())\n"
        "features_evaluation = features_training\n"
        "features_testing = testing_df[['f1','f2']].to_numpy()\n"
    )
    status, body = bs.create({
        "trainDatasetName": "tr", "testDatasetName": "te",
        "modelingCode": code, "classifiersList": ["LR", "DT", "NB"]})
    assert status == 201
    assert len(body["result"]) == 3
    ctx.jobs.wait("teLR", timeout=120)
    for c in ("LR", "DT", "NB"):
        meta = ctx.catalog.get_metadata(f"te{c}")
        assert meta["finished"], c
        assert meta["accuracy"] > 0.8
        assert meta["fitTime"] > 0
        rows = ctx.catalog.read_rows(f"te{c}", limit=3)
        assert "prediction" in rows[0]
    # invalid classifier name -> 406
    from learningorchestra_tpu.services.validators import HttpError
    with pytest.raises(HttpError) as e:
        bs.create({"trainDatasetName": "tr", "testDatasetName": "te",
                   "modelingCode": code, "classifiersList": ["XX"]})
    assert e.value.status == 406
