"""Model service: instantiate a tool class and persist the live object.

Reference parity (model_image/): ``POST /defaultModel?type=model/
{tensorflow,scikitlearn}`` with ``modelName``, ``description``,
``modulePath``, ``class``, ``classParameters`` (constants.py:2-9,
server.py:23-64) — validates module/class/ctor kwargs synchronously,
then on a worker thread resolves the ``$``/``#`` parameter DSL,
instantiates, and stores the instance as the root of every later
train/tune lineage (model.py:112-162). PATCH re-instantiates with new
``classParameters`` (server.py:66-107).

TPU-native notes: ``modulePath: "tensorflow.keras.*"`` resolves to the
JAX-backed keras shim (models/tf_compat) so the stored object is a
:class:`~learningorchestra_tpu.models.neural.NeuralModel` handle —
a mesh-sharded jit engine, not a TF graph. scikit-learn paths load the
real sklearn class (CPU-side, exactly as the reference runs it).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from learningorchestra_tpu import analysis as A
from learningorchestra_tpu.catalog import documents as D
from learningorchestra_tpu.services import validators as V

MODEL_NAME_FIELD = "modelName"
ANALYSIS_FIELD = "analysis"
DESCRIPTION_FIELD = "description"
MODULE_PATH_FIELD = "modulePath"
CLASS_FIELD = "class"
CLASS_PARAMETERS_FIELD = "classParameters"


def _valid_sweep_scoring(cls, class_parameters: Dict[str, Any]) -> None:
    """Submit-time 406 for an unknown sweep ``scoring`` metric —
    without it the name only failed in ``_score`` after every trial
    had already trained."""
    try:
        from learningorchestra_tpu.models.sweep import GridSearch
    except Exception:
        return
    if isinstance(cls, type) and issubclass(cls, GridSearch):
        V.valid_scoring(class_parameters.get(V.SCORING_FIELD))


class ModelService:
    def __init__(self, context):
        self._ctx = context
        self._validator = V.RequestValidator(context)

    def create(self, body: Dict[str, Any], tool: str,
               ) -> Tuple[int, Dict[str, Any]]:
        self._validator.required_fields(
            body, [MODEL_NAME_FIELD, MODULE_PATH_FIELD, CLASS_FIELD,
                   CLASS_PARAMETERS_FIELD])
        name = self._validator.safe_name(body[MODEL_NAME_FIELD])
        module_path = body[MODULE_PATH_FIELD]
        class_name = body[CLASS_FIELD]
        class_parameters = body[CLASS_PARAMETERS_FIELD] or {}
        description = body.get(DESCRIPTION_FIELD, "")
        self._validator.not_duplicate(name)
        cls = self._validator.valid_class(module_path, class_name)
        self._validator.valid_class_parameters(cls, class_parameters)
        _valid_sweep_scoring(cls, class_parameters)
        analysis = self._preflight(module_path, class_name,
                                   class_parameters)
        type_string = D.normalize_type(f"model/{tool}")
        extra = {
            D.MODULE_PATH_FIELD: module_path,
            D.CLASS_FIELD: class_name,
            D.CLASS_PARAMETERS_FIELD: class_parameters,
            D.DESCRIPTION_FIELD: description,
        }
        if analysis:
            extra[ANALYSIS_FIELD] = analysis
        self._ctx.catalog.create_collection(name, type_string, extra)
        self._submit(name, type_string, cls, class_parameters, description)
        return V.HTTP_CREATED, {
            "result": f"/api/learningOrchestra/v1/model/{tool}/{name}"}

    def update(self, name: str, body: Dict[str, Any], tool: str,
               ) -> Tuple[int, Dict[str, Any]]:
        meta = self._validator.existing(name)
        class_parameters = body.get(
            CLASS_PARAMETERS_FIELD, meta.get(D.CLASS_PARAMETERS_FIELD)) or {}
        description = body.get(DESCRIPTION_FIELD, "")
        cls = self._validator.valid_class(
            meta[D.MODULE_PATH_FIELD], meta[D.CLASS_FIELD])
        self._validator.valid_class_parameters(cls, class_parameters)
        _valid_sweep_scoring(cls, class_parameters)
        analysis = self._preflight(meta[D.MODULE_PATH_FIELD],
                                   meta[D.CLASS_FIELD], class_parameters)
        type_string = meta[D.TYPE_FIELD]
        self._ctx.catalog.update_metadata(
            name, {D.CLASS_PARAMETERS_FIELD: class_parameters,
                   ANALYSIS_FIELD: analysis,
                   D.FINISHED_FIELD: False})
        self._submit(name, type_string, cls, class_parameters, description)
        return V.HTTP_SUCCESS, {
            "result": f"/api/learningOrchestra/v1/model/{tool}/{name}"}

    def delete(self, name: str, tool: str) -> Tuple[int, Dict[str, Any]]:
        meta = self._validator.existing(name)
        self._ctx.catalog.delete_collection(name)
        self._ctx.artifacts.delete(name, meta.get(D.TYPE_FIELD))
        return V.HTTP_SUCCESS, {"result": f"deleted model {name}"}

    # ------------------------------------------------------------------
    def _preflight(self, module_path, class_name, class_parameters) -> list:
        """Pre-flight the spec (406 on provable failure); returns the
        advisory findings to store on the document."""
        if not self._ctx.config.preflight:
            return []
        findings = A.check_model(module_path, class_name,
                                 class_parameters,
                                 mode=self._ctx.config.sandbox_mode)
        return V.run_preflight(findings)

    def _submit(self, name: str, type_string: str, cls,
                class_parameters: Dict[str, Any], description: str) -> None:
        def run():
            treated = self._ctx.params.treat(class_parameters)
            instance = cls(**treated)
            self._ctx.artifacts.save(instance, name, type_string)
            return instance

        self._ctx.jobs.submit(
            name, run, description=description,
            parameters=class_parameters,
            needs_mesh=type_string.endswith(("/tensorflow", "/jax")),
            pool=type_string.split("/", 1)[0])
