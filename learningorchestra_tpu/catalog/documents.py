"""Document schemas and field names.

The reference keeps a reserved document ``_id: 0`` per collection as
metadata/lineage (binary_executor_image/utils.py:73-97,
projection_image/utils.py:10-30) and appends execution documents with
incrementing ``_id`` per re-run (utils.py:112-136). We preserve the
exact field vocabulary so API responses are shape-compatible, but make
creation/update atomic (the reference allocates execution ids with a
read-max-then-insert race, utils.py:116-131 — fixed here by doing it
in one SQL transaction).
"""

from __future__ import annotations

import datetime
from typing import Any, Dict, List, Optional

# --- field names (reference binary_executor_image/constants.py:1-79) ---
ID = "_id"
METADATA_ID = 0

TYPE_FIELD = "type"
NAME_FIELD = "name"
FINISHED_FIELD = "finished"
TIME_CREATED_FIELD = "timeCreated"
PARENT_NAME_FIELD = "parentName"
PARENT_DATASET_NAME_FIELD = "parentDatasetName"
MODULE_PATH_FIELD = "modulePath"
CLASS_FIELD = "class"
CLASS_PARAMETERS_FIELD = "classParameters"
METHOD_FIELD = "method"
METHOD_PARAMETERS_FIELD = "methodParameters"
FIELDS_FIELD = "fields"
DESCRIPTION_FIELD = "description"
EXCEPTION_FIELD = "exception"
FUNCTION_FIELD = "function"
FUNCTION_PARAMETERS_FIELD = "functionParameters"
FUNCTION_MESSAGE_FIELD = "functionMessage"

# --- job lifecycle (beyond the reference: its only job state is the
# boolean ``finished`` flag, binary_execution.py:118-175 — clients
# cannot tell running from stuck from dead. The metadata ``status``
# field narrates queued -> running -> terminal; see docs/LIFECYCLE.md)
STATUS_FIELD = "status"
PROGRESS_FIELD = "progress"
STATUS_QUEUED = "queued"
STATUS_RUNNING = "running"
STATUS_FINISHED = "finished"
STATUS_TIMED_OUT = "timedOut"
STATUS_CANCELLED = "cancelled"
STATUS_STALLED = "stalled"
STATUS_DEAD_LETTERED = "deadLettered"
STATUS_SHUTDOWN_ABORTED = "shutdownAborted"
STATUS_WORKER_LOST = "workerLost"

# --- artifact type strings (reference constants.py:41-76 + krakend routes) ---
DATASET_CSV_TYPE = "dataset/csv"
DATASET_GENERIC_TYPE = "dataset/generic"
MODEL_TENSORFLOW_TYPE = "model/tensorflow"
MODEL_SCIKITLEARN_TYPE = "model/scikitlearn"
TRAIN_TENSORFLOW_TYPE = "train/tensorflow"
TRAIN_SCIKITLEARN_TYPE = "train/scikitlearn"
TUNE_TENSORFLOW_TYPE = "tune/tensorflow"
TUNE_SCIKITLEARN_TYPE = "tune/scikitlearn"
EVALUATE_TENSORFLOW_TYPE = "evaluate/tensorflow"
EVALUATE_SCIKITLEARN_TYPE = "evaluate/scikitlearn"
# The reference gateway itself contains the typo "sckitlearn" for the
# evaluate backend (krakend.json evaluate routes); accept it as alias.
EVALUATE_SCIKITLEARN_TYPO = "evaluate/sckitlearn"
PREDICT_TENSORFLOW_TYPE = "predict/tensorflow"
PREDICT_SCIKITLEARN_TYPE = "predict/scikitlearn"
EXPLORE_TENSORFLOW_TYPE = "explore/tensorflow"
EXPLORE_SCIKITLEARN_TYPE = "explore/scikitlearn"
EXPLORE_HISTOGRAM_TYPE = "explore/histogram"
TRANSFORM_TENSORFLOW_TYPE = "transform/tensorflow"
TRANSFORM_SCIKITLEARN_TYPE = "transform/scikitlearn"
TRANSFORM_PROJECTION_TYPE = "transform/projection"
TRANSFORM_DATATYPE_TYPE = "transform/dataType"
FUNCTION_PYTHON_TYPE = "function/python"
BUILDER_SPARKML_TYPE = "builder/sparkml"
# JAX-native tool alias: everywhere the reference accepts "tensorflow"
# the rebuild also accepts "jax" with identical semantics.
MODEL_JAX_TYPE = "model/jax"
TRAIN_JAX_TYPE = "train/jax"
TUNE_JAX_TYPE = "tune/jax"
EVALUATE_JAX_TYPE = "evaluate/jax"
PREDICT_JAX_TYPE = "predict/jax"
EXPLORE_JAX_TYPE = "explore/jax"
TRANSFORM_JAX_TYPE = "transform/jax"

DATASET_TYPES = (DATASET_CSV_TYPE, DATASET_GENERIC_TYPE)

# Types whose artifact is a live Python/JAX object persisted to the
# artifact store (vs. tabular output persisted as rows).
OBJECT_TYPES_PREFIXES = ("model/", "train/", "tune/", "transform/", "function/")

TABULAR_OUTPUT_TYPES = (
    TRANSFORM_PROJECTION_TYPE,
    TRANSFORM_DATATYPE_TYPE,
    EXPLORE_HISTOGRAM_TYPE,
    BUILDER_SPARKML_TYPE,
)


def normalize_type(type_string: str) -> str:
    """Map reference typos/aliases onto canonical type strings."""
    if type_string == EVALUATE_SCIKITLEARN_TYPO:
        return EVALUATE_SCIKITLEARN_TYPE
    return type_string


def now_iso() -> str:
    """Fresh per-document timestamp.

    (The reference freezes one timestamp at service construction so all
    documents of a service share it, utils.py:69-77 — a bug we fix.)
    """
    return datetime.datetime.now().strftime("%Y-%m-%dT%H-%M-%S")


def metadata_document(name: str, type_string: str,
                      extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Build the reserved ``_id: 0`` metadata document
    (reference binary_executor_image/utils.py:79-97)."""
    doc: Dict[str, Any] = {
        ID: METADATA_ID,
        NAME_FIELD: name,
        TYPE_FIELD: normalize_type(type_string),
        FINISHED_FIELD: False,
        TIME_CREATED_FIELD: now_iso(),
    }
    if extra:
        doc.update(extra)
    return doc


def execution_document(description: str,
                       parameters: Optional[Dict[str, Any]] = None,
                       exception: Optional[str] = None,
                       extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Append-only run-history document (reference utils.py:112-136)."""
    doc: Dict[str, Any] = {
        DESCRIPTION_FIELD: description,
        METHOD_PARAMETERS_FIELD: parameters,
        EXCEPTION_FIELD: exception,
        TIME_CREATED_FIELD: now_iso(),
    }
    if extra:
        doc.update(extra)
    return doc


def matches_query(doc: Dict[str, Any], query: Optional[Dict[str, Any]]) -> bool:
    """Tiny Mongo-style filter evaluator for document reads.

    Supports equality and {$eq,$gt,$gte,$lt,$lte,$ne,$in} — covering
    the reference's pass-through ``query`` parameter on reads
    (database_api_image/database.py:19-28).
    """
    if not query:
        return True
    for key, cond in query.items():
        value = doc.get(key)
        if isinstance(cond, dict):
            for op, rhs in cond.items():
                try:
                    if op == "$eq" and not value == rhs:
                        return False
                    elif op == "$gt" and not value > rhs:
                        return False
                    elif op == "$gte" and not value >= rhs:
                        return False
                    elif op == "$lt" and not value < rhs:
                        return False
                    elif op == "$lte" and not value <= rhs:
                        return False
                    elif op == "$ne" and not value != rhs:
                        return False
                    elif op == "$in" and value not in rhs:
                        return False
                    elif op not in ("$eq", "$gt", "$gte", "$lt", "$lte",
                                    "$ne", "$in"):
                        raise ValueError(f"unsupported query operator: {op}")
                except TypeError:
                    return False
        else:
            if value != cond:
                return False
    return True


def project_fields(doc: Dict[str, Any],
                   fields: Optional[List[str]]) -> Dict[str, Any]:
    if not fields:
        return doc
    return {k: v for k, v in doc.items() if k in fields or k == ID}
