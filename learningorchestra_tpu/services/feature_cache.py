"""Feature-plane cache: the host tier and façade every compute
service routes dataset reads through (docs/PERFORMANCE.md).

``builder_service._run``, the execution verbs' ``$name`` resolution
(services/params.py) and the columnar transforms all used to call
``catalog.read_dataframe`` independently — a full Parquet read +
pandas materialization per pipeline step, per classifier. This cache
memoizes the materialized host data once per *content version* and
hands device staging off to the HBM arena (``runtime/arena.py``).

Keying: ``(collection, version, projection, dtype policy)`` where
version is ``(catalog.collection_seq(name), catalog.dataset_version(
name))`` — the same pair the gateway GET cache revalidates on
(services/server.py ``_get``). Both components are required: parquet
part swaps don't ride the change feed, and ``delete_collection``
removes the files whose stat the dataset_version reflects.

Invalidation is belt and braces:

- *revalidate-on-read*: every hit re-checks the stored version, so a
  mutated dataset (append / replace / delete) can never serve stale
  rows to the next job;
- *change-feed sweep*: each access drains ``changes_since(last_seq)``
  and drops touched collections from both tiers (including the
  arena's tagged device arrays) so deleted datasets free budget
  promptly instead of lingering until LRU pressure.

Reads use a bounded stable-version loop (read version, read data,
re-read version; retry on mismatch) so a reader racing
``write_dataframe``'s staging-rename swap caches either the old or
the new version in full — never a mix.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from learningorchestra_tpu.runtime import arena as arena_lib
from learningorchestra_tpu.runtime import locks

# attempts at reading a frame under one stable version before giving
# up on caching it (the data is still returned)
_STABLE_READ_ATTEMPTS = 3


class FeatureCache:
    """Version-keyed host-tier cache of materialized DataFrames /
    numpy column dicts, bounded by a byte budget with LRU eviction."""

    def __init__(self, catalog, host_bytes: int = 256 << 20,
                 arena: Optional[arena_lib.DeviceArena] = None):
        self._catalog = catalog
        self._limit = int(host_bytes)
        self._arena = arena
        self._entries: "collections.OrderedDict[Any, tuple]" = \
            collections.OrderedDict()  # key -> (version, value, nbytes)
        self._bytes = 0
        self._lock = locks.make_lock("feature_cache.store")
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self._last_seq = catalog.latest_seq()

    # -- identity ------------------------------------------------------
    @property
    def arena(self) -> arena_lib.DeviceArena:
        return self._arena or arena_lib.get_default_arena()

    def version(self, name: str) -> Tuple[Any, Any]:
        """Content version of a collection: change-feed seq + parquet
        part stats (either alone misses a class of mutations)."""
        return (self._catalog.collection_seq(name),
                self._catalog.dataset_version(name))

    def token(self, name: str, *extra: Any) -> Tuple[Any, ...]:
        """Opaque, hashable identity of this collection's CURRENT
        content (+ caller qualifiers) — the arena key component that
        makes device-tier entries self-invalidate on version change."""
        return ("ds", name, self.version(name)) + extra

    # -- host tier -----------------------------------------------------
    def dataframe(self, name: str,
                  columns: Optional[Sequence[str]] = None):
        """The collection as a DataFrame, served from the version-keyed
        host tier. Callers get a shallow copy: adding/dropping columns
        never corrupts the cached frame (same contract the parameter
        resolver's cache had)."""
        key = ("df", name, tuple(columns) if columns else None)
        df = self._get(key, name, lambda: self._catalog.read_dataframe(
            name, columns=list(columns) if columns else None))
        return df.copy(deep=False)

    def arrays(self, name: str, columns: Sequence[str],
               dtype) -> Dict[str, Any]:
        """Materialized numpy column dict (feature-plane layout) for
        ``columns`` under one dtype policy."""
        import numpy as np

        cols = tuple(columns)
        key = ("np", name, cols, np.dtype(dtype).str)

        def build():
            df = self._catalog.read_dataframe(name, columns=list(cols))
            return {c: df[c].to_numpy(dtype) for c in cols}

        return dict(self._get(key, name, build))

    def _get(self, key: Any, name: str, build) -> Any:
        self._sweep()
        version = self.version(name)
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                if hit[0] == version:
                    self._entries.move_to_end(key)
                    self.hits += 1
                    return hit[1]
                # stale: the parquet parts changed under the change
                # feed's nose — drop this entry AND the arena's device
                # copies of the old version
                self._drop_locked(key)
                self.invalidations += 1
            self.misses += 1
        value, version = self._stable_read(name, version, build)
        if version is not None:
            self._insert(key, version, value)
        return value

    def _stable_read(self, name: str, version, build):
        """(value, version-or-None): re-reads until the version is
        identical before and after the data read, so a read racing a
        writer returns one coherent snapshot. None = never stabilized;
        the last read is returned uncached."""
        for _ in range(_STABLE_READ_ATTEMPTS):
            value = build()
            after = self.version(name)
            if after == version:
                return value, version
            version = after
        return value, None

    def _insert(self, key: Any, version, value) -> None:
        nbytes = _sizeof(value)
        if nbytes is None or nbytes <= 0 or nbytes > self._limit:
            return
        with self._lock:
            self._drop_locked(key)
            while self._entries and self._bytes + nbytes > self._limit:
                old_key, (_, _, evicted) = self._entries.popitem(last=False)
                self._bytes -= evicted
            self._entries[key] = (version, value, nbytes)
            self._bytes += nbytes

    def _drop_locked(self, key: Any) -> None:
        old = self._entries.pop(key, None)
        if old is not None:
            self._bytes -= old[2]

    # -- invalidation --------------------------------------------------
    def _sweep(self) -> None:
        """Drain the catalog change feed and drop touched collections
        from both tiers. Cheap (one indexed sqlite query when idle)."""
        seq = self._catalog.latest_seq()
        if seq == self._last_seq:
            return
        with self._lock:
            if seq == self._last_seq:
                return
            last, self._last_seq = self._last_seq, seq
        touched = {c["collection"]
                   for c in self._catalog.changes_since(last)}
        for name in touched:
            self.invalidate(name)

    def invalidate(self, name: str) -> int:
        """Drop every host-tier entry for ``name`` and the arena's
        device arrays staged from it."""
        dropped = 0
        with self._lock:
            for key in [k for k in self._entries if k[1] == name]:
                self._drop_locked(key)
                dropped += 1
            self.invalidations += dropped
        dropped += self.arena.invalidate(name)
        return dropped

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    # -- observability -------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytesInUse": self._bytes,
                "byteBudget": self._limit,
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
            }


def _sizeof(value: Any) -> Optional[int]:
    """Approximate host bytes of a cached value; None = unsizable
    (exotic dtypes) -> skip caching, matching the old resolver cache."""
    try:
        if hasattr(value, "memory_usage"):  # DataFrame
            return int(value.memory_usage(index=True, deep=False).sum())
        if isinstance(value, dict):
            return sum(int(v.nbytes) for v in value.values())
        return int(value.nbytes)
    except Exception:  # noqa: BLE001
        return None
