"""learningOrchestra-TPU — a TPU-native ML pipeline orchestration framework.

A ground-up rebuild of the capabilities of learningOrchestra
(reference: /root/reference, REST-orchestrated ML pipelines over Docker
Swarm + Flask + MongoDB + Spark) on an idiomatic JAX/XLA/pjit/Pallas
stack:

- One REST control plane with the reference's URI contract
  (``/api/learningOrchestra/v1/{service}/{tool}``, async 201 +
  ``finished``-flag polling; reference krakend.json:1-1773).
- A catalog (SQLite metadata + Parquet datasets + typed binary
  artifacts) replacing MongoDB-as-everything (reference
  docker-compose.yml:42-90).
- A JAX runtime: device-mesh manager, jit/pjit training engines,
  double-buffered host->HBM input feed, Orbax checkpointing.
- A parallelism library: DP/FSDP/TP/PP/SP(ring attention)/Ulysses/EP
  over `jax.sharding.Mesh` — all absent in the reference (SURVEY §2.4).
"""

__version__ = "0.1.0"

from learningorchestra_tpu.config import Config, get_config  # noqa: F401
