"""Cooperative preemption + cancellation hooks for long device jobs.

The reference gives each Spark service its own FAIR scheduler pool so
a long job cannot monopolize the cluster
(reference spark_image/fairscheduler.xml:1-8, builder_image
server.py:57-63). The TPU analogue: the mesh is an exclusive lease
(services/scheduler.FairLease), and long engine fits offer to YIELD
the lease at epoch boundaries — per-epoch orbax checkpoints make the
hand-off durable, and since all jobs share one process the model
state stays live in memory across the yield.

The engine can't import the services layer (layering), so the lease
installs a thread-local callback here and the engine's epoch loops
call :func:`maybe_yield` between epochs. No lease installed (direct
library use, tests, workers) → no-op.

The SAME yield points double as cancellation points: the job manager
installs a :class:`CancelToken` per job thread and the engine's
epoch/step loops call :func:`check_cancel` / :func:`heartbeat` — so a
deadline expiry or a ``DELETE .../run`` surfaces as
:class:`JobCancelled` at the next safe boundary, the lease is
released, and no single request can wedge the accelerator
(docs/LIFECYCLE.md).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional
from learningorchestra_tpu.runtime import locks

_tls = threading.local()


class JobCancelled(Exception):
    """Cooperative cancellation signal. ``reason`` is the terminal
    lifecycle state it produces: ``"timedOut"`` (deadline expired),
    ``"cancelled"`` (user DELETE), or ``"stalled"`` (watchdog
    escalation). Raised from :meth:`CancelToken.check` at the engine /
    sandbox / scheduler yield points, caught by the job manager."""

    def __init__(self, reason: str, message: str = ""):
        super().__init__(message or f"job {reason}")
        self.reason = reason


class CancelToken:
    """Per-job cancellation + progress record.

    - ``cancel(reason)`` flips a latched event (first reason wins:
      a user cancel that races the deadline keeps its attribution);
    - ``deadline`` (``time.monotonic`` basis) is checked lazily on
      every :meth:`cancelled` call, so an expired job cancels itself
      at its next cooperative check with no timer thread per job;
    - ``beat(**progress)`` publishes a heartbeat (step/epoch
      counters) the stall watchdog reads via :meth:`heartbeat_age`.
    """

    def __init__(self, deadline: Optional[float] = None):
        self._event = threading.Event()
        self._lock = locks.make_lock("preempt.token")
        self.deadline = deadline
        self.reason: Optional[str] = None
        self.progress: Dict[str, Any] = {}
        self.last_beat: Optional[float] = None
        self.started: Optional[float] = None
        # -- live migration (services/migration.py) --------------------
        # latched until the engine consumes it at a step boundary
        self.migrate_pending: Optional[str] = None
        self.migrations: int = 0
        # stamped by the slice lease at grant time: the job's current
        # device indices (None = whole mesh) and whether a migrate
        # request makes sense for it (sliced, single-host)
        self.slice_devices: Optional[tuple] = None
        self.migratable: bool = False
        # -- elastic resize (services/autoscaler.py) -------------------
        # declared (min, max) device bounds when the job's footprint
        # is elastic; ``resize_want`` rides the migrate latch to the
        # scheduler's migrate point, ``resize_inflight`` serializes
        # placement changes (one per job) until the engine reports the
        # outcome via :meth:`resize_done`
        self.elastic: Optional[tuple] = None
        self.resize_want: Optional[int] = None
        self.resize_inflight: bool = False
        self.resizes: int = 0
        self.resize_rollbacks: int = 0
        self.last_resize_error: Optional[str] = None
        # placement timeline (grants, resizes, rollbacks) — surfaced
        # as the job's ``sliceHistory`` metadata
        self.slice_history: list = []

    # -- migration signal ----------------------------------------------
    def request_migrate(self, reason: str = "migrate") -> bool:
        """Latch a cooperative migrate request. Returns False when the
        job is already cancelled (nothing to migrate) or a request is
        already pending (idempotent)."""
        with self._lock:
            if self.reason is not None or self._event.is_set():
                return False
            if self.migrate_pending is not None:
                return False
            self.migrate_pending = reason
            return True

    def consume_migrate(self) -> Optional[str]:
        """Take the pending request (engine, at a step boundary)."""
        with self._lock:
            reason, self.migrate_pending = self.migrate_pending, None
            return reason

    # -- elastic resize signal -----------------------------------------
    def request_resize(self, want: int, reason: str = "autoscale",
                       ) -> bool:
        """Latch a resize-via-migration request: the engine's next
        epoch boundary releases the slice and re-acquires ``want``
        devices. Refused (False) when the job is cancelled, another
        migrate/resize is already in flight (one placement change per
        job — a racing defrag or second resize coalesces), or ``want``
        violates the declared elastic bounds (the scheduler never sees
        a below-``min`` or above-``max`` target)."""
        with self._lock:
            if self.reason is not None or self._event.is_set():
                return False
            if self.migrate_pending is not None or self.resize_inflight:
                return False
            if self.elastic is not None:
                lo, hi = self.elastic
                if not lo <= int(want) <= hi:
                    return False
            self.resize_want = int(want)
            self.resize_inflight = True
            self.migrate_pending = f"resize:{reason}"
            return True

    def resize_done(self, ok: bool, devices=None,
                    error: Optional[str] = None) -> None:
        """Engine reports a consumed resize's outcome (state re-placed
        on the new slice, or rolled back to an old-size one). Clears
        the in-flight latch so the autoscaler may request again."""
        with self._lock:
            self.resize_want = None
            self.resize_inflight = False
            if self.migrate_pending is not None \
                    and self.migrate_pending.startswith("resize:"):
                # outcome reported before the engine consumed the
                # latch (request refused downstream): drop it so the
                # next placement change isn't wedged
                self.migrate_pending = None
            if ok:
                self.resizes += 1
            else:
                self.resize_rollbacks += 1
                self.last_resize_error = error
            entry: Dict[str, Any] = {
                "event": "resize" if ok else "rollback",
                "devices": (list(devices)
                            if devices is not None else None),
                "wallTime": time.time()}
            if error:
                entry["error"] = error
            self.slice_history.append(entry)

    def record_placement(self, event: str, devices) -> None:
        """Append a placement event (grant/migrate) to the job's
        ``sliceHistory`` timeline."""
        with self._lock:
            self.slice_history.append({
                "event": event,
                "devices": (list(devices)
                            if devices is not None else None),
                "wallTime": time.time()})

    # -- cancellation --------------------------------------------------
    def cancel(self, reason: str = "cancelled") -> bool:
        """Latch the token. Returns True if this call set the reason
        (False when already cancelled — the original reason stands)."""
        with self._lock:
            if self.reason is None:
                self.reason = reason
                self._event.set()
                return True
            return False

    def cancelled(self) -> bool:
        if self._event.is_set():
            return True
        if self.deadline is not None and \
                time.monotonic() >= self.deadline:
            self.cancel("timedOut")
            return True
        return False

    def check(self) -> None:
        if self.cancelled():
            raise JobCancelled(self.reason or "cancelled")

    def wait(self, seconds: float) -> bool:
        """Cancel-aware sleep (retry backoff): returns True the moment
        the token cancels, False after the full wait. Deadline-based
        expiry is honored too — the wait is clipped so a backoff never
        outsleeps the job's own deadline."""
        end = time.monotonic() + max(0.0, seconds)
        while True:
            if self.cancelled():
                return True
            now = time.monotonic()
            if now >= end:
                return False
            step = end - now
            if self.deadline is not None:
                step = min(step, max(0.0, self.deadline - now))
            if self._event.wait(min(step, 0.5) or 0.001):
                return True

    def remaining(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return max(0.0, self.deadline - time.monotonic())

    # -- progress heartbeat --------------------------------------------
    def beat(self, **progress: Any) -> None:
        with self._lock:
            self.last_beat = time.monotonic()
            self.progress.update(progress)

    def heartbeat_age(self) -> Optional[float]:
        """Seconds since the last beat; None before the first beat
        (jobs that never publish progress — sklearn fits, ingests —
        are exempt from stall detection)."""
        last = self.last_beat
        return None if last is None else time.monotonic() - last

    def progress_snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self.progress)


# ----------------------------------------------------------------------
# thread-local install points (yield + cancel are separate slots: the
# lease CM owns the yield slot, the job manager owns the cancel slot)
# ----------------------------------------------------------------------
def install(fn: Callable[[], None],
            contended_fn: Optional[Callable[[], bool]] = None) -> None:
    """Register ``fn`` as this thread's between-epochs yield point
    (called by the mesh lease when a job thread acquires it).
    ``contended_fn`` lets long jobs ASK whether a yield is wanted
    without performing one — sweeps use it to drain in-flight trials
    before handing the lease over."""
    _tls.fn = fn
    _tls.contended = contended_fn


def clear() -> None:
    _tls.fn = None
    _tls.contended = None
    _tls.migrate = None


def current() -> Optional[Callable[[], None]]:
    return getattr(_tls, "fn", None)


def contended() -> bool:
    """True when another job is waiting for this thread's lease (a
    yield at the next safe point would hand it over). Always False
    outside the service layer."""
    fn = getattr(_tls, "contended", None)
    return bool(fn()) if fn is not None else False


def install_migrate(fn: Optional[Callable[[], Any]]) -> None:
    """Register this thread's migrate point (the slice lease CM):
    ``fn()`` releases the held slice, re-acquires a fresh placement
    through the fair queue, and returns the new grant's device
    indices (or None for a whole-mesh grant)."""
    _tls.migrate = fn


def migrate_requested() -> bool:
    """Peek (don't consume): does this thread's job have a pending
    migrate request AND a way to perform one?"""
    token = current_cancel()
    return (token is not None
            and token.migrate_pending is not None
            and getattr(_tls, "migrate", None) is not None)


def perform_migrate():
    """Consume the pending request and run the installed migrate
    point. Returns ``(performed, new_devices)`` — ``(False, None)``
    when there was nothing to do. Called by the ENGINE after it has
    snapshotted state off the devices (runtime/engine.py). A pending
    elastic resize threads its device-count target through to the
    migrate point, which re-acquires at the new size."""
    token = current_cancel()
    fn = getattr(_tls, "migrate", None)
    if token is None or fn is None:
        return False, None
    if token.consume_migrate() is None:
        return False, None
    want = token.resize_want
    if want is not None:
        return True, fn(want)
    return True, fn()


def migrate_fn():
    """The raw installed migrate point, if any. The engine's resize
    ROLLBACK path calls it directly with the old device count after a
    failed resize — no pending request needed."""
    return getattr(_tls, "migrate", None)


def snapshot():
    """(yield_fn, contended_fn, migrate_fn) for save/restore around
    nested installs (the lease CM restores its predecessor on exit)."""
    return (getattr(_tls, "fn", None),
            getattr(_tls, "contended", None),
            getattr(_tls, "migrate", None))


def restore(snap) -> None:
    # older 2-tuple snapshots (pre-migration callers) still restore
    if len(snap) == 2:
        _tls.fn, _tls.contended = snap
        _tls.migrate = None
    else:
        _tls.fn, _tls.contended, _tls.migrate = snap


def install_cancel(token: Optional[CancelToken]) -> None:
    """Bind ``token`` to this thread (job manager, around each job)."""
    _tls.cancel = token


def clear_cancel() -> None:
    _tls.cancel = None


def current_cancel() -> Optional[CancelToken]:
    return getattr(_tls, "cancel", None)


def check_cancel() -> None:
    """Raise :class:`JobCancelled` if this thread's job was cancelled
    or ran past its deadline. No token installed → no-op (direct
    library use, tests, workers)."""
    token = current_cancel()
    if token is not None:
        token.check()


def heartbeat(**progress: Any) -> None:
    """Publish step/epoch progress for the stall watchdog. No token
    installed → no-op."""
    token = current_cancel()
    if token is not None:
        token.beat(**progress)


def maybe_yield() -> None:
    """Engine epoch boundary: first honor any pending cancellation,
    then hand the mesh lease to a waiting job of another pool (if any)
    and re-acquire it through the fair queue."""
    check_cancel()
    fn = current()
    if fn is not None:
        fn()
