"""Full-data histogram gradient boosting over pre-binned features.

The reference's Builder trains GBTClassifier on ALL rows via the Spark
cluster (builder_image/builder.py:118). The rebuild's streaming path
previously bounded GB to a 500k reservoir; this module removes that
cap: features are binned to uint8 codes (edges from a sampled quantile
sketch — sampling bin BOUNDARIES is not training on a sample; every
row still contributes gradients to every iteration), the codes live in
memory at one byte per value, and the boosting loop runs in the
first-party C++ core (``csrc/locore.cpp lo_hgb_*``) with a numpy
fallback when no toolchain exists.

Memory: rows x nfeats bytes of codes + one f64 raw score per row (per
class beyond binary) — 10M rows x 5 features ~ 50 MB + 80 MB.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional

import numpy as np

from learningorchestra_tpu import native

MAX_BINS = 256

DEFAULT_ITERS = int(os.environ.get("LO_HGB_ITERS", "60"))
DEFAULT_DEPTH = int(os.environ.get("LO_HGB_DEPTH", "6"))
DEFAULT_LR = float(os.environ.get("LO_HGB_LR", "0.2"))


def quantile_edges(sample: np.ndarray, max_bins: int = MAX_BINS,
                   ) -> List[np.ndarray]:
    """Per-feature cut points (at most ``max_bins - 1``) from a sample
    of rows; duplicates collapse for low-cardinality features."""
    edges = []
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    for f in range(sample.shape[1]):
        col = sample[:, f]
        col = col[np.isfinite(col)]
        if col.size == 0:
            edges.append(np.empty((0,), np.float64))
            continue
        e = np.unique(np.quantile(col, qs))
        edges.append(np.asarray(e, np.float64))
    return edges


def bin_codes(x: np.ndarray, edges: List[np.ndarray]) -> np.ndarray:
    """uint8 bin codes for a feature batch (NaN -> bin 0; +/-inf sort
    correctly through searchsorted and keep their extreme bins)."""
    out = np.empty(x.shape, np.uint8)
    for f, e in enumerate(edges):
        col = x[:, f]
        codes = np.searchsorted(e, col, side="left")
        codes = np.where(np.isnan(col), 0, codes)
        out[:, f] = codes.astype(np.uint8)
    return out


class HistGB:
    """sklearn-shaped binary/multiclass classifier over binned codes."""

    def __init__(self, n_iter: int = DEFAULT_ITERS,
                 max_depth: int = DEFAULT_DEPTH,
                 learning_rate: float = DEFAULT_LR,
                 l2: float = 1.0, min_samples_leaf: int = 20):
        self.n_iter = n_iter
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.l2 = l2
        self.min_samples_leaf = min_samples_leaf
        self.classes_: Optional[np.ndarray] = None
        self._model = None       # ctypes ptr (native path)
        self._py = None          # python model (fallback path)
        self._lib = None

    # ------------------------------------------------------------------
    def fit_binned(self, codes: np.ndarray, y: np.ndarray) -> "HistGB":
        if self._model is not None and self._lib is not None:
            # refit: release the previous native model's tree arrays
            self._lib.lo_hgb_free.argtypes = [ctypes.c_void_p]
            self._lib.lo_hgb_free(ctypes.c_void_p(self._model))
            self._model = None
        self._py = None
        codes = np.ascontiguousarray(codes, np.uint8)
        self.classes_, y_idx = np.unique(y, return_inverse=True)
        y_idx = np.ascontiguousarray(y_idx, np.int32)
        nclass = len(self.classes_)
        if nclass < 2:
            raise ValueError("need at least 2 classes")
        lib = native.get_lib()
        if lib is not None and hasattr(lib, "lo_hgb_train"):
            self._lib = lib
            lib.lo_hgb_train.restype = ctypes.c_void_p
            lib.lo_hgb_train.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.c_int, ctypes.c_double,
                ctypes.c_double, ctypes.c_int64]
            ptr = lib.lo_hgb_train(
                codes.ctypes.data_as(ctypes.c_char_p), codes.shape[0],
                codes.shape[1], y_idx.ctypes.data_as(ctypes.c_char_p),
                nclass, self.n_iter, self.max_depth, MAX_BINS,
                self.learning_rate, self.l2, self.min_samples_leaf)
            if ptr:
                self._model = ptr
                return self
        self._py = _py_train(codes, y_idx, nclass, self.n_iter,
                             self.max_depth, self.learning_rate,
                             self.l2, self.min_samples_leaf)
        return self

    def predict_binned(self, codes: np.ndarray) -> np.ndarray:
        codes = np.ascontiguousarray(codes, np.uint8)
        nclass = len(self.classes_)
        k = 1 if nclass == 2 else nclass
        if self._model is not None:
            out = np.empty((codes.shape[0], k), np.float64)
            self._lib.lo_hgb_predict.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                ctypes.c_char_p]
            self._lib.lo_hgb_predict(
                ctypes.c_void_p(self._model),
                codes.ctypes.data_as(ctypes.c_char_p), codes.shape[0],
                out.ctypes.data_as(ctypes.c_char_p))
        else:
            out = _py_predict(self._py, codes)
        if nclass == 2:
            idx = (out[:, 0] > 0).astype(np.int64)
        else:
            idx = np.argmax(out, axis=1)
        return self.classes_[idx]

    def __del__(self):
        if self._model is not None and self._lib is not None:
            try:
                self._lib.lo_hgb_free.argtypes = [ctypes.c_void_p]
                self._lib.lo_hgb_free(ctypes.c_void_p(self._model))
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass


# ----------------------------------------------------------------------
# numpy fallback — same algorithm (depth-wise, heap layout); per-node
# boolean masks + per-feature bincounts keep it vectorized enough for
# toolchain-less images (the C++ path is the performance one)
# ----------------------------------------------------------------------
def _py_build_tree(codes, g, h, max_depth, lr, l2, min_leaf):
    nrows, nfeats = codes.shape
    slots = (1 << (max_depth + 1)) - 1
    tfeat = np.full(slots, -2, np.int64)
    tbin = np.zeros(slots, np.uint8)
    tval = np.zeros(slots, np.float64)
    assign = np.zeros(nrows, np.int64)
    tfeat[0] = -1
    for depth in range(max_depth):
        first, count = (1 << depth) - 1, 1 << depth
        active = [n for n in range(first, first + count)
                  if tfeat[n] == -1]
        if not active:
            break
        any_split = False
        for n in active:
            rows = assign == n
            G, H, C = g[rows].sum(), h[rows].sum(), int(rows.sum())
            parent_obj = G * G / (H + l2 + 1e-12)
            best = (1e-7, -1, -1)
            for f in range(nfeats):
                b = codes[rows, f].astype(np.int64)
                fg = np.bincount(b, weights=g[rows], minlength=MAX_BINS)
                fh = np.bincount(b, weights=h[rows], minlength=MAX_BINS)
                fc = np.bincount(b, minlength=MAX_BINS)
                GL = np.cumsum(fg)[:-1]
                HL = np.cumsum(fh)[:-1]
                CL = np.cumsum(fc)[:-1]
                CR = C - CL
                ok = (CL >= min_leaf) & (CR >= min_leaf)
                HR, GR = H - HL, G - GL
                gain = np.where(
                    ok,
                    GL * GL / (HL + l2 + 1e-12) +
                    GR * GR / (HR + l2 + 1e-12) - parent_obj,
                    -np.inf)
                bi = int(np.argmax(gain))
                if gain[bi] > best[0]:
                    best = (float(gain[bi]), f, bi)
            if best[1] < 0:
                tval[n] = -lr * G / (H + l2 + 1e-12)
                continue
            tfeat[n] = best[1]
            tbin[n] = best[2]
            left = 2 * n + 1
            if left < slots:
                tfeat[left] = -1
                tfeat[left + 1] = -1
            any_split = True
            go_left = rows & (codes[:, best[1]] <= best[2])
            assign[go_left] = left
            assign[rows & ~go_left] = left + 1
        if not any_split:
            break
    # finalize remaining provisional leaves
    for n in range(slots):
        if tfeat[n] == -1 and tval[n] == 0.0:
            rows = assign == n
            if rows.any():
                tval[n] = (-lr * g[rows].sum() /
                           (h[rows].sum() + l2 + 1e-12))
            tfeat[n] = -1
    # resolve each row's final leaf (callers update their score slice)
    node = assign.copy()
    internal = tfeat[node] >= 0
    while internal.any():
        f = tfeat[node[internal]]
        c = codes[np.nonzero(internal)[0], f]
        node[internal] = np.where(c <= tbin[node[internal]],
                                  2 * node[internal] + 1,
                                  2 * node[internal] + 2)
        internal = tfeat[node] >= 0
    return tfeat, tbin, tval, node


def _py_train(codes, y_idx, nclass, n_iter, max_depth, lr, l2,
              min_leaf):
    nrows = codes.shape[0]
    k = 1 if nclass == 2 else nclass
    counts = np.bincount(y_idx, minlength=nclass) / nrows
    if nclass == 2:
        p = min(max(counts[1], 1e-9), 1 - 1e-9)
        bases = np.array([np.log(p / (1 - p))])
    else:
        bases = np.log(np.maximum(counts, 1e-9))
    scores = np.tile(bases, (nrows, 1))
    trees = []
    for _ in range(n_iter):
        if nclass == 2:
            p = 1.0 / (1.0 + np.exp(-scores[:, 0]))
            g = p - y_idx
            h = np.maximum(p * (1 - p), 1e-12)
            tfeat, tbin, tval, leaf = _py_build_tree(
                codes, g, h, max_depth, lr, l2, min_leaf)
            scores[:, 0] += tval[leaf]
            trees.append((0, tfeat, tbin, tval))
        else:
            mx = scores.max(axis=1, keepdims=True)
            e = np.exp(scores - mx)
            probs = e / e.sum(axis=1, keepdims=True)
            for kk in range(nclass):
                g = probs[:, kk] - (y_idx == kk)
                h = np.maximum(probs[:, kk] * (1 - probs[:, kk]), 1e-12)
                tfeat, tbin, tval, leaf = _py_build_tree(
                    codes, g, h, max_depth, lr, l2, min_leaf)
                scores[:, kk] += tval[leaf]
                trees.append((kk, tfeat, tbin, tval))
    return {"bases": bases, "trees": trees, "k": k}


def _py_predict(model, codes):
    nrows = codes.shape[0]
    out = np.tile(model["bases"], (nrows, 1))
    for kk, tfeat, tbin, tval in model["trees"]:
        node = np.zeros(nrows, np.int64)
        internal = tfeat[node] >= 0
        while internal.any():
            f = tfeat[node[internal]]
            c = codes[np.nonzero(internal)[0], f]
            node[internal] = np.where(c <= tbin[node[internal]],
                                      2 * node[internal] + 1,
                                      2 * node[internal] + 2)
            internal = tfeat[node] >= 0
        out[:, kk] += tval[node]
    return out
