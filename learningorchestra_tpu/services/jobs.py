"""Async job manager.

The reference's execution model, shared by every service
(SURVEY §L2): the POST handler validates synchronously, writes a
metadata document with ``finished: False``, submits the pipeline to a
``ThreadPoolExecutor`` and returns 201 immediately; clients poll the
``finished`` flag (binary_executor_image/binary_execution.py:118-175).
On success the flag flips and an execution document is appended; on
failure the flag stays False and the execution document records
``repr(exception)`` (binary_execution.py:160-175).

Beyond the reference (its in-flight jobs are simply lost on failure,
README.md:194-198):

- **Device leasing.** A TPU mesh is an exclusive resource; jobs that
  need it acquire a lease so concurrent REST jobs queue instead of
  fighting over HBM (SURVEY §7 hard part #1). The lease is FAIR
  across job classes (services/scheduler.py — fairscheduler.xml
  parity) and long fits yield it at epoch boundaries; a preempted
  job's device state stays in HBM, so LO_MESH_YIELD=0 restores
  strict serialization when concurrent footprints would not fit.
- **Retry.** ``max_retries`` re-runs a failed pipeline; each attempt
  appends its own execution document.
- **Timing.** Every execution document records ``elapsedSeconds``
  (superset of the reference's builder-only ``fitTime``,
  builder.py:117-122) plus queue wait time for lease contention.
"""

from __future__ import annotations

import contextlib
import threading
import time
import traceback
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional

from learningorchestra_tpu.catalog import documents as D
from learningorchestra_tpu.catalog.store import Catalog


class JobManager:
    def __init__(self, catalog: Catalog, max_workers: int = 8,
                 mesh_leases: int = 1,
                 pod_failure_fn: Optional[Callable[[], Optional[str]]]
                 = None,
                 pool_weights: Optional[Dict[str, float]] = None):
        from learningorchestra_tpu.services.scheduler import FairLease

        self._catalog = catalog
        self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                        thread_name_prefix="lo-job")
        self._mesh = FairLease(mesh_leases, pool_weights)
        self._futures: Dict[str, Future] = {}
        self._mesh_jobs: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        # returns a failure description when the multi-host pod has
        # lost a worker (runtime.distributed.pod_failure); mesh jobs
        # are then refused instead of hanging in a collective
        self._pod_failure_fn = pod_failure_fn or (lambda: None)

    # ------------------------------------------------------------------
    def mesh_lease(self, pool: str = "default"):
        """Context manager granting accelerator access through the
        fair queue (``with jobs.mesh_lease(): ...``)."""
        return self._mesh.lease(pool)

    def mesh_served(self) -> Dict[str, float]:
        """Cumulative mesh seconds per pool (observability)."""
        return self._mesh.served()

    # ------------------------------------------------------------------
    def submit(self, name: str, fn: Callable[[], Any], *,
               description: str = "",
               parameters: Optional[Dict[str, Any]] = None,
               needs_mesh: bool = False,
               pool: str = "default",
               max_retries: int = 0,
               on_success: Optional[Callable[[Any], None]] = None,
               mark_finished: bool = True,
               failure_names: Optional[list] = None,
               only_if_idle: bool = False,
               ) -> Future:
        """Run ``fn`` asynchronously under the reference's
        finished-flag contract for collection ``name`` (which must
        already exist with ``finished: False``). Multi-output jobs
        (Builder: one collection per classifier) pass
        ``failure_names`` so a TERMINAL job failure documents EVERY
        output — a client polling any of them must see the error, not
        hang on a forever-False finished flag."""
        doc_names = list(failure_names) if failure_names else [name]

        def fail_all(document: Dict[str, Any]) -> None:
            for n in doc_names:
                if n != name:
                    # outputs that already finished (e.g. classifiers
                    # that completed before a sibling's failure sank
                    # the job) keep their clean record
                    meta = self._catalog.get_metadata(n)
                    if meta is None or meta.get(D.FINISHED_FIELD):
                        continue
                self._catalog.append_document(n, dict(document))

        def run() -> Any:
            submitted = time.monotonic()
            attempts = max_retries + 1
            for attempt in range(attempts):
                if needs_mesh:
                    failure = self._pod_failure_fn()
                    if failure:
                        # a degraded pod cannot run mesh collectives:
                        # record a TERMINAL typed failure instead of
                        # entering a jit that would hang forever
                        fail_all(D.execution_document(
                            description, parameters,
                            exception=f"WorkerLost({failure!r})",
                            extra={"workerLost": True,
                                   "attempt": attempt + 1}))
                        return None
                lease = (self._mesh.lease(pool) if needs_mesh
                         else contextlib.nullcontext())
                with lease as token:
                    queue_wait = time.monotonic() - submitted
                    start = time.monotonic()

                    def timing(extra_base):
                        # elapsedSeconds is the job's OWN runtime:
                        # epochs spent preempted (lease handed to
                        # another pool) are reported separately so
                        # throughput comparisons stay meaningful
                        # under contention
                        elapsed = time.monotonic() - start
                        preempted = getattr(token, "preempted_seconds",
                                            0.0)
                        extra = dict(extra_base)
                        extra["elapsedSeconds"] = round(
                            elapsed - preempted, 6)
                        if preempted > 0:
                            extra["preemptedSeconds"] = round(
                                preempted, 6)
                            extra["leaseYields"] = token.yields
                        return extra

                    try:
                        result = fn()
                        if on_success is not None:
                            on_success(result)
                        if mark_finished:
                            self._catalog.mark_finished(name)
                        self._catalog.append_document(
                            name, D.execution_document(
                                description, parameters,
                                extra=timing(
                                    {"queueWaitSeconds": round(
                                        queue_wait, 6),
                                     "attempt": attempt + 1})))
                        return result
                    except Exception as exception:  # noqa: BLE001
                        traceback.print_exc()
                        terminal = attempt + 1 >= attempts
                        extra = timing({"attempt": attempt + 1})
                        if needs_mesh and self._pod_failure_fn():
                            # a mesh job failing WHILE the pod is
                            # degraded is a worker-loss casualty (a
                            # collective erroring out under it), not a
                            # code failure — flag it so elastic
                            # recovery requeues it on heal
                            extra["workerLost"] = True
                        doc = D.execution_document(
                            description, parameters,
                            exception=repr(exception), extra=extra)
                        if terminal:
                            fail_all(doc)
                            # finished stays False (reference parity)
                            return None
                        self._catalog.append_document(name, doc)

        with self._lock:
            existing = self._futures.get(name)
            if only_if_idle:
                # elastic-recovery guard vs a concurrent client PATCH:
                # the live-future check, the finished re-check and the
                # registration share one lock, so the same job can
                # never be double-submitted — and a job that FINISHED
                # between the caller's catalog read and this point is
                # not re-run either
                if existing is not None and not existing.done():
                    return existing
                meta = self._catalog.get_metadata(name)
                if meta is not None and meta.get(D.FINISHED_FIELD):
                    if existing is not None:
                        return existing
                    done_future: Future = Future()
                    done_future.set_result(None)
                    return done_future
            future = self._pool.submit(run)
            # prune finished entries so a long-lived server doesn't
            # leak a Future per job (results live in the catalog; wait()
            # on a pruned job returns immediately)
            done = [k for k, f in self._futures.items()
                    if f.done() and k != name]
            for k in done:
                del self._futures[k]
                self._mesh_jobs.pop(k, None)
            self._futures[name] = future
            if needs_mesh:
                self._mesh_jobs[name] = {"description": description,
                                         "parameters": parameters}
        return future

    def fail_running_mesh_jobs(self, reason: str) -> int:
        """Append a terminal ``WorkerLost`` execution document to every
        in-flight mesh job (their threads are stuck in collectives a
        dead worker will never join — clients polling the documents
        must see a typed failure, not silence). Returns the count."""
        with self._lock:
            stuck = [(k, v) for k, v in self._mesh_jobs.items()
                     if k in self._futures
                     and not self._futures[k].done()]
        for name, info in stuck:
            self._catalog.append_document(
                name, D.execution_document(
                    info["description"], info["parameters"],
                    exception=f"WorkerLost({reason!r})",
                    extra={"workerLost": True}))
        return len(stuck)

    def resubmit(self, name: str, fn: Callable[[], Any],
                 **kwargs: Any) -> Future:
        """The PATCH verb: reset ``finished`` and re-run (reference
        Execution.update, binary_execution.py:136-145)."""
        self._catalog.update_metadata(name, {D.FINISHED_FIELD: False})
        return self.submit(name, fn, **kwargs)

    # ------------------------------------------------------------------
    def wait(self, name: str, timeout: Optional[float] = None) -> Any:
        """Block until job ``name`` completes (test/CLI convenience —
        REST clients poll the ``finished`` flag instead)."""
        with self._lock:
            future = self._futures.get(name)
        if future is None:
            return None
        return future.result(timeout=timeout)

    def running(self) -> int:
        with self._lock:
            return sum(1 for f in self._futures.values() if not f.done())

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
