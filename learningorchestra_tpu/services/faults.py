"""Deterministic fault injection (SURVEY §5: the reference has no
fault injection anywhere; its swarm restart_policy is the only failure
response). ``Config.fault_inject`` (env ``LO_FAULT_INJECT``) names
injection sites and counts — ``"artifact_save:2"`` makes the first two
artifact-store writes raise — so failure-handling paths (retries,
failure execution documents, boot requeue) are testable end-to-end
through the real REST/job stack instead of only with hand-made flaky
callables."""

from __future__ import annotations

import threading
from typing import Dict

_lock = threading.Lock()
_used: Dict[str, int] = {}


class InjectedFault(IOError):
    pass


def reset() -> None:
    with _lock:
        _used.clear()


def maybe_inject(site: str) -> None:
    """Raise InjectedFault if ``site`` still has injection budget in
    ``Config.fault_inject`` (comma-separated ``site:count`` entries)."""
    from learningorchestra_tpu.config import get_config

    spec = getattr(get_config(), "fault_inject", "") or ""
    if not spec:
        return
    for part in spec.split(","):
        name, _, count = part.strip().partition(":")
        if name != site:
            continue
        budget = int(count or 1)
        with _lock:
            used = _used.get(site, 0)
            if used < budget:
                _used[site] = used + 1
                raise InjectedFault(
                    f"injected fault at {site} ({used + 1}/{budget})")
        return
