"""Columnar dataset ops: Histogram, DataType coercion, Projection.

These replace the reference's Mongo-aggregation / per-document /
Spark-job implementations with single-pass Arrow-columnar compute:

- **Histogram** (histogram_image/histogram.py:25-44): the reference
  runs a ``$group/$sum`` aggregation per field and stores one document
  per field of shape ``{field: [{_id: value, count: n}, ...], _id: i}``.
  Here it is a vectorized ``value_counts`` over the Arrow table —
  output document shape preserved.
- **DataType** (data_type_handler_image/data_type_update.py:15-45):
  the reference rewrites every document over the wire, one
  ``update_one`` per row. Here it is a columnar cast + dataset rewrite:
  ``"number"`` coerces strings to float (int when integral, "" -> None),
  ``"string"`` stringifies — same value semantics, O(columns) round
  trips instead of O(rows).
- **Projection** (projection_image/projection.py:32-48): the
  reference's Spark job is ``select(fields + _id)`` via mongo-spark.
  Here projection is a zero-copy Arrow column select written to a new
  dataset. (Row-parallel distribution over hosts is the ingest/feed
  layer's job; a column select needs no cluster.)

Request field names preserved: ``inputDatasetName``,
``outputDatasetName``, ``names`` (projection/histogram server.py),
``datasetName`` + ``types`` (data_type_handler server.py:16-17).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from learningorchestra_tpu.catalog import documents as D
from learningorchestra_tpu.services import validators as V

INPUT_FIELD = "inputDatasetName"
OUTPUT_FIELD = "outputDatasetName"
NAMES_FIELD = "names"
DATASET_NAME_FIELD = "datasetName"
TYPES_FIELD = "types"

STRING_TYPE = "string"
NUMBER_TYPE = "number"


class HistogramService:
    def __init__(self, context):
        self._ctx = context
        self._validator = V.RequestValidator(context)

    def create(self, body: Dict[str, Any], tool: str = "histogram",
               ) -> Tuple[int, Dict[str, Any]]:
        self._validator.required_fields(
            body, [INPUT_FIELD, OUTPUT_FIELD, NAMES_FIELD])
        parent = body[INPUT_FIELD]
        name = self._validator.safe_name(body[OUTPUT_FIELD])
        fields = body[NAMES_FIELD]
        self._validator.not_duplicate(name)
        self._validator.existing_finished(parent)
        self._validator.valid_fields(parent, fields)
        self._ctx.catalog.create_collection(
            name, D.EXPLORE_HISTOGRAM_TYPE,
            {D.PARENT_NAME_FIELD: parent, D.FIELDS_FIELD: fields})
        self._ctx.jobs.submit(
            name, lambda: self._run(parent, name, fields),
            description=f"histogram of {parent}",
            parameters={NAMES_FIELD: fields})
        return V.HTTP_CREATED, {
            "result": f"/api/learningOrchestra/v1/explore/{tool}/{name}"}

    def _run(self, parent: str, name: str, fields: List[str]) -> None:
        from learningorchestra_tpu.native import ops as nops

        table = self._ctx.catalog.read_table(parent, columns=fields)
        for i, field in enumerate(fields):
            # native-core hash aggregation (csrc/locore.cpp) over the
            # column buffers; Arrow's kernel covers nulls/exotic types
            values, counts = nops.value_counts_arrow(table.column(field))
            buckets = [
                {"_id": v, "count": int(c)}
                for v, c in zip(values, counts)]
            self._ctx.catalog.append_document(
                name, {field: buckets})
        self._ctx.catalog.update_metadata(name, {"rows": len(fields)})


class DataTypeService:
    def __init__(self, context):
        self._ctx = context
        self._validator = V.RequestValidator(context)

    def create(self, body: Dict[str, Any], tool: str = "dataType",
               ) -> Tuple[int, Dict[str, Any]]:
        self._validator.required_fields(
            body, [DATASET_NAME_FIELD, TYPES_FIELD])
        name = body[DATASET_NAME_FIELD]
        types = body[TYPES_FIELD]
        meta = self._validator.existing(name)
        if not meta.get(D.FINISHED_FIELD, False):
            raise V.HttpError(V.HTTP_NOT_ACCEPTABLE,
                              f"{V.MESSAGE_UNFINISHED_PARENT}: {name}")
        if not isinstance(types, dict) or not types:
            raise V.HttpError(V.HTTP_NOT_ACCEPTABLE, "invalid types")
        self._validator.valid_fields(name, list(types))
        for t in types.values():
            if t not in (STRING_TYPE, NUMBER_TYPE):
                raise V.HttpError(V.HTTP_NOT_ACCEPTABLE,
                                  f"invalid field type: {t}")
        # in-place rewrite: finished -> False while converting
        # (reference convert_existent_file, data_type_update.py:47-60)
        self._ctx.catalog.update_metadata(name, {D.FINISHED_FIELD: False})
        self._ctx.jobs.submit(
            name, lambda: self._run(name, types),
            description=f"dataType {types}", parameters={TYPES_FIELD: types})
        return V.HTTP_SUCCESS, {
            "result": f"/api/learningOrchestra/v1/transform/{tool}/{name}"}

    def _run(self, name: str, types: Dict[str, str]) -> None:
        import numpy as np
        import pandas as pd

        # feature-cache read (whole-column assignment on the shallow
        # copy never touches the cached frame); the write below bumps
        # the version, so the next reader re-materializes
        df = self._ctx.features.dataframe(name)
        for field, target in types.items():
            if target == STRING_TYPE:
                col = df[field].astype(object)
                df[field] = col.where(~col.isna(), "").astype(str)
            else:
                col = df[field].replace("", np.nan)
                numeric = pd.to_numeric(col, errors="raise")
                # ints stay ints when every value is integral
                # (reference float->int downcast, data_type_update.py:40-44)
                if numeric.dropna().apply(
                        lambda v: float(v).is_integer()).all():
                    numeric = numeric.astype("Int64")
                df[field] = numeric
        self._ctx.catalog.write_dataframe(name, df)
        self._ctx.catalog.update_metadata(
            name, {D.FIELDS_FIELD: [c for c in df.columns if c != "_id"]})


class ProjectionService:
    def __init__(self, context):
        self._ctx = context
        self._validator = V.RequestValidator(context)

    def create(self, body: Dict[str, Any], tool: str = "projection",
               ) -> Tuple[int, Dict[str, Any]]:
        self._validator.required_fields(
            body, [INPUT_FIELD, OUTPUT_FIELD, NAMES_FIELD])
        parent = body[INPUT_FIELD]
        name = self._validator.safe_name(body[OUTPUT_FIELD])
        fields = body[NAMES_FIELD]
        self._validator.not_duplicate(name)
        self._validator.existing_finished(parent)
        self._validator.valid_fields(parent, fields)
        self._ctx.catalog.create_collection(
            name, D.TRANSFORM_PROJECTION_TYPE,
            {D.PARENT_NAME_FIELD: parent, D.FIELDS_FIELD: fields})
        self._ctx.jobs.submit(
            name, lambda: self._run(parent, name, fields),
            description=f"projection of {parent}",
            parameters={NAMES_FIELD: fields})
        return V.HTTP_CREATED, {
            "result": f"/api/learningOrchestra/v1/transform/{tool}/{name}"}

    def _run(self, parent: str, name: str, fields: List[str]) -> None:
        table = self._ctx.catalog.read_table(parent, columns=fields)
        with self._ctx.catalog.dataset_writer(name) as writer:
            writer.write_batch(table)
        self._ctx.catalog.update_metadata(
            name, {D.FIELDS_FIELD: fields, "rows": table.num_rows})
