"""Client-library tests: drive a live server through
learningorchestra_tpu.client.Context (parity with the external
learning-orchestra-client package, reference README.md:92-103)."""

import csv

import numpy as np
import pytest


@pytest.fixture()
def server(tmp_config):
    from learningorchestra_tpu.services.server import RestServer

    srv = RestServer(host="127.0.0.1", port=0).start()
    yield srv
    srv.stop()


@pytest.fixture()
def client(server):
    from learningorchestra_tpu.client import Context

    return Context(server.base_url)


@pytest.fixture()
def small_csv(tmp_path):
    rng = np.random.default_rng(3)
    path = tmp_path / "d.csv"
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["a", "b", "label"])
        for _ in range(60):
            a, b = rng.normal(size=2)
            w.writerow([round(a, 3), round(b, 3), int(a + b > 0)])
    return path


def test_client_end_to_end(client, small_csv):
    client.dataset_csv.insert("d", str(small_csv))
    meta = client.wait("d", timeout=60)  # observe-driven wait
    assert meta["rows"] == 60

    client.function_python.run_function(
        "fx",
        "x = d[['a','b']].to_numpy()\n"
        "y = d['label'].to_numpy('int64')\n"
        "response = {'x': x, 'y': y}\n",
        parameters={"d": "$d"})
    client.function_python.wait("fx", timeout=60)

    client.model_scikitlearn.create(
        "m", "sklearn.linear_model", "LogisticRegression",
        {"max_iter": 300})
    client.model_scikitlearn.wait("m", timeout=60)

    client.train_scikitlearn.run(
        "mt", "m", "fit", {"X": "$fx.x", "y": "$fx.y"})
    client.train_scikitlearn.wait("mt", timeout=60)

    client.evaluate_scikitlearn.run(
        "me", "mt", "score", {"X": "$fx.x", "y": "$fx.y"})
    client.evaluate_scikitlearn.wait("me", timeout=60)
    body = client.evaluate_scikitlearn.read("me")
    scores = [d["result"] for d in body["result"] if "result" in d]
    assert scores and scores[0] > 0.8

    assert any(m["name"] == "d" for m in client.dataset_csv.search())
    client.predict_scikitlearn.run("mp", "mt", "predict", {"X": "$fx.x"})
    client.predict_scikitlearn.wait("mp", timeout=60)
    client.predict_scikitlearn.delete("mp")

    from learningorchestra_tpu.client import ApiError
    with pytest.raises(ApiError) as e:
        client.dataset_csv.insert("d", str(small_csv))
    assert e.value.status == 409

    health = client.health()
    assert health["status"] == "ok"
