"""Transformer family: causality, learnability, multi-axis sharding
(TP/SP/EP on the 8-virtual-device CPU mesh), artifact round-trip."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learningorchestra_tpu import config as config_mod
from learningorchestra_tpu.models.transformer import (
    LanguageModel,
    TextClassifier,
    TransformerLM,
)
from learningorchestra_tpu.parallel import sharding as sharding_lib
from learningorchestra_tpu.runtime import mesh as mesh_lib


def _mesh_config(tmp_path, shape):
    cfg = config_mod.Config(home=str(tmp_path / "lo_home"),
                            mesh_shape=shape, compute_dtype="float32")
    config_mod.set_config(cfg)
    return cfg


@pytest.fixture(autouse=True)
def _reset(tmp_path):
    yield
    config_mod.reset_config()


def _toy_tokens(n=64, seq=16, vocab=32, seed=0):
    """ABAB… pattern per sample: next token fully predictable."""
    rng = np.random.default_rng(seed)
    a = rng.integers(1, vocab, size=(n, 1))
    b = rng.integers(1, vocab, size=(n, 1))
    row = np.tile(np.stack([a, b], axis=-1).reshape(n, 2), (1, seq // 2))
    return row.astype(np.int32)


def test_causality(tmp_path):
    _mesh_config(tmp_path, "dp=1")
    module = TransformerLM(vocab_size=16, d_model=32, n_layers=2,
                           n_heads=2, attention="dot")
    tokens = jnp.asarray(np.arange(1, 13, dtype=np.int32)[None, :])
    params = module.init(jax.random.PRNGKey(0), tokens)["params"]
    logits, _ = module.apply({"params": params}, tokens)
    perturbed = tokens.at[0, -1].set(5)
    logits2, _ = module.apply({"params": params}, perturbed)
    # all positions before the perturbed one must be unchanged
    np.testing.assert_allclose(np.asarray(logits[:, :-1]),
                               np.asarray(logits2[:, :-1]),
                               atol=1e-5)
    assert not np.allclose(np.asarray(logits[:, -1]),
                           np.asarray(logits2[:, -1]))


def test_lm_learns_copy_task(tmp_path):
    _mesh_config(tmp_path, "auto")
    model = LanguageModel(vocab_size=32, d_model=32, n_layers=1,
                          n_heads=2, max_len=16, attention="dot")
    model.compile({"kind": "adam", "learning_rate": 5e-3})
    x = _toy_tokens()
    hist = model.fit(x, batch_size=32, epochs=12, shuffle=False)
    losses = hist.history["loss"]
    assert losses[-1] < losses[0] * 0.5
    ev = model.evaluate(x, batch_size=32)
    assert np.isfinite(ev["loss"])
    assert ev["accuracy"] > 0.5  # ABAB pattern is learnable fast


def test_param_shardings_tp():
    mesh = mesh_lib.build_mesh("dp=2,tp=4")
    module = TransformerLM(vocab_size=32, d_model=32, n_layers=1,
                           n_heads=4, attention="dot")
    params = module.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 8), jnp.int32))["params"]
    shardings = sharding_lib.param_shardings(params, mesh)
    q = shardings["layer_0"]["attn"]["q_proj"]["kernel"].spec
    assert "tp" in tuple(q)
    head = shardings["lm_head"]["kernel"].spec
    assert "tp" in tuple(head)


@pytest.mark.parametrize("attention", ["ring", "ulysses"])
def test_sequence_parallel_fit(tmp_path, attention):
    _mesh_config(tmp_path, "dp=2,sp=2,tp=2")
    model = LanguageModel(vocab_size=32, d_model=16, n_layers=1,
                          n_heads=2, max_len=16, attention=attention)
    x = _toy_tokens(n=32)
    hist = model.fit(x, batch_size=16, epochs=1, shuffle=False)
    assert np.isfinite(hist.history["loss"][0])


def test_moe_expert_parallel_fit(tmp_path):
    _mesh_config(tmp_path, "dp=2,ep=2,tp=2")
    model = LanguageModel(vocab_size=32, d_model=16, n_layers=1,
                          n_heads=2, d_ff=32, max_len=16,
                          attention="dot", n_experts=4)
    x = _toy_tokens(n=32)
    hist = model.fit(x, batch_size=16, epochs=1, shuffle=False)
    assert np.isfinite(hist.history["loss"][0])
    assert "moe" in model.params["layer_0"]


def test_save_load_generate(tmp_path):
    _mesh_config(tmp_path, "dp=2")
    model = LanguageModel(vocab_size=16, d_model=16, n_layers=1,
                          n_heads=2, max_len=12, attention="dot",
                          name="lm_rt")
    x = _toy_tokens(n=16, seq=8, vocab=16)
    model.fit(x, batch_size=8, epochs=1)
    art = tmp_path / "artifact"
    os.makedirs(art)
    model.__lo_save__(str(art))
    loaded = LanguageModel.__lo_load__(str(art))
    assert loaded.num_params() == model.num_params()
    p1 = model.predict(x[:8], batch_size=8)
    p2 = loaded.predict(x[:8], batch_size=8)
    np.testing.assert_allclose(p1, p2, atol=1e-5)
    gen = loaded.generate(x[0, :4], max_new_tokens=4)
    assert gen.shape == (1, 8)
    assert (gen[:, :4] == x[0, :4]).all()
    # max_new_tokens=0 must return the prompt untouched (the prefill
    # buf.at[:, s] set would clamp onto the final prompt column)
    gen0 = loaded.generate(x[0, :4], max_new_tokens=0)
    assert (gen0 == x[0, :4][None]).all()


def test_flash_sharded_fit(tmp_path):
    """The TPU-default path: shard_map'd pallas flash attention under a
    dp×tp mesh, forward AND backward (custom VJP) through fit()."""
    _mesh_config(tmp_path, "dp=2,tp=2")
    model = LanguageModel(vocab_size=32, d_model=16, n_layers=1,
                          n_heads=2, max_len=16, attention="flash")
    x = _toy_tokens(n=16)
    hist = model.fit(x, batch_size=8, epochs=1, shuffle=False)
    assert np.isfinite(hist.history["loss"][0])


def test_flash_attention_in_module(tmp_path):
    """flash impl (interpret-mode pallas) matches dot inside the LM."""
    _mesh_config(tmp_path, "dp=1")
    tokens = jnp.asarray(_toy_tokens(n=2, seq=16)[:, :16])
    mk = lambda impl: TransformerLM(  # noqa: E731
        vocab_size=32, d_model=32, n_layers=1, n_heads=2, attention=impl)
    params = mk("dot").init(jax.random.PRNGKey(0), tokens)["params"]
    out_dot, _ = mk("dot").apply({"params": params}, tokens)
    out_flash, _ = mk("flash").apply({"params": params}, tokens)
    np.testing.assert_allclose(np.asarray(out_dot), np.asarray(out_flash),
                               atol=1e-4, rtol=1e-4)


def test_sample_top_k_top_p_filters():
    """top_k=1 at temperature>0 must equal greedy; a tight nucleus
    (top_p -> 0) likewise keeps only the argmax token; and the pad
    token 0 is never emitted by any mode."""
    import jax
    import jax.numpy as jnp

    logits = jnp.asarray([[5.0, 1.0, 4.0, 3.0, 2.0],
                          [0.0, 2.0, 9.0, 1.0, 8.0]])
    key = jax.random.PRNGKey(0)
    greedy = LanguageModel._sample(logits, 0.0, key)
    k1 = LanguageModel._sample(logits, 1.0, key, top_k=1)
    p_tiny = LanguageModel._sample(logits, 1.0, key, top_p=1e-6)
    assert jnp.array_equal(greedy, k1)
    assert jnp.array_equal(greedy, p_tiny)
    # pad-token mask: a logits row where 0 dominates must not pick it
    pad_heavy = jnp.asarray([[99.0, 1.0, 2.0, 3.0, 4.0]])
    for draw in range(4):
        out = LanguageModel._sample(
            pad_heavy, 1.0, jax.random.PRNGKey(draw), top_k=3)
        assert int(out[0]) != 0
    # a loose nucleus still samples inside the top mass
    wide = LanguageModel._sample(logits, 1.0, key, top_k=3, top_p=0.9)
    assert wide.shape == (2,)


def test_generate_with_sampling_filters(tmp_path):
    _mesh_config(tmp_path, "dp=2")
    model = LanguageModel(vocab_size=16, d_model=16, n_layers=1,
                          n_heads=2, max_len=12, attention="dot",
                          name="lm_topk")
    x = _toy_tokens(n=16, seq=8, vocab=16)
    model.fit(x=x, epochs=1, batch_size=8)
    out = model.generate(x[:2, :4], max_new_tokens=4, temperature=0.8,
                         top_k=4, top_p=0.9, seed=3)
    assert out.shape == (2, 8)
    assert (out[:, :4] == x[:2, :4]).all()
    assert (out > 0).all()


def test_generate_sampling_validation(tmp_path):
    _mesh_config(tmp_path, "dp=2")
    model = LanguageModel(vocab_size=16, d_model=16, n_layers=1,
                          n_heads=2, max_len=12, attention="dot",
                          name="lm_val")
    x = _toy_tokens(n=16, seq=8, vocab=16)
    model.fit(x=x, epochs=1, batch_size=8)
    with pytest.raises(ValueError):
        model.generate(x[:1, :4], temperature=1.0, top_k=0)
    with pytest.raises(ValueError):
        model.generate(x[:1, :4], temperature=1.0, top_p=0.0)
    with pytest.raises(ValueError):
        model.generate(x[:1, :4], temperature=1.0, top_p=1.5)
    # no-op values normalize to the unfiltered compile (same sig)
    model.generate(x[:1, :4], max_new_tokens=2, temperature=1.0)
    n_compiles = len(model._gen_cache_fns)
    model.generate(x[:1, :4], max_new_tokens=2, temperature=1.0,
                   top_k=16, top_p=1.0)
    assert len(model._gen_cache_fns) == n_compiles


def test_ring_attention_32k_step_lowers(tmp_path):
    """Long-context static-shape proof: the full sharded train step at
    seq 32768 over an sp=8 ring LOWERS (trace + SPMD partitioning)
    without materializing any (s, s) buffer — execution would be the
    TPU's job; the lowering is what must not depend on sequence
    length fitting in one device's memory."""
    _mesh_config(tmp_path, "sp=8")
    model = LanguageModel(vocab_size=64, d_model=32, n_layers=1,
                          n_heads=4, d_ff=64, max_len=32768,
                          attention="ring", name="lm32k")
    x = np.ones((1, 32768), np.int32)
    model._build_params(x[:, :8])  # tiny init; shapes are per-call
    eng = model._get_engine()
    state = eng.init_state(model.params)
    step = jax.jit(eng._train_step_body)
    lowered = step.lower(state, {"x": jax.ShapeDtypeStruct(
        (1, 32768), jnp.int32)}, jax.random.PRNGKey(0))
    text = lowered.as_text()
    # the ring runs inside a shard_map manual computation over the
    # 8-way sp mesh (the ppermute appears only after XLA partitioning,
    # which .compile() would run — lowering is the static-shape proof)
    assert "num_partitions = 8" in text
    assert "manual_computation" in text or "SPMDFullToShardShape" in text
    # the invariant that makes 32k viable: nothing in the lowered
    # program materializes the (s, s) score/mask tensor (the dot path
    # lowers a 32768x32768 buffer here; the ring must not)
    assert "32768x32768" not in text


def test_ulysses_16k_mixed_mesh_step_lowers(tmp_path):
    """Ulysses head-sharded SP composed with dp on one mesh: the
    seq-16384 train step partitions over sp=4,dp=2 with the
    head-scatter/seq-gather all_to_all pair in the manual
    computation. (On TPU the inner per-head attention is the flash
    kernel — no (s, s) buffer, ulysses.py:41-48; the dense tile in
    this CPU lowering is the test backend's reference fallback.)"""
    _mesh_config(tmp_path, "dp=2,sp=4")
    model = LanguageModel(vocab_size=64, d_model=32, n_layers=1,
                          n_heads=4, d_ff=64, max_len=16384,
                          attention="ulysses", name="lm16k")
    x = np.ones((2, 16384), np.int32)
    model._build_params(x[:, :8])
    eng = model._get_engine()
    state = eng.init_state(model.params)
    step = jax.jit(eng._train_step_body)
    text = step.lower(state, {"x": jax.ShapeDtypeStruct(
        (2, 16384), jnp.int32)}, jax.random.PRNGKey(0)).as_text()
    assert "num_partitions = 8" in text
    assert "manual_computation" in text or "SPMDFullToShardShape" in text
    assert "all_to_all" in text


# ----------------------------------------------------------------------
# fused lm_head (chunked projection + CE: the d=512 roofline epilogue
# fix — BENCHMARKS.md names the vocab-32k logits tensor as the gap)
# ----------------------------------------------------------------------
def test_fused_head_matches_full_logits_loss_and_grads(tmp_path):
    """FusedHeadOut training path == full-logits path: same loss,
    same grads (to float tolerance), accuracy emitted from the scan
    equals token_accuracy on full logits."""
    from learningorchestra_tpu.models import transformer as T

    _mesh_config(tmp_path, "dp=2")
    mod_full = T.TransformerLM(vocab_size=61, d_model=16, n_layers=1,
                               n_heads=2, fused_head_chunk=0)
    mod_fused = T.TransformerLM(vocab_size=61, d_model=16, n_layers=1,
                                n_heads=2, fused_head_chunk=7)
    toks = (np.arange(4 * 13).reshape(4, 13) % 60 + 1).astype(np.int32)
    toks[2, 7:] = 0  # padding must stay masked in both paths
    params = mod_full.init(jax.random.PRNGKey(0),
                           jnp.asarray(toks[:1]), train=False)["params"]
    loss_fn = T.next_token_loss(0.01, head_chunk=7)
    batch = {"x": jnp.asarray(toks)}

    def full_loss(p):
        return loss_fn(mod_full.apply({"params": p}, batch["x"],
                                      train=True), batch, None)

    def fused_loss(p):
        loss, extra = loss_fn(mod_fused.apply({"params": p}, batch["x"],
                                              train=True), batch, None)
        return loss, extra

    lf, gf = jax.value_and_grad(full_loss)(params)
    (lz, extra), gz = jax.value_and_grad(fused_loss, has_aux=True)(
        params)
    assert abs(float(lf) - float(lz)) < 1e-5
    for a, b in zip(jax.tree_util.tree_leaves(gf),
                    jax.tree_util.tree_leaves(gz)):
        np.testing.assert_allclose(a, b, atol=1e-6)
    acc_s, acc_c = T.token_accuracy(
        mod_full.apply({"params": params}, batch["x"], train=True),
        batch, None)
    assert float(extra["accuracy"][0]) == float(acc_s)
    assert float(extra["accuracy"][1]) == float(acc_c)


def test_fused_head_auto_rule_and_training(tmp_path):
    """Auto rule: large vocab fuses (including under seq-parallel
    attention — the shard_map loss twin), small vocab does not;
    LO_LM_HEAD_CHUNK=0 force-disables. A fused fit still reports loss
    AND accuracy through the engine."""
    import os as _os

    from learningorchestra_tpu.models.transformer import LanguageModel

    _mesh_config(tmp_path, "dp=2")
    big = LanguageModel(vocab_size=8192, d_model=32, n_layers=1,
                        n_heads=4, max_len=16)
    assert big._head_chunk() == 1024
    small = LanguageModel(vocab_size=100, d_model=32, n_layers=1,
                          n_heads=4, max_len=16)
    assert small._head_chunk() == 0
    ring = LanguageModel(vocab_size=8192, d_model=32, n_layers=1,
                         n_heads=4, max_len=16, attention="ring")
    # SP meshes fuse too (the shard_map loss twin); auto rule is
    # vocab-driven only
    assert ring._head_chunk() == 1024
    _os.environ["LO_LM_HEAD_CHUNK"] = "0"
    try:
        assert big._head_chunk() == 0
    finally:
        del _os.environ["LO_LM_HEAD_CHUNK"]

    toks = (np.random.default_rng(0).integers(
        1, 8192, size=(8, 12))).astype(np.int32)
    hist = big.fit(toks, batch_size=4, epochs=1)
    assert np.isfinite(hist.history["loss"][0])
    assert "accuracy" in hist.history


def test_remat_policies_match_no_remat(tmp_path):
    """Per-layer rematerialization (dots / full policies) changes
    memory, never math: identical seeds give identical training
    losses across all three settings."""
    losses = {}
    for remat in ("none", "dots", "full"):
        _mesh_config(tmp_path, "dp=2")
        from learningorchestra_tpu.models.transformer import (
            LanguageModel)

        lm = LanguageModel(vocab_size=64, d_model=32, n_layers=2,
                           n_heads=4, max_len=16, attention="dot",
                           remat=remat)
        toks = (np.arange(8 * 12).reshape(8, 12) % 63 + 1
                ).astype(np.int32)
        hist = lm.fit(toks, batch_size=4, epochs=1, shuffle=False)
        losses[remat] = hist.history["loss"][0]
    assert np.isfinite(losses["none"])
    np.testing.assert_allclose(losses["dots"], losses["none"],
                               rtol=1e-5)
    np.testing.assert_allclose(losses["full"], losses["none"],
                               rtol=1e-5)


@pytest.mark.parametrize("mesh_shape", ["dp=2,sp=4", "sp=2,tp=4"])
def test_sharded_fused_head_matches_flat(tmp_path, mesh_shape):
    """The shard_map fused loss (sequence-parallel + Megatron-style
    tp vocab reduction) equals the flat chunked path: same loss, same
    grads, same accuracy sums."""
    from learningorchestra_tpu.models import transformer as T
    from learningorchestra_tpu.runtime import mesh as mesh_lib

    _mesh_config(tmp_path, mesh_shape)
    mesh = mesh_lib.get_default_mesh()
    mod = T.TransformerLM(vocab_size=64, d_model=16, n_layers=1,
                          n_heads=2, fused_head_chunk=5,
                          attention="dot")
    toks = (np.arange(4 * 8).reshape(4, 8) % 63 + 1).astype(np.int32)
    toks[1, 5:] = 0
    params = mod.init(jax.random.PRNGKey(0), jnp.asarray(toks[:1]),
                      train=False)["params"]
    batch = {"x": jnp.asarray(toks)}
    out = mod.apply({"params": params}, batch["x"], train=True)
    assert isinstance(out, T.FusedHeadOut)

    flat_loss, flat_extra = T._fused_head_loss(out, batch, None, 5,
                                               0.01)
    sh_loss, sh_extra = T._fused_head_loss_sharded(out, batch, None,
                                                   5, 0.01, mesh)
    np.testing.assert_allclose(float(sh_loss), float(flat_loss),
                               rtol=1e-5)
    np.testing.assert_allclose(float(sh_extra["accuracy"][0]),
                               float(flat_extra["accuracy"][0]))
    np.testing.assert_allclose(float(sh_extra["accuracy"][1]),
                               float(flat_extra["accuracy"][1]),
                               rtol=1e-6)

    # grads agree through either loss
    def loss_of(p, sharded):
        o = mod.apply({"params": p}, batch["x"], train=True)
        if sharded:
            loss, _ = T._fused_head_loss_sharded(o, batch, None, 5,
                                                 0.01, mesh)
        else:
            loss, _ = T._fused_head_loss(o, batch, None, 5, 0.01)
        return loss

    g_flat = jax.grad(lambda p: loss_of(p, False))(params)
    g_sh = jax.grad(lambda p: loss_of(p, True))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_flat),
                    jax.tree_util.tree_leaves(g_sh)):
        np.testing.assert_allclose(a, b, atol=1e-5)


def test_ring_fit_uses_sharded_fused_head(tmp_path):
    """End-to-end: a large-vocab ring-attention fit takes the fused
    head (auto rule no longer excludes SP) and still reports loss +
    accuracy through the engine."""
    from learningorchestra_tpu.models.transformer import LanguageModel

    _mesh_config(tmp_path, "dp=2,sp=4")
    lm = LanguageModel(vocab_size=8192, d_model=32, n_layers=1,
                       n_heads=4, max_len=16, attention="ring")
    assert lm._head_chunk() == 1024
    toks = (np.random.default_rng(0).integers(
        1, 8192, size=(8, 16))).astype(np.int32)
    hist = lm.fit(toks, batch_size=8, epochs=1)
    assert np.isfinite(hist.history["loss"][0])
    assert "accuracy" in hist.history


# ----------------------------------------------------------------------
# grouped-query attention (GQA / MQA)
# ----------------------------------------------------------------------
def test_gqa_param_shapes_and_training(tmp_path):
    """n_kv_heads < n_heads projects K/V to fewer heads: the KV cache
    and k/v_proj shrink by n_heads/n_kv_heads while q/o keep full
    width; training still learns (the repeat-to-full-heads path)."""
    _mesh_config(tmp_path, "auto")
    model = LanguageModel(vocab_size=32, d_model=32, n_layers=1,
                          n_heads=4, n_kv_heads=2, max_len=16,
                          attention="dot")
    model.compile({"kind": "adam", "learning_rate": 5e-3})
    x = _toy_tokens()
    hist = model.fit(x, batch_size=32, epochs=12, shuffle=False)
    assert hist.history["loss"][-1] < hist.history["loss"][0] * 0.5
    attn = model.params["layer_0"]["attn"]
    head_dim = 32 // 4
    assert attn["q_proj"]["kernel"].shape == (32, 4 * head_dim)
    assert attn["k_proj"]["kernel"].shape == (32, 2 * head_dim)
    assert attn["v_proj"]["kernel"].shape == (32, 2 * head_dim)


def test_gqa_n_kv_heads_must_divide():
    with pytest.raises(ValueError, match="positive divisor"):
        LanguageModel(vocab_size=8, n_heads=4, n_kv_heads=3)
    with pytest.raises(ValueError, match="positive divisor"):
        # 4 % -2 == 0 — the sign check must fire, not the divide check
        LanguageModel(vocab_size=8, n_heads=4, n_kv_heads=-2)


def test_gqa_cached_decode_matches_full_forward(tmp_path):
    """The grouped single-token decode path (KV cache stored at
    n_kv_heads, grouped einsum — no head repeat) must produce the
    same greedy continuation as argmax over the full training-path
    forward re-run per position."""
    _mesh_config(tmp_path, "dp=1")
    model = LanguageModel(vocab_size=16, d_model=16, n_layers=2,
                          n_heads=4, n_kv_heads=2, max_len=12,
                          attention="dot")
    x = _toy_tokens(n=8, seq=8, vocab=16)
    model.fit(x, batch_size=8, epochs=1)

    prompt = x[:2, :4]
    gen = model.generate(prompt, max_new_tokens=4, temperature=0.0)

    # oracle: full forward per position, argmax with pad masked out
    module = model._module_for(None)
    buf = np.zeros((2, 8), np.int32)
    buf[:, :4] = prompt
    for pos in range(4, 8):
        logits, _ = module.apply({"params": model.params},
                                 jnp.asarray(buf))
        last = np.asarray(logits[:, pos - 1]).astype(np.float64)
        last[:, 0] = -np.inf
        buf[:, pos] = last.argmax(-1)
    np.testing.assert_array_equal(gen, buf)

    # the cache really is kv-heads sized
    _, mut = module.apply({"params": model.params},
                          jnp.asarray(prompt), cache_len=8,
                          mutable=["cache"])
    k_cache = mut["cache"]["layer_0"]["attn"]["k"]
    assert k_cache.shape == (2, 8, 2, 16 // 4)


def test_mqa_tp_sharding_replicates_non_divisible_kv(tmp_path):
    """MQA under TP: a k_proj column dim narrower than the tp axis
    replicates (spec_for drops the non-divisible axis) instead of
    erroring, while q_proj stays column-sharded."""
    mesh = mesh_lib.build_mesh("tp=4")
    module = TransformerLM(vocab_size=32, d_model=8, n_layers=1,
                           n_heads=4, n_kv_heads=1, attention="dot")
    params = module.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 8), jnp.int32))["params"]
    shardings = sharding_lib.param_shardings(params, mesh)
    q = shardings["layer_0"]["attn"]["q_proj"]["kernel"].spec
    k = shardings["layer_0"]["attn"]["k_proj"]["kernel"].spec
    assert "tp" in tuple(q)
    assert "tp" not in tuple(jax.tree_util.tree_leaves(tuple(k)) or ())


def test_gqa_tp_rules_are_head_granular(tmp_path):
    """kv_heads=2 under tp=4: raw k_proj columns (2*head_dim=64)
    DIVIDE tp, but sharding would split mid-head — the model's rule
    set must replicate k/v_proj while q/o stay TP-sharded."""
    _mesh_config(tmp_path, "tp=4")
    lm = LanguageModel(vocab_size=32, d_model=256, n_layers=1,
                       n_heads=8, n_kv_heads=2, max_len=16,
                       attention="dot")
    mesh = mesh_lib.build_mesh("tp=4")
    rules = lm._param_rules(mesh)
    k_spec = sharding_lib.spec_for("layer_0/attn/k_proj/kernel",
                                   (256, 64), mesh, rules, fsdp=False)
    q_spec = sharding_lib.spec_for("layer_0/attn/q_proj/kernel",
                                   (256, 256), mesh, rules, fsdp=False)
    assert tuple(k_spec) == (None, None) or tuple(k_spec) == ()
    assert "tp" in tuple(q_spec)
    # kv_heads=4 divides tp=4 -> no override, k_proj TP-sharded
    lm4 = LanguageModel(vocab_size=32, d_model=256, n_layers=1,
                        n_heads=8, n_kv_heads=4, max_len=16,
                        attention="dot")
    k4 = sharding_lib.spec_for("layer_0/attn/k_proj/kernel",
                               (256, 128), mesh, lm4._param_rules(mesh),
                               fsdp=False)
    assert "tp" in tuple(k4)


def test_gqa_trains_under_tp_and_sp(tmp_path):
    """GQA fit on a real multi-axis mesh: kv_heads=2 under tp=2 (kv
    divides tp -> k/v stay TP-sharded) composing with sequence-
    parallel ring attention; loss must be finite through the GSPMD
    engine."""
    _mesh_config(tmp_path, "dp=2,sp=2,tp=2")
    model = LanguageModel(vocab_size=32, d_model=16, n_layers=1,
                          n_heads=4, n_kv_heads=2, max_len=16,
                          attention="ring")
    x = _toy_tokens(n=32)
    hist = model.fit(x, batch_size=16, epochs=1, shuffle=False)
    assert np.isfinite(hist.history["loss"][0])


def test_gqa_artifact_round_trip(tmp_path):
    _mesh_config(tmp_path, "dp=1")
    model = LanguageModel(vocab_size=16, d_model=16, n_layers=1,
                          n_heads=4, n_kv_heads=1, max_len=12,
                          attention="dot", name="gqa_rt")
    x = _toy_tokens(n=8, seq=8, vocab=16)
    model.fit(x, batch_size=8, epochs=1)
    art = tmp_path / "artifact"
    os.makedirs(art)
    model.__lo_save__(str(art))
    loaded = LanguageModel.__lo_load__(str(art))
    assert loaded.n_kv_heads == 1
    np.testing.assert_allclose(model.predict(x[:4], batch_size=4),
                               loaded.predict(x[:4], batch_size=4),
                               atol=1e-5)


# ----------------------------------------------------------------------
# fused q/k/v + gate/up projections (the d=512 MXU-tiling experiment)
# ----------------------------------------------------------------------
def test_fused_proj_matches_unfused_math(tmp_path):
    """fused_proj concatenates the SAME three projections into one
    matmul: splitting an unfused init into the fused layout must give
    bit-comparable logits."""
    from learningorchestra_tpu.models import transformer as T

    _mesh_config(tmp_path, "dp=1")
    kw = dict(vocab_size=32, d_model=16, n_layers=1, n_heads=2,
              attention="dot")
    plain = T.TransformerLM(**kw)
    fused = T.TransformerLM(fused_proj=True, **kw)
    toks = (np.arange(2 * 8).reshape(2, 8) % 31 + 1).astype(np.int32)
    params = plain.init(jax.random.PRNGKey(0), jnp.asarray(toks))["params"]

    fp = jax.tree_util.tree_map(lambda x: x, params)  # shallow copy
    attn = dict(fp["layer_0"]["attn"])
    attn["qkv_proj"] = {"kernel": jnp.concatenate(
        [attn.pop("q_proj")["kernel"], attn.pop("k_proj")["kernel"],
         attn.pop("v_proj")["kernel"]], axis=1)}
    mlp = dict(fp["layer_0"]["mlp"])
    mlp["gate_up"] = {"kernel": jnp.concatenate(
        [mlp.pop("gate")["kernel"], mlp.pop("up_proj")["kernel"]],
        axis=1)}
    fp["layer_0"] = dict(fp["layer_0"], attn=attn, mlp=mlp)

    lg_plain, _ = plain.apply({"params": params}, jnp.asarray(toks))
    lg_fused, _ = fused.apply({"params": fp}, jnp.asarray(toks))
    np.testing.assert_allclose(np.asarray(lg_fused),
                               np.asarray(lg_plain), atol=1e-5)


def test_fused_proj_trains_and_generates(tmp_path):
    _mesh_config(tmp_path, "dp=1")
    lm = LanguageModel(vocab_size=32, d_model=16, n_layers=1,
                       n_heads=2, max_len=12, attention="dot",
                       fused_proj=True)
    x = _toy_tokens(n=16, seq=8, vocab=32)
    hist = lm.fit(x, batch_size=8, epochs=2)
    assert np.isfinite(hist.history["loss"][0])
    attn = lm.params["layer_0"]["attn"]
    assert "qkv_proj" in attn and "q_proj" not in attn
    assert "gate_up" in lm.params["layer_0"]["mlp"]
    gen = lm.generate(x[:1, :4], max_new_tokens=4, temperature=0.0)
    assert gen.shape == (1, 8)


def test_fused_proj_tree_is_mesh_independent(tmp_path):
    """The param tree depends only on the model config: a fused
    artifact trained on a tp=1 mesh loads and predicts under tp=2 —
    the sharding rules replicate the fused kernels there (a column
    shard would cross q/k/v block boundaries) instead of changing
    the tree."""
    _mesh_config(tmp_path, "dp=1")
    lm = LanguageModel(vocab_size=32, d_model=16, n_layers=1,
                       n_heads=2, max_len=12, attention="dot",
                       fused_proj=True, name="fp_rt")
    x = _toy_tokens(n=8, seq=8, vocab=32)
    lm.fit(x, batch_size=8, epochs=1)
    art = tmp_path / "artifact"
    os.makedirs(art)
    lm.__lo_save__(str(art))
    p_ref = lm.predict(x[:4], batch_size=4)

    _mesh_config(tmp_path, "tp=2")
    loaded = LanguageModel.__lo_load__(str(art))
    assert "qkv_proj" in loaded.params["layer_0"]["attn"]
    p_tp = loaded.predict(x[:4], batch_size=4)
    np.testing.assert_allclose(p_tp, p_ref, atol=1e-5)
    # and the tp rules replicate the fused kernels
    mesh = mesh_lib.build_mesh("tp=2")
    spec = sharding_lib.spec_for(
        "layer_0/attn/qkv_proj/kernel", (16, 48), mesh,
        loaded._param_rules(mesh), fsdp=False)
    assert "tp" not in tuple(jax.tree_util.tree_leaves(tuple(spec))
                             or ())


def test_fused_proj_gqa_keeps_mlp_fusion(tmp_path):
    """Under GQA only the q/k/v widths differ: attention self-gates
    back to separate projections while the MLP still fuses."""
    from learningorchestra_tpu.models import transformer as T

    _mesh_config(tmp_path, "dp=1")
    mod = T.TransformerLM(vocab_size=32, d_model=16, n_layers=1,
                          n_heads=2, n_kv_heads=1, attention="dot",
                          fused_proj=True)
    params = mod.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 8), jnp.int32))["params"]
    assert "q_proj" in params["layer_0"]["attn"]
    assert "qkv_proj" not in params["layer_0"]["attn"]
    assert "gate_up" in params["layer_0"]["mlp"]


def test_fused_proj_env_override_strict(tmp_path, monkeypatch):
    _mesh_config(tmp_path, "dp=1")
    lm = LanguageModel(vocab_size=8, d_model=8, n_heads=2,
                       fused_proj=True)
    monkeypatch.setenv("LO_TLM_FUSED_PROJ", "0")
    assert lm._resolved_fused_proj() is False
    monkeypatch.setenv("LO_TLM_FUSED_PROJ", "on")
    with pytest.raises(ValueError, match="LO_TLM_FUSED_PROJ"):
        lm._resolved_fused_proj()
    monkeypatch.setenv("LO_TLM_FUSED_PROJ", "")
    assert lm._resolved_fused_proj() is True


# ----------------------------------------------------------------------
# LoRA fine-tuning
# ----------------------------------------------------------------------
def test_lora_fit_trains_only_adapters(tmp_path):
    """With lora_rank set, fit() must leave every base kernel
    bit-identical and move only lora_a/lora_b (the frozen-base
    multi_transform optimizer)."""
    _mesh_config(tmp_path, "dp=1")
    lm = LanguageModel(vocab_size=32, d_model=16, n_layers=1,
                       n_heads=2, max_len=12, attention="dot",
                       lora_rank=4)
    x = _toy_tokens(n=16, seq=8, vocab=32)
    lm.fit(x, batch_size=8, epochs=1)  # builds params
    import jax.tree_util as jtu
    before = {jtu.keystr(p): np.asarray(v)
              for p, v in jtu.tree_flatten_with_path(lm.params)[0]}
    lm.fit(x, batch_size=8, epochs=3)
    after = {jtu.keystr(p): np.asarray(v)
             for p, v in jtu.tree_flatten_with_path(lm.params)[0]}
    moved = {k for k in before
             if not np.array_equal(before[k], after[k])}
    assert moved, "nothing trained at all"
    assert all("lora_" in k for k in moved), moved
    frozen = {k for k in before if "lora_" not in k}
    assert frozen and all(np.array_equal(before[k], after[k])
                          for k in frozen)


def test_lora_enable_merge_roundtrip(tmp_path):
    """Plain pretrain -> enable_lora (step-0 predictions unchanged:
    B=0) -> adapter fit -> merge_lora folds W += A·B·α/r with
    identical predictions and a plain param tree."""
    _mesh_config(tmp_path, "dp=1")
    lm = LanguageModel(vocab_size=32, d_model=16, n_layers=1,
                       n_heads=2, max_len=12, attention="dot")
    x = _toy_tokens(n=16, seq=8, vocab=32)
    lm.fit(x, batch_size=8, epochs=2)
    base_pred = lm.predict(x[:4], batch_size=4)

    lm.enable_lora(rank=4)
    np.testing.assert_allclose(lm.predict(x[:4], batch_size=4),
                               base_pred, atol=1e-5)
    lm.fit(x, batch_size=8, epochs=3)
    adapted_pred = lm.predict(x[:4], batch_size=4)

    lm.merge_lora()
    assert lm.lora_rank == 0
    flat = jax.tree_util.tree_flatten_with_path(lm.params)[0]
    assert not any("lora_" in jax.tree_util.keystr(p)
                   for p, _ in flat)
    np.testing.assert_allclose(lm.predict(x[:4], batch_size=4),
                               adapted_pred, atol=1e-4)
    # double-merge and re-enable guards
    with pytest.raises(RuntimeError):
        lm.merge_lora()
    lm.enable_lora(rank=2)
    with pytest.raises(RuntimeError):
        lm.enable_lora(rank=2)


def test_lora_artifact_round_trip(tmp_path):
    _mesh_config(tmp_path, "dp=1")
    lm = LanguageModel(vocab_size=32, d_model=16, n_layers=1,
                       n_heads=2, max_len=12, attention="dot",
                       lora_rank=2, name="lora_rt")
    x = _toy_tokens(n=8, seq=8, vocab=32)
    lm.fit(x, batch_size=8, epochs=1)
    art = tmp_path / "artifact"
    os.makedirs(art)
    lm.__lo_save__(str(art))
    loaded = LanguageModel.__lo_load__(str(art))
    assert loaded.lora_rank == 2
    np.testing.assert_allclose(loaded.predict(x[:4], batch_size=4),
                               lm.predict(x[:4], batch_size=4),
                               atol=1e-5)


# ----------------------------------------------------------------------
# sliding-window attention
# ----------------------------------------------------------------------
def test_sliding_window_locality_and_decode_parity(tmp_path):
    """A windowed LM's logits at position p must ignore tokens before
    p-W+1 (locality), and the windowed cached decode must match the
    windowed full-forward argmax rollout."""
    from learningorchestra_tpu.models import transformer as T

    _mesh_config(tmp_path, "dp=1")
    W = 4
    mod = T.TransformerLM(vocab_size=16, d_model=16, n_layers=2,
                          n_heads=2, attention="dot", sliding_window=W)
    toks = jnp.asarray((np.arange(1, 13) % 15 + 1)[None, :]
                       .astype(np.int32))
    params = mod.init(jax.random.PRNGKey(0), toks)["params"]
    logits, _ = mod.apply({"params": params}, toks)
    # perturb position 0: with 2 layers the receptive field at p is
    # 2(W-1) back, so positions >= 2W-1 are out of reach of token 0
    pert = toks.at[0, 0].set(9)
    logits2, _ = mod.apply({"params": params}, pert)
    reach = 2 * (W - 1)
    np.testing.assert_allclose(np.asarray(logits[:, reach + 1:]),
                               np.asarray(logits2[:, reach + 1:]),
                               atol=1e-5)
    assert not np.allclose(np.asarray(logits[:, 0]),
                           np.asarray(logits2[:, 0]))

    # decode parity through generate()
    lm = LanguageModel(vocab_size=16, d_model=16, n_layers=2,
                       n_heads=2, max_len=12, attention="dot",
                       sliding_window=W)
    x = _toy_tokens(n=8, seq=8, vocab=16)
    lm.fit(x, batch_size=8, epochs=1)
    prompt = x[:2, :4]
    gen = lm.generate(prompt, max_new_tokens=4, temperature=0.0)
    module = lm._module_for(None)
    buf = np.zeros((2, 8), np.int32)
    buf[:, :4] = prompt
    for pos in range(4, 8):
        lg, _ = module.apply({"params": lm.params}, jnp.asarray(buf))
        last = np.asarray(lg[:, pos - 1]).astype(np.float64)
        last[:, 0] = -np.inf
        buf[:, pos] = last.argmax(-1)
    np.testing.assert_array_equal(gen, buf)


def test_sliding_window_flash_matches_dot_in_module(tmp_path):
    _mesh_config(tmp_path, "dp=1")
    from learningorchestra_tpu.models import transformer as T

    tokens = jnp.asarray(_toy_tokens(n=2, seq=16)[:, :16])
    mk = lambda impl: T.TransformerLM(  # noqa: E731
        vocab_size=32, d_model=32, n_layers=1, n_heads=2,
        attention=impl, sliding_window=5)
    params = mk("dot").init(jax.random.PRNGKey(0), tokens)["params"]
    out_dot, _ = mk("dot").apply({"params": params}, tokens)
    out_flash, _ = mk("flash").apply({"params": params}, tokens)
    np.testing.assert_allclose(np.asarray(out_dot),
                               np.asarray(out_flash),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("attention", ["ring", "ulysses"])
def test_sliding_window_sequence_parallel_fit(tmp_path, attention):
    """Windowed attention composes with sequence parallelism: ring
    hops apply the banded mask at static cross-shard offsets (hops
    wholly below the band skip), Ulysses windows its gathered local
    attention."""
    _mesh_config(tmp_path, "dp=2,sp=2")
    model = LanguageModel(vocab_size=32, d_model=16, n_layers=1,
                          n_heads=2, max_len=16, attention=attention,
                          sliding_window=6)
    x = _toy_tokens(n=32)
    hist = model.fit(x, batch_size=16, epochs=1, shuffle=False)
    assert np.isfinite(hist.history["loss"][0])
    # parity with the single-device banded path on the same params
    from learningorchestra_tpu.models import transformer as T

    toks = jnp.asarray(x[:4])
    sp_mod = model._module_for(None)
    logits_sp, _ = sp_mod.apply({"params": model.params}, toks)
    config_mod.set_config(config_mod.Config(
        home=str(tmp_path / "lo_home"), mesh_shape="dp=1",
        compute_dtype="float32"))
    ref_mod = T.TransformerLM(
        vocab_size=32, d_model=16, n_layers=1, n_heads=2,
        attention="dot", sliding_window=6)
    logits_ref, _ = ref_mod.apply({"params": model.params}, toks)
    np.testing.assert_allclose(np.asarray(logits_sp),
                               np.asarray(logits_ref),
                               atol=2e-4, rtol=2e-4)


def test_gqa_flash_matches_dot_in_module(tmp_path):
    """GQA through the flash impl (kernel consumes kv-width K/V
    natively) equals the dot impl's repeat-based math."""
    from learningorchestra_tpu.models import transformer as T

    _mesh_config(tmp_path, "dp=1")
    tokens = jnp.asarray(_toy_tokens(n=2, seq=16)[:, :16])
    mk = lambda impl: T.TransformerLM(  # noqa: E731
        vocab_size=32, d_model=32, n_layers=1, n_heads=4,
        n_kv_heads=2, attention=impl)
    params = mk("dot").init(jax.random.PRNGKey(0), tokens)["params"]
    out_dot, _ = mk("dot").apply({"params": params}, tokens)
    out_flash, _ = mk("flash").apply({"params": params}, tokens)
    np.testing.assert_allclose(np.asarray(out_dot),
                               np.asarray(out_flash),
                               atol=1e-4, rtol=1e-4)


def test_gqa_flash_sharded_fit_stays_native(tmp_path):
    """GQA + flash under a dp×tp mesh where kv heads divide tp: the
    shard_map path feeds kv-width K/V (no repeat) and training still
    produces a finite loss."""
    _mesh_config(tmp_path, "dp=2,tp=2")
    model = LanguageModel(vocab_size=32, d_model=32, n_layers=1,
                          n_heads=4, n_kv_heads=2, max_len=16,
                          attention="flash")
    x = _toy_tokens(n=16)
    hist = model.fit(x, batch_size=8, epochs=1, shuffle=False)
    assert np.isfinite(hist.history["loss"][0])


# ----------------------------------------------------------------------
# beam search
# ----------------------------------------------------------------------
def _seq_logprob(lm, seq, prompt_len):
    """Model's own summed log-prob of seq's continuation (pad-masked)."""
    logits = lm.predict(seq[None], batch_size=1)[0]
    lp = jax.nn.log_softmax(
        jnp.asarray(logits).astype(jnp.float32).at[..., 0]
        .set(-1e30), axis=-1)
    tot = 0.0
    for pos in range(prompt_len, len(seq)):
        tot += float(lp[pos - 1, seq[pos]])
    return tot


def test_beam_search_matches_greedy_and_finds_optimum(tmp_path):
    """num_beams=1 must equal greedy decode exactly. For a 2-token
    horizon a FULL-WIDTH beam (num_beams = vocab-1, every non-pad
    first token kept) is exhaustive search, so its result must be the
    global argmax continuation — a guaranteed property, unlike
    beam-vs-greedy comparisons (narrow beams may prune the greedy
    path)."""
    _mesh_config(tmp_path, "dp=1")
    V = 12
    lm = LanguageModel(vocab_size=V, d_model=16, n_layers=1,
                       n_heads=2, max_len=16, attention="dot")
    x = _toy_tokens(n=16, seq=12, vocab=V)
    lm.fit(x, batch_size=8, epochs=2)
    prompt = x[:2, :4]

    greedy = lm.generate(prompt, max_new_tokens=6, temperature=0.0)
    beam1 = lm.generate(prompt, max_new_tokens=6, num_beams=1)
    np.testing.assert_array_equal(beam1, greedy)

    full = lm.generate(prompt, max_new_tokens=2, num_beams=V - 1)
    assert (full[:, :4] == prompt).all() and (full > 0).all()
    # brute-force oracle over all (V-1)^2 continuations
    for i in range(2):
        best_lp, best_seq = -np.inf, None
        for t1 in range(1, V):
            for t2 in range(1, V):
                seq = np.concatenate([prompt[i], [t1, t2]])
                lp = _seq_logprob(lm, seq, 4)
                if lp > best_lp:
                    best_lp, best_seq = lp, seq
        np.testing.assert_array_equal(full[i], best_seq)

    with pytest.raises(ValueError, match="num_beams"):
        lm.generate(prompt, max_new_tokens=2, num_beams=V)


def test_beam_search_rejects_sampling(tmp_path):
    _mesh_config(tmp_path, "dp=1")
    lm = LanguageModel(vocab_size=16, d_model=16, n_layers=1,
                       n_heads=2, max_len=12, attention="dot")
    x = _toy_tokens(n=8, seq=8, vocab=16)
    lm.fit(x, batch_size=8, epochs=1)
    with pytest.raises(ValueError, match="beam"):
        lm.generate(x[:1, :4], max_new_tokens=2, temperature=0.8,
                    num_beams=2)
    # top_k/top_p are sampling filters: silently dropping them under
    # beams would return deterministic beams the caller didn't ask for
    with pytest.raises(ValueError, match="top_k/top_p"):
        lm.generate(x[:1, :4], max_new_tokens=2, num_beams=2, top_k=5)
    with pytest.raises(ValueError, match="top_k/top_p"):
        lm.generate(x[:1, :4], max_new_tokens=2, num_beams=2, top_p=0.9)


def test_auto_attention_resolves_from_actual_seq_len(tmp_path,
                                                     monkeypatch):
    """attention="auto" picks flash vs dot from the ACTUAL sequence
    width, not the configured max_len — a long-capable classifier fed
    short batches must stay on dot below the measured 1024 crossover
    (and the LM already did; pin both)."""
    import jax as jax_mod

    _mesh_config(tmp_path, "dp=1")
    monkeypatch.setattr(jax_mod, "default_backend", lambda: "tpu")
    clf = TextClassifier(vocab_size=64, n_classes=2, d_model=16,
                         n_layers=1, n_heads=2, max_len=2048,
                         attention="auto")
    assert clf._resolved_attention(128) == "dot"
    assert clf._resolved_attention(1024) == "flash"
    assert clf._resolved_attention() == "flash"  # falls back to max_len
    lm = LanguageModel(vocab_size=64, d_model=16, n_layers=1,
                       n_heads=2, max_len=2048, attention="auto")
    assert lm._resolved_attention(128) == "dot"
    assert lm._resolved_attention(1024) == "flash"


def test_set_mesh_drops_decode_caches(tmp_path):
    """Generation/beam compiles close over the mesh-resolved module;
    re-pinning the mesh (sweep sub-slices) must drop them so a stale
    compile can't serve the old mesh."""
    _mesh_config(tmp_path, "dp=1")
    lm = LanguageModel(vocab_size=16, d_model=16, n_layers=1,
                       n_heads=2, max_len=12, attention="dot")
    x = _toy_tokens(n=8, seq=8, vocab=16)
    lm.fit(x, batch_size=8, epochs=1)
    lm.generate(x[:1, :4], max_new_tokens=2)
    lm.generate(x[:1, :4], max_new_tokens=2, num_beams=2)
    assert lm._gen_cache_fns and lm._beam_cache_fns
    lm.set_mesh(mesh_lib.build_mesh("dp=2"))
    assert not lm._gen_cache_fns and not lm._beam_cache_fns


def test_rope_base_changes_positions_and_round_trips(tmp_path):
    """rope_base != default changes the positional encoding (logits
    differ on the same params) and survives the artifact round trip;
    cached decode stays consistent with the full forward."""
    _mesh_config(tmp_path, "dp=1")
    lm = LanguageModel(vocab_size=16, d_model=16, n_layers=1,
                       n_heads=2, max_len=12, attention="dot",
                       rope_base=100000.0, name="rope_rt")
    x = _toy_tokens(n=8, seq=8, vocab=16)
    lm.fit(x, batch_size=8, epochs=1)

    from learningorchestra_tpu.models import transformer as T
    base_mod = T.TransformerLM(vocab_size=16, d_model=16, n_layers=1,
                               n_heads=2, attention="dot")
    stretched, _ = lm._module_for(None).apply(
        {"params": lm.params}, jnp.asarray(x[:2]))
    vanilla, _ = base_mod.apply({"params": lm.params}, jnp.asarray(x[:2]))
    assert not np.allclose(np.asarray(stretched), np.asarray(vanilla))

    art = tmp_path / "artifact"
    os.makedirs(art)
    lm.__lo_save__(str(art))
    loaded = LanguageModel.__lo_load__(str(art))
    assert loaded.rope_base == 100000.0
    # cached decode (scalar-position rope) == full-forward rollout
    gen = loaded.generate(x[:1, :4], max_new_tokens=3, temperature=0.0)
    buf = np.zeros((1, 7), np.int32)
    buf[:, :4] = x[:1, :4]
    mod = loaded._module_for(None)
    for pos in range(4, 7):
        lg, _ = mod.apply({"params": loaded.params}, jnp.asarray(buf))
        last = np.asarray(lg[:, pos - 1]).astype(np.float64)
        last[:, 0] = -np.inf
        buf[:, pos] = last.argmax(-1)
    np.testing.assert_array_equal(gen, buf)
    with pytest.raises(ValueError, match="rope_base"):
        LanguageModel(vocab_size=8, rope_base=0.5)


# ----------------------------------------------------------------------
# TextClassifier (non-causal encoder)
# ----------------------------------------------------------------------
def test_text_classifier_learns_and_round_trips(tmp_path):
    """Bidirectional encoder + masked mean pool learns a token-set
    task (label = whether token 3 appears ANYWHERE — needs non-causal
    attention at the pool), round-trips as an artifact, and
    classifies identically after reload."""
    _mesh_config(tmp_path, "dp=2")
    rng = np.random.default_rng(0)
    x = rng.integers(4, 16, size=(128, 10)).astype(np.int32)
    y = rng.integers(0, 2, size=128).astype(np.int32)
    pos = rng.integers(0, 10, size=128)
    x[np.arange(128)[y == 1], pos[y == 1]] = 3  # marker token

    from learningorchestra_tpu.models import TextClassifier as TC
    clf = TC(vocab_size=16, n_classes=2, d_model=32, n_layers=1,
             n_heads=2, max_len=10, name="tc_rt")
    clf.compile({"kind": "adam", "learning_rate": 5e-3})
    hist = clf.fit(x, y, batch_size=32, epochs=15, shuffle=False)
    assert hist.history["loss"][-1] < hist.history["loss"][0]
    ev = clf.evaluate(x, y, batch_size=32)
    assert ev["accuracy"] > 0.9, ev

    probs = clf.predict(x[:8], batch_size=8)
    assert probs.shape == (8, 2)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)

    art = tmp_path / "artifact"
    os.makedirs(art)
    clf.__lo_save__(str(art))
    loaded = TC.__lo_load__(str(art))
    np.testing.assert_allclose(loaded.predict(x[:8], batch_size=8),
                               probs, atol=1e-5)


def test_text_classifier_sharded_and_gqa(tmp_path):
    """The encoder shares the block stack: GQA + flash attention under
    a dp×tp mesh trains with finite loss."""
    _mesh_config(tmp_path, "dp=2,tp=2")
    from learningorchestra_tpu.models import TextClassifier as TC

    rng = np.random.default_rng(1)
    x = rng.integers(1, 32, size=(32, 16)).astype(np.int32)
    y = rng.integers(0, 3, size=32).astype(np.int32)
    clf = TC(vocab_size=32, n_classes=3, d_model=32, n_layers=1,
             n_heads=4, n_kv_heads=2, max_len=16, attention="flash")
    hist = clf.fit(x, y, batch_size=16, epochs=1, shuffle=False)
    assert np.isfinite(hist.history["loss"][0])


def test_feature_stack_interactions(tmp_path):
    """All the round-4 features composed in ONE model — GQA +
    sliding window + fused projections off (GQA gates qkv) + LoRA +
    grad accumulation + beam search — train, decode parity, merge."""
    _mesh_config(tmp_path, "dp=1")
    lm = LanguageModel(vocab_size=24, d_model=16, n_layers=2,
                       n_heads=4, n_kv_heads=2, max_len=16,
                       attention="dot", sliding_window=6,
                       rope_base=50000.0)
    x = _toy_tokens(n=16, seq=12, vocab=24)
    lm.fit(x, batch_size=8, epochs=2, grad_accum=2)
    lm.enable_lora(rank=2)
    lm.fit(x, batch_size=8, epochs=1, grad_accum=2)
    lm.merge_lora()

    prompt = x[:2, :4]
    greedy = lm.generate(prompt, max_new_tokens=4, temperature=0.0)
    # greedy == full-forward rollout under the whole feature stack
    mod = lm._module_for(None)
    buf = np.zeros((2, 8), np.int32)
    buf[:, :4] = prompt
    for pos in range(4, 8):
        lg, _ = mod.apply({"params": lm.params}, jnp.asarray(buf))
        last = np.asarray(lg[:, pos - 1]).astype(np.float64)
        last[:, 0] = -np.inf
        buf[:, pos] = last.argmax(-1)
    np.testing.assert_array_equal(greedy, buf)

    beams = lm.generate(prompt, max_new_tokens=4, num_beams=3)
    assert beams.shape == greedy.shape and (beams > 0).all()


def test_lm_fit_validation_split(tmp_path):
    """validation_split on the LM: the tail windows score next-token
    val_loss/val_accuracy after training (keras-parity surface)."""
    _mesh_config(tmp_path, "dp=1")
    lm = LanguageModel(vocab_size=32, d_model=16, n_layers=1,
                       n_heads=2, max_len=16, attention="dot")
    x = _toy_tokens(n=32)
    hist = lm.fit(x, batch_size=8, epochs=2, validation_split=0.25)
    assert "val_loss" in hist.history and "val_accuracy" in hist.history
    assert np.isfinite(hist.history["val_loss"][-1])
    with pytest.raises(ValueError, match="validation_split"):
        lm.fit(x[:1], batch_size=1, epochs=1, validation_split=0.5)


def test_generate_unequal_prompts_left_pad(tmp_path):
    """Batched generate over UNEQUAL-length prompts (list of lists):
    rows left-pad to a shared width with the attention mask hiding pad
    columns, and each row's continuation must be exactly what a solo
    generate of that row produces — greedy AND sampled."""
    _mesh_config(tmp_path, "dp=1")
    lm = LanguageModel(vocab_size=32, d_model=32, n_layers=1,
                       n_heads=2, max_len=24, attention="dot")
    lm.fit(_toy_tokens(), batch_size=32, epochs=1)
    rng = np.random.default_rng(5)
    prompts = [[int(t) for t in rng.integers(1, 32, size=n)]
               for n in (4, 7, 9)]
    s, new = max(len(p) for p in prompts), 6
    out = lm.generate(prompts, max_new_tokens=new)  # greedy
    assert out.shape == (3, s + new)
    for i, p in enumerate(prompts):
        pad = s - len(p)
        # documented convention: leading pad zeros keep rows
        # rectangular; row[pad:] is the solo-shaped sequence
        assert list(out[i, :pad]) == [0] * pad
        solo = lm.generate(np.asarray([p], np.int32),
                           max_new_tokens=new)
        np.testing.assert_array_equal(out[i, pad:], solo[0])
    # sampled path stays shape-correct and pad-clean (per-row keys
    # come from the shared buffer layout, so rows need not bit-match
    # a solo run — the greedy check above pins the masking math)
    sampled = lm.generate(prompts, max_new_tokens=new,
                          temperature=0.8, top_k=8, seed=1)
    assert sampled.shape == (3, s + new)
    for i, p in enumerate(prompts):
        pad = s - len(p)
        assert list(sampled[i, :pad]) == [0] * pad
        assert (sampled[i, pad:] > 0).all()
