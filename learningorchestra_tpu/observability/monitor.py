"""Cluster resource sampler (docs/OBSERVABILITY.md "Cluster monitor").

A single daemon thread samples, every ``LO_MONITOR_INTERVAL_MS``
milliseconds, the resources the rest of the stack only reads at
isolated points: per-device HBM watermarks (``memory_stats()``), the
HBM arena's occupancy/evictions (:mod:`runtime.arena`), the slice
scheduler's occupancy and fragmentation
(:meth:`services.scheduler.SliceLease.stats`), serving queue depth and
batch fill (:mod:`services.serving`), job-queue depth, and host RSS.
Each scalar lands in a bounded time-series ring (``LO_MONITOR_RING``
samples), readable as one JSON document through
``GET /observability/cluster``; the latest structured sample also
backs the ``lo_hbm_bytes_in_use`` / ``lo_slice_fragmentation`` /
``lo_host_rss_bytes`` Prometheus gauges.

The sampler is strictly best-effort — a failing collector is recorded
as a ``sampleErrors`` count, never raised — and never imports jax at
module import time (the device plane may not exist in this process).

This module also hosts the **footprint-calibration registry**: jobs
record their measured ``peakHbmBytes`` under the footprint's
``calibrationKey`` and, behind ``LO_FOOTPRINT_CALIBRATE``, the
execution layer prefers that measurement (safety-margined, clamped to
the static estimate's order of magnitude) over the preflight
heuristic for repeat executions (docs/SCALING.md §7).
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional
from learningorchestra_tpu.runtime import locks

# scalar series kept as (ts, value) rings; everything else only in the
# latest structured sample
_SCALAR_SERIES = (
    "hbmBytesInUse", "hbmPeakBytesInUse", "hbmHeadroomFrac",
    "arenaBytesInUse", "arenaEvictions",
    "sliceDevicesBusy", "sliceFragmentation",
    "servingQueueDepth", "servingBatchFill",
    # paged-KV serving (services/serving.py PagedLMServingSession):
    # pool free-page headroom and cross-stream prefix sharing
    "servingKvPagesFree", "servingKvPagesShared",
    "jobsRunning", "jobQueueDepth", "deadLettered",
    "hostRssBytes",
    # X-ray HBM attribution (observability/xray): ledger total and the
    # unattributed remainder the leak-detector SLO differences
    "xrayAttributedBytes", "xrayUnattributedBytes",
)


def device_memory_stats() -> List[Dict[str, Any]]:
    """Per-device HBM watermarks, best-effort. CPU/TFRT backends
    without ``memory_stats`` report the device with null fields rather
    than vanishing, so the cluster document always names every
    device."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return []
    out: List[Dict[str, Any]] = []
    for d in devices:
        entry: Dict[str, Any] = {
            "device": getattr(d, "id", len(out)),
            "platform": getattr(d, "platform", "unknown"),
            "bytesInUse": None, "peakBytesInUse": None,
            "bytesLimit": None,
        }
        try:
            ms = d.memory_stats() or {}
            entry["bytesInUse"] = ms.get("bytes_in_use")
            entry["peakBytesInUse"] = ms.get("peak_bytes_in_use")
            entry["bytesLimit"] = ms.get("bytes_limit")
        except Exception:
            pass
        out.append(entry)
    return out


def peak_hbm_bytes() -> Optional[int]:
    """Max ``peak_bytes_in_use`` across local devices, or None when
    the backend does not measure it (CPU). The jobs layer calls this
    after a job's function returns to stamp ``peakHbmBytes`` on the
    terminal metadata."""
    peaks = [d["peakBytesInUse"] for d in device_memory_stats()
             if d.get("peakBytesInUse")]
    return max(peaks) if peaks else None


def host_rss_bytes() -> Optional[int]:
    """Resident set size of this process (stdlib only)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        import resource

        rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss is KiB on Linux, bytes on macOS
        return rss_kb * 1024 if os.uname().sysname == "Linux" else rss_kb
    except Exception:
        return None


class ClusterMonitor:
    """Background sampler + ring store. Collectors are injected as
    zero-arg callables so the monitor has no import-time dependency on
    the service layer (and tests can feed it fakes)."""

    def __init__(self,
                 interval_seconds: float = 1.0,
                 ring: int = 600,
                 scheduler_stats: Optional[Callable[[], dict]] = None,
                 serving_stats: Optional[Callable[[], dict]] = None,
                 job_stats: Optional[Callable[[], dict]] = None,
                 arena_stats: Optional[Callable[[], dict]] = None,
                 device_stats: Callable[
                     [], List[Dict[str, Any]]] = device_memory_stats,
                 watchdog: Optional[Any] = None):
        self.interval_seconds = max(0.01, float(interval_seconds))
        self._ring = max(8, int(ring))
        self._scheduler_stats = scheduler_stats
        self._serving_stats = serving_stats
        self._job_stats = job_stats
        self._arena_stats = arena_stats
        self._device_stats = device_stats
        self.watchdog = watchdog
        self._lock = locks.make_lock("monitor.rings")
        self._series: Dict[str, "collections.deque"] = {
            name: collections.deque(maxlen=self._ring)
            for name in _SCALAR_SERIES}
        self._latest: Optional[Dict[str, Any]] = None
        self._samples = 0
        self._sample_errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "ClusterMonitor":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="lo-monitor", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_seconds):
            try:
                self.sample_once()
            except Exception:
                with self._lock:
                    self._sample_errors += 1

    # -- sampling -----------------------------------------------------

    def _call(self, fn: Optional[Callable[[], Any]]) -> Any:
        if fn is None:
            return None
        try:
            return fn()
        except Exception:
            self._sample_errors += 1
            return None

    def sample_once(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Collect one structured sample, append the scalar rings, and
        run the SLO watchdog. Synchronously callable from tests."""
        now = time.time() if now is None else now
        sample: Dict[str, Any] = {"ts": round(now, 3)}

        devices = self._call(self._device_stats) or []
        sample["devices"] = devices
        in_use = sum(d.get("bytesInUse") or 0 for d in devices)
        peak = sum(d.get("peakBytesInUse") or 0 for d in devices)
        limit = sum(d.get("bytesLimit") or 0 for d in devices)
        sample["hbm"] = {
            "bytesInUse": in_use, "peakBytesInUse": peak,
            "bytesLimit": limit,
            "headroomFrac": (round(1.0 - in_use / limit, 6)
                             if limit else None)}

        arena = self._call(self._arena_stats)
        sample["arena"] = arena
        sched = self._call(self._scheduler_stats)
        sample["scheduler"] = sched
        serving = self._call(self._serving_stats)
        sample["serving"] = serving
        jobs = self._call(self._job_stats)
        sample["jobs"] = jobs
        sample["hostRssBytes"] = host_rss_bytes()

        scalars: Dict[str, Any] = {
            "hbmBytesInUse": in_use or None,
            "hbmPeakBytesInUse": peak or None,
            "hbmHeadroomFrac": sample["hbm"]["headroomFrac"],
            "hostRssBytes": sample["hostRssBytes"],
        }
        if arena:
            scalars["arenaBytesInUse"] = arena.get("bytesInUse")
            scalars["arenaEvictions"] = arena.get("evictions")
        if sched:
            scalars["sliceDevicesBusy"] = sched.get("devicesBusy")
            scalars["sliceFragmentation"] = sched.get("fragmentation")
        if serving:
            scalars["servingQueueDepth"] = serving.get("queueDepth")
            scalars["servingBatchFill"] = serving.get("batchFill")
            scalars["servingKvPagesFree"] = serving.get("kvPagesFree")
            scalars["servingKvPagesShared"] = serving.get(
                "kvPagesShared")
        if jobs:
            scalars["jobsRunning"] = jobs.get("running")
            scalars["jobQueueDepth"] = jobs.get("queued")
            scalars["deadLettered"] = jobs.get("deadLettered")
        try:
            from learningorchestra_tpu.observability import xray

            attributed, unattributed = xray.ring_sample()
            scalars["xrayAttributedBytes"] = attributed
            scalars["xrayUnattributedBytes"] = unattributed
            sample["xray"] = {"attributedBytes": attributed,
                              "unattributedBytes": unattributed,
                              "owners": xray.by_owner()}
        except Exception:  # noqa: BLE001 — sampler is best-effort
            self._sample_errors += 1

        with self._lock:
            for name, value in scalars.items():
                if value is not None and name in self._series:
                    self._series[name].append((round(now, 3), value))
            self._latest = sample
            self._samples += 1

        if self.watchdog is not None:
            try:
                self.watchdog.evaluate(now=now, monitor=self)
            except Exception:
                with self._lock:
                    self._sample_errors += 1
        return sample

    # -- read side ----------------------------------------------------

    def latest(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._latest) if self._latest else None

    def series(self, name: str) -> List[Any]:
        with self._lock:
            ring = self._series.get(name)
            return [list(p) for p in ring] if ring else []

    def series_window(self, name: str, window: float,
                      now: Optional[float] = None) -> List[Any]:
        """Samples of one series newer than ``now - window``."""
        now = time.time() if now is None else now
        cutoff = now - window
        return [p for p in self.series(name) if p[0] >= cutoff]

    def snapshot(self) -> Dict[str, Any]:
        """The `/observability/cluster` document."""
        with self._lock:
            series = {name: [list(p) for p in ring]
                      for name, ring in self._series.items() if ring}
            latest = dict(self._latest) if self._latest else None
            samples, errors = self._samples, self._sample_errors
        return {"intervalSeconds": self.interval_seconds,
                "ring": self._ring, "samples": samples,
                "sampleErrors": errors, "latest": latest,
                "series": series}


# -- footprint-calibration registry ----------------------------------
#
# Measured peak HBM per calibration key ("{root}:{method}" — the
# repeat-execution cache key). In-process and best-effort by design:
# the durable copy is the `peakHbmBytes` field on the job's terminal
# metadata, which the update path reads back directly.

_cal_lock = locks.make_lock("monitor.calibration")
_measured_peaks: Dict[str, int] = {}


def record_peak(key: Optional[str], nbytes: Optional[int]) -> None:
    if not key or not nbytes or nbytes <= 0:
        return
    with _cal_lock:
        # keep the high-water mark: a job's footprint must cover its
        # worst observed epoch, not its last
        prior = _measured_peaks.get(key, 0)
        _measured_peaks[key] = max(prior, int(nbytes))


def measured_peak(key: Optional[str]) -> Optional[int]:
    if not key:
        return None
    with _cal_lock:
        return _measured_peaks.get(key)


def calibrated_hbm_bytes(measured: int, estimate: int,
                         margin: float) -> int:
    """Safety-margined measured peak, clamped to within one order of
    magnitude of the static estimate (a wild measurement — e.g. a
    prior run that shared devices — cannot collapse or explode the
    grant)."""
    cal = int(measured * max(1.0, margin))
    if estimate > 0:
        cal = max(cal, estimate // 10)
        cal = min(cal, estimate * 10)
    return cal


def reset_calibration() -> None:
    with _cal_lock:
        _measured_peaks.clear()
