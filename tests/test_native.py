"""Native core (csrc/locore.cpp) — build, parity with the pure-Python
fallbacks, and the ingest/query/batcher wiring."""

import math

import numpy as np
import pytest

from learningorchestra_tpu import native
from learningorchestra_tpu.native import ops

CSV = (b"name,age,score\n"
       b"alice,30,1.5\n"
       b'"bob, jr",41,\n'
       b'"say ""hi""",-2,0\n'
       b"carol,7e1,2.25\r\n")


def test_native_builds():
    # g++ is baked into the image; the toolchain path must work here
    assert native.available()


def test_csv_parse_native_matches_python():
    cols, types = ops.parse_csv(CSV)
    pcols, ptypes = ops._parse_csv_py(CSV, delimiter=",", has_header=True,
                                      forced_types=None)
    assert types == ptypes == [1, 0, 0]
    assert list(cols[0]) == list(pcols[0]) == [
        "alice", "bob, jr", 'say "hi"', "carol"]
    np.testing.assert_array_equal(cols[1], [30.0, 41.0, -2.0, 70.0])
    np.testing.assert_array_equal(cols[1], pcols[1])
    assert math.isnan(cols[2][1]) and math.isnan(pcols[2][1])
    np.testing.assert_array_equal(cols[2][[0, 2, 3]], [1.5, 0.0, 2.25])


def test_csv_parse_forced_types():
    # chunk 2 of a split file: no header, schema pinned by chunk 1
    chunk = b"dave,notanumber,3\n"
    cols, types = ops.parse_csv(chunk, has_header=False,
                                forced_types=[1, 0, 0])
    assert types == [1, 0, 0]
    assert cols[0][0] == "dave"
    assert math.isnan(cols[1][0])  # forced numeric, unparseable -> NaN
    assert cols[2][0] == 3.0


def test_csv_parse_ragged_raises():
    with pytest.raises(ValueError):
        ops.parse_csv(b"a,b\n1,2\n3\n")


def test_safe_split_respects_quotes():
    data = b'a,b\n1,"x\ny",\n2,'
    cut = ops.safe_split(data)
    # the newline inside the quoted field must not be chosen
    assert data[:cut] == b'a,b\n1,"x\ny",\n'


def test_value_counts_parity_floats_and_strings():
    v = np.array([1.0, 2.0, 1.0, np.nan, np.nan, 3.0])
    keys, counts = ops.value_counts(v)
    pkeys, pcounts = ops._value_counts_py(v)
    assert [k if not (isinstance(k, float) and math.isnan(k)) else "nan"
            for k in keys] == [1.0, 2.0, "nan", 3.0]
    np.testing.assert_array_equal(counts, [2, 1, 2, 1])
    np.testing.assert_array_equal(counts, pcounts)
    assert len(pkeys) == len(keys)

    s = np.array(["x", "y", "x", "z", "x"], dtype=object)
    keys, counts = ops.value_counts(s)
    assert keys == ["x", "y", "z"]
    np.testing.assert_array_equal(counts, [3, 1, 1])


def test_filter_mask_numeric_and_string():
    cols = {"age": np.array([30.0, 41.0, -2.0, 70.0]),
            "name": np.array(["a", "b", "a", "c"], dtype=object)}
    mask = ops.filter_mask(cols, {"age": {"$gt": 0, "$lt": 50}})
    np.testing.assert_array_equal(mask, [True, True, False, False])
    mask = ops.filter_mask(cols, {"name": "a", "age": {"$gte": -2}})
    np.testing.assert_array_equal(mask, [True, False, True, False])
    mask = ops.filter_mask(cols, {"name": {"$ne": "a"}})
    np.testing.assert_array_equal(mask, [False, True, False, True])
    # unsupported shapes defer to the row evaluator
    assert ops.filter_mask(cols, {"age": {"$in": [30.0]}}) is None
    assert ops.filter_mask(cols, {"missing": 1}) is None


def test_whitespace_cell_stays_numeric():
    # parity: a spaces-only cell is "missing" in BOTH paths (review
    # finding: native used to demote the whole column to string)
    buf = b"x\n1\n  \n3\n"
    cols, types = ops.parse_csv(buf)
    pcols, ptypes = ops._parse_csv_py(buf, delimiter=",",
                                      has_header=True, forced_types=None)
    assert types == ptypes == [0]
    assert math.isnan(cols[0][1]) and math.isnan(pcols[0][1])


def test_filter_mask_arrow_strings_and_ints():
    import pyarrow as pa

    table = pa.table({
        "age": pa.array([30, 41, None, 70], type=pa.int64()),
        "name": pa.array(["a", "b", None, "a"]),
    })
    mask = ops.filter_mask_arrow(table, {"name": "a"})
    np.testing.assert_array_equal(mask, [True, False, False, True])
    # null passes $ne (None != "a"), matching matches_query
    mask = ops.filter_mask_arrow(table, {"name": {"$ne": "a"}})
    np.testing.assert_array_equal(mask, [False, True, True, False])
    mask = ops.filter_mask_arrow(table, {"age": {"$gte": 41}, "name": "a"})
    np.testing.assert_array_equal(mask, [False, False, False, True])
    assert ops.filter_mask_arrow(table, {"age": {"$in": [30]}}) is None


def test_value_counts_arrow_native_and_fallback():
    import pyarrow as pa

    col = pa.chunked_array([["x", "y"], ["x", "z", "x"]])
    keys, counts = ops.value_counts_arrow(col)
    assert dict(zip(keys, counts.tolist())) == {"x": 3, "y": 1, "z": 1}
    ints = pa.chunked_array([[1, 2, 2, None]])
    keys, counts = ops.value_counts_arrow(ints)
    assert dict(zip([k for k in keys], counts.tolist())) == {
        1: 1, 2: 2, None: 1}
    floats = pa.chunked_array([[1.5, 1.5, 2.0]])
    keys, counts = ops.value_counts_arrow(floats)
    assert dict(zip(keys, counts.tolist())) == {1.5: 2, 2.0: 1}
    assert all(isinstance(k, float) for k in keys)  # JSON-safe


def test_eq_operator_consistency():
    from learningorchestra_tpu.catalog import documents as D

    assert D.matches_query({"a": 30}, {"a": {"$eq": 30}})
    assert not D.matches_query({"a": 31}, {"a": {"$eq": 30}})
    cols = {"a": np.array([30.0, 31.0])}
    np.testing.assert_array_equal(
        ops.filter_mask(cols, {"a": {"$eq": 30}}), [True, False])


def test_header_only_first_chunk_does_not_pin_schema(tmp_config,
                                                     tmp_path):
    """Review finding: a chunk boundary right after the header must not
    pin every column to float64."""
    import learningorchestra_tpu.services.dataset as dataset_mod
    from learningorchestra_tpu.services.context import ServiceContext
    from learningorchestra_tpu.services.dataset import DatasetService

    # header is exactly one small chunk; rows arrive later
    csv_path = tmp_path / "late.csv"
    csv_path.write_text("name,age\n" + "".join(
        f"person{i},{i}\n" for i in range(200)))
    ctx = ServiceContext(tmp_config)
    svc = DatasetService(ctx)
    old_chunk = dataset_mod._CHUNK
    dataset_mod._CHUNK = 16  # header alone fills the first chunk
    try:
        svc.create({"datasetName": "late",
                    "datasetURI": csv_path.as_uri()}, "csv")
        ctx.jobs.wait("late", timeout=60)
    finally:
        dataset_mod._CHUNK = old_chunk
    rows = ctx.catalog.read_rows("late", limit=2)
    assert rows[0]["name"] == "person0"
    assert rows[0]["age"] == 0  # integral column refined to int64


def test_gather_rows_matches_fancy_indexing():
    src = np.arange(20, dtype=np.float32).reshape(5, 4)
    idx = np.array([3, 1, 1, 0])
    np.testing.assert_array_equal(ops.gather_rows(src, idx), src[idx])
    # non-eligible dtype silently falls back
    src64 = src.astype(np.float64)
    np.testing.assert_array_equal(ops.gather_rows(src64, idx), src64[idx])


def test_native_ingest_end_to_end(tmp_config, tmp_path):
    from learningorchestra_tpu.services.context import ServiceContext

    from learningorchestra_tpu.services.dataset import DatasetService

    csv_path = tmp_path / "people.csv"
    csv_path.write_bytes(CSV)
    ctx = ServiceContext(tmp_config)
    svc = DatasetService(ctx)
    status, _ = svc.create(
        {"datasetName": "people", "datasetURI": csv_path.as_uri()}, "csv")
    assert status == 201
    ctx.jobs.wait("people", timeout=30)
    meta = ctx.catalog.get_metadata("people")
    assert meta["finished"] is True
    assert meta["fields"] == ["name", "age", "score"]
    assert meta["rows"] == 4
    rows = ctx.catalog.read_rows("people")
    assert rows[0] == {"name": "alice", "age": 30.0, "score": 1.5,
                       "_id": 1}
    assert rows[1]["score"] is None  # empty numeric cell -> null
    # columnar fast-path query matches the row evaluator
    q = {"age": {"$gt": 0}}
    fast = ctx.catalog.read_rows("people", query=q)
    import learningorchestra_tpu.catalog.documents as D
    slow = [r for r in ctx.catalog.read_rows("people")
            if D.matches_query(r, q)]
    assert fast == slow


def test_chunked_native_ingest_large(tmp_config, tmp_path):
    """Multi-chunk path: file bigger than one chunk, schema pinned."""
    import learningorchestra_tpu.services.dataset as dataset_mod
    from learningorchestra_tpu.services.context import ServiceContext
    from learningorchestra_tpu.services.dataset import DatasetService

    n = 5000
    lines = ["x,label"] + [f"{i}.5,row{i % 7}" for i in range(n)]
    csv_path = tmp_path / "big.csv"
    csv_path.write_text("\n".join(lines) + "\n")
    ctx = ServiceContext(tmp_config)
    svc = DatasetService(ctx)
    old_chunk = dataset_mod._CHUNK
    dataset_mod._CHUNK = 4096  # force many chunks
    try:
        svc.create({"datasetName": "big",
                    "datasetURI": csv_path.as_uri()}, "csv")
        ctx.jobs.wait("big", timeout=60)
    finally:
        dataset_mod._CHUNK = old_chunk
    meta = ctx.catalog.get_metadata("big")
    assert meta["rows"] == n
    assert meta["finished"] is True
    rows = ctx.catalog.read_rows("big", skip=n - 1)
    assert rows[0]["x"] == n - 1 + 0.5
    assert rows[0]["label"] == f"row{(n - 1) % 7}"


def test_csv_float_fast_path_bit_identical_to_strtod():
    """The parser's Clinger fast path (plain decimals, <=15 digits)
    must produce BIT-IDENTICAL doubles to the strtod fallback /
    Python float(): mantissa and 10^frac are both exact, so the one
    division is correctly rounded. Exotic forms (exponents, inf/nan,
    16+ digits, hex) take the fallback and must also match."""
    import random

    rng = random.Random(7)
    values = []
    # plain decimals across magnitudes and digit counts (fast path)
    for _ in range(500):
        digits = rng.randint(1, 15)
        frac = rng.randint(0, min(digits, 12))
        s = "".join(rng.choice("0123456789") for _ in range(digits))
        if frac:
            s = (s[:-frac] or "0") + "." + s[-frac:]
        if rng.random() < 0.5:
            s = "-" + s
        if rng.random() < 0.2:
            s = " " + s + " "
        values.append(s)
    # fallback forms
    values += ["1e10", "-2.5E-3", "inf", "-inf", "nan",
               "0.12345678901234567890", "9" * 17,
               "123456789012345678",
               "+4.25", "000123.5", ".5", "5.", "0", "-0.0"]
    csv = "x\n" + "\n".join(values) + "\n"
    cols, types = ops.parse_csv(csv.encode())
    assert types == [0], types
    expected = [float(v.strip()) for v in values]
    got = list(cols[0])
    assert len(got) == len(expected)
    for s, e, g in zip(values, expected, got):
        if math.isnan(e):
            assert math.isnan(g), s
        else:
            assert g == e and math.copysign(1, g) == \
                math.copysign(1, e), (s, e, g)
