#!/usr/bin/env python
"""Repo self-lint: the framework's own source held to the standards
it enforces on user code.

Reuses the analysis AST machinery to flag, under
``learningorchestra_tpu/``:

- bare ``exec(`` / ``eval(`` calls anywhere except
  ``services/sandbox.py`` (the one module allowed to execute user
  code — everything else must route through it);
- ``jax.debug.*`` calls and ``breakpoint()`` leftovers (debug
  scaffolding that must not ship: ``jax.debug.print`` /
  ``jax.debug.breakpoint`` silently serialize TPU programs).

Exit 0 when clean, 1 with a finding listing otherwise. Run by
``deploy/ci.sh`` before the tier-1 suite.
"""

from __future__ import annotations

import ast
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PACKAGE = REPO / "learningorchestra_tpu"

# the one module that legitimately exec()s (user code, in the jail)
EXEC_ALLOWED = {PACKAGE / "services" / "sandbox.py"}

_EXEC_FAMILY = {"exec", "eval"}


def _findings_for(path: pathlib.Path) -> list:
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except SyntaxError as e:
        return [(path, e.lineno or 0, f"does not parse: {e.msg}")]
    out = []
    exec_ok = path in EXEC_ALLOWED
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Name):
            if func.id in _EXEC_FAMILY and not exec_ok:
                out.append((path, node.lineno,
                            f"bare {func.id}() outside services/"
                            f"sandbox.py — route through the sandbox"))
            elif func.id == "breakpoint":
                out.append((path, node.lineno,
                            "breakpoint() left in library code"))
        elif isinstance(func, ast.Attribute):
            # jax.debug.print / jax.debug.breakpoint / jax.debug.callback
            value = func.value
            if isinstance(value, ast.Attribute) and \
                    value.attr == "debug" and \
                    isinstance(value.value, ast.Name) and \
                    value.value.id == "jax":
                out.append((path, node.lineno,
                            f"jax.debug.{func.attr}() left in library "
                            f"code"))
    return out


def main() -> int:
    findings = []
    for path in sorted(PACKAGE.rglob("*.py")):
        findings.extend(_findings_for(path))
    for path, lineno, message in findings:
        rel = path.relative_to(REPO)
        print(f"{rel}:{lineno}: {message}", file=sys.stderr)
    if findings:
        print(f"selflint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print("selflint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
