"""Parameter/activation sharding rules (DP / FSDP / TP).

The scaling-book recipe: pick a mesh, annotate shardings on params and
batch, let GSPMD insert the collectives. Rules here are (path-regex →
PartitionSpec) pairs matched against flax param paths like
``"decoder/layer_3/attn/q_proj/kernel"``; first match wins. FSDP is a
fallback rule that shards the largest divisible axis of any still-
replicated tensor over the ``fsdp`` axis (ZeRO-3-style, gathered by
XLA just-in-time per layer).
"""

from __future__ import annotations

import re
from typing import Any, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from learningorchestra_tpu.runtime import mesh as mesh_lib

Rule = Tuple[str, P]


# TP rules for the transformer family (models/transformer.py naming):
# column-parallel in-projections, row-parallel out-projections —
# activations stay sharded on heads between the two, so the only
# collective per block is one reduce-scatter/all-gather pair inserted
# by XLA.
TRANSFORMER_RULES: Sequence[Rule] = (
    # qkv_proj/gate_up are the fused-projection layouts; under tp > 1
    # the model's _param_rules prepends a replicate override for them
    # (a column shard would cross the concatenation's block
    # boundaries), so their TP entry here serves meshes without tp
    (r".*(q_proj|k_proj|v_proj|qkv_proj|wi|gate|gate_up|up_proj)"
     r"/kernel$",
     P(None, mesh_lib.TP)),
    (r".*(o_proj|wo|down_proj)/kernel$", P(mesh_lib.TP, None)),
    (r".*embed/embedding$", P(None, mesh_lib.TP)),
    (r".*lm_head/kernel$", P(None, mesh_lib.TP)),
    (r".*experts/(wi|gate)$", P(mesh_lib.EP, None, mesh_lib.TP)),
    (r".*experts/wo$", P(mesh_lib.EP, mesh_lib.TP, None)),
    (r".*(bias|scale)$", P()),
)


def _path_str(path) -> str:
    parts = []
    for key in path:
        name = getattr(key, "key", None) or getattr(key, "name", None) \
            or getattr(key, "idx", None)
        parts.append(str(name))
    return "/".join(parts)


def _axes_in_mesh(spec: P, mesh: Mesh) -> P:
    """Drop rule axes the mesh doesn't have (so one rule set serves
    every mesh shape; a missing axis just means replicated there)."""
    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in mesh.axis_names
                         and mesh.shape[a] > 1)
            return kept if kept else None
        return entry if entry in mesh.axis_names and \
            mesh.shape[entry] > 1 else None

    return P(*(keep(e) for e in spec))


def _fsdp_spec(shape: Tuple[int, ...], base: P, mesh: Mesh) -> P:
    """Extend ``base`` by sharding the largest unsharded divisible dim
    over the fsdp axis."""
    if mesh_lib.FSDP not in mesh.axis_names or \
            mesh.shape[mesh_lib.FSDP] <= 1:
        return base
    fsdp_size = mesh.shape[mesh_lib.FSDP]
    entries = list(base) + [None] * (len(shape) - len(base))
    candidates = [(dim, i) for i, (dim, e) in enumerate(zip(shape, entries))
                  if e is None and dim % fsdp_size == 0 and dim >= fsdp_size]
    if not candidates:
        return base
    _, idx = max(candidates)
    entries[idx] = mesh_lib.FSDP
    return P(*entries)


def _axes_size(entry, mesh: Mesh) -> int:
    """Total device count of a PartitionSpec entry (axis name or
    tuple of names)."""
    axes = entry if isinstance(entry, (tuple, list)) else (entry,)
    size = 1
    for a in axes:
        size *= mesh.shape[a]
    return size


def _drop_non_divisible(base: P, shape: Tuple[int, ...],
                        mesh: Mesh) -> P:
    """Replicate (instead of erroring) any rule-sharded dim the mesh
    axis doesn't divide — e.g. an MQA k_proj whose single-head output
    column is narrower than the tp axis."""
    entries = []
    for i, entry in enumerate(base):
        if entry is not None and i < len(shape) and \
                shape[i] % _axes_size(entry, mesh):
            entry = None
        entries.append(entry)
    return P(*entries)


def spec_for(path: str, shape: Tuple[int, ...], mesh: Mesh,
             rules: Sequence[Rule] = TRANSFORMER_RULES,
             fsdp: bool = True) -> P:
    base = P()
    for pattern, spec in rules:
        if re.match(pattern, path):
            base = _drop_non_divisible(
                _axes_in_mesh(spec, mesh), shape, mesh)
            break
    return _fsdp_spec(shape, base, mesh) if fsdp else base


def param_shardings(params: Any, mesh: Mesh,
                    rules: Sequence[Rule] = TRANSFORMER_RULES,
                    fsdp: bool = True) -> Any:
    """NamedSharding pytree matching ``params`` (use as
    ``in_shardings``/``device_put`` target)."""
    def leaf_sharding(path, leaf):
        shape = getattr(leaf, "shape", ())
        spec = spec_for(_path_str(path), tuple(shape), mesh, rules, fsdp)
        if len(spec) > len(shape):  # rule wider than tensor: replicate
            spec = P()
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(leaf_sharding, params)


def shard_params(params: Any, mesh: Mesh,
                 rules: Sequence[Rule] = TRANSFORMER_RULES,
                 fsdp: bool = True) -> Any:
    return jax.device_put(params, param_shardings(params, mesh, rules, fsdp))


def batch_spec(mesh: Mesh, seq_axis: bool = False) -> P:
    """Batch activations: batch dim over (dp, fsdp), optionally the
    sequence dim over sp."""
    data = mesh_lib.data_axes(mesh)
    first = data if data else None
    if seq_axis and mesh_lib.SP in mesh.axis_names and \
            mesh.shape[mesh_lib.SP] > 1:
        return P(first, mesh_lib.SP)
    return P(first)


def config_axis_spec(mesh: Mesh, n_configs: int) -> P:
    """PartitionSpec for the leading config axis of a fused sweep
    (docs/PERFORMANCE.md "Sweep fusion"): shard it over the data axes
    when the cohort size divides them — GSPMD then places each config's
    params/opt_state on its own device group, the same trick the batch
    axis uses — else replicate (small cohorts still win by sharing one
    compile)."""
    data = mesh_lib.data_axes(mesh)
    if not data:
        return P()
    size = 1
    for a in data:
        size *= mesh.shape[a]
    if size > 1 and n_configs % size == 0:
        return P(data)
    return P()


def fused_state_shardings(state: Any, mesh: Mesh, n_configs: int) -> Any:
    """NamedSharding pytree for config-stacked train state: every leaf
    whose leading dim is the config axis gets ``config_axis_spec``;
    scalars (the step counter, optimizer counts that vmap left
    unstacked) stay replicated."""
    spec = config_axis_spec(mesh, n_configs)

    def leaf_sharding(leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) >= 1 and shape[0] == n_configs:
            return NamedSharding(mesh, spec)
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(leaf_sharding, state)


def constrain(x, mesh: Mesh, *spec_entries) -> Any:
    """``with_sharding_constraint`` shorthand that tolerates axes
    missing from the mesh and dims the axis size doesn't divide (e.g.
    the 1-sample trace during param init)."""
    spec = _axes_in_mesh(P(*spec_entries), mesh)

    entries = [e if (e is not None and d % _axes_size(e, mesh) == 0)
               else None
               for e, d in zip(spec, x.shape)]
    spec = P(*entries)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
