"""SLO watchdog (docs/OBSERVABILITY.md "Cluster monitor, SLOs &
alerts").

Declarative objectives — serving p99 latency, lease queue wait, HBM
headroom, dead-letter rate — are evaluated every monitor tick over a
**fast** and a **slow** burn-rate window (``LO_SLO_FAST_WINDOW_S`` /
``LO_SLO_SLOW_WINDOW_S``): an objective fires only when it is
breached in BOTH windows (acute *and* sustained), and resolves as
soon as the fast window clears, so a transient spike neither pages
nor flaps.

Latency objectives are computed from the PR-8 cumulative histograms
(:mod:`.hist`) by differencing bucket snapshots taken at window
boundaries — a windowed p99 from counters that only ever grow.
Resource objectives read the sampler rings
(:class:`.monitor.ClusterMonitor`).

Firing → resolved transitions are appended to the ``LO_EVENT_LOG``
JSONL (:func:`.export.log_event`) with the active job/serving trace
name attached, so an alert correlates with the trace that caused it
in one file. A firing **page**-severity alert flips ``GET /healthz``
to 503 (services/server.py).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from learningorchestra_tpu.observability import export as obs_export
from learningorchestra_tpu.observability import hist as obs_hist
from learningorchestra_tpu.observability import incidents as obs_incidents
from learningorchestra_tpu.runtime import locks

_HISTORY = 256

# per-tenant serving latency series emitted by the paged serving
# session (services/serving.py); each discovered tenant gets its own
# page-severity servingP99 objective so one tenant breaching cannot
# hide behind (or be blamed on) the aggregate
_TENANT_HIST_PREFIX = "lo_serving_request_seconds_tenant_"

# per-role serving latency series (prefill/decode/draft — a CLOSED
# set, services/serving.py) emitted by the disaggregated/speculative
# serving path; each role gets a ticket-severity p99 objective so a
# prefill-side regression is attributed to the prefill worker instead
# of smearing across the aggregate
_ROLE_HIST_PREFIX = "lo_serving_request_seconds_role_"

# ----------------------------------------------------------------------
# producer-pushed gauges: latest value + timestamp, for signals that
# have no histogram or sampler ring behind them (the quantized-serving
# drift probe pushes ``servingDrift`` here). The watchdog reads them in
# _measure with the window as a freshness bound, so a gauge whose
# producer stopped updating (session degraded/closed) ages out and the
# alert resolves instead of firing on stale data forever.
# ----------------------------------------------------------------------
_gauge_lock = locks.make_lock("slo.gauges")
_gauges: Dict[str, tuple] = {}


def set_gauge(name: str, value: float,
              now: Optional[float] = None) -> None:
    """Record the latest value of a pushed gauge (thread-safe)."""
    with _gauge_lock:
        _gauges[name] = (float(value),
                         time.time() if now is None else now)


def get_gauge(name: str, max_age: Optional[float] = None,
              now: Optional[float] = None) -> Optional[float]:
    """Latest value of ``name``, or None when unset or older than
    ``max_age`` seconds."""
    with _gauge_lock:
        entry = _gauges.get(name)
    if entry is None:
        return None
    value, ts = entry
    if max_age is not None:
        now = time.time() if now is None else now
        if now - ts > max_age:
            return None
    return value


def gauges() -> Dict[str, float]:
    """All pushed gauges (latest values), for /metrics export."""
    with _gauge_lock:
        return {name: entry[0] for name, entry in _gauges.items()}


def reset_gauges() -> None:
    """Test isolation."""
    with _gauge_lock:
        _gauges.clear()


class _HistWindow:
    """Bounded ring of (ts, cumulative-bucket-snapshot) pairs for one
    histogram, supporting windowed quantiles by snapshot diffing."""

    def __init__(self, name: str, keep: int = 512):
        self.name = name
        self._samples: "collections.deque" = collections.deque(
            maxlen=keep)

    def observe(self, now: float) -> None:
        snap = obs_hist.get(self.name).snapshot()
        self._samples.append((now, snap["buckets"]))

    def quantile_over(self, q: float, window: float,
                      now: float) -> Optional[float]:
        """q-quantile (bucket upper bound, seconds) of observations in
        ``[now - window, now]``, or None when the window saw no
        traffic."""
        if not self._samples:
            return None
        latest = self._samples[-1][1]
        cutoff = now - window
        baseline: Optional[Dict[str, int]] = None
        for ts, buckets in reversed(self._samples):
            if ts <= cutoff:
                baseline = buckets
                break
        # no snapshot predates the window: the whole history IS the
        # window (monitor younger than the window)
        get_base = baseline.get if baseline else (lambda _k, _d=0: 0)
        deltas = []
        for le, cum in latest.items():
            ub = float("inf") if le == "+Inf" else float(le)
            deltas.append((ub, cum - get_base(le, 0)))
        deltas.sort(key=lambda p: p[0])
        total = deltas[-1][1] if deltas else 0
        if total <= 0:
            return None
        target = q * total
        for ub, cum in deltas:
            if cum >= target:
                return ub
        return deltas[-1][0]


class Alert:
    """One objective's alert state."""

    __slots__ = ("name", "severity", "threshold", "state", "since",
                 "value", "trace")

    def __init__(self, name: str, severity: str, threshold: float):
        self.name = name
        self.severity = severity
        self.threshold = threshold
        self.state = "ok"
        self.since: Optional[float] = None
        self.value: Optional[float] = None
        self.trace: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "severity": self.severity,
                "state": self.state, "sinceUnixSeconds": self.since,
                "value": self.value, "threshold": self.threshold,
                "trace": self.trace}


class SloWatchdog:
    """Evaluates the configured objectives; owns alert state."""

    def __init__(self,
                 active_trace: Optional[Callable[
                     [], Optional[str]]] = None):
        self._active_trace = active_trace
        self._lock = locks.make_lock("slo.alerts")
        self._alerts: Dict[str, Alert] = {}
        self._history: "collections.deque" = collections.deque(
            maxlen=_HISTORY)
        self._serving = _HistWindow("lo_serving_request_seconds")
        self._lease = _HistWindow("lo_lease_wait_seconds")
        # tenant -> window, discovered lazily from the hist registry
        self._tenant_serving: Dict[str, _HistWindow] = {}
        # role -> window (prefill/decode/draft), same discovery path
        self._role_serving: Dict[str, _HistWindow] = {}

    # -- config -------------------------------------------------------

    @staticmethod
    def _cfg():
        from learningorchestra_tpu.config import get_config

        return get_config()

    def objectives(self) -> Dict[str, Dict[str, Any]]:
        cfg = self._cfg()
        out: Dict[str, Dict[str, Any]] = {
            "servingP99": {
                "severity": "page",
                "threshold": float(cfg.slo_serving_p99_ms),
                "unit": "ms"},
            "queueWait": {
                "severity": "ticket",
                "threshold": float(cfg.slo_queue_wait_s),
                "unit": "s"},
            "hbmHeadroom": {
                "severity": "page",
                "threshold": float(cfg.slo_hbm_headroom_frac),
                "unit": "frac"},
            "deadLetterRate": {
                "severity": "ticket",
                "threshold": float(cfg.slo_deadletter_rate),
                "unit": "perMin"},
            # leak detector (observability/xray): sustained growth of
            # device bytes NOBODY in the ledger owns — XLA temps are
            # sawtooth, a leak (or an unledgered allocation site) is
            # monotone across both windows
            "unattributedGrowth": {
                "severity": "page",
                "threshold": float(getattr(
                    cfg, "slo_unattributed_growth_bytes", 0.0)),
                "unit": "bytes"},
            # quantized-serving quality gate: the drift probe
            # (services/serving.py) pushes its latest relative error
            # here; the session degrades itself to bf16 on breach, this
            # objective is the paper trail that it happened
            "servingDrift": {
                "severity": "ticket",
                "threshold": float(getattr(
                    cfg, "serve_drift_max", 0.0)),
                "unit": "frac"},
        }
        thr = float(cfg.slo_serving_p99_ms)
        for tenant in sorted(list(self._tenant_serving)):
            out[f"servingP99:{tenant}"] = {
                "severity": "page", "threshold": thr, "unit": "ms"}
        for role in sorted(list(self._role_serving)):
            out[f"servingRoleP99:{role}"] = {
                "severity": "ticket", "threshold": thr, "unit": "ms"}
        return out

    # -- evaluation ---------------------------------------------------

    def evaluate(self, now: Optional[float] = None,
                 monitor: Optional[Any] = None) -> None:
        """One watchdog tick. ``monitor`` supplies the resource rings;
        latency objectives need only the histograms."""
        now = time.time() if now is None else now
        cfg = self._cfg()
        fast = max(0.1, float(cfg.slo_fast_window_s))
        slow = max(fast, float(cfg.slo_slow_window_s))
        self._serving.observe(now)
        self._lease.observe(now)
        for name in obs_hist.names():
            if name.startswith(_TENANT_HIST_PREFIX):
                tenant = name[len(_TENANT_HIST_PREFIX):]
                if tenant not in self._tenant_serving:
                    self._tenant_serving[tenant] = _HistWindow(name)
            elif name.startswith(_ROLE_HIST_PREFIX):
                role = name[len(_ROLE_HIST_PREFIX):]
                if role not in self._role_serving:
                    self._role_serving[role] = _HistWindow(name)
        for win in self._tenant_serving.values():
            win.observe(now)
        for win in self._role_serving.values():
            win.observe(now)
        objectives = self.objectives()

        for name, spec in objectives.items():
            thr = spec["threshold"]
            if not thr or thr <= 0:
                self._retire(name, now)
                continue
            fast_val = self._measure(name, monitor, fast, now)
            fast_breach = fast_val is not None and self._breached(
                name, fast_val, thr)
            if fast_breach:
                slow_val = self._measure(name, monitor, slow, now)
                slow_breach = slow_val is not None and self._breached(
                    name, slow_val, thr)
            else:
                slow_val, slow_breach = None, False
            self._transition(name, spec, fast_breach and slow_breach,
                             fast_breach,
                             fast_val if fast_val is not None
                             else slow_val, now)

    def _measure(self, name: str, monitor: Optional[Any],
                 window: float, now: float) -> Optional[float]:
        if name == "servingP99":
            p99 = self._serving.quantile_over(0.99, window, now)
            return None if p99 is None else p99 * 1000.0
        if name.startswith("servingP99:"):
            win = self._tenant_serving.get(name.split(":", 1)[1])
            if win is None:
                return None
            p99 = win.quantile_over(0.99, window, now)
            return None if p99 is None else p99 * 1000.0
        if name.startswith("servingRoleP99:"):
            win = self._role_serving.get(name.split(":", 1)[1])
            if win is None:
                return None
            p99 = win.quantile_over(0.99, window, now)
            return None if p99 is None else p99 * 1000.0
        if name == "queueWait":
            return self._lease.quantile_over(0.99, window, now)
        if name == "hbmHeadroom":
            if monitor is None:
                return None
            pts = monitor.series_window("hbmHeadroomFrac", window, now)
            return min((p[1] for p in pts), default=None)
        if name == "deadLetterRate":
            if monitor is None:
                return None
            pts = monitor.series_window("deadLettered", window, now)
            if len(pts) < 2:
                return None
            span = max(pts[-1][0] - pts[0][0], 1e-9)
            return (pts[-1][1] - pts[0][1]) / span * 60.0
        if name == "servingDrift":
            # pushed gauge; the window doubles as the freshness bound
            return get_gauge("servingDrift", max_age=window, now=now)
        if name == "unattributedGrowth":
            if monitor is None:
                return None
            pts = monitor.series_window("xrayUnattributedBytes",
                                        window, now)
            if len(pts) < 2:
                return None
            return float(pts[-1][1] - pts[0][1])
        return None

    @staticmethod
    def _breached(name: str, value: float, threshold: float) -> bool:
        # headroom is a floor (too LITTLE memory breaches); the other
        # objectives are ceilings
        if name == "hbmHeadroom":
            return value < threshold
        return value > threshold

    # -- state transitions --------------------------------------------

    def _transition(self, name: str, spec: Dict[str, Any],
                    fire: bool, fast_breach: bool,
                    value: Optional[float], now: float) -> None:
        with self._lock:
            alert = self._alerts.get(name)
            if alert is None:
                alert = self._alerts[name] = Alert(
                    name, spec["severity"], spec["threshold"])
            alert.threshold = spec["threshold"]
            if value is not None:
                alert.value = round(value, 6)
            was_firing = alert.state == "firing"
            if not was_firing and fire:
                alert.state = "firing"
                alert.since = now
                alert.trace = self._trace()
                self._record(alert, "firing", now)
            elif was_firing and not fast_breach:
                alert.state = "ok"
                self._record(alert, "resolved", now)
                alert.since = None

    def _retire(self, name: str, now: float) -> None:
        """Objective disabled (threshold 0): resolve if firing."""
        with self._lock:
            alert = self._alerts.get(name)
            if alert is not None and alert.state == "firing":
                alert.state = "ok"
                self._record(alert, "resolved", now)
                alert.since = None

    def _trace(self) -> Optional[str]:
        if self._active_trace is None:
            return None
        try:
            return self._active_trace()
        except Exception:
            return None

    def _record(self, alert: Alert, transition: str,
                now: float) -> None:
        """Caller holds ``self._lock``. Event-log write is strictly
        best-effort (log_event already swallows)."""
        entry = dict(alert.to_dict(), transition=transition,
                     atUnixSeconds=round(now, 3))
        self._history.append(entry)
        obs_export.log_event(
            "alert", f"{alert.name}.{transition}",
            trace_id=alert.trace, severity=alert.severity,
            value=alert.value, threshold=alert.threshold)
        if transition == "firing":
            # flight-recorder hook. MUST stay a cheap enqueue: we hold
            # the watchdog's non-reentrant lock here, and the capture
            # worker will call snapshot() on this very watchdog
            obs_incidents.trigger(
                f"slo:{alert.name}", trace=alert.trace,
                alert=entry)

    # -- read side ----------------------------------------------------

    def firing(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [a.to_dict() for a in self._alerts.values()
                    if a.state == "firing"]

    def firing_count(self) -> int:
        return len(self.firing())

    def page_firing(self) -> bool:
        return any(a["severity"] == "page" for a in self.firing())

    def snapshot(self) -> Dict[str, Any]:
        """The `/observability/alerts` document."""
        with self._lock:
            alerts = [a.to_dict() for a in self._alerts.values()]
            history = list(self._history)
        return {"objectives": self.objectives(), "alerts": alerts,
                "firing": [a for a in alerts
                           if a["state"] == "firing"],
                "history": history}
