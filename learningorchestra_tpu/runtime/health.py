"""Training health sentinel: policy + counters (docs/RELIABILITY.md).

The reference's only numerical-failure story is the one it has for
every failure: the job dies with ``finished: False`` and is re-run
from its stored parent (SURVEY §5) — and a re-run replays the same
divergence. Here the engine computes a cheap on-device health word
per train step (loss finiteness + global grad-norm, folded into the
metric sums it already carries) and checks it, together with an EMA
loss-spike test, at every epoch boundary against a per-job
:class:`HealthPolicy`:

- ``skip``      drop the poisoned update on-device, count the step;
- ``rollback``  restore the last-good checkpoint, re-seed the
                data/RNG cursor, resume with a spike-check cooldown;
- ``fail``      raise :class:`NumericalDivergence`, which
                services/jobs.py classifies as the ``numerical``
                error class (bounded rollback-retries, then
                deadLettered).

This module is deliberately jax-free: the services layer imports it
for classification and policy plumbing without touching a backend.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, Optional
from learningorchestra_tpu.runtime import locks

ACTIONS = ("off", "skip", "rollback", "fail")


class NumericalDivergence(RuntimeError):
    """A train job failed its health policy (non-finite step or loss
    spike with no rollback budget left). Its own error class in
    services/jobs.py: retried with bounded rollback-retries — a re-run
    of a checkpointed fit resumes from the last-good step — before
    dead-lettering."""


@dataclasses.dataclass(frozen=True)
class HealthPolicy:
    """Per-fit sentinel policy (request ``healthPolicy`` field /
    ``LO_HEALTH_*`` defaults)."""

    action: str = "skip"
    # epoch mean loss > spike_factor * EMA(loss) counts as a spike
    spike_factor: float = 4.0
    ema_alpha: float = 0.3
    # in-fit rollback budget before the fit raises NumericalDivergence
    max_rollbacks: int = 2
    # epochs after a rollback/restore during which the spike check is
    # suppressed (the EMA is stale relative to the restored params)
    cooldown_epochs: int = 1

    def jit_signature(self) -> tuple:
        """What the sentinel changes about the TRACED program: the
        instrumentation itself plus the on-device skip guard. Part of
        the engine's executable-cache key."""
        return ("health", self.action == "skip")


def coerce_policy(value: Any) -> Optional[HealthPolicy]:
    """``None`` | action string | camelCase dict (the REST request
    shape) | HealthPolicy -> HealthPolicy or None (disabled). Raises
    ValueError naming the bad field on malformed input."""
    if value is None:
        return None
    if isinstance(value, HealthPolicy):
        return None if value.action in ("", "off") else value
    if isinstance(value, str):
        value = {"action": value}
    if not isinstance(value, dict):
        raise ValueError(
            f"healthPolicy must be an action string or object, "
            f"got {type(value).__name__}")
    action = value.get("action", "skip")
    if action not in ACTIONS:
        raise ValueError(
            f"healthPolicy.action must be one of {ACTIONS}, "
            f"got {action!r}")
    if action == "off":
        return None
    policy = HealthPolicy(
        action=action,
        spike_factor=float(value.get("spikeFactor", 4.0)),
        ema_alpha=float(value.get("emaAlpha", 0.3)),
        max_rollbacks=int(value.get("maxRollbacks", 2)),
        cooldown_epochs=int(value.get("cooldownEpochs", 1)))
    if policy.spike_factor <= 1.0:
        raise ValueError(
            f"healthPolicy.spikeFactor must be > 1, "
            f"got {policy.spike_factor!r}")
    if not 0.0 < policy.ema_alpha <= 1.0:
        raise ValueError(
            f"healthPolicy.emaAlpha must be in (0, 1], "
            f"got {policy.ema_alpha!r}")
    if policy.max_rollbacks < 0:
        raise ValueError(
            f"healthPolicy.maxRollbacks must be >= 0, "
            f"got {policy.max_rollbacks!r}")
    if policy.cooldown_epochs < 0:
        raise ValueError(
            f"healthPolicy.cooldownEpochs must be >= 0, "
            f"got {policy.cooldown_epochs!r}")
    return policy


def resolve_policy(request: Any, config) -> Optional[HealthPolicy]:
    """The effective policy for a job: the request's ``healthPolicy``
    (already-validated dict/string) merged OVER the ``LO_HEALTH_*``
    config defaults; None when disabled both ways."""
    defaults = {
        "action": getattr(config, "health_action", "") or "off",
        "spikeFactor": getattr(config, "health_spike_factor", 4.0),
        "emaAlpha": getattr(config, "health_ema_alpha", 0.3),
        "maxRollbacks": getattr(config, "health_max_rollbacks", 2),
        "cooldownEpochs": getattr(config, "health_cooldown_epochs", 1),
    }
    if isinstance(request, str):
        request = {"action": request}
    if isinstance(request, dict):
        defaults.update(request)
    elif request is not None:
        return coerce_policy(request)
    return coerce_policy(defaults)


# ----------------------------------------------------------------------
# process-wide monotonic counters, exported as lo_nonfinite_steps_total
# / lo_rollbacks_total / lo_loss_spikes_total /
# lo_checkpoints_quarantined_total by the Api (/metrics)
# ----------------------------------------------------------------------
_lock = locks.make_lock("health.counters")
_counters: Dict[str, int] = {"nonfiniteSteps": 0, "lossSpikes": 0,
                             "rollbacks": 0, "quarantined": 0,
                             # quantized-serving quality gate
                             # (services/serving.py): drift-probe
                             # breaches and quant→bf16 degrades,
                             # exported as lo_serving_drift_breaches
                             # _total / lo_serving_quant_degrades_total
                             "driftBreaches": 0, "quantDegrades": 0}
# observers of sentinel events (the incident flight recorder
# subscribes to rollbacks); notified OUTSIDE the counter lock so a
# listener can read health_stats() without deadlocking, and strictly
# best-effort — a raising listener never touches the fit
_listeners: list = []


def add_listener(fn) -> None:
    """Register ``fn(kind, n)`` to be called after every
    :func:`record`."""
    with _lock:
        _listeners.append(fn)


def remove_listener(fn) -> None:
    with _lock:
        try:
            _listeners.remove(fn)
        except ValueError:
            pass


def record(kind: str, n: int = 1) -> None:
    with _lock:
        _counters[kind] = _counters.get(kind, 0) + n
        listeners = list(_listeners)
    for fn in listeners:
        try:
            fn(kind, n)
        except Exception:  # noqa: BLE001
            pass


def health_stats() -> Dict[str, int]:
    with _lock:
        return dict(_counters)


def reset_health_stats() -> None:
    with _lock:
        for key in _counters:
            _counters[key] = 0
