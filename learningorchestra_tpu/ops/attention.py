"""Fused flash attention (Pallas TPU kernels, forward AND backward).

Forward: one ``pallas_call`` over a ``(batch*heads, q_blocks,
kv_blocks)`` grid — the Q tile stays resident in VMEM while K/V tiles
stream past it, an online-softmax accumulator (running max +
log-sum-exp) keeps the math exact, and scores never round-trip to HBM.
The MXU sees two matmuls per tile (``q·kᵀ`` and ``p·v``), both with
``preferred_element_type=float32``.

Backward: custom VJP with two hand-scheduled Pallas kernels using the
standard flash recurrence (score tiles recomputed from the saved
log-sum-exp; the (seq × seq) matrix is never materialised):

- dQ kernel — Q/dO tiles resident, K/V stream past; 3 MXU matmuls per
  tile (``q·kᵀ``, ``do·vᵀ``, ``ds·k``), dQ accumulates in VMEM.
- dK/dV kernel — K/V tiles resident, Q/dO stream past; 4 MXU matmuls
  per tile, dK/dV accumulate in VMEM.

``delta = Σ do·o`` is a cheap XLA fusion outside the kernels. Causal
runs skip fully-masked tiles in all three kernels (grid-level
``pl.when``), halving causal FLOPs.

The reference framework has no attention op at all (SURVEY §5
"long-context" row — sequence models run inside user TF code through
the generic executor, binary_execution.py:177-189); flash attention is
one of the net-new TPU-first components. On CPU (tests, the 8-virtual-
device mesh) the same kernels run in interpreter mode.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from jax.experimental.pallas import tpu as pltpu

# jax >= 0.5 renamed TPUCompilerParams -> CompilerParams; accept both
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    getattr(pltpu, "TPUCompilerParams")

NEG_INF = -1e30


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _auto_interpret() -> bool:
    return jax.default_backend() != "tpu"


# Default tile edge for block_q/block_k when the caller doesn't pick
# one. Measured on a real v5e chip (seq 4096, d 64, fwd+bwd): 128-wide
# tiles leave the kernel grid-overhead-bound at 65 ms vs the 36 ms XLA
# fused-dot oracle, while 512-wide tiles amortize the per-step
# bookkeeping and overtake it at 21 ms (1024: 18.6 ms, but coarse
# tiles blunt the causal block-skip and cost 4x the VMEM for ~12%
# more, so 512 is the cap; override per-call or via LO_FLASH_BLOCK).
def _auto_block(seq: int) -> int:
    raw = os.environ.get("LO_FLASH_BLOCK", "512")
    try:
        cap = int(raw)
    except ValueError:
        raise ValueError(f"LO_FLASH_BLOCK must be an integer, got {raw!r}")
    if cap < 8 or cap % 8:
        raise ValueError(
            f"LO_FLASH_BLOCK must be a multiple of 8 and >= 8 "
            f"(TPU sublane tiling), got {cap}")
    block = cap
    # shrink while the tile would pad the sequence by more than ~12%:
    # e.g. seq 640 under a 512 tile pads to 1024 (2.5x the MXU work
    # of the exact 128-tile grid); 128 tiles pad it not at all
    while block > 128 and _round_up(seq, block) > seq * 1.125:
        block //= 2
    return block


def _band_lo(i, block_q: int, block_k: int, window: int,
             offset: int = 0):
    """First in-band kv tile for q tile ``i`` (0 when unwindowed).
    ``offset`` is the static global-position shift of the k axis
    relative to q (cross-shard ring hops): col_global = c + offset."""
    if window <= 0:
        return 0
    return jnp.maximum(0, (i * block_q - window + 1 - offset)
                       // block_k)


def _band_width(nk: int, block_q: int, block_k: int,
                window: int) -> int:
    """Grid width (in kv tiles) that covers any q tile's band."""
    if window <= 0:
        return nk
    span = block_q + window - 1
    return min(nk, (span - 2) // block_k + 2)


def _kv_index_map(block_q: int, block_k: int, window: int,
                  causal: bool, nk: int, nq_head: int,
                  offset: int = 0):
    """BlockSpec index map for the streamed K/V tiles: maps grid step
    j to kv tile clip(lo+j, 0, hi). Out-of-band steps repeat the
    boundary tile index — Mosaic's pipeline only issues a copy when
    the block index CHANGES between steps, so the clamp turns the
    causal upper triangle (and both sides of a sliding-window band)
    into zero-copy revisits instead of dead DMA. Under grouped-query
    folding the q-tile position within its head is i % nq_head."""

    def index(b, i, j):
        ih = i % nq_head
        j_eff = _band_lo(ih, block_q, block_k, window, offset) + j
        hi = nk - 1
        if causal:
            # floored: a positive offset (future-shifted keys) could
            # push the causal bound below 0 — the DMA index must stay
            # in bounds even for tiles the run predicate discards
            hi = jnp.maximum(
                jnp.minimum(
                    hi,
                    (ih * block_q + block_q - 1 - offset) // block_k),
                0)
        return (b, jnp.clip(j_eff, 0, hi), 0)

    return index


def _qband_lo(j, block_q: int, block_k: int, causal: bool,
              offset: int = 0):
    """First q tile whose rows can see kv tile ``j`` (causal)."""
    if not causal:
        return 0
    return jnp.maximum(0, (j * block_k + offset) // block_q)


def _qband_width(nq: int, block_q: int, block_k: int,
                 window: int) -> int:
    """Grid width (in q tiles) covering any kv tile's visible rows
    when windowed (causal-only bands run to the end, width nq)."""
    if window <= 0:
        return nq
    span = block_k + window - 1
    return min(nq, (span - 2) // block_q + 2)


def _q_index_map(block_q: int, block_k: int, window: int,
                 causal: bool, nq: int, band_ni: int,
                 offset: int = 0):
    """Streamed-Q BlockSpec index map for the dK/dV kernel: grid step
    i = (head, within-band) -> folded q tile
    head·nq + clip(lo+within, 0, hi); out-of-band steps revisit."""

    def index(b, j, i):
        head = i // band_ni
        within = i % band_ni
        i_eff = _qband_lo(j, block_q, block_k, causal, offset) + within
        hi = nq - 1
        if window > 0:
            # a negative ring offset can push the whole band before
            # row 0 (hi < 0) — floor it so the clip never emits a
            # negative block index (the run predicate discards the
            # tile's data, but the DMA itself must stay in bounds)
            hi = jnp.maximum(
                jnp.minimum(
                    hi,
                    (j * block_k + block_k - 1 + offset + window - 1)
                    // block_q),
                0)
        return (b, head * nq + jnp.clip(i_eff, 0, hi), 0)

    return index


def _resolve_blocks(block_q: Optional[int], block_k: Optional[int],
                    sq: int, sk: int) -> Tuple[int, int]:
    return (int(block_q) if block_q else _auto_block(sq),
            int(block_k) if block_k else _auto_block(sk))


# ----------------------------------------------------------------------
# forward kernel
# ----------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref,
                *, scale: float, causal: bool, kv_len: int,
                block_q: int, block_k: int, window: int = 0,
                nk_total: int = 0, nq_head: int = 0,
                offset: int = 0):
    # grouped-query folding: the q-row axis stacks `group` query heads
    # per kv head, so the tile's POSITION within its head is
    # i % nq_head (== i when ungrouped) — all causal/window math uses
    # that, while the storage index stays i. `offset` statically
    # shifts k positions (cross-shard ring hops): col = c + offset.
    i = pl.program_id(1)
    ih = i % nq_head
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # banded iteration: grid step j covers the kv tile lo+j, where lo
    # is the first in-band tile for this q tile (window) — the kv
    # BlockSpec index map clamps with the same formula, so
    # out-of-band steps revisit a fetched block (no DMA) and are
    # predicated off here
    j_eff = _band_lo(ih, block_q, block_k, window, offset) + j
    run = True
    if causal:
        run = (j_eff * block_k + offset
               <= ih * block_q + block_q - 1)
    if window > 0:
        run = jnp.logical_and(run, j_eff <= nk_total - 1)

    @pl.when(run)
    def _tile():
        q = q_ref[0]                       # (block_q, d)
        k = k_ref[0]                       # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bq, bk)

        col = j_eff * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = col < kv_len
        if causal or window > 0:
            row = ih * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
        if causal:
            valid = jnp.logical_and(valid, row >= col + offset)
        if window > 0:
            valid = jnp.logical_and(valid,
                                    col + offset > row - window)
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[:, :1]                              # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # guard: a fully-masked row has s = m_new = NEG_INF and
        # exp(0) = 1 junk — zero it explicitly
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)                    # (bq, 1)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = m_new * jnp.ones_like(m_ref)

    @pl.when(j == nk - 1)
    def _finish():
        l = l_ref[:, :1]
        safe_l = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)
        m = m_ref[:, :1]
        # a row with NO visible keys (possible on windowed/offset
        # hops) must carry lse = -inf so ring log-sum-exp merges give
        # it ZERO weight — 0.0 would weigh it exp(0) = 1
        lse = jnp.where(l > 0, m + jnp.log(safe_l), NEG_INF)  # (bq, 1)
        # lse output carries a 128-lane trailing dim (Mosaic requires
        # the last two block dims tile to (8, 128)); value broadcast
        # across lanes, wrapper reads lane 0
        lse_ref[0] = lse * jnp.ones_like(lse_ref[0])


def _fwd_pallas(q, k, v, *, scale: float, causal: bool,
                block_q: int, block_k: int, interpret: bool,
                window: int = 0, group: int = 1, seq_q: int = 0,
                offset: int = 0
                ) -> Tuple[jax.Array, jax.Array]:
    """q: (b·kv, group·sq_p, d) pre-padded/folded (``_fold_q``);
    k/v: (b·kv, sk, d). Returns (o, lse) in the folded layout.
    ``seq_q`` is the per-head padded q length (sq_p)."""
    bh, sq_fold, d = q.shape
    sq_p = seq_q or sq_fold
    sk = k.shape[1]
    block_k = min(block_k, _round_up(sk, 8))
    sk_p = _round_up(sk, block_k)
    d_p = _round_up(d, 128)
    q = jnp.pad(q, ((0, 0), (0, 0), (0, d_p - d)))
    k = jnp.pad(k, ((0, 0), (0, sk_p - sk), (0, d_p - d)))
    v = jnp.pad(v, ((0, 0), (0, sk_p - sk), (0, d_p - d)))

    nk = sk_p // block_k
    nj = _band_width(nk, block_q, block_k, window)
    nq_head = sq_p // block_q
    grid = (bh, group * nq_head, nj)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, kv_len=sk,
        block_q=block_q, block_k=block_k, window=window, nk_total=nk,
        nq_head=nq_head, offset=offset)
    kv_map = _kv_index_map(block_q, block_k, window, causal, nk,
                           nq_head, offset)
    lanes = 128
    scratch = [
        pltpu.VMEM((block_q, d_p), jnp.float32),
        pltpu.VMEM((block_q, lanes), jnp.float32),
        pltpu.VMEM((block_q, lanes), jnp.float32),
    ]
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d_p), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d_p), kv_map),
            pl.BlockSpec((1, block_k, d_p), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d_p), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, lanes), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, group * sq_p, d_p), q.dtype),
            jax.ShapeDtypeStruct((bh, group * sq_p, lanes),
                                 jnp.float32),
        ],
        scratch_shapes=scratch,
        # bh and the Q-tile axis own disjoint outputs/accumulator
        # streaks -> Mosaic may split them across megacore; the KV
        # stream axis accumulates and must stay sequential
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return o[..., :d], lse[..., 0]


# ----------------------------------------------------------------------
# backward kernels (flash recurrence, hand-scheduled)
# ----------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc_ref,
                   *, scale: float, causal: bool, kv_len: int,
                   block_q: int, block_k: int, window: int = 0,
                   nk_total: int = 0, nq_head: int = 0,
                   offset: int = 0):
    """Grid (bh, q_blocks, kv_band): Q/dO resident, K/V stream the
    band (same clamped-index revisit scheme as the forward; grouped
    folding puts `group` query heads on the q axis — see
    _fwd_kernel)."""
    i = pl.program_id(1)
    ih = i % nq_head
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    j_eff = _band_lo(ih, block_q, block_k, window, offset) + j
    run = True
    if causal:
        run = (j_eff * block_k + offset
               <= ih * block_q + block_q - 1)
    if window > 0:
        run = jnp.logical_and(run, j_eff <= nk_total - 1)

    @pl.when(run)
    def _tile():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]                            # (bq, 1)
        delta = delta_ref[0][:, :1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # (bq, bk)
        col = j_eff * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = col < kv_len
        if causal or window > 0:
            row = ih * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
        if causal:
            valid = jnp.logical_and(valid, row >= col + offset)
        if window > 0:
            valid = jnp.logical_and(valid,
                                    col + offset > row - window)
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bq, bk)
        ds = p * (dp - delta) * scale
        dq_acc_ref[...] += jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
                    *, scale: float, causal: bool, kv_len: int,
                    block_q: int, block_k: int, window: int = 0,
                    nq_total: int = 0, band_ni: int = 0,
                    offset: int = 0):
    """Grid (bh·kv, kv_blocks, group·q_band): K/V resident, Q/dO
    stream the band of q tiles whose rows can see this kv tile
    (causal: from the diagonal down; window: at most W-1 rows past
    it), once per grouped query head — dK/dV accumulate over the
    whole group. Same clamped-index revisit scheme as the forward."""
    j = pl.program_id(1)
    i = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    within = i % band_ni
    i_eff = _qband_lo(j, block_q, block_k, causal, offset) + within
    run = i_eff <= nq_total - 1
    if causal:
        run = jnp.logical_and(
            run,
            j * block_k + offset <= i_eff * block_q + block_q - 1)
    if window > 0:
        run = jnp.logical_and(
            run,
            i_eff * block_q
            <= j * block_k + block_k - 1 + offset + window - 1)

    @pl.when(run)
    def _tile():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # (bq, bk)
        col = j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = col < kv_len
        if causal or window > 0:
            row = i_eff * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
        if causal:
            valid = jnp.logical_and(valid, row >= col + offset)
        if window > 0:
            valid = jnp.logical_and(valid,
                                    col + offset > row - window)
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)         # (bq, bk)
        dv_acc_ref[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bk, d)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                       # (bq, bk)
        dk_acc_ref[...] += jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bk, d)

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _bwd_pallas(q, k, v, o, lse, do, *, scale: float, causal: bool,
                block_q: int, block_k: int, interpret: bool,
                dlse=None, window: int = 0, group: int = 1,
                seq_q: int = 0, offset: int = 0):
    """Folded layout (see ``_fwd_pallas``): q/o/do (b·kv, g·sq_p, d),
    lse (b·kv, g·sq_p), k/v (b·kv, sk, d). Returns (dq, dk, dv) in
    the same folded layout. ``seq_q`` is the per-head padded q length.

    ``dlse``, when given, is the upstream gradient on the
    log-sum-exp output (ring-flash merges consume lse, so it carries
    real gradient there). Math: dL/ds_ij gains the term
    ``dlse_i · ∂lse_i/∂s_ij = dlse_i · p_ij``, so
    ``ds = p·(dp - delta + dlse)`` — exactly the existing kernels with
    ``delta - dlse`` fed in place of ``delta``. No kernel change.
    """
    bh, sq_fold, d = q.shape
    sq_p = seq_q or sq_fold
    sk = k.shape[1]
    block_k = min(block_k, _round_up(sk, 8))
    sk_p = _round_up(sk, block_k)
    d_p = _round_up(d, 128)
    lanes = 128
    nq_head = sq_p // block_q

    # delta = rowsum(do * o): one XLA fusion, no kernel needed. Padded
    # rows carry q = do = 0, so their p·(dp - delta) contributions to
    # dk/dv vanish without an explicit row mask.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                             # (bh, g·sq_p)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)

    q = jnp.pad(q, ((0, 0), (0, 0), (0, d_p - d)))
    k = jnp.pad(k, ((0, 0), (0, sk_p - sk), (0, d_p - d)))
    v = jnp.pad(v, ((0, 0), (0, sk_p - sk), (0, d_p - d)))
    do = jnp.pad(do, ((0, 0), (0, 0), (0, d_p - d)))
    lse_l = lse[..., None] * jnp.ones((1, 1, lanes), jnp.float32)
    delta_l = delta[..., None] * jnp.ones((1, 1, lanes), jnp.float32)

    nk = sk_p // block_k
    nj = _band_width(nk, block_q, block_k, window)
    q_spec_i = pl.BlockSpec((1, block_q, d_p), lambda b, i, j: (b, i, 0))
    kv_spec_j = pl.BlockSpec((1, block_k, d_p),
                             _kv_index_map(block_q, block_k, window,
                                           causal, nk, nq_head,
                                           offset))
    row_spec_i = pl.BlockSpec((1, block_q, lanes),
                              lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          kv_len=sk, block_q=block_q, block_k=block_k,
                          window=window, nk_total=nk, nq_head=nq_head,
                          offset=offset),
        grid=(bh, group * nq_head, nj),
        in_specs=[q_spec_i, kv_spec_j, kv_spec_j, q_spec_i, row_spec_i,
                  row_spec_i],
        out_specs=q_spec_i,
        out_shape=jax.ShapeDtypeStruct((bh, group * sq_p, d_p),
                                       jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_q, d_p), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse_l, delta_l)

    # second kernel: K/V resident, Q streams — grid dims (b, j, i)
    band_ni = _qband_width(nq_head, block_q, block_k, window)
    q_map = _q_index_map(block_q, block_k, window, causal, nq_head,
                         band_ni, offset)
    q_spec_g2 = pl.BlockSpec((1, block_q, d_p), q_map)
    kv_spec_g2 = pl.BlockSpec((1, block_k, d_p), lambda b, j, i: (b, j, 0))
    row_spec_g2 = pl.BlockSpec((1, block_q, lanes), q_map)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          kv_len=sk, block_q=block_q, block_k=block_k,
                          window=window, nq_total=nq_head,
                          band_ni=band_ni, offset=offset),
        grid=(bh, sk_p // block_k, group * band_ni),
        in_specs=[q_spec_g2, kv_spec_g2, kv_spec_g2, q_spec_g2,
                  row_spec_g2, row_spec_g2],
        out_specs=[kv_spec_g2, kv_spec_g2],
        out_shape=[jax.ShapeDtypeStruct((bh, sk_p, d_p), jnp.float32),
                   jax.ShapeDtypeStruct((bh, sk_p, d_p), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((block_k, d_p), jnp.float32),
                        pltpu.VMEM((block_k, d_p), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse_l, delta_l)
    return (dq[..., :d], dk[:, :sk, :d], dv[:, :sk, :d])


# ----------------------------------------------------------------------
# grouped fold helpers + custom-vjp wrapper
# ----------------------------------------------------------------------
def _fold_q(x, kvh: int, group: int, sq_p: int):
    """(b, sq, h, d) -> (b*kv, group*sq_p, d): head-major fold with
    per-head row padding, so each query head's rows are a contiguous
    run of whole q tiles and K/V stream ONCE per kv head."""
    b, sq, h, d = x.shape
    x = jnp.pad(x, ((0, 0), (0, sq_p - sq), (0, 0), (0, 0)))
    x = x.transpose(0, 2, 1, 3).reshape(b, kvh, group, sq_p, d)
    return x.reshape(b * kvh, group * sq_p, d)


def _unfold_q(x, b: int, kvh: int, group: int, sq_p: int, sq: int):
    d = x.shape[-1]
    x = x.reshape(b, kvh * group, sq_p, d)
    return x.transpose(0, 2, 1, 3)[:, :sq]


def _merge_kv(x):
    """(b, sk, kv, d) -> (b*kv, sk, d)."""
    b, sk, kvh, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * kvh, sk, d)


def _split_kv(x, b: int, kvh: int):
    bkv, sk, d = x.shape
    return x.reshape(b, kvh, sk, d).transpose(0, 2, 1, 3)


def _flash_plan(q, k, block_q: int):
    b, sq, h, d = q.shape
    kvh = k.shape[2]
    group = h // kvh
    bq = min(block_q, _round_up(sq, 8))
    sq_p = _round_up(sq, bq)
    return b, sq, h, d, kvh, group, bq, sq_p


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret,
           window=0):
    """q: (b, sq, h, d); k/v: (b, sk, kv, d) with kv | h. Grouped
    query heads fold into the q-row axis, so K/V never materialize at
    h heads (the GQA point: HBM traffic scales with kv, not h)."""
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k,
                        interpret, window)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               window=0):
    b, sq, h, d, kvh, group, bq, sq_p = _flash_plan(q, k, block_q)
    qf = _fold_q(q, kvh, group, sq_p)
    kf, vf = _merge_kv(k), _merge_kv(v)
    o, lse = _fwd_pallas(qf, kf, vf, scale=scale, causal=causal,
                         block_q=bq, block_k=block_k,
                         interpret=interpret, window=window,
                         group=group, seq_q=sq_p)
    out = _unfold_q(o, b, kvh, group, sq_p, sq)
    return out, (qf, kf, vf, o, lse, (b, sq, kvh, group, bq, sq_p))


def _flash_bwd(causal, scale, block_q, block_k, interpret, window,
               res, g):
    qf, kf, vf, o, lse, meta = res
    b, sq, kvh, group, bq, sq_p = meta
    gf = _fold_q(g, kvh, group, sq_p)
    dq, dk, dv = _bwd_pallas(qf, kf, vf, o, lse, gf, scale=scale,
                             causal=causal, block_q=bq,
                             block_k=block_k, interpret=interpret,
                             window=window, group=group, seq_q=sq_p)
    dq4 = _unfold_q(dq, b, kvh, group, sq_p, sq).astype(qf.dtype)
    dk4 = _split_kv(dk, b, kvh).astype(kf.dtype)
    dv4 = _split_kv(dv, b, kvh).astype(vf.dtype)
    return dq4, dk4, dv4


_flash.defvjp(_flash_fwd, _flash_bwd)


def _pad_rows(x, sq_p: int):
    return jnp.pad(x, ((0, 0), (0, sq_p - x.shape[1])) +
                   ((0, 0),) * (x.ndim - 2))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8,
                                                    9))
def _flash_lse(q, k, v, causal, scale, block_q, block_k, interpret,
               window=0, offset=0):
    """Like ``_flash`` but merged-head 3D (bh, s, d) and also returns
    the log-sum-exp rows — the merge quantity sequence-parallel (ring)
    composition needs. lse carries real gradient through the merge
    weights, handled in the vjp via the ``delta - dlse`` identity
    (see _bwd_pallas). Ungrouped (ring repeats KV to full heads
    before sharding)."""
    out, _ = _flash_lse_fwd(q, k, v, causal, scale, block_q, block_k,
                            interpret, window, offset)
    return out


def _flash_lse_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                   window=0, offset=0):
    bh, sq, d = q.shape
    bq = min(block_q, _round_up(sq, 8))
    sq_p = _round_up(sq, bq)
    qp = _pad_rows(q, sq_p)
    o, lse = _fwd_pallas(qp, k, v, scale=scale, causal=causal,
                         block_q=bq, block_k=block_k,
                         interpret=interpret, seq_q=sq_p,
                         window=window, offset=offset)
    return (o[:, :sq], lse[:, :sq]), (qp, k, v, o, lse, sq, sq_p, bq)


def _flash_lse_bwd(causal, scale, block_q, block_k, interpret, window,
                   offset, res, g):
    qp, k, v, o, lse, sq, sq_p, bq = res
    do, dlse = g
    dq, dk, dv = _bwd_pallas(qp, k, v, o, lse, _pad_rows(do, sq_p),
                             scale=scale, causal=causal, block_q=bq,
                             block_k=block_k, interpret=interpret,
                             dlse=_pad_rows(dlse, sq_p), seq_q=sq_p,
                             window=window, offset=offset)
    return (dq[:, :sq].astype(qp.dtype), dk.astype(k.dtype),
            dv.astype(v.dtype))


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


# ----------------------------------------------------------------------
# public API
# ----------------------------------------------------------------------
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False, scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    window: int = 0) -> jax.Array:
    """Fused attention over (batch, seq, heads, head_dim) arrays.

    Layout matches :mod:`learningorchestra_tpu.parallel.ring` so the
    transformer can swap between single-chip flash and ring/Ulysses SP
    without reshuffling. Differentiable (custom VJP).

    GQA-native: ``k``/``v`` may carry FEWER heads than ``q``
    (``kv | h``) — the query-head group folds into the kernel's q-row
    axis, so K/V stream once per KV head and never materialize at
    ``h`` heads in HBM (the grouped-attention memory win survives the
    kernel boundary).

    ``window=W`` (requires ``causal=True``) is sliding-window
    attention: query p attends keys in ``[p-W+1, p]``. The kv grid
    axis is BANDED: it spans only ~(block+W)/block tiles per q tile,
    with clamped index maps so boundary revisits issue no DMA — both
    compute AND copy traffic scale ~O(s·W) instead of O(s²)
    (Mistral-style SWA). Plain causal runs get the same clamp on the
    upper triangle, halving their K/V copy traffic.
    """
    b, sq, h, d = q.shape
    kvh = k.shape[2]
    if h % kvh:
        raise ValueError(
            f"q has {h} heads but k/v have {kvh} — kv heads must "
            f"divide query heads (GQA)")
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window and not causal:
        raise ValueError("window requires causal=True (banded causal "
                         "attention)")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _auto_interpret()
    block_q, block_k = _resolve_blocks(block_q, block_k,
                                       sq, k.shape[1])
    return _flash(q, k, v, causal, float(scale), block_q, block_k,
                  bool(interpret), int(window))


def flash_attention_with_lse(q: jax.Array, k: jax.Array, v: jax.Array,
                             *, causal: bool = False,
                             scale: Optional[float] = None,
                             block_q: Optional[int] = None,
                             block_k: Optional[int] = None,
                             interpret: Optional[bool] = None,
                             window: int = 0, kv_offset: int = 0,
                             ) -> Tuple[jax.Array, jax.Array]:
    """(out (b, sq, h, d), lse (b, sq, h)) — the blockwise form ring
    attention composes across devices (parallel/ring.py): hop outputs
    merge exactly via log-sum-exp weights. Differentiable in both
    outputs (lse gradient flows through the merge)."""
    b, sq, h, d = q.shape
    if k.shape[2] != h:
        raise ValueError(
            f"flash_attention_with_lse needs equal head counts "
            f"(q has {h}, k/v have {k.shape[2]}) — repeat K/V to "
            f"full heads first; grouped GQA is flash_attention only")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _auto_interpret()
    block_q, block_k = _resolve_blocks(block_q, block_k,
                                       sq, k.shape[1])

    def merge_heads(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    o, lse = _flash_lse(merge_heads(q), merge_heads(k), merge_heads(v),
                        causal, float(scale), block_q,
                        block_k, bool(interpret), int(window),
                        int(kv_offset))
    o = o.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    lse = lse.reshape(b, h, sq).transpose(0, 2, 1)
    return o, lse


def reference_attention(q, k, v, causal: bool = False,
                        scale: Optional[float] = None) -> jax.Array:
    """Unfused full-softmax oracle (same layout/contract)."""
    from learningorchestra_tpu.parallel.ring import full_attention_reference

    return full_attention_reference(q, k, v, causal=causal, scale=scale)


# ---------------------------------------------------------------------------
# Single-token decode attention (the serving plane's hot op).
#
# These are deliberately NOT pallas kernels: the serving bit-identity
# contract (docs/SERVING.md) requires the continuous batcher to
# reproduce the solo decode loop's float32 reduction order exactly,
# so the math below mirrors models/transformer.py's decode branch
# einsum-for-einsum. A fused single-token kernel saves little anyway —
# q is one row, the op is bandwidth-bound on the KV cache read.


def decode_attention(q: jax.Array, k_cache: jax.Array,
                     v_cache: jax.Array, col: jax.Array, *,
                     pad_offset: Optional[jax.Array] = None,
                     window: int = 0,
                     scale: Optional[float] = None) -> jax.Array:
    """One-token GQA attention against a per-row cache position.

    ``q`` is ``(b, 1, n_heads, d)``, ``k_cache``/``v_cache`` are
    ``(b, L, kv_heads, d)``, ``col`` is ``(b,)`` — each batch row
    attends its own prefix ``[pad_offset[i], col[i]]`` of the cache
    (continuous batching: rows sit at different decode positions).
    ``pad_offset`` (``(b,)``, optional) hides left-pad rows;
    ``window > 0`` restricts to the last ``window`` positions. Masked
    scores take ``NEG_INF`` whose softmax term underflows to exact
    zero, so a row's output bits match a solo batch-1 decode."""
    b, s, h, d = q.shape
    kv = k_cache.shape[2]
    group = h // kv
    qg = q.astype(jnp.float32).reshape(b, s, kv, group, d)
    scores = jnp.einsum(
        "bqhgd,bkhd->bqhgk", qg, k_cache.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    # DIVIDE by sqrt(d) (not multiply by the reciprocal): x/s and
    # x*(1/s) round differently, and the solo decode branch in
    # models/transformer.py divides — the bit-identity contract hangs
    # on matching it exactly
    scores = scores * scale if scale is not None \
        else scores / (d ** 0.5)
    length = k_cache.shape[1]
    positions = jnp.arange(length)
    visible = positions[None, :] <= col[:, None]
    if pad_offset is not None:
        visible = visible & (positions[None, :] >= pad_offset[:, None])
    if window > 0:
        visible = visible & (positions[None, :] >
                             (col - window)[:, None])
    scores = jnp.where(visible[:, None, None, None, :], scores,
                       NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum("bqhgk,bkhd->bqhgd", p,
                   v_cache.astype(jnp.float32))
    return o.reshape(b, s, h, d).astype(q.dtype)


def paged_decode_attention(q: jax.Array, k_pool: jax.Array,
                           v_pool: jax.Array,
                           block_tables: jax.Array,
                           col: jax.Array, *,
                           pad_offset: Optional[jax.Array] = None,
                           window: int = 0,
                           scale: Optional[float] = None,
                           max_pages: int = 0) -> jax.Array:
    """:func:`decode_attention` over a paged KV pool (vLLM layout).

    ``k_pool``/``v_pool`` are ``(pages, page_len, kv_heads, d)``;
    ``block_tables`` (``(b, n_pages)`` int) maps each request's
    logical cache to physical pages, so a request joining a serving
    slot reuses whatever pages are free — no recompile, no copy of
    other requests' state. Pages are gathered into the contiguous
    ``(b, n_pages * page_len, kv, d)`` layout and fed through the
    SAME reduction as :func:`decode_attention`: masked positions
    contribute exact zeros, so padding the key axis with garbage
    pages never changes the live positions' float sums and the
    gathered path stays bit-identical to the contiguous one
    (``tests/test_ops.py::test_paged_decode_*`` bit-parity suite;
    end-to-end vs ``generate`` in tests/test_serving.py).

    ``max_pages > 0`` statically clamps the gather to the first
    ``max_pages`` table columns: every masked-softmax term past the
    highest live ``col`` is an exact zero, so the caller (the paged
    serving session) can bucket the gather width to the longest live
    stream and short streams stop paying long-stream HBM reads.
    Under ``jit`` the clamp must be a static Python int (it picks the
    compiled gather shape)."""
    if max_pages and max_pages < block_tables.shape[1]:
        block_tables = block_tables[:, :max_pages]
    b = block_tables.shape[0]
    n_pages = block_tables.shape[1]
    page_len, kv, d = k_pool.shape[1], k_pool.shape[2], k_pool.shape[3]
    k = jnp.take(k_pool, block_tables, axis=0).reshape(
        b, n_pages * page_len, kv, d)
    v = jnp.take(v_pool, block_tables, axis=0).reshape(
        b, n_pages * page_len, kv, d)
    return decode_attention(q, k, v, col, pad_offset=pad_offset,
                            window=window, scale=scale)


def checked_pool_cast(pool: jax.Array, values: jax.Array) -> jax.Array:
    """Cast ``values`` to ``pool.dtype`` — REFUSING the cast when the
    pool is an integer (quantized) pool and the values are raw floats.
    A bare ``.astype(int8)`` silently truncates bf16 activations to
    garbage with no scaling; every raw pool write funnels through here
    so that mistake raises instead of corrupting a token stream. The
    quantized write path (:func:`quantized_paged_append_token` /
    :func:`quantized_paged_prefill_write`) scales first and never hits
    this guard."""
    if jnp.issubdtype(pool.dtype, jnp.integer) and \
            jnp.issubdtype(values.dtype, jnp.inexact):
        raise TypeError(
            f"raw write of {values.dtype} values into a quantized "
            f"{pool.dtype} KV pool — use the quantized_* ops, which "
            f"scale per page/head before narrowing")
    return values.astype(pool.dtype)


def paged_append_token(pool: jax.Array, new: jax.Array,
                       block_tables: jax.Array,
                       pos: jax.Array, page_len: int) -> jax.Array:
    """Scatter one decode step's K (or V) rows into their pages.

    ``pool`` is ``(pages, page_len, kv, d)``, ``new`` is
    ``(b, kv, d)`` (this step's projected key/value per stream),
    ``pos`` is ``(b,)`` absolute cache positions. Row ``i`` lands at
    ``pool[block_tables[i, pos[i] // page_len], pos[i] % page_len]``
    — the paged analog of the slot cache's ``at[rows, pos].set``."""
    rows = jnp.arange(new.shape[0])
    page = block_tables[rows, pos // page_len]
    return pool.at[page, pos % page_len].set(
        checked_pool_cast(pool, new))


def paged_prefill_write(pool: jax.Array, kv_rows: jax.Array,
                        page_ids: jax.Array,
                        start_row: jax.Array) -> jax.Array:
    """Write a prefill's prompt KV directly into pages.

    ``kv_rows`` is the ``(L, kv, d)`` contiguous K (or V) a prompt
    prefill produced; rows ``[start_row, start_row + n*page_len)``
    are reshaped into ``n = page_ids.shape[0]`` page chunks and
    scattered to ``pool[page_ids]``. ``start_row`` (a multiple of
    ``page_len``) is traced, so one compile per PAGE COUNT covers
    every prefix-cache split point — shared prefix pages are simply
    not in ``page_ids`` and never rewritten while other streams read
    them."""
    n = page_ids.shape[0]
    page_len = pool.shape[1]
    chunk = jax.lax.dynamic_slice_in_dim(
        kv_rows, start_row, n * page_len, axis=0)
    chunk = chunk.reshape((n, page_len) + kv_rows.shape[1:])
    return pool.at[page_ids].set(checked_pool_cast(pool, chunk))


# ---------------------------------------------------------------------------
# Quantized paged KV (int8 pages + per-page-per-head scales).
#
# Decode is bandwidth-bound on the pool read, so halving pool bytes
# roughly doubles resident streams at fixed HBM and tokens/s/chip
# (docs/SERVING.md "Quantized serving"). Layout: the int8 pool keeps
# the bf16 pool's (pages, page_len, kv, d) shape; a parallel scale
# pool (pages, kv) float32 holds one symmetric scale per page per KV
# head — coarse enough to be ~0.4% of pool bytes, fine enough that a
# loud head in one page never clips a quiet head. Dequant is fused
# into the bounded paged gather: only the gathered (b, width*page_len)
# working set is ever materialized in float, never a pool-sized bf16
# copy. The dequantized rows then flow through the SAME
# decode_attention reduction as the exact path, so quantization error
# is confined to the value rounding itself (bounded by the round-trip
# property test in tests/test_ops.py) and measured end-to-end by the
# serving drift gate.

_QUANT_EPS = 1e-8


def quantize_kv_pages(pages: jax.Array,
                      eps: float = _QUANT_EPS) -> Tuple[jax.Array,
                                                        jax.Array]:
    """Symmetric int8 quantization of a stack of KV pages.

    ``pages`` is ``(n, page_len, kv, d)`` float; returns
    ``(q (n, page_len, kv, d) int8, scales (n, kv) float32)`` with
    ``scale = max(amax / 127, eps)`` over each page's ``(page_len, d)``
    plane per KV head. The eps clamp keeps all-zero pages (fresh
    allocations, masked rows) from dividing by zero — they round-trip
    to exact zeros."""
    amax = jnp.max(jnp.abs(pages.astype(jnp.float32)), axis=(1, 3))
    scales = jnp.maximum(amax / 127.0, eps)
    scaled = pages.astype(jnp.float32) / scales[:, None, :, None]
    q = jnp.clip(jnp.round(scaled), -127, 127).astype(jnp.int8)
    return q, scales


def dequantize_kv_pages(q: jax.Array, scales: jax.Array) -> jax.Array:
    """Inverse of :func:`quantize_kv_pages`: ``(n, page_len, kv, d)``
    int8 + ``(n, kv)`` scales -> float32 pages."""
    return q.astype(jnp.float32) * scales[:, None, :, None]


def quantized_paged_prefill_write(pool: jax.Array, scales: jax.Array,
                                  kv_rows: jax.Array,
                                  page_ids: jax.Array,
                                  start_row: jax.Array,
                                  ) -> Tuple[jax.Array, jax.Array]:
    """:func:`paged_prefill_write` into an int8 pool: quantize the
    page chunks, scatter values to ``pool[page_ids]`` and their scales
    to ``scales[page_ids]``. Rows of ``kv_rows`` past the prompt
    length are exact zeros (the prefill cache is zero-initialized), so
    a partial last page's scale reflects only the live rows."""
    n = page_ids.shape[0]
    page_len = pool.shape[1]
    chunk = jax.lax.dynamic_slice_in_dim(
        kv_rows, start_row, n * page_len, axis=0)
    chunk = chunk.reshape((n, page_len) + kv_rows.shape[1:])
    q, s = quantize_kv_pages(chunk)
    return pool.at[page_ids].set(q), scales.at[page_ids].set(s)


def quantized_paged_append_token(pool: jax.Array, scales: jax.Array,
                                 new: jax.Array,
                                 block_tables: jax.Array,
                                 pos: jax.Array, page_len: int,
                                 ) -> Tuple[jax.Array, jax.Array]:
    """:func:`paged_append_token` into an int8 pool, requantizing the
    touched page in place.

    Each stream's current page is gathered, dequantized, masked to its
    LIVE rows (``row < pos % page_len`` — a freshly allocated page may
    carry a previous stream's stale int8 garbage, and masking kills it
    without any host-side page reset), the new row is inserted, and
    the page is requantized against the live maximum. While the scale
    is unchanged the old int8 values round-trip exactly (they are
    integer multiples of the scale); when the new row grows the amax
    the page re-rounds once against the larger scale — the same
    bounded per-value error as the original quantization. Duplicate
    trash-page-0 scatters (retired streams all point at page 0) pick
    an arbitrary winner, which is fine: page 0 is never read
    unmasked."""
    rows = jnp.arange(new.shape[0])
    page = block_tables[rows, pos // page_len]
    slot = pos % page_len
    cur = dequantize_kv_pages(pool[page], scales[page])  # (b,pl,kv,d)
    live = jnp.arange(page_len)[None, :, None, None] < \
        slot[:, None, None, None]
    cur = jnp.where(live, cur, 0.0)
    cur = jax.vmap(lambda p, i, r: p.at[i].set(r))(
        cur, slot, new.astype(jnp.float32))
    q, s = quantize_kv_pages(cur)
    return pool.at[page].set(q), scales.at[page].set(s)


# ---------------------------------------------------------------------------
# Speculative-decode verify: k+1 positions per paged step.
#
# The draft model proposes k tokens; the target model scores all k+1
# known positions (last accepted token + k drafts) in ONE dispatch.
# Bit-identity is preserved by construction: the appends below are the
# SAME per-token scatter the sequential path issues (in the same
# order), and each query position runs the SAME decode_attention
# reduction at its own ``col + j`` over the gathered pages — positions
# beyond a query's col are masked to NEG_INF exactly as a not-yet-
# written cache row would be, so query j's float sums cannot see
# drafts j+1..k. Rejected drafts need no KV rollback for the same
# reason: their rows sit beyond the new col, masked until the next
# window overwrites them.


def paged_append_tokens(pool: jax.Array, new: jax.Array,
                        block_tables: jax.Array, pos: jax.Array,
                        page_len: int,
                        limit: Optional[jax.Array] = None) -> jax.Array:
    """Scatter ``s`` consecutive decode positions' K (or V) rows.

    ``new`` is ``(b, s, kv, d)``; row ``i``'s position ``j`` lands
    where a sequential :func:`paged_append_token` at ``pos[i] + j``
    would put it. ``limit`` (``(b,)``, optional) is each stream's last
    fundable position: writes past it are routed to trash page 0
    (never read unmasked), so a speculative window near the end of a
    stream's funded pages can neither scribble on another stream's
    pages nor fall off its block-table row."""
    rows = jnp.arange(new.shape[0])
    width = block_tables.shape[1]
    for j in range(new.shape[1]):
        p = pos + j
        page = block_tables[rows, jnp.clip(p // page_len, 0, width - 1)]
        if limit is not None:
            page = jnp.where(p <= limit, page, 0)
        pool = pool.at[page, p % page_len].set(
            checked_pool_cast(pool, new[:, j]))
    return pool


def paged_verify_attention(q: jax.Array, k_pool: jax.Array,
                           v_pool: jax.Array,
                           block_tables: jax.Array,
                           col: jax.Array, *,
                           pad_offset: Optional[jax.Array] = None,
                           window: int = 0,
                           scale: Optional[float] = None,
                           max_pages: int = 0) -> jax.Array:
    """:func:`paged_decode_attention` for ``s`` query positions at
    once: ``q`` is ``(b, s, n_heads, d)`` and query ``j`` attends
    ``[0, col + j]``. Pages are gathered ONCE and each position runs
    the exact single-token reduction, so position ``j``'s output bits
    match a sequential single-token step at ``col + j`` — the
    speculative verify step inherits the serving bit-identity
    contract instead of re-proving it."""
    if max_pages and max_pages < block_tables.shape[1]:
        block_tables = block_tables[:, :max_pages]
    b, s = q.shape[0], q.shape[1]
    n_pages = block_tables.shape[1]
    page_len, kv, d = k_pool.shape[1], k_pool.shape[2], k_pool.shape[3]
    k = jnp.take(k_pool, block_tables, axis=0).reshape(
        b, n_pages * page_len, kv, d)
    v = jnp.take(v_pool, block_tables, axis=0).reshape(
        b, n_pages * page_len, kv, d)
    outs = [decode_attention(q[:, j:j + 1], k, v, col + j,
                             pad_offset=pad_offset, window=window,
                             scale=scale)
            for j in range(s)]
    return jnp.concatenate(outs, axis=1)


def quantized_paged_append_tokens(pool: jax.Array, scales: jax.Array,
                                  new: jax.Array,
                                  block_tables: jax.Array,
                                  pos: jax.Array, page_len: int,
                                  limit: Optional[jax.Array] = None,
                                  ) -> Tuple[jax.Array, jax.Array]:
    """:func:`paged_append_tokens` into an int8 pool: the ``s`` rows
    are appended SEQUENTIALLY through
    :func:`quantized_paged_append_token` (each append requantizes its
    page against the live rows, exactly as the one-token path would
    have), with past-``limit`` writes routed to trash page 0."""
    rows = jnp.arange(new.shape[0])
    width = block_tables.shape[1]
    for j in range(new.shape[1]):
        p = pos + j
        bt = block_tables.at[
            rows, jnp.clip(p // page_len, 0, width - 1)].get()
        if limit is not None:
            bt = jnp.where(p <= limit, bt, 0)
        # one-column table: quantized_paged_append_token indexes it
        # with p // page_len — rebuild a table whose hit column IS the
        # resolved page so the shared helper stays untouched
        pool, scales = quantized_paged_append_token(
            pool, scales, new[:, j],
            jnp.broadcast_to(bt[:, None], (bt.shape[0], 1)),
            p % page_len, page_len)
    return pool, scales


def quantized_paged_verify_attention(q: jax.Array, k_pool: jax.Array,
                                     k_scales: jax.Array,
                                     v_pool: jax.Array,
                                     v_scales: jax.Array,
                                     block_tables: jax.Array,
                                     col: jax.Array, *,
                                     pad_offset: Optional[jax.Array]
                                     = None,
                                     window: int = 0,
                                     scale: Optional[float] = None,
                                     max_pages: int = 0) -> jax.Array:
    """:func:`paged_verify_attention` over int8 pools — one fused
    dequant gather shared by all ``s`` query positions."""
    if max_pages and max_pages < block_tables.shape[1]:
        block_tables = block_tables[:, :max_pages]
    b, s = q.shape[0], q.shape[1]
    n_pages = block_tables.shape[1]
    page_len, kv, d = (k_pool.shape[1], k_pool.shape[2],
                       k_pool.shape[3])

    def gather(pool, pool_scales):
        pages = jnp.take(pool, block_tables, axis=0)
        sc = jnp.take(pool_scales, block_tables, axis=0)
        deq = pages.astype(jnp.float32) * sc[:, :, None, :, None]
        return deq.reshape(b, n_pages * page_len, kv, d)

    k = gather(k_pool, k_scales)
    v = gather(v_pool, v_scales)
    outs = [decode_attention(q[:, j:j + 1], k, v, col + j,
                             pad_offset=pad_offset, window=window,
                             scale=scale)
            for j in range(s)]
    return jnp.concatenate(outs, axis=1)


def quantized_paged_decode_attention(q: jax.Array, k_pool: jax.Array,
                                     k_scales: jax.Array,
                                     v_pool: jax.Array,
                                     v_scales: jax.Array,
                                     block_tables: jax.Array,
                                     col: jax.Array, *,
                                     pad_offset: Optional[jax.Array]
                                     = None,
                                     window: int = 0,
                                     scale: Optional[float] = None,
                                     max_pages: int = 0) -> jax.Array:
    """:func:`paged_decode_attention` over int8 pools with dequant
    fused into the bounded gather: pages and their scales are gathered
    together, multiplied out into the ``(b, width * page_len, kv, d)``
    float32 working set, and fed through the exact
    :func:`decode_attention` reduction. HBM traffic is the int8 pool
    read (+0.4% scales) — half the bf16 path's — and no pool-sized
    float copy ever exists."""
    if max_pages and max_pages < block_tables.shape[1]:
        block_tables = block_tables[:, :max_pages]
    b = block_tables.shape[0]
    n_pages = block_tables.shape[1]
    page_len, kv, d = (k_pool.shape[1], k_pool.shape[2],
                       k_pool.shape[3])

    def gather(pool, pool_scales):
        pages = jnp.take(pool, block_tables, axis=0)
        s = jnp.take(pool_scales, block_tables, axis=0)
        deq = pages.astype(jnp.float32) * s[:, :, None, :, None]
        return deq.reshape(b, n_pages * page_len, kv, d)

    return decode_attention(q, gather(k_pool, k_scales),
                            gather(v_pool, v_scales), col,
                            pad_offset=pad_offset, window=window,
                            scale=scale)
