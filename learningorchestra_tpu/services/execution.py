"""Binary execution service: Train / Tune / Evaluate / Predict.

One generic "call method X on stored object Y with kwargs Z" executor
backs four API verbs × two tools, exactly like the reference's
binary_executor_image (8 type strings, constants.py:41-51; POST body
``name``, ``modelName``, ``parentName``, ``description``, ``method``,
``methodParameters``, server.py:23-71).

Semantics preserved (binary_execution.py:118-189):
- validation walks the parent chain to the root model/* metadata to
  resolve the module+class whose methods are being called
  (utils.py:257-276);
- ``methodParameters`` go through the ``$``/``#``/``.`` DSL;
- train/tune results ARE the mutated instance itself
  (binary_execution.py:184-188); evaluate/predict store the returned
  value;
- PATCH re-runs a finished execution against its stored parent with
  new parameters (server.py:74-118);
- every run appends an execution document; failures record
  ``repr(exception)`` and leave ``finished`` False.

TPU-native: when the stored parent is a NeuralModel, ``fit`` /
``evaluate`` / ``predict`` dispatch into the mesh-sharded jit engine
(runtime/engine.py) — the accelerator lease is held for the duration
(jobs.py). sklearn parents run their real methods on host CPU.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from learningorchestra_tpu import analysis as A
from learningorchestra_tpu.catalog import documents as D
from learningorchestra_tpu.observability import trace as obs_trace
from learningorchestra_tpu.services import validators as V

NAME_FIELD = "name"
ANALYSIS_FIELD = "analysis"
MODEL_NAME_FIELD = "modelName"
PARENT_NAME_FIELD = "parentName"
DESCRIPTION_FIELD = "description"
METHOD_FIELD = "method"
METHOD_PARAMETERS_FIELD = "methodParameters"

# verbs whose result is the mutated parent instance
_INSTANCE_RESULT_PREFIXES = ("train/", "tune/")


class ExecutionService:
    def __init__(self, context):
        self._ctx = context
        self._validator = V.RequestValidator(context)

    # ------------------------------------------------------------------
    def root_model_metadata(self, name: str) -> Dict[str, Any]:
        """Walk the parentName chain until a model/* artifact — the
        root whose class defines the callable surface (reference
        utils.py:257-276)."""
        seen = set()
        meta = self._validator.existing(name)
        while not meta[D.TYPE_FIELD].startswith("model/"):
            parent = meta.get(D.PARENT_NAME_FIELD)
            if not parent or parent in seen:
                raise V.HttpError(
                    V.HTTP_NOT_ACCEPTABLE,
                    f"no model root in lineage of: {name}")
            seen.add(parent)
            meta = self._validator.existing(parent)
        return meta

    def _validate_method(self, root_meta: Dict[str, Any], method: str,
                         method_parameters: Dict[str, Any]) -> None:
        cls = self._validator.valid_class(
            root_meta[D.MODULE_PATH_FIELD], root_meta[D.CLASS_FIELD])
        if not isinstance(cls, type):
            # the root was created by a FACTORY (e.g.
            # tensorflow.keras.models.load_model on a SavedModel dir):
            # methods live on the returned instance's class, not the
            # factory — resolve it from the artifact's meta.json
            # (never deserializing weights on the request thread;
            # dill-stored foreign objects fall back to a full load)
            try:
                cls = self._ctx.artifacts.stored_class(
                    root_meta[D.NAME_FIELD], root_meta[D.TYPE_FIELD])
                if cls is None:
                    cls = self._ctx.artifacts.load(
                        root_meta[D.NAME_FIELD],
                        root_meta[D.TYPE_FIELD])
            except V.HttpError:
                raise
            except Exception as exc:  # noqa: BLE001 — a validation
                # failure must be a 406, not a request-thread 500
                raise V.HttpError(
                    V.HTTP_NOT_ACCEPTABLE,
                    f"cannot resolve stored model "
                    f"{root_meta[D.NAME_FIELD]!r}: {exc!r}") from exc
        self._validator.valid_method(cls, method)
        self._validator.valid_method_parameters(
            cls, method, method_parameters)

    # ------------------------------------------------------------------
    def create(self, body: Dict[str, Any], verb: str, tool: str,
               ) -> Tuple[int, Dict[str, Any]]:
        self._validator.required_fields(
            body, [NAME_FIELD, MODEL_NAME_FIELD, METHOD_FIELD,
                   METHOD_PARAMETERS_FIELD])
        name = self._validator.safe_name(body[NAME_FIELD])
        parent_name = body.get(PARENT_NAME_FIELD) or body[MODEL_NAME_FIELD]
        method = body[METHOD_FIELD]
        method_parameters = body[METHOD_PARAMETERS_FIELD] or {}
        description = body.get(DESCRIPTION_FIELD, "")
        timeout = V.valid_timeout(body.get(V.TIMEOUT_FIELD))
        slice_devices = V.valid_slice_devices(
            body.get(V.SLICE_DEVICES_FIELD))
        health_policy = V.valid_health_policy(
            body.get(V.HEALTH_POLICY_FIELD))
        # the trace (id == collection name) starts HERE, on the HTTP
        # thread: submit/validate/preflight spans precede the job
        # root span the worker thread opens later
        with obs_trace.span("submit", trace=name, verb=verb,
                            tool=tool):
            with obs_trace.span("validate"):
                self._validator.not_duplicate(name)
                self._validator.existing_finished(parent_name)
                root_meta = self.root_model_metadata(parent_name)
                self._validate_method(root_meta, method,
                                      method_parameters)
            with obs_trace.span("preflight"):
                analysis = self._preflight(root_meta, method,
                                           method_parameters)
                footprint = self._footprint(root_meta, method,
                                            method_parameters,
                                            slice_devices)
        type_string = D.normalize_type(f"{verb}/{tool}")
        extra = {
            D.PARENT_NAME_FIELD: parent_name,
            D.METHOD_FIELD: method,
            D.METHOD_PARAMETERS_FIELD: method_parameters,
            D.DESCRIPTION_FIELD: description,
        }
        if timeout is not None:
            # stored in metadata so boot/elastic requeues replay the
            # same deadline (server._requeue_execution)
            extra[V.TIMEOUT_FIELD] = timeout
        if health_policy is not None:
            # same boot-replay contract as timeout
            extra[V.HEALTH_POLICY_FIELD] = health_policy
        if analysis:
            extra[ANALYSIS_FIELD] = analysis
        if footprint:
            # the _id:0 record of what the scheduler was told — the
            # "why did my job wait" answer for polling clients
            extra[A.FOOTPRINT_FIELD] = footprint
        self._ctx.catalog.create_collection(name, type_string, extra)
        self._submit(name, type_string, parent_name, method,
                     method_parameters, description, timeout=timeout,
                     footprint=footprint, health_policy=health_policy)
        return V.HTTP_CREATED, {
            "result": f"/api/learningOrchestra/v1/{verb}/{tool}/{name}"}

    def update(self, name: str, body: Dict[str, Any], verb: str, tool: str,
               ) -> Tuple[int, Dict[str, Any]]:
        meta = self._validator.existing(name)
        method = meta[D.METHOD_FIELD]
        method_parameters = body.get(
            METHOD_PARAMETERS_FIELD, meta.get(D.METHOD_PARAMETERS_FIELD)) \
            or {}
        description = body.get(DESCRIPTION_FIELD, "")
        timeout = V.valid_timeout(
            body.get(V.TIMEOUT_FIELD, meta.get(V.TIMEOUT_FIELD)))
        stored_fp = meta.get(A.FOOTPRINT_FIELD) or {}
        # elastic bounds outlive the re-run: a PATCH without an
        # explicit sliceDevices keeps the stored {min, max}, not just
        # the (possibly resized) flat device count
        slice_devices = V.valid_slice_devices(
            body.get(V.SLICE_DEVICES_FIELD,
                     stored_fp.get("elastic") or stored_fp.get("devices")))
        health_policy = V.valid_health_policy(
            body.get(V.HEALTH_POLICY_FIELD,
                     meta.get(V.HEALTH_POLICY_FIELD)))
        parent_name = meta[D.PARENT_NAME_FIELD]
        root_meta = self.root_model_metadata(parent_name)
        self._validate_method(root_meta, method, method_parameters)
        analysis = self._preflight(root_meta, method, method_parameters)
        # re-seed the in-process calibration registry from the prior
        # run's durable measurement, so calibration survives restarts
        if getattr(self._ctx.config, "footprint_calibrate", False) \
                and meta.get("peakHbmBytes"):
            from learningorchestra_tpu.observability import \
                monitor as monitor_lib

            monitor_lib.record_peak(
                f"{root_meta.get(D.NAME_FIELD)}:{method}",
                int(meta["peakHbmBytes"]))
        footprint = self._footprint(root_meta, method, method_parameters,
                                    slice_devices)
        self._ctx.catalog.update_metadata(
            name, {D.METHOD_PARAMETERS_FIELD: method_parameters,
                   ANALYSIS_FIELD: analysis,
                   A.FOOTPRINT_FIELD: footprint,
                   V.TIMEOUT_FIELD: timeout,
                   V.HEALTH_POLICY_FIELD: health_policy,
                   D.FINISHED_FIELD: False})
        self._submit(name, meta[D.TYPE_FIELD], parent_name, method,
                     method_parameters, description, timeout=timeout,
                     footprint=footprint, health_policy=health_policy)
        return V.HTTP_SUCCESS, {
            "result": f"/api/learningOrchestra/v1/{verb}/{tool}/{name}"}

    def delete(self, name: str, verb: str, tool: str,
               ) -> Tuple[int, Dict[str, Any]]:
        import shutil

        meta = self._validator.existing(name)
        self._ctx.catalog.delete_collection(name)
        self._ctx.artifacts.delete(name, meta.get(D.TYPE_FIELD))
        # a stale checkpoint dir would make a future execution reusing
        # this name silently resume from the deleted run
        shutil.rmtree(checkpoint_dir_for(self._ctx, name),
                      ignore_errors=True)
        return V.HTTP_SUCCESS, {"result": f"deleted {name}"}

    # ------------------------------------------------------------------
    def _preflight(self, root_meta: Dict[str, Any], method: str,
                   method_parameters: Dict[str, Any]) -> list:
        """Static shape pre-flight + '#'-DSL lint BEFORE the job
        document exists: a provably-broken spec 406s here and leaves
        no ``finished: False`` orphan. Advisory findings come back for
        the job document."""
        if not self._ctx.config.preflight:
            return []
        findings = A.check_execution(
            self._ctx.catalog, root_meta, method, method_parameters,
            mode=self._ctx.config.sandbox_mode)
        return V.run_preflight(findings)

    def _footprint(self, root_meta: Dict[str, Any], method: str,
                   method_parameters: Dict[str, Any],
                   slice_devices: Optional[int],
                   ) -> Optional[Dict[str, Any]]:
        """The slice-scheduler footprint for this execution: the
        request's explicit ``sliceDevices`` merged over the preflight
        HBM estimate (eval_shape init + lowered-step memory_analysis,
        heuristic fallback). None = no claim; the scheduler
        gang-acquires the full mesh, which is always safe."""
        estimate = None
        if self._ctx.config.preflight:
            estimate = A.estimate_footprint(
                self._ctx.catalog, root_meta, method, method_parameters)
        footprint = dict(estimate) if estimate else {}
        self._calibrate(footprint, root_meta, method)
        if isinstance(slice_devices, dict):
            # elastic bounds: start at max (the job takes what it can
            # and shrinks under pressure — services/autoscaler.py)
            footprint["devices"] = int(slice_devices["max"])
            footprint["elastic"] = {"min": int(slice_devices["min"]),
                                    "max": int(slice_devices["max"])}
        elif slice_devices is not None:
            footprint["devices"] = slice_devices
        return footprint or None

    def _calibrate(self, footprint: Dict[str, Any],
                   root_meta: Dict[str, Any], method: str) -> None:
        """Closed-loop footprint calibration (docs/SCALING.md §7,
        LO_FOOTPRINT_CALIBRATE): when a prior execution of the same
        (model, method) recorded its measured peak HBM
        (``peakHbmBytes`` on the terminal metadata, mirrored into the
        in-process registry), prefer that — with LO_FOOTPRINT_MARGIN
        safety padding, clamped to one order of magnitude around the
        static estimate — over the eval-shape heuristic, which pads
        hardest exactly where it matters most (repeat sweeps of one
        architecture). Always stamps ``calibrationKey`` so the job
        layer knows where to record this run's measured peak."""
        from learningorchestra_tpu.observability import \
            monitor as monitor_lib

        cfg = self._ctx.config
        if not getattr(cfg, "footprint_calibrate", False):
            return
        key = f"{root_meta.get(D.NAME_FIELD)}:{method}"
        footprint["calibrationKey"] = key
        estimate = footprint.get("hbmBytes")
        measured = monitor_lib.measured_peak(key)
        if not measured or not estimate:
            return
        footprint["estimatedHbmBytes"] = int(estimate)
        footprint["hbmBytes"] = monitor_lib.calibrated_hbm_bytes(
            measured, int(estimate),
            float(getattr(cfg, "footprint_margin", 1.25)))
        footprint["estimator"] = "measured-peak"

    def _submit(self, name: str, type_string: str, parent_name: str,
                method: str, method_parameters: Dict[str, Any],
                description: str, only_if_idle: bool = False,
                timeout: Optional[float] = None,
                footprint: Optional[Dict[str, Any]] = None,
                health_policy: Optional[Any] = None) -> None:
        def run():
            _broadcast_to_workers(name, type_string, parent_name, method,
                                  method_parameters, health_policy)
            with obs_trace.span("dataLoad"):
                parent_type = self._ctx.params.artifact_type(
                    parent_name)
                instance = self._ctx.artifacts.load(parent_name,
                                                    parent_type)
                treated = self._ctx.params.treat(method_parameters)
            ckpt = _prepare_checkpointer(self._ctx, name, type_string,
                                         treated)
            _inject_epoch_log(self._ctx, name, instance, method, treated)
            _inject_health_policy(self._ctx, instance, method, treated,
                                  health_policy)
            try:
                result = getattr(instance, method)(**treated)
            finally:
                if ckpt is not None:
                    ckpt.close()  # flush async orbax writes
            if type_string.startswith(_INSTANCE_RESULT_PREFIXES):
                result = instance  # the fitted object is the artifact
            with obs_trace.span("artifactSave"):
                self._ctx.artifacts.save(result, name, type_string)
            _record_result_shapes(self._ctx, name, result)
            _record_sweep_fusion(self._ctx, name, result)
            summary = summarize_result(result)
            if summary is not None:
                self._ctx.catalog.append_document(name, {"result": summary})
            return result

        self._ctx.jobs.submit(
            name, run, description=description,
            parameters=method_parameters, needs_mesh=True,
            # the executor verb (train/tune/evaluate/predict) is the
            # fair-scheduling pool — per-service FAIR pool parity
            # (reference spark_image/fairscheduler.xml:1-8)
            pool=type_string.split("/", 1)[0],
            only_if_idle=only_if_idle,
            max_retries=self._ctx.config.job_max_retries,
            timeout=timeout, footprint=footprint)


def _record_result_shapes(ctx, name: str, result: Any) -> None:
    """Record the result's static array shapes on the metadata doc so
    later executions referencing ``$name``/``$name.key`` get shape
    pre-flight (analysis/preflight.py). Best-effort: shape metadata
    must never sink a finished job."""
    try:
        shapes = A.result_shapes(result)
        if shapes:
            ctx.catalog.update_metadata(
                name, {A.RESULT_SHAPES_FIELD: shapes})
    except Exception:  # noqa: BLE001
        pass


def _record_sweep_fusion(ctx, name: str, result: Any) -> None:
    """Record how much of a finished sweep the fusion planner claimed
    (``fusedTrials``/``cohorts``/``fallbackTrials``/``earlyStopped``)
    plus any isolated per-trial errors on the job's metadata doc.
    Best-effort, like shape metadata: never sinks a finished job."""
    try:
        updates: Dict[str, Any] = {}
        info = getattr(result, "fusion_info_", None)
        if info:
            updates["sweepFusion"] = dict(info)
        errors = getattr(result, "cv_results_", {}).get("error")
        if errors:
            updates["trialErrors"] = [e for e in errors if e]
        if updates:
            ctx.catalog.update_metadata(name, updates)
    except Exception:  # noqa: BLE001
        pass


def _inject_epoch_log(ctx, name: str, instance: Any, method: str,
                      treated: Dict[str, Any]) -> None:
    """Stream per-epoch training records (loss/accuracy/samplesPerSecond
    and the engine's roofline block — tflopsPerSecPerChip/mfu plus
    gbPerSecPerChip/arithmeticIntensity/hbmBwUtil/boundBy when bytes
    and peaks are known, observability/perf) into the execution's
    documents as they happen, when the target method takes a
    ``log_fn`` (our engine-backed fits do; sklearn methods don't). The
    reference's only perf instrumentation is Builder's post-hoc fitTime
    (builder_image/builder.py:117-122) — live epoch records through the
    universal GET reader are a strict superset."""
    import inspect

    if "log_fn" in treated:
        return
    try:
        params = inspect.signature(getattr(instance, method)).parameters
    except (TypeError, ValueError):
        return
    if "log_fn" not in params:
        return

    seen = {"n": 0}
    health = {"rollbacks": 0, "nonfiniteSteps": 0, "lossSpikes": 0,
              "events": []}

    def log_record(record: Dict[str, Any]) -> None:
        event = record.get("healthEvent")
        if event is not None:
            # sentinel events (runtime/health.py) bypass the throttle —
            # they are rare by construction (bounded by the rollback
            # budget) and the acceptance contract is their presence on
            # the job's metadata document
            health["events"].append(event)
            del health["events"][:-32]
            if "restoredStep" in event:
                health["rollbacks"] += 1
            if event.get("kind") == "spike":
                health["lossSpikes"] += 1
            else:
                health["nonfiniteSteps"] += max(
                    int(event.get("badSteps") or 0), 1)
            try:
                ctx.catalog.append_document(name, {"healthEvent": event})
                ctx.catalog.update_metadata(name, {
                    "rollbacks": health["rollbacks"],
                    "nonfiniteSteps": health["nonfiniteSteps"],
                    "lossSpikes": health["lossSpikes"],
                    "healthEvents": list(health["events"])})
            except Exception:  # noqa: BLE001 — must never sink a fit
                pass
            return
        # bounded stream: every epoch up to 512, then every 16th — a
        # 10k-epoch fit appends ~1.1k docs, not 10k (job-history DoS cap)
        i = seen["n"]
        seen["n"] = i + 1
        if i >= 512 and i % 16 != 0:
            return
        try:
            ctx.catalog.append_document(name, {"epochRecord": record})
        except Exception:  # noqa: BLE001 — logging must never sink a fit
            pass

    treated["log_fn"] = log_record


def _inject_health_policy(ctx, instance: Any, method: str,
                          treated: Dict[str, Any],
                          requested: Optional[Any]) -> None:
    """Arm the engine's training-health sentinel
    (docs/RELIABILITY.md) when the target method takes a
    ``health_policy`` kwarg (engine-backed fits do; sklearn methods
    don't): the request's validated ``healthPolicy`` field merged over
    the ``LO_HEALTH_*`` defaults. No-op when both are off."""
    import inspect

    if "health_policy" in treated:
        return
    try:
        params = inspect.signature(getattr(instance, method)).parameters
    except (TypeError, ValueError):
        return
    if "health_policy" not in params:
        return
    from learningorchestra_tpu.runtime import health as health_lib

    policy = health_lib.resolve_policy(requested, ctx.config)
    if policy is not None:
        treated["health_policy"] = policy


def checkpoint_dir_for(ctx, name: str) -> str:
    import os

    return os.path.join(ctx.config.checkpoints_dir, name)


def _prepare_checkpointer(ctx, name: str, type_string: str,
                          treated: Dict[str, Any]):
    """``"checkpoint": true`` in fit methodParameters enables per-epoch
    orbax checkpointing under the execution's name; a PATCH re-run of
    the same execution then resumes from the latest step (the engine
    restores before training — beyond the reference, whose failed jobs
    restart from scratch, README.md:194-198).

    Train executions only: a tune sweep runs many concurrent trial
    fits that would collide in one checkpoint manager (and restoring
    trial A's weights into trial B corrupts the sweep)."""
    enabled = treated.pop("checkpoint", False)
    if not type_string.startswith("train/") or not enabled:
        return None
    from learningorchestra_tpu.runtime.async_ckpt import \
        wrap_checkpointer
    from learningorchestra_tpu.runtime.checkpoint import Checkpointer

    # LO_CKPT_ASYNC=1 moves the commit (serialize+hash+fsync) off the
    # train thread onto a background worker; the engine barriers at
    # fit end and before any restore/rollback (docs/RELIABILITY.md)
    ckpt = wrap_checkpointer(Checkpointer(checkpoint_dir_for(ctx, name)),
                             config=ctx.config)
    treated["checkpointer"] = ckpt
    return ckpt


# ----------------------------------------------------------------------
# multi-host fan-out (SURVEY §7 hard part #5: one REST call -> N hosts)
# ----------------------------------------------------------------------
def _broadcast_to_workers(name: str, type_string: str, parent_name: str,
                          method: str,
                          method_parameters: Dict[str, Any],
                          health_policy: Optional[Any] = None) -> None:
    """On a multi-host pod the coordinator publishes every mesh job
    before entering it: the jitted train/eval/predict step runs over
    the GLOBAL mesh, whose collectives need all processes to execute
    the same program. Workers replay the identical method call from
    the shared artifact store (see :func:`replay_method_call`). The
    health policy rides along because sentinel instrumentation changes
    the traced program — a coordinator-only policy would diverge the
    SPMD replay."""
    import jax

    from learningorchestra_tpu.runtime import distributed as dist

    if jax.process_count() <= 1:
        return
    dist.HostBridge().publish({
        "op": "run",
        "target": "learningorchestra_tpu.services.execution:"
                  "replay_method_call",
        "kwargs": {"name": name, "type_string": type_string,
                   "parent_name": parent_name, "method": method,
                   "method_parameters": method_parameters,
                   "health_policy": health_policy}})


_worker_ctx = None


def replay_method_call(name: str, type_string: str, parent_name: str,
                       method: str,
                       method_parameters: Dict[str, Any],
                       health_policy: Optional[Any] = None) -> None:
    """Worker-side twin of the coordinator's pipeline: load the same
    artifact from the shared store, resolve the same parameters, call
    the same method — so every host participates in the global-mesh
    jit (including orbax checkpoint saves, which are collective).
    Catalog/artifact WRITES stay with the coordinator; the worker's
    copy of the result is discarded."""
    global _worker_ctx
    if _worker_ctx is None:
        from learningorchestra_tpu.services.context import ServiceContext

        _worker_ctx = ServiceContext()
    ctx = _worker_ctx
    parent_type = ctx.params.artifact_type(parent_name)
    instance = ctx.artifacts.load(parent_name, parent_type)
    treated = ctx.params.treat(method_parameters)
    ckpt = _prepare_checkpointer(ctx, name, type_string, treated)
    _inject_health_policy(ctx, instance, method, treated, health_policy)
    try:
        getattr(instance, method)(**treated)
    finally:
        if ckpt is not None:
            ckpt.close()


def summarize_result(result: Any) -> Optional[Any]:
    """A JSON-compatible view of an evaluate/predict result for the
    universal GET reader (the reference leaves results opaque in
    volumes; surfacing them in documents is a strict superset)."""
    import numpy as np

    if result is None or isinstance(result, (bool, int, float, str)):
        return result
    if isinstance(result, dict):
        return {str(k): summarize_result(v) for k, v in result.items()}
    if isinstance(result, (list, tuple)):
        if len(result) > 1000:
            return [summarize_result(v) for v in result[:1000]]
        return [summarize_result(v) for v in result]
    if isinstance(result, np.ndarray):
        flat = result.tolist()
        return flat[:1000] if isinstance(flat, list) and \
            len(flat) > 1000 else flat
    if hasattr(result, "history") and isinstance(
            getattr(result, "history"), (dict, list)):
        return summarize_result(result.history)
    return None
