#!/usr/bin/env bash
# CI gate: repo self-lint, the tier-1 test suite, then a chaos stage
# that re-runs the fault/lifecycle suites under an injecting
# environment (docs/LIFECYCLE.md).
#
# Usage: deploy/ci.sh            (from anywhere; paths are self-rooted)
# Env:   LO_CI_TIMEOUT        seconds for the tier-1 run (default 870)
#        LO_CI_CHAOS_TIMEOUT  seconds for the chaos stage (default 300)

set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO"

echo "== selflint =="
python scripts/selflint.py

echo "== tier-1 tests =="
TIMEOUT="${LO_CI_TIMEOUT:-870}"
timeout -k 10 "$TIMEOUT" env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== chaos: lifecycle under fault injection =="
# A bounded hang at the job_run site (reclaimed by deadlines/cancel)
# plus a slow artifact store. Tests that arm their own LO_FAULT_INJECT
# override this ambient spec; the point is that the lifecycle suites
# keep passing with chaos in the environment.
CHAOS_TIMEOUT="${LO_CI_CHAOS_TIMEOUT:-300}"
timeout -k 10 "$CHAOS_TIMEOUT" env JAX_PLATFORMS=cpu \
    LO_FAULT_INJECT="job_run:1:hang:0.2,artifact_save:1:latency:0.05" \
    python -m pytest tests/test_faults.py tests/test_lifecycle.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== ci: OK =="
