"""Device-mesh manager.

Axis-name conventions (scaling-book style), used consistently by the
parallelism library and every sharded engine:

==========  =====================================================
axis        meaning
==========  =====================================================
``dcn``     cross-slice data parallel (OUTERMOST axis; spans pod
            slices over the data-center network — only the gradient
            all-reduce crosses it, everything else stays in-slice)
``dp``      data parallel (batch dim; gradients all-reduced)
``fsdp``    fully-sharded data parallel (params sharded over it too)
``tp``      tensor parallel (weight matrices split; activations
            all-gathered / reduce-scattered by XLA)
``pp``      pipeline parallel (layer stages; shard_map + ppermute)
``sp``      sequence/context parallel (ring attention over seq dim)
``ep``      expert parallel (MoE experts)
==========  =====================================================

Multi-slice discipline (SURVEY §2.5; scaling-book): DCN bandwidth is
orders of magnitude below ICI, so ``dcn`` carries ONLY per-step
gradient all-reduces (weight-update cost, overlappable); params and
optimizer state replicate across slices and every tp/sp/ep/pp
collective stays inside a slice. ``build_mesh`` enforces dcn
outermost so device order maps slice boundaries to the dcn axis.

The reference has no device concept at all — its "cluster" is Docker
Swarm placement (SURVEY §2.4). Here the mesh is the cluster.
"""

from __future__ import annotations

import contextlib
import re
import threading
from typing import Dict, Iterator, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DCN, DP, FSDP, TP, PP, SP, EP = \
    "dcn", "dp", "fsdp", "tp", "pp", "sp", "ep"
KNOWN_AXES = (DCN, DP, FSDP, TP, PP, SP, EP)

# jax >= 0.5 exposes shard_map at top level and spells the
# replication-check toggle ``check_vma``; 0.4.x has it under
# jax.experimental as ``check_rep``. Alias here so callers stay
# version-agnostic (always pass ``check_vma``). On 0.4.x the vma type
# system backing the check does not exist (no ``lax.pcast`` to mark
# scan carries varying), so the static check is disabled outright —
# it never affects computed values.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map_04x

    def shard_map(f, **kwargs):
        kwargs.pop("check_vma", None)
        kwargs["check_rep"] = False
        return _shard_map_04x(f, **kwargs)

# vma ("varying mesh axes") helpers, identity/empty on 0.4.x where
# values inside shard_map carry no per-axis varying type
_pcast = getattr(jax.lax, "pcast", None)


def pcast(x, axis_name, to="varying"):
    if _pcast is None:
        return x
    return _pcast(x, axis_name, to=to)


def typeof(x):
    fn = getattr(jax, "typeof", None)
    if fn is not None:
        return fn(x)
    return jax.core.get_aval(x)


def parse_mesh_spec(spec: str) -> Dict[str, int]:
    """Parse ``"dp=2,tp=4"`` into an ordered axis->size dict."""
    out: Dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        m = re.fullmatch(r"([a-z_]+)\s*=\s*(-?\d+)", part)
        if not m:
            raise ValueError(f"bad mesh spec element: {part!r}")
        out[m.group(1)] = int(m.group(2))
    if not out:
        raise ValueError(f"empty mesh spec: {spec!r}")
    return out


def build_mesh(spec: str = "auto",
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the global mesh.

    ``"auto"`` = 1-D data-parallel over all devices. An explicit spec
    like ``"dp=2,tp=4"`` may leave one axis as ``-1`` to absorb the
    remaining devices.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    # AxisType.Auto = classic GSPMD propagation: we annotate inputs /
    # outputs, XLA infers internals and inserts collectives. (Newer
    # JAX defaults to Explicit, which demands out_shardings on every
    # ambiguous gather/scatter — wrong trade-off for a framework that
    # runs arbitrary user models.) JAX 0.4.x has no AxisType at all —
    # every mesh is GSPMD-auto there, so omitting the argument keeps
    # identical semantics.
    axis_type = getattr(jax.sharding, "AxisType", None)

    def make(shapes, names, devs):
        if axis_type is None:
            return jax.make_mesh(shapes, names, devices=devs)
        return jax.make_mesh(shapes, names,
                             (axis_type.Auto,) * len(names), devices=devs)

    if spec == "auto":
        return make((n,), (DP,), devices)
    sizes = parse_mesh_spec(spec)
    if DCN in sizes and next(iter(sizes)) != DCN:
        # slice-crossing traffic must map to the outermost axis, so
        # contiguous device blocks (slices, in a real multislice
        # topology) land on the inner in-slice axes
        raise ValueError(
            f"dcn must be the OUTERMOST (first) mesh axis: {spec!r}")
    unknown = [a for a, s in sizes.items() if s == -1]
    if len(unknown) > 1:
        raise ValueError("at most one -1 axis allowed")
    known = int(np.prod([s for s in sizes.values() if s != -1]))
    if unknown:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[unknown[0]] = n // known
    total = int(np.prod(list(sizes.values())))
    if total > n:
        raise ValueError(
            f"mesh {sizes} needs {total} devices, have {n}")
    # a mesh smaller than the host's device count is legal (e.g. a
    # sub-slice lease, or dp=1 debugging on a multi-chip host)
    return make(tuple(sizes.values()), tuple(sizes.keys()),
                devices[:total])


_default_mesh: Optional[Mesh] = None


def get_default_mesh() -> Mesh:
    """Process-wide mesh built from config (cached; the mesh is the
    cluster, and there is one per process)."""
    global _default_mesh
    if _default_mesh is None:
        from learningorchestra_tpu.config import get_config
        _default_mesh = build_mesh(get_config().mesh_shape)
    return _default_mesh


def reset_default_mesh() -> None:
    global _default_mesh
    _default_mesh = None


def slice_mesh(devices: Sequence[jax.Device],
               spec: str = "auto") -> Mesh:
    """First-class sub-mesh over an explicit device subset.

    Axis names follow the same convention as :func:`build_mesh`
    (``"auto"`` = 1-D ``dp``), so two slices over the SAME devices
    compare equal — engine executable-cache keys that embed the mesh
    stay stable across repeat grants of an identical slice.
    """
    return build_mesh(spec, devices=list(devices))


def sub_meshes(mesh: Mesh, k: int) -> list:
    """Split ``mesh`` into ``k`` disjoint equal 1-D dp sub-meshes
    (trailing remainder devices are left unused). The scheduler's
    slice allocator and the builder's per-family spatial multiplexing
    both cut the mesh this way, so contiguous blocks map to the same
    slices everywhere."""
    devices = list(np.asarray(mesh.devices).flat)
    k = max(1, min(k, len(devices)))
    per = len(devices) // k
    return [slice_mesh(devices[i * per:(i + 1) * per])
            for i in range(k)]


# -- per-job mesh override ------------------------------------------------
# The slice scheduler grants a job a device subset; the job's thread
# sees it through this thread-local so model code deep in the stack
# (estimators, neural, sweep) trains on the granted slice without
# threading a mesh through every signature. Absent an override,
# current_mesh() is exactly get_default_mesh().
_mesh_override = threading.local()


def current_mesh() -> Mesh:
    """The mesh THIS thread should compute on: the granted slice when
    running under ``use_mesh`` (scheduler slice grants), else the
    process-wide default mesh."""
    mesh = getattr(_mesh_override, "mesh", None)
    return mesh if mesh is not None else get_default_mesh()


@contextlib.contextmanager
def use_mesh(mesh: Optional[Mesh]) -> Iterator[Optional[Mesh]]:
    """Scope ``current_mesh()`` to ``mesh`` on this thread (None is a
    no-op, keeping the default-mesh fast path allocation-free)."""
    if mesh is None:
        yield None
        return
    previous = getattr(_mesh_override, "mesh", None)
    _mesh_override.mesh = mesh
    try:
        yield mesh
    finally:
        _mesh_override.mesh = previous


def set_current_mesh(mesh: Optional[Mesh]) -> Optional[Mesh]:
    """Swap this thread's mesh override IN PLACE and return the
    previous one. Live migration (services/migration.py) uses this to
    re-point a job that is already inside a ``use_mesh`` scope at its
    NEW slice; the enclosing context manager's finally still restores
    whatever preceded the scope, so the swap never leaks past the
    lease."""
    previous = getattr(_mesh_override, "mesh", None)
    _mesh_override.mesh = mesh
    return previous


def mesh_for_slice(device_indices: Optional[Sequence[int]]) -> Mesh:
    """Materialize a scheduler grant (indices into the default mesh's
    flat device order) as a mesh. ``None`` or a full-cover grant
    returns the default-mesh OBJECT itself so cache keys and ``is``
    checks treat full-mesh jobs exactly as before slicing existed."""
    base = get_default_mesh()
    if device_indices is None:
        return base
    devices = list(np.asarray(base.devices).flat)
    indices = sorted(int(i) for i in device_indices)
    if len(indices) >= len(devices):
        return base
    return slice_mesh([devices[i] for i in indices])


def mesh_fraction(mesh: Mesh) -> float:
    """``mesh``'s share of the default mesh (per-slice arena budgets);
    1.0 when the default mesh is unavailable or smaller."""
    try:
        base = get_default_mesh()
        return min(1.0, float(mesh.size) / max(1, int(base.size)))
    except Exception:  # noqa: BLE001 — no default mesh formed yet
        return 1.0


def data_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Axes the batch dimension is sharded over (dcn, dp and fsdp all
    shard data; dcn outermost so each slice holds a contiguous batch
    block and only gradients cross the slice boundary)."""
    return tuple(a for a in (DCN, DP, FSDP) if a in mesh.axis_names)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    axes = data_axes(mesh)
    return NamedSharding(mesh, P(axes if axes else None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_parallel_size(mesh: Mesh) -> int:
    size = 1
    for a in data_axes(mesh):
        size *= mesh.shape[a]
    return size


def pad_to_multiple(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple
