"""Model layer.

The reference trains whatever class the user names by module path —
``tensorflow.keras.applications.ResNet50``,
``sklearn.linear_model.LogisticRegression`` — via reflection
(model_image/model.py:133-162). Capability parity here:

- sklearn classes work as-is (CPU, in-process — same as reference);
- ``tensorflow.keras.*`` module paths resolve to :mod:`.tf_compat`, a
  keras-compatible API surface backed entirely by JAX/flax/optax and
  the mesh-sharded engine (real TensorFlow is not a dependency);
- :mod:`.neural` is the native API those shims produce — a
  config-serializable ``NeuralModel`` with compile/fit/evaluate/predict
  whose artifacts persist as JSON config + msgpack params (no pickles);
- :mod:`.sequential_module` is the flax implementation;
- :mod:`.resnet` / :mod:`.transformer` are the larger architectures.
"""

from learningorchestra_tpu.models.neural import NeuralModel  # noqa: F401
from learningorchestra_tpu.models.sweep import (  # noqa: F401
    GridSearch,
    RandomSearch,
)
from learningorchestra_tpu.models.transformer import (  # noqa: F401
    LanguageModel,
    TextClassifier,
    TransformerEncoder,
    TransformerLM,
)
