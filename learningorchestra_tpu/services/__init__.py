"""Service layer: the behavioral contract of the reference's 11
microservices, collapsed into one process (SURVEY §7: "One Python
framework — library + single REST server").

- ``context``   — shared wiring (catalog, artifacts, jobs, runtime)
- ``jobs``      — async job manager (validate → record metadata →
                  spawn → poll ``finished``; the reference's universal
                  execution model, binary_executor_image/server.py:65-71)
- ``params``    — the ``$``/``#``/``.`` parameter-resolution DSL
- ``validators``— request validation with reference status codes
- ``sandbox``   — restricted exec for ``#`` expressions / Function code
- per-service executors: dataset, model, binary (train/tune/evaluate/
  predict), dbexec (explore/transform), histogram, projection,
  datatype, function, builder
- ``server``    — the REST front end with the krakend.json URI contract
"""

from learningorchestra_tpu.services.context import ServiceContext  # noqa: F401
