"""Keras layer shims: lightweight descriptors consumed by Sequential
(each carries a JSON layer config for
models/sequential_module.py)."""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence


class Layer:
    def __init__(self, config: Dict[str, Any]):
        self.config = config


def _pair(v):
    if v is None:
        return None
    if isinstance(v, int):
        return [v, v]
    return list(v)


class Dense(Layer):
    def __init__(self, units: int, activation: Optional[str] = None,
                 input_shape: Optional[Sequence[int]] = None, **_: Any):
        super().__init__({"kind": "dense", "units": int(units),
                          "activation": activation})
        self.input_shape = list(input_shape) if input_shape else None


class Conv2D(Layer):
    def __init__(self, filters: int, kernel_size=3, strides=1,
                 padding: str = "valid", activation: Optional[str] = None,
                 input_shape: Optional[Sequence[int]] = None, **_: Any):
        super().__init__({
            "kind": "conv2d", "filters": int(filters),
            "kernel": _pair(kernel_size), "strides": _pair(strides),
            "padding": padding.upper(), "activation": activation})
        self.input_shape = list(input_shape) if input_shape else None


class Conv1D(Layer):
    def __init__(self, filters: int, kernel_size=3, strides=1,
                 padding: str = "valid", activation: Optional[str] = None,
                 input_shape: Optional[Sequence[int]] = None, **_: Any):
        super().__init__({
            "kind": "conv1d", "filters": int(filters),
            "kernel": int(kernel_size) if not isinstance(
                kernel_size, (list, tuple)) else int(kernel_size[0]),
            "strides": int(strides) if not isinstance(
                strides, (list, tuple)) else int(strides[0]),
            "padding": padding.upper(), "activation": activation})
        self.input_shape = list(input_shape) if input_shape else None


class MaxPooling1D(Layer):
    def __init__(self, pool_size=2, strides=None, **_: Any):
        super().__init__({"kind": "maxpool1d", "pool": int(pool_size),
                          "strides": int(strides or pool_size)})


class MaxPooling2D(Layer):
    def __init__(self, pool_size=2, strides=None, **_: Any):
        super().__init__({"kind": "maxpool2d", "pool": _pair(pool_size),
                          "strides": _pair(strides) or _pair(pool_size)})


class AveragePooling2D(Layer):
    def __init__(self, pool_size=2, strides=None, **_: Any):
        super().__init__({"kind": "avgpool2d", "pool": _pair(pool_size),
                          "strides": _pair(strides) or _pair(pool_size)})


class GlobalAveragePooling2D(Layer):
    def __init__(self, **_: Any):
        super().__init__({"kind": "globalavgpool2d"})


class GlobalAveragePooling1D(Layer):
    def __init__(self, **_: Any):
        super().__init__({"kind": "globalavgpool1d"})


class GlobalMaxPooling1D(Layer):
    def __init__(self, **_: Any):
        super().__init__({"kind": "globalmaxpool1d"})


class GlobalMaxPooling2D(Layer):
    def __init__(self, **_: Any):
        super().__init__({"kind": "globalmaxpool2d"})


class Conv2DTranspose(Layer):
    def __init__(self, filters: int, kernel_size=3, strides=1,
                 padding: str = "valid", activation: Optional[str] = None,
                 input_shape: Optional[Sequence[int]] = None, **_: Any):
        super().__init__({
            "kind": "conv2d_transpose", "filters": int(filters),
            "kernel": _pair(kernel_size), "strides": _pair(strides),
            "padding": padding.upper(), "activation": activation})
        self.input_shape = list(input_shape) if input_shape else None


class Flatten(Layer):
    def __init__(self, **_: Any):
        super().__init__({"kind": "flatten"})


class Reshape(Layer):
    def __init__(self, target_shape, **_: Any):
        super().__init__({"kind": "reshape", "shape": list(target_shape)})


class Dropout(Layer):
    def __init__(self, rate: float, **_: Any):
        super().__init__({"kind": "dropout", "rate": float(rate)})


class BatchNormalization(Layer):
    def __init__(self, momentum: float = 0.99, epsilon: float = 1e-3,
                 **_: Any):
        super().__init__({"kind": "batchnorm", "momentum": momentum,
                          "epsilon": epsilon})


class LayerNormalization(Layer):
    def __init__(self, epsilon: float = 1e-3, **_: Any):
        # keras's default epsilon is 1e-3 (flax's is 1e-6) — carry it
        # in the config so imported models normalize identically
        super().__init__({"kind": "layernorm",
                          "epsilon": float(epsilon)})


class Embedding(Layer):
    def __init__(self, input_dim: int, output_dim: int, **_: Any):
        super().__init__({"kind": "embedding", "vocab": int(input_dim),
                          "dim": int(output_dim)})


def _require_default_gates(kind: str, activation: str,
                           recurrent_activation: str) -> None:
    """flax {OptimizedLSTM,GRU}Cell hard-code tanh/sigmoid gates;
    fail loudly instead of silently computing different math."""
    if activation != "tanh" or recurrent_activation != "sigmoid":
        raise ValueError(
            f"{kind}: only activation='tanh' with recurrent_activation="
            f"'sigmoid' is supported (got {activation!r}/"
            f"{recurrent_activation!r})")


class LSTM(Layer):
    def __init__(self, units: int, return_sequences: bool = False,
                 activation: str = "tanh",
                 recurrent_activation: str = "sigmoid", **_: Any):
        _require_default_gates("LSTM", activation, recurrent_activation)
        super().__init__({"kind": "lstm", "units": int(units),
                          "return_sequences": bool(return_sequences)})


class GRU(Layer):
    def __init__(self, units: int, return_sequences: bool = False,
                 activation: str = "tanh",
                 recurrent_activation: str = "sigmoid", **_: Any):
        _require_default_gates("GRU", activation, recurrent_activation)
        super().__init__({"kind": "gru", "units": int(units),
                          "return_sequences": bool(return_sequences)})


class SimpleRNN(Layer):
    def __init__(self, units: int, return_sequences: bool = False,
                 activation: str = "tanh", **_: Any):
        super().__init__({"kind": "simple_rnn", "units": int(units),
                          "activation": activation,
                          "return_sequences": bool(return_sequences)})


class Bidirectional(Layer):
    """``Bidirectional(LSTM(n))`` — wraps an LSTM/GRU shim."""

    def __init__(self, layer: Layer, **_: Any):
        inner = dict(layer.config)
        if inner["kind"] not in ("lstm", "gru"):
            raise ValueError("Bidirectional supports LSTM/GRU only")
        super().__init__({"kind": f"bidirectional_{inner['kind']}",
                          "units": inner["units"],
                          "return_sequences": inner["return_sequences"]})


class Activation(Layer):
    def __init__(self, activation: str, **_: Any):
        super().__init__({"kind": "activation", "fn": activation})


class ReLU(Layer):
    def __init__(self, **_: Any):
        super().__init__({"kind": "activation", "fn": "relu"})


class InputLayer(Layer):
    def __init__(self, input_shape=None, shape=None, **_: Any):
        super().__init__({"kind": "input"})
        self.input_shape = list(input_shape or shape or [])


class Input(InputLayer):
    pass
