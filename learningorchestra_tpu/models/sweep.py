"""Hyperparameter sweeps over mesh sub-slices.

The reference's Tune service is ``GridSearchCV.fit`` running on one
host through the generic executor (SURVEY §3.3; constants.py:41-51
``tune/*`` type strings). That path still works here for sklearn
estimators. This module is the TPU-native counterpart for JAX models:
trials are scheduled onto **disjoint sub-slices of the device mesh**
and run concurrently — JAX dispatches jitted computations on disjoint
devices asynchronously, so k sub-slices give k-way trial parallelism
over ICI where the reference used Spark workers (SURVEY §2.4,
BASELINE north star).

The surface is GridSearchCV-shaped on purpose (``fit``,
``best_params_``, ``best_score_``, ``cv_results_``) because those
names are what reference clients send through the REST method-call
contract.

Sweep fusion (docs/PERFORMANCE.md "Sweep fusion"): before dispatching
trials, a planner partitions the grid into cohorts whose points share
everything that changes the traced program (architecture, optimizer
kind, batch_size, epochs) and differ only in vmappable optimizer
scalars (learning rate, decay, momentum, betas). Each cohort trains as
ONE compiled vmapped program over a config axis — ~1 compile and ~1
job slot for the whole cohort — while the residual falls back
unchanged to the slice-parallel trial path above. ``LO_SWEEP_FUSION=0``
disables the planner entirely.
"""

from __future__ import annotations

import itertools
import json
import os
import random as random_mod
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from learningorchestra_tpu.observability import trace as obs_trace
from learningorchestra_tpu.runtime import mesh as mesh_lib
from learningorchestra_tpu.runtime import locks

# hyperparameter names routed into the optimizer spec
_OPTIMIZER_KEYS = {"kind", "learning_rate", "lr", "momentum",
                   "weight_decay", "beta_1", "beta_2", "rho", "nesterov"}
# names routed into fit() kwargs
_FIT_KEYS = {"batch_size", "epochs"}


# process-wide fusion counters, exported as lo_sweep_* gauges by the
# /metrics endpoint (services/server.py)
_FUSION_LOCK = locks.make_lock("sweep.fusion")
_FUSION_STATS = {"fusedTrials": 0, "cohorts": 0, "fallbackTrials": 0,
                 "earlyStopped": 0, "trialErrors": 0}


def _fusion_count(**deltas: int) -> None:
    with _FUSION_LOCK:
        for k, v in deltas.items():
            _FUSION_STATS[k] = _FUSION_STATS.get(k, 0) + v


def fusion_stats() -> Dict[str, int]:
    with _FUSION_LOCK:
        out = dict(_FUSION_STATS)
    from learningorchestra_tpu.runtime import engine as engine_lib

    out["fusedEpochTraces"] = engine_lib.fused_epoch_traces()
    return out


def _clone(estimator):
    """Config-level clone through the artifact save/load protocol —
    fresh params, fresh engine, no shared state with the original."""
    with tempfile.TemporaryDirectory(prefix="lo_sweep_clone_") as tmp:
        estimator.__lo_save__(tmp)
        clone = type(estimator).__lo_load__(tmp)
    clone.params = None  # sweep trials train from scratch
    return clone


def _apply_overrides(model, overrides: Dict[str, Any]) -> Dict[str, Any]:
    """Route hyperparameters to optimizer spec / fit kwargs / model
    attributes. Returns the fit kwargs."""
    fit_kwargs: Dict[str, Any] = {}
    for key, value in overrides.items():
        if key in _FIT_KEYS:
            fit_kwargs[key] = value
        elif key in _OPTIMIZER_KEYS:
            if key == "lr":
                key = "learning_rate"
            model.optimizer_spec[key] = value
        elif key == "optimizer":
            model.optimizer_spec["kind"] = value
        elif hasattr(model, key):
            setattr(model, key, value)
        else:
            raise ValueError(
                f"unknown hyperparameter {key!r} for "
                f"{type(model).__name__}")
    model._engine = None  # spec changes must rebuild the engine
    return fit_kwargs


class GridSearch:
    """Exhaustive (or sampled) hyperparameter search for the
    framework's keras-shaped models, trial-parallel over the mesh.

    Parameters
    ----------
    estimator:
        A NeuralModel / LanguageModel (typically a ``$model`` artifact
        reference through the parameter DSL).
    param_grid:
        dict of name -> list of candidate values. Names route to the
        optimizer spec (``learning_rate``, ``optimizer``, ...), fit
        kwargs (``batch_size``, ``epochs``), or model attributes
        (``dropout``, ``seed``, ...).
    n_iter:
        If set, sample this many random combinations instead of the
        full grid (random search).
    scoring:
        Metric name from evaluate() to maximize; ``"loss"`` is
        minimized. Default: accuracy if the model reports it.
    validation_split:
        Tail fraction of the data held out for scoring each trial.
    max_parallel:
        Cap on concurrent trials (default: one per mesh device).
    refit:
        Retrain the best config on the full data into
        ``best_estimator_`` (default True).
    """

    def __init__(self, estimator, param_grid: Dict[str, Sequence[Any]],
                 n_iter: Optional[int] = None, scoring: str = "auto",
                 validation_split: float = 0.2,
                 max_parallel: Optional[int] = None, refit: bool = True,
                 seed: int = 0, name: str = "grid_search"):
        if not param_grid:
            raise ValueError("param_grid must not be empty")
        self.name = name
        self.estimator = estimator
        self.param_grid = {k: list(v) for k, v in param_grid.items()}
        self.n_iter = n_iter
        self.scoring = scoring
        self.validation_split = float(validation_split)
        self.max_parallel = max_parallel
        self.refit = refit
        self.seed = int(seed)
        self.cv_results_: Dict[str, List[Any]] = {}
        self.best_params_: Optional[Dict[str, Any]] = None
        self.best_score_: Optional[float] = None
        self.best_estimator_ = None
        # filled by fit(): how much of the sweep the fusion planner
        # claimed (job metadata surfaces this as "sweepFusion")
        self.fusion_info_: Dict[str, int] = {}

    # ------------------------------------------------------------------
    def _combinations(self) -> List[Dict[str, Any]]:
        keys = sorted(self.param_grid)
        combos = [dict(zip(keys, values)) for values in
                  itertools.product(*(self.param_grid[k] for k in keys))]
        if self.n_iter is not None and self.n_iter < len(combos):
            rng = random_mod.Random(self.seed)
            combos = rng.sample(combos, self.n_iter)
        return combos

    def _split(self, x, y):
        x = np.asarray(x)
        n = len(x)
        n_val = max(1, int(n * self.validation_split)) \
            if self.validation_split > 0 else 0
        if n_val == 0 or n_val >= n:
            return x, y, x, y  # degenerate: score on train data
        tx, vx = x[:-n_val], x[-n_val:]
        if y is None:
            return tx, None, vx, None
        y = np.asarray(y)
        return tx, y[:-n_val], vx, y[-n_val:]

    @staticmethod
    def _run_trials_preemptibly(run_trial, combos, k: int) -> List[Any]:
        """Run trials over the sub-slice worker pool, yielding the
        mesh lease to waiting jobs of other pools at TRIAL boundaries:
        when contention appears, stop dispatching, let in-flight
        trials drain, hand the lease over (preempt.maybe_yield), then
        resume. Without this a long sweep holds the whole mesh for its
        entire duration (round-4 verdict weak #6); with it a train
        submitted mid-sweep interleaves. Runs on the lease-holding
        thread — only it may yield."""
        from concurrent.futures import FIRST_COMPLETED, wait

        from learningorchestra_tpu.runtime import preempt

        pending = list(enumerate(combos))
        in_flight: Dict[Any, int] = {}
        results: Dict[int, Any] = {}
        just_resumed = False
        with ThreadPoolExecutor(max_workers=k) as pool:
            while pending or in_flight:
                # one full dispatch wave is GUARANTEED after each
                # yield: re-checking contention before dispatching
                # anything would livelock under a steady stream of
                # other-pool jobs (re-acquire, see the next waiter,
                # re-yield with zero trials run, forever)
                draining = not just_resumed and preempt.contended()
                while pending and len(in_flight) < k and not draining:
                    idx, combo = pending.pop(0)
                    in_flight[pool.submit(run_trial, combo)] = idx
                just_resumed = False
                if not in_flight:
                    # fully drained under contention: hand over the
                    # lease, re-acquire through the fair queue, refill
                    preempt.maybe_yield()
                    just_resumed = True
                    continue
                done, _ = wait(in_flight, return_when=FIRST_COMPLETED)
                for future in done:
                    results[in_flight.pop(future)] = future.result()
        return [results[i] for i in range(len(combos))]

    def _score(self, metrics: Dict[str, float]) -> float:
        if self.scoring == "auto":
            if "accuracy" in metrics:
                return float(metrics["accuracy"])
            return -float(metrics["loss"])
        if self.scoring == "loss":
            return -float(metrics["loss"])
        if self.scoring not in metrics:
            raise ValueError(
                f"scoring metric {self.scoring!r} not reported by the "
                f"estimator; available: {sorted(metrics)}")
        return float(metrics[self.scoring])

    # ------------------------------------------------------------------
    # fusion planner (docs/PERFORMANCE.md "Sweep fusion")
    # ------------------------------------------------------------------
    def _plan_cohorts(self, combos: List[Dict[str, Any]]
                      ) -> Tuple[List[Dict[str, Any]], List[int]]:
        """Partition ``combos`` into fusable cohorts + residual
        indices. A cohort's points share every program-shaping entry
        (architecture, optimizer kind, batch_size/epochs, attribute
        overrides) and differ only in the optimizer scalars the
        estimator declares vmappable for its kind; groups of one stay
        residual (nothing to fuse)."""
        from learningorchestra_tpu.models import neural as neural_lib

        est = self.estimator
        supports = getattr(est, "supports_sweep_fusion", None)
        if supports is None or not supports():
            return [], list(range(len(combos)))
        spec = getattr(est, "optimizer_spec", None) or {}
        base_kind = str(spec.get("kind", "adam")).lower()
        groups: Dict[Any, List[Tuple[int, Dict[str, float],
                                     Dict[str, Any]]]] = {}
        residual: List[int] = []
        for i, combo in enumerate(combos):
            kind = str(combo.get("optimizer",
                                 combo.get("kind", base_kind))).lower()
            allowed = set(neural_lib._FUSABLE_BY_KIND.get(kind, ()))
            hyper: Dict[str, float] = {}
            shared: Dict[str, Any] = {}
            for k, v in combo.items():
                nk = "learning_rate" if k == "lr" else k
                if nk in allowed and isinstance(v, (int, float)) \
                        and not isinstance(v, bool):
                    hyper[nk] = float(v)
                else:
                    shared[k] = v
            if not hyper:
                residual.append(i)
                continue
            key = (tuple(sorted((k, repr(v)) for k, v in shared.items())),
                   tuple(sorted(hyper)))
            groups.setdefault(key, []).append((i, hyper, shared))
        cohorts = []
        for members in groups.values():
            if len(members) < 2:
                residual.extend(i for i, _, _ in members)
                continue
            cohorts.append({"indices": [i for i, _, _ in members],
                            "hyper": [h for _, h, _ in members],
                            "shared": dict(members[0][2])})
        residual.sort()
        return cohorts, residual

    def _run_fused_cohort(self, cohort: Dict[str, Any],
                          combos: List[Dict[str, Any]], tx, ty, vx, vy,
                          fit_kwargs: Dict[str, Any], mesh
                          ) -> Tuple[List[Dict[str, Any]], List[Any]]:
        from learningorchestra_tpu.config import get_config

        model = _clone(self.estimator)
        model.set_mesh(mesh)
        trial_kwargs = dict(fit_kwargs)
        trial_kwargs.update(
            _apply_overrides(model, dict(cohort["shared"])))
        cfg = get_config()
        earlystop = None
        if cfg.sweep_earlystop_margin > 0:
            earlystop = {"margin": cfg.sweep_earlystop_margin,
                         "min_epochs": cfg.sweep_earlystop_min_epochs,
                         "alpha": cfg.sweep_earlystop_alpha}
        t0 = time.perf_counter()
        per_config, stopped = model.fit_sweep_fused(
            tx, ty, cohort["hyper"],
            batch_size=trial_kwargs.get("batch_size"),
            epochs=trial_kwargs.get("epochs", 1),
            validation_data=(vx, vy),
            shuffle=trial_kwargs.get("shuffle", True),
            score_fn=self._score, earlystop=earlystop)
        # one program trained the whole cohort: amortize its wall-clock
        # evenly so mean_fit_time stays comparable across paths
        dt = (time.perf_counter() - t0) / max(1, len(per_config))
        results = []
        for idx, metrics in zip(cohort["indices"], per_config):
            results.append({"params": combos[idx], "metrics": metrics,
                            "score": self._score(metrics),
                            "fit_time": round(dt, 4)})
        return results, stopped

    # ------------------------------------------------------------------
    def fit(self, x=None, y=None, **fit_kwargs) -> "GridSearch":
        import queue as queue_mod

        import jax

        from learningorchestra_tpu.config import get_config
        from learningorchestra_tpu.runtime import preempt

        combos = self._combinations()
        tx, ty, vx, vy = self._split(x, y)
        # current_mesh: a sweep running under a scheduler slice grant
        # cuts ITS slice into trial sub-slices, not the whole mesh
        mesh = mesh_lib.current_mesh()
        self.fusion_info_ = {"fusedTrials": 0, "cohorts": 0,
                             "fallbackTrials": 0, "earlyStopped": 0}
        results: List[Optional[Dict[str, Any]]] = [None] * len(combos)
        residual_idx = list(range(len(combos)))
        # Fusion is single-host only: the multi-host fan-out replays
        # this fit on every host and the residual path already
        # serializes there; a fused cohort would be fine numerically
        # but buys nothing over the per-host sequential trials.
        if get_config().sweep_fusion and jax.process_count() == 1:
            cohorts, residual_idx = self._plan_cohorts(combos)
            for cohort in cohorts:
                try:
                    with obs_trace.span(
                            "fusedCohort",
                            points=len(cohort["indices"]),
                            hyper=sorted(cohort["hyper"][0])):
                        cohort_results, stopped = \
                            self._run_fused_cohort(
                                cohort, combos, tx, ty, vx, vy,
                                fit_kwargs, mesh)
                except preempt.JobCancelled:
                    raise
                except Exception:
                    # any fused failure (scan budget exceeded, odd
                    # spec, device error) reverts the cohort to
                    # independent trials — fusion is an optimization,
                    # never a behavior change
                    residual_idx.extend(cohort["indices"])
                    _fusion_count(
                        fallbackTrials=len(cohort["indices"]))
                    self.fusion_info_["fallbackTrials"] += \
                        len(cohort["indices"])
                    continue
                for idx, res in zip(cohort["indices"], cohort_results):
                    results[idx] = res
                n_stopped = sum(1 for s in stopped if s is not None)
                self.fusion_info_["fusedTrials"] += \
                    len(cohort["indices"])
                self.fusion_info_["cohorts"] += 1
                self.fusion_info_["earlyStopped"] += n_stopped
                _fusion_count(fusedTrials=len(cohort["indices"]),
                              cohorts=1, earlyStopped=n_stopped)
            residual_idx.sort()
        residual = [combos[i] for i in residual_idx]
        if residual:
            if jax.process_count() > 1:
                # multi-host: every host replays this fit
                # (execution.py fan-out) and must execute identical
                # programs in identical order — sub-slice thread
                # scheduling is timing-dependent and a sub-slice may
                # own no local devices, so trials run sequentially
                # over the full global mesh instead
                k = 1
                slices = [mesh]
            else:
                k = min(len(residual), self.max_parallel or mesh.size)
                slices = mesh_lib.sub_meshes(mesh, k)
                k = min(k, len(slices))  # never more workers than slices
            # free pool, not idx % k: a fast trial returns its slice
            # for the next combo instead of contending with a slow
            # neighbour
            free = queue_mod.Queue()
            for s in slices:
                free.put(s)
            # trials may run on pool threads with an empty span stack,
            # so anchor unfused-trial spans to the sweep's open span
            # here and add them retroactively per trial
            sweep_anchor = obs_trace.current()

            def run_trial(combo):
                from learningorchestra_tpu.services import faults

                model = _clone(self.estimator)
                sub = free.get()
                t0 = time.perf_counter()
                mono0 = time.monotonic()
                try:
                    faults.maybe_inject("sweep_trial")
                    model.set_mesh(sub)
                    trial_kwargs = dict(fit_kwargs)
                    trial_kwargs.update(_apply_overrides(model, combo))
                    if ty is None:
                        model.fit(tx, **trial_kwargs)
                        metrics = model.evaluate(
                            vx,
                            batch_size=trial_kwargs.get("batch_size"))
                    else:
                        model.fit(tx, ty, **trial_kwargs)
                        metrics = model.evaluate(
                            vx, vy,
                            batch_size=trial_kwargs.get("batch_size"))
                    return {"params": combo, "metrics": metrics,
                            "score": self._score(metrics),
                            "fit_time":
                                round(time.perf_counter() - t0, 4)}
                except preempt.JobCancelled:
                    raise
                except Exception as exc:
                    # trial fault isolation: one bad point must not
                    # abort the sweep — record it and keep searching;
                    # the raw exception rides along so an all-failed
                    # sweep can re-raise the real cause
                    _fusion_count(trialErrors=1)
                    return {"params": combo, "metrics": {},
                            "score": float("-inf"),
                            "fit_time":
                                round(time.perf_counter() - t0, 4),
                            "error": f"{type(exc).__name__}: {exc}",
                            "_exc": exc}
                finally:
                    if sweep_anchor is not None:
                        obs_trace.add(
                            "trial", sweep_anchor[0], mono0,
                            time.monotonic(), parent=sweep_anchor[1],
                            params={k: v for k, v in combo.items()
                                    if isinstance(v, (int, float, str))})
                    free.put(sub)

            if k > 1:
                res_list = self._run_trials_preemptibly(
                    run_trial, residual, k)
            else:
                # sequential trials run on THIS thread, so the
                # engine's per-epoch preempt hook fires naturally
                # inside each fit
                res_list = [run_trial(c) for c in residual]
            for i, r in zip(residual_idx, res_list):
                results[i] = r

        failed = [r for r in results if "error" in r]
        ok = [r for r in results if "error" not in r]
        if not ok:
            first = failed[0].get("_exc")
            if first is not None:
                raise first
            raise RuntimeError(
                f"all {len(results)} sweep trials failed; first: "
                f"{failed[0]['error']}")
        for r in failed:
            r.pop("_exc", None)
        self.cv_results_ = {
            "params": [r["params"] for r in results],
            "mean_test_score": [r["score"] for r in results],
            "mean_fit_time": [r["fit_time"] for r in results],
            "metrics": [r["metrics"] for r in results],
        }
        if failed:
            self.cv_results_["error"] = [r.get("error")
                                         for r in results]
        best = max(ok, key=lambda r: r["score"])
        self.best_params_ = best["params"]
        self.best_score_ = best["score"]
        if self.refit:
            model = _clone(self.estimator)
            refit_kwargs = dict(fit_kwargs)
            refit_kwargs.update(_apply_overrides(model,
                                                 dict(best["params"])))
            if y is None:
                model.fit(x, **refit_kwargs)
            else:
                model.fit(x, y, **refit_kwargs)
            self.best_estimator_ = model
        return self

    # keras-ish conveniences so tune results flow through the generic
    # summarize/evaluate/predict REST verbs
    def evaluate(self, x=None, y=None, **kwargs) -> Dict[str, float]:
        self._require_fitted()
        return self.best_estimator_.evaluate(x, y, **kwargs)

    def predict(self, x=None, **kwargs):
        self._require_fitted()
        return self.best_estimator_.predict(x, **kwargs)

    def _require_fitted(self) -> None:
        if self.best_estimator_ is None:
            raise RuntimeError(
                "sweep has no refit model — call fit() first "
                "(with refit=True)")

    def summary(self) -> Dict[str, Any]:
        return {"best_params": self.best_params_,
                "best_score": self.best_score_,
                "n_trials": len(self.cv_results_.get("params", []))}

    # ------------------------------------------------------------------
    # artifact-store native protocol (catalog/artifacts.py)
    # ------------------------------------------------------------------
    def __lo_save__(self, path: str) -> None:
        est_dir = os.path.join(path, "estimator")
        os.makedirs(est_dir, exist_ok=True)
        self.estimator.__lo_save__(est_dir)
        best_dir = None
        if self.best_estimator_ is not None:
            best_dir = os.path.join(path, "best_estimator")
            os.makedirs(best_dir, exist_ok=True)
            self.best_estimator_.__lo_save__(best_dir)
        config = {
            "name": self.name,
            "estimator_class": type(self.estimator).__name__,
            "param_grid": self.param_grid,
            "n_iter": self.n_iter,
            "scoring": self.scoring,
            "validation_split": self.validation_split,
            "max_parallel": self.max_parallel,
            "refit": self.refit,
            "seed": self.seed,
            "cv_results": self.cv_results_,
            "best_params": self.best_params_,
            "best_score": self.best_score_,
            "has_best": best_dir is not None,
        }
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump(config, f)

    @classmethod
    def __lo_load__(cls, path: str) -> "GridSearch":
        from learningorchestra_tpu import models as models_pkg

        with open(os.path.join(path, "config.json")) as f:
            config = json.load(f)
        est_cls = getattr(models_pkg, config["estimator_class"])
        estimator = est_cls.__lo_load__(os.path.join(path, "estimator"))
        sweep = cls(estimator, config["param_grid"],
                    n_iter=config["n_iter"], scoring=config["scoring"],
                    validation_split=config["validation_split"],
                    max_parallel=config["max_parallel"],
                    refit=config["refit"], seed=config["seed"],
                    name=config["name"])
        sweep.cv_results_ = config["cv_results"]
        sweep.best_params_ = config["best_params"]
        sweep.best_score_ = config["best_score"]
        if config["has_best"]:
            sweep.best_estimator_ = est_cls.__lo_load__(
                os.path.join(path, "best_estimator"))
        return sweep


class RandomSearch(GridSearch):
    """GridSearch with sampled combinations (``n_iter`` required)."""

    def __init__(self, estimator, param_grid: Dict[str, Sequence[Any]],
                 n_iter: int = 8, **kwargs):
        super().__init__(estimator, param_grid, n_iter=n_iter, **kwargs)
