// locore — first-party native host-compute core for learningorchestra_tpu.
//
// The reference outsources all native-performance work to off-the-shelf
// infrastructure (Spark/JVM executors, MongoDB's C++ storage engine —
// SURVEY.md §2.2); this module is the rebuild's equivalent native muscle
// for the host side of the pipeline: CSV -> columnar ingest, predicate
// filtering, value-count histograms (histogram_image/histogram.py:25-44
// capability), and the batch-gather hot loop of the device feed. The TPU
// compute path stays JAX/XLA; everything here runs on the host CPU and is
// exposed to Python over a plain C ABI via ctypes (no pybind11 in the
// image).
//
// Build: g++ -O3 -std=c++17 -shared -fPIC (learningorchestra_tpu/native
// builds and caches the .so on first import; every caller keeps a pure
// Python fallback so the framework works without a toolchain).

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// CSV parsing: RFC-4180-ish (quoted fields, embedded delimiters/newlines,
// doubled quotes), CRLF tolerant. One LoTable owns all column buffers.
// Column types: 0 = float64 (missing -> NaN), 1 = string (offsets+data,
// arrow LargeString layout).
// ---------------------------------------------------------------------------

struct LoTable {
  int64_t rows = 0;
  int64_t cols = 0;
  std::vector<uint8_t> types;                 // 0 float64, 1 string
  std::vector<std::vector<double>> fcols;     // per float column
  std::vector<std::vector<int64_t>> offsets;  // per string column, rows+1
  std::vector<std::string> sdata;             // per string column, bytes
};

namespace {

// Parse one record starting at p (end at limit) into cells; returns the
// position one past the record's newline. Cells are unescaped into `scratch`
// only when quoted; plain cells are views into the buffer.
struct Cell {
  const char* ptr;
  int64_t len;
};

inline const char* parse_record(const char* p, const char* limit,
                                char delim, std::vector<Cell>& cells,
                                std::string& scratch,
                                std::vector<size_t>& scratch_marks) {
  cells.clear();
  scratch.clear();
  scratch_marks.clear();
  const char* cell_start = p;
  bool in_scratch = false;
  size_t scratch_begin = 0;
  auto flush = [&](const char* end) {
    if (in_scratch) {
      scratch_marks.push_back(cells.size());
      cells.push_back({nullptr, (int64_t)(scratch.size() - scratch_begin)});
      // ptr fixed up after the record completes (scratch may reallocate)
    } else {
      cells.push_back({cell_start, (int64_t)(end - cell_start)});
    }
    in_scratch = false;
  };
  while (p < limit) {
    char c = *p;
    if (c == '"' && p == cell_start && !in_scratch) {
      // quoted cell: unescape into scratch
      in_scratch = true;
      scratch_begin = scratch.size();
      ++p;
      while (p < limit) {
        if (*p == '"') {
          if (p + 1 < limit && p[1] == '"') {
            scratch.push_back('"');
            p += 2;
          } else {
            ++p;
            break;
          }
        } else {
          scratch.push_back(*p++);
        }
      }
      continue;  // next char should be delim/newline/EOF
    }
    if (c == delim) {
      flush(p);
      ++p;
      cell_start = p;
      scratch_begin = scratch.size();
      continue;
    }
    if (c == '\n' || c == '\r') {
      flush(p > cell_start && p[-1] == '\r' && !in_scratch ? p - 1 : p);
      if (c == '\r' && p + 1 < limit && p[1] == '\n') ++p;
      ++p;
      // fix up scratch-backed cell pointers now that scratch is stable
      {
        size_t off = 0;
        for (size_t k = 0; k < scratch_marks.size(); ++k) {
          Cell& cell = cells[scratch_marks[k]];
          cell.ptr = scratch.data() + off;
          off += cell.len;
        }
      }
      return p;
    }
    ++p;
  }
  // record ends at EOF without newline
  flush(limit);
  {
    size_t off = 0;
    for (size_t k = 0; k < scratch_marks.size(); ++k) {
      Cell& cell = cells[scratch_marks[k]];
      cell.ptr = scratch.data() + off;
      off += cell.len;
    }
  }
  return limit;
}

// Fast decimal path (Clinger): for plain [+-]ddd[.ddd] cells with at
// most 15 mantissa digits and at most 22 fractional digits, mantissa
// and 10^frac are both exact doubles, so ONE IEEE division yields the
// correctly rounded value — bit-identical to strtod, ~6x cheaper (no
// copy, no locale machinery). Everything else (exponents, inf/nan,
// hex, long digit strings) falls back to bounded strtod.
static const double kPow10[23] = {
    1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,
    1e8,  1e9,  1e10, 1e11, 1e12, 1e13, 1e14, 1e15,
    1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};

inline bool fast_decimal(const char* s, int64_t len, double* out) {
  int64_t i = 0;
  bool neg = false;
  if (i < len && (s[i] == '+' || s[i] == '-')) {
    neg = (s[i] == '-');
    ++i;
  }
  uint64_t mant = 0;
  int digits = 0, frac = 0;
  bool seen_dot = false, any_digit = false;
  for (; i < len; ++i) {
    const char c = s[i];
    if (c >= '0' && c <= '9') {
      if (digits >= 15) return false;  // strtod for full precision
      mant = mant * 10 + (uint64_t)(c - '0');
      if (mant) ++digits;  // leading zeros don't consume the budget
      any_digit = true;
      if (seen_dot) ++frac;
    } else if (c == '.' && !seen_dot) {
      seen_dot = true;
    } else {
      return false;
    }
  }
  if (!any_digit || frac > 22) return false;
  const double v = (double)mant / kPow10[frac];
  *out = neg ? -v : v;
  return true;
}

// numeric cell parse; empty/whitespace-only cells are "missing"
// (NaN, still numeric — matches the Python fallback's strip-then-empty).
inline bool parse_float(const Cell& cell, double* out) {
  const char* p = cell.ptr;
  int64_t len = cell.len;
  while (len > 0 && (*p == ' ' || *p == '\t')) { ++p; --len; }
  while (len > 0 && (p[len - 1] == ' ' || p[len - 1] == '\t')) --len;
  if (len == 0) {
    *out = std::nan("");
    return true;
  }
  if (fast_decimal(p, len, out)) return true;
  if (len >= 64) return false;
  char tmp[64];
  std::memcpy(tmp, p, len);
  tmp[len] = '\0';
  char* end = nullptr;
  double v = std::strtod(tmp, &end);
  if (end != tmp + len) return false;
  *out = v;
  return true;
}

}  // namespace

// Parse a complete-records buffer. forced_types: nullptr to sniff (a column
// is float64 iff every cell parses), else an int8 array of length >= ncols
// from a previous chunk's sniff so all chunks share one schema. has_header:
// skip the first record. Returns nullptr on malformed input (ragged rows).
LoTable* lo_csv_parse(const char* buf, int64_t len, char delim,
                      int has_header, const int8_t* forced_types) {
  auto table = new LoTable();
  const char* p = buf;
  const char* limit = buf + len;
  std::vector<Cell> cells;
  std::string scratch;
  std::vector<size_t> scratch_marks;

  if (has_header) {
    if (p >= limit) return table;
    p = parse_record(p, limit, delim, cells, scratch, scratch_marks);
    table->cols = (int64_t)cells.size();
  }

  // Column-major staging: first pass collects raw cells row by row and
  // numeric candidacy; we keep parsed doubles as we go so numeric columns
  // need no second text scan.
  std::vector<std::vector<double>> fvals;
  std::vector<std::vector<std::string>> svals;  // raw text per column
  std::vector<uint8_t> numeric_ok;              // candidacy while sniffing

  int64_t row = 0;
  while (p < limit) {
    // skip blank lines
    if (*p == '\n' || *p == '\r') {
      ++p;
      continue;
    }
    p = parse_record(p, limit, delim, cells, scratch, scratch_marks);
    if (table->cols == 0) table->cols = (int64_t)cells.size();
    if ((int64_t)cells.size() != table->cols) {
      delete table;
      return nullptr;  // ragged
    }
    if (row == 0) {
      fvals.resize(table->cols);
      svals.resize(table->cols);
      numeric_ok.assign(table->cols, 1);
      if (forced_types) {
        for (int64_t j = 0; j < table->cols; ++j)
          numeric_ok[j] = forced_types[j] == 0;
      }
    }
    for (int64_t j = 0; j < table->cols; ++j) {
      double v;
      if (numeric_ok[j] && parse_float(cells[j], &v)) {
        fvals[j].push_back(v);
      } else {
        if (numeric_ok[j] && !forced_types) {
          numeric_ok[j] = 0;  // demote: keep nothing, text below rebuilds
        } else if (numeric_ok[j]) {
          // forced numeric but unparseable -> NaN
          fvals[j].push_back(std::nan(""));
          continue;
        }
      }
      svals[j].emplace_back(cells[j].ptr, (size_t)cells[j].len);
    }
    ++row;
  }
  table->rows = row;
  if (table->cols == 0) return table;
  if (fvals.empty()) {
    fvals.resize(table->cols);
    svals.resize(table->cols);
    numeric_ok.assign(table->cols, 1);
    if (forced_types)
      for (int64_t j = 0; j < table->cols; ++j)
        numeric_ok[j] = forced_types[j] == 0;
  }

  table->types.resize(table->cols);
  for (int64_t j = 0; j < table->cols; ++j) {
    bool is_float = numeric_ok[j] &&
                    (int64_t)fvals[j].size() == table->rows;
    if (forced_types) is_float = forced_types[j] == 0;
    table->types[j] = is_float ? 0 : 1;
    if (is_float) {
      table->fcols.push_back(std::move(fvals[j]));
      table->offsets.emplace_back();
      table->sdata.emplace_back();
    } else {
      std::vector<int64_t> offs;
      offs.reserve(table->rows + 1);
      std::string data;
      int64_t off = 0;
      offs.push_back(0);
      for (auto& s : svals[j]) {
        data.append(s);
        off += (int64_t)s.size();
        offs.push_back(off);
      }
      table->fcols.emplace_back();
      table->offsets.push_back(std::move(offs));
      table->sdata.push_back(std::move(data));
    }
  }
  return table;
}

void lo_table_free(LoTable* t) { delete t; }
int64_t lo_table_rows(const LoTable* t) { return t->rows; }
int64_t lo_table_cols(const LoTable* t) { return t->cols; }
int32_t lo_table_col_type(const LoTable* t, int64_t j) {
  return t->types[j];
}
const double* lo_table_fcol(const LoTable* t, int64_t j) {
  return t->fcols[j].data();
}
const int64_t* lo_table_scol_offsets(const LoTable* t, int64_t j) {
  return t->offsets[j].data();
}
const char* lo_table_scol_data(const LoTable* t, int64_t j) {
  return t->sdata[j].data();
}
int64_t lo_table_scol_data_len(const LoTable* t, int64_t j) {
  return (int64_t)t->sdata[j].size();
}

// ---------------------------------------------------------------------------
// Value counts (histogram service: Mongo $group/$sum equivalent,
// histogram_image/histogram.py:25-44). Insertion-ordered keys.
// ---------------------------------------------------------------------------

struct LoCounts {
  std::vector<double> fkeys;
  std::vector<std::string> skeys;  // parallel to counts when string-keyed
  std::vector<int64_t> counts;
  std::string sdata;               // packed string keys
  std::vector<int64_t> soffsets;
  bool is_string = false;
};

LoCounts* lo_value_counts_f64(const double* vals, int64_t n) {
  auto out = new LoCounts();
  std::unordered_map<double, int64_t> idx;
  idx.reserve((size_t)(n / 4 + 8));
  int64_t nan_slot = -1;  // NaN != NaN, so the map can't key it
  for (int64_t i = 0; i < n; ++i) {
    double key = vals[i];
    if (std::isnan(key)) {
      if (nan_slot < 0) {
        nan_slot = (int64_t)out->fkeys.size();
        out->fkeys.push_back(std::nan(""));
        out->counts.push_back(0);
      }
      ++out->counts[nan_slot];
      continue;
    }
    auto it = idx.find(key);
    if (it == idx.end()) {
      idx.emplace(key, (int64_t)out->fkeys.size());
      out->fkeys.push_back(key);
      out->counts.push_back(1);
    } else {
      ++out->counts[it->second];
    }
  }
  return out;
}

LoCounts* lo_value_counts_str(const char* data, const int64_t* offsets,
                              int64_t n) {
  auto out = new LoCounts();
  out->is_string = true;
  std::unordered_map<std::string_view, int64_t> idx;
  idx.reserve((size_t)(n / 4 + 8));
  for (int64_t i = 0; i < n; ++i) {
    std::string_view key(data + offsets[i],
                         (size_t)(offsets[i + 1] - offsets[i]));
    auto it = idx.find(key);
    if (it == idx.end()) {
      idx.emplace(key, (int64_t)out->skeys.size());
      out->skeys.emplace_back(key);
      out->counts.push_back(1);
    } else {
      ++out->counts[it->second];
    }
  }
  out->soffsets.push_back(0);
  for (auto& s : out->skeys) {
    out->sdata.append(s);
    out->soffsets.push_back((int64_t)out->sdata.size());
  }
  return out;
}

void lo_counts_free(LoCounts* c) { delete c; }
int64_t lo_counts_n(const LoCounts* c) {
  return (int64_t)c->counts.size();
}
const double* lo_counts_fkeys(const LoCounts* c) { return c->fkeys.data(); }
const int64_t* lo_counts_counts(const LoCounts* c) {
  return c->counts.data();
}
const char* lo_counts_sdata(const LoCounts* c) { return c->sdata.data(); }
const int64_t* lo_counts_soffsets(const LoCounts* c) {
  return c->soffsets.data();
}

// ---------------------------------------------------------------------------
// Predicate filter: AND of simple comparisons over float64 columns.
// op: 0 ==, 1 !=, 2 <, 3 <=, 4 >, 5 >=. Writes a 0/1 mask.
// ---------------------------------------------------------------------------

void lo_filter_f64(const double* const* cols, int64_t nrows, int64_t npreds,
                   const int64_t* col_idx, const int32_t* ops,
                   const double* operands, uint8_t* mask) {
  std::memset(mask, 1, (size_t)nrows);
  for (int64_t k = 0; k < npreds; ++k) {
    const double* col = cols[col_idx[k]];
    const double v = operands[k];
    const int32_t op = ops[k];
    for (int64_t i = 0; i < nrows; ++i) {
      if (!mask[i]) continue;
      double x = col[i];
      bool keep;
      switch (op) {
        case 0: keep = x == v; break;
        case 1: keep = x != v; break;
        case 2: keep = x < v; break;
        case 3: keep = x <= v; break;
        case 4: keep = x > v; break;
        default: keep = x >= v; break;
      }
      if (!keep) mask[i] = 0;
    }
  }
}

// String equality predicate applied on top of an existing mask.
void lo_filter_str_eq(const char* data, const int64_t* offsets,
                      int64_t nrows, const char* needle, int64_t needle_len,
                      int32_t negate, uint8_t* mask) {
  std::string_view want(needle, (size_t)needle_len);
  for (int64_t i = 0; i < nrows; ++i) {
    if (!mask[i]) continue;
    std::string_view got(data + offsets[i],
                         (size_t)(offsets[i + 1] - offsets[i]));
    bool eq = got == want;
    if (negate ? eq : !eq) mask[i] = 0;
  }
}

// ---------------------------------------------------------------------------
// Batch gather: rows of a C-contiguous float32 matrix by index — the device
// feed's per-step hot loop (shuffled minibatch assembly).
// ---------------------------------------------------------------------------

void lo_gather_f32(const float* src, int64_t nrows, int64_t ncols,
                   const int64_t* idx, int64_t nidx, float* dst) {
  const size_t rowbytes = (size_t)ncols * sizeof(float);
  for (int64_t i = 0; i < nidx; ++i) {
    int64_t r = idx[i];
    if (r < 0 || r >= nrows) {
      std::memset(dst + i * ncols, 0, rowbytes);
    } else {
      std::memcpy(dst + i * ncols, src + r * ncols, rowbytes);
    }
  }
}


// ---------------------------------------------------------------------------
// Histogram gradient boosting over pre-binned uint8 feature codes — the
// full-data replacement for the reference's Spark GBTClassifier path
// (builder_image/builder.py:118): every row contributes gradients on every
// iteration (no reservoir), memory stays rows x nfeats bytes + one raw
// score per row/class. Depth-wise growth in an implicit heap layout; one
// pass over the data builds the histograms of every node of a level
// (hist indexed by the row''s current node), logistic / softmax objective.
// ---------------------------------------------------------------------------

struct HgbModel {
  int nfeats = 0;
  int nclass = 0;        // 2 => single sigmoid tree per iter
  int max_depth = 0;
  double base = 0.0;     // binary: log-odds; multiclass: per-class in bases
  std::vector<double> bases;
  // trees laid out iteration-major; each tree is a full implicit heap of
  // (2^(max_depth+1) - 1) slots: feat[i] >= 0 -> internal (go left if
  // code <= bin[i]); feat[i] == -1 -> leaf with value val[i];
  // feat[i] == -2 -> dead slot (under a leaf ancestor)
  std::vector<int> feat;
  std::vector<uint8_t> bin;
  std::vector<double> val;
  int slots_per_tree = 0;
  int n_trees = 0;
};

static inline double hgb_leaf(double g, double h, double l2, double lr) {
  return -lr * g / (h + l2 + 1e-12);
}

// one histogram cell, array-of-structs so a row's (g, h, count)
// update touches one cache line instead of three far-apart arrays
struct HistCell {
  double g, h;
  int64_t c;
};

// shared by the binary and multiclass gradient passes: one row's
// (g, h) lands in the tree's root histogram as the gradients are
// computed, so no separate root-accumulation scan exists
static inline void hgb_root_add(HistCell* root, const uint8_t* row,
                                int nfeats, int max_bins, double gi,
                                double hi) {
  for (int f = 0; f < nfeats; ++f) {
    HistCell& cell = root[f * max_bins + row[f]];
    cell.g += gi;
    cell.h += hi;
    cell.c += 1;
  }
}

// builds ONE regression tree on (g, h); updates scores in place.
// Histograms use the LightGBM sibling-subtraction trick: after level
// 0, only the SMALLER child of each split is accumulated from rows;
// the larger child is parent - sibling (counts exact; g/h differ from
// direct accumulation only by float summation order). This roughly
// halves the dominant per-level accumulate work.
static void hgb_build_tree(const uint8_t* codes, int64_t nrows, int nfeats,
                           const double* g, const double* h,
                           double* scores, int64_t score_stride,
                           int max_depth, int max_bins, double lr,
                           double l2, int64_t min_leaf,
                           std::vector<int>& feat_out,
                           std::vector<uint8_t>& bin_out,
                           std::vector<double>& val_out,
                           std::vector<int32_t>& assign,
                           std::vector<HistCell>& root_hist) {
  const int slots = (1 << (max_depth + 1)) - 1;
  const int base_slot = (int)feat_out.size();
  feat_out.insert(feat_out.end(), slots, -2);
  bin_out.insert(bin_out.end(), slots, 0);
  val_out.insert(val_out.end(), slots, 0.0);
  int* tfeat = feat_out.data() + base_slot;
  uint8_t* tbin = bin_out.data() + base_slot;
  double* tval = val_out.data() + base_slot;

  std::fill(assign.begin(), assign.end(), 0);
  tfeat[0] = -1;  // provisional leaf (filled from level-0 totals below)

  const size_t fb = (size_t)nfeats * max_bins;  // cells per node hist

  // Pass structure (single-core: passes over rows dominate, so each
  // level costs ONE fused pass): level 0's root histogram is built by
  // a plain scan; every later level's build-marked histograms are
  // accumulated DURING the routing pass that moves rows down through
  // the parents' splits. The smaller child of each split is known at
  // split time (CL vs C-CL), so the build marks exist before routing;
  // larger siblings are derived parent - sibling before selection.
  std::vector<int> active(1, 0);        // nodes of the current level
  std::vector<int> id_in_level(1, 0);   // in-level -> hist idx (-1 none)
  std::vector<char> build_flag(1, 1);   // accumulated from rows?
  // the root histogram arrives pre-filled: the caller accumulates it
  // during its gradient pass, saving one full scan of the rows
  std::vector<HistCell> hist = std::move(root_hist);
  std::vector<HistCell> parent_hist;
  std::vector<int> parent_id;
  std::vector<double> leaf_g, leaf_h;   // deepest-level totals

  for (int depth = 0; depth < max_depth; ++depth) {
    const int first = (1 << depth) - 1;
    if (active.empty()) break;

    // complete the level: derive non-built siblings from parents
    if (depth > 0) {
      const int pfirst = (1 << (depth - 1)) - 1;
      for (size_t a = 0; a < active.size(); ++a) {
        const int node = active[a];
        const int in_level = node - first;
        if (build_flag[in_level]) continue;
        const int parent = (node - 1) / 2;
        // left children sit at EVEN in-level offsets (left = 2p+1 =
        // first + 2j)
        const int sib = (in_level % 2 == 0) ? in_level + 1
                                            : in_level - 1;
        const HistCell* pp = parent_hist.data() +
            (size_t)parent_id[parent - pfirst] * fb;
        const HistCell* sp = hist.data() +
            (size_t)id_in_level[sib] * fb;
        HistCell* dp = hist.data() +
            (size_t)id_in_level[in_level] * fb;
        for (size_t cix = 0; cix < fb; ++cix) {
          dp[cix].g = pp[cix].g - sp[cix].g;
          dp[cix].h = pp[cix].h - sp[cix].h;
          dp[cix].c = pp[cix].c - sp[cix].c;
        }
      }
    }

    // split selection; build marks for the next level come straight
    // from each winning split's left/right row counts
    const int next_first = (1 << (depth + 1)) - 1;
    const int next_count = 1 << (depth + 1);
    std::vector<char> next_build(next_count, 0);
    std::vector<int> next_active;
    bool any_split = false;
    for (size_t a = 0; a < active.size(); ++a) {
      const int node = active[a];
      const HistCell* hp = hist.data() +
          (size_t)id_in_level[node - first] * fb;
      double G = 0.0, H = 0.0;
      int64_t C = 0;
      for (int b = 0; b < max_bins; ++b) {
        G += hp[b].g; H += hp[b].h; C += hp[b].c;
      }
      // (feature 0 totals == node totals; every feature sums the same rows)
      const double parent_obj = G * G / (H + l2 + 1e-12);
      double best_gain = 1e-7;
      int best_f = -1, best_b = -1;
      int64_t best_cl = 0;
      for (int f = 0; f < nfeats; ++f) {
        double GL = 0.0, HL = 0.0;
        int64_t CL = 0;
        const HistCell* fp = hp + (size_t)f * max_bins;
        for (int b = 0; b < max_bins - 1; ++b) {
          GL += fp[b].g; HL += fp[b].h; CL += fp[b].c;
          const int64_t CR = C - CL;
          if (CL < min_leaf || CR < min_leaf) continue;
          const double HR = H - HL, GR = G - GL;
          const double gain = GL * GL / (HL + l2 + 1e-12) +
                              GR * GR / (HR + l2 + 1e-12) - parent_obj;
          if (gain > best_gain) {
            best_gain = gain; best_f = f; best_b = b; best_cl = CL;
          }
        }
      }
      if (best_f < 0 || depth + 1 >= max_depth + 1) {
        tval[node] = hgb_leaf(G, H, l2, lr);  // stays a leaf
        continue;
      }
      tfeat[node] = best_f;
      tbin[node] = (uint8_t)best_b;
      const int left = 2 * node + 1, right = 2 * node + 2;
      if (left < slots) {
        tfeat[left] = -1;
        tfeat[right] = -1;
        next_active.push_back(left);
        next_active.push_back(right);
        // accumulate only the smaller child; the other subtracts
        const int small = (best_cl <= C - best_cl) ? left : right;
        next_build[small - next_first] = 1;
      }
      any_split = true;
    }
    if (!any_split) break;

    // prepare next-level storage
    std::vector<int> next_id(next_count, -1);
    for (size_t a = 0; a < next_active.size(); ++a)
      next_id[next_active[a] - next_first] = (int)a;
    std::vector<HistCell> next_hist;
    const bool last_level = (depth + 1 == max_depth);
    if (!last_level) {
      next_hist.assign(next_active.size() * fb, HistCell{0.0, 0.0, 0});
    } else {
      leaf_g.assign(next_count, 0.0);
      leaf_h.assign(next_count, 0.0);
    }

    // ONE fused pass: route each row through its node's new split and
    // accumulate it into its child's histogram (or, at the deepest
    // level, into the child leaf's g/h totals)
    const int count = 1 << depth;
    for (int64_t i = 0; i < nrows; ++i) {
      const int32_t node = assign[i];
      if (node < first || node >= first + count) continue;
      if (tfeat[node] < 0) continue;
      const uint8_t* row = codes + i * nfeats;
      const uint8_t c = row[tfeat[node]];
      const int child = (c <= tbin[node]) ? 2 * node + 1 : 2 * node + 2;
      assign[i] = child;
      const int child_in = child - next_first;
      if (last_level) {
        leaf_g[child_in] += g[i];
        leaf_h[child_in] += h[i];
      } else if (next_build[child_in]) {
        hgb_root_add(next_hist.data() + (size_t)next_id[child_in] * fb,
                     row, nfeats, max_bins, g[i], h[i]);
      }
    }

    if (last_level) {
      // next_first + next_count - 1 == slots - 1 at the last level,
      // so every slot index here is in bounds by construction
      for (int n = 0; n < next_count; ++n)
        if (tfeat[next_first + n] == -1)
          tval[next_first + n] = hgb_leaf(leaf_g[n], leaf_h[n], l2, lr);
      break;
    }
    parent_hist = std::move(hist);
    parent_id = std::move(id_in_level);
    hist = std::move(next_hist);
    id_in_level = std::move(next_id);
    build_flag = std::move(next_build);
    active = std::move(next_active);
  }

  // update scores: every row adds its leaf''s value
  for (int64_t i = 0; i < nrows; ++i) {
    int node = assign[i];
    // walk down if the row stopped on an internal node (can''t happen in
    // this layout, but cheap to guard), walk up never needed
    while (tfeat[node] >= 0) {
      const uint8_t c = codes[i * nfeats + tfeat[node]];
      node = (c <= tbin[node]) ? 2 * node + 1 : 2 * node + 2;
    }
    scores[i * score_stride] += tval[node];
  }
}

void* lo_hgb_train(const uint8_t* codes, int64_t nrows, int nfeats,
                   const int32_t* y, int nclass, int n_iter, int max_depth,
                   int max_bins, double lr, double l2,
                   int64_t min_samples_leaf) {
  if (nrows <= 0 || nfeats <= 0 || nclass < 2 || max_bins > 256)
    return nullptr;
  HgbModel* m = new HgbModel();
  m->nfeats = nfeats;
  m->nclass = nclass;
  m->max_depth = max_depth;
  m->slots_per_tree = (1 << (max_depth + 1)) - 1;

  const int K = (nclass == 2) ? 1 : nclass;
  std::vector<double> scores((size_t)nrows * K, 0.0);
  std::vector<int64_t> class_count(nclass, 0);
  for (int64_t i = 0; i < nrows; ++i) ++class_count[y[i]];
  m->bases.assign(K, 0.0);
  if (nclass == 2) {
    const double p = std::max(
        1e-9, std::min(1.0 - 1e-9,
                       (double)class_count[1] / (double)nrows));
    m->bases[0] = std::log(p / (1.0 - p));
  } else {
    for (int k = 0; k < K; ++k)
      m->bases[k] = std::log(std::max(
          1e-9, (double)class_count[k] / (double)nrows));
  }
  for (int64_t i = 0; i < nrows; ++i)
    for (int k = 0; k < K; ++k) scores[i * K + k] = m->bases[k];

  std::vector<double> g(nrows), h(nrows);
  std::vector<int32_t> assign(nrows);
  std::vector<double> probs;  // multiclass: nrows x K, one softmax/iter
  if (nclass > 2) probs.resize((size_t)nrows * K);
  const size_t fb = (size_t)nfeats * max_bins;
  std::vector<HistCell> root_hist;

  for (int it = 0; it < n_iter; ++it) {
    if (nclass == 2) {
      root_hist.assign(fb, HistCell{0.0, 0.0, 0});
      for (int64_t i = 0; i < nrows; ++i) {
        const double p = 1.0 / (1.0 + std::exp(-scores[i]));
        const double gi = p - (double)y[i];
        const double hi = std::max(p * (1.0 - p), 1e-12);
        g[i] = gi;
        h[i] = hi;
        hgb_root_add(root_hist.data(), codes + i * nfeats, nfeats,
                     max_bins, gi, hi);
      }
      hgb_build_tree(codes, nrows, nfeats, g.data(), h.data(),
                     scores.data(), 1, max_depth, max_bins, lr, l2,
                     min_samples_leaf, m->feat, m->bin, m->val, assign,
                     root_hist);
      ++m->n_trees;
    } else {
      // standard softmax boosting: ONE softmax per iteration drives
      // all K trees (matching the numpy fallback — per-class
      // recomputation would make the two paths diverge)
      for (int64_t i = 0; i < nrows; ++i) {
        const double* s = scores.data() + i * K;
        double mx = s[0];
        for (int j = 1; j < K; ++j) mx = std::max(mx, s[j]);
        double denom = 0.0;
        double* p = probs.data() + i * K;
        for (int j = 0; j < K; ++j) {
          p[j] = std::exp(s[j] - mx);
          denom += p[j];
        }
        for (int j = 0; j < K; ++j) p[j] /= denom;
      }
      for (int k = 0; k < K; ++k) {
        root_hist.assign(fb, HistCell{0.0, 0.0, 0});
        for (int64_t i = 0; i < nrows; ++i) {
          const double pk = probs[i * K + k];
          const double gi = pk - (y[i] == k ? 1.0 : 0.0);
          const double hi = std::max(pk * (1.0 - pk), 1e-12);
          g[i] = gi;
          h[i] = hi;
          hgb_root_add(root_hist.data(), codes + i * nfeats, nfeats,
                       max_bins, gi, hi);
        }
        hgb_build_tree(codes, nrows, nfeats, g.data(), h.data(),
                       scores.data() + k, K, max_depth, max_bins, lr, l2,
                       min_samples_leaf, m->feat, m->bin, m->val, assign,
                       root_hist);
        ++m->n_trees;
      }
    }
  }
  return m;
}

// raw scores: out has nrows x K (K = 1 for binary)
void lo_hgb_predict(void* model, const uint8_t* codes, int64_t nrows,
                    double* out) {
  HgbModel* m = (HgbModel*)model;
  const int K = (m->nclass == 2) ? 1 : m->nclass;
  const int slots = m->slots_per_tree;
  // tree-outer on purpose: the serially-dependent node walk dominates
  // (codes re-streaming is ~30 ms for 2M x 5 rows), and per-tree
  // branch patterns predict far better when one tree processes all
  // rows before the next (row-outer measured 40% SLOWER here)
  for (int64_t i = 0; i < nrows; ++i)
    for (int k = 0; k < K; ++k) out[i * K + k] = m->bases[k];
  for (int t = 0; t < m->n_trees; ++t) {
    const int* tfeat = m->feat.data() + (size_t)t * slots;
    const uint8_t* tbin = m->bin.data() + (size_t)t * slots;
    const double* tval = m->val.data() + (size_t)t * slots;
    const int k = t % K;
    for (int64_t i = 0; i < nrows; ++i) {
      const uint8_t* row = codes + i * m->nfeats;
      int node = 0;
      while (tfeat[node] >= 0)
        node = (row[tfeat[node]] <= tbin[node]) ? 2 * node + 1
                                                : 2 * node + 2;
      out[i * K + k] += tval[node];
    }
  }
}

int32_t lo_hgb_nclass(void* model) { return ((HgbModel*)model)->nclass; }
void lo_hgb_free(void* model) { delete (HgbModel*)model; }

int32_t lo_abi_version() { return 2; }

}  // extern "C"
