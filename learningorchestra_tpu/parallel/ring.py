"""Ring attention: sequence/context parallelism over the ``sp`` axis.

Long-context attention where the sequence is sharded across devices:
each device keeps its Q block resident and the K/V blocks rotate
around the ring (``ppermute`` over ICI neighbours) while an online-
softmax accumulator (running max + log-sum-exp) keeps the math exact —
the composition of blockwise softmax corrections equals full softmax.
Compute on each hop is a dense (seq_local × seq_local) attention block
that XLA maps onto the MXU, and the rotation overlaps with it in the
usual XLA async-collective schedule.

The reference has no attention at all (SURVEY §5 long-context row);
this module is one of the net-new first-class components. Used inside
``shard_map`` (see :func:`ring_attention_sharded` for the pjit-level
wrapper).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from learningorchestra_tpu.runtime import mesh as mesh_lib

NEG_INF = -1e30


def _block_attn(q, k, v, scale, mask):
    """One (q_block × kv_block) attention tile.

    q: (b, sq, h, d)  k/v: (b, sk, h, d)  mask: (sq, sk) or None.
    Returns (numerator (b, sq, h, d), row_max (b, sq, h),
    row_sumexp (b, sq, h)) of THIS tile only.
    """
    scores = jnp.einsum("bqhd,bkhd->bqhk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        scores = jnp.where(mask[None, :, None, :], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)
    p = jnp.exp(scores - m[..., None])
    if mask is not None:
        # rows with no visible keys: exp(NEG_INF - NEG_INF) = 1 junk
        any_visible = jnp.any(mask, axis=-1)  # (sq,)
        p = jnp.where(any_visible[None, :, None, None], p, 0.0)
        m = jnp.where(any_visible[None, :, None], m, NEG_INF)
    num = jnp.einsum("bqhk,bkhd->bqhd", p,
                     v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return num, m, jnp.sum(p, axis=-1)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = mesh_lib.SP,
                   causal: bool = False,
                   scale: Optional[float] = None,
                   window: int = 0) -> jax.Array:
    """Exact attention over a sequence sharded on ``axis_name``.

    Call INSIDE ``shard_map``; q/k/v are the local sequence shards
    shaped (batch, seq_local, heads, head_dim). Returns the local
    output shard, same shape as ``q``, in ``q``'s dtype.
    """
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if window and not causal:
        raise ValueError("window requires causal=True")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    qf = q.astype(jnp.float32)

    q_pos = my_idx * sq + jnp.arange(sq)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, hop):
        o, m, l, k_blk, v_blk = carry
        # after `hop` rotations we hold the block that started on
        # device (my_idx - hop) mod n
        kv_idx = (my_idx - hop) % n

        def attend(o, m, l):
            mask = None
            if causal:
                k_pos = kv_idx * sk + jnp.arange(sk)
                mask = q_pos[:, None] >= k_pos[None, :]
                if window > 0:
                    mask = mask & (k_pos[None, :]
                                   > q_pos[:, None] - window)
            num, bm, bl = _block_attn(qf, k_blk.astype(jnp.float32),
                                      v_blk, scale, mask)
            new_m = jnp.maximum(m, bm)
            old_c = jnp.exp(m - new_m)
            blk_c = jnp.exp(bm - new_m)
            o = o * old_c[..., None] + num * blk_c[..., None]
            l = l * old_c + bl * blk_c
            return o, new_m, l

        if causal:
            # skip K/V blocks strictly in this shard's future (every
            # key position > every local query position): the block is
            # fully masked, so attending would compute then discard it.
            # Each device branches on its own index — halves total
            # causal FLOPs around the ring. A sliding window also
            # skips blocks wholly BELOW the band (too far in the
            # past), so only ~(W/sk + 1) hops attend at all.
            fully_masked = kv_idx * sk > my_idx * sq + sq - 1
            if window > 0:
                below = (kv_idx * sk + sk - 1
                         < my_idx * sq - window + 1)
                fully_masked = jnp.logical_or(fully_masked, below)
            o, m, l = lax.cond(fully_masked,
                               lambda o, m, l: (o, m, l), attend, o, m, l)
        else:
            o, m, l = attend(o, m, l)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (o, m, l, k_blk, v_blk), None

    # carries derived from qf so shard_map marks them device-varying
    # (plain zeros are "unvarying" and fail the scan vma check)
    o0 = qf * 0.0
    m0 = qf[..., 0] * 0.0 + NEG_INF
    l0 = qf[..., 0] * 0.0
    (o, _, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v),
                                  jnp.arange(n))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def ring_flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                         axis_name: str = mesh_lib.SP,
                         causal: bool = False,
                         scale: Optional[float] = None,
                         interpret: Optional[bool] = None,
                         window: int = 0) -> jax.Array:
    """Ring attention with the PALLAS FLASH KERNEL as the per-hop
    block (call inside ``shard_map``; same contract as
    :func:`ring_attention`).

    The dense ring materializes a (b, sq_local, h, sk_local) score
    tile per hop; here each hop is a fused flash call — intra-shard
    memory stays O(block), so local shards can themselves be long.
    Hop results merge EXACTLY via log-sum-exp weights (the kernel
    returns lse; its custom VJP carries the merge gradient through
    ``delta - dlse``). With equal shard sizes every causal hop is one
    of three static shapes: fully-past (unmasked flash), diagonal
    (aligned causal flash), or fully-future (skipped) — no
    offset-mask kernel variant is needed.
    """
    from learningorchestra_tpu.ops import attention as attn_ops

    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if sk != sq:
        raise ValueError("ring_flash_attention needs equal shards "
                         f"(sq={sq}, sk={sk})")
    if window and not causal:
        raise ValueError("window requires causal=True")
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    def flash_hop(k_blk, v_blk, hop_causal: bool, offset: int = 0,
                  win: int = 0):
        o, lse = attn_ops.flash_attention_with_lse(
            q, k_blk, v_blk, causal=hop_causal, scale=scale,
            interpret=interpret, window=win, kv_offset=offset)
        return o.astype(jnp.float32), lse

    def skip_hop(kb, vb):
        # lse = -inf: zero weight in the log-sum-exp merge
        return (jnp.zeros((b, sq, h, d), jnp.float32),
                jnp.full((b, sq, h), NEG_INF))

    def step(carry, hop):
        o_acc, lse_acc, k_blk, v_blk = carry
        kv_idx = (my_idx - hop) % n

        if causal and window > 0:
            # one branch per past-hop distance: the kernel applies the
            # exact banded mask at static offset -dist*sk, and hops
            # wholly below the band (dist*sk >= W + sq - 1) are
            # statically skipped — a W << total_seq ring attends only
            # ~(W/sk + 1) hops
            dist = my_idx - kv_idx
            case = jnp.where(dist >= 0, dist, n)
            branches = []
            for d_ in range(n):
                if d_ * sk >= window + sq - 1:
                    branches.append(skip_hop)
                else:
                    branches.append(functools.partial(
                        flash_hop, hop_causal=True, offset=-d_ * sk,
                        win=window))
            branches.append(skip_hop)  # future
            o_hop, lse_hop = lax.switch(case, branches, k_blk, v_blk)
        elif causal:
            # 0 = fully past (unmasked), 1 = diagonal (aligned
            # causal), 2 = fully future (skip — zero weight)
            case = jnp.where(kv_idx < my_idx, 0,
                             jnp.where(kv_idx == my_idx, 1, 2))
            o_hop, lse_hop = lax.switch(
                case,
                [lambda kb, vb: flash_hop(kb, vb, False),
                 lambda kb, vb: flash_hop(kb, vb, True),
                 skip_hop],
                k_blk, v_blk)
        else:
            o_hop, lse_hop = flash_hop(k_blk, v_blk, False)

        new_lse = jnp.logaddexp(lse_acc, lse_hop)
        w_acc = jnp.exp(lse_acc - new_lse)
        w_hop = jnp.exp(lse_hop - new_lse)
        o_acc = o_acc * w_acc[..., None] + o_hop * w_hop[..., None]
        k_blk = lax.ppermute(k_blk, axis_name, _ring_perm(n))
        v_blk = lax.ppermute(v_blk, axis_name, _ring_perm(n))
        return (o_acc, new_lse, k_blk, v_blk), None

    o0 = q.astype(jnp.float32) * 0.0
    lse0 = q[..., 0].astype(jnp.float32) * 0.0 + NEG_INF
    (o, _, _, _), _ = lax.scan(step, (o0, lse0, k, v), jnp.arange(n))
    return o.astype(q.dtype)


def _ring_perm(n) -> list:
    return [(i, (i + 1) % int(n)) for i in range(int(n))]


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                           mesh: Mesh, causal: bool = False,
                           scale: Optional[float] = None,
                           block_impl: str = "auto",
                           window: int = 0) -> jax.Array:
    """pjit-level entry: global (b, seq, h, d) arrays, sequence sharded
    over ``sp``, batch over the data axes.

    ``block_impl``: ``"dense"`` (XLA einsum tiles), ``"flash"``
    (Pallas kernel per hop), or ``"auto"`` (flash on TPU, dense
    elsewhere — interpret-mode pallas is for tests, not speed)."""
    if mesh_lib.SP not in mesh.axis_names:
        raise ValueError("mesh has no 'sp' axis")
    if block_impl == "auto":
        block_impl = "flash" if jax.default_backend() == "tpu" else "dense"
    data = mesh_lib.data_axes(mesh)
    spec = P(data if data else None, mesh_lib.SP, None, None)
    inner = (ring_flash_attention if block_impl == "flash"
             else ring_attention)
    # pallas_call emits ShapeDtypeStructs with no varying-mesh-axes
    # info, which the vma checker rejects (same as the tp flash path)
    extra = {"check_vma": False} if block_impl == "flash" else {}
    fn = mesh_lib.shard_map(
        functools.partial(inner, axis_name=mesh_lib.SP,
                          causal=causal, scale=scale, window=window),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, **extra)
    return fn(q, k, v)


def full_attention_reference(q, k, v, causal: bool = False,
                             scale: Optional[float] = None,
                             window: int = 0,
                             kv_valid=None) -> jax.Array:
    """Plain full-softmax attention (the oracle ring_attention must
    match; also the single-device fallback). ``window=W`` with
    ``causal`` restricts query p to keys in [p-W+1, p] (sliding
    window). ``kv_valid`` (bool, ``(b, sk)``) additionally masks
    per-batch-row key positions — padded prompt slots in batched
    prefill (left-pad generate, serving bucket prefill). NEG_INF
    scores underflow to exact zero under softmax, so a masked key
    never perturbs the unmasked rows' bits."""
    d = q.shape[-1]
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window and not causal:
        raise ValueError("window requires causal=True")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bqhk", q.astype(jnp.float32),
                        k.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = scores.shape[1], scores.shape[3]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        if window > 0:
            mask = mask & (jnp.arange(sk)[None, :] >
                           jnp.arange(sq)[:, None] - window)
        scores = jnp.where(mask[None, :, None, :], scores, NEG_INF)
    if kv_valid is not None:
        scores = jnp.where(kv_valid[:, None, None, :], scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bqhk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
