"""Subprocess-jail tests: user code must not reach the host.

The reference runs Function / Builder / ``#`` code with bare ``exec``
in-process (code_execution.py:169-196, builder.py:84-105,
binary_execution.py:52-64). Our default ``sandbox_mode="subprocess"``
is a real jail: separate process, rlimits, cwd pinned to a scratch
dir, and an audit hook denying fs access outside
{scratch, interpreter tree}, process spawning, and sockets. These
tests drive the escape attempts the in-process namespace jail could
not stop (SURVEY §7 hard part #3).
"""

import numpy as np
import pytest

from learningorchestra_tpu.services import sandbox


def test_jail_normal_code_and_stdout(tmp_config):
    g, out = sandbox.run_user_code(
        "import numpy as np\n"
        "print('computed')\n"
        "response = {'x': np.arange(6, dtype='float32').reshape(2, 3)}\n",
        mode="subprocess")
    assert g["response"]["x"].shape == (2, 3)
    assert g["response"]["x"].dtype == np.float32
    assert "computed" in out


def test_jail_dataframe_params_cross_boundary(tmp_config):
    import pandas as pd

    df = pd.DataFrame({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    g, _ = sandbox.run_user_code(
        "response = {'vals': frame['a'].to_numpy() * 2,"
        " 'frame': frame[frame['a'] > 1]}",
        {"frame": df}, mode="subprocess")
    assert list(g["response"]["vals"]) == [2, 4, 6]
    assert list(g["response"]["frame"]["b"]) == ["y", "z"]


def test_jail_blocks_passwd_read_via_pandas(tmp_config):
    with pytest.raises(PermissionError, match="denied"):
        sandbox.run_user_code(
            "import pandas as pd\n"
            "response = pd.read_csv('/etc/passwd')\n",
            mode="subprocess")


def test_jail_blocks_passwd_read_via_numpy(tmp_config):
    with pytest.raises(PermissionError, match="denied"):
        sandbox.run_user_code(
            "import numpy as np\n"
            "response = np.loadtxt('/etc/passwd', dtype=str)\n",
            mode="subprocess")


def test_jail_blocks_dunder_escape_to_os_system(tmp_config, tmp_path):
    """The classic namespace-jail escape — object-graph traversal to a
    loader, then os.system — dies on the audit hook instead."""
    marker = tmp_path / "pwned"
    code = (
        "cls = [c for c in ().__class__.__base__.__subclasses__()"
        " if c.__name__ == 'BuiltinImporter'][0]\n"
        "os = cls().load_module('os')\n"
        f"response = os.system('touch {marker}')\n")
    with pytest.raises(PermissionError, match="os.system"):
        sandbox.run_user_code(code, mode="subprocess", lint=False)
    assert not marker.exists()


def test_jail_blocks_ctypes_ffi_escape(tmp_config, tmp_path):
    """ctypes is a total audit-hook bypass (raw libc calls fire no
    events) — the dlopen/call_function events themselves are denied."""
    marker = tmp_path / "escape_ctypes"
    code = (
        "cls = [c for c in ().__class__.__base__.__subclasses__()"
        " if c.__name__ == 'BuiltinImporter'][0]\n"
        "ct = cls().load_module('ctypes')\n"
        "libc = ct.CDLL(None)\n"
        f"response = libc.system(b'touch {marker}')\n")
    with pytest.raises(PermissionError, match="ctypes"):
        sandbox.run_user_code(code, mode="subprocess", lint=False)
    assert not marker.exists()


def test_jail_batched_hash_exprs_are_distinct_objects(tmp_config):
    """One child evaluates the whole batch; textually identical
    expressions still produce distinct spec objects (no aliasing)."""
    a, b = sandbox.eval_hash_expressions(
        ["#tensorflow.keras.optimizers.Adam(0.01)",
         "#tensorflow.keras.optimizers.Adam(0.01)"], mode="subprocess")
    assert a is not b
    assert a.spec == b.spec


def test_jail_blocks_write_outside_scratch(tmp_config, tmp_path):
    target = tmp_path / "leak.npy"
    code = (
        "cls = [c for c in ().__class__.__base__.__subclasses__()"
        " if c.__name__ == 'BuiltinImporter'][0]\n"
        "io_mod = cls().load_module('io')\n"
        f"f = io_mod.open('{target}', 'w')\n"
        "f.write('x')\n"
        "response = 1\n")
    with pytest.raises(PermissionError, match="denied"):
        sandbox.run_user_code(code, mode="subprocess", lint=False)
    assert not target.exists()


def test_jail_blocks_rename_out_of_scratch(tmp_config, tmp_path):
    """Write escape via multi-path events: create a file INSIDE scratch
    then os.rename / os.replace / shutil.move it onto an outside path.
    The hook must check every path argument, not just args[0]
    (advisor round-2 high finding)."""
    target = tmp_path / "renamed_out"
    for fn in ("os_mod.rename", "os_mod.replace"):
        code = (
            "cls = [c for c in ().__class__.__base__.__subclasses__()"
            " if c.__name__ == 'BuiltinImporter'][0]\n"
            "io_mod = cls().load_module('io')\n"
            "os_mod = cls().load_module('os')\n"
            "f = io_mod.open('inside.txt', 'w')\n"
            "f.write('x')\n"
            "f.close()\n"
            f"{fn}('inside.txt', '{target}')\n"
            "response = 1\n")
        with pytest.raises(PermissionError, match="denied"):
            sandbox.run_user_code(code, mode="subprocess", lint=False)
        assert not target.exists()


def test_jail_blocks_symlink_and_link_out(tmp_config, tmp_path):
    target = tmp_path / "linked_out"
    for call in (f"os_mod.link('inside.txt', '{target}')",
                 f"os_mod.symlink('inside.txt', '{target}')"):
        code = (
            "cls = [c for c in ().__class__.__base__.__subclasses__()"
            " if c.__name__ == 'BuiltinImporter'][0]\n"
            "io_mod = cls().load_module('io')\n"
            "os_mod = cls().load_module('os')\n"
            "f = io_mod.open('inside.txt', 'w')\n"
            "f.write('x')\n"
            "f.close()\n"
            f"{call}\n"
            "response = 1\n")
        with pytest.raises(PermissionError, match="denied"):
            sandbox.run_user_code(code, mode="subprocess", lint=False)
        assert not target.exists()


def test_jail_dropped_vars_surface_reason(tmp_config):
    """A live object assigned to `response` can't cross the boundary;
    the error must say so and point at the escalation path instead of
    the misleading 'must assign a response variable' (advisor round-2
    medium finding)."""
    g, _ = sandbox.run_user_code(
        "class Foo:\n"
        "    pass\n"
        "response = Foo()\n", mode="subprocess")
    assert "response" in g.get(sandbox.DROPPED_KEY, [])
    err = sandbox.missing_variable_error(
        g, "response", "function must assign a 'response' variable")
    assert isinstance(err, TypeError)
    assert "response" in str(err) and "restricted" in str(err)


def test_jail_import_allowlist_still_applies(tmp_config):
    with pytest.raises(ImportError):
        sandbox.run_user_code("import os\nresponse = 1",
                              mode="subprocess", lint=False)
    with pytest.raises(ImportError):
        sandbox.run_user_code("import subprocess\nresponse = 1",
                              mode="subprocess", lint=False)


def test_jail_hash_dsl_returns_spec_objects(tmp_config):
    opt = sandbox.eval_hash_expression(
        "#tensorflow.keras.optimizers.Adam(0.01)", mode="subprocess")
    assert type(opt).__name__ == "Adam"
    assert opt.spec["learning_rate"] == 0.01


def test_jail_runtime_errors_propagate_with_type(tmp_config):
    with pytest.raises(ValueError, match="boom"):
        sandbox.run_user_code("raise ValueError('boom')",
                              mode="subprocess")


def test_restricted_unpickler_blocks_gadgets(tmp_config):
    """A compromised child can write arbitrary bytes to the result
    file; the parent-side unpickler must refuse to resolve anything
    outside the tf_compat class allowlist (no pickle-gadget escapes
    back into the server process)."""
    import pickle

    class Evil:
        def __reduce__(self):
            return (print, ("gadget-fired",))

    raw = pickle.dumps({"vars": {"response": Evil()}, "stdout": ""})
    with pytest.raises(pickle.UnpicklingError, match="may not reference"):
        sandbox._safe_load_envelope(raw)

    # referencing module-level CALLABLES inside the framework is also
    # refused — only tf_compat classes resolve
    raw2 = pickle.dumps(sandbox.run_user_code)
    with pytest.raises(pickle.UnpicklingError):
        sandbox._safe_load_envelope(raw2)


def test_stored_model_through_function_capability_seam(tmp_config):
    """The reference's live-object Function flow (a stored model passed
    as a `$` parameter, code_execution.py:169-196): in the default
    subprocess jail the live object cannot cross and the job fails
    with a typed pointer at the escalation path; a per-request
    `sandboxMode: "restricted"` (within the operator ceiling) runs it
    in-process and succeeds; `trusted` is above the default ceiling
    and is rejected at POST time with 406."""
    import dataclasses

    import numpy as np

    from learningorchestra_tpu.services import validators as V
    from learningorchestra_tpu.services.context import ServiceContext
    from learningorchestra_tpu.services.function_service import (
        FunctionService)
    from learningorchestra_tpu.models.neural import NeuralModel

    # escalation is an operator opt-in: with the DEFAULT ceiling even
    # "restricted" is refused at POST time
    ctx0 = ServiceContext(tmp_config)
    try:
        with pytest.raises(V.HttpError) as exc0:
            FunctionService(ctx0).create({
                "name": "no_opt_in", "function": "response = 1",
                "functionParameters": {}, "sandboxMode": "restricted"})
        assert exc0.value.status == V.HTTP_NOT_ACCEPTABLE
    finally:
        ctx0.close()

    ctx = ServiceContext(dataclasses.replace(
        tmp_config, sandbox_max_mode="restricted"))
    try:
        model = NeuralModel([{"kind": "dense", "units": 2,
                              "activation": "softmax"}], name="m")
        model._build_params(np.zeros((1, 4), np.float32))
        ctx.catalog.create_collection("stored_model", "model/tensorflow",
                                      {})
        ctx.artifacts.save(model, "stored_model", "model/tensorflow")
        ctx.catalog.mark_finished("stored_model")
        fs = FunctionService(ctx)
        code = "response = float(model.num_params())"

        # 1. default jail: live object cannot cross -> typed error
        fs.create({"name": "live_default", "function": code,
                   "functionParameters": {"model": "$stored_model"}})
        ctx.jobs.wait("live_default", timeout=120)
        docs = ctx.catalog.get_documents("live_default")
        errs = [d.get("exception") for d in docs if d.get("exception")]
        assert errs and "restricted" in errs[0] and "TypeError" in errs[0]

        # 2. per-request escalation to restricted (within the default
        # ceiling) runs the same flow in-process
        fs.create({"name": "live_restricted", "function": code,
                   "functionParameters": {"model": "$stored_model"},
                   "sandboxMode": "restricted"})
        ctx.jobs.wait("live_restricted", timeout=120)
        assert ctx.catalog.get_metadata("live_restricted")["finished"]
        result = ctx.artifacts.load("live_restricted", "function/python")
        assert result == float(model.num_params())

        # 3. trusted exceeds the default ceiling -> 406 at POST time
        with pytest.raises(V.HttpError) as exc:
            fs.create({"name": "live_trusted", "function": code,
                       "functionParameters": {"model": "$stored_model"},
                       "sandboxMode": "trusted"})
        assert exc.value.status == V.HTTP_NOT_ACCEPTABLE
        assert "ceiling" in exc.value.message
    finally:
        ctx.close()


def test_jail_function_service_end_to_end(tmp_config):
    """FunctionService under the default (subprocess) mode: jobs fail
    closed on escape attempts and succeed on real work."""
    from learningorchestra_tpu.services.context import ServiceContext
    from learningorchestra_tpu.services.function_service import (
        FunctionService)

    ctx = ServiceContext(tmp_config)
    try:
        assert ctx.config.sandbox_mode == "subprocess"
        fs = FunctionService(ctx)
        fs.create({"name": "evil_read",
                   "function": "import pandas as pd\n"
                               "response = pd.read_csv('/etc/passwd')",
                   "functionParameters": {}})
        ctx.jobs.wait("evil_read", timeout=120)
        meta = ctx.catalog.get_metadata("evil_read")
        assert meta["finished"] is False
        docs = ctx.catalog.get_documents("evil_read")
        assert any("PermissionError" in (d.get("exception") or "")
                   for d in docs)
    finally:
        ctx.close()


# ----------------------------------------------------------------------
# restricted-mode runtime guards: dunder names smuggled as STRINGS
# through getattr/setattr/vars must die at run time even with the
# static lint off (dynamic names are invisible to the AST pass).
# The `lint=False` above/below is deliberate: these tests prove the
# RUNTIME layer holds on its own; submit-time rejection of the same
# payloads is covered in test_analysis.py.
# ----------------------------------------------------------------------
def test_restricted_getattr_blocks_dynamic_dunder_smuggle(tmp_config):
    code = (
        "name = '__cl' + 'ass__'\n"  # invisible to the AST lint
        "response = getattr((), name)\n")
    with pytest.raises(AttributeError, match="blocked"):
        sandbox.run_user_code(code, mode="restricted", lint=False)


def test_restricted_setattr_blocks_dunder_smuggle(tmp_config):
    code = (
        "class Foo:\n"
        "    pass\n"
        "setattr(Foo, '__getattr' + '__', lambda s, n: n)\n"
        "response = 1\n")
    with pytest.raises(AttributeError, match="blocked"):
        sandbox.run_user_code(code, mode="restricted", lint=False)


def test_restricted_vars_blocks_dict_access(tmp_config):
    code = (
        "class Foo:\n"
        "    pass\n"
        "response = vars(Foo)\n")
    with pytest.raises(TypeError, match="blocked"):
        sandbox.run_user_code(code, mode="restricted", lint=False)


def test_restricted_guards_allow_normal_attribute_use(tmp_config):
    g, _ = sandbox.run_user_code(
        "import math\n"
        "response = getattr(math, 'pi')\n"
        "class Box:\n"
        "    pass\n"
        "b = Box()\n"
        "setattr(b, 'x', 3)\n"
        "response = response + b.x\n", mode="restricted", lint=False)
    assert g["response"] > 6


def test_subprocess_jail_also_blocks_dynamic_dunder_smuggle(tmp_config):
    """The guarded builtins ship into the child process too."""
    code = (
        "name = '__subcl' + 'asses__'\n"
        "response = getattr((), '__class__', None) or "
        "getattr((), name)\n")
    with pytest.raises(AttributeError, match="blocked"):
        sandbox.run_user_code(code, mode="subprocess", lint=False)
