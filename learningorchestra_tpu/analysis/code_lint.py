"""AST safety lint for user-supplied code.

Screens Function-service code and ``#``-DSL expressions BEFORE any
``exec`` (reference executes blind: code_execution.py:169-196,
binary_execution.py:52-64). The rules mirror the sandbox's runtime
jail (:mod:`learningorchestra_tpu.services.sandbox`) so a request that
would die inside the job is rejected at submit time with the finding
list in the 406 body — and escape attempts are refused even in the
``trusted``/``restricted`` modes whose runtime jail is weaker.

Rules (ids are stable; see docs/ANALYSIS.md):

- ``syntax-error`` — code does not parse. Error in every mode.
- ``forbidden-import`` — import outside the sandbox module whitelist
  (or a relative import). Error under ``subprocess``/``restricted``
  where the runtime would refuse it anyway; advisory warning under
  ``trusted``.
- ``forbidden-call`` — call to an exec-family builtin the sandbox
  withholds (``eval``, ``exec``, ``__import__``, ``open``, …). Same
  mode policy as ``forbidden-import``.
- ``dunder-attribute`` — attribute traversal through an
  escape-capable dunder (``__class__``, ``__subclasses__``,
  ``__globals__``, …). Error in EVERY mode: there is no legitimate
  use in pipeline code and it defeats the in-process jails.
- ``dunder-string-smuggle`` — the same dunders smuggled as constant
  strings through ``getattr``/``setattr``/``delattr``. Error in every
  mode (dynamic names are caught at runtime by the restricted-mode
  guard in sandbox.py).
- ``tpu-sync-in-loop`` — ``.block_until_ready()`` inside a Python
  loop (forces a device round-trip per iteration). Warning.
- ``tpu-traced-branch`` — Python ``if``/``while`` on an argument of a
  jitted function (traced values have no runtime truth value; this
  either fails under jit or silently bakes in one branch). Warning.

Anything the linter cannot model is permitted, never rejected — the
rules above only fire on positively identified constructs.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from learningorchestra_tpu.analysis.findings import (
    Finding,
    LintRejected,
    SEVERITY_ERROR,
    SEVERITY_WARNING,
    error_findings,
)
from learningorchestra_tpu.services.sandbox import (
    DANGEROUS_DUNDERS,  # noqa: F401 — re-exported; single source of truth
    _ALLOWED_MODULE_PREFIXES,
    _SHIMMED_MODULES,
)

# exec-family builtins the sandbox withholds (_SAFE_BUILTIN_NAMES);
# calling them is either a NameError-to-be (restricted/subprocess) or
# an open door (trusted)
_FORBIDDEN_CALLS = frozenset({
    "eval", "exec", "__import__", "open", "compile", "globals",
    "locals", "breakpoint", "input",
})

# getattr/setattr/delattr can smuggle a dunder as a string
_ATTR_SMUGGLERS = frozenset({"getattr", "setattr", "delattr"})

_JIT_NAMES = frozenset({"jit", "pjit"})


def module_allowed(name: str) -> bool:
    """Mirror of sandbox._restricted_import's whitelist decision."""
    root = name.split(".")[0]
    if root in _SHIMMED_MODULES or name in _SHIMMED_MODULES:
        return True
    return any(root == p for p in _ALLOWED_MODULE_PREFIXES)


def _is_jit_decorator(node: ast.expr) -> bool:
    """``@jit`` / ``@jax.jit`` / ``@partial(jax.jit, ...)`` etc."""
    if isinstance(node, ast.Name):
        return node.id in _JIT_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr in _JIT_NAMES
    if isinstance(node, ast.Call):
        if _is_jit_decorator(node.func):
            return True
        return any(_is_jit_decorator(a) for a in node.args)
    return False


class _Walker(ast.NodeVisitor):
    def __init__(self, blocking_severity: str):
        self.findings: List[Finding] = []
        # severity of forbidden-import/forbidden-call in this mode
        self._blocking = blocking_severity
        self._loop_depth = 0
        # argument names of the innermost jitted function, if any
        self._jit_args: List[set] = []

    def _add(self, severity: str, rule: str, node: ast.AST,
             message: str) -> None:
        loc = f"line {getattr(node, 'lineno', '?')}:" \
              f"{getattr(node, 'col_offset', '?')}"
        self.findings.append(Finding(severity, rule, loc, message))

    # -- imports -------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if not module_allowed(alias.name):
                self._add(self._blocking, "forbidden-import", node,
                          f"import of {alias.name!r} is outside the "
                          f"sandbox module whitelist")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.level != 0:
            self._add(self._blocking, "forbidden-import", node,
                      "relative imports are not allowed in sandboxed "
                      "code")
        elif node.module and not module_allowed(node.module):
            self._add(self._blocking, "forbidden-import", node,
                      f"import from {node.module!r} is outside the "
                      f"sandbox module whitelist")
        self.generic_visit(node)

    # -- calls ---------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Name):
            if func.id in _FORBIDDEN_CALLS:
                self._add(self._blocking, "forbidden-call", node,
                          f"call to {func.id}() is not available in "
                          f"sandboxed code")
            if func.id in _ATTR_SMUGGLERS:
                self._check_smuggle(node, func.id)
        if isinstance(func, ast.Attribute) and \
                func.attr == "block_until_ready" and self._loop_depth:
            self._add(SEVERITY_WARNING, "tpu-sync-in-loop", node,
                      ".block_until_ready() inside a Python loop "
                      "forces a host-device sync every iteration; "
                      "hoist it after the loop")
        self.generic_visit(node)

    def _check_smuggle(self, node: ast.Call, fname: str) -> None:
        if len(node.args) < 2:
            return
        name_arg = node.args[1]
        if isinstance(name_arg, ast.Constant) and \
                isinstance(name_arg.value, str) and \
                name_arg.value in DANGEROUS_DUNDERS:
            self._add(SEVERITY_ERROR, "dunder-string-smuggle", node,
                      f"{fname}(..., {name_arg.value!r}) smuggles an "
                      f"escape-capable dunder attribute by name")

    # -- attribute traversal -------------------------------------------
    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr in DANGEROUS_DUNDERS:
            self._add(SEVERITY_ERROR, "dunder-attribute", node,
                      f"attribute access .{node.attr} reaches "
                      f"interpreter internals and is refused in user "
                      f"code")
        self.generic_visit(node)

    # -- loops / jitted branches ---------------------------------------
    def _visit_loop(self, node: ast.AST) -> None:
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    def visit_For(self, node: ast.For) -> None:
        self._visit_loop(node)

    def visit_While(self, node: ast.While) -> None:
        if self._jit_args:
            self._check_traced_test(node, node.test)
        self._visit_loop(node)

    def visit_If(self, node: ast.If) -> None:
        if self._jit_args:
            self._check_traced_test(node, node.test)
        self.generic_visit(node)

    def _check_traced_test(self, node: ast.AST, test: ast.expr) -> None:
        args = self._jit_args[-1]
        names = {n.id for n in ast.walk(test) if isinstance(n, ast.Name)}
        traced = sorted(names & args)
        if traced:
            self._add(SEVERITY_WARNING, "tpu-traced-branch", node,
                      f"Python branch on traced value(s) "
                      f"{', '.join(traced)} inside a jitted function; "
                      f"use jax.lax.cond/select instead")

    def _visit_function(self, node) -> None:
        jitted = any(_is_jit_decorator(d) for d in node.decorator_list)
        if jitted:
            a = node.args
            names = {p.arg for p in (a.posonlyargs + a.args
                                     + a.kwonlyargs)}
            self._jit_args.append(names)
            self.generic_visit(node)
            self._jit_args.pop()
        else:
            self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_function(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._visit_function(node)


def lint_code(code: str, mode: str = "subprocess",
              filename: str = "<user-code>") -> List[Finding]:
    """Lint ``code`` under sandbox trust level ``mode``
    (``subprocess`` / ``restricted`` / ``trusted``). Returns all
    findings; never raises on bad user code (a parse failure is
    itself a finding)."""
    try:
        tree = ast.parse(code, filename=filename)
    except SyntaxError as e:
        loc = f"line {e.lineno or '?'}:{(e.offset or 1) - 1}"
        return [Finding(SEVERITY_ERROR, "syntax-error", loc,
                        f"code does not parse: {e.msg}")]
    # trusted mode is the reference's trust model: imports/builtins
    # outside the whitelist still WORK there, so they only warn;
    # dunder traversal stays an error in every mode
    blocking = SEVERITY_WARNING if mode == "trusted" else SEVERITY_ERROR
    walker = _Walker(blocking_severity=blocking)
    walker.visit(tree)
    return walker.findings


def assert_code_safe(code: str, mode: Optional[str] = None,
                     filename: str = "<user-code>") -> List[Finding]:
    """Lint and raise :class:`LintRejected` if any error-severity
    finding fired; otherwise return the (warning-only) findings for
    the caller to store with the job."""
    if mode is None:
        from learningorchestra_tpu.config import get_config

        mode = get_config().sandbox_mode
    findings = lint_code(code, mode=mode, filename=filename)
    if error_findings(findings):
        raise LintRejected(findings)
    return findings
