"""Elastic slice autoscaler (docs/SCALING.md "Elastic autoscaling"):
pure policy targets never violate declared bounds, the closed loop
shrinks a running elastic job under aged-waiter pressure so the
waiter lands, resizes ride the migration path bit-identically, the
``autoscale_resize`` fault site rolls back to the old slice (transient
retries succeed; a latched fault dead-letters only the RESIZE ledger
while the job finishes untouched), and a racing defrag pick coalesces
with an in-flight resize."""

import dataclasses
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from learningorchestra_tpu.runtime import preempt
from learningorchestra_tpu.services.autoscaler import (
    SliceAutoscaler, grow_target, shrink_target)


def _token(devices, elastic):
    token = preempt.CancelToken()
    token.slice_devices = tuple(range(devices))
    token.migratable = True
    token.elastic = elastic
    return token


class _FakeJobs:
    """Just enough JobManager surface for SliceAutoscaler.tick():
    the coordinator candidate set, scheduler stats, and the resize
    latch (backed by REAL CancelTokens, so inflight/bounds semantics
    are the production ones)."""

    def __init__(self, tokens, stats):
        self.tokens = tokens
        self.stats = stats
        self.requests = []

    @property
    def migration(self):
        return self

    def elastic_jobs(self):
        return sorted(self.tokens.items())

    def scheduler_stats(self):
        return dict(self.stats)

    def request_resize(self, name, want, reason="autoscale"):
        ok = self.tokens[name].request_resize(int(want), reason)
        if ok:
            self.requests.append((name, int(want), reason))
        return ok


# ----------------------------------------------------------------------
# pure policy targets: property-style sweeps over the whole small grid
# ----------------------------------------------------------------------
def test_shrink_target_never_below_min():
    for current in range(1, 17):
        for min_d in range(1, 17):
            want = shrink_target(current, min_d)
            if want is None:
                assert current <= max(1, min_d)
            else:
                assert max(1, min_d) <= want < current


def test_grow_target_bounded_by_max_capacity_and_gang_line():
    for current in range(1, 17):
        for max_d in range(1, 17):
            for free in range(0, 17):
                for total in range(2, 17):
                    want = grow_target(current, max_d, free, total)
                    if want is None:
                        continue
                    assert current < want <= max_d
                    assert want <= current + free
                    # never a whole-mesh want: that would convert the
                    # job to an unresizable gang grant
                    assert want < total


def test_token_rejects_out_of_bounds_resize():
    token = _token(4, (2, 6))
    assert token.request_resize(1) is False  # below min
    assert token.request_resize(7) is False  # above max
    assert token.request_resize(2) is True
    # one placement change per job: second request coalesces
    assert token.request_resize(3) is False
    token.resize_done(True, (0, 1))
    assert token.resizes == 1
    assert token.request_resize(4) is True


# ----------------------------------------------------------------------
# policy loop over fake jobs (deterministic single ticks)
# ----------------------------------------------------------------------
def _autoscaler(jobs, **kw):
    kw.setdefault("interval_seconds", 60.0)  # never self-ticks
    kw.setdefault("backoff_seconds", 0.0)
    return SliceAutoscaler(jobs, **kw)


def test_shrinks_largest_job_on_aged_waiter_pressure():
    jobs = _FakeJobs(
        {"small": _token(4, (1, 8)), "big": _token(6, (2, 8))},
        {"sliced": True, "agedWaiters": 1, "waiters": 1,
         "devicesFree": 0, "devicesTotal": 8})
    scaler = _autoscaler(jobs)
    assert scaler.tick() == "big"
    assert jobs.requests == [("big", 3, "shrink:agedWaiters")]
    assert jobs.tokens["big"].resize_want == 3


def test_never_shrinks_below_declared_min():
    jobs = _FakeJobs(
        {"a": _token(2, (2, 8))},
        {"sliced": True, "agedWaiters": 1, "waiters": 1,
         "devicesFree": 0, "devicesTotal": 8})
    scaler = _autoscaler(jobs)
    assert scaler.tick() is None
    assert jobs.requests == []


def test_grows_smallest_job_on_quiet_cluster():
    jobs = _FakeJobs(
        {"small": _token(2, (1, 8)), "big": _token(4, (1, 8))},
        {"sliced": True, "agedWaiters": 0, "waiters": 0,
         "devicesFree": 2, "devicesTotal": 8})
    scaler = _autoscaler(jobs)
    assert scaler.tick() == "small"
    assert jobs.requests == [("small", 4, "grow:quietCluster")]


def test_no_grow_while_waiters_or_pages():
    class _PagingWatchdog:
        def page_firing(self):
            return True

    jobs = _FakeJobs(
        {"a": _token(2, (1, 8))},
        {"sliced": True, "agedWaiters": 0, "waiters": 1,
         "devicesFree": 4, "devicesTotal": 8})
    assert _autoscaler(jobs).tick() is None  # waiter present
    # a firing PAGE alert (serving p99 burn / hbm headroom floor)
    # flips the policy to shrink even with free devices
    jobs2 = _FakeJobs(
        {"a": _token(4, (1, 8))},
        {"sliced": True, "agedWaiters": 0, "waiters": 0,
         "devicesFree": 4, "devicesTotal": 8})
    scaler2 = _autoscaler(jobs2, watchdog_fn=lambda: _PagingWatchdog())
    assert scaler2.tick() == "a"
    assert jobs2.requests == [("a", 2, "shrink:sloPage")]


def test_rollbacks_back_off_then_dead_letter_resize_ledger():
    jobs = _FakeJobs(
        {"a": _token(8, (1, 8))},
        {"sliced": True, "agedWaiters": 1, "waiters": 1,
         "devicesFree": 0, "devicesTotal": 8})
    scaler = _autoscaler(jobs, retries=2)
    assert scaler.tick() == "a"
    # the engine's failure ladder: rollback, job keeps training
    jobs.tokens["a"].resize_done(False, tuple(range(8)),
                                 error="injected")
    # zero backoff: the settling tick immediately retries
    assert scaler.tick() == "a"
    assert scaler.stats()["counters"]["rollbacks"] == 1
    jobs.tokens["a"].resize_done(False, tuple(range(8)),
                                 error="injected")
    assert scaler.tick() is None  # budget burnt -> no retry latched
    assert scaler.stats()["counters"]["rollbacks"] == 2
    # budget exhausted: the RESIZE ledger is dead-lettered — no more
    # requests for this job, but nothing cancelled the job itself
    assert scaler.stats()["counters"]["deadLettered"] == 1
    n = len(jobs.requests)
    assert scaler.tick() is None
    assert len(jobs.requests) == n
    assert not jobs.tokens["a"].cancelled()
    ledger = scaler.stats()["jobs"]["a"]
    assert ledger["dead"] is True and ledger["attempts"] == 2


def test_successful_resize_resets_backoff_curve():
    jobs = _FakeJobs(
        {"a": _token(8, (1, 8))},
        {"sliced": True, "agedWaiters": 1, "waiters": 1,
         "devicesFree": 0, "devicesTotal": 8})
    scaler = _autoscaler(jobs, retries=3)
    assert scaler.tick() == "a"
    jobs.tokens["a"].resize_done(False, None, error="race")
    # zero backoff: the settling tick retries in the same pass
    assert scaler.tick() == "a"
    assert scaler.stats()["jobs"]["a"]["attempts"] == 1
    jobs.tokens["a"].slice_devices = tuple(range(4))
    jobs.tokens["a"].resize_done(True, tuple(range(4)))
    scaler.tick()
    ledger = scaler.stats()["jobs"]["a"]
    assert ledger["attempts"] == 0 and ledger["dead"] is False
    assert scaler.stats()["counters"]["shrinksCompleted"] == 1


# ----------------------------------------------------------------------
# defrag vs resize race: one placement change per job (satellite 3)
# ----------------------------------------------------------------------
class _Registry:
    """Minimal JobManager registry surface MigrationCoordinator
    reads (lock + job_info + live futures)."""

    def __init__(self, tokens):
        self._lock = threading.Lock()
        self._job_info = {name: {"needs_mesh": True, "token": token}
                          for name, token in tokens.items()}
        self._futures = {name: Future() for name in tokens}


def test_defrag_and_resize_coalesce_to_one_placement_change():
    from learningorchestra_tpu.services.migration import (
        MigrationCoordinator)

    token = _token(4, (2, 6))
    coord = MigrationCoordinator(_Registry({"a": token}))
    assert coord.request_resize("a", 2) is True
    # a defrag pick racing the in-flight resize coalesces: refusal,
    # not a double move
    assert coord.request("a", "defrag") is False
    assert coord.defrag_pick() is None
    assert coord.request_resize("a", 3) is False
    stats = coord.stats()
    assert stats["resizesRequested"] == 1
    assert stats["resizesRefused"] == 1 and stats["refused"] == 1
    # outcome reported -> the next placement change may proceed
    token.slice_devices = tuple(range(2))
    token.resize_done(True, (0, 1))
    assert coord.request("a", "defrag") is True
    # and the reverse order: a latched plain migrate blocks a resize
    token2 = _token(4, (2, 6))
    coord2 = MigrationCoordinator(_Registry({"b": token2}))
    assert coord2.request("b", "defrag") is True
    assert coord2.request_resize("b", 2) is False


def test_non_elastic_job_is_never_resized():
    from learningorchestra_tpu.services.migration import (
        MigrationCoordinator)

    token = _token(4, None)
    coord = MigrationCoordinator(_Registry({"rigid": token}))
    assert coord.elastic_jobs() == []
    assert coord.request_resize("rigid", 2) is False
    assert coord.stats()["resizesRefused"] == 1


# ----------------------------------------------------------------------
# end-to-end over the real engine/scheduler (8-device CPU mesh)
# ----------------------------------------------------------------------
def _make_jobs(catalog, **kw):
    from learningorchestra_tpu.services.jobs import JobManager

    kw.setdefault("max_workers", 4)
    kw.setdefault("mesh_leases", 2)
    return JobManager(catalog, **kw)


def _fit_job(ckpt_dir, epochs, sink):
    """Deterministic linear fit (same as tests/test_migration.py):
    two runs must end bit-identical regardless of mid-run resizes."""
    import jax.numpy as jnp
    import optax

    from learningorchestra_tpu.runtime import data as data_lib
    from learningorchestra_tpu.runtime import mesh as mesh_lib
    from learningorchestra_tpu.runtime.checkpoint import Checkpointer
    from learningorchestra_tpu.runtime.engine import (
        Engine, mse_loss, to_host)

    rng = np.random.RandomState(0)
    x = rng.randn(64, 4).astype(np.float32)
    y = (x @ np.array([[1.0], [2.0], [-1.0], [0.5]],
                      np.float32))[:, 0]

    def apply_fn(params, model_state, batch, train, step_rng):
        return batch["x"] @ params["w"], model_state

    def job():
        eng = Engine(apply_fn=apply_fn, loss_fn=mse_loss,
                     optimizer=optax.sgd(0.05),
                     mesh=mesh_lib.current_mesh(),
                     compute_dtype=jnp.float32, donate_state=False)
        state = eng.init_state({"w": jnp.zeros((4,), jnp.float32)})
        batcher = data_lib.ArrayBatcher({"x": x, "y": y},
                                        batch_size=16, seed=3)
        ckpt = Checkpointer(ckpt_dir)
        try:
            state, _ = eng.fit(state, batcher, epochs=epochs, seed=7,
                               checkpointer=ckpt, scan_batches=False)
        finally:
            ckpt.close()
        host = to_host(state)
        sink.append(host)
        return int(host.step)

    return job


_ELASTIC_FP = {"devices": 4, "elastic": {"min": 2, "max": 4}}


def _resize_until_accepted(jobs, name, want, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if jobs.request_resize(name, want):
            return True
        time.sleep(0.02)
    return False


def _wait_counter(token, attr, value, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if getattr(token, attr) >= value:
            return True
        time.sleep(0.02)
    return False


def test_elastic_resized_twice_bit_identical(tmp_path, catalog):
    """Shrink 4→2 then grow 2→4 mid-fit: the final params must equal
    a rigid run's bit-for-bit (fold_in replay over the re-sharded
    batches), and the job's sliceHistory records both resizes."""
    jobs = _make_jobs(catalog)
    try:
        results = {}
        for tag in ("base", "ela"):
            name = f"as_{tag}"
            catalog.create_collection(name, "train/neural")
            sink = []
            results[tag] = sink
            jobs.submit(
                name, _fit_job(str(tmp_path / tag), 6, sink),
                needs_mesh=True, pool="train",
                footprint=(dict(_ELASTIC_FP) if tag == "ela"
                           else {"devices": 4}))
            if tag == "ela":
                token = jobs._job_info[name]["token"]
                assert _resize_until_accepted(jobs, name, 2)
                assert _wait_counter(token, "resizes", 1)
                assert len(token.slice_devices) == 2
                assert _resize_until_accepted(jobs, name, 4)
                assert _wait_counter(token, "resizes", 2)
                assert len(token.slice_devices) == 4
            jobs.wait(name, timeout=180)
        base, ela = results["base"][0], results["ela"][0]
        assert int(base.step) == int(ela.step)
        np.testing.assert_array_equal(np.asarray(base.params["w"]),
                                      np.asarray(ela.params["w"]))
        events = [e["event"] for e in token.slice_history]
        assert events.count("resize") == 2
        assert token.resize_rollbacks == 0
        meta = catalog.get_metadata("as_ela")
        assert [e["event"] for e in meta["sliceHistory"]].count(
            "resize") == 2
    finally:
        jobs.shutdown()


def test_resize_fault_transient_rolls_back_then_retry_succeeds(
        tmp_path, tmp_config, catalog, monkeypatch):
    """``autoscale_resize:1:raise`` fires inside the guarded region:
    the resize rolls back (old slice, job keeps training, incident
    fired with resize context), the budget is spent, and the NEXT
    request succeeds — final params bit-identical to a rigid run."""
    from learningorchestra_tpu import config as config_mod
    from learningorchestra_tpu.observability import (
        incidents as obs_incidents)
    from learningorchestra_tpu.services import faults

    config_mod.set_config(dataclasses.replace(
        tmp_config, fault_inject="autoscale_resize:1:raise"))
    faults.reset()
    fired = []
    monkeypatch.setattr(
        obs_incidents, "trigger",
        lambda name, **context: fired.append((name, context)) or False)
    jobs = _make_jobs(catalog)
    try:
        results = {}
        for tag in ("base", "chaos"):
            name = f"asf_{tag}"
            catalog.create_collection(name, "train/neural")
            sink = []
            results[tag] = sink
            jobs.submit(
                name, _fit_job(str(tmp_path / tag), 6, sink),
                needs_mesh=True, pool="train",
                footprint=(dict(_ELASTIC_FP) if tag == "chaos"
                           else {"devices": 4}))
            if tag == "chaos":
                token = jobs._job_info[name]["token"]
                assert _resize_until_accepted(jobs, name, 2)
                assert _wait_counter(token, "resize_rollbacks", 1)
                # rolled back to an old-size slice, still training
                assert len(token.slice_devices) == 4
                assert not token.cancelled()
                # retry: the transient budget is spent, so it lands
                assert _resize_until_accepted(jobs, name, 2)
                assert _wait_counter(token, "resizes", 1)
                assert len(token.slice_devices) == 2
            jobs.wait(name, timeout=180)
        base, chaos = results["base"][0], results["chaos"][0]
        assert int(base.step) == int(chaos.step)
        np.testing.assert_array_equal(np.asarray(base.params["w"]),
                                      np.asarray(chaos.params["w"]))
        rollbacks = [c for n, c in fired if n == "autoscaler:rollback"]
        assert rollbacks and rollbacks[0]["want"] == 2
        assert "InjectedFault" in rollbacks[0]["error"]
        assert any(e["event"] == "rollback"
                   for e in token.slice_history)
    finally:
        faults.reset()
        jobs.shutdown()


def test_resize_fault_latched_never_kills_the_job(
        tmp_path, tmp_config, catalog):
    """A LATCHED ``autoscale_resize`` fault (large count) fails every
    resize attempt: each rolls back to the old slice, and the job
    itself still finishes bit-identically — only the resize requests
    die."""
    from learningorchestra_tpu import config as config_mod
    from learningorchestra_tpu.services import faults

    config_mod.set_config(dataclasses.replace(
        tmp_config, fault_inject="autoscale_resize:99:raise"))
    faults.reset()
    jobs = _make_jobs(catalog)
    try:
        results = {}
        for tag in ("base", "latch"):
            name = f"asl_{tag}"
            catalog.create_collection(name, "train/neural")
            sink = []
            results[tag] = sink
            jobs.submit(
                name, _fit_job(str(tmp_path / tag), 6, sink),
                needs_mesh=True, pool="train",
                footprint=(dict(_ELASTIC_FP) if tag == "latch"
                           else {"devices": 4}))
            if tag == "latch":
                token = jobs._job_info[name]["token"]
                for attempt in (1, 2):
                    assert _resize_until_accepted(jobs, name, 2)
                    assert _wait_counter(token, "resize_rollbacks",
                                         attempt)
                    assert len(token.slice_devices) == 4
            jobs.wait(name, timeout=180)
        base, latch = results["base"][0], results["latch"][0]
        assert int(base.step) == int(latch.step)
        np.testing.assert_array_equal(np.asarray(base.params["w"]),
                                      np.asarray(latch.params["w"]))
        assert token.resizes == 0 and token.resize_rollbacks == 2
    finally:
        faults.reset()
        jobs.shutdown()


def test_closed_loop_shrink_places_aged_waiter(catalog):
    """The tentpole loop end-to-end: an elastic holder on 6/8 devices
    blocks a 4-device waiter; the running autoscaler sees the AGED
    waiter, shrinks the holder 6→3 (never preempt-kills it), and the
    waiter lands while the holder keeps running."""
    jobs = _make_jobs(catalog, slice_aging_seconds=0.3)
    scaler = SliceAutoscaler(jobs, interval_seconds=0.1,
                             backoff_seconds=0.1).start()
    started = threading.Event()
    stop = threading.Event()

    def holder():
        started.set()
        token = preempt.current_cancel()
        while not stop.is_set():
            if preempt.migrate_requested():
                want = token.resize_want
                performed, devices = preempt.perform_migrate()
                if performed and want is not None:
                    # the engine's success report, minus the engine
                    token.resize_done(True, devices)
            time.sleep(0.02)
        return "held"

    try:
        catalog.create_collection("as_holder", "train/neural")
        catalog.create_collection("as_waiter", "train/neural")
        jobs.submit("as_holder", holder, needs_mesh=True, pool="train",
                    footprint={"devices": 6,
                               "elastic": {"min": 2, "max": 6}})
        assert started.wait(timeout=30)
        jobs.submit("as_waiter", lambda: "landed", needs_mesh=True,
                    pool="train", footprint={"devices": 4})
        # only a shrink can make room — the holder never exits on its
        # own and is never cancelled
        assert jobs.wait("as_waiter", timeout=60) == "landed"
        token = jobs._job_info["as_holder"]["token"]
        assert not token.cancelled()
        assert token.resizes >= 1
        counters = scaler.stats()["counters"]
        assert counters["shrinksRequested"] >= 1
        # the ledger settles on the NEXT tick after the engine reports
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            counters = scaler.stats()["counters"]
            if counters["shrinksCompleted"] + counters["rollbacks"] >= 1:
                break
            time.sleep(0.05)
        assert counters["shrinksCompleted"] + counters["rollbacks"] >= 1
    finally:
        scaler.stop()
        stop.set()
        try:
            jobs.wait("as_holder", timeout=30)
        finally:
            jobs.shutdown()


def test_scheduler_fairness_holds_with_elastic_jobs(catalog):
    """Aging anti-starvation still applies when elastic jobs are in
    the mix: a gang job enqueued behind a stream of sliced elastic
    jobs is not starved (grant order honors the aging freeze)."""
    jobs = _make_jobs(catalog, slice_aging_seconds=0.2)
    stop = threading.Event()

    def looper():
        while not stop.is_set():
            time.sleep(0.02)
        return "loop"

    try:
        catalog.create_collection("fair_e", "train/neural")
        jobs.submit("fair_e", looper, needs_mesh=True, pool="train",
                    footprint={"devices": 4,
                               "elastic": {"min": 2, "max": 4}})
        time.sleep(0.1)
        catalog.create_collection("fair_gang", "train/neural")
        gang = jobs.submit("fair_gang", lambda: "gang",
                           needs_mesh=True, pool="tune")
        # the gang job needs EVERY device; it can only land after the
        # elastic holder exits — but it must not be starved by fresh
        # sliced submissions once aged
        for i in range(3):
            catalog.create_collection(f"fair_s{i}", "train/neural")
            jobs.submit(f"fair_s{i}", lambda: "s", needs_mesh=True,
                        pool="train", footprint={"devices": 2})
        stop.set()
        jobs.wait("fair_e", timeout=30)
        assert gang.result(timeout=30) == "gang"
        for i in range(3):
            jobs.wait(f"fair_s{i}", timeout=30)
    finally:
        stop.set()
        jobs.shutdown()


# ----------------------------------------------------------------------
# REST surface + request validation
# ----------------------------------------------------------------------
def test_valid_slice_devices_elastic_bounds():
    from learningorchestra_tpu.services import validators as V

    assert V.valid_slice_devices({"min": 2, "max": 6}) == \
        {"min": 2, "max": 6}
    assert V.valid_slice_devices(3) == 3
    assert V.valid_slice_devices(None) is None
    for bad in ({"min": 0, "max": 4}, {"min": 2},
                {"min": 4, "max": 2}, {"min": 2, "max": 4, "x": 1},
                {"min": True, "max": 4}, {"min": 1.5, "max": 4},
                True, -1, "4"):
        with pytest.raises(V.HttpError):
            V.valid_slice_devices(bad)


def test_rest_observability_autoscaler(tmp_config):
    from learningorchestra_tpu.services.server import Api

    api = Api()
    prefix = tmp_config.api_prefix
    try:
        status, body, _ = api.dispatch(
            "GET", f"{prefix}/observability/autoscaler", {}, None)
        assert status == 200, body
        assert "counters" in body and "migration" in body
        # prometheus exposition carries the new counter families
        text = api.metrics_prometheus().decode()
        assert 'lo_autoscaler_resizes_total{direction="shrink"}' in text
        assert "lo_autoscaler_rollbacks_total" in text
    finally:
        api.ctx.close()
