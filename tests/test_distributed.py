"""Multi-host runtime: REAL 2-process jax.distributed formation on the
CPU backend — global device view, a cross-host collective, and a
HostBridge publish/follow round-trip."""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

from conftest import JAX_CACHE_ENV
from learningorchestra_tpu.runtime import distributed as dist


def test_single_host_noop(monkeypatch):
    monkeypatch.delenv("LO_COORDINATOR", raising=False)
    monkeypatch.delenv("LO_NUM_HOSTS", raising=False)
    assert dist.initialize() is False


def test_host_info_single():
    info = dist.host_info()
    assert info["processCount"] == 1
    assert info["processIndex"] == 0
    assert info["globalDevices"] >= 1


_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, "@REPO@")
    from learningorchestra_tpu.runtime import distributed as dist

    ok = dist.initialize(coordinator_address="@COORD@",
                         num_processes=2, process_id=@PID@)
    assert ok
    info = dist.host_info()
    assert info["processCount"] == 2, info
    assert info["globalDevices"] == 4, info

    # cross-host collective over the global mesh
    import jax.numpy as jnp
    from jax.experimental import multihost_utils as mhu
    total = mhu.process_allgather(jnp.asarray([info["processIndex"]]))
    assert sorted(int(x) for x in total.ravel()) == [0, 1], total

    bridge = dist.HostBridge()
    if info["processIndex"] == 0:
        bridge.publish({"op": "custom", "value": 41})
        bridge.publish({"op": "shutdown"})
    else:
        seen = []
        bridge.follow(lambda m: seen.append(m["value"]))
        assert seen == [41], seen
    print("HOST_OK", info["processIndex"])
""")


def test_two_process_formation_and_bridge(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    procs = []
    for pid in range(2):
        script = (_WORKER.replace("@REPO@", "/root/repo")
                  .replace("@COORD@", coord).replace("@PID@", str(pid)))
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env={"PATH": "/usr/bin:/bin", **JAX_CACHE_ENV}))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out.decode(errors="replace"))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"host {pid} failed:\n{out}"
        assert f"HOST_OK {pid}" in out


_TRAIN = textwrap.dedent("""
    import os, sys, json
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["LO_HOME"] = "@HOME@"
    os.environ["LO_MESH_SHAPE"] = "auto"
    os.environ["LO_COMPUTE_DTYPE"] = "float32"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, "@REPO@")
    from learningorchestra_tpu.runtime import distributed as dist

    assert dist.initialize(coordinator_address="@COORD@",
                           num_processes=2, process_id=@PID@)
    assert jax.device_count() == 4

    if @PID@ == 0:
        import time
        from learningorchestra_tpu.services.server import Api
        api = Api()
        prefix = "/api/learningOrchestra/v1"

        def wait(uri):
            for _ in range(600):
                st, body, _h = api.dispatch("GET", uri, {"limit": "1"}, None)
                if st == 200 and body["metadata"].get("finished"):
                    return
                docs = api.ctx.catalog.get_documents(
                    uri.rstrip("/").split("/")[-1])
                errs = [d["exception"] for d in docs if d.get("exception")]
                assert not errs, errs
                time.sleep(0.2)
            raise SystemExit("timeout: " + uri)

        st, body, _h = api.dispatch("POST", prefix + "/function/python", {}, {
            "name": "mh_data", "functionParameters": {},
            "function": ("import numpy as np\\n"
                         "rng = np.random.default_rng(0)\\n"
                         "x = rng.normal(size=(32, 8)).astype(np.float32)\\n"
                         "y = (x[:, 0] > 0).astype(np.int32)\\n"
                         "response = {'x': x, 'y': y}\\n")})
        assert st == 201, body
        wait(body["result"])

        st, body, _h = api.dispatch("POST", prefix + "/model/tensorflow", {}, {
            "modelName": "mh_model",
            "modulePath": "learningorchestra_tpu.models",
            "class": "NeuralModel",
            "classParameters": {"layer_configs": [
                {"kind": "dense", "units": 8, "activation": "relu"},
                {"kind": "dense", "units": 2, "activation": "softmax"}]}})
        assert st == 201, body
        wait(body["result"])

        st, body, _h = api.dispatch("POST", prefix + "/train/tensorflow", {}, {
            "name": "mh_train", "modelName": "mh_model", "method": "fit",
            "methodParameters": {"x": "$mh_data.x", "y": "$mh_data.y",
                                 "epochs": 2, "batch_size": 8}})
        assert st == 201, body
        wait(body["result"])
        trained = api.ctx.artifacts.load("mh_train", "train/tensorflow")
        assert trained.history, "no training history"
        dist.HostBridge().publish({"op": "shutdown"})
        api.ctx.jobs.shutdown()
    else:
        dist.HostBridge().follow(lambda m: None)
    print("TRAIN_OK", @PID@)
""")


def test_two_process_entry_point_serves_rest(tmp_path):
    """The packaged launcher (`lo-server` / `python -m
    learningorchestra_tpu`, docs/DEPLOY.md): two processes form a pod
    via CLI flags; the coordinator serves REST and answers /health
    with the pod topology; a /train round-trips over real HTTP."""
    import json
    import shutil
    import time
    import urllib.request

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord_port = s.getsockname()[1]
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        rest_port = s.getsockname()[1]
    home = str(tmp_path / "shared_home")
    env = {**JAX_CACHE_ENV,
           "PATH": "/usr/bin:/bin:/opt/venv/bin",
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
           "PYTHONPATH": "/root/repo",
           "LO_MESH_SHAPE": "auto", "LO_COMPUTE_DTYPE": "float32"}
    launcher = shutil.which("lo-server", path=env["PATH"])
    base_cmd = [launcher] if launcher else \
        [sys.executable, "-m", "learningorchestra_tpu"]
    procs = []
    for pid in range(2):
        procs.append(subprocess.Popen(
            base_cmd + ["--home", home, "--host", "127.0.0.1",
                        "--port", str(rest_port),
                        "--coordinator", f"127.0.0.1:{coord_port}",
                        "--num-hosts", "2", "--host-id", str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env))
    base = f"http://127.0.0.1:{rest_port}"
    api = "/api/learningOrchestra/v1"

    def req(method, path, body=None, timeout=30):
        data = json.dumps(body).encode() if body is not None else None
        r = urllib.request.Request(
            base + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())

    try:
        health = None
        deadline = time.time() + 240
        while time.time() < deadline:
            if any(p.poll() is not None for p in procs):
                outs = [p.communicate()[0].decode(errors="replace")
                        for p in procs]
                raise AssertionError(f"a pod process died:\n{outs}")
            try:
                _, health = req("GET", "/health", timeout=5)
                break
            except OSError:
                time.sleep(0.5)
        assert health is not None, "REST never came up"
        assert health["processCount"] == 2, health
        assert health["globalDevices"] == 4, health

        st, body = req("POST", api + "/function/python", {
            "name": "ep_data", "functionParameters": {},
            "function": ("import numpy as np\n"
                         "rng = np.random.default_rng(0)\n"
                         "x = rng.normal(size=(32, 8)).astype"
                         "(np.float32)\n"
                         "y = (x[:, 0] > 0).astype(np.int32)\n"
                         "response = {'x': x, 'y': y}\n")})
        assert st == 201, body

        def poll(uri, timeout=240):
            t0 = time.time()
            while time.time() - t0 < timeout:
                st2, b2 = req("GET", uri + "?limit=1")
                if st2 == 200 and b2["metadata"].get("finished"):
                    return b2
                time.sleep(0.3)
            raise AssertionError(f"timeout polling {uri}")

        poll(body["result"])
        st, body = req("POST", api + "/model/tensorflow", {
            "modelName": "ep_model",
            "modulePath": "learningorchestra_tpu.models",
            "class": "NeuralModel",
            "classParameters": {"layer_configs": [
                {"kind": "dense", "units": 4, "activation": "relu"},
                {"kind": "dense", "units": 2,
                 "activation": "softmax"}]}})
        assert st == 201, body
        poll(body["result"])
        st, body = req("POST", api + "/train/tensorflow", {
            "name": "ep_train", "modelName": "ep_model",
            "method": "fit",
            "methodParameters": {"x": "$ep_data.x", "y": "$ep_data.y",
                                 "epochs": 1, "batch_size": 8}})
        assert st == 201, body
        poll(body["result"])
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.communicate(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
                p.communicate()


def test_two_process_rest_train_replay(tmp_path):
    """A /train REST job on the coordinator fans out to the worker via
    the HostBridge and the fit jits over the GLOBAL 4-device mesh."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    home = str(tmp_path / "shared_home")
    procs = []
    for pid in range(2):
        script = (_TRAIN.replace("@REPO@", "/root/repo")
                  .replace("@COORD@", coord).replace("@PID@", str(pid))
                  .replace("@HOME@", home))
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env={"PATH": "/usr/bin:/bin", **JAX_CACHE_ENV}))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out.decode(errors="replace"))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"host {pid} failed:\n{out}"
        assert f"TRAIN_OK {pid}" in out


_GUARD = textwrap.dedent("""
    import os, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["LO_HOME"] = "@HOME@"
    os.environ["LO_MESH_SHAPE"] = "auto"
    os.environ["LO_COMPUTE_DTYPE"] = "float32"
    os.environ["LO_HEARTBEAT_INTERVAL"] = "0.25"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, "@REPO@")
    from learningorchestra_tpu.runtime import distributed as dist

    assert dist.initialize(coordinator_address="@COORD@",
                           num_processes=2, process_id=@PID@)

    if @PID@ == 1:
        # worker: follow until SIGKILLed by the test
        dist.HostBridge().follow(lambda m: None)
        sys.exit(0)

    from learningorchestra_tpu.services.server import Api
    api = Api()
    prefix = "/api/learningOrchestra/v1"

    st, body, _h = api.dispatch("POST", prefix + "/function/python", {}, {
        "name": "g_data", "functionParameters": {},
        "function": ("import numpy as np\\n"
                     "rng = np.random.default_rng(0)\\n"
                     "x = rng.normal(size=(64, 8)).astype(np.float32)\\n"
                     "y = (x[:, 0] > 0).astype(np.int32)\\n"
                     "response = {'x': x, 'y': y}\\n")})
    assert st == 201, body
    for _ in range(300):
        st, b, _h = api.dispatch("GET", body["result"], {"limit": "1"}, None)
        if st == 200 and b["metadata"].get("finished"):
            break
        time.sleep(0.1)

    st, body, _h = api.dispatch("POST", prefix + "/model/tensorflow", {}, {
        "modelName": "g_model",
        "modulePath": "learningorchestra_tpu.models",
        "class": "NeuralModel",
        "classParameters": {"layer_configs": [
            {"kind": "dense", "units": 8, "activation": "relu"},
            {"kind": "dense", "units": 2, "activation": "softmax"}]}})
    assert st == 201, body
    for _ in range(300):
        st, b, _h = api.dispatch("GET", body["result"], {"limit": "1"}, None)
        if st == 200 and b["metadata"].get("finished"):
            break
        time.sleep(0.1)

    # a long-running mesh job stands in for a train step stuck in a
    # collective: on TPU a dead peer makes collectives HANG (the
    # failure mode the guard exists for); the CPU backend's Gloo
    # errors the thread instead, so a sleep models the hang honestly
    api.ctx.catalog.create_collection("g_stuck", "train/tensorflow")
    api.ctx.jobs.submit("g_stuck", lambda: time.sleep(300),
                        description="stuck mesh step",
                        needs_mesh=True)
    open("@HOME@/train_started", "w").write("1")

    # the pod guard must surface WorkerLost on the in-flight job.
    # NOTE the clock: jax's own coordination service also notices the
    # dead task and FATALLY terminates this process ~10s after the
    # kill (client.h:80) — every assertion below must finish first,
    # which is itself evidence the guard beats the runtime's handling
    deadline = time.time() + 45
    seen = None
    while time.time() < deadline:
        docs = api.ctx.catalog.get_documents("g_stuck")
        lost = [d for d in docs if d.get("exception")
                and "WorkerLost" in d["exception"]]
        if lost:
            seen = lost[0]
            break
        time.sleep(0.1)
    assert seen is not None, "no WorkerLost doc within bound"
    print("GUARD_SAW_LOSS", time.time(), flush=True)

    # /health reports degraded
    health = api._health()
    assert health["status"] == "degraded", health
    assert "podFailure" in health, health

    # new mesh jobs are refused with a terminal typed failure
    st, body, _h = api.dispatch("POST", prefix + "/train/tensorflow", {}, {
        "name": "g_train2", "modelName": "g_model", "method": "fit",
        "methodParameters": {"x": "$g_data.x", "y": "$g_data.y",
                             "epochs": 1, "batch_size": 8}})
    assert st == 201, body
    deadline = time.time() + 8
    refused = False
    while time.time() < deadline:
        docs = api.ctx.catalog.get_documents("g_train2")
        if any(d.get("exception") and "WorkerLost" in d["exception"]
               for d in docs):
            refused = True
            break
        time.sleep(0.1)
    assert refused, "new mesh job was not refused"
    print("GUARD_OK", flush=True)
    # exit before jax's fatal error handler fires, and skip joining
    # the stuck mesh thread
    os._exit(0)
""")


def test_worker_sigkill_reports_failure(tmp_path):
    """SIGKILL one of two pod processes mid-train: the coordinator's
    pod guard marks the in-flight mesh job failed with a typed
    WorkerLost execution document within the heartbeat bound, /health
    reports degraded, and new mesh jobs are refused (VERDICT round-3
    missing #4 — Swarm re-placement parity, reference
    README.md:200-202)."""
    import os
    import time

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    home = str(tmp_path / "guard_home")
    procs = []
    for pid in range(2):
        script = (_GUARD.replace("@REPO@", "/root/repo")
                  .replace("@COORD@", coord).replace("@PID@", str(pid))
                  .replace("@HOME@", home))
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env={"PATH": "/usr/bin:/bin", **JAX_CACHE_ENV}))

    started = os.path.join(home, "train_started")
    deadline = time.time() + 240
    while time.time() < deadline and not os.path.exists(started):
        if procs[0].poll() is not None:
            out = procs[0].communicate()[0].decode(errors="replace")
            procs[1].kill()
            raise AssertionError(f"coordinator died early:\n{out}")
        time.sleep(0.2)
    assert os.path.exists(started), "train never started"
    time.sleep(1.0)  # let the train enter its first mesh step
    procs[1].kill()  # SIGKILL the worker mid-train

    try:
        out, _ = procs[0].communicate(timeout=120)
    except subprocess.TimeoutExpired:
        procs[0].kill()
        out, _ = procs[0].communicate()
    text = out.decode(errors="replace")
    assert procs[0].returncode == 0, f"coordinator failed:\n{text}"
    assert "GUARD_OK" in text, text


def test_heartbeat_monitor_loss_and_resume():
    """Unit-level liveness semantics: a silent worker is reported
    lost, junk datagrams don't kill the monitor or poison state, and
    resumed heartbeats CLEAR the loss (a transient pause must not
    wedge a healthy pod)."""
    import json as json_mod
    import time

    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as probe:
        probe.bind(("127.0.0.1", 0))
        addr = probe.getsockname()
    mon = dist.HeartbeatMonitor(addr, expected=[1, 2], timeout=0.6)
    try:
        sender = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

        def beat(host_id):
            sender.sendto(json_mod.dumps(
                {"hostId": host_id}).encode(), addr)

        # both beating -> healthy
        for _ in range(4):
            beat(1)
            beat(2)
            # junk must be ignored, not fatal
            sender.sendto(b"null", addr)
            sender.sendto(b'{"hostId": "x"}', addr)
            sender.sendto(b'{"hostId": 99}', addr)  # not in expected
            time.sleep(0.1)
        assert mon.lost_workers() == []

        # worker 2 goes silent -> lost within the timeout bound
        deadline = time.time() + 5
        lost = []
        while time.time() < deadline:
            beat(1)
            lost = mon.lost_workers()
            if lost:
                break
            time.sleep(0.1)
        # only assert membership: a scheduler stall on a loaded runner
        # can transiently mark worker 1 too (it recovers below)
        assert 2 in lost, lost

        # worker 2 resumes -> loss clears
        deadline = time.time() + 5
        while time.time() < deadline:
            beat(1)
            beat(2)
            if mon.lost_workers() == []:
                break
            time.sleep(0.1)
        assert mon.lost_workers() == []
        sender.close()
    finally:
        mon.close()
