"""Checkpointing.

The reference has NO mid-training checkpointing — persistence is the
final artifact only, and a failed job is simply re-run from its stored
parent (SURVEY §5: binary_executor utils.py:195-208, server.py:74-118).
Here training jobs checkpoint per-epoch/step via Orbax and can resume,
and pytree artifacts are serialized with msgpack (flax.serialization)
instead of pickles.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
from flax import serialization


class Checkpointer:
    """Thin Orbax wrapper: save(step, pytree) / latest() / restore."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    def save(self, step: int, tree: Any) -> None:
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(tree))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, target: Any, step: Optional[int] = None) -> Any:
        import orbax.checkpoint as ocp

        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            return None
        return self._mgr.restore(step, args=ocp.args.StandardRestore(target))

    def saved_metadata(self, step: Optional[int] = None) -> Any:
        """The SAVED tree's structure as a pytree of ArrayMetadata
        leaves (shape/dtype) — reads checkpoint metadata only, no
        array data. This is the layout-drift discriminator: comparing
        it structurally against the live state beats sniffing orbax's
        mismatch message, which rewords across releases."""
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            return None
        meta = self._mgr.item_metadata(step)
        return getattr(meta, "tree", meta)

    def restore_partial(self, target_subtree: Any,
                        step: Optional[int] = None) -> Any:
        """Restore only the subtrees named in ``target_subtree`` (e.g.
        params + step, skipping a drifted opt_state entirely, so the
        stale optimizer arrays are never read into host memory). Uses
        a fresh read-only manager: the instance manager's handler
        registry is pinned to StandardRestore by the failed full
        restore that precedes a migration."""
        import orbax.checkpoint as ocp

        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            return None
        mgr = ocp.CheckpointManager(self._dir)
        try:
            return mgr.restore(step, args=ocp.args.PyTreeRestore(
                item=target_subtree, partial_restore=True))
        finally:
            mgr.close()

    # -- sidecar progress metadata ------------------------------------
    # Epoch progress can't be reconstructed from the restored step when
    # a re-run reshapes the feed (different batch_size / data size), so
    # the engine records it here next to the orbax steps.
    def save_meta(self, meta: dict) -> None:
        import json

        path = os.path.join(self._dir, "progress.json")
        with open(path + ".tmp", "w") as f:
            json.dump(meta, f)
        os.replace(path + ".tmp", path)

    def load_meta(self) -> Optional[dict]:
        import json

        path = os.path.join(self._dir, "progress.json")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()


# ----------------------------------------------------------------------
# msgpack pytree IO for artifact persistence (no pickle of jax arrays)
# ----------------------------------------------------------------------
def save_pytree(tree: Any, path: str) -> None:
    host_tree = jax.tree_util.tree_map(np.asarray, tree)
    with open(path, "wb") as f:
        f.write(serialization.to_bytes(host_tree))


def load_pytree(path: str, target: Any) -> Any:
    with open(path, "rb") as f:
        data = f.read()
    return serialization.from_bytes(target, data)
