"""Dataset service: URL ingest + the universal artifact read API.

Reference behavior being re-provided (database_api_image/):
- ``POST /files?type=dataset/csv``: stream a CSV from ``datasetURI``
  into storage through a 3-stage pipeline — download ∥ parse ∥ write
  (database.py:99-151 runs download/treat/save threads over bounded
  queues; ours streams bytes through a pipe into a chunked Arrow CSV
  parser feeding a Parquet writer, so the hot loop is columnar instead
  of per-row ``insert_one`` — database.py:144 is the throughput cliff
  this design removes).
- ``POST /files?type=dataset/generic``: stream any file to binary
  storage (database.py:61-83).
- ``GET /files`` catalog listing and ``GET /files/<name>`` paged/
  queried reads for EVERY artifact type (database.py:19-44) — the
  gateway routes all read GETs of all services here
  (krakend.json:722-757).
- ``DELETE /files/<name>`` (server.py:96-111).

Field names match the reference API: ``datasetName``, ``datasetURI``
(constants.py:17-18), read params ``skip``/``limit``/``query``
(constants.py:40-48).
"""

from __future__ import annotations

import io
import json
import os
import threading
from typing import Any, Dict, Optional, Tuple

from learningorchestra_tpu.catalog import documents as D
from learningorchestra_tpu.services import validators as V

DATASET_NAME_FIELD = "datasetName"
DATASET_URI_FIELD = "datasetURI"

_CHUNK = 1 << 20  # 1 MiB download chunks


def _open_uri_stream(uri: str):
    """Readable binary stream for http(s)/file URIs and local paths."""
    if uri.startswith(("http://", "https://")):
        import requests

        resp = requests.get(uri, stream=True, timeout=600)
        resp.raise_for_status()
        resp.raw.decode_content = True
        return resp.raw
    if uri.startswith("file://"):
        return open(uri[len("file://"):], "rb")
    return open(uri, "rb")


class _PipeReader(io.RawIOBase):
    """File-like fed by the download thread; read by the parser thread.

    The bounded buffer is the same backpressure the reference gets from
    its bounded queues (database.py:96-105) — a slow writer throttles
    the download instead of buffering the whole file in memory.
    """

    def __init__(self, max_buffered: int = 64):
        super().__init__()
        import queue

        self._q: "queue.Queue[Optional[bytes]]" = queue.Queue(max_buffered)
        self._leftover = b""
        self._eof = False
        self._err: list = []

    # producer side
    def feed(self, data: bytes) -> None:
        self._q.put(data)

    def finish(self, error: Optional[BaseException] = None) -> None:
        if error is not None:
            self._err.append(error)
        self._q.put(None)

    # consumer side
    def readable(self) -> bool:
        return True

    def readinto(self, b) -> int:
        while not self._leftover and not self._eof:
            item = self._q.get()
            if item is None:
                self._eof = True
                if self._err:
                    raise self._err[0]
            else:
                self._leftover = item
        n = min(len(b), len(self._leftover))
        b[:n] = self._leftover[:n]
        self._leftover = self._leftover[n:]
        return n


class DatasetService:
    def __init__(self, context):
        self._ctx = context
        self._validator = V.RequestValidator(context)

    # -- POST -----------------------------------------------------------
    def create(self, body: Dict[str, Any], tool: str,
               ) -> Tuple[int, Dict[str, Any]]:
        self._validator.required_fields(
            body, [DATASET_NAME_FIELD, DATASET_URI_FIELD])
        name = self._validator.safe_name(body[DATASET_NAME_FIELD])
        uri = body[DATASET_URI_FIELD]
        self._validator.not_duplicate(name)
        if tool not in ("csv", "generic"):
            raise V.HttpError(V.HTTP_NOT_ACCEPTABLE,
                              f"unknown dataset tool: {tool}")
        if not isinstance(uri, str) or not uri:
            raise V.HttpError(V.HTTP_NOT_ACCEPTABLE,
                              "invalid url")
        type_string = f"dataset/{tool}"
        self._ctx.catalog.create_collection(name, type_string, {"url": uri})
        run = (self._ingest_csv if tool == "csv" else self._ingest_generic)
        self._ctx.jobs.submit(
            name, lambda: run(name, uri),
            description=f"ingest {uri}", parameters={"url": uri})
        return V.HTTP_CREATED, {
            "result": f"/api/learningOrchestra/v1/dataset/{tool}/{name}"}

    # -- pipelines ------------------------------------------------------
    def _ingest_csv(self, name: str, uri: str) -> None:
        """download ∥ parse ∥ write, all streaming.

        Parsing prefers the first-party native core (csrc/locore.cpp,
        our equivalent of the native muscle the reference rents from
        Spark/Mongo — SURVEY §2.2); without a toolchain it rides
        Arrow's C++ CSV reader instead. Both paths append columnar
        record batches, removing the reference's per-row ``insert_one``
        cliff (database.py:144).
        """
        from learningorchestra_tpu import native

        if native.available():
            self._ingest_csv_native(name, uri)
            return
        from pyarrow import csv as pa_csv

        pipe = _PipeReader()

        def download() -> None:
            try:
                with _open_uri_stream(uri) as stream:
                    while True:
                        chunk = stream.read(_CHUNK)
                        if not chunk:
                            break
                        pipe.feed(chunk)
                pipe.finish()
            except BaseException as e:  # noqa: BLE001
                pipe.finish(e)

        t = threading.Thread(target=download, daemon=True,
                             name=f"lo-ingest-{name}")
        t.start()
        rows = 0
        with self._ctx.catalog.dataset_writer(name) as writer:
            reader = pa_csv.open_csv(
                pipe, read_options=pa_csv.ReadOptions(block_size=_CHUNK))
            for batch in reader:
                if batch.num_rows:
                    writer.write_batch(batch)
                    rows += batch.num_rows
            fields = writer.fields()
        t.join()
        self._ctx.catalog.update_metadata(
            name, {D.FIELDS_FIELD: fields, "rows": rows})

    def _ingest_csv_native(self, name: str, uri: str) -> None:
        """Chunked ingest through the native CSV parser: a download
        thread streams bytes into a pipe (download ∥ parse ∥ write,
        like the Arrow path), the consumer cuts at quote-safe record
        boundaries, parses complete records to columns in C++, and
        appends Parquet record batches. The first data-bearing chunk
        sniffs per-column types (float64 -> int64 when all values are
        integral, Arrow-reader parity); later chunks are pinned to that
        schema (unparseable numerics become nulls)."""
        from learningorchestra_tpu.native import ops as nops

        pipe = _PipeReader()

        def download() -> None:
            try:
                with _open_uri_stream(uri) as stream:
                    while True:
                        chunk = stream.read(_CHUNK)
                        if not chunk:
                            break
                        pipe.feed(chunk)
                pipe.finish()
            except BaseException as e:  # noqa: BLE001
                pipe.finish(e)

        t = threading.Thread(target=download, daemon=True,
                             name=f"lo-ingest-{name}")
        t.start()
        header = None
        forced = None
        rows = 0
        buf = b""
        with self._ctx.catalog.dataset_writer(name) as writer:
            while True:
                data = pipe.read(_CHUNK)
                if not data:
                    break
                buf += data
                if len(buf) < _CHUNK:
                    continue
                cut = nops.safe_split(buf)
                if cut <= 0:
                    continue
                chunk, buf = buf[:cut], buf[cut:]
                header, forced, n = self._write_native_chunk(
                    writer, chunk, header, forced)
                rows += n
            if buf.strip():
                header, forced, n = self._write_native_chunk(
                    writer, buf, header, forced)
                rows += n
            fields = writer.fields()
        t.join()
        self._ctx.catalog.update_metadata(
            name, {D.FIELDS_FIELD: fields, "rows": rows})

    # forced-type codes carried between chunks: 0 float64, 1 string,
    # 2 int64 (the C++ core knows 0/1; 2 is refined here)
    @staticmethod
    def _write_native_chunk(writer, chunk: bytes, header, forced):
        import numpy as np
        import pyarrow as pa

        from learningorchestra_tpu.native import ops as nops

        has_header = header is None
        if has_header:
            nl = chunk.find(b"\n")
            first = chunk[:nl if nl >= 0 else len(chunk)]
            header = nops.csv_header(
                first.decode("utf-8", "replace").rstrip("\r"))
        native_forced = (None if forced is None else
                         [1 if t == 1 else 0 for t in forced])
        cols, types = nops.parse_csv(chunk, has_header=has_header,
                                     forced_types=native_forced)
        n = len(cols[0]) if cols else 0
        if n == 0:
            # header-only / blank chunk: nothing learned, nothing
            # pinned (a zero-row sniff would default every column to
            # float64 and corrupt later string chunks)
            return header, forced, 0
        if len(cols) != len(header):
            raise ValueError(
                f"CSV has {len(cols)} columns but header names "
                f"{len(header)}")
        if forced is None:
            forced = list(types)
            for j, (kind, col) in enumerate(zip(types, cols)):
                if kind != 0:
                    continue
                finite = col[np.isfinite(col)]
                if (finite.size and np.all(finite == np.floor(finite))
                        and np.all(np.abs(finite) < 2.0 ** 53)):
                    forced[j] = 2
        arrays = []
        for kind, col in zip(forced, cols):
            if kind == 1:
                arrays.append(pa.array(col.tolist(), type=pa.string()))
                continue
            # from_pandas: NaN -> null, matching the Arrow CSV reader's
            # empty-cell handling (and keeping row reads JSON-safe)
            arr = pa.array(np.asarray(col, np.float64), from_pandas=True)
            if kind == 2:
                # a later chunk with non-integral values fails the safe
                # cast — same error class as Arrow's streaming reader
                # hitting a type change after block-1 inference
                arr = arr.cast(pa.int64())
            arrays.append(arr)
        writer.write_batch(pa.Table.from_arrays(arrays, names=header))
        return header, forced, n

    def _ingest_generic(self, name: str, uri: str) -> None:
        buf = io.BytesIO()
        with _open_uri_stream(uri) as stream:
            while True:
                chunk = stream.read(_CHUNK)
                if not chunk:
                    break
                buf.write(chunk)
        filename = os.path.basename(uri.split("?")[0]) or "payload.bin"
        self._ctx.artifacts.save_bytes(
            buf.getvalue(), name, D.DATASET_GENERIC_TYPE, filename=filename)
        self._ctx.catalog.update_metadata(name, {"sizeBytes": buf.tell()})

    # -- universal GET/DELETE ------------------------------------------
    def list_files(self) -> Tuple[int, Any]:
        """Catalog listing: every collection's metadata document
        (reference Storage.get_metadata_files, database.py:30-44)."""
        return V.HTTP_SUCCESS, {
            "result": self._ctx.catalog.list_collections()}

    def read_file(self, name: str, skip: int = 0,
                  limit: Optional[int] = None,
                  query: Optional[Dict[str, Any]] = None,
                  ) -> Tuple[int, Any]:
        meta = self._validator.existing(name)
        rows = self._ctx.catalog.read_entries(
            name, skip=skip, limit=limit, query=query)
        return V.HTTP_SUCCESS, {"metadata": meta, "result": rows}

    def delete_file(self, name: str) -> Tuple[int, Any]:
        meta = self._ctx.catalog.get_metadata(name)
        if meta is None:
            raise V.HttpError(V.HTTP_NOT_FOUND,
                              f"{V.MESSAGE_NONEXISTENT_FILE}: {name}")
        self._ctx.catalog.delete_collection(name)
        self._ctx.artifacts.delete(name, meta.get(D.TYPE_FIELD))
        return V.HTTP_SUCCESS, {"result": f"deleted file {name}"}


def parse_query_param(raw: Optional[str]) -> Optional[Dict[str, Any]]:
    """The reference passes ``query`` as a JSON string query param
    (database.py:19-28)."""
    if not raw:
        return None
    try:
        q = json.loads(raw)
    except json.JSONDecodeError:
        raise V.HttpError(V.HTTP_NOT_ACCEPTABLE, "invalid query")
    if not isinstance(q, dict):
        raise V.HttpError(V.HTTP_NOT_ACCEPTABLE, "invalid query")
    return q
