"""`lo-cluster` pod supervisor: one-command bring-up + pod-level
restart-on-failure (reference parity: `bash run.sh` deploys the whole
stack under Swarm's restart_policy on-failure, run.sh:1-130,
docker-compose.yml:3-6)."""

import json
import socket
import threading
import time
import urllib.request

from learningorchestra_tpu.cluster import PodSupervisor


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _health(port: int, timeout: float = 5.0):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/health", timeout=timeout) as r:
        return json.loads(r.read())


def _wait_healthy(port: int, want_hosts: int, deadline_s: float,
                  sup: PodSupervisor):
    deadline = time.time() + deadline_s
    last = None
    while time.time() < deadline:
        try:
            last = _health(port)
            if last.get("status") == "ok" and \
                    last.get("processCount") == want_hosts:
                return last
        except OSError:
            pass
        time.sleep(0.5)
    raise AssertionError(f"pod never healthy: {last}; "
                         f"logs under {sup.home}/logs")


def test_cluster_bringup_and_restart_on_failure(tmp_path):
    """2-host pod up in one call; SIGKILL a worker; the supervisor
    re-forms the pod and /health returns to ok with the full host
    count (the capability Swarm re-placement provided the reference,
    README.md:200-202)."""
    rest_port = _free_port()
    sup = PodSupervisor(
        hosts=2, port=rest_port, home=str(tmp_path / "pod"),
        backoff=0.5,
        extra_env={"JAX_PLATFORMS": "cpu",
                   "XLA_FLAGS":
                       "--xla_force_host_platform_device_count=2",
                   "LO_MESH_SHAPE": "auto",
                   "LO_COMPUTE_DTYPE": "float32",
                   "LO_HEARTBEAT_INTERVAL": "0.25"})
    sup.start()
    result = {}
    thread = threading.Thread(
        target=lambda: result.update(code=sup.supervise()),
        daemon=True)
    thread.start()
    try:
        _wait_healthy(rest_port, want_hosts=2, deadline_s=240, sup=sup)
        first_gen = list(sup.procs)

        first_gen[1].kill()  # SIGKILL the worker mid-flight

        # the supervisor must tear down + re-form; the new pod serves
        # a healthy /health again with the full host count
        deadline = time.time() + 240
        while time.time() < deadline:
            if sup.procs and sup.procs[0] is not first_gen[0]:
                break
            time.sleep(0.5)
        assert sup.procs[0] is not first_gen[0], "pod never re-formed"
        _wait_healthy(rest_port, want_hosts=2, deadline_s=240, sup=sup)
    finally:
        sup._stopping = True
        thread.join(timeout=60)
    assert result.get("code") == 0
    assert not thread.is_alive()


def test_cluster_gives_up_after_restart_budget(tmp_path):
    """A crash-looping pod stops restarting once the budget is spent
    (no infinite flapping)."""
    sup = PodSupervisor(
        hosts=1, port=_free_port(), home=str(tmp_path / "pod"),
        max_restarts=2, restart_window=60.0, backoff=0.1,
        extra_env={"JAX_PLATFORMS": "cpu",
                   # an unparseable int env makes boot fail fast (the
                   # mesh itself is built lazily, after REST is up)
                   "LO_MAX_WORKERS": "zero"})
    sup.start()
    code = sup.supervise()
    assert code == 1
