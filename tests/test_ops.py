"""Flash attention kernel vs the full-softmax oracle.

Runs the real Pallas kernel in interpreter mode on the CPU backend
(same kernel source the TPU compiles), checking values AND gradients
against reference_attention.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learningorchestra_tpu.ops import flash_attention, reference_attention
from learningorchestra_tpu.ops.attention import flash_attention_with_lse


def _rand(shape, key):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sq,sk", [(32, 32), (40, 56)])
def test_forward_matches_reference(causal, sq, sk):
    if causal and sq != sk:
        pytest.skip("causal oracle assumes square positions")
    b, h, d = 2, 3, 16
    q, k, v = (_rand((b, s, h, d), i)
               for i, s in enumerate((sq, sk, sk)))
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_reference(causal):
    b, s, h, d = 1, 24, 2, 8
    q, k, v = (_rand((b, s, h, d), 10 + i) for i in range(3))

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(reference_attention(q, k, v, causal=causal)))

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=5e-5, rtol=5e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_lse_output_matches_oracle(causal):
    """The lse rows ring composition merges on must equal the
    full-softmax log-sum-exp."""
    b, s, h, d = 2, 32, 2, 16
    q, k, v = (_rand((b, s, h, d), 30 + i) for i in range(3))
    _, lse = flash_attention_with_lse(q, k, v, causal=causal,
                                      block_q=16, block_k=16)
    scale = 1.0 / np.sqrt(d)
    scores = jnp.einsum("bqhd,bkhd->bqhk", q, k) * scale
    if causal:
        mask = jnp.arange(s)[:, None] >= jnp.arange(s)[None, :]
        scores = jnp.where(mask[None, :, None, :], scores, -1e30)
    want = jax.scipy.special.logsumexp(scores, axis=-1)  # (b, sq, h)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_lse_gradient_flows_through_merge():
    """A loss that consumes BOTH outputs (the ring-merge pattern):
    grads must match autodiff of the dense oracle computing the same
    (o, lse) pair — this exercises the `delta - dlse` path in the
    backward kernels."""
    b, s, h, d = 1, 16, 2, 8
    q, k, v = (_rand((b, s, h, d), 40 + i) for i in range(3))
    scale = 1.0 / np.sqrt(d)

    def merge_loss(o, lse):
        # lse-weighted combination, like a ring hop merge
        w = jax.nn.sigmoid(lse)
        return jnp.sum(jnp.sin(o) * w[..., None]) + jnp.sum(lse ** 2) * 0.1

    def loss_flash(q, k, v):
        o, lse = flash_attention_with_lse(q, k, v, causal=True,
                                          block_q=8, block_k=8)
        return merge_loss(o, lse)

    def loss_ref(q, k, v):
        scores = jnp.einsum("bqhd,bkhd->bqhk", q, k) * scale
        mask = jnp.arange(s)[:, None] >= jnp.arange(s)[None, :]
        scores = jnp.where(mask[None, :, None, :], scores, -1e30)
        lse = jax.scipy.special.logsumexp(scores, axis=-1)
        p = jnp.exp(scores - lse[..., None])
        o = jnp.einsum("bqhk,bkhd->bqhd", p, v)
        return merge_loss(o, lse)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=5e-5, rtol=5e-4)


def test_jit_and_uneven_blocks():
    b, s, h, d = 2, 50, 2, 12  # nothing divides the block sizes
    q, k, v = (_rand((b, s, h, d), 20 + i) for i in range(3))
    f = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=16, block_k=16))
    out = f(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_bfloat16_path():
    b, s, h, d = 1, 32, 2, 16
    q, k, v = (_rand((b, s, h, d), 30 + i).astype(jnp.bfloat16)
               for i in range(3))
    out = flash_attention(q, k, v, causal=False, block_q=16, block_k=16)
    assert out.dtype == jnp.bfloat16
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-2, rtol=3e-2)


@pytest.mark.parametrize("window", [8, 17, 64])
def test_sliding_window_forward_matches_reference(window):
    """window=W bands the causal mask to [p-W+1, p]; W >= seq must
    equal plain causal. Odd seq/blocks exercise the tile-skip edges."""
    from learningorchestra_tpu.parallel.ring import (
        full_attention_reference)

    b, s, h, d = 2, 40, 2, 16
    q, k, v = (_rand((b, s, h, d), 40 + i) for i in range(3))
    out = flash_attention(q, k, v, causal=True, window=window,
                          block_q=16, block_k=16)
    ref = full_attention_reference(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    if window >= s:
        plain = flash_attention(q, k, v, causal=True,
                                block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(plain),
                                   atol=2e-5, rtol=2e-5)


def test_sliding_window_gradients_match_reference():
    from learningorchestra_tpu.parallel.ring import (
        full_attention_reference)

    b, s, h, d, w = 1, 24, 2, 8, 7

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, window=w,
                                       block_q=8, block_k=8) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(full_attention_reference(
            q, k, v, causal=True, window=w) ** 2)

    q, k, v = (_rand((b, s, h, d), 50 + i) for i in range(3))
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   atol=3e-5, rtol=3e-5)


def test_sliding_window_requires_causal():
    q = _rand((1, 16, 1, 8), 0)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, q, q, causal=False, window=4)


@pytest.mark.parametrize("window", [0, 20])
def test_banded_iteration_many_blocks(window):
    """Banded/clamped kv iteration across many tiles (seq 96, 16-wide
    blocks -> 6x6 tile grid) must stay exact for causal and windowed
    runs, forward AND backward — this is the shape class where the
    revisit-clamp index maps actually reorder the stream."""
    from learningorchestra_tpu.parallel.ring import (
        full_attention_reference)

    b, s, h, d = 1, 96, 2, 16
    q, k, v = (_rand((b, s, h, d), 60 + i) for i in range(3))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       window=window,
                                       block_q=16, block_k=16) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(full_attention_reference(
            q, k, v, causal=True, window=window) ** 2)

    out = flash_attention(q, k, v, causal=True, window=window,
                          block_q=16, block_k=16)
    ref = full_attention_reference(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5, rtol=3e-5)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   atol=5e-5, rtol=5e-5)


@pytest.mark.parametrize("kvh,window", [(1, 0), (2, 0), (2, 9)])
def test_gqa_grouped_kernel_matches_repeat(kvh, window):
    """GQA-native path: k/v carry kv heads < q heads and the group
    folds into the kernel's q-row axis. Values AND gradients must
    match repeating K/V to full heads (the mathematical definition of
    GQA), including under a sliding window and odd seq."""
    from learningorchestra_tpu.parallel.ring import (
        full_attention_reference)

    b, s, h, d = 2, 40, 4, 16
    g = h // kvh
    q = _rand((b, s, h, d), 70)
    k = _rand((b, s, kvh, d), 71)
    v = _rand((b, s, kvh, d), 72)

    def grouped(q, k, v):
        return flash_attention(q, k, v, causal=True, window=window,
                               block_q=16, block_k=16)

    def oracle(q, k, v):
        return full_attention_reference(
            q, jnp.repeat(k, g, axis=2), jnp.repeat(v, g, axis=2),
            causal=True, window=window)

    out = grouped(q, k, v)
    ref = oracle(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    gf = jax.grad(lambda *a: jnp.sum(grouped(*a) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(oracle(*a) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   atol=5e-5, rtol=5e-5)
