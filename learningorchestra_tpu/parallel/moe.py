"""Mixture-of-experts with expert parallelism over the ``ep`` axis.

Two dispatch schedules with IDENTICAL routing semantics (top-k,
shared per-expert capacity with choice-0 priority, token-order
tie-break, renormalized gate weights):

- ``route="sparse"`` (default) — sort/segment routing: the (T·k)
  token-copies are stably sorted by expert id (choice-major, so
  earlier choices win capacity), each copy's slot inside its expert's
  (capacity, d) buffer comes from a cumsum of per-expert counts, and
  dispatch/combine are two O(T·k·d) scatter/gathers. Peak routing
  memory is O(E·C·d + T·k) — no (T, E, C) tensor ever exists, so
  T=8k, E=32 routes fine.
- ``route="dense"`` — GShard-style (T, E, C) one-hot dispatch where
  routing is three einsums; simplest lowering to all-to-alls under
  GSPMD but O(T·E·C) memory. Kept for small-shape parity checks.

Both are static-shape and jit/vjp-safe (sort indices are constants of
the backward pass; gradients flow through values and gate weights).

Functional params layout (stacked experts, shardable by
sharding.TRANSFORMER_RULES):
  ``gate``          (d_model, n_experts)   — replicated
  ``experts/wi``    (n_experts, d_model, d_ff)
  ``experts/wo``    (n_experts, d_ff, d_model)
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from learningorchestra_tpu.parallel import sharding as sharding_lib
from learningorchestra_tpu.runtime import mesh as mesh_lib


def init_moe_params(rng, d_model: int, d_ff: int, n_experts: int,
                    dtype=jnp.float32) -> Dict[str, Any]:
    kg, ki, ko = jax.random.split(rng, 3)
    scale_in = 1.0 / math.sqrt(d_model)
    scale_out = 1.0 / math.sqrt(d_ff)
    return {
        "gate": (jax.random.normal(kg, (d_model, n_experts)) *
                 scale_in).astype(dtype),
        "experts": {
            "wi": (jax.random.normal(ki, (n_experts, d_model, d_ff)) *
                   scale_in).astype(dtype),
            "wo": (jax.random.normal(ko, (n_experts, d_ff, d_model)) *
                   scale_out).astype(dtype),
        },
    }


def _topk_renorm(logits: jax.Array, k: int,
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Shared router head: softmax -> top-k -> renormalize + Switch
    aux loss. ONE implementation so the sparse and dense schedules
    cannot drift apart. Returns (gate_idx (T,k), gate_vals (T,k),
    aux)."""
    e = logits.shape[-1]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)
    # load-balancing aux loss (Switch: E * mean(frac_tokens*mean_prob))
    top1 = jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32)
    aux = e * jnp.mean(jnp.mean(top1, axis=0) * jnp.mean(probs, axis=0))
    return gate_idx, gate_vals, aux


def top_k_gating(logits: jax.Array, k: int, capacity: int,
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (dispatch (T,E,C) {0,1}, combine (T,E,C) weights,
    aux_loss scalar) from router logits (T, E)."""
    t, e = logits.shape
    gate_idx, gate_vals, aux = _topk_renorm(logits, k)

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    # expert fill persists across the k choices so capacity is shared
    fill = jnp.zeros((e,), jnp.int32)
    for choice in range(k):
        idx = gate_idx[:, choice]                          # (T,)
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)   # (T, E)
        # position of each token within its chosen expert's buffer
        pos_in_e = (jnp.cumsum(onehot, axis=0) - 1) + fill[None, :]
        fill = fill + jnp.sum(onehot, axis=0)
        pos = jnp.sum(pos_in_e * onehot, axis=-1)          # (T,)
        keep = pos < capacity
        pos = jnp.clip(pos, 0, capacity - 1)
        hot = (jax.nn.one_hot(idx, e, dtype=jnp.float32)[:, :, None] *
               jax.nn.one_hot(pos, capacity, dtype=jnp.float32)[:, None, :])
        hot = hot * keep[:, None, None]
        dispatch = dispatch + hot
        combine = combine + hot * gate_vals[:, choice, None, None]
    return dispatch, combine, aux


def sparse_route(gate_idx: jax.Array, gate_vals: jax.Array, e: int,
                 capacity: int,
                 ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Sort/segment routing plan (no (T,E,C) tensor).

    Returns ``(tok, slot, keep, w)``, each (T·k,), in expert-sorted
    order: ``tok`` is each kept copy's source token, ``slot`` its flat
    index into the (E·C, d) expert buffer, ``keep`` the capacity mask,
    ``w`` the gate weight. Stable choice-major sort reproduces the
    dense schedule's priority exactly (choice 0 first, then token id).
    """
    t, k = gate_idx.shape
    flat_e = gate_idx.T.reshape(-1)           # (k·T,) choice-major
    flat_w = gate_vals.T.reshape(-1)
    flat_tok = jnp.tile(jnp.arange(t, dtype=jnp.int32), k)
    order = jnp.argsort(flat_e, stable=True)  # choice/token priority
    se = flat_e[order]
    counts = jnp.bincount(flat_e, length=e)
    starts = jnp.concatenate([jnp.zeros((1,), counts.dtype),
                              jnp.cumsum(counts)[:-1]])
    pos = jnp.arange(k * t, dtype=jnp.int32) - starts[se].astype(jnp.int32)
    keep = pos < capacity
    slot = se * capacity + jnp.clip(pos, 0, capacity - 1)
    return flat_tok[order], slot, keep, flat_w[order]


def moe_layer(params: Dict[str, Any], x: jax.Array, *, k: int = 2,
              capacity_factor: float = 1.25,
              mesh: Optional[Mesh] = None, route: str = "sparse",
              ) -> Tuple[jax.Array, jax.Array]:
    """x: (..., d_model) -> (same shape, aux_loss).

    With ``mesh`` given, expert-stacked tensors are constrained to the
    ``ep`` axis so GSPMD executes each expert's FFN on its own mesh
    slice (dispatch/combine become all-to-alls / collective scatters).
    """
    orig_shape = x.shape
    d = orig_shape[-1]
    tokens = x.reshape(-1, d)
    t = tokens.shape[0]
    e = params["gate"].shape[-1]
    capacity = max(1, int(capacity_factor * k * t / e))

    logits = tokens @ params["gate"].astype(tokens.dtype)
    if route == "sparse":
        gate_idx, gate_vals, aux = _topk_renorm(logits, k)
        tok, slot, keep, w = sparse_route(gate_idx, gate_vals, e, capacity)
        buf = jnp.zeros((e * capacity, d), tokens.dtype)
        expert_in = buf.at[slot].add(
            tokens[tok] * keep[:, None].astype(tokens.dtype)
        ).reshape(e, capacity, d)
    else:
        dispatch, combine, aux = top_k_gating(logits, k, capacity)
        expert_in = jnp.einsum("tec,td->ecd",
                               dispatch.astype(tokens.dtype), tokens)

    if mesh is not None:
        expert_in = sharding_lib.constrain(
            expert_in, mesh, mesh_lib.EP, None, None)
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in,
                               params["experts"]["wi"].astype(tokens.dtype),
                               preferred_element_type=jnp.float32))
    h = h.astype(tokens.dtype)
    expert_out = jnp.einsum("ecf,efd->ecd", h,
                            params["experts"]["wo"].astype(tokens.dtype),
                            preferred_element_type=jnp.float32)
    if mesh is not None:
        expert_out = sharding_lib.constrain(
            expert_out.astype(tokens.dtype), mesh, mesh_lib.EP, None, None)

    if route == "sparse":
        copies = expert_out.astype(jnp.float32).reshape(e * capacity, d)[slot]
        copies = copies * (w * keep.astype(jnp.float32))[:, None]
        out = jnp.zeros((t, d), jnp.float32).at[tok].add(copies)
    else:
        out = jnp.einsum("tec,ecd->td", combine.astype(jnp.float32),
                         expert_out.astype(jnp.float32))
    return out.reshape(orig_shape).astype(x.dtype), aux
