"""Async tiered checkpointing (Orbax-style, docs/RELIABILITY.md).

A synchronous ``Checkpointer.save()`` serializes, hashes and fsyncs
on the TRAINING thread — at production cadence the train loop stalls
for the full commit on every epoch. ``AsyncCheckpointManager`` splits
the save into the two tiers the Orbax paper describes:

1. **snapshot** (caller thread, cheap): the train state is copied
   device→host (``np.asarray`` per leaf). This must happen before the
   step path continues — the jitted step donates its input buffers,
   so the device arrays the state references are dead the moment the
   next step runs. The snapshot wall-clock is the only stall the
   train thread pays (``lo_checkpoint_snapshot_seconds``).
2. **commit** (background worker): the host tree is enqueued to a
   single worker thread that runs the SAME atomic
   tmp+fsync+manifest machinery as the sync path
   (``Checkpointer._commit_host``). One worker + a FIFO queue gives
   the ordering guarantee for free: a newer commit can never land
   before an older one finishes.

Semantics:

- the queue is bounded (``LO_CKPT_INFLIGHT``): when full, ``save()``
  blocks until the oldest commit drains — backpressure, not unbounded
  host memory;
- a worker failure is LATCHED and re-raised on the next ``save()`` or
  barrier — an async commit failure surfaces on the job, it never
  kills or deadlocks the worker (which keeps draining);
- every READ (``latest_step``/``restore``/``restore_partial``/
  ``saved_metadata``/``load_meta``) barriers first, so the health
  sentinel's rollback-to-last-good and resume-from-latest semantics
  are unchanged: what was saved is on disk before anything reads;
- ``wait_until_finished()`` is the explicit barrier for job end;
  ``close()`` drains without re-raising (teardown must not mask the
  job's real error).

The manager duck-types ``Checkpointer``, so the engine and the
health sentinel run unmodified against either.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Optional

import jax
import numpy as np

from learningorchestra_tpu.runtime.checkpoint import Checkpointer
from learningorchestra_tpu.runtime import locks

_SENTINEL = object()


def _maybe_inject(site: str) -> None:
    # lazy import mirrors checkpoint._chaos_corrupt: the runtime layer
    # stays importable without the services package
    try:
        from learningorchestra_tpu.services import faults
    except Exception:  # noqa: BLE001
        return
    faults.maybe_inject(site)


def _observe(name: str, t0: float, end: float, ctx, **attrs) -> None:
    """Record a span (against a trace context captured on the CALLER
    thread — the worker has no thread-local trace) + histogram."""
    try:
        from learningorchestra_tpu.observability import hist
        from learningorchestra_tpu.observability import trace

        if ctx is not None:
            trace.add(name, ctx[0], t0, end, parent=ctx[1], **attrs)
        hist.observe(
            {"checkpointSnapshot": "lo_checkpoint_snapshot_seconds",
             "checkpointCommit": "lo_checkpoint_commit_seconds",
             }.get(name, f"lo_{name}_seconds"), end - t0)
    except Exception:  # noqa: BLE001 — observability is advisory
        pass


def _trace_ctx():
    try:
        from learningorchestra_tpu.observability import trace

        return trace.current()
    except Exception:  # noqa: BLE001
        return None


def _xray_register(token: Any, host: Any) -> None:
    """Ledger one in-flight host snapshot (owner ``snapshot``). The
    bytes live in HOST memory, not HBM — ``host=True`` keeps them out
    of the device-unattributed subtraction (observability/xray)."""
    try:
        from learningorchestra_tpu.observability import xray

        nbytes = sum(int(getattr(a, "nbytes", 0))
                     for a in jax.tree_util.tree_leaves(host))
        ctx = _trace_ctx()
        xray.register("snapshot", token, nbytes, host=True,
                      name=ctx[0] if ctx else None)
    except Exception:  # noqa: BLE001 — observability is advisory
        pass


def _xray_release(token: Any) -> None:
    try:
        from learningorchestra_tpu.observability import xray

        xray.release("snapshot", token)
    except Exception:  # noqa: BLE001
        pass


class AsyncCheckpointError(RuntimeError):
    """A background commit failed. Carries the original exception as
    ``__cause__``; raised on the train thread at the next save() or
    barrier so the failure lands on the JOB, not the worker."""


class AsyncCheckpointManager:
    """Checkpointer facade that commits on a background worker.

    ``save()`` = device→host snapshot (caller thread) + enqueue;
    reads and ``wait_until_finished()`` barrier; errors latch."""

    def __init__(self, checkpointer: Checkpointer,
                 inflight: int = 2):
        self._ckpt = checkpointer
        # the queue bound is the max host snapshots alive at once —
        # the memory/stall trade the LO_CKPT_INFLIGHT knob sets
        self._queue: "queue.Queue" = queue.Queue(
            maxsize=max(1, int(inflight)))
        self._error: Optional[BaseException] = None
        self._error_lock = locks.make_lock("async_ckpt.error")
        self._closed = False
        self._worker = threading.Thread(
            target=self._drain, daemon=True, name="lo-ckpt-commit")
        self._worker.start()

    # -- background worker ---------------------------------------------
    def _drain(self) -> None:
        while True:
            item = self._queue.get()
            try:
                if item is _SENTINEL:
                    return
                kind, payload, ctx, t_enq = item
                t0 = time.monotonic()
                try:
                    _maybe_inject("ckpt_async_commit")
                    if kind == "save":
                        step, host, token = payload
                        try:
                            self._ckpt._commit_host(step, host)
                        finally:
                            # committed (or failed): the host snapshot
                            # is droppable either way
                            _xray_release(token)
                        _observe("checkpointCommit", t0,
                                 time.monotonic(), ctx, step=int(step),
                                 async_=True,
                                 queued_seconds=round(t0 - t_enq, 6))
                    else:  # "meta" — sidecar rides the same FIFO so
                        # progress.json never outruns its step commit
                        self._ckpt.save_meta(payload)
                except BaseException as exc:  # noqa: BLE001 — latch,
                    # keep draining: the worker must never die or
                    # deadlock; the error surfaces on the train thread
                    with self._error_lock:
                        if self._error is None:
                            self._error = exc
            finally:
                self._queue.task_done()

    def _check_error(self) -> None:
        with self._error_lock:
            exc = self._error
        if exc is not None:
            raise AsyncCheckpointError(
                f"async checkpoint commit failed: {exc!r}") from exc

    # -- write path ----------------------------------------------------
    def save(self, step: int, tree: Any) -> None:
        """Snapshot device→host and enqueue the commit. Blocks only
        for the snapshot (and for backpressure when ``inflight``
        commits are already queued). Re-raises a prior commit failure
        first — the job sees the error at its next step boundary."""
        self._check_error()
        if self._closed:
            raise AsyncCheckpointError(
                "save() after close(): manager is shut down")
        ctx = _trace_ctx()
        t0 = time.monotonic()
        host = jax.tree_util.tree_map(np.asarray, tree)
        _observe("checkpointSnapshot", t0, time.monotonic(), ctx,
                 step=int(step))
        # ledger the snapshot while it waits for its commit; the
        # worker releases it (id(host) is unique while the queue
        # keeps the tree alive — exactly the entry's lifetime)
        token = (id(self), int(step), id(host))
        _xray_register(token, host)
        self._queue.put(("save", (int(step), host, token), ctx,
                         time.monotonic()))

    def save_meta(self, meta: dict) -> None:
        self._check_error()
        if self._closed:
            raise AsyncCheckpointError(
                "save_meta() after close(): manager is shut down")
        self._queue.put(("meta", dict(meta), _trace_ctx(),
                         time.monotonic()))

    # -- barrier ---------------------------------------------------------
    def wait_until_finished(self, reraise: bool = True) -> None:
        """Block until every enqueued commit has landed (or failed).
        Call at job end and before any restore/rollback — all read
        methods below do it implicitly."""
        self._queue.join()
        if reraise:
            self._check_error()

    # -- read path (barriers first) --------------------------------------
    def latest_step(self) -> Optional[int]:
        self.wait_until_finished()
        return self._ckpt.latest_step()

    def restore(self, target: Any, step: Optional[int] = None) -> Any:
        self.wait_until_finished()
        return self._ckpt.restore(target, step)

    def restore_partial(self, target_subtree: Any,
                        step: Optional[int] = None) -> Any:
        self.wait_until_finished()
        return self._ckpt.restore_partial(target_subtree, step)

    def saved_metadata(self, step: Optional[int] = None) -> Any:
        self.wait_until_finished()
        return self._ckpt.saved_metadata(step)

    def load_meta(self) -> Optional[dict]:
        self.wait_until_finished()
        return self._ckpt.load_meta()

    # -- teardown --------------------------------------------------------
    def close(self) -> None:
        """Drain (without re-raising — teardown must not mask the
        job's own exception), stop the worker, close the inner
        checkpointer."""
        if self._closed:
            return
        self._closed = True
        self._queue.join()
        self._queue.put(_SENTINEL)
        self._worker.join(timeout=30.0)
        self._ckpt.close()


def wrap_checkpointer(checkpointer: Checkpointer,
                      config=None) -> Any:
    """``checkpointer`` or an async facade over it, per
    ``LO_CKPT_ASYNC``/``LO_CKPT_INFLIGHT`` (services/execution.py
    calls this where train jobs build their checkpointer)."""
    if config is None:
        try:
            from learningorchestra_tpu.config import get_config

            config = get_config()
        except Exception:  # noqa: BLE001
            return checkpointer
    if not getattr(config, "ckpt_async", False):
        return checkpointer
    return AsyncCheckpointManager(
        checkpointer,
        inflight=int(getattr(config, "ckpt_inflight", 2)))
